"""Dataset: lazy, fused, block-parallel transforms over the object store.

Reference analogs: python/ray/data/dataset.py (:319 map_batches, :950
split, :2422 iter_batches), read_api.py:227, _internal/plan.py:70
ExecutionPlan with stage fusion (:59 fuse).  Design deltas, TPU-first:
blocks are Arrow tables in shared memory (zero-copy to workers on the
same node), a chain of map-style stages compiles to ONE remote task per
block, and iter_batches can emit jax-ready numpy dicts for
Train ingest (`get_dataset_shard`).
"""

from __future__ import annotations

import builtins
from typing import (Any, Callable, Dict, Iterator, List, Optional,
                    Sequence, Union)

import numpy as np

import ray_tpu
from ray_tpu.data import block as block_util

_DEFAULT_BLOCK_ROWS = 8192


def _fused_apply(table, stages):
    for fn in stages:
        table = fn(table)
    return table


@ray_tpu.remote
def _run_stages(table, stages):
    return _fused_apply(table, stages)


class ActorPoolStrategy:
    """Run a dataset's fused stage chain on a pool of long-lived actors
    instead of one task per block (reference:
    data/_internal/compute.py:173 ActorPoolStrategy — the right choice
    when stages carry expensive setup such as model weights)."""

    def __init__(self, size: int = 2, num_cpus: float = 1.0,
                 num_tpus: float = 0.0):
        self.size = size
        self.num_cpus = num_cpus
        self.num_tpus = num_tpus


class _StageActor:
    def __init__(self):
        self._stages_cache: Dict[bytes, Any] = {}

    def run(self, table, stages_ser: bytes):
        import cloudpickle

        stages = self._stages_cache.get(stages_ser)
        if stages is None:
            stages = cloudpickle.loads(stages_ser)
            self._stages_cache[stages_ser] = stages
        return _fused_apply(table, stages)


class Dataset:
    """A list of block ObjectRefs + pending (unfused) stages."""

    def __init__(self, block_refs: List, stages: Optional[List] = None,
                 compute: Optional[ActorPoolStrategy] = None,
                 stats: Optional[List] = None):
        self._block_refs = list(block_refs)
        self._stages: List[Callable] = list(stages or [])
        self._compute = compute
        #: ExecStats records, shared down the transform chain so
        #: ds.map(...).iter_batches(); ds.stats() sees the execution
        self._stats: List = stats if stats is not None else []

    # -- plan -------------------------------------------------------------
    def _with_stage(self, fn: Callable,
                    compute: Optional[ActorPoolStrategy] = None
                    ) -> "Dataset":
        return Dataset(self._block_refs, self._stages + [fn],
                       compute or self._compute, stats=self._stats)

    def materialize(self) -> "Dataset":
        """Execute pending stages: one fused task per block (the stage-
        fusion property: N stages do NOT mean N tasks per block).  The
        result is cached in place, so repeated consumption (count() then
        iter_batches(), ...) never re-runs the pipeline."""
        if not self._stages:
            return self
        import time as _time

        from ray_tpu.data.streaming import ExecStats

        stats = ExecStats(f"materialize[{len(self._stages)} fused stages]")
        t0 = _time.perf_counter()
        if self._compute is not None:
            refs = self._materialize_on_actors()
        else:
            refs = [_run_stages.remote(b, self._stages)
                    for b in self._block_refs]
        stats.blocks = len(refs)
        stats.wall_s = _time.perf_counter() - t0  # submit (+actor wait)
        self._stats.append(stats)
        self._block_refs = refs
        self._stages = []
        self._compute = None
        return self

    def _materialize_on_actors(self) -> List:
        import cloudpickle

        strat = self._compute
        cls = ray_tpu.remote(num_cpus=strat.num_cpus,
                             num_tpus=strat.num_tpus)(_StageActor)
        pool = [cls.remote() for _ in builtins.range(strat.size)]
        ser = cloudpickle.dumps(self._stages)
        refs = [pool[i % len(pool)].run.remote(b, ser)
                for i, b in enumerate(self._block_refs)]
        # Block until EVERY block finished, then retire the pool (the
        # results live in the object store independently of the actors —
        # but killing mid-execution would destroy in-flight blocks).
        remaining = list(refs)
        while remaining:
            _, remaining = ray_tpu.wait(
                remaining, num_returns=len(remaining), timeout=60.0)
        for a in pool:
            try:
                ray_tpu.kill(a)
            except Exception:  # noqa: BLE001
                pass
        return refs

    def _tables(self) -> List:
        ds = self.materialize()
        return ray_tpu.get(list(ds._block_refs), timeout=300)

    # -- transforms (lazy) ------------------------------------------------
    def map_batches(self, fn: Callable, *, batch_format: str = "numpy",
                    compute: Optional[ActorPoolStrategy] = None,
                    **_unused) -> "Dataset":
        """fn over whole blocks.  compute=ActorPoolStrategy(...) runs the
        stage chain on a pool of long-lived actors (amortizes expensive
        fn setup; reference _internal/compute.py:173).  Callable-class
        fns are constructed once per actor."""
        if isinstance(fn, type):
            holder: Dict[str, Any] = {}

            def stage(table, _cls=fn):
                inst = holder.get("i")
                if inst is None:
                    inst = holder["i"] = _cls()
                batch = block_util.format_batch(table, batch_format)
                return block_util.to_table(inst(batch))
        else:
            def stage(table):
                batch = block_util.format_batch(table, batch_format)
                return block_util.to_table(fn(batch))

        return self._with_stage(stage, compute)

    def map(self, fn: Callable) -> "Dataset":
        def stage(table):
            rows = table.to_pylist()
            return block_util.to_table([fn(r) for r in rows])

        return self._with_stage(stage)

    def filter(self, fn: Callable) -> "Dataset":
        def stage(table):
            rows = [r for r in table.to_pylist() if fn(r)]
            if not rows:
                return table.slice(0, 0)
            return block_util.to_table(rows)

        return self._with_stage(stage)

    def flat_map(self, fn: Callable) -> "Dataset":
        def stage(table):
            out = []
            for r in table.to_pylist():
                out.extend(fn(r))
            if not out:
                return table.slice(0, 0)
            return block_util.to_table(out)

        return self._with_stage(stage)

    def add_column(self, name: str, fn: Callable) -> "Dataset":
        def stage(table):
            batch = block_util.format_batch(table, "numpy")
            batch[name] = np.asarray(fn(batch))
            return block_util.to_table(batch)

        return self._with_stage(stage)

    def select_columns(self, cols: List[str]) -> "Dataset":
        """Keep only `cols` (reference: Dataset.select_columns)."""
        cols = list(cols)

        def stage(table, _cols=cols):
            return table.select(_cols)

        return self._with_stage(stage)

    def drop_columns(self, cols: List[str]) -> "Dataset":
        """Remove `cols` (reference: Dataset.drop_columns)."""
        drop = set(cols)

        def stage(table, _drop=drop):
            keep = [c for c in table.column_names if c not in _drop]
            return table.select(keep)

        return self._with_stage(stage)

    def rename_columns(self, mapping: Dict[str, str]) -> "Dataset":
        """Rename columns by dict (reference: Dataset.rename_columns)."""
        m = dict(mapping)

        def stage(table, _m=m):
            return table.rename_columns(
                [_m.get(c, c) for c in table.column_names])

        return self._with_stage(stage)

    def limit(self, n: int) -> "Dataset":
        """First n rows (reference: Dataset.limit).  Materializes only
        as many blocks as the limit needs."""
        out, taken = [], 0
        if n > 0:
            for t in self._iter_tables():
                take = min(n - taken, t.num_rows)
                out.append(ray_tpu.put(t.slice(0, take)))
                taken += take
                if taken >= n:
                    break       # before pulling (executing) more blocks
        return Dataset(out)

    def unique(self, column: str) -> List[Any]:
        """Distinct values of one column (reference: Dataset.unique)."""
        seen: Dict[Any, None] = {}
        for t in self._iter_tables():
            for v in t.column(column).to_pylist():
                seen.setdefault(v, None)
        return list(seen)

    def zip(self, other: "Dataset") -> "Dataset":
        """Column-wise join of two equal-length datasets (reference:
        Dataset.zip); duplicate names from `other` get a _1 suffix."""
        a = block_util.concat_tables(self._tables())
        b = block_util.concat_tables(other._tables())
        if a.num_rows != b.num_rows:
            raise ValueError(
                f"zip needs equal row counts: {a.num_rows} vs "
                f"{b.num_rows}")
        cols = {c: a.column(c) for c in a.column_names}
        for c in b.column_names:
            name, i = c, 0
            while name in cols:     # first FREE suffix — never clobber
                i += 1
                name = f"{c}_{i}"
            cols[name] = b.column(c)
        import pyarrow as pa

        return Dataset([ray_tpu.put(pa.table(cols))])

    def show(self, limit: int = 20) -> None:
        """Print the first rows (reference: Dataset.show)."""
        for row in self.take(limit):
            print(row)

    # -- geometry ---------------------------------------------------------
    def repartition(self, num_blocks: int) -> "Dataset":
        tables = self._tables()
        big = block_util.concat_tables(tables)
        n = big.num_rows
        sizes = [(n + i) // num_blocks
                 for i in builtins.range(num_blocks)]
        refs, start = [], 0
        for s in sizes:
            refs.append(ray_tpu.put(big.slice(start, s)))
            start += s
        return Dataset(refs)

    def split(self, n: int, *, equal: bool = True) -> List["Dataset"]:
        """Per-consumer shards (reference dataset.py:950; Train ingest
        path train/_internal/dataset_spec.py:66 get_dataset_shards)."""
        ds = self.materialize()
        if equal or len(ds._block_refs) % n:
            ds = ds.repartition(n)  # near-equal row counts per block
        per = len(ds._block_refs) // n
        return [Dataset(ds._block_refs[i * per:(i + 1) * per])
                for i in builtins.range(n)]

    def union(self, *others: "Dataset") -> "Dataset":
        ds = self.materialize()
        refs = list(ds._block_refs)
        for o in others:
            refs.extend(o.materialize()._block_refs)
        return Dataset(refs)

    def random_shuffle(self, *, seed: Optional[int] = None) -> "Dataset":
        """Distributed two-phase shuffle: rows mix across blocks via
        multi-return map tasks + per-partition reduce tasks; no block
        ever rides through the driver (reference:
        _internal/push_based_shuffle.py)."""
        from ray_tpu.data import shuffle as shuffle_mod

        ds = self.materialize()
        n = max(1, len(ds._block_refs))
        # local permutation pass so rows also mix WITHIN output blocks
        shuffled = shuffle_mod.shuffle_blocks(ds._block_refs, n, seed)

        def perm_stage(table, _seed=seed):
            rng = np.random.RandomState(_seed)
            return table.take(rng.permutation(table.num_rows))

        return Dataset(shuffled, [perm_stage]).materialize()

    def sort(self, key: str, descending: bool = False) -> "Dataset":
        """Distributed sample-partitioned sort (reference: data sort_impl
        boundary sampling + per-range reduce)."""
        from ray_tpu.data import shuffle as shuffle_mod

        ds = self.materialize()
        refs = shuffle_mod.sort_blocks(
            ds._block_refs, key, descending,
            max(1, len(ds._block_refs)))
        return Dataset(refs)

    def groupby(self, key: str) -> "GroupedDataset":
        """Hash-partitioned groupby (reference: data groupby —
        equal keys land in one block, aggregates run per block)."""
        return GroupedDataset(self, key)

    # -- consumption ------------------------------------------------------
    def count(self) -> int:
        return sum(t.num_rows for t in self._tables())

    def take(self, n: int = 20) -> List[Dict[str, Any]]:
        out: List[Dict[str, Any]] = []
        for t in self._tables():
            out.extend(t.to_pylist())
            if len(out) >= n:
                break
        return out[:n]

    def take_all(self) -> List[Dict[str, Any]]:
        return [r for t in self._tables() for r in t.to_pylist()]

    def schema(self):
        if not self._block_refs:
            return None
        if self._stages:  # run the fused pipeline on ONE block only
            ref = _run_stages.remote(self._block_refs[0], self._stages)
            return ray_tpu.get([ref], timeout=60)[0].schema
        return ray_tpu.get([self._block_refs[0]], timeout=60)[0].schema

    @property
    def num_blocks(self) -> int:
        return len(self._block_refs)

    def iter_batches(self, *, batch_size: int = 256,
                     batch_format: str = "numpy",
                     drop_last: bool = False) -> Iterator:
        carry = None
        for t in self._iter_tables():
            if carry is not None and carry.num_rows:
                t = block_util.concat_tables([carry, t])
            start = 0
            while t.num_rows - start >= batch_size:
                yield block_util.format_batch(
                    t.slice(start, batch_size), batch_format)
                start += batch_size
            carry = t.slice(start)
        if carry is not None and carry.num_rows and not drop_last:
            yield block_util.format_batch(carry, batch_format)

    def _iter_tables(self) -> Iterator:
        """Streaming table iterator: pending stages execute through the
        bounded-in-flight, bytes-backpressured StreamingExecutor —
        batches flow while later blocks still compute, peak memory = the
        in-flight window, not the dataset (reference:
        streaming_executor.py).  Actor-pool compute streams through the
        SAME window over a pool of stage actors (reference:
        ActorPoolMapOperator) instead of a materialize barrier.  A FULL
        consumption leaves the dataset materialized (cached), same as
        materialize()."""
        if not self._stages:
            yield from self._tables()
            return
        from ray_tpu.data.streaming import ExecStats, StreamingExecutor

        pool = None
        stages_ser = None
        if self._compute is not None:
            import cloudpickle

            strat = self._compute
            cls = ray_tpu.remote(num_cpus=strat.num_cpus,
                                 num_tpus=strat.num_tpus)(_StageActor)
            pool = [cls.remote() for _ in builtins.range(strat.size)]
            stages_ser = cloudpickle.dumps(self._stages)
        label = ("actor-pool" if pool is not None else "stream")
        stats = ExecStats(f"{label}[{len(self._stages)} fused stages]")
        out_refs = []
        try:
            for ref in StreamingExecutor().execute(
                    self._block_refs, self._stages, stats,
                    pool=pool, stages_ser=stages_ser):
                out_refs.append(ref)
                yield ray_tpu.get([ref], timeout=600)[0]
        finally:
            if pool is not None:
                for a in pool:
                    try:
                        ray_tpu.kill(a)
                    except Exception:  # noqa: BLE001
                        pass
        self._stats.append(stats)
        self._block_refs = out_refs  # fully consumed: cache in place
        self._stages = []
        self._compute = None

    def stats(self) -> str:
        """Execution summaries recorded on this dataset's lineage
        (reference: Dataset.stats / _internal/stats.py)."""
        if not self._stats:
            return "(no executions recorded)"
        return "\n".join(s.summary() for s in self._stats)

    def iter_jax_batches(self, *, batch_size: int = 256,
                         sharding=None, drop_last: bool = True,
                         dtypes: Optional[Dict[str, Any]] = None
                         ) -> Iterator:
        """TPU ingest bridge: numpy batches device_put as jax arrays,
        optionally placed under a NamedSharding so a global batch lands
        already sharded over the mesh's data axis (no per-host gather —
        the TPU-first analog of the reference's iter_torch_batches +
        get_dataset_shard ingest, train/_internal/dataset_spec.py:66).

        sharding: a jax.sharding.Sharding applied to every column (e.g.
        NamedSharding(mesh, P("data"))).  dtypes: per-column casts
        applied host-side before transfer (bf16 casts are cheaper on
        device; cast there instead when possible).

        drop_last defaults to True — the OPPOSITE of iter_batches —
        because jitted train steps want static shapes and a sharded
        device_put of a ragged final batch fails when rows don't divide
        the shard count.  Datasets smaller than one batch therefore
        yield NOTHING; pass drop_last=False (and a divisible batch) when
        every row must be seen."""
        import jax

        for batch in self.iter_batches(batch_size=batch_size,
                                       batch_format="numpy",
                                       drop_last=drop_last):
            if dtypes:
                batch = {k: (v.astype(dtypes[k]) if k in dtypes else v)
                         for k, v in batch.items()}
            # one pytree transfer: jax batches the H2D copies per dict
            yield jax.device_put(batch, sharding)

    def iter_torch_batches(self, *, batch_size: int = 256,
                           drop_last: bool = False,
                           dtypes: Optional[Dict[str, Any]] = None,
                           device: Optional[str] = None) -> Iterator:
        """Torch-tensor batches (reference: Dataset.iter_torch_batches,
        python/ray/data/iterator.py) — the CPU-side twin of
        iter_jax_batches for torch training loops (TorchTrainer /
        HuggingFaceTrainer workers).

        dtypes: per-column torch dtypes; device: e.g. "cpu" (TPU work
        goes through iter_jax_batches — torch here is host-side)."""
        import torch

        for batch in self.iter_batches(batch_size=batch_size,
                                       batch_format="numpy",
                                       drop_last=drop_last):
            out = {}
            for k, v in batch.items():
                t = torch.as_tensor(v)
                if dtypes and k in dtypes:
                    t = t.to(dtypes[k])
                if device:
                    t = t.to(device)
                out[k] = t
            yield out

    def iter_rows(self) -> Iterator[Dict[str, Any]]:
        for t in self._iter_tables():
            yield from t.to_pylist()

    def to_pandas(self):
        return block_util.concat_tables(self._tables()).to_pandas()

    def to_numpy_refs(self) -> List:
        ds = self.materialize()
        return list(ds._block_refs)

    def write_datasource(self, source, **write_args) -> None:
        """Fan blocks out to a Datasource's write_block tasks
        (reference: Dataset.write_datasource)."""
        from ray_tpu.data.datasource import write_datasource

        write_datasource(self, source, **write_args)

    def write_parquet(self, path: str) -> None:
        import pyarrow.parquet as pq

        from ray_tpu.data import filesystem as fs_mod

        for i, t in enumerate(self._tables()):
            fs, p = fs_mod.resolve(
                fs_mod.join(path, f"part-{i:05d}.parquet"))
            with fs.open_output(p) as f:
                pq.write_table(t, f)

    # -- pipelining -------------------------------------------------------
    def window(self, *, blocks_per_window: int = 2) -> "DatasetPipeline":
        """Stream execution window-by-window (reference:
        data/dataset_pipeline.py — bounds memory to one window of
        blocks instead of the whole dataset)."""
        wins = [Dataset(self._block_refs[i:i + blocks_per_window],
                        list(self._stages), self._compute)
                for i in builtins.range(0, len(self._block_refs),
                                        blocks_per_window)]
        return DatasetPipeline(wins)

    def repeat(self, times: Optional[int] = None) -> "DatasetPipeline":
        return DatasetPipeline([self], repeat=times)

    def __repr__(self):
        return (f"Dataset(num_blocks={self.num_blocks}, "
                f"pending_stages={len(self._stages)})")


class DatasetPipeline:
    """A sequence of Dataset windows executed lazily, one window at a
    time (reference: data/dataset_pipeline.py DatasetPipeline)."""

    def __init__(self, windows: List[Dataset],
                 repeat: Optional[int] = 1):
        self._windows = windows
        self._repeat = repeat  # None = infinite

    def map_batches(self, fn, **kw) -> "DatasetPipeline":
        return DatasetPipeline([w.map_batches(fn, **kw)
                                for w in self._windows], self._repeat)

    def foreach_window(self, fn: Callable[[Dataset], Dataset]
                       ) -> "DatasetPipeline":
        return DatasetPipeline([fn(w) for w in self._windows],
                               self._repeat)

    def repeat(self, times: Optional[int] = None) -> "DatasetPipeline":
        return DatasetPipeline(self._windows, times)

    def iter_batches(self, **kw) -> Iterator:
        epoch = 0
        while self._repeat is None or epoch < self._repeat:
            for w in self._windows:
                # copy: window stages re-run each epoch only if unfused
                yield from Dataset(w._block_refs, list(w._stages),
                                   w._compute).iter_batches(**kw)
            epoch += 1

    def iter_epochs(self) -> Iterator[List[Dataset]]:
        epoch = 0
        while self._repeat is None or epoch < self._repeat:
            yield list(self._windows)
            epoch += 1

    def __repr__(self):
        return (f"DatasetPipeline(windows={len(self._windows)}, "
                f"repeat={self._repeat})")


class GroupedDataset:
    """Aggregations over hash-partitioned groups (reference:
    data/grouped_dataset.py)."""

    _AGGS = {
        "count": lambda v: len(v),
        "sum": lambda v: v.sum(),
        "mean": lambda v: v.mean(),
        "min": lambda v: v.min(),
        "max": lambda v: v.max(),
        "std": lambda v: v.std(),
    }

    def __init__(self, ds: Dataset, key: str):
        self._ds = ds
        self._key = key

    def _aggregate(self, agg: str, on: Optional[str]) -> Dataset:
        from ray_tpu.data import shuffle as shuffle_mod

        ds = self._ds.materialize()
        n = max(1, len(ds._block_refs))
        parts = shuffle_mod.hash_partition_blocks(ds._block_refs,
                                                  self._key, n)
        key, fn = self._key, self._AGGS[agg]
        out_col = f"{agg}({on})" if on else agg

        def stage(table, _key=key, _on=on, _fn=fn, _out=out_col):
            rows: Dict[Any, List] = {}
            keys_col = table.column(_key).to_pylist()
            vals_col = table.column(_on).to_numpy(
                zero_copy_only=False) if _on else np.zeros(len(keys_col))
            for k_, v_ in zip(keys_col, vals_col):
                rows.setdefault(k_, []).append(v_)
            return block_util.to_table({
                _key: list(rows),
                _out: [float(_fn(np.asarray(v)))
                       for v in rows.values()],
            })

        return Dataset(parts, [stage]).materialize()

    def count(self) -> Dataset:
        return self._aggregate("count", None)

    def sum(self, on: str) -> Dataset:
        return self._aggregate("sum", on)

    def mean(self, on: str) -> Dataset:
        return self._aggregate("mean", on)

    def min(self, on: str) -> Dataset:
        return self._aggregate("min", on)

    def max(self, on: str) -> Dataset:
        return self._aggregate("max", on)

    def std(self, on: str) -> Dataset:
        return self._aggregate("std", on)


# -- creation APIs ---------------------------------------------------------

def _split_rows(n_rows: int, parallelism: int) -> List[builtins.range]:
    per = max(1, n_rows // max(1, parallelism))
    return [builtins.range(i, min(i + per, n_rows))
            for i in builtins.range(0, n_rows, per)]


def from_items(items: Sequence[Any], *, parallelism: int = 8) -> Dataset:
    refs = []
    for rng in _split_rows(len(items), parallelism):
        chunk = [items[i] for i in rng]
        refs.append(ray_tpu.put(block_util.to_table(chunk)))
    return Dataset(refs)


def range(n: int, *, parallelism: int = 8) -> Dataset:
    refs = [ray_tpu.put(block_util.to_table(
        {"id": np.arange(r.start, r.stop, dtype=np.int64)}))
        for r in _split_rows(n, parallelism)]
    return Dataset(refs)


def from_numpy(arrays: Union[np.ndarray, Dict[str, np.ndarray]], *,
               parallelism: int = 8) -> Dataset:
    if isinstance(arrays, np.ndarray):
        arrays = {"value": arrays}
    n = len(next(iter(arrays.values())))
    refs = [ray_tpu.put(block_util.to_table(
        {k: v[r.start:r.stop] for k, v in arrays.items()}))
        for r in _split_rows(n, parallelism)]
    return Dataset(refs)


def from_pandas(df, *, parallelism: int = 8) -> Dataset:
    import pyarrow as pa

    table = pa.Table.from_pandas(df, preserve_index=False)
    return from_arrow(table, parallelism=parallelism)


def from_arrow(table, *, parallelism: int = 8) -> Dataset:
    refs = [ray_tpu.put(table.slice(r.start, r.stop - r.start))
            for r in _split_rows(table.num_rows, parallelism)]
    return Dataset(refs)


@ray_tpu.remote
def _read_file_task(fmt: str, path: str):
    """One file -> one block, parsed INSIDE a task so reads parallelize
    across the cluster instead of serializing through the driver
    (reference: read tasks from read_api.py:227 read_datasource).
    The path resolves through the filesystem seam (local / kv:// /
    s3:// …, filesystem.py) — local paths must be readable on every
    node, like the reference's file-based datasources."""
    return _parse_file(fmt, path)


def _parse_file(fmt: str, path: str):
    from ray_tpu.data import filesystem as fs_mod

    fs, p = fs_mod.resolve(path)
    if fmt == "parquet":
        import pyarrow.parquet as pq

        with fs.open_input(p) as f:
            return pq.read_table(f)
    if fmt == "csv":
        from pyarrow import csv as pa_csv

        with fs.open_input(p) as f:
            return pa_csv.read_csv(f)
    if fmt == "json":
        from pyarrow import json as pa_json

        with fs.open_input(p) as f:
            return pa_json.read_json(f)
    if fmt == "text":
        with fs.open_input(p) as f:
            lines = f.read().decode().splitlines()
        return block_util.to_table({"text": lines})
    if fmt == "numpy":
        import io as _io

        with fs.open_input(p) as f:
            arr = np.load(_io.BytesIO(f.read()))
        return block_util.to_table({"value": arr})
    raise ValueError(f"unknown format {fmt!r}")


def _list_files(path: str, suffix: str) -> List[str]:
    from ray_tpu.data import filesystem as fs_mod

    fs, p = fs_mod.resolve(path)
    files = fs.list(p, suffix)
    if not files:
        raise FileNotFoundError(f"no {suffix} files under {path}")
    # re-attach the scheme so worker-side resolve() routes the same way
    if "://" in path and "://" not in files[0]:
        scheme = path.split("://", 1)[0]
        files = [f"{scheme}://{f}" for f in files]
    return files


def _read_files(fmt: str, suffix: str, path: str) -> Dataset:
    """Shared body of the read_* helpers: list via the filesystem seam,
    parse per-file in remote tasks (driver-side for process-local
    mem:// paths, which workers cannot see)."""
    files = _list_files(path, suffix)
    if path.startswith("mem://"):
        return Dataset([ray_tpu.put(_parse_file(fmt, f))
                        for f in files])
    return Dataset([_read_file_task.remote(fmt, f) for f in files])


def read_parquet(path: str) -> Dataset:
    return _read_files("parquet", ".parquet", path)


def read_csv(path: str) -> Dataset:
    return _read_files("csv", ".csv", path)


def read_json(path: str) -> Dataset:
    """Newline-delimited JSON records (reference: read_json)."""
    return _read_files("json", ".json", path)


def read_text(path: str) -> Dataset:
    """One row per line, column "text" (reference: read_text)."""
    return _read_files("text", ".txt", path)


def read_numpy(path: str) -> Dataset:
    """.npy files, column "value" (reference: read_numpy)."""
    return _read_files("numpy", ".npy", path)
