"""Distributed datasets (reference analog: python/ray/data/).

Blocks are pyarrow Tables living in the object store as ObjectRefs;
transforms build a lazy stage chain that is FUSED into one remote task
per block at execution (the reference's ExecutionPlan stage fusion,
data/_internal/plan.py:59,368, done eagerly-on-demand instead of via a
separate optimizer pass).
"""

from ray_tpu.data.dataset import (ActorPoolStrategy, Dataset,
                                  DatasetPipeline, GroupedDataset,
                                  from_arrow, from_items, from_numpy,
                                  from_pandas, range as range_, read_csv,
                                  read_json, read_numpy, read_parquet,
                                  read_text)
from ray_tpu.data.datasource import (Datasource, FileDatasource,
                                     RangeDatasource, ReadTask,
                                     read_datasource)
from ray_tpu.data.filesystem import (FileSystem, KVFileSystem,
                                     LocalFileSystem, MemoryFileSystem,
                                     register_filesystem)

# `range` shadows the builtin only inside this namespace, as in the
# reference's ray.data.range
range = range_

__all__ = ["Dataset", "DatasetPipeline", "GroupedDataset",
           "ActorPoolStrategy", "from_items", "from_numpy",
           "from_pandas", "from_arrow", "range", "read_parquet",
           "read_csv", "read_json", "read_text", "read_numpy",
           "Datasource", "ReadTask", "RangeDatasource",
           "FileDatasource", "read_datasource",
           "FileSystem", "LocalFileSystem", "MemoryFileSystem",
           "KVFileSystem", "register_filesystem"]
