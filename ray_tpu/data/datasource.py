"""Datasource plugin API: custom parallel readers/writers.

Reference analog: data/datasource/datasource.py (Datasource /
ReadTask / write API).  A Datasource describes HOW to read a source as
independent tasks; ``read_datasource`` turns those into object-store
blocks (one remote task per ReadTask — streaming/fusion then apply like
any other dataset), and ``Dataset.write_datasource`` fans blocks out to
``write_block`` tasks.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Iterable, List, Optional

import ray_tpu
from ray_tpu.data import block as block_util


class ReadTask:
    """One independently-executable read unit: a zero-arg callable
    returning an iterable of row-dicts (or a pyarrow table), plus
    optional size metadata for scheduling."""

    def __init__(self, fn: Callable[[], Any],
                 num_rows: Optional[int] = None):
        self.fn = fn
        self.num_rows = num_rows

    def __call__(self):
        return self.fn()


class Datasource:
    """Implement ``get_read_tasks`` for reading; override
    ``write_block`` for writing."""

    def get_read_tasks(self, parallelism: int,
                       **read_args: Any) -> List[ReadTask]:
        raise NotImplementedError

    def write_block(self, block, task_index: int, **write_args) -> Any:
        raise NotImplementedError(
            f"{type(self).__name__} does not support writes")

    def on_write_complete(self, results: List[Any]) -> None:
        """Called on the driver after every block write finished."""


class RangeDatasource(Datasource):
    """Example/testing datasource: integers [0, n)."""

    def __init__(self, n: int):
        self.n = n

    def get_read_tasks(self, parallelism: int,
                       **read_args: Any) -> List[ReadTask]:
        per = -(-self.n // max(1, parallelism))
        tasks = []
        for lo in range(0, self.n, per):
            hi = min(lo + per, self.n)
            tasks.append(ReadTask(
                lambda lo=lo, hi=hi: [{"id": i} for i in range(lo, hi)],
                num_rows=hi - lo))
        return tasks


@ray_tpu.remote
def _exec_read_task(task: ReadTask):
    out = task()
    import pyarrow as pa

    if isinstance(out, pa.Table):
        return out
    return block_util.to_table(list(out))


def read_datasource(source: Datasource, *, parallelism: int = 8,
                    **read_args) -> "Any":
    """Datasource → Dataset: one remote task per ReadTask; blocks land
    in the object store without routing through the driver."""
    from ray_tpu.data.dataset import Dataset

    tasks = source.get_read_tasks(parallelism, **read_args)
    if not tasks:
        return Dataset([_exec_read_task.remote(
            ReadTask(lambda: []))])
    return Dataset([_exec_read_task.remote(t) for t in tasks])


def write_datasource(ds, source: Datasource, **write_args) -> None:
    """Dataset → Datasource: one write task per block."""
    @ray_tpu.remote
    def _write(table, i, src_ser):
        import cloudpickle

        src = cloudpickle.loads(src_ser)
        return src.write_block(table, i, **write_args)

    import cloudpickle

    mat = ds.materialize()
    ser = cloudpickle.dumps(source)
    results = ray_tpu.get(
        [_write.remote(b, i, ser)
         for i, b in enumerate(mat._block_refs)], timeout=600)
    source.on_write_complete(results)
