"""Datasource plugin API: custom parallel readers/writers.

Reference analog: data/datasource/datasource.py (Datasource /
ReadTask / write API).  A Datasource describes HOW to read a source as
independent tasks; ``read_datasource`` turns those into object-store
blocks (one remote task per ReadTask — streaming/fusion then apply like
any other dataset), and ``Dataset.write_datasource`` fans blocks out to
``write_block`` tasks.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Iterable, List, Optional

import ray_tpu
from ray_tpu.data import block as block_util


class ReadTask:
    """One independently-executable read unit: a zero-arg callable
    returning an iterable of row-dicts (or a pyarrow table), plus
    optional size metadata for scheduling."""

    def __init__(self, fn: Callable[[], Any],
                 num_rows: Optional[int] = None):
        self.fn = fn
        self.num_rows = num_rows

    def __call__(self):
        return self.fn()


class Datasource:
    """Implement ``get_read_tasks`` for reading; override
    ``write_block`` for writing."""

    def get_read_tasks(self, parallelism: int,
                       **read_args: Any) -> List[ReadTask]:
        raise NotImplementedError

    def write_block(self, block, task_index: int, **write_args) -> Any:
        raise NotImplementedError(
            f"{type(self).__name__} does not support writes")

    def on_write_complete(self, results: List[Any]) -> None:
        """Called on the driver after every block write finished."""


class FileDatasource(Datasource):
    """File-format datasource over the pluggable filesystem seam
    (reference: file_based_datasource.py:181 FileBasedDatasource — every
    path resolves through a filesystem, so local / kv:// / s3:// sources
    all flow through the same read/write tasks).

    ``fmt``: parquet | csv | json | text | numpy.
    """

    _SUFFIX = {"parquet": ".parquet", "csv": ".csv", "json": ".json",
               "text": ".txt", "numpy": ".npy"}

    def __init__(self, path: str, fmt: str = "parquet"):
        if fmt not in self._SUFFIX:
            raise ValueError(f"unknown format {fmt!r}")
        self.path = path
        self.fmt = fmt

    def get_read_tasks(self, parallelism: int,
                       **read_args: Any) -> List[ReadTask]:
        from ray_tpu.data.dataset import _list_files, _parse_file

        files = _list_files(self.path, self._SUFFIX[self.fmt])
        fmt = self.fmt
        return [ReadTask(lambda f=f: _parse_file(fmt, f))
                for f in files]

    def write_block(self, block, task_index: int, **write_args) -> str:
        from ray_tpu.data import filesystem as fs_mod

        out = fs_mod.join(self.path,
                          f"part-{task_index:05d}{self._SUFFIX[self.fmt]}")
        fs, p = fs_mod.resolve(out)
        if self.fmt == "parquet":
            import pyarrow.parquet as pq

            with fs.open_output(p) as f:
                pq.write_table(block, f)
        elif self.fmt == "csv":
            from pyarrow import csv as pa_csv

            with fs.open_output(p) as f:
                pa_csv.write_csv(block, f)
        elif self.fmt == "json":
            with fs.open_output(p) as f:
                import json as _json

                for row in block.to_pylist():
                    f.write((_json.dumps(row) + "\n").encode())
        else:
            raise ValueError(
                f"writes not supported for format {self.fmt!r}")
        return out


class RangeDatasource(Datasource):
    """Example/testing datasource: integers [0, n)."""

    def __init__(self, n: int):
        self.n = n

    def get_read_tasks(self, parallelism: int,
                       **read_args: Any) -> List[ReadTask]:
        per = -(-self.n // max(1, parallelism))
        tasks = []
        for lo in range(0, self.n, per):
            hi = min(lo + per, self.n)
            tasks.append(ReadTask(
                lambda lo=lo, hi=hi: [{"id": i} for i in range(lo, hi)],
                num_rows=hi - lo))
        return tasks


@ray_tpu.remote
def _exec_read_task(task: ReadTask):
    out = task()
    import pyarrow as pa

    if isinstance(out, pa.Table):
        return out
    return block_util.to_table(list(out))


def read_datasource(source: Datasource, *, parallelism: int = 8,
                    **read_args) -> "Any":
    """Datasource → Dataset: one remote task per ReadTask; blocks land
    in the object store without routing through the driver."""
    from ray_tpu.data.dataset import Dataset

    tasks = source.get_read_tasks(parallelism, **read_args)
    if not tasks:
        return Dataset([_exec_read_task.remote(
            ReadTask(lambda: []))])
    return Dataset([_exec_read_task.remote(t) for t in tasks])


def write_datasource(ds, source: Datasource, **write_args) -> None:
    """Dataset → Datasource: one write task per block."""
    @ray_tpu.remote
    def _write(table, i, src_ser):
        import cloudpickle

        src = cloudpickle.loads(src_ser)
        return src.write_block(table, i, **write_args)

    import cloudpickle

    mat = ds.materialize()
    ser = cloudpickle.dumps(source)
    results = ray_tpu.get(
        [_write.remote(b, i, ser)
         for i, b in enumerate(mat._block_refs)], timeout=600)
    source.on_write_complete(results)
