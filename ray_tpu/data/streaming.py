"""Streaming block execution with bounded in-flight work.

Reference analog: data/_internal/execution/streaming_executor.py (the
operator/backpressure engine behind Dataset.iter_batches).  Collapsed to
the pieces that matter for this runtime: stages are already fused into
one task per block (dataset.py), so streaming =

- a SUBMISSION window: at most ``max_in_flight`` block tasks alive,
  results yield in input order as they (and their predecessors) finish;
- BYTES backpressure: completed-but-unyielded results are counted
  against a bytes budget derived from the object-store capacity — a
  slow consumer (or head-of-line-blocked index 0) stalls submission
  before the store fills and spill-thrashes (reference:
  backpressure_policy / ReservationOpResourceAllocator);
- optional ACTOR-POOL compute: blocks round-robin over a pool of
  long-lived stage actors instead of stateless tasks, still inside the
  same streamed window (reference: ActorPoolMapOperator).

Peak cluster memory is O(window) blocks instead of O(dataset);
first-batch latency is one block's work instead of the whole
pipeline's.
"""

from __future__ import annotations

import time
from typing import Any, Dict, Iterator, List, Optional

import ray_tpu


class ExecStats:
    """Wall-clock/throughput/memory record of one execution (reference:
    _internal/stats.py DatasetStats, driver-side portion)."""

    def __init__(self, op: str):
        self.op = op
        self.blocks = 0
        self.wall_s = 0.0
        self.first_block_s: Optional[float] = None
        #: bytes of results that flowed through (where sizes were known)
        self.total_bytes = 0
        #: high-water mark of completed-but-unyielded result bytes
        self.peak_inflight_bytes = 0
        #: times submission stalled on the bytes budget
        self.backpressure_stalls = 0

    def summary(self) -> str:
        first = (f", first block {self.first_block_s:.3f}s"
                 if self.first_block_s is not None else "")
        mem = ""
        if self.total_bytes:
            mem = (f", {self.total_bytes / 1e6:.1f}MB through, "
                   f"peak inflight {self.peak_inflight_bytes / 1e6:.1f}MB"
                   + (f", {self.backpressure_stalls} bp-stalls"
                      if self.backpressure_stalls else ""))
        return (f"{self.op}: {self.blocks} blocks in "
                f"{self.wall_s:.3f}s{first}{mem}")


def _object_nbytes(ref) -> Optional[int]:
    """Size of a completed object (memory-store inline or shm), without
    fetching its payload to python."""
    from ray_tpu._private import worker_context
    from ray_tpu._private.ids import ObjectID

    cw = worker_context.maybe_core_worker()
    if cw is None:
        return None
    oid = ref._info.oid
    try:
        entry = cw.memory_store.get(oid)
        if entry is not None and entry.data is not None:
            return len(entry.data)
        buf = cw.store.get(ObjectID(oid), timeout_ms=0)
        if buf is not None:
            with buf:
                return len(buf.data) + len(buf.metadata)
    except Exception:  # noqa: BLE001 - size probe must never break exec
        return None
    return None


def _default_bytes_budget() -> int:
    """~1/4 of the object store: streaming results may occupy at most
    this much before the consumer must drain."""
    from ray_tpu._private import worker_context

    cw = worker_context.maybe_core_worker()
    try:
        cap = cw.store.stats().get("capacity", 0) if cw else 0
    except Exception:  # noqa: BLE001
        cap = 0
    return int(cap * 0.25) if cap else 256 * 1024 * 1024


class StreamingExecutor:
    def __init__(self, max_in_flight: int = 0, max_bytes: int = 0):
        if max_in_flight <= 0:
            cpus = ray_tpu.cluster_resources().get("CPU", 2)
            max_in_flight = max(2, int(cpus) * 2)
        self.max_in_flight = max_in_flight
        self.max_bytes = max_bytes or _default_bytes_budget()

    def execute(self, block_refs: List, stages: List,
                stats: Optional[ExecStats] = None,
                pool: Optional[List] = None,
                stages_ser: Optional[bytes] = None) -> Iterator:
        """Yield one result ref per input block, in input order, with at
        most ``max_in_flight`` stage tasks alive at once and at most
        ``max_bytes`` of completed results waiting to be consumed.
        ``pool``: stage actors (with .run(block, stages_ser)) — blocks
        round-robin over them instead of spawning stateless tasks."""
        from ray_tpu.data.dataset import _run_stages

        t0 = time.perf_counter()
        n = len(block_refs)
        inflight: Dict[Any, int] = {}
        done: Dict[int, Any] = {}
        done_bytes: Dict[int, int] = {}
        inflight_bytes = 0
        completed_total = 0
        completed_count = 0
        submitted = 0
        yielded = 0

        def _est_result_bytes(idx: int) -> int:
            # running tasks' eventual output counts against the budget
            # too: estimate by the running average of completed results,
            # falling back to the input block's size before any finish
            if completed_count:
                return completed_total // completed_count
            return _object_nbytes(block_refs[idx]) or 0

        while yielded < n:
            # window counts submitted-but-UNYIELDED blocks (running +
            # completed-waiting), not just running tasks: under
            # head-of-line blocking (block 0 slow, 1..N fast) counting
            # only running tasks would submit — and materialize — the
            # whole dataset while waiting to yield index 0
            while submitted < n and \
                    submitted - yielded < self.max_in_flight:
                est = inflight_bytes + sum(
                    _est_result_bytes(i) for i in inflight.values())
                if inflight and est >= self.max_bytes:
                    # budget spoken for (completed results waiting +
                    # running tasks' expected output); wait for the
                    # consumer instead of submitting more
                    if stats is not None:
                        stats.backpressure_stalls += 1
                    break
                if pool is not None:
                    ref = pool[submitted % len(pool)].run.remote(
                        block_refs[submitted], stages_ser)
                else:
                    ref = _run_stages.remote(block_refs[submitted],
                                             stages)
                inflight[ref] = submitted
                submitted += 1
            while yielded in done:
                if stats is not None:
                    stats.blocks += 1
                    if stats.first_block_s is None:
                        stats.first_block_s = time.perf_counter() - t0
                    stats.wall_s = time.perf_counter() - t0
                inflight_bytes -= done_bytes.pop(yielded, 0)
                yield done.pop(yielded)
                yielded += 1
            if yielded >= n:
                break
            if not inflight:
                continue
            ready, _ = ray_tpu.wait(list(inflight), num_returns=1,
                                    timeout=600.0)
            for r in ready:
                idx = inflight.pop(r)
                done[idx] = r
                nbytes = _object_nbytes(r) or 0
                done_bytes[idx] = nbytes
                inflight_bytes += nbytes
                completed_total += nbytes
                completed_count += 1
                if stats is not None:
                    stats.total_bytes += nbytes
                    stats.peak_inflight_bytes = max(
                        stats.peak_inflight_bytes, inflight_bytes)
