"""Streaming block execution with bounded in-flight work.

Reference analog: data/_internal/execution/streaming_executor.py (the
operator/backpressure engine behind Dataset.iter_batches).  Collapsed to
the piece that matters for this runtime: stages are already fused into
one task per block (dataset.py), so streaming = a submission window —
at most ``max_in_flight`` block tasks run concurrently, results yield
in order the moment they (and everything before them) finish, and later
blocks are not even SUBMITTED until a slot frees.  Peak cluster memory
is O(max_in_flight) blocks instead of O(dataset); first-batch latency
is one block's work instead of the whole pipeline's.
"""

from __future__ import annotations

import time
from typing import Any, Dict, Iterator, List, Optional

import ray_tpu


class ExecStats:
    """Wall-clock/throughput record of one execution (reference:
    _internal/stats.py DatasetStats, driver-side portion)."""

    def __init__(self, op: str):
        self.op = op
        self.blocks = 0
        self.wall_s = 0.0
        self.first_block_s: Optional[float] = None

    def summary(self) -> str:
        first = (f", first block {self.first_block_s:.3f}s"
                 if self.first_block_s is not None else "")
        return (f"{self.op}: {self.blocks} blocks in "
                f"{self.wall_s:.3f}s{first}")


class StreamingExecutor:
    def __init__(self, max_in_flight: int = 0):
        if max_in_flight <= 0:
            cpus = ray_tpu.cluster_resources().get("CPU", 2)
            max_in_flight = max(2, int(cpus) * 2)
        self.max_in_flight = max_in_flight

    def execute(self, block_refs: List, stages: List,
                stats: Optional[ExecStats] = None) -> Iterator:
        """Yield one result ref per input block, in input order, with at
        most ``max_in_flight`` stage tasks alive at once."""
        from ray_tpu.data.dataset import _run_stages

        t0 = time.perf_counter()
        n = len(block_refs)
        inflight: Dict[Any, int] = {}
        done: Dict[int, Any] = {}
        submitted = 0
        yielded = 0
        while yielded < n:
            # window counts submitted-but-UNYIELDED blocks (running +
            # completed-waiting), not just running tasks: under
            # head-of-line blocking (block 0 slow, 1..N fast) counting
            # only running tasks would submit — and materialize — the
            # whole dataset while waiting to yield index 0
            while submitted < n and \
                    submitted - yielded < self.max_in_flight:
                ref = _run_stages.remote(block_refs[submitted], stages)
                inflight[ref] = submitted
                submitted += 1
            while yielded in done:
                if stats is not None:
                    stats.blocks += 1
                    if stats.first_block_s is None:
                        stats.first_block_s = time.perf_counter() - t0
                    stats.wall_s = time.perf_counter() - t0
                yield done.pop(yielded)
                yielded += 1
            if yielded >= n:
                break
            if not inflight:
                continue
            ready, _ = ray_tpu.wait(list(inflight), num_returns=1,
                                    timeout=600.0)
            for r in ready:
                done[inflight.pop(r)] = r
