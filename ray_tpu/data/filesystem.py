"""Pluggable filesystem seam under Data IO and spill.

Reference analog: the pyarrow-filesystem plumbing of
``data/datasource/file_based_datasource.py:181`` (every reader/writer
takes a ``filesystem``) and the smart_open/remote spill path of
``_private/external_storage.py:445``.

Paths carry their scheme: ``/x`` or ``file:///x`` → local disk,
``mem://bucket/x`` → in-process memory store (unit tests),
``kv://x`` → the cluster KV (a REAL remote scheme inside any running
cluster: readable/writable from every worker, no external service
needed), and ``s3:// gs:// hdfs://`` delegate to ``pyarrow.fs`` when
its bindings are available.  ``register_filesystem`` adds schemes —
the plugin hook mirroring the reference's fsspec registry.
"""

from __future__ import annotations

import io
import os
import posixpath
from typing import Callable, Dict, List, Optional, Tuple

_REGISTRY: Dict[str, Callable[[], "FileSystem"]] = {}


def register_filesystem(scheme: str,
                        factory: Callable[[], "FileSystem"]) -> None:
    """Plugin hook: map ``scheme://`` paths to a FileSystem factory."""
    _REGISTRY[scheme] = factory


def resolve(path: str) -> Tuple["FileSystem", str]:
    """Split a path into (filesystem, scheme-less path)."""
    if "://" not in path:
        return LocalFileSystem(), path
    scheme, rest = path.split("://", 1)
    if scheme == "file":
        return LocalFileSystem(), "/" + rest.lstrip("/")
    if scheme in _REGISTRY:
        return _REGISTRY[scheme](), rest
    if scheme == "mem":
        return MemoryFileSystem(), rest
    if scheme == "kv":
        return KVFileSystem(), rest
    # cloud schemes: the ArrowFileSystem binds the full URI at
    # construction; the path operand is the URI itself
    return ArrowFileSystem(path), path


class FileSystem:
    """Minimal surface every backend implements; binary IO only."""

    def open_input(self, path: str):
        raise NotImplementedError

    def open_output(self, path: str):
        raise NotImplementedError

    def list(self, path: str, suffix: str = "") -> List[str]:
        """Files under ``path`` (or [path] if it names a file)."""
        raise NotImplementedError

    def delete(self, path: str) -> None:
        raise NotImplementedError

    def exists(self, path: str) -> bool:
        raise NotImplementedError

    def size(self, path: str) -> Optional[int]:
        """Object size in bytes without reading the payload where the
        backend allows; None if absent.  Default falls back to a full
        read — override where metadata is cheap."""
        try:
            with self.open_input(path) as f:
                return len(f.read())
        except FileNotFoundError:
            return None

    def list_tree(self, path: str) -> List[str]:
        """Every file under ``path`` recursively (sync/restore walks).
        Flat-keyed backends (kv/mem) already list recursively."""
        return self.list(path)


class LocalFileSystem(FileSystem):
    def open_input(self, path: str):
        return open(path, "rb")

    def open_output(self, path: str):
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        return open(path, "wb")

    def list(self, path: str, suffix: str = "") -> List[str]:
        if os.path.isdir(path):
            return sorted(
                os.path.join(path, f) for f in os.listdir(path)
                if f.endswith(suffix))
        return [path] if os.path.exists(path) else []

    def delete(self, path: str) -> None:
        try:
            os.unlink(path)
        except OSError:
            pass

    def exists(self, path: str) -> bool:
        return os.path.exists(path)

    def size(self, path: str) -> Optional[int]:
        try:
            return os.path.getsize(path)
        except OSError:
            return None

    def list_tree(self, path: str) -> List[str]:
        if not os.path.isdir(path):
            return [path] if os.path.exists(path) else []
        out = []
        for root, _dirs, files in os.walk(path):
            out.extend(os.path.join(root, f) for f in files)
        return sorted(out)


class _MemFile(io.BytesIO):
    """Write buffer that commits atomically on CLEAN close — an
    exception inside a ``with`` block discards the partial write
    instead of publishing a truncated object (parity with the local
    path's tmp+rename atomicity)."""

    def __init__(self, commit: Callable[[bytes], None]):
        super().__init__()
        self._commit = commit
        self._failed = False

    def __exit__(self, exc_type, exc, tb):
        self._failed = exc_type is not None
        return super().__exit__(exc_type, exc, tb)

    def discard(self):
        self._failed = True
        super().close()

    def close(self):
        if not self.closed and not self._failed:
            self._commit(self.getvalue())
        super().close()


#: process-global store backing mem:// (unit tests / single-process)
_MEM: Dict[str, bytes] = {}


class MemoryFileSystem(FileSystem):
    """In-process bytes store — the mockable 'remote' backend for tests
    (deterministic, inspectable, no disk)."""

    def open_input(self, path: str):
        if path not in _MEM:
            raise FileNotFoundError(f"mem://{path}")
        return io.BytesIO(_MEM[path])

    def open_output(self, path: str):
        return _MemFile(lambda data: _MEM.__setitem__(path, data))

    def list(self, path: str, suffix: str = "") -> List[str]:
        if path in _MEM:
            return [path]
        prefix = path.rstrip("/") + "/"
        return sorted(k for k in _MEM
                      if k.startswith(prefix) and k.endswith(suffix))

    def delete(self, path: str) -> None:
        _MEM.pop(path, None)

    def exists(self, path: str) -> bool:
        return path in _MEM or bool(self.list(path))

    def size(self, path: str) -> Optional[int]:
        data = _MEM.get(path)
        return None if data is None else len(data)


class KVFileSystem(FileSystem):
    """Cluster-KV-backed filesystem: a genuinely remote scheme inside a
    running cluster — every worker reads/writes through the GCS, so
    read tasks and spill work across processes with zero external
    dependencies.  Sized for metadata/modest blocks, not bulk data
    (the KV is in the GCS's memory)."""

    _PREFIX = "fs/"

    def _cw(self):
        from ray_tpu._private import worker_context

        return worker_context.core_worker()

    def open_input(self, path: str):
        raw = self._cw().kv_get(self._PREFIX + path)
        if raw is None:
            raise FileNotFoundError(f"kv://{path}")
        return io.BytesIO(raw)

    def open_output(self, path: str):
        cw = self._cw()
        return _MemFile(
            lambda data: cw.kv_put(self._PREFIX + path, data))

    def list(self, path: str, suffix: str = "") -> List[str]:
        cw = self._cw()
        keys = cw.kv_keys(self._PREFIX + path)
        out = []
        for k in keys:
            rel = k[len(self._PREFIX):]
            if rel == path or (rel.startswith(path.rstrip("/") + "/")
                               and rel.endswith(suffix)):
                out.append(rel)
        return sorted(out)

    def delete(self, path: str) -> None:
        self._cw().kv_del(self._PREFIX + path)

    def exists(self, path: str) -> bool:
        return self._cw().kv_len(self._PREFIX + path) is not None

    def size(self, path: str) -> Optional[int]:
        # metadata-only: a spill stats poll must not move payloads
        # through the control plane
        return self._cw().kv_len(self._PREFIX + path)


class ArrowFileSystem(FileSystem):
    """Cloud schemes (s3:// gs:// hdfs://) through pyarrow.fs —
    the reference's own remote-IO engine (file_based_datasource.py
    resolves paths with pyarrow filesystems the same way).  Import-
    gated: raises a clear error when the bindings are absent.

    The backend client is constructed once from the URI; every method
    takes a scheme-less operand path (as ``resolve`` hands out), so one
    cached instance serves a whole directory of objects — e.g. the
    spill manager's per-object reads never rebuild an S3 client."""

    def __init__(self, uri: str):
        try:
            from pyarrow import fs as pafs
        except ImportError as e:  # pragma: no cover
            raise ImportError(
                f"pyarrow.fs is required for {uri!r}") from e
        try:
            self._fs, self._base = pafs.FileSystem.from_uri(uri)
        except Exception as e:
            raise ValueError(
                f"cannot resolve filesystem for {uri!r}: {e}") from e
        self._scheme = uri.split("://", 1)[0]

    def _op(self, path: str) -> str:
        if "://" in path:  # full URI passed through resolve()
            return path.split("://", 1)[1]
        return path or self._base

    def open_input(self, path: str):
        # open_input_file (seekable): parquet needs random access for
        # the footer, and spill range reads seek
        return self._fs.open_input_file(self._op(path))

    def open_output(self, path: str):
        return self._fs.open_output_stream(self._op(path))

    def list(self, path: str, suffix: str = "") -> List[str]:
        from pyarrow import fs as pafs

        base = self._op(path)
        info = self._fs.get_file_info(base)
        if info.type == pafs.FileType.File:
            return [f"{self._scheme}://{base}"]
        sel = pafs.FileSelector(base, recursive=False,
                                allow_not_found=True)
        # re-prefix the scheme so each listed path resolves back here
        return sorted(f"{self._scheme}://{f.path}"
                      for f in self._fs.get_file_info(sel)
                      if f.type == pafs.FileType.File
                      and f.path.endswith(suffix))

    def delete(self, path: str) -> None:
        self._fs.delete_file(self._op(path))

    def exists(self, path: str) -> bool:
        from pyarrow import fs as pafs

        return (self._fs.get_file_info(self._op(path)).type
                != pafs.FileType.NotFound)

    def size(self, path: str) -> Optional[int]:
        from pyarrow import fs as pafs

        info = self._fs.get_file_info(self._op(path))
        return None if info.type == pafs.FileType.NotFound else info.size

    def list_tree(self, path: str) -> List[str]:
        from pyarrow import fs as pafs

        base = self._op(path)
        info = self._fs.get_file_info(base)
        if info.type == pafs.FileType.File:
            return [f"{self._scheme}://{base}"]
        sel = pafs.FileSelector(base, recursive=True,
                                allow_not_found=True)
        return sorted(f"{self._scheme}://{f.path}"
                      for f in self._fs.get_file_info(sel)
                      if f.type == pafs.FileType.File)


def join(base: str, *parts: str) -> str:
    """Scheme-aware path join (posix semantics for remote schemes)."""
    if "://" in base:
        scheme, rest = base.split("://", 1)
        return f"{scheme}://{posixpath.join(rest, *parts)}"
    return os.path.join(base, *parts)
