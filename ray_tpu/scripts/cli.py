"""Cluster CLI (reference analog: python/ray/scripts/scripts.py —
`ray start/stop/status/...`).  Run as `python -m ray_tpu <cmd>`.

`start --head` runs GCS + a node manager in the foreground (daemonize
with --block=false + nohup/systemd as you prefer); `start --address`
joins an existing head; `status` prints the cluster resource summary;
`stop` kills nodes started on this host.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import time

_ADDR_FILE = "/tmp/raytpu/ray_current_cluster"
_PID_DIR = "/tmp/raytpu/pids"


def _write_pidfile(role: str) -> str:
    os.makedirs(_PID_DIR, exist_ok=True)
    path = os.path.join(_PID_DIR, f"{role}-{os.getpid()}.pid")
    with open(path, "w") as f:
        f.write(str(os.getpid()))
    return path


def cmd_start(args) -> int:
    from ray_tpu._private.config import Config
    from ray_tpu._private.node import Node

    config = Config().apply_env()
    if args.head:
        gcs_address = f"{args.host}:{args.port}"
        node = Node(head=True, num_cpus=args.num_cpus,
                    num_tpus=args.num_tpus,
                    object_store_memory=args.object_store_memory,
                    config=config, gcs_address=gcs_address)
        node.start()
        os.makedirs(os.path.dirname(_ADDR_FILE), exist_ok=True)
        with open(_ADDR_FILE, "w") as f:
            f.write(node.gcs_address)
        print(f"head started; GCS at {node.gcs_address}")
        print(f"attach drivers with ray_tpu.init("
              f"address={node.gcs_address!r})")
        print(f"join workers with: python -m ray_tpu start "
              f"--address {node.gcs_address}")
    else:
        address = args.address or _read_addr()
        if not address:
            print("--address required (no local cluster found)",
                  file=sys.stderr)
            return 1
        node = Node(head=False, num_cpus=args.num_cpus,
                    num_tpus=args.num_tpus,
                    object_store_memory=args.object_store_memory,
                    config=config, gcs_address=address)
        node.start()
        print(f"node {node.node_id.hex()[:12]} joined {address}")

    # Mark this node as process-backed: shutdown_node (chaos tooling)
    # hard-exits instead of just closing the in-process server.
    os.environ["RAYTPU_NODE_PROCESS"] = "1"
    pidfile = _write_pidfile("head" if args.head else "node")
    stop = {"flag": False}

    def _sig(_s, _f):
        stop["flag"] = True

    signal.signal(signal.SIGTERM, _sig)
    signal.signal(signal.SIGINT, _sig)
    try:
        while not stop["flag"]:
            time.sleep(0.5)
    finally:
        node.stop()
        for p in (pidfile, _ADDR_FILE if args.head else None):
            if p:
                try:
                    os.unlink(p)
                except OSError:
                    pass
    return 0


def _read_addr() -> str:
    try:
        with open(_ADDR_FILE) as f:
            return f.read().strip()
    except OSError:
        return ""


def cmd_stop(_args) -> int:
    n = 0
    if os.path.isdir(_PID_DIR):
        for name in os.listdir(_PID_DIR):
            try:
                pid = int(open(os.path.join(_PID_DIR, name)).read())
                os.kill(pid, signal.SIGTERM)
                n += 1
            except (OSError, ValueError):
                pass
            try:
                os.unlink(os.path.join(_PID_DIR, name))
            except OSError:
                pass
    print(f"signalled {n} node process(es)")
    return 0


def cmd_status(args) -> int:
    import ray_tpu

    address = args.address or _read_addr()
    if not address:
        print("no cluster address (start one or pass --address)",
              file=sys.stderr)
        return 1
    ray_tpu.init(address=address, num_cpus=0, num_tpus=0)
    try:
        nodes = ray_tpu.nodes()
        total = ray_tpu.cluster_resources()
        avail = ray_tpu.available_resources()
        print(f"{len(nodes)} node(s) @ {address}")
        for n in nodes:
            print(f"  {n['NodeID'][:12]} alive={n['Alive']} "
                  f"total={n['Resources']}")
        print("cluster totals:", json.dumps(total))
        print("available:   ", json.dumps(avail))
    finally:
        ray_tpu.shutdown()
    return 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="ray_tpu", description="TPU-native distributed runtime CLI")
    sub = parser.add_subparsers(dest="cmd", required=True)

    p_start = sub.add_parser("start", help="start a head or worker node")
    p_start.add_argument("--head", action="store_true")
    p_start.add_argument("--address", default="")
    p_start.add_argument("--host", default="0.0.0.0")
    p_start.add_argument("--port", type=int, default=6380)
    p_start.add_argument("--num-cpus", type=int, default=None,
                         dest="num_cpus")
    p_start.add_argument("--num-tpus", type=int, default=None,
                         dest="num_tpus")
    p_start.add_argument("--object-store-memory", type=int, default=None,
                         dest="object_store_memory")
    p_start.set_defaults(fn=cmd_start)

    p_stop = sub.add_parser("stop", help="stop nodes on this host")
    p_stop.set_defaults(fn=cmd_stop)

    p_status = sub.add_parser("status", help="cluster resource summary")
    p_status.add_argument("--address", default="")
    p_status.set_defaults(fn=cmd_status)

    p_list = sub.add_parser(
        "list", help="list cluster state: actors|nodes|tasks|pgs")
    p_list.add_argument("kind",
                        choices=["actors", "nodes", "tasks", "pgs"])
    p_list.add_argument("--address", default="")
    p_list.set_defaults(fn=cmd_list)

    p_sum = sub.add_parser("summary", help="task/actor summaries")
    p_sum.add_argument("--address", default="")
    p_sum.set_defaults(fn=cmd_summary)

    p_tl = sub.add_parser("timeline",
                          help="dump chrome-trace of task events")
    p_tl.add_argument("--address", default="")
    p_tl.add_argument("--out", default="timeline.json")
    p_tl.set_defaults(fn=cmd_timeline)

    # Job submission (reference: dashboard/modules/job/cli.py +
    # `ray job submit/status/logs/stop/list`).
    p_mem = sub.add_parser(
        "memory", help="per-node object store + spill usage")
    p_mem.add_argument("--address", default="")
    p_mem.set_defaults(fn=cmd_memory)

    p_krn = sub.add_parser(
        "kill-random-node",
        help="chaos: kill a random non-head worker node")
    p_krn.add_argument("--address", default="")
    p_krn.set_defaults(fn=cmd_kill_random_node)

    p_submit = sub.add_parser("submit", help="submit a job to the cluster")
    p_submit.add_argument("--address", default="")
    p_submit.add_argument("--working-dir", default="", dest="working_dir")
    p_submit.add_argument("--env", action="append", default=[],
                          help="KEY=VALUE env var for the job")
    p_submit.add_argument("--no-wait", action="store_true", dest="no_wait")
    p_submit.add_argument("entrypoint", nargs=argparse.REMAINDER,
                          help="command to run (prefix with --)")
    p_submit.set_defaults(fn=cmd_submit)

    p_job = sub.add_parser("job", help="job operations")
    job_sub = p_job.add_subparsers(dest="job_cmd", required=True)
    for name in ("list", "status", "logs", "stop"):
        pj = job_sub.add_parser(name)
        pj.add_argument("--address", default="")
        if name != "list":
            pj.add_argument("job_id")
        pj.set_defaults(fn=cmd_job, job_cmd=name)

    p_rllib = sub.add_parser(
        "rllib", help="train / evaluate RLlib algorithms by name "
                      "(reference: the `rllib` CLI)")
    rl_sub = p_rllib.add_subparsers(dest="rllib_cmd", required=True)
    p_rt = rl_sub.add_parser("train")
    p_rt.add_argument("--run", required=True,
                      help="registry name, e.g. PPO (see "
                           "`rllib algorithms`)")
    p_rt.add_argument("--env", required=True,
                      help="gymnasium env id, e.g. CartPole-v1")
    p_rt.add_argument("--stop-iters", type=int, default=10,
                      dest="stop_iters")
    p_rt.add_argument("--stop-reward", type=float, default=None,
                      dest="stop_reward")
    p_rt.add_argument("--config", default="{}",
                      help="JSON of Config field overrides")
    p_rt.add_argument("--checkpoint-dir", default="",
                      dest="checkpoint_dir",
                      help="save the final state here")
    p_rt.set_defaults(fn=cmd_rllib_train)
    p_re = rl_sub.add_parser("evaluate")
    p_re.add_argument("checkpoint", help="path from `rllib train "
                                         "--checkpoint-dir`")
    p_re.add_argument("--run", required=True)
    p_re.add_argument("--env", required=True)
    p_re.add_argument("--episodes", type=int, default=10)
    p_re.add_argument("--config", default="{}")
    p_re.set_defaults(fn=cmd_rllib_evaluate)
    p_ra = rl_sub.add_parser("algorithms",
                             help="list registered algorithm names")
    p_ra.set_defaults(fn=cmd_rllib_algorithms)

    args = parser.parse_args(argv)
    return args.fn(args)


def _build_algorithm(args, overrides=None):
    import ray_tpu
    from ray_tpu.rllib.registry import get_algorithm_class

    cls, cfg_cls = get_algorithm_class(args.run, return_config=True)
    if overrides is None:
        overrides = json.loads(args.config)
    overrides.pop("env", None)       # --env wins over a config "env"
    # logical-CPU headroom: rollout workers + a lazy eval worker must
    # co-schedule even on a 1-core box (they are IO/step-bound)
    ray_tpu.init(num_cpus=max(4, (os.cpu_count() or 1) * 2))
    return cls(cfg_cls(env=args.env, **overrides))


def cmd_rllib_train(args) -> int:
    import ray_tpu

    algo = _build_algorithm(args)
    try:
        for _ in range(args.stop_iters):
            result = algo.train()
            print(json.dumps({
                k: result.get(k) for k in
                ("training_iteration", "timesteps_total",
                 "episode_reward_mean", "episodes_total")},
                default=float), flush=True)
            reward = result.get("episode_reward_mean")
            if (args.stop_reward is not None and reward is not None
                    and reward == reward        # not NaN
                    and reward >= args.stop_reward):
                break
        if args.checkpoint_dir:
            path = algo.save(args.checkpoint_dir)
            print(f"checkpoint saved: {path}")
    finally:
        algo.stop()
        ray_tpu.shutdown()
    return 0


def cmd_rllib_evaluate(args) -> int:
    import ray_tpu

    # evaluation uses only the dedicated eval worker — don't spin up
    # the full rollout gang unless the user asked for it
    overrides = json.loads(args.config)
    overrides.setdefault("num_workers", 0)
    algo = _build_algorithm(args, overrides)
    try:
        algo.restore(args.checkpoint)
        algo.config.evaluation_num_episodes = args.episodes
        try:
            result = algo.evaluate()
        except NotImplementedError:
            # no dedicated eval worker (DQN-class algos): greedy
            # in-process rollout through the policy's action surface
            result = _greedy_rollout_eval(algo, args.env,
                                          args.episodes)
        print(json.dumps(result, default=float))
    finally:
        algo.stop()
        ray_tpu.shutdown()
    return 0


def _greedy_rollout_eval(algo, env_id: str, episodes: int):
    import numpy as np

    from ray_tpu.rllib.rollout_worker import _make_env

    policy = getattr(algo, "policy", None) \
        or getattr(algo, "learner_policy", None)
    if policy is None or not hasattr(policy, "compute_actions"):
        raise SystemExit(
            f"{type(algo).__name__} exposes no evaluable policy")
    # greedy where the policy offers it (JaxPolicy); QPolicy's
    # compute_actions defaults to epsilon=0 which IS greedy
    act_fn = getattr(policy, "compute_deterministic_actions",
                     policy.compute_actions)
    env = _make_env(env_id, None)
    space = getattr(env, "action_space", None)
    discrete = space is None or getattr(space, "n", None) is not None
    low = np.asarray(getattr(space, "low", -1.0))
    high = np.asarray(getattr(space, "high", 1.0))
    returns = []
    try:
        for ep in range(episodes):
            obs, _ = env.reset(seed=10_000 + ep)
            total = 0.0
            for _ in range(10_000):
                acts = act_fn(
                    np.asarray(obs, np.float32).ravel()[None])
                a = np.asarray(acts[0] if isinstance(acts, tuple)
                               else acts)
                if discrete:
                    env_a = int(a.ravel()[0])
                else:
                    # continuous policies act in [-1, 1]; rescale to
                    # the env bounds (worker-side convention)
                    env_a = (low + (a.reshape(space.shape) + 1.0)
                             * 0.5 * (high - low))
                obs, r, term, trunc, _ = env.step(env_a)
                total += float(r)
                if term or trunc:
                    break
            returns.append(total)
    finally:
        env.close() if hasattr(env, "close") else None
    return {"episode_reward_mean": float(np.mean(returns)),
            "episodes": episodes, "mode": "greedy_rollout"}


def cmd_rllib_algorithms(_args) -> int:
    from ray_tpu.rllib.registry import registered_algorithms

    for name in registered_algorithms():
        print(name)
    return 0


def _attached(args):
    import contextlib

    import ray_tpu

    @contextlib.contextmanager
    def ctx():
        address = args.address or _read_addr()
        if not address:
            raise SystemExit("no cluster address; pass --address")
        ray_tpu.init(address=address)
        try:
            yield
        finally:
            ray_tpu.shutdown()

    return ctx()


def cmd_list(args) -> int:
    from ray_tpu.util import state

    fns = {"actors": state.list_actors, "nodes": state.list_nodes,
           "tasks": state.list_tasks, "pgs": state.list_placement_groups}
    with _attached(args):
        print(json.dumps(fns[args.kind](), indent=2, default=str))
    return 0


def cmd_summary(args) -> int:
    from ray_tpu.util import state

    with _attached(args):
        print(json.dumps({"tasks": state.summarize_tasks(),
                          "actors": state.summarize_actors()}, indent=2))
    return 0


def cmd_timeline(args) -> int:
    import ray_tpu

    with _attached(args):
        events = ray_tpu.timeline(args.out)
    print(f"wrote {len(events)} events to {args.out}")
    return 0


def _each_node_stats(timeout: float = 10.0):
    """Dial every alive node manager and fetch node_stats."""
    import asyncio

    from ray_tpu._private import protocol, worker_context

    cw = worker_context.core_worker()
    nodes = [n for n in cw.nodes() if n["alive"]]

    async def fetch(addr):
        if addr.startswith("/"):
            conn = await protocol.connect_unix(addr)
        else:
            host, port = addr.rsplit(":", 1)
            conn = await protocol.connect_tcp(host, int(port))
        try:
            return await conn.call("node_stats", {}, timeout=timeout)
        finally:
            await conn.close()

    for n in nodes:
        try:
            yield n, cw.io.run(fetch(n["address"]), timeout=timeout + 2)
        except Exception as e:  # noqa: BLE001 - node mid-death
            yield n, {"error": str(e)}


def cmd_memory(args) -> int:
    """Reference analog: `ray memory` (scripts.py memory command)."""
    with _attached(args):
        out = []
        for n, stats in _each_node_stats():
            store = stats.get("object_store", {})
            out.append({
                "node_id": n["node_id"].hex()[:16],
                "address": n["address"],
                "store_bytes_used": store.get("bytes_used"),
                "store_capacity": store.get("capacity"),
                "store_objects": store.get("num_objects"),
                "evictions": store.get("evictions"),
                "spilled_objects": stats.get("spilled_objects"),
                "spilled_bytes": stats.get("spilled_bytes"),
                "error": stats.get("error"),
            })
        print(json.dumps(out, indent=2))
    return 0


def cmd_kill_random_node(args) -> int:
    """Reference analog: `ray kill-random-node` (scripts.py:1269)."""
    import random

    from ray_tpu._private import protocol, worker_context

    import socket

    with _attached(args):
        cw = worker_context.core_worker()
        raw_addr = args.address or _read_addr()
        if "://" in raw_addr:  # init() accepts ray://host:port URIs
            raw_addr = raw_addr.split("://", 1)[1]
        gcs_host = raw_addr.rsplit(":", 1)[0]
        try:  # hostnames must compare as IPs against node addresses
            gcs_ips = {ai[4][0] for ai in socket.getaddrinfo(
                gcs_host, None)}
        except OSError:
            gcs_ips = {gcs_host}
        gcs_ips |= {gcs_host, "127.0.0.1", "localhost"}

        def is_head(n) -> bool:
            addr = n["address"]
            if addr.startswith("/"):
                return True  # same-host unix node: could host the GCS
            return addr.rsplit(":", 1)[0] in gcs_ips

        candidates = [n for n in cw.nodes()
                      if n["alive"] and not is_head(n)]
        if not candidates:
            print("no safely-killable worker nodes (refusing to risk "
                  "the head)")
            return 1
        victim = random.choice(candidates)

        async def kill(addr):
            host, port = addr.rsplit(":", 1)
            conn = await protocol.connect_tcp(host, int(port)) \
                if not addr.startswith("/") else \
                await protocol.connect_unix(addr)
            try:
                await conn.call("shutdown_node", {}, timeout=5)
            finally:
                await conn.close()

        try:
            cw.io.run(kill(victim["address"]), timeout=10)
        except Exception:  # noqa: BLE001 - it died mid-reply: success
            pass
        print(f"killed node {victim['node_id'].hex()[:16]} "
              f"at {victim['address']}")
    return 0


def cmd_submit(args) -> int:
    import shlex

    from ray_tpu import job as job_api

    entry = list(args.entrypoint)
    if entry and entry[0] == "--":  # drop only the leading separator
        entry = entry[1:]
    if not entry:
        raise SystemExit("no entrypoint; usage: ray_tpu submit -- cmd ...")
    runtime_env = {}
    if args.working_dir:
        runtime_env["working_dir"] = args.working_dir
    if args.env:
        runtime_env["env_vars"] = dict(e.split("=", 1) for e in args.env)
    with _attached(args):
        jid = job_api.submit_job(shlex.join(entry),
                                 runtime_env=runtime_env or None)
        print(f"submitted job {jid}")
        if not args.no_wait:
            info = job_api.wait_job(jid, timeout=24 * 3600)
            print(job_api.get_job_logs(jid))
            print(f"job {jid} finished: {info.status} {info.message}")
            return 0 if info.status == "SUCCEEDED" else 1
    return 0


def cmd_job(args) -> int:
    from dataclasses import asdict

    from ray_tpu import job as job_api

    with _attached(args):
        if args.job_cmd == "list":
            print(json.dumps([asdict(j) for j in job_api.list_jobs()],
                             indent=2))
        elif args.job_cmd == "status":
            print(json.dumps(asdict(job_api.get_job_info(args.job_id)),
                             indent=2))
        elif args.job_cmd == "logs":
            print(job_api.get_job_logs(args.job_id))
        elif args.job_cmd == "stop":
            print(job_api.stop_job(args.job_id))
    return 0


if __name__ == "__main__":
    sys.exit(main())
