"""Perf ledger: persistent bench trajectory + regression gates.

Every bench.py run prints JSON metric lines and every sweep_tpu.py run
prints ``SWEEPJSON`` records — and until now they evaporated with the
terminal scrollback (PERF_NOTES: "everything since round 5 unmeasured").
This module gives them a durable home, ``BENCH_HISTORY.jsonl`` at the
repo root, and turns the accumulated trajectory into CI-style verdicts:

    python -m ray_tpu.tools.perfledger ingest bench_out.log
    python -m ray_tpu.tools.perfledger ingest BENCH_r0*.json
    python -m ray_tpu.tools.perfledger check            # exit 1 on regress
    python -m ray_tpu.tools.perfledger report           # markdown trends
    python -m ray_tpu.tools.perfledger publish latest   # arm the baseline

``bench.py`` and ``sweep_tpu.py`` append automatically (``--no-ledger``
opts out), so every future TPU session grows the trajectory instead of
losing it.

Ledger entries are one JSON object per line::

    {"recorded_at": ..., "source": "bench"|"sweep"|"ingest",
     "provenance": {"git_sha", "jax_version", "backend",
                    "device_kind", "hostname"},
     "record": {...original bench/sweep record...},
     "metrics": {name: {"value": v, "unit": u,
                        "higher_is_better": bool}}}

``metrics`` is flattened at append time: bench lines contribute their
``metric`` name directly; sweep records contribute one series per
numeric field, keyed by the variant's canonical hash so e.g. the
``[32, {"remat_policy": "dots_nb"}]`` series never gets compared
against ``[24, {}]``.  Direction is inferred from the name (latencies —
``*_ms`` / ``ttft`` — regress upward; throughput/MFU/hit-rates regress
downward).

``check`` compares the newest point of every series against the
previous point and against ``BASELINE.json``'s ``published`` table
(empty today — the comparison is skipped until someone publishes
numbers) with a relative tolerance band (default 5%), and exits
nonzero when anything regresses — the gate ROADMAP item 3's MFU push
reports through.
"""

from __future__ import annotations

import argparse
import hashlib
import json
import os
import sys
import time
from typing import Any, Dict, Iterable, List, Optional, Tuple

DEFAULT_TOLERANCE = 0.05

#: numeric fields of a sweep record that form trend series (anything
#: else in the record is context, not a measurement)
_SWEEP_FIELDS = (
    "tok_s_chip", "mfu", "mfu_xla", "prefill_ttft_ms", "decode_tok_s",
    "decode_tok_s_chip", "prefix_hit_rate", "slo_attainment",
    "ttft_slo_attainment", "e2e_slo_attainment", "spec_accept_rate",
    "latency_p50_ms", "latency_p95_ms",
    # traffic_fleet records: router-pooled hit rate + per-tenant
    # attainment (all fractions — higher is better via the
    # slo_attainment override, including the ttft-named ones)
    "router_prefix_hit_rate",
    "interactive_ttft_slo_attainment",
    "interactive_e2e_slo_attainment",
    "batch_ttft_slo_attainment", "batch_e2e_slo_attainment",
    # tracebus per-token anatomy (itl = inter-token latency, ms →
    # lower is better via the _ms suffix; no override applies)
    "itl_ms_p50", "itl_ms_p99",
    # chunked-prefill A/B (round 14): per-tenant TTFT p99 under the
    # long-prompt mixture — "ttft"/"_ms" mark these lower-is-better
    # (unlike the *_ttft_slo_attainment fractions above)
    "interactive_ttft_ms_p99", "batch_ttft_ms_p99",
    # trainwatch (train/goodput.py): productive-device-time ratio
    # (higher via the goodput override) + input-stall percentiles
    "train_goodput", "train_data_wait_ms_p50", "train_data_wait_ms_p99",
    # kvscope (serve/kvscope.py): KV pool pressure + cache-thrash
    # waste — both fractions where SMALLER is better ("occupancy" /
    # "waste" below; no higher-is-better override contains either)
    "kv_occupancy_p95", "reprefill_waste_frac",
    # tiered host-RAM KV cache (serve/kv_tier.py): fraction of
    # second-chance probes the tier absorbed — higher is better via
    # the "hit_rate" override below
    "kv_tier_hit_rate",
    # disaggregated prefill/decode (traffic_disagg records): tail
    # cost of the block-granular KV handoff hop — "_ms" marks it
    # lower-is-better
    "handoff_ms_p99",
    # healthwatch (serve/health.py, traffic_chaos records): fault
    # injection → DEAD-transition latency — "_ms" marks it
    # lower-is-better (detection latency is the Podracer-style
    # first-class fleet metric)
    "time_to_detect_ms",
)

#: substrings marking a metric where SMALLER is better
_LOWER_IS_BETTER = ("_ms", "ttft", "latency", "_bytes", "compile",
                    "occupancy", "waste")

#: substrings that trump _LOWER_IS_BETTER: "ttft_slo_attainment"
#: contains "ttft" but is a fraction where BIGGER is better,
#: "goodput" is a productive-time fraction regardless of neighbors,
#: and "hit_rate" covers prefix/router/kv-tier cache hit fractions
_HIGHER_OVERRIDES = ("slo_attainment", "accept_rate", "goodput",
                     "hit_rate")

#: substrings marking a metric where BIGGER is better in its own right
#: (throughput and utilization).  Every _SWEEP_FIELDS entry must match
#: at least one token across the three tuples — graftcheck's
#: perfledger-direction rule enforces it, so a new sweep field whose
#: name resolves to no explicit direction (the near-miss class PR
#: 10/13 each fixed by hand) fails lint instead of silently getting
#: "higher" by fallthrough.
_HIGHER_IS_BETTER = ("tok_s", "mfu")


def repo_root() -> str:
    """The repo checkout this installed/source tree lives in (ledger
    and BASELINE.json live at its root)."""
    return os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))


def history_path(path: Optional[str] = None) -> str:
    if path:
        return path
    env = os.environ.get("RAYTPU_BENCH_HISTORY")
    if env:
        return env
    return os.path.join(repo_root(), "BENCH_HISTORY.jsonl")


def baseline_path(path: Optional[str] = None) -> str:
    return path or os.path.join(repo_root(), "BASELINE.json")


def higher_is_better(name: str) -> bool:
    low = name.lower()
    if any(tok in low for tok in _HIGHER_OVERRIDES):
        return True
    if any(tok in low for tok in _LOWER_IS_BETTER):
        return False
    # explicit throughput/utilization tokens and the free-form
    # fallthrough both resolve higher; the distinction matters to the
    # perfledger-direction lint, which accepts only explicit matches
    # for _SWEEP_FIELDS entries
    return True


def explicit_direction(name: str) -> Optional[bool]:
    """True/False when ``name`` matches an explicit direction token,
    None when it would only resolve by fallthrough.  graftcheck's
    perfledger-direction rule requires every _SWEEP_FIELDS entry to
    resolve explicitly."""
    low = name.lower()
    if any(tok in low for tok in _HIGHER_OVERRIDES):
        return True
    if any(tok in low for tok in _LOWER_IS_BETTER):
        return False
    if any(tok in low for tok in _HIGHER_IS_BETTER):
        return True
    return None


def _variant_key(variant: Dict[str, Any]) -> str:
    """Stable 8-hex identity for one sweep variant (mode + every knob),
    so series only ever compare like-for-like configurations."""
    canon = json.dumps(variant, sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(canon.encode()).hexdigest()[:8]


def extract_metrics(record: Dict[str, Any]) -> Dict[str, Dict[str, Any]]:
    """Flatten one bench / sweep record into named numeric series."""
    out: Dict[str, Dict[str, Any]] = {}
    if "metric" in record and isinstance(
            record.get("value"), (int, float)):
        name = str(record["metric"])
        out[name] = {"value": float(record["value"]),
                     "unit": record.get("unit"),
                     "higher_is_better": higher_is_better(name)}
        return out
    variant = record.get("sweep")
    if isinstance(variant, dict) and "failed" not in record:
        mode = variant.get("mode", "train")
        vk = _variant_key(variant)
        for field in _SWEEP_FIELDS:
            val = record.get(field)
            if isinstance(val, (int, float)):
                name = f"sweep.{mode}.{field}#{vk}"
                out[name] = {"value": float(val), "unit": None,
                             "higher_is_better": higher_is_better(field)}
    return out


# ---------------------------------------------------------------------------
# ingest
# ---------------------------------------------------------------------------

def parse_text(text: str) -> List[Dict[str, Any]]:
    """Recover bench/sweep records from arbitrary captured output:
    bench JSON lines, ``SWEEPJSON``-prefixed lines, whole-file JSON
    (including the historical ``BENCH_rNN.json`` wrappers whose payload
    sits under ``parsed``), or lists of any of those.  Non-records are
    skipped, never fatal."""

    def _norm(obj: Any) -> List[Dict[str, Any]]:
        if isinstance(obj, list):
            return [r for item in obj for r in _norm(item)]
        if not isinstance(obj, dict):
            return []
        if isinstance(obj.get("parsed"), dict):
            return _norm(obj["parsed"])
        if "metric" in obj or "sweep" in obj:
            return [obj]
        return []

    try:
        return _norm(json.loads(text))
    except ValueError:
        pass
    records: List[Dict[str, Any]] = []
    for line in text.splitlines():
        line = line.strip()
        if line.startswith("SWEEPJSON "):
            line = line[len("SWEEPJSON "):]
        if not line.startswith("{"):
            continue
        try:
            records.extend(_norm(json.loads(line)))
        except ValueError:
            continue
    return records


_provenance_cache: Optional[Dict[str, Any]] = None


def provenance() -> Dict[str, Any]:
    """Where/what produced a ledger record: git SHA, jax version,
    backend + device kind, hostname.  Stamped on every entry at
    ``append_records`` time so cross-session BENCH_HISTORY series are
    honestly comparable — the autopilot's staleness logic keys off the
    SHA, and its CPU-vs-TPU gating off the backend.  Every field is
    best-effort ``None``; backend/device are only read when jax is
    ALREADY imported (ingesting a log must not drag a backend up just
    to stamp it).  Cached per process."""
    global _provenance_cache
    if _provenance_cache is not None:
        return dict(_provenance_cache)
    import socket
    import subprocess

    out: Dict[str, Any] = {"git_sha": None, "jax_version": None,
                           "backend": None, "device_kind": None,
                           "hostname": None}
    try:
        r = subprocess.run(["git", "-C", repo_root(), "rev-parse",
                            "--short", "HEAD"],
                           capture_output=True, text=True, timeout=10)
        if r.returncode == 0:
            out["git_sha"] = r.stdout.strip() or None
    except Exception:  # noqa: BLE001 - no git / not a checkout
        pass
    try:
        import importlib.metadata as _md

        out["jax_version"] = _md.version("jax")
    except Exception:  # noqa: BLE001 - jax not installed
        pass
    jax = sys.modules.get("jax")
    if jax is not None:
        try:
            out["backend"] = jax.default_backend()
            out["device_kind"] = jax.devices()[0].device_kind
        except Exception:  # noqa: BLE001 - backend init failed
            pass
    try:
        out["hostname"] = socket.gethostname()
    except Exception:  # noqa: BLE001
        pass
    _provenance_cache = dict(out)
    return out


def append_records(records: Iterable[Dict[str, Any]], source: str,
                   path: Optional[str] = None) -> int:
    """Append each record (with its flattened metric series and the
    process provenance stamp) as one ledger line; returns how many
    lines landed.  Records with no numeric series (audit summaries,
    failures) are kept too — they document the trajectory — but
    contribute nothing to ``check``."""
    path = history_path(path)
    stamp = time.strftime("%Y-%m-%d %H:%M:%S")
    prov = provenance()
    n = 0
    with open(path, "a") as f:
        for rec in records:
            if not isinstance(rec, dict):
                continue
            entry = {"recorded_at": stamp, "source": source,
                     "provenance": prov,
                     "record": rec, "metrics": extract_metrics(rec)}
            f.write(json.dumps(entry, sort_keys=True) + "\n")
            n += 1
    return n


def load_history(path: Optional[str] = None) -> List[Dict[str, Any]]:
    path = history_path(path)
    entries: List[Dict[str, Any]] = []
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    obj = json.loads(line)
                except ValueError:
                    continue
                if isinstance(obj, dict):
                    entries.append(obj)
    except OSError:
        pass
    return entries


def metric_series(entries: List[Dict[str, Any]]
                  ) -> Dict[str, List[Tuple[int, Dict[str, Any]]]]:
    """name -> [(entry_index, {"value", "unit", "higher_is_better"})]
    in ledger order."""
    series: Dict[str, List[Tuple[int, Dict[str, Any]]]] = {}
    for i, entry in enumerate(entries):
        for name, m in (entry.get("metrics") or {}).items():
            if isinstance(m, dict) and isinstance(
                    m.get("value"), (int, float)):
                series.setdefault(name, []).append((i, m))
    return series


# ---------------------------------------------------------------------------
# check
# ---------------------------------------------------------------------------

def _classify(new: float, ref: float, better: bool,
              tolerance: float) -> Tuple[str, float]:
    """(verdict, relative_delta) of `new` vs `ref` under a relative
    tolerance band.  delta is signed in the metric's raw direction."""
    if ref == 0:
        delta = 0.0 if new == 0 else float("inf") * (1 if new > 0 else -1)
    else:
        delta = (new - ref) / abs(ref)
    gain = delta if better else -delta
    if gain < -tolerance:
        return "regress", delta
    if gain > tolerance:
        return "improve", delta
    return "flat", delta


def load_baseline(path: Optional[str] = None) -> Dict[str, float]:
    """BASELINE.json's ``published`` table as {metric: value}; empty
    when nothing is published (the common case today) — then the
    baseline comparison is skipped, not failed."""
    try:
        with open(baseline_path(path)) as f:
            pub = json.load(f).get("published") or {}
    except Exception:  # noqa: BLE001 - missing/invalid baseline file
        return {}
    return {k: float(v) for k, v in pub.items()
            if isinstance(v, (int, float))}


def check(history: Optional[str] = None,
          baseline: Optional[str] = None,
          tolerance: float = DEFAULT_TOLERANCE) -> Dict[str, Any]:
    """Verdict for the newest point of every metric series vs its
    previous point and vs the published baseline.  ``ok`` is False iff
    anything regressed beyond the tolerance band."""
    entries = load_history(history)
    series = metric_series(entries)
    published = load_baseline(baseline)
    verdicts: Dict[str, Any] = {}
    ok = True
    for name, points in sorted(series.items()):
        idx, cur = points[-1]
        v: Dict[str, Any] = {"value": cur["value"],
                             "unit": cur.get("unit"),
                             "higher_is_better": cur["higher_is_better"],
                             "entry": idx, "n_points": len(points)}
        if len(points) >= 2:
            prev = points[-2][1]["value"]
            verdict, delta = _classify(cur["value"], prev,
                                       cur["higher_is_better"],
                                       tolerance)
            v.update(prev=prev, delta=round(delta, 4), verdict=verdict)
        else:
            v.update(prev=None, delta=None, verdict="new")
        if name in published:
            bverdict, bdelta = _classify(cur["value"], published[name],
                                         cur["higher_is_better"],
                                         tolerance)
            v.update(baseline=published[name],
                     vs_baseline=round(bdelta, 4),
                     baseline_verdict=bverdict)
            if bverdict == "regress":
                ok = False
        if v["verdict"] == "regress":
            ok = False
        verdicts[name] = v
    return {"ok": ok, "tolerance": tolerance,
            "entries": len(entries), "verdicts": verdicts}


# ---------------------------------------------------------------------------
# report
# ---------------------------------------------------------------------------

def _fmt(v: Any) -> str:
    if v is None:
        return "—"
    if isinstance(v, float):
        return f"{v:,.4g}" if abs(v) < 1000 else f"{v:,.0f}"
    return str(v)


def report(history: Optional[str] = None,
           baseline: Optional[str] = None,
           tolerance: float = DEFAULT_TOLERANCE) -> str:
    """Markdown trend table over the whole ledger."""
    entries = load_history(history)
    result = check(history, baseline, tolerance)
    lines = [
        "# Perf ledger trend report",
        "",
        f"{len(entries)} ledger entries, "
        f"{len(result['verdicts'])} metric series, "
        f"tolerance ±{tolerance:.0%}.",
        "",
        "| metric | points | previous | latest | delta | verdict |",
        "|---|---:|---:|---:|---:|---|",
    ]
    for name, v in result["verdicts"].items():
        delta = ("—" if v["delta"] is None
                 else f"{v['delta']:+.1%}")
        arrow = {"improve": "improve ✅", "regress": "regress ❌",
                 "flat": "flat", "new": "new"}[v["verdict"]]
        lines.append(f"| `{name}` | {v['n_points']} "
                     f"| {_fmt(v['prev'])} | {_fmt(v['value'])} "
                     f"| {delta} | {arrow} |")
    lines.append("")
    if not any(v.get("baseline") is not None
               for v in result["verdicts"].values()):
        lines.append("No published baselines in BASELINE.json "
                     "(`published: {}`) — verdicts are vs the previous "
                     "ledger point only.")
    lines.append("")
    lines.append("ok" if result["ok"] else
                 "REGRESSIONS DETECTED — see verdicts above.")
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# publish
# ---------------------------------------------------------------------------

def entry_backend(entry: Dict[str, Any]) -> Optional[str]:
    """Best available backend label for one ledger entry: the
    provenance stamp when present (post round-12 entries), else the
    bench record's own ``detail.backend``."""
    prov = entry.get("provenance") or {}
    if prov.get("backend"):
        return str(prov["backend"])
    rec = entry.get("record") or {}
    detail = rec.get("detail") if isinstance(rec, dict) else None
    if isinstance(detail, dict) and detail.get("backend"):
        return str(detail["backend"])
    return None


def publish(selector: str = "latest",
            history: Optional[str] = None,
            baseline: Optional[str] = None,
            allow_cpu: bool = False,
            dry_run: bool = False) -> Dict[str, Any]:
    """Promote one ledger entry's metrics into BASELINE.json's
    ``published`` table — the act that arms the baseline gate ``check``
    has been skipping while the table sat empty.

    ``selector`` is a 0-based history index or ``latest`` (the newest
    entry that carries metrics).  CPU-backend entries are refused
    unless ``allow_cpu`` — a laptop smoke number must never become the
    bar TPU sessions are graded against.  ``dry_run`` computes the
    diff without writing.  Returns ``{entry, backend, diff, written}``;
    raises ValueError on a bad selector or a refused publish."""
    entries = load_history(history)
    with_metrics = [(i, e) for i, e in enumerate(entries)
                    if e.get("metrics")]
    if not with_metrics:
        raise ValueError("ledger has no entries with metrics")
    if selector == "latest":
        idx, entry = with_metrics[-1]
    else:
        idx = int(selector)
        if not 0 <= idx < len(entries):
            raise ValueError(f"history index {idx} out of range "
                             f"(0..{len(entries) - 1})")
        entry = entries[idx]
        if not entry.get("metrics"):
            raise ValueError(f"history entry {idx} carries no metrics "
                             f"(source={entry.get('source')!r})")
    backend = entry_backend(entry)
    if backend == "cpu" and not allow_cpu:
        raise ValueError(
            f"history entry {idx} was measured on the CPU backend — "
            f"refusing to publish a smoke number as the baseline "
            f"(pass --allow-cpu to override)")
    bpath = baseline_path(baseline)
    try:
        with open(bpath) as f:
            data = json.load(f)
    except Exception:  # noqa: BLE001 - missing/invalid baseline file
        data = {}
    published = dict(data.get("published") or {})
    diff: Dict[str, Any] = {}
    for name, m in sorted(entry["metrics"].items()):
        if not isinstance(m, dict) or not isinstance(
                m.get("value"), (int, float)):
            continue
        new = float(m["value"])
        old = published.get(name)
        if old != new:
            diff[name] = {"old": old, "new": new}
        published[name] = new
    if not dry_run:
        data["published"] = published
        tmp = bpath + ".tmp"
        with open(tmp, "w") as f:
            json.dump(data, f, indent=2)
            f.write("\n")
        os.replace(tmp, bpath)
    return {"entry": idx, "backend": backend, "diff": diff,
            "published": published, "written": not dry_run,
            "baseline_path": bpath}


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------

def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m ray_tpu.tools.perfledger",
        description="persistent bench/sweep trajectory with "
                    "regression gates")
    ap.add_argument("--history", default=None,
                    help="ledger path (default: <repo>/"
                         "BENCH_HISTORY.jsonl, env RAYTPU_BENCH_HISTORY"
                         " overrides)")
    sub = ap.add_subparsers(dest="cmd", required=True)
    p_in = sub.add_parser("ingest",
                          help="parse bench/sweep output into the "
                               "ledger")
    p_in.add_argument("files", nargs="*",
                      help="bench logs / JSON files ('-' or empty = "
                           "stdin)")
    p_in.add_argument("--source", default="ingest")
    p_chk = sub.add_parser("check",
                           help="exit 1 when the newest point of any "
                                "series regressed")
    p_chk.add_argument("--baseline", default=None)
    p_chk.add_argument("--tolerance", type=float,
                       default=DEFAULT_TOLERANCE)
    p_rep = sub.add_parser("report", help="markdown trend report")
    p_rep.add_argument("--baseline", default=None)
    p_rep.add_argument("--tolerance", type=float,
                       default=DEFAULT_TOLERANCE)
    p_rep.add_argument("--out", default="",
                       help="write the report here as well as stdout")
    p_pub = sub.add_parser(
        "publish",
        help="promote one entry's metrics into BASELINE.json's "
             "'published' table (arms the baseline gate)")
    p_pub.add_argument("selector", nargs="?", default="latest",
                       help="0-based history index, or 'latest' "
                            "(newest entry with metrics)")
    p_pub.add_argument("--baseline", default=None)
    p_pub.add_argument("--allow-cpu", action="store_true",
                       help="publish even a CPU-backend record "
                            "(refused by default: a smoke number must "
                            "not become the TPU bar)")
    p_pub.add_argument("--dry-run", action="store_true",
                       help="print the diff without writing "
                            "BASELINE.json")
    args = ap.parse_args(argv)

    if args.cmd == "ingest":
        records: List[Dict[str, Any]] = []
        if not args.files or args.files == ["-"]:
            records.extend(parse_text(sys.stdin.read()))
        else:
            for fname in args.files:
                try:
                    with open(fname) as f:
                        records.extend(parse_text(f.read()))
                except OSError as e:
                    print(f"perfledger: skipping {fname}: {e}",
                          file=sys.stderr)
        n = append_records(records, source=args.source,
                           path=args.history)
        print(f"perfledger: appended {n} record(s) to "
              f"{history_path(args.history)}")
        return 0

    if args.cmd == "check":
        result = check(args.history, args.baseline, args.tolerance)
        print(json.dumps(result, indent=1, sort_keys=True))
        return 0 if result["ok"] else 1

    if args.cmd == "publish":
        try:
            res = publish(args.selector, history=args.history,
                          baseline=args.baseline,
                          allow_cpu=args.allow_cpu,
                          dry_run=args.dry_run)
        except ValueError as e:
            print(f"perfledger: publish refused: {e}", file=sys.stderr)
            return 2
        verb = "would publish" if args.dry_run else "published"
        print(f"perfledger: {verb} entry {res['entry']} "
              f"(backend={res['backend']}) -> {res['baseline_path']}")
        for name, d in sorted(res["diff"].items()):
            print(f"  {name}: {_fmt(d['old'])} -> {_fmt(d['new'])}")
        if not res["diff"]:
            print("  (no changes — already published)")
        return 0

    text = report(args.history, args.baseline, args.tolerance)
    print(text)
    if args.out:
        with open(args.out, "w") as f:
            f.write(text + "\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
