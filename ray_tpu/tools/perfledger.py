"""Perf ledger: persistent bench trajectory + regression gates.

Every bench.py run prints JSON metric lines and every sweep_tpu.py run
prints ``SWEEPJSON`` records — and until now they evaporated with the
terminal scrollback (PERF_NOTES: "everything since round 5 unmeasured").
This module gives them a durable home, ``BENCH_HISTORY.jsonl`` at the
repo root, and turns the accumulated trajectory into CI-style verdicts:

    python -m ray_tpu.tools.perfledger ingest bench_out.log
    python -m ray_tpu.tools.perfledger ingest BENCH_r0*.json
    python -m ray_tpu.tools.perfledger check            # exit 1 on regress
    python -m ray_tpu.tools.perfledger report           # markdown trends

``bench.py`` and ``sweep_tpu.py`` append automatically (``--no-ledger``
opts out), so every future TPU session grows the trajectory instead of
losing it.

Ledger entries are one JSON object per line::

    {"recorded_at": ..., "source": "bench"|"sweep"|"ingest",
     "record": {...original bench/sweep record...},
     "metrics": {name: {"value": v, "unit": u,
                        "higher_is_better": bool}}}

``metrics`` is flattened at append time: bench lines contribute their
``metric`` name directly; sweep records contribute one series per
numeric field, keyed by the variant's canonical hash so e.g. the
``[32, {"remat_policy": "dots_nb"}]`` series never gets compared
against ``[24, {}]``.  Direction is inferred from the name (latencies —
``*_ms`` / ``ttft`` — regress upward; throughput/MFU/hit-rates regress
downward).

``check`` compares the newest point of every series against the
previous point and against ``BASELINE.json``'s ``published`` table
(empty today — the comparison is skipped until someone publishes
numbers) with a relative tolerance band (default 5%), and exits
nonzero when anything regresses — the gate ROADMAP item 3's MFU push
reports through.
"""

from __future__ import annotations

import argparse
import hashlib
import json
import os
import sys
import time
from typing import Any, Dict, Iterable, List, Optional, Tuple

DEFAULT_TOLERANCE = 0.05

#: numeric fields of a sweep record that form trend series (anything
#: else in the record is context, not a measurement)
_SWEEP_FIELDS = (
    "tok_s_chip", "mfu", "mfu_xla", "prefill_ttft_ms", "decode_tok_s",
    "decode_tok_s_chip", "prefix_hit_rate", "slo_attainment",
    "ttft_slo_attainment", "e2e_slo_attainment", "spec_accept_rate",
    "latency_p50_ms", "latency_p95_ms",
    # traffic_fleet records: router-pooled hit rate + per-tenant
    # attainment (all fractions — higher is better via the
    # slo_attainment override, including the ttft-named ones)
    "router_prefix_hit_rate",
    "interactive_ttft_slo_attainment",
    "interactive_e2e_slo_attainment",
    "batch_ttft_slo_attainment", "batch_e2e_slo_attainment",
)

#: substrings marking a metric where SMALLER is better
_LOWER_IS_BETTER = ("_ms", "ttft", "latency", "_bytes", "compile")

#: substrings that trump _LOWER_IS_BETTER: "ttft_slo_attainment"
#: contains "ttft" but is a fraction where BIGGER is better
_HIGHER_OVERRIDES = ("slo_attainment", "accept_rate")


def repo_root() -> str:
    """The repo checkout this installed/source tree lives in (ledger
    and BASELINE.json live at its root)."""
    return os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))


def history_path(path: Optional[str] = None) -> str:
    if path:
        return path
    env = os.environ.get("RAYTPU_BENCH_HISTORY")
    if env:
        return env
    return os.path.join(repo_root(), "BENCH_HISTORY.jsonl")


def baseline_path(path: Optional[str] = None) -> str:
    return path or os.path.join(repo_root(), "BASELINE.json")


def higher_is_better(name: str) -> bool:
    low = name.lower()
    if any(tok in low for tok in _HIGHER_OVERRIDES):
        return True
    return not any(tok in low for tok in _LOWER_IS_BETTER)


def _variant_key(variant: Dict[str, Any]) -> str:
    """Stable 8-hex identity for one sweep variant (mode + every knob),
    so series only ever compare like-for-like configurations."""
    canon = json.dumps(variant, sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(canon.encode()).hexdigest()[:8]


def extract_metrics(record: Dict[str, Any]) -> Dict[str, Dict[str, Any]]:
    """Flatten one bench / sweep record into named numeric series."""
    out: Dict[str, Dict[str, Any]] = {}
    if "metric" in record and isinstance(
            record.get("value"), (int, float)):
        name = str(record["metric"])
        out[name] = {"value": float(record["value"]),
                     "unit": record.get("unit"),
                     "higher_is_better": higher_is_better(name)}
        return out
    variant = record.get("sweep")
    if isinstance(variant, dict) and "failed" not in record:
        mode = variant.get("mode", "train")
        vk = _variant_key(variant)
        for field in _SWEEP_FIELDS:
            val = record.get(field)
            if isinstance(val, (int, float)):
                name = f"sweep.{mode}.{field}#{vk}"
                out[name] = {"value": float(val), "unit": None,
                             "higher_is_better": higher_is_better(field)}
    return out


# ---------------------------------------------------------------------------
# ingest
# ---------------------------------------------------------------------------

def parse_text(text: str) -> List[Dict[str, Any]]:
    """Recover bench/sweep records from arbitrary captured output:
    bench JSON lines, ``SWEEPJSON``-prefixed lines, whole-file JSON
    (including the historical ``BENCH_rNN.json`` wrappers whose payload
    sits under ``parsed``), or lists of any of those.  Non-records are
    skipped, never fatal."""

    def _norm(obj: Any) -> List[Dict[str, Any]]:
        if isinstance(obj, list):
            return [r for item in obj for r in _norm(item)]
        if not isinstance(obj, dict):
            return []
        if isinstance(obj.get("parsed"), dict):
            return _norm(obj["parsed"])
        if "metric" in obj or "sweep" in obj:
            return [obj]
        return []

    try:
        return _norm(json.loads(text))
    except ValueError:
        pass
    records: List[Dict[str, Any]] = []
    for line in text.splitlines():
        line = line.strip()
        if line.startswith("SWEEPJSON "):
            line = line[len("SWEEPJSON "):]
        if not line.startswith("{"):
            continue
        try:
            records.extend(_norm(json.loads(line)))
        except ValueError:
            continue
    return records


def append_records(records: Iterable[Dict[str, Any]], source: str,
                   path: Optional[str] = None) -> int:
    """Append each record (with its flattened metric series) as one
    ledger line; returns how many lines landed.  Records with no
    numeric series (audit summaries, failures) are kept too — they
    document the trajectory — but contribute nothing to ``check``."""
    path = history_path(path)
    stamp = time.strftime("%Y-%m-%d %H:%M:%S")
    n = 0
    with open(path, "a") as f:
        for rec in records:
            if not isinstance(rec, dict):
                continue
            entry = {"recorded_at": stamp, "source": source,
                     "record": rec, "metrics": extract_metrics(rec)}
            f.write(json.dumps(entry, sort_keys=True) + "\n")
            n += 1
    return n


def load_history(path: Optional[str] = None) -> List[Dict[str, Any]]:
    path = history_path(path)
    entries: List[Dict[str, Any]] = []
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    obj = json.loads(line)
                except ValueError:
                    continue
                if isinstance(obj, dict):
                    entries.append(obj)
    except OSError:
        pass
    return entries


def metric_series(entries: List[Dict[str, Any]]
                  ) -> Dict[str, List[Tuple[int, Dict[str, Any]]]]:
    """name -> [(entry_index, {"value", "unit", "higher_is_better"})]
    in ledger order."""
    series: Dict[str, List[Tuple[int, Dict[str, Any]]]] = {}
    for i, entry in enumerate(entries):
        for name, m in (entry.get("metrics") or {}).items():
            if isinstance(m, dict) and isinstance(
                    m.get("value"), (int, float)):
                series.setdefault(name, []).append((i, m))
    return series


# ---------------------------------------------------------------------------
# check
# ---------------------------------------------------------------------------

def _classify(new: float, ref: float, better: bool,
              tolerance: float) -> Tuple[str, float]:
    """(verdict, relative_delta) of `new` vs `ref` under a relative
    tolerance band.  delta is signed in the metric's raw direction."""
    if ref == 0:
        delta = 0.0 if new == 0 else float("inf") * (1 if new > 0 else -1)
    else:
        delta = (new - ref) / abs(ref)
    gain = delta if better else -delta
    if gain < -tolerance:
        return "regress", delta
    if gain > tolerance:
        return "improve", delta
    return "flat", delta


def load_baseline(path: Optional[str] = None) -> Dict[str, float]:
    """BASELINE.json's ``published`` table as {metric: value}; empty
    when nothing is published (the common case today) — then the
    baseline comparison is skipped, not failed."""
    try:
        with open(baseline_path(path)) as f:
            pub = json.load(f).get("published") or {}
    except Exception:  # noqa: BLE001 - missing/invalid baseline file
        return {}
    return {k: float(v) for k, v in pub.items()
            if isinstance(v, (int, float))}


def check(history: Optional[str] = None,
          baseline: Optional[str] = None,
          tolerance: float = DEFAULT_TOLERANCE) -> Dict[str, Any]:
    """Verdict for the newest point of every metric series vs its
    previous point and vs the published baseline.  ``ok`` is False iff
    anything regressed beyond the tolerance band."""
    entries = load_history(history)
    series = metric_series(entries)
    published = load_baseline(baseline)
    verdicts: Dict[str, Any] = {}
    ok = True
    for name, points in sorted(series.items()):
        idx, cur = points[-1]
        v: Dict[str, Any] = {"value": cur["value"],
                             "unit": cur.get("unit"),
                             "higher_is_better": cur["higher_is_better"],
                             "entry": idx, "n_points": len(points)}
        if len(points) >= 2:
            prev = points[-2][1]["value"]
            verdict, delta = _classify(cur["value"], prev,
                                       cur["higher_is_better"],
                                       tolerance)
            v.update(prev=prev, delta=round(delta, 4), verdict=verdict)
        else:
            v.update(prev=None, delta=None, verdict="new")
        if name in published:
            bverdict, bdelta = _classify(cur["value"], published[name],
                                         cur["higher_is_better"],
                                         tolerance)
            v.update(baseline=published[name],
                     vs_baseline=round(bdelta, 4),
                     baseline_verdict=bverdict)
            if bverdict == "regress":
                ok = False
        if v["verdict"] == "regress":
            ok = False
        verdicts[name] = v
    return {"ok": ok, "tolerance": tolerance,
            "entries": len(entries), "verdicts": verdicts}


# ---------------------------------------------------------------------------
# report
# ---------------------------------------------------------------------------

def _fmt(v: Any) -> str:
    if v is None:
        return "—"
    if isinstance(v, float):
        return f"{v:,.4g}" if abs(v) < 1000 else f"{v:,.0f}"
    return str(v)


def report(history: Optional[str] = None,
           baseline: Optional[str] = None,
           tolerance: float = DEFAULT_TOLERANCE) -> str:
    """Markdown trend table over the whole ledger."""
    entries = load_history(history)
    result = check(history, baseline, tolerance)
    lines = [
        "# Perf ledger trend report",
        "",
        f"{len(entries)} ledger entries, "
        f"{len(result['verdicts'])} metric series, "
        f"tolerance ±{tolerance:.0%}.",
        "",
        "| metric | points | previous | latest | delta | verdict |",
        "|---|---:|---:|---:|---:|---|",
    ]
    for name, v in result["verdicts"].items():
        delta = ("—" if v["delta"] is None
                 else f"{v['delta']:+.1%}")
        arrow = {"improve": "improve ✅", "regress": "regress ❌",
                 "flat": "flat", "new": "new"}[v["verdict"]]
        lines.append(f"| `{name}` | {v['n_points']} "
                     f"| {_fmt(v['prev'])} | {_fmt(v['value'])} "
                     f"| {delta} | {arrow} |")
    lines.append("")
    if not any(v.get("baseline") is not None
               for v in result["verdicts"].values()):
        lines.append("No published baselines in BASELINE.json "
                     "(`published: {}`) — verdicts are vs the previous "
                     "ledger point only.")
    lines.append("")
    lines.append("ok" if result["ok"] else
                 "REGRESSIONS DETECTED — see verdicts above.")
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------

def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m ray_tpu.tools.perfledger",
        description="persistent bench/sweep trajectory with "
                    "regression gates")
    ap.add_argument("--history", default=None,
                    help="ledger path (default: <repo>/"
                         "BENCH_HISTORY.jsonl, env RAYTPU_BENCH_HISTORY"
                         " overrides)")
    sub = ap.add_subparsers(dest="cmd", required=True)
    p_in = sub.add_parser("ingest",
                          help="parse bench/sweep output into the "
                               "ledger")
    p_in.add_argument("files", nargs="*",
                      help="bench logs / JSON files ('-' or empty = "
                           "stdin)")
    p_in.add_argument("--source", default="ingest")
    p_chk = sub.add_parser("check",
                           help="exit 1 when the newest point of any "
                                "series regressed")
    p_chk.add_argument("--baseline", default=None)
    p_chk.add_argument("--tolerance", type=float,
                       default=DEFAULT_TOLERANCE)
    p_rep = sub.add_parser("report", help="markdown trend report")
    p_rep.add_argument("--baseline", default=None)
    p_rep.add_argument("--tolerance", type=float,
                       default=DEFAULT_TOLERANCE)
    p_rep.add_argument("--out", default="",
                       help="write the report here as well as stdout")
    args = ap.parse_args(argv)

    if args.cmd == "ingest":
        records: List[Dict[str, Any]] = []
        if not args.files or args.files == ["-"]:
            records.extend(parse_text(sys.stdin.read()))
        else:
            for fname in args.files:
                try:
                    with open(fname) as f:
                        records.extend(parse_text(f.read()))
                except OSError as e:
                    print(f"perfledger: skipping {fname}: {e}",
                          file=sys.stderr)
        n = append_records(records, source=args.source,
                           path=args.history)
        print(f"perfledger: appended {n} record(s) to "
              f"{history_path(args.history)}")
        return 0

    if args.cmd == "check":
        result = check(args.history, args.baseline, args.tolerance)
        print(json.dumps(result, indent=1, sort_keys=True))
        return 0 if result["ok"] else 1

    text = report(args.history, args.baseline, args.tolerance)
    print(text)
    if args.out:
        with open(args.out, "w") as f:
            f.write(text + "\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
