"""Repo-level registry checks: ``contract-registry`` and
``perfledger-direction``.

Same shape as lint.py's observatory-mapping / autopilot-attribution
rules — import the live registries, diff them, and report any drift as
violations (an import failure IS the finding).

``contract-registry`` single-sources the exact-sum critical path:
``serve/telemetry.py``'s ``CRITICAL_PATH_COMPONENTS`` is the registry,
and every member must stay pinned in each downstream view —

* the tracebus span taxonomy (``tools/tracebus.py``'s
  ``COMPONENT_SPANS`` maps every component to its span name, and each
  named span must still be emitted by ``build_request_spans``);
* the engine-stats golden schema
  (``tests/test_engine_stats_schema.py``'s ``CRITICAL_PATH_KEYS`` ==
  components + ``e2e_ms``, read by ast so the test stays the single
  literal);
* traffic's TTFT decomposition (``serve/traffic.py``'s
  ``_TTFT_COMPONENTS`` is a subset);
* the docs tables (``docs/observability.md`` names every component
  and every trainwatch ``ANATOMY_COMPONENTS`` leg verbatim).

``perfledger-direction`` closes the ``_HIGHER_OVERRIDES`` near-miss
class (PR 10 and PR 13 each patched one by hand): every
``_SWEEP_FIELDS`` entry must resolve to an explicit higher/lower
direction token — a field that would only get a direction by
fallthrough fails lint.
"""

from __future__ import annotations

import ast
import pathlib
from typing import List

from ray_tpu.tools.graftcheck.core import Violation

__all__ = ["contract_registry", "perfledger_direction"]


def _schema_critical_path_keys(root: pathlib.Path):
    """CRITICAL_PATH_KEYS set literal out of the golden-schema test,
    by ast — importing a test module would execute pytest plumbing."""
    path = root / "tests" / "test_engine_stats_schema.py"
    tree = ast.parse(path.read_text(), filename=str(path))
    for node in tree.body:
        if isinstance(node, ast.Assign) \
                and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and node.targets[0].id == "CRITICAL_PATH_KEYS":
            return set(ast.literal_eval(node.value))
    return None


def contract_registry(root) -> List[Violation]:
    root = pathlib.Path(root)
    try:
        from ray_tpu.serve.telemetry import CRITICAL_PATH_COMPONENTS
        from ray_tpu.serve.traffic import _TTFT_COMPONENTS
        from ray_tpu.tools.tracebus import COMPONENT_SPANS
        from ray_tpu.train.goodput import ANATOMY_COMPONENTS
    except Exception as e:  # noqa: BLE001 - import failure IS the finding
        return [Violation(
            "contract-registry",
            f"critical-path registry unavailable: "
            f"{type(e).__name__}: {e}",
            file="ray_tpu/serve/telemetry.py")]
    comps = list(CRITICAL_PATH_COMPONENTS)
    out: List[Violation] = []

    # -- tracebus span taxonomy ----------------------------------------
    tb_file = "ray_tpu/tools/tracebus.py"
    for c in comps:
        if c not in COMPONENT_SPANS:
            out.append(Violation(
                "contract-registry",
                f"critical-path component '{c}' has no COMPONENT_SPANS "
                f"entry — map it to its tracebus span (or None for a "
                f"derived leg)", file=tb_file))
    for c, span in COMPONENT_SPANS.items():
        if c not in comps:
            out.append(Violation(
                "contract-registry",
                f"COMPONENT_SPANS entry '{c}' is not a "
                f"CRITICAL_PATH_COMPONENTS member — stale mapping",
                file=tb_file))
    tb_path = root / tb_file
    # a synthetic --root (tests, --changed worktrees) may not carry
    # the source files — the registries above came from the installed
    # package either way, so only the source-text checks are skipped
    tb_src = tb_path.read_text() if tb_path.exists() else None
    for c, span in COMPONENT_SPANS.items():
        # the span name must appear beyond the mapping itself — i.e.
        # build_request_spans still emits it
        if tb_src is not None and span is not None \
                and tb_src.count(f'"{span}"') < 2:
            out.append(Violation(
                "contract-registry",
                f"COMPONENT_SPANS['{c}'] -> '{span}' but "
                f"build_request_spans never emits a '{span}' span — "
                f"the trace view of this leg went dark", file=tb_file))

    # -- engine-stats golden schema ------------------------------------
    schema_file = "tests/test_engine_stats_schema.py"
    keys = None
    if (root / schema_file).exists():
        try:
            keys = _schema_critical_path_keys(root)
        except Exception as e:  # noqa: BLE001 - unreadable IS the finding
            out.append(Violation(
                "contract-registry",
                f"golden schema unreadable: {type(e).__name__}: {e}",
                file=schema_file))
        else:
            if keys is None:
                out.append(Violation(
                    "contract-registry",
                    "golden schema defines no CRITICAL_PATH_KEYS "
                    "literal", file=schema_file))
    if keys is not None:
        want = {"e2e_ms"} | set(comps)
        for c in sorted(want - keys):
            out.append(Violation(
                "contract-registry",
                f"critical-path key '{c}' missing from the golden "
                f"schema's CRITICAL_PATH_KEYS", file=schema_file))
        for c in sorted(keys - want):
            out.append(Violation(
                "contract-registry",
                f"golden-schema key '{c}' is not e2e_ms or a "
                f"CRITICAL_PATH_COMPONENTS member — stale schema",
                file=schema_file))

    # -- traffic TTFT decomposition ------------------------------------
    for c in _TTFT_COMPONENTS:
        if c not in comps:
            out.append(Violation(
                "contract-registry",
                f"_TTFT_COMPONENTS entry '{c}' is not a "
                f"CRITICAL_PATH_COMPONENTS member",
                file="ray_tpu/serve/traffic.py"))

    # -- docs tables ---------------------------------------------------
    docs_file = "docs/observability.md"
    docs_path = root / docs_file
    docs_src = docs_path.read_text() if docs_path.exists() else None
    if docs_src is None:
        return out
    for c in comps:
        if f"`{c}`" not in docs_src:
            out.append(Violation(
                "contract-registry",
                f"critical-path component '{c}' is not documented in "
                f"{docs_file} — add it to the components table",
                file=docs_file))
    for leg in ANATOMY_COMPONENTS:
        if f"`{leg}`" not in docs_src:
            out.append(Violation(
                "contract-registry",
                f"trainwatch anatomy leg '{leg}' is not documented in "
                f"{docs_file} — add it to the goodput legs table",
                file=docs_file))
    return out


def perfledger_direction(root) -> List[Violation]:
    pl_file = "ray_tpu/tools/perfledger.py"
    try:
        from ray_tpu.tools.perfledger import (_SWEEP_FIELDS,
                                              explicit_direction)
    except Exception as e:  # noqa: BLE001 - import failure IS the finding
        return [Violation(
            "perfledger-direction",
            f"perfledger direction registry unavailable: "
            f"{type(e).__name__}: {e}", file=pl_file)]
    out: List[Violation] = []
    for field in _SWEEP_FIELDS:
        if explicit_direction(field) is None:
            out.append(Violation(
                "perfledger-direction",
                f"_SWEEP_FIELDS entry '{field}' resolves to no "
                f"explicit higher/lower-is-better token — the ledger "
                f"would call regressions improvements by fallthrough; "
                f"add a token to _LOWER_IS_BETTER / _HIGHER_IS_BETTER "
                f"/ _HIGHER_OVERRIDES", file=pl_file))
    return out
