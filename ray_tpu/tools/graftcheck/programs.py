"""The canonical hot-path programs graftcheck audits.

These are the jitted programs the paper's perf story rides on: the
train step for both model families, batched (ragged) prefill, the
pooled ragged decode step, and the fused-CE kernel's forward and
backward.  Every spec uses the CPU-traceable nano presets — jaxpr
structure (primitives, scans, buffer shapes, donation) is preset- and
backend-independent, so invariants proven on nano hold for the real
configs.

Conventions:

* ``n_tokens`` for the logits-buffer rule is the full token count of
  the traced batch; it must exceed ``d_model`` so a transposed
  ``(d_model, padded_vocab)`` weight view can never alias the
  forbidden shape class.
* HBM budgets are the measured peak estimate of the healthy program
  rounded up ~2-3x — generous enough to survive jax-version jitter in
  the trace, tight enough that an order-of-magnitude blowup (remat
  accidentally storing every layer's activations, a full-cache copy
  per decode step) trips the rule.  To declare a budget for a new
  program: run ``python -m ray_tpu.tools.graftcheck --format json``,
  read ``programs.<name>.peak_hbm_bytes``, round up 2-3x.  Measured
  2026-08 (jax 0.4.37, CPU trace): train 2.2-3.0 MiB, prefill/decode
  1.3-2.1 MiB, fused-CE 0.2-0.3 MiB.
"""

from __future__ import annotations

from typing import List

from ray_tpu.tools.graftcheck.jaxpr_audit import ProgramSpec

#: nano-family shape constants shared by the builders below
_B, _T = 2, 64           # train batch: 128 tokens (> d_model=64)
_PB, _PT0 = 4, 64        # prefill batch: T0 != n_layer so no aliasing
_CE_N, _CE_D, _CE_V, _CE_VALID = 128, 64, 512, 500
_NANO_VOCAB = 512        # padded_vocab of the nano presets
_CHUNK_T = 32            # chunked-prefill tail bucket (< max_seq=128)

_MiB = 2 ** 20


def _nano_gpt2_cfg():
    from ray_tpu.models import gpt2_config

    return gpt2_config("nano", ce_impl="pallas", ce_block_n=16,
                       ce_block_v=128, remat=False)


def _nano_llama_cfg():
    from ray_tpu.models import llama_config

    return llama_config("nano", ce_impl="pallas", ce_block_n=16,
                        ce_block_v=128)


def _sgd_step(loss_fn):
    """The minimal donated train step shape (value_and_grad + in-place
    update) — optimizer choice doesn't change the audited invariants."""
    import jax

    def step(params, batch):
        loss, grads = jax.value_and_grad(
            lambda p: loss_fn(p, batch))(params)
        new = jax.tree.map(lambda p, g: p - 0.01 * g.astype(p.dtype),
                           params, grads)
        return new, loss

    return step


def _build_gpt2_train_step():
    import jax
    import jax.numpy as jnp

    from ray_tpu.models import gpt2_init, gpt2_loss

    cfg = _nano_gpt2_cfg()
    params = gpt2_init(jax.random.PRNGKey(0), cfg)
    batch = {"tokens": jnp.zeros((_B, _T + 1), jnp.int32)}
    return _sgd_step(lambda p, b: gpt2_loss(p, b, cfg)), (params, batch)


def _build_llama_train_step():
    import jax
    import jax.numpy as jnp

    from ray_tpu.models import llama_init, llama_loss

    cfg = _nano_llama_cfg()
    params = llama_init(jax.random.PRNGKey(0), cfg)
    batch = {"tokens": jnp.zeros((_B, _T + 1), jnp.int32)}
    return _sgd_step(lambda p, b: llama_loss(p, b, cfg)), (params, batch)


def _build_gpt2_prefill():
    import jax
    import jax.numpy as jnp

    from ray_tpu.models import gpt2_config, gpt2_init
    from ray_tpu.models.gpt2_decode import prefill

    cfg = gpt2_config("nano", dtype=jnp.float32, use_flash=False,
                      remat=False)
    params = gpt2_init(jax.random.PRNGKey(0), cfg)
    toks = jnp.zeros((_PB, _PT0), jnp.int32)
    lens = jnp.full((_PB,), _PT0 // 2, jnp.int32)
    return (lambda p, t, n: prefill(p, t, cfg, lengths=n),
            (params, toks, lens))


def _build_llama_prefill():
    import jax
    import jax.numpy as jnp

    from ray_tpu.models import llama_config, llama_init
    from ray_tpu.models.llama_decode import llama_prefill

    cfg = llama_config("nano")
    params = llama_init(jax.random.PRNGKey(0), cfg)
    toks = jnp.zeros((_PB, _PT0), jnp.int32)
    lens = jnp.full((_PB,), _PT0 // 2, jnp.int32)
    return (lambda p, t, n: llama_prefill(p, t, cfg, lengths=n),
            (params, toks, lens))


def _build_gpt2_decode_step():
    import jax
    import jax.numpy as jnp

    from ray_tpu.models import gpt2_config, gpt2_init
    from ray_tpu.models.gpt2_decode import decode_step, init_cache

    cfg = gpt2_config("nano", dtype=jnp.float32, use_flash=False,
                      remat=False)
    params = gpt2_init(jax.random.PRNGKey(0), cfg)
    cache = init_cache(cfg, _PB)
    toks = jnp.zeros((_PB,), jnp.int32)
    return (lambda p, c, t: decode_step(p, c, t, cfg),
            (params, cache, toks))


def _build_gpt2_paged_decode_step():
    import jax
    import jax.numpy as jnp

    from ray_tpu.models import gpt2_config, gpt2_init
    from ray_tpu.models.gpt2_decode import decode_step, init_paged_cache

    cfg = gpt2_config("nano", dtype=jnp.float32, use_flash=False,
                      remat=False)
    params = gpt2_init(jax.random.PRNGKey(0), cfg)
    # null block + one full sequence of blocks per pooled row — the
    # serve engine's default sizing (llm.py _init_continuous)
    bs = 16
    per_row = cfg.max_seq // bs
    cache = init_paged_cache(cfg, _PB, num_blocks=1 + _PB * per_row,
                             block_size=bs)
    # identity tables so the traced program exercises the real
    # gather/scatter indirection (all-zero tables would too, but this
    # mirrors a live engine's layout)
    cache["block_tables"] = 1 + jnp.arange(
        _PB * per_row, dtype=jnp.int32).reshape(_PB, per_row)
    toks = jnp.zeros((_PB,), jnp.int32)
    return (lambda p, c, t: decode_step(p, c, t, cfg),
            (params, cache, toks))


def _build_gpt2_sharded_decode_step():
    """The paged decode step with params + pool committed to an
    8-device (data=4, tensor=2) mesh under DECODE_RULES — the serve
    engine's tensor-parallel configuration.  Compiled-HLO rules assert
    the TP collectives exist and the full (unsharded) pool shape does
    NOT: GSPMD silently replicating an input it can no longer shard is
    exactly the regression class this spec exists to catch."""
    import jax
    import jax.numpy as jnp

    from ray_tpu.models import gpt2_config, gpt2_init, gpt2_logical_axes
    from ray_tpu.models.decode_common import shard_cache
    from ray_tpu.models.gpt2_decode import decode_step, init_paged_cache
    from ray_tpu.parallel import MeshSpec, fake_mesh
    from ray_tpu.parallel.sharding import DECODE_RULES, shard_by_shape

    cfg = gpt2_config("nano", dtype=jnp.float32, use_flash=False,
                      remat=False)
    mesh = fake_mesh(8, MeshSpec(data=4, tensor=2))
    params = shard_by_shape(gpt2_init(jax.random.PRNGKey(0), cfg),
                            gpt2_logical_axes(cfg), mesh, DECODE_RULES)
    bs = 16
    per_row = cfg.max_seq // bs
    cache = init_paged_cache(cfg, _PB, num_blocks=1 + _PB * per_row,
                             block_size=bs, mesh=mesh)
    cache["block_tables"] = 1 + jnp.arange(
        _PB * per_row, dtype=jnp.int32).reshape(_PB, per_row)
    cache = shard_cache(cache, mesh)   # re-commit the edited tables
    toks = jnp.zeros((_PB,), jnp.int32)
    return (lambda p, c, t: decode_step(p, c, t, cfg),
            (params, cache, toks))


def _build_gpt2_spec_verify_step():
    """The spec-decode verify program (round 11): ONE dispatch ingests
    a (B, k+1) draft block, scores every position, runs the
    accept/reject fold, and advances the paged pool by the kept
    count.  The logits rule forbids a (B*max_seq, V) buffer — the
    whole point of the verify step is that its logits are (B, k+1, V),
    never the full-sequence shape; the KV pool (arg 1) is donated
    because the verify round is the engine's steady-state hot program
    and keeping two pools alive would double decode HBM."""
    import jax
    import jax.numpy as jnp

    from ray_tpu.models import gpt2_config, gpt2_init
    from ray_tpu.models.decode_common import make_spec_verify
    from ray_tpu.models.gpt2_decode import init_paged_cache, verify_step

    cfg = gpt2_config("nano", dtype=jnp.float32, use_flash=False,
                      remat=False)
    params = gpt2_init(jax.random.PRNGKey(0), cfg)
    bs = 16
    per_row = cfg.max_seq // bs
    cache = init_paged_cache(cfg, _PB, num_blocks=1 + _PB * per_row,
                             block_size=bs)
    cache["block_tables"] = 1 + jnp.arange(
        _PB * per_row, dtype=jnp.int32).reshape(_PB, per_row)
    spec_verify = make_spec_verify(verify_step, cfg)
    block = jnp.zeros((_PB, 5), jnp.int32)      # [cur, d_1..d_4], k=4
    key = jax.random.PRNGKey(0)
    return (lambda p, c, b, k: spec_verify(p, c, b, k),
            (params, cache, block, key))


def _build_gpt2_chunked_prefill():
    """One chunk of streaming prefill (round 14): the serve engine's
    chunked admission runs the SAME ``paged_prefill`` program once per
    chunk with ``prefix_len`` = tokens already filled, so the audited
    shape is a chunk-sized tail bucket (Tt=32) against a warm pool
    with one resident prefix block.  The invariants that make
    chunking's TTFT story real: the forward must never scan over the
    FULL sequence length (the chunk's cost must be O(chunk), not
    O(max_seq) — that is the whole head-of-line-blocking fix), and
    peak HBM must stay at pool + chunk-sized temps (a dense
    re-materialization of the pool per chunk would multiply the
    engine's hottest loop)."""
    import jax
    import jax.numpy as jnp

    from ray_tpu.models import gpt2_config, gpt2_init
    from ray_tpu.models.gpt2_decode import init_paged_cache, paged_prefill

    cfg = gpt2_config("nano", dtype=jnp.float32, use_flash=False,
                      remat=False)
    params = gpt2_init(jax.random.PRNGKey(0), cfg)
    bs = 16
    per_row = cfg.max_seq // bs
    cache = init_paged_cache(cfg, _PB, num_blocks=1 + _PB * per_row,
                             block_size=bs)
    cache["block_tables"] = 1 + jnp.arange(
        _PB * per_row, dtype=jnp.int32).reshape(_PB, per_row)
    row_bt = 1 + jnp.arange(per_row, dtype=jnp.int32)
    toks = jnp.zeros((1, _CHUNK_T), jnp.int32)
    # prefix_len=16: one already-resident block (the previous chunk);
    # n_tail == bucket (full chunk); dynamic scalars as in the engine
    return (lambda p, c, t, bt, pl, nt, s: paged_prefill(
        p, c, t, cfg, row_bt=bt, prefix_len=pl, n_tail=nt, slot=s),
        (params, cache, toks, row_bt, jnp.int32(16),
         jnp.int32(_CHUNK_T), jnp.int32(0)))


def _paged_nano_pool():
    """The serve engine's default nano paged pool (null block + one
    full chain per pooled row) with identity tables — shared by the
    handoff program builders below."""
    import jax.numpy as jnp

    from ray_tpu.models import gpt2_config, gpt2_decode

    cfg = gpt2_config("nano", dtype=jnp.float32, use_flash=False,
                      remat=False)
    bs = 16
    per_row = cfg.max_seq // bs
    cache = gpt2_decode.init_paged_cache(
        cfg, _PB, num_blocks=1 + _PB * per_row, block_size=bs)
    cache["block_tables"] = 1 + jnp.arange(
        _PB * per_row, dtype=jnp.int32).reshape(_PB, per_row)
    return cache, per_row


def _build_gpt2_kv_handoff_export():
    """Disaggregated serving's prefill-side program (round 18): ONE
    dispatch gathers a finished prefill's filled block rows out of the
    pool — the read twin of the tier's install program, fixed-shape
    over a padded id vector.  The export must be a pure slice of the
    pool: no logits buffer may appear (the handoff moves K/V bytes,
    never recomputes), and peak HBM is pool + one stacked-row copy —
    a densified whole-pool intermediate would double the prefill
    replica's steady-state footprint on every handoff."""
    import jax.numpy as jnp

    cache, per_row = _paged_nano_pool()
    ids = jnp.zeros((per_row,), jnp.int32)

    def export(c, blk_ids):
        return (c["k"][:, blk_ids].swapaxes(0, 1),
                c["v"][:, blk_ids].swapaxes(0, 1))

    return export, (cache, ids)


def _build_gpt2_kv_handoff_install():
    """The decode-side splice: exported rows + block table + pos +
    start land in ONE donated dispatch, so the receiving row is
    decode-ready when the program retires and the first decode step
    reads exactly the rows the prefill replica wrote.  The pool (arg
    0) must be donated — an undonated install would hold two pools
    live per handoff, exactly the HBM spike disaggregation cannot
    afford on the decode fleet."""
    import jax.numpy as jnp

    cache, per_row = _paged_nano_pool()
    ids = jnp.zeros((per_row,), jnp.int32)
    row_shape = (per_row,) + cache["k"][:, 0].shape
    k_stack = jnp.zeros(row_shape, cache["k"].dtype)
    v_stack = jnp.zeros(row_shape, cache["v"].dtype)
    row_bt = jnp.zeros((per_row,), jnp.int32)

    def install(c, blk_ids, ks, vs, slot, bt, pos):
        out = dict(c)
        out["k"] = c["k"].at[:, blk_ids].set(ks.swapaxes(0, 1))
        out["v"] = c["v"].at[:, blk_ids].set(vs.swapaxes(0, 1))
        out["block_tables"] = c["block_tables"].at[slot].set(bt)
        out["pos"] = c["pos"].at[slot].set(pos)
        out["start"] = c["start"].at[slot].set(0)
        return out

    return install, (cache, ids, k_stack, v_stack, jnp.int32(0),
                     row_bt, jnp.int32(48))


def _ce_inputs():
    import jax
    import jax.numpy as jnp

    k = jax.random.PRNGKey(0)
    h = jax.random.normal(k, (_CE_N, _CE_D), jnp.float32)
    w = jax.random.normal(k, (_CE_V, _CE_D), jnp.float32)
    t = jnp.zeros((_CE_N,), jnp.int32)
    return h, w, t


def _build_fused_ce_fwd():
    import jax.numpy as jnp

    from ray_tpu.ops.fused_ce import fused_lm_ce

    h, w, t = _ce_inputs()
    return (lambda a, b, c: fused_lm_ce(
        a, b, c, _CE_VALID, block_n=16, block_v=128,
        compute_dtype=jnp.bfloat16), (h, w, t))


def _build_fused_ce_bwd():
    import jax
    import jax.numpy as jnp

    from ray_tpu.ops.fused_ce import fused_lm_ce

    h, w, t = _ce_inputs()

    def loss(a, b):
        return jnp.sum(fused_lm_ce(a, b, t, _CE_VALID, block_n=16,
                                   block_v=128,
                                   compute_dtype=jnp.bfloat16))

    return jax.grad(loss, argnums=(0, 1)), (h, w)


def default_programs() -> List[ProgramSpec]:
    """The registry ``python -m ray_tpu.tools.graftcheck`` audits."""
    return [
        ProgramSpec(
            name="gpt2_train_step",
            build=_build_gpt2_train_step,
            forbid_logits=(_B * _T, _NANO_VOCAB),
            donate_argnums=(0,),
            hbm_budget_bytes=8 * _MiB),
        ProgramSpec(
            name="llama_train_step",
            build=_build_llama_train_step,
            forbid_logits=(_B * _T, _NANO_VOCAB),
            donate_argnums=(0,),
            hbm_budget_bytes=8 * _MiB),
        ProgramSpec(
            name="gpt2_prefill_ragged",
            build=_build_gpt2_prefill,
            forbid_logits=(_PB * _PT0, _NANO_VOCAB),
            forbid_scan_lengths=(_PT0,),
            # prefill runs the full-precision f32 nano config on CPU;
            # the dtype policy is audited on the train-step programs
            allow_f32_matmul=True,
            hbm_budget_bytes=6 * _MiB),
        ProgramSpec(
            name="llama_prefill_ragged",
            build=_build_llama_prefill,
            forbid_logits=(_PB * _PT0, _NANO_VOCAB),
            forbid_scan_lengths=(_PT0,),
            hbm_budget_bytes=6 * _MiB),
        ProgramSpec(
            name="gpt2_decode_step",
            build=_build_gpt2_decode_step,
            forbid_logits=(_PB * 128, _NANO_VOCAB),  # B * max_seq rows
            allow_f32_matmul=True,
            hbm_budget_bytes=6 * _MiB),
        ProgramSpec(
            name="gpt2_paged_decode_step",
            build=_build_gpt2_paged_decode_step,
            forbid_logits=(_PB * 128, _NANO_VOCAB),  # B * max_seq rows
            allow_f32_matmul=True,
            # budget covers the block pool (1 + B*max_seq/bs blocks,
            # == dense cache footprint + one null block) plus the
            # per-layer gathered (B, max_seq) views inside the scan; a
            # hidden dense re-materialization of the WHOLE pool per
            # layer would blow straight through it
            hbm_budget_bytes=6 * _MiB),
        ProgramSpec(
            name="gpt2_sharded_decode_step",
            build=_build_gpt2_sharded_decode_step,
            forbid_logits=(_PB * 128, _NANO_VOCAB),  # B * max_seq rows
            allow_f32_matmul=True,
            min_devices=8,
            # TP attention/MLP insert a tensor-axis all-gather (per-chip
            # KV head shards -> the attention view) and all-reduce (the
            # row-parallel o/proj partial sums); the full (L, 1+B*8,
            # bs, H, hd) pool shape must never appear in the compiled
            # HLO — its presence means GSPMD replicated the pool
            require_collectives=("all-gather", "all-reduce"),
            forbid_hlo_shapes=("f32[2,33,16,2,32]",),
            hbm_budget_bytes=6 * _MiB,
            # measured compiled per-partition arg+temp ~0.74 MiB on 8
            # CPU devices (jax 0.4.37); ~2x headroom.  Pool-replication
            # regressions are caught by the forbidden-shape rule above;
            # this budget catches per-chip blowups from new temps (e.g.
            # a densified per-layer pool copy inside the scan)
            per_chip_hbm_budget_bytes=int(1.6 * _MiB)),
        ProgramSpec(
            name="gpt2_spec_verify_step",
            build=_build_gpt2_spec_verify_step,
            forbid_logits=(_PB * 128, _NANO_VOCAB),  # B * max_seq rows
            allow_f32_matmul=True,
            donate_argnums=(1,),
            # same pool sizing as the paged decode step plus the tiny
            # (B, k+1, V) verify logits and accept-fold temps
            hbm_budget_bytes=6 * _MiB),
        ProgramSpec(
            name="gpt2_chunked_prefill",
            build=_build_gpt2_chunked_prefill,
            # full-sequence logits must never appear: the chunk emits
            # one row of logits (and intermediate chunks discard it)
            forbid_logits=(128, _NANO_VOCAB),        # max_seq rows
            # the chunk forward must be O(chunk): no scan of length
            # max_seq (a per-position pool walk would re-introduce the
            # head-of-line stall chunking exists to remove)
            forbid_scan_lengths=(128,),
            allow_f32_matmul=True,
            # pool (same sizing as the paged decode step) + (Tt, ...)
            # chunk temps; a dense pool re-materialization per chunk
            # blows through this
            hbm_budget_bytes=6 * _MiB),
        ProgramSpec(
            name="gpt2_kv_handoff_export",
            build=_build_gpt2_kv_handoff_export,
            # a handoff never computes: full-sequence logits in the
            # export program mean someone routed a forward through it
            forbid_logits=(_PB * 128, _NANO_VOCAB),  # B * max_seq rows
            allow_f32_matmul=True,
            # pool + one (maxn, L, bs, H, hd) stacked-row pair; a
            # densified whole-pool gather would blow through this
            hbm_budget_bytes=6 * _MiB),
        ProgramSpec(
            name="gpt2_kv_handoff_install",
            build=_build_gpt2_kv_handoff_install,
            forbid_logits=(_PB * 128, _NANO_VOCAB),  # B * max_seq rows
            allow_f32_matmul=True,
            # the donated pool is the whole point: two live pools per
            # install is the regression this spec exists to catch
            donate_argnums=(0,),
            hbm_budget_bytes=6 * _MiB),
        ProgramSpec(
            name="fused_ce_fwd",
            build=_build_fused_ce_fwd,
            forbid_logits=(_CE_N, _CE_V),
            hbm_budget_bytes=1 * _MiB),
        ProgramSpec(
            name="fused_ce_bwd",
            build=_build_fused_ce_bwd,
            forbid_logits=(_CE_N, _CE_V),
            hbm_budget_bytes=1 * _MiB),
    ]
