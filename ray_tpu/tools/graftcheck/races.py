"""Concurrency passes: ``shared-state-race`` and ``rng-discipline``.

The fleet is genuinely threaded — the controller reconciler
(serve/controller.py), handle long-poll listeners (serve/handle.py),
the metrics publisher (util/metrics.py), drain threads, the dashboard
server — while the serve engines and router run as asyncio tasks on
the event loop.  Today many cross-context mutations are accidentally
safe because CPython's GIL makes single bytecode-level container ops
atomic; ROADMAP item 4 (router and replicas in separate processes)
removes that accident.  These passes enforce the discipline statically
so the multi-host refactor doesn't inherit latent races.

``shared-state-race`` — a per-class, interprocedural (within the
class) model of attribute access:

* :data:`THREAD_ROOTS` seeds which methods run on which execution
  contexts (``"ClassName.method" -> (context, ...)``); additionally
  every ``threading.Thread(target=self.m)`` seeds ``m`` with its own
  thread context, and async methods default to the shared
  ``event-loop`` context (asyncio tasks interleave only at awaits, so
  coroutines on one loop are a single context).
* Contexts propagate caller -> callee through ``self.m()`` calls.
* Attributes touched from >= 2 distinct contexts are *shared*; on
  shared attributes the pass flags non-GIL-atomic mutations outside a
  ``with self._lock`` block: read-modify-write (``x += 1``,
  ``x = f(x)``), check-then-act (test reads the attribute, body writes
  it), iteration over a mutable shared container, and multi-step init
  (>= 3 consecutive plain stores another thread can observe half-done).
* GIL-atomic single ops are whitelisted (flightrec's documented
  discipline): plain stores, subscript stores/deletes, and single
  mutator calls (``append``/``popleft``/``add``/...).
* Lock tracking is lexical plus two inferences: methods named
  ``*_locked`` are caller-locked by convention, and a method whose
  self-call sites ALL sit inside lock blocks is treated as lock-held.
* Locals assigned from an expression that reads a self attribute
  (``rep = self._reps.get(name)``) alias that attribute; snapshot
  copies (``list(self._reps.values())``) do not.  Parameters are never
  aliased — per-request record dicts are handed across methods
  deliberately and are engine-loop-local.

``rng-discipline`` — the serve path's bit-identity contracts
(deterministic replay, seeded chaos/traffic) require every random
stream to be seeded and every jax.random key to be consumed once:

* a jax.random key passed to two sampler/``split`` calls without an
  intervening rebind is key reuse (identical streams);
* keys or seeds derived from wallclock/``os.urandom``/pid/uuid are
  unreproducible by construction;
* unseeded module-level ``random.*`` / ``np.random.*`` draws use
  process-global state no test can pin.

Scope: ``shared-state-race`` covers ray_tpu/serve/, ray_tpu/_private/
and ray_tpu/util/; ``rng-discipline`` covers ray_tpu/serve/ (traffic
and chaos generators included).  Both honor the standard
``# graftcheck: disable=<rule>(<reason>)`` waiver.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from ray_tpu.tools.graftcheck.core import Violation

__all__ = ["THREAD_ROOTS", "shared_state_races", "rng_discipline"]

_RACE_SCOPES = ("ray_tpu/serve/", "ray_tpu/_private/", "ray_tpu/util/")
_RNG_SCOPE = "ray_tpu/serve/"

#: "ClassName.method" -> execution contexts that invoke it.  This is
#: the pass's ground truth for *who runs what*: per-class analysis
#: cannot see cross-class call edges (the engine loop calling
#: HealthMonitor.heartbeat), so the known entry points are seeded
#: here.  Methods reached only via self-calls inherit their callers'
#: contexts; a class whose methods all land on one context is skipped.
THREAD_ROOTS: Dict[str, Tuple[str, ...]] = {
    # healthwatch: engine wave loops stamp liveness, the router pump
    # probes and requeues, chaos injects faults, the controller
    # reconciler sweeps, and the dashboard/metrics threads read the
    # stats blocks.  In-process these mostly share the serve event
    # loop; the contexts model the multi-host split (ROADMAP item 4)
    # plus the dashboard/publisher threads that exist today.
    "HealthMonitor.heartbeat": ("engine-wave-loop",),
    "HealthMonitor.note_idle": ("engine-wave-loop",),
    "HealthMonitor.note_fault": ("chaos-injector",),
    "HealthMonitor.note_requeued": ("router-pump",),
    "HealthMonitor.maybe_probe": ("engine-wave-loop", "router-pump"),
    "HealthMonitor.probe": ("controller-reconcile",),
    "HealthMonitor.state": ("router-pump",),
    "HealthMonitor.register": ("controller-reconcile",),
    "HealthMonitor.unregister": ("controller-reconcile",),
    "HealthMonitor.replicas": ("dashboard-handler",),
    "HealthMonitor.replica_block": ("dashboard-handler",),
    "HealthMonitor.fleet_block": ("dashboard-handler",
                                  "metrics-publisher"),
    "HealthMonitor.time_to_detect_ms": ("dashboard-handler",),
    # engine telemetry: recorded from the wave loop, scraped from the
    # dashboard thread, stall-swept from the health probe
    "EngineTelemetry.engine_stats": ("dashboard-handler",),
    "EngineTelemetry.stalled_requests": ("controller-reconcile",),
    "EngineTelemetry.record_enqueue": ("engine-wave-loop",),
    "EngineTelemetry.record_step": ("engine-wave-loop",),
    "EngineTelemetry.record_finish": ("engine-wave-loop",),
    # deployment handles: routing happens on the calling thread while
    # the long-poll listener thread swaps membership under it
    "DeploymentHandle.remote": ("api-caller",),
    "DeploymentHandle.call": ("api-caller",),
    "DeploymentHandle.queue_len": ("controller-reconcile",),
    "DeploymentHandle._apply_membership": ("handle-longpoll",),
    "_SharedListener.register": ("api-caller",),
    "_SharedListener.healthy": ("api-caller",),
    # serve controller: API surface runs on caller threads while the
    # reconcile loop (auto-seeded Thread target) and drain threads
    # mutate the same tables
    "ServeController.deploy": ("api-caller",),
    "ServeController.delete_deployment": ("api-caller",),
    "ServeController.get_replicas": ("api-caller",),
    "ServeController.listen_for_change": ("handle-longpoll",),
    "ServeController.get_routing_table": ("api-caller",),
    "ServeController.status": ("dashboard-handler",),
    # process-wide metric registry: metrics register from any thread,
    # the publisher (auto-seeded Thread target) flushes snapshots
    "_Registry.register": ("api-caller",),
    "_Registry.snapshot": ("dashboard-handler",),
}

#: self-attribute mutator calls that are one bytecode-level container
#: op under the GIL (flightrec's documented single-op discipline)
_ATOMIC_MUTATORS = frozenset({
    "append", "appendleft", "pop", "popleft", "add", "discard",
    "clear", "remove", "extend", "update", "setdefault",
    "put", "put_nowait", "get_nowait", "set", "release",
})
#: non-atomic container mutators we still count as writes
_ALL_MUTATORS = _ATOMIC_MUTATORS | {"insert", "sort", "reverse"}

#: calls that take a snapshot copy — a local built through these does
#: NOT alias the underlying shared attribute
_SNAPSHOT_FNS = frozenset({"list", "dict", "tuple", "set", "sorted",
                           "frozenset", "len", "sum", "max", "min",
                           "str", "repr", "int", "float", "bool"})

_LOCK_FACTORIES = ("Lock", "RLock", "Condition", "Semaphore",
                   "BoundedSemaphore")


def _unparse(node: ast.AST) -> str:
    try:
        return ast.unparse(node)
    except Exception:  # noqa: BLE001 - exotic nodes
        return ""


def _self_attr(node: ast.AST) -> Optional[str]:
    """'x' when node is ``self.x``, else None."""
    if isinstance(node, ast.Attribute) \
            and isinstance(node.value, ast.Name) \
            and node.value.id == "self":
        return node.attr
    return None


def _base_key(node: ast.AST, aliases: Dict[str, str]
              ) -> Optional[Tuple[str, str]]:
    """(attr, sub) storage key for an access target.

    ``self.x``            -> ("x", "")
    ``self.x[k]``         -> ("x", "[]")
    ``rep.y`` / ``rep.y[k]`` where rep aliases self._reps
                          -> ("_reps", "y")
    """
    if isinstance(node, ast.Subscript):
        node = node.value
    attr = _self_attr(node)
    if attr is not None:
        return (attr, "")
    if isinstance(node, ast.Attribute) \
            and isinstance(node.value, ast.Name) \
            and node.value.id in aliases:
        return (aliases[node.value.id], node.attr)
    return None


def _reads_of(node: ast.AST, aliases: Dict[str, str]) -> Set[str]:
    """Base attrs read anywhere in an expression subtree (including
    through aliases and ``getattr(self, "x")``).  A ``self.m(...)``
    callee is method dispatch, not a data read — counting it would
    poison alias tracking through helper calls."""
    callees = {id(sub.func) for sub in ast.walk(node)
               if isinstance(sub, ast.Call)
               and isinstance(sub.func, ast.Attribute)
               and isinstance(sub.func.value, ast.Name)
               and sub.func.value.id == "self"}
    out: Set[str] = set()
    for sub in ast.walk(node):
        if id(sub) in callees:
            continue
        attr = _self_attr(sub)
        if attr is not None:
            out.add(attr)
        elif isinstance(sub, ast.Name) and sub.id in aliases:
            out.add(aliases[sub.id])
        elif (isinstance(sub, ast.Call)
                and isinstance(sub.func, ast.Name)
                and sub.func.id == "getattr" and len(sub.args) >= 2
                and isinstance(sub.args[0], ast.Name)
                and sub.args[0].id == "self"
                and isinstance(sub.args[1], ast.Constant)
                and isinstance(sub.args[1].value, str)):
            out.add(sub.args[1].value)
    return out


class _Access:
    """One attribute access event inside a method body."""

    __slots__ = ("key", "sub", "kind", "locked", "lineno", "cta")

    def __init__(self, key: str, sub: str, kind: str, locked: bool,
                 lineno: int, cta: bool = False):
        self.key = key          # base self attribute
        self.sub = sub          # sub-attribute through an alias ("" = direct)
        self.kind = kind        # read|store|aug|rmw|mutcall|iterate|subscript
        self.locked = locked
        self.lineno = lineno
        self.cta = cta          # write guarded by a test that read the key

    @property
    def is_write(self) -> bool:
        return self.kind != "read" and self.kind != "iterate"

    def label(self) -> str:
        return f"self.{self.key}" + (f".{self.sub}" if self.sub else "")


class _MethodInfo:
    __slots__ = ("name", "node", "accesses", "calls", "thread_targets",
                 "is_async", "fully_locked")

    def __init__(self, name: str, node):
        self.name = name
        self.node = node
        self.accesses: List[_Access] = []
        #: (callee, lexically_locked) for each self.m() site
        self.calls: List[Tuple[str, bool]] = []
        #: methods handed to threading.Thread(target=self.m)
        self.thread_targets: List[str] = []
        self.is_async = isinstance(node, ast.AsyncFunctionDef)
        self.fully_locked = name.endswith("_locked")


def _find_lock_attrs(cls: ast.ClassDef) -> Set[str]:
    """Self attributes holding threading.Lock/RLock/Condition/... —
    by construction site or by having "lock" in the name."""
    locks: Set[str] = set()
    for node in ast.walk(cls):
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            attr = _self_attr(node.targets[0])
            if attr is None:
                continue
            if "lock" in attr.lower():
                locks.add(attr)
                continue
            v = node.value
            if isinstance(v, ast.Call):
                label = _unparse(v.func)
                if label.split(".")[-1] in _LOCK_FACTORIES:
                    locks.add(attr)
    return locks


def _is_lock_ctx(item: ast.withitem, locks: Set[str]) -> bool:
    expr = item.context_expr
    attr = _self_attr(expr)
    if attr is not None:
        return attr in locks or "lock" in attr.lower()
    # ``with lock:`` through a bare local (rare) — name heuristic
    return isinstance(expr, ast.Name) and "lock" in expr.id.lower()


def _is_snapshot(value: ast.expr) -> bool:
    return (isinstance(value, ast.Call)
            and isinstance(value.func, ast.Name)
            and value.func.id in _SNAPSHOT_FNS)


class _MethodWalker:
    """Collects accesses/calls for one method, tracking lexical lock
    state, derived-alias locals, and check-then-act context."""

    def __init__(self, info: _MethodInfo, locks: Set[str]):
        self.info = info
        self.locks = locks
        self.aliases: Dict[str, str] = {}

    def walk(self):
        self._block(self.info.node.body,
                    locked=self.info.fully_locked, cta=set())

    # -- statement dispatch --------------------------------------------

    def _block(self, stmts, locked: bool, cta: Set[str]):
        run: List[Tuple[str, str, int]] = []  # multi-step-init window
        for stmt in stmts:
            stored = self._stmt(stmt, locked, cta)
            if stored is not None and not locked:
                run.append(stored)
            else:
                self._flush_run(run, locked)
                run = []
        self._flush_run(run, locked)

    def _flush_run(self, run, locked: bool):
        """>= 3 consecutive unlocked plain stores to distinct fields of
        one shared object read like initialization another thread can
        observe half-done."""
        if locked or len(run) < 3:
            return
        key = run[0][0]
        fields = {sub for k, sub, _ in run if k == key}
        if len([1 for k, _, _ in run if k == key]) >= 3 \
                and len(fields) >= 3:
            self.info.accesses.append(_Access(
                key, "", "multi-init", False, run[0][2]))

    def _stmt(self, stmt, locked: bool, cta: Set[str]
              ) -> Optional[Tuple[str, str, int]]:
        """Process one statement; returns (key, sub, lineno) when it is
        a plain store eligible for the multi-step-init window."""
        if isinstance(stmt, ast.With):
            inner = locked or any(_is_lock_ctx(i, self.locks)
                                  for i in stmt.items)
            for item in stmt.items:
                self._expr(item.context_expr, locked, cta)
            self._block(stmt.body, inner, cta)
            return None
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return None  # nested defs run elsewhere
        if isinstance(stmt, ast.If):
            tested = _reads_of(stmt.test, self.aliases)
            self._expr(stmt.test, locked, cta)
            self._block(stmt.body, locked, cta | tested)
            self._block(stmt.orelse, locked, cta | tested)
            return None
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            self._iterate(stmt.iter, locked)
            self._alias_from(stmt.target, stmt.iter)
            self._block(stmt.body, locked, cta)
            self._block(stmt.orelse, locked, cta)
            return None
        if isinstance(stmt, ast.While):
            tested = _reads_of(stmt.test, self.aliases)
            self._expr(stmt.test, locked, cta)
            self._block(stmt.body, locked, cta | tested)
            return None
        if isinstance(stmt, ast.Try):
            self._block(stmt.body, locked, cta)
            for h in stmt.handlers:
                self._block(h.body, locked, cta)
            self._block(stmt.orelse, locked, cta)
            self._block(stmt.finalbody, locked, cta)
            return None
        if isinstance(stmt, ast.AugAssign):
            self._expr(stmt.value, locked, cta)
            key = _base_key(stmt.target, self.aliases)
            if key is not None:
                self._emit(key, "aug", locked, stmt.lineno, cta)
            return None
        if isinstance(stmt, (ast.Assign, ast.AnnAssign)):
            targets = (stmt.targets if isinstance(stmt, ast.Assign)
                       else ([stmt.target] if stmt.value is not None
                             else []))
            value = stmt.value
            if value is None:
                return None
            self._expr(value, locked, cta)
            plain_store = None
            for t in targets:
                for el in (t.elts if isinstance(t, (ast.Tuple, ast.List))
                           else [t]):
                    key = _base_key(el, self.aliases)
                    if key is None:
                        if isinstance(el, ast.Name):
                            self._alias_from(el, value)
                        continue
                    reads = _reads_of(value, self.aliases)
                    if key[0] in reads:
                        self._emit(key, "rmw", locked, stmt.lineno, cta)
                    elif isinstance(el, ast.Subscript):
                        self._emit(key, "subscript", locked,
                                   stmt.lineno, cta)
                    else:
                        self._emit(key, "store", locked,
                                   stmt.lineno, cta)
                        plain_store = (key[0], key[1], stmt.lineno)
            return plain_store
        if isinstance(stmt, ast.Delete):
            for t in stmt.targets:
                key = _base_key(t, self.aliases)
                if key is not None:
                    self._emit(key, "subscript", locked,
                               stmt.lineno, cta)
            return None
        if isinstance(stmt, (ast.Return, ast.Expr, ast.Raise,
                             ast.Assert, ast.Await)):
            val = getattr(stmt, "value", None) \
                or getattr(stmt, "exc", None) \
                or getattr(stmt, "test", None)
            if val is not None:
                self._expr(val, locked, cta)
            return None
        for child in ast.iter_child_nodes(stmt):
            if isinstance(child, ast.expr):
                self._expr(child, locked, cta)
        return None

    # -- expression-level events ---------------------------------------

    def _alias_from(self, target, value):
        """Bind a Name target to the base attr its value reads (alias),
        unless the value is a snapshot copy or reads no self attr."""
        names = ([target.id] if isinstance(target, ast.Name)
                 else [e.id for e in getattr(target, "elts", [])
                       if isinstance(e, ast.Name)])
        if not names:
            return
        if _is_snapshot(value):
            for n in names:
                self.aliases.pop(n, None)
            return
        reads = sorted(_reads_of(value, self.aliases))
        for n in names:
            if len(reads) == 1:
                self.aliases[n] = reads[0]
            else:
                self.aliases.pop(n, None)

    def _iterate(self, iter_expr, locked: bool):
        node = iter_expr
        # unwrap ``self.x.items()/values()/keys()``
        if isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr in ("items", "values", "keys"):
            node = node.func.value
        key = _base_key(node, self.aliases)
        if key is not None:
            self.info.accesses.append(_Access(
                key[0], key[1], "iterate", locked,
                iter_expr.lineno))
            return
        self._expr(iter_expr, locked, set())

    def _emit(self, key: Tuple[str, str], kind: str, locked: bool,
              lineno: int, cta: Set[str]):
        self.info.accesses.append(_Access(
            key[0], key[1], kind, locked, lineno,
            cta=key[0] in cta))

    def _expr(self, node, locked: bool, cta: Set[str]):
        """Reads, self-calls, mutator calls, thread targets, and
        comprehension iterations inside one expression."""
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call):
                self._call(sub, locked, cta)
            elif isinstance(sub, (ast.GeneratorExp, ast.ListComp,
                                  ast.SetComp, ast.DictComp)):
                for gen in sub.generators:
                    self._iterate(gen.iter, locked)
            else:
                attr = _self_attr(sub)
                if attr is not None and isinstance(sub.ctx, ast.Load):
                    self.info.accesses.append(_Access(
                        attr, "", "read", locked, sub.lineno))
                elif isinstance(sub, ast.Name) \
                        and isinstance(sub.ctx, ast.Load) \
                        and sub.id in self.aliases:
                    self.info.accesses.append(_Access(
                        self.aliases[sub.id], "", "read", locked,
                        sub.lineno))

    def _call(self, call: ast.Call, locked: bool, cta: Set[str]):
        f = call.func
        # threading.Thread(target=self.m) seeds a per-method context
        label = _unparse(f)
        if label.split(".")[-1] == "Thread":
            for kw in call.keywords:
                if kw.arg == "target":
                    attr = _self_attr(kw.value)
                    if attr is not None:
                        self.info.thread_targets.append(attr)
        if isinstance(f, ast.Attribute):
            owner = f.value
            if isinstance(owner, ast.Name) and owner.id == "self":
                # self.m(...) -> call edge (not an attribute access)
                self.info.calls.append((f.attr, locked))
                return
            key = _base_key(owner, self.aliases)
            if key is not None and f.attr in _ALL_MUTATORS:
                self.info.accesses.append(_Access(
                    key[0], key[1], "mutcall", locked, call.lineno,
                    cta=key[0] in cta))


# ---------------------------------------------------------------------------
# per-class analysis
# ---------------------------------------------------------------------------

def _class_methods(cls: ast.ClassDef) -> List[_MethodInfo]:
    out = []
    for node in cls.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            out.append(_MethodInfo(node.name, node))
    return out


def _method_contexts(cls_name: str, methods: List[_MethodInfo]
                     ) -> Dict[str, Set[str]]:
    """Seeded contexts + fixpoint propagation through self-calls."""
    ctx: Dict[str, Set[str]] = {m.name: set() for m in methods}
    by_name = {m.name: m for m in methods}
    for m in methods:
        seeds = THREAD_ROOTS.get(f"{cls_name}.{m.name}")
        if seeds:
            ctx[m.name].update(seeds)
        elif m.is_async:
            ctx[m.name].add("event-loop")
        for tgt in m.thread_targets:
            if tgt in ctx:
                ctx[tgt].add(f"{tgt.lstrip('_')}-thread")
    changed = True
    while changed:
        changed = False
        for m in methods:
            for callee, _ in m.calls:
                if callee in by_name \
                        and not ctx[m.name] <= ctx[callee]:
                    ctx[callee] |= ctx[m.name]
                    changed = True
    return ctx


def _locked_methods(methods: List[_MethodInfo], cls_name: str
                    ) -> Set[str]:
    """Methods treated as lock-held for their whole body: ``*_locked``
    by convention, plus helpers whose self-call sites are ALL inside
    lock blocks (and that aren't independently seeded/threaded)."""
    by_name = {m.name: m for m in methods}
    held = {m.name for m in methods if m.fully_locked}
    changed = True
    while changed:
        changed = False
        for m in methods:
            if m.name in held or m.name == "__init__":
                continue
            if f"{cls_name}.{m.name}" in THREAD_ROOTS or m.is_async:
                continue
            if any(m.name in mm.thread_targets for mm in methods):
                continue
            sites = [(caller, locked) for mm in methods
                     for caller, locked in
                     [(mm.name, lk) for cal, lk in mm.calls
                      if cal == m.name]]
            if not sites:
                continue
            if all(locked or caller in held
                   for caller, locked in sites):
                held.add(m.name)
                changed = True
    return held


_KIND_TEXT = {
    "aug": "read-modify-write ({label} {op})",
    "rmw": "read-modify-write store to {label}",
    "multi-init": "multi-step re-initialization of {label} fields",
    "iterate": "iteration over mutable shared {label}",
    "cta": "check-then-act on {label}",
}


def shared_state_races(tree: ast.AST, rel: str) -> List[Violation]:
    rel_posix = rel.replace("\\", "/")
    if not any(rel_posix.startswith(s) for s in _RACE_SCOPES):
        return []
    out: List[Violation] = []
    for cls in ast.walk(tree):
        if isinstance(cls, ast.ClassDef):
            out.extend(_check_class(cls, rel))
    return out


def _check_class(cls: ast.ClassDef, rel: str) -> List[Violation]:
    methods = _class_methods(cls)
    if not methods:
        return []
    locks = _find_lock_attrs(cls)
    for m in methods:
        _MethodWalker(m, locks).walk()
    ctx = _method_contexts(cls.name, methods)
    all_ctx = set().union(*ctx.values()) if ctx else set()
    if len(all_ctx) < 2:
        return []  # single execution context: no interleaving
    held = _locked_methods(methods, cls.name)

    # shared = attrs whose accessing methods span >= 2 contexts
    attr_ctx: Dict[str, Set[str]] = {}
    attr_written: Dict[str, bool] = {}
    for m in methods:
        if m.name == "__init__":
            continue  # construction happens-before publication
        for a in m.accesses:
            attr_ctx.setdefault(a.key, set()).update(ctx[m.name])
            if a.is_write:
                attr_written[a.key] = True
    shared = {k for k, c in attr_ctx.items()
              if len(c) >= 2 and k not in locks}

    out: List[Violation] = []
    for m in methods:
        if m.name == "__init__":
            continue
        m_locked = m.name in held
        for a in m.accesses:
            if a.key not in shared:
                continue
            if a.locked or m_locked:
                continue
            kind = a.kind
            if kind in ("store", "subscript", "mutcall"):
                # GIL-atomic single op — unless it acts on a value the
                # enclosing test just read (check-then-act)
                if not a.cta:
                    continue
                kind = "cta"
            elif kind == "read":
                continue
            elif kind == "iterate":
                if not attr_written.get(a.key):
                    continue
            elif kind in ("aug", "rmw") and a.cta:
                pass  # RMW message is the more specific one
            what = _KIND_TEXT.get(kind, kind).format(
                label=a.label(), op="+=/-=")
            ctxs = ", ".join(sorted(attr_ctx[a.key]))
            out.append(Violation(
                "shared-state-race",
                f"unlocked {what} in {cls.name}.{m.name}: "
                f"'{a.key}' is reached from contexts [{ctxs}] — hold "
                f"the class lock around the compound op, or mark a "
                f"deliberate GIL-atomic site with "
                f"disable=shared-state-race(<reason>)",
                file=rel, line=a.lineno))
    return out


# ---------------------------------------------------------------------------
# rng-discipline
# ---------------------------------------------------------------------------

#: jax.random attrs that CONSTRUCT keys rather than consume them
_KEY_MAKERS = frozenset({"PRNGKey", "key", "wrap_key_data", "fold_in"})
#: seed/ctor calls whose argument must not come from wallclock/urandom
_SEED_SINKS = ("jax.random.PRNGKey", "jax.random.key", "random.Random",
               "random.seed", "np.random.RandomState",
               "np.random.default_rng", "np.random.seed",
               "numpy.random.RandomState", "numpy.random.default_rng",
               "numpy.random.seed")
#: wallclock/entropy sources that break bit-identity
_ENTROPY_CALLS = ("time.time", "time.time_ns", "time.monotonic",
                  "time.perf_counter", "os.urandom", "os.getpid",
                  "uuid.uuid4", "uuid.uuid1")
#: module-level stdlib random draws (process-global state)
_GLOBAL_RANDOM_FNS = frozenset({
    "random", "randint", "randrange", "choice", "choices", "shuffle",
    "sample", "uniform", "gauss", "normalvariate", "betavariate",
    "expovariate", "triangular", "getrandbits", "randbytes",
})


def _jax_random_attr(label: str) -> Optional[str]:
    """'normal' for 'jax.random.normal' / 'jrandom.normal'; None when
    the call is not a jax.random one."""
    parts = label.split(".")
    if len(parts) >= 2 and parts[-2] in ("random", "jrandom") \
            and (len(parts) < 3 or parts[-3] in ("jax",)):
        return parts[-1]
    if len(parts) == 2 and parts[0] in ("jrandom", "jr"):
        return parts[1]
    return None


def rng_discipline(tree: ast.AST, rel: str) -> List[Violation]:
    rel_posix = rel.replace("\\", "/")
    if not rel_posix.startswith(_RNG_SCOPE):
        return []
    out: List[Violation] = []
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Module)):
            out.extend(_rng_scan_body(node, rel))
    return out


def _rng_scan_body(fn, rel: str) -> List[Violation]:
    """Linear scan of one function body (module top level included):
    key symbols consumed twice without a rebind, entropy-derived
    seeds, and unseeded module-level draws."""
    out: List[Violation] = []
    consumed: Dict[str, int] = {}  # key symbol -> lineno of first use

    def rebind(target):
        for el in ([target] if not isinstance(target, (ast.Tuple,
                                                       ast.List))
                   else target.elts):
            consumed.pop(_unparse(el), None)

    body = fn.body if not isinstance(fn, ast.Module) else [
        s for s in fn.body
        if not isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.ClassDef))]
    stmts: List[ast.stmt] = []

    def flat(ss):
        for s in ss:
            if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.ClassDef)):
                continue
            stmts.append(s)
            for attr in ("body", "orelse", "finalbody"):
                flat(getattr(s, attr, []) or [])
            for h in getattr(s, "handlers", []) or []:
                flat(h.body)

    flat(body)

    def header_calls(stmt):
        """Calls in the statement's own expressions only — child
        statements are separately in the flat list, so descending
        into them here would double-count every call."""
        work = [c for c in ast.iter_child_nodes(stmt)
                if not isinstance(c, ast.stmt)]
        while work:
            n = work.pop()
            if isinstance(n, ast.Call):
                yield n
            work.extend(c for c in ast.iter_child_nodes(n)
                        if not isinstance(c, ast.stmt))

    for stmt in stmts:
        for call in header_calls(stmt):
            label = _unparse(call.func)
            out.extend(_check_entropy_seed(call, label, rel))
            out.extend(_check_global_draw(call, label, rel))
            attr = _jax_random_attr(label)
            if attr is None or attr in _KEY_MAKERS or not call.args:
                continue
            sym = _unparse(call.args[0])
            if not sym or "(" in sym:
                continue  # expression-valued key: fresh each time
            prev = consumed.get(sym)
            if prev is not None:
                out.append(Violation(
                    "rng-discipline",
                    f"jax.random key '{sym}' consumed again (first "
                    f"use line {prev}) without an intervening "
                    f"split/rebind — identical streams; use "
                    f"'{sym}, sub = jax.random.split({sym})' and "
                    f"consume the sub-key",
                    file=rel, line=call.lineno))
            else:
                consumed[sym] = call.lineno
        # rebinds apply after the statement's consumptions, so the
        # ``key, sub = jax.random.split(key)`` idiom stays clean
        if isinstance(stmt, ast.Assign):
            for t in stmt.targets:
                rebind(t)
        elif isinstance(stmt, (ast.AnnAssign, ast.AugAssign)):
            rebind(stmt.target)
        elif isinstance(stmt, (ast.For, ast.AsyncFor)):
            rebind(stmt.target)
    return out


def _check_entropy_seed(call: ast.Call, label: str,
                        rel: str) -> List[Violation]:
    if label not in _SEED_SINKS:
        return []
    for arg in list(call.args) + [kw.value for kw in call.keywords]:
        for sub in ast.walk(arg):
            if isinstance(sub, ast.Call) \
                    and _unparse(sub.func) in _ENTROPY_CALLS:
                return [Violation(
                    "rng-discipline",
                    f"{label}(...) seeded from "
                    f"'{_unparse(sub.func)}()' — wallclock/entropy "
                    f"seeds are unreproducible; thread an explicit "
                    f"seed through the config (the TrafficSpec.seed / "
                    f"ChaosConfig.seed idiom)",
                    file=rel, line=call.lineno)]
    return []


def _check_global_draw(call: ast.Call, label: str,
                       rel: str) -> List[Violation]:
    parts = label.split(".")
    if len(parts) == 2 and parts[0] == "random" \
            and parts[1] in _GLOBAL_RANDOM_FNS:
        pass
    elif len(parts) == 3 and parts[0] in ("np", "numpy") \
            and parts[1] == "random" \
            and parts[2] not in ("RandomState", "default_rng",
                                 "Generator"):
        pass
    else:
        return []
    return [Violation(
        "rng-discipline",
        f"module-level '{label}(...)' draws from process-global "
        f"unseeded RNG state on the serve path — use a seeded "
        f"instance (random.Random(seed) / np.random.RandomState("
        f"seed)) so traffic replay and chaos schedules stay "
        f"bit-identical",
        file=rel, line=call.lineno)]
