"""CLI driver: ``python -m ray_tpu.tools.graftcheck``.

Exit status 0 iff no un-suppressed violation was found, so the
command drops straight into CI.  ``--format json`` prints the full
machine-readable report (the same dict ``run_repo_check`` returns);
``sweep_tpu.py`` embeds its summary in a SWEEPJSON line per sweep.
"""

from __future__ import annotations

import argparse
import json
import sys


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m ray_tpu.tools.graftcheck",
        description="Audit traced hot-path programs and lint the repo "
                    "for TPU hot-path invariant violations.")
    parser.add_argument(
        "--root", default=None,
        help="repo root to scan (default: the checkout containing "
             "the ray_tpu package)")
    parser.add_argument(
        "--format", choices=("text", "json"), default="text",
        help="report format (default: text)")
    parser.add_argument(
        "--skip-jaxpr", action="store_true",
        help="skip the jaxpr auditor (lint only; no jax tracing)")
    parser.add_argument(
        "--skip-lint", action="store_true",
        help="skip the repo linter (jaxpr programs only)")
    args = parser.parse_args(argv)

    from ray_tpu.tools.graftcheck import render_text, run_repo_check

    report = run_repo_check(args.root, skip_jaxpr=args.skip_jaxpr,
                            skip_lint=args.skip_lint)
    if args.format == "json":
        json.dump(report, sys.stdout, indent=2, sort_keys=True)
        sys.stdout.write("\n")
    else:
        print(render_text(report))
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
