"""CLI driver: ``python -m ray_tpu.tools.graftcheck``.

Exit status 0 iff no un-suppressed violation was found, so the
command drops straight into CI.  ``--format json`` prints the full
machine-readable report (the same dict ``run_repo_check`` returns);
``--format github`` prints ``::error`` workflow annotations;
``sweep_tpu.py`` embeds the report summary in a SWEEPJSON line per
sweep.  ``--changed <git-range>`` lints only the package files the
range touches — the fast pre-commit path (repo-level registry checks
and the jaxpr auditor are skipped; the full CI run holds that line).
"""

from __future__ import annotations

import argparse
import json
import subprocess
import sys


def _changed_files(git_range: str, root) -> list:
    """Repo-relative paths touched in ``git_range`` (``HEAD~1..HEAD``,
    ``main...``, or a single rev — anything diff accepts)."""
    out = subprocess.run(
        ["git", "diff", "--name-only", git_range],
        cwd=root, capture_output=True, text=True, check=True)
    return [line.strip() for line in out.stdout.splitlines()
            if line.strip()]


def _github_escape(text: str) -> str:
    """GitHub workflow-command data escaping (newlines become %0A)."""
    return (text.replace("%", "%25").replace("\r", "%0D")
            .replace("\n", "%0A"))


def render_github(report) -> str:
    """``::error file=...,line=...::[rule] message`` annotations, one
    per violation, plus a trailing notice with the totals."""
    lines = []
    for v in report["violations"]:
        where = ""
        if v.get("file"):
            where = f" file={_github_escape(v['file'])}"
            if v.get("line") is not None:
                where += f",line={v['line']}"
        msg = _github_escape(f"[{v['rule']}] {v['message']}")
        lines.append(f"::error{where}::{msg}")
    s = report["summary"]
    lines.append(
        f"::notice::graftcheck: {s['n_violations']} violation(s), "
        f"{s['n_suppressed']} suppressed, "
        f"{s['files_scanned']} files scanned")
    return "\n".join(lines)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m ray_tpu.tools.graftcheck",
        description="Audit traced hot-path programs and lint the repo "
                    "for TPU hot-path invariant violations.")
    parser.add_argument(
        "--root", default=None,
        help="repo root to scan (default: the checkout containing "
             "the ray_tpu package)")
    parser.add_argument(
        "--format", choices=("text", "json", "github"), default="text",
        help="report format (default: text; github emits ::error "
             "workflow annotations)")
    parser.add_argument(
        "--changed", metavar="GIT_RANGE", default=None,
        help="lint only package files touched in this git range "
             "(e.g. HEAD~1..HEAD or main...) — skips the jaxpr "
             "auditor and repo-level registry checks for pre-commit "
             "speed")
    parser.add_argument(
        "--skip-jaxpr", action="store_true",
        help="skip the jaxpr auditor (lint only; no jax tracing)")
    parser.add_argument(
        "--skip-lint", action="store_true",
        help="skip the repo linter (jaxpr programs only)")
    args = parser.parse_args(argv)

    from ray_tpu.tools.graftcheck import (render_text, run_changed_check,
                                          run_repo_check)

    if args.changed is not None:
        import pathlib

        root = args.root or pathlib.Path(
            __file__).resolve().parents[3]
        try:
            rels = _changed_files(args.changed, root)
        except subprocess.CalledProcessError as e:
            sys.stderr.write(
                f"graftcheck: git diff failed for range "
                f"{args.changed!r}: {e.stderr.strip()}\n")
            return 2
        report = run_changed_check(root, rels=rels)
    else:
        report = run_repo_check(args.root, skip_jaxpr=args.skip_jaxpr,
                                skip_lint=args.skip_lint)
    if args.format == "json":
        json.dump(report, sys.stdout, indent=2, sort_keys=True)
        sys.stdout.write("\n")
    elif args.format == "github":
        print(render_github(report))
    else:
        print(render_text(report))
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
