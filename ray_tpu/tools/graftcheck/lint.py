"""The repo linter: stdlib-``ast`` rules over ray_tpu/ source.

Where the jaxpr auditor proves invariants about traced programs, this
engine catches the host-side habits that erode them: blocking calls on
the async serve path, wall-clock reads in telemetry code that promised
an injectable clock, module-level mutable state shared across remote
invocations, and metric declarations the Prometheus exposition would
reject.  Two repo-level checks (pallas kernels need interpret-mode
tests; the kernel entry points stay exported) absorb what
``tests/test_ops_kernel_guard.py`` used to pin.

Every rule honors ``# graftcheck: disable=<rule>(<reason>)`` on the
offending line or a standalone comment line directly above it
(core.py).  The reason is required: a bare waiver is flagged by
``suppression-reason`` and a waiver that drops nothing by
``stale-suppression`` — suppression is deliberate, explained, and
pruned when the code it excused goes away.

Rule ids:

* ``blocking-call-in-async`` — ``.block_until_ready()``,
  ``np.asarray(...)``, sync ``ray.get``/``ray_tpu.get``, and
  ``time.sleep`` inside ``async def`` bodies under ``ray_tpu/serve/``
  (healthwatch's ``serve/health.py``/``serve/chaos.py`` included),
  ``tools/incidents.py``, or ``ray_tpu/tools/autopilot/`` (the
  dashboard calls the autopilot from its event loop): each blocks the
  event loop (and usually the decode engine) on a device or cluster
  round-trip.  Deliberate host fences carry a disable comment naming
  the reason.
* ``wallclock-in-telemetry`` — ``time.time()`` in ``*/telemetry.py``,
  ``util/tracing.py``, ``_private/flightrec.py``, ``serve/slo.py``,
  ``serve/kv_tier.py`` (the host tier never reads a clock — the
  engine feeds it measured H2D/D2H seconds via ``note_h2d`` /
  ``note_d2h``, the trainwatch idiom),
  ``serve/router.py`` (the fleet router timestamps routing/autoscale
  decisions and measures drain deadlines — interval math like the
  rest), ``serve/health.py``/``serve/chaos.py``/``tools/incidents.py``
  (healthwatch: heartbeat ages, detection latency, and merged
  incident timelines are all perf_counter interval math with
  injectable ``now=``), ``train/goodput.py`` (the trainwatch anatomy
  promises legs
  that sum exactly to the step wall — one wall-clock read breaks the
  invariant), or anywhere under ``ray_tpu/tools/autopilot/``
  (verdicts must be reproducible from ledger contents alone):
  telemetry takes an injectable ``now`` (tests drive deterministic
  clocks) and intervals must use the monotonic ``perf_counter`` —
  the flight-recorder journal and SLO burn-rate windows are interval
  math end to end, so one wall-clock read corrupts them under NTP
  steps.
* ``mutable-global-in-remote`` — a ``@remote`` function or
  remote-actor method mutating a module-level list/dict/set: each
  worker process gets its own copy, so the mutation is a silent no-op
  cross-process and a race within one (heuristic: flags mutating
  calls/subscript-stores only, not reads).
* ``metric-name`` — every ``Counter``/``Gauge``/``Histogram`` from
  ``ray_tpu.util.metrics`` must carry a literal
  ``^[a-z][a-z0-9_]*$`` name (absorbs tests/test_metrics_guard.py).
* ``shared-state-race`` / ``rng-discipline`` — the concurrency and
  determinism passes (races.py): unlocked compound mutations on
  attributes reachable from two execution contexts, and jax.random
  key reuse / entropy-derived seeds / unseeded global RNG draws on
  the serve path.
* ``suppression-reason`` / ``stale-suppression`` — waiver hygiene:
  every disable comment must carry a parenthesized reason naming a
  known rule, and must actually drop a violation on its covered
  lines.
* ``pallas-interpret-test`` — an ``ops/*.py`` building a pallas kernel
  without an interpret-mode test module keeps numerics
  CPU-unverifiable.
* ``kernel-exports`` — the public kernel entry points must stay
  exported (and resolvable) from ``ray_tpu.ops``.
* ``observatory-mapping`` — every ProgramSpec in
  ``tools/graftcheck/programs.py`` must map to a runtime program name
  in ``_private/device_stats.py``'s ``STATIC_PROGRAM_MAP`` (and every
  mapping must target a KNOWN_PROGRAMS name): the static auditor's
  catalog of hot-path programs and the runtime perf observatory's must
  not drift apart.
* ``autopilot-attribution`` — every runtime program name
  ``STATIC_PROGRAM_MAP`` targets must have a knob entry in
  ``tools/autopilot/attribution.py``'s ``PROGRAM_KNOBS`` (and every
  knob entry must name a KNOWN_PROGRAMS program): the tuning loop
  cannot name a bottleneck it has no catalogued way to move.
* ``contract-registry`` / ``perfledger-direction`` — the registry
  drift checks (contracts.py): the exact-sum critical-path component
  list must stay pinned in the tracebus span taxonomy, the
  engine-stats golden schema, traffic's TTFT decomposition and the
  docs tables; every perfledger sweep field must resolve to an
  explicit higher/lower-is-better direction.
"""

from __future__ import annotations

import ast
import pathlib
import re
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.tools.graftcheck.contracts import (contract_registry,
                                                perfledger_direction)
from ray_tpu.tools.graftcheck.core import (Violation, parse_suppressions,
                                           parse_suppression_entries,
                                           split_suppressed)
from ray_tpu.tools.graftcheck.races import (rng_discipline,
                                            shared_state_races)

_METRIC_CLASSES = {"Counter", "Gauge", "Histogram"}
_METRIC_NAME_RE = re.compile(r"^[a-z][a-z0-9_]*$")
_MUTATORS = {"append", "add", "update", "setdefault", "extend",
             "insert", "remove", "clear", "pop", "popleft",
             "appendleft"}
_MUTABLE_FACTORIES = {"list", "dict", "set", "defaultdict", "deque",
                      "OrderedDict", "Counter"}
#: entry points that must stay exported from ray_tpu.ops
KERNEL_EXPORTS = ("causal_attention", "flash_attention", "fused_lm_ce",
                  "streaming_ce", "ring_attention", "ulysses_attention")

#: every rule id a disable comment may legitimately name — a waiver
#: for anything else is a typo or a removed rule (stale-suppression)
KNOWN_RULES = frozenset({
    # lint per-file rules
    "parse-error", "blocking-call-in-async", "wallclock-in-telemetry",
    "mutable-global-in-remote", "metric-name", "shared-state-race",
    "rng-discipline",
    # repo-level checks
    "pallas-interpret-test", "kernel-exports", "observatory-mapping",
    "autopilot-attribution", "contract-registry",
    "perfledger-direction",
    # hygiene (listed so `disable=all` docs stay honest; the hygiene
    # rules themselves are never suppressable)
    "suppression-reason", "stale-suppression",
    # jaxpr auditor rules
    "host-transfer", "f64", "f32-matmul", "logits-buffer", "t0-scan",
    "donation", "collectives", "per-chip-hbm", "hbm-budget",
    "audit-error",
    "all",
})


def _call_label(func: ast.AST) -> str:
    try:
        return ast.unparse(func)
    except Exception:  # noqa: BLE001 - exotic call targets
        return ""


# ---------------------------------------------------------------------------
# per-file rules
# ---------------------------------------------------------------------------

def _blocking_calls_in_async(tree: ast.AST, rel: str) -> List[Violation]:
    rel_posix = rel.replace("\\", "/")
    if not (rel_posix.startswith("ray_tpu/serve/")
            or rel_posix.startswith("ray_tpu/tools/autopilot/")
            or rel_posix.endswith("tools/tracebus.py")
            or rel_posix.endswith("tools/incidents.py")):
        return []
    out: List[Violation] = []

    def walk_async_body(node):
        """Yield calls lexically inside one async def, not descending
        into nested function/class definitions (they run elsewhere)."""
        stack = list(ast.iter_child_nodes(node))
        while stack:
            sub = stack.pop()
            if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.ClassDef)):
                continue
            if isinstance(sub, ast.Call):
                yield sub
            stack.extend(ast.iter_child_nodes(sub))

    for node in ast.walk(tree):
        if not isinstance(node, ast.AsyncFunctionDef):
            continue
        for call in walk_async_body(node):
            label = _call_label(call.func)
            blocking = (
                label.endswith(".block_until_ready")
                or label in ("np.asarray", "numpy.asarray")
                or label in ("ray.get", "ray_tpu.get")
                or label in ("time.sleep", "_time.sleep"))
            if blocking:
                out.append(Violation(
                    "blocking-call-in-async",
                    f"'{label}(...)' blocks the event loop inside "
                    f"async '{node.name}' on the serve path — await an "
                    f"executor, or mark a deliberate host fence with a "
                    f"disable comment", file=rel, line=call.lineno))
    return out


def _wallclock_in_telemetry(tree: ast.AST, rel: str) -> List[Violation]:
    rel_posix = rel.replace("\\", "/")
    if not (rel_posix.endswith("/telemetry.py")
            or rel_posix.endswith("util/tracing.py")
            or rel_posix.endswith("_private/flightrec.py")
            or rel_posix.endswith("serve/slo.py")
            or rel_posix.endswith("serve/router.py")
            or rel_posix.endswith("serve/kvscope.py")
            or rel_posix.endswith("serve/kv_tier.py")
            or rel_posix.endswith("serve/health.py")
            or rel_posix.endswith("serve/chaos.py")
            or rel_posix.endswith("tools/tracebus.py")
            or rel_posix.endswith("tools/kvscope.py")
            or rel_posix.endswith("tools/incidents.py")
            or rel_posix.endswith("train/goodput.py")
            or rel_posix.startswith("ray_tpu/tools/autopilot/")):
        return []
    out: List[Violation] = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) \
                and _call_label(node.func) in ("time.time", "_time.time"):
            out.append(Violation(
                "wallclock-in-telemetry",
                "time.time() in telemetry code — intervals must use "
                "time.perf_counter() (monotonic) and record_* methods "
                "take an injectable `now` for deterministic tests",
                file=rel, line=node.lineno))
    return out


def _module_mutables(tree: ast.Module) -> set:
    """Module-level names bound to mutable list/dict/set containers."""
    names = set()
    for node in tree.body:
        if isinstance(node, ast.Assign):
            targets, value = node.targets, node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets, value = [node.target], node.value
        else:
            continue
        mutable = isinstance(value, (ast.List, ast.Dict, ast.Set)) or (
            isinstance(value, ast.Call)
            and isinstance(value.func, ast.Name)
            and value.func.id in _MUTABLE_FACTORIES)
        if not mutable:
            continue
        for t in targets:
            if isinstance(t, ast.Name):
                names.add(t.id)
    return names


def _is_remote_decorated(node) -> bool:
    for dec in node.decorator_list:
        root = dec.func if isinstance(dec, ast.Call) else dec
        label = _call_label(root)
        if label == "remote" or label.endswith(".remote"):
            return True
    return False


def _mutable_global_in_remote(tree: ast.Module,
                              rel: str) -> List[Violation]:
    mutables = _module_mutables(tree)
    if not mutables:
        return []
    remote_fns: List = []
    for node in tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and _is_remote_decorated(node):
            remote_fns.append(node)
        elif isinstance(node, ast.ClassDef) and _is_remote_decorated(node):
            remote_fns.extend(
                n for n in node.body
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)))
    out: List[Violation] = []
    for fn in remote_fns:
        for sub in ast.walk(fn):
            name = None
            if isinstance(sub, ast.Call) \
                    and isinstance(sub.func, ast.Attribute) \
                    and sub.func.attr in _MUTATORS \
                    and isinstance(sub.func.value, ast.Name):
                name = sub.func.value.id
            elif isinstance(sub, (ast.Assign, ast.AugAssign)):
                targets = (sub.targets if isinstance(sub, ast.Assign)
                           else [sub.target])
                for t in targets:
                    if isinstance(t, ast.Subscript) \
                            and isinstance(t.value, ast.Name):
                        name = t.value.id
            if name and name in mutables:
                out.append(Violation(
                    "mutable-global-in-remote",
                    f"remote '{fn.name}' mutates module-level "
                    f"'{name}' — each worker process has its own copy "
                    f"(cross-process no-op, in-process race); pass "
                    f"state explicitly or use an actor",
                    file=rel, line=sub.lineno))
    return out


def _metric_calls(tree: ast.Module):
    """(lineno, class_label, name_node) for util.metrics constructions
    — bare aliases from ``from ray_tpu.util.metrics import X`` or
    attribute calls on a module imported as ``metrics``."""
    aliases: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) \
                and node.module == "ray_tpu.util.metrics":
            for a in node.names:
                if a.name in _METRIC_CLASSES:
                    aliases[a.asname or a.name] = a.name
    out = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        label = None
        if isinstance(f, ast.Name) and f.id in aliases:
            label = aliases[f.id]
        elif (isinstance(f, ast.Attribute) and f.attr in _METRIC_CLASSES
                and isinstance(f.value, ast.Name)
                and f.value.id == "metrics"):
            label = f.attr
        if label is None:
            continue
        name_node = node.args[0] if node.args else None
        for kw in node.keywords:
            if kw.arg == "name":
                name_node = kw.value
        out.append((node.lineno, label, name_node))
    return out


def _metric_names(tree: ast.Module, rel: str,
                  seen: List[str]) -> List[Violation]:
    out: List[Violation] = []
    for lineno, label, name_node in _metric_calls(tree):
        if not (isinstance(name_node, ast.Constant)
                and isinstance(name_node.value, str)):
            out.append(Violation(
                "metric-name",
                f"{label} name is not a string literal (the Prometheus "
                f"exposition guard can't verify it)",
                file=rel, line=lineno))
            continue
        name = name_node.value
        seen.append(name)
        if not _METRIC_NAME_RE.match(name):
            out.append(Violation(
                "metric-name",
                f"{label} name {name!r} violates ^[a-z][a-z0-9_]*$ "
                f"(Prometheus would reject or mangle it)",
                file=rel, line=lineno))
    return out


def _suppression_hygiene(source: str, rel: str,
                         dropped: List[Violation]) -> List[Violation]:
    """``suppression-reason`` + ``stale-suppression`` for one file:
    every disable entry must name a known rule WITH a parenthesized
    reason, and must have dropped at least one violation on its
    covered lines.  Computed after the split so these are never
    themselves suppressable."""
    out: List[Violation] = []
    dropped_at: Dict[int, set] = {}
    for v in dropped:
        if v.line is not None:
            dropped_at.setdefault(v.line, set()).add(v.rule)
    for entry in parse_suppression_entries(source):
        for rule, reason in entry.rules.items():
            if rule not in KNOWN_RULES:
                out.append(Violation(
                    "stale-suppression",
                    f"disable comment names unknown rule '{rule}' — "
                    f"typo, or a rule this linter no longer has",
                    file=rel, line=entry.line))
                continue
            if reason is None or not reason.strip():
                out.append(Violation(
                    "suppression-reason",
                    f"disable={rule} carries no reason — waivers are "
                    f"reviewable only when they say why: "
                    f"disable={rule}(<reason>)",
                    file=rel, line=entry.line))
            hit = any(
                rule in dropped_at.get(line, ())
                or (rule == "all" and dropped_at.get(line))
                for line in entry.covered)
            if not hit:
                out.append(Violation(
                    "stale-suppression",
                    f"disable={rule} suppresses nothing on line(s) "
                    f"{'/'.join(map(str, entry.covered))} — the code "
                    f"it excused is gone; delete the waiver",
                    file=rel, line=entry.line))
    return out


def lint_source(source: str, rel: str,
                metric_names_seen: List[str] = None
                ) -> Tuple[List[Violation], int]:
    """Lint one file's source; returns (kept violations, n suppressed).
    ``rel`` is the repo-relative posix path — the rules scope on it."""
    try:
        tree = ast.parse(source, filename=rel)
    except SyntaxError as e:
        return [Violation("parse-error", f"file does not parse: {e}",
                          file=rel, line=e.lineno)], 0
    violations: List[Violation] = []
    violations += _blocking_calls_in_async(tree, rel)
    violations += _wallclock_in_telemetry(tree, rel)
    violations += _mutable_global_in_remote(tree, rel)
    violations += _metric_names(
        tree, rel,
        metric_names_seen if metric_names_seen is not None else [])
    violations += shared_state_races(tree, rel)
    violations += rng_discipline(tree, rel)
    kept, dropped = split_suppressed(violations,
                                     parse_suppressions(source))
    kept.extend(_suppression_hygiene(source, rel, dropped))
    return kept, len(dropped)


# ---------------------------------------------------------------------------
# repo-level checks
# ---------------------------------------------------------------------------

def pallas_modules(root: pathlib.Path) -> List[str]:
    """ops/*.py stems that build a pallas kernel (pallas_call in
    source)."""
    ops_dir = root / "ray_tpu" / "ops"
    return sorted(
        p.stem for p in ops_dir.glob("*.py")
        if p.name != "__init__.py" and "pallas_call" in p.read_text())


def _pallas_interpret_tests(root: pathlib.Path) -> List[Violation]:
    out: List[Violation] = []
    tests_dir = root / "tests"
    for stem in pallas_modules(root):
        rel = f"ray_tpu/ops/{stem}.py"
        test_file = tests_dir / f"test_{stem}.py"
        if not test_file.exists():
            out.append(Violation(
                "pallas-interpret-test",
                f"builds a pallas kernel but has no tests/test_{stem}"
                f".py — add an interpret-mode numerics test (see "
                f"tests/test_flash_attention.py for the pattern)",
                file=rel))
        elif "interpret" not in test_file.read_text():
            out.append(Violation(
                "pallas-interpret-test",
                f"tests/test_{stem}.py never runs the kernel in "
                f"interpret mode; tier-1 must verify numerics on CPU "
                f"without the TPU tunnel", file=rel))
    return out


def _kernel_exports() -> List[Violation]:
    out: List[Violation] = []
    try:
        import ray_tpu.ops as ops
    except Exception as e:  # noqa: BLE001 - import failure IS the finding
        return [Violation(
            "kernel-exports",
            f"ray_tpu.ops failed to import: {type(e).__name__}: {e}",
            file="ray_tpu/ops/__init__.py")]
    for name in KERNEL_EXPORTS:
        if name not in getattr(ops, "__all__", ()):
            out.append(Violation(
                "kernel-exports",
                f"'{name}' missing from ray_tpu.ops.__all__",
                file="ray_tpu/ops/__init__.py"))
        elif not callable(getattr(ops, name, None)):
            out.append(Violation(
                "kernel-exports",
                f"ray_tpu.ops.{name} is not callable",
                file="ray_tpu/ops/__init__.py"))
    for name in getattr(ops, "__all__", ()):
        if getattr(ops, name, None) is None:
            out.append(Violation(
                "kernel-exports",
                f"__all__ entry '{name}' does not resolve",
                file="ray_tpu/ops/__init__.py"))
    return out


def _observatory_mapping() -> List[Violation]:
    """Every audited ProgramSpec must have a runtime observatory
    mapping, and every mapping must point at a program name the
    runtime hooks actually register — otherwise the static and
    runtime views of 'the hot-path programs' silently diverge."""
    ds_file = "ray_tpu/_private/device_stats.py"
    try:
        from ray_tpu._private.device_stats import (KNOWN_PROGRAMS,
                                                   STATIC_PROGRAM_MAP)
        from ray_tpu.tools.graftcheck.programs import default_programs

        spec_names = [s.name for s in default_programs()]
    except Exception as e:  # noqa: BLE001 - import failure IS the finding
        return [Violation(
            "observatory-mapping",
            f"observatory mapping unavailable: {type(e).__name__}: {e}",
            file=ds_file)]
    out: List[Violation] = []
    for name in spec_names:
        if name not in STATIC_PROGRAM_MAP:
            out.append(Violation(
                "observatory-mapping",
                f"ProgramSpec '{name}' has no entry in "
                f"STATIC_PROGRAM_MAP — map it to the runtime program "
                f"name the perf observatory registers it under",
                file=ds_file))
    for spec, runtime in STATIC_PROGRAM_MAP.items():
        if runtime not in KNOWN_PROGRAMS:
            out.append(Violation(
                "observatory-mapping",
                f"STATIC_PROGRAM_MAP['{spec}'] -> '{runtime}' is not a "
                f"KNOWN_PROGRAMS runtime name", file=ds_file))
        if spec not in spec_names:
            out.append(Violation(
                "observatory-mapping",
                f"STATIC_PROGRAM_MAP entry '{spec}' matches no "
                f"ProgramSpec in tools/graftcheck/programs.py — stale "
                f"mapping for a removed/renamed spec", file=ds_file))
    return out


def _autopilot_attribution() -> List[Violation]:
    """Every runtime program the observatory can register must have an
    autopilot knob entry (PROGRAM_KNOBS), and every knob entry must
    name a real runtime program — otherwise the tuning loop's
    'attribute' stage silently reports a bottleneck with no catalogued
    way to move it (or grids over a program that can never appear).
    Mirrors the observatory-mapping rule one layer up."""
    ap_file = "ray_tpu/tools/autopilot/attribution.py"
    try:
        from ray_tpu._private.device_stats import (KNOWN_PROGRAMS,
                                                   STATIC_PROGRAM_MAP)
        from ray_tpu.tools.autopilot.attribution import PROGRAM_KNOBS
    except Exception as e:  # noqa: BLE001 - import failure IS the finding
        return [Violation(
            "autopilot-attribution",
            f"autopilot attribution catalog unavailable: "
            f"{type(e).__name__}: {e}", file=ap_file)]
    out: List[Violation] = []
    for spec, runtime in STATIC_PROGRAM_MAP.items():
        if runtime not in PROGRAM_KNOBS:
            out.append(Violation(
                "autopilot-attribution",
                f"runtime program '{runtime}' (ProgramSpec '{spec}') "
                f"has no PROGRAM_KNOBS entry — the autopilot can name "
                f"it as the bottleneck but catalogs no knob to move it",
                file=ap_file))
    for runtime in PROGRAM_KNOBS:
        if runtime not in KNOWN_PROGRAMS:
            out.append(Violation(
                "autopilot-attribution",
                f"PROGRAM_KNOBS entry '{runtime}' is not a "
                f"KNOWN_PROGRAMS runtime name — stale knob catalog for "
                f"a removed/renamed program", file=ap_file))
    return out


def lint_repo(root) -> Tuple[List[Violation], Dict[str, Any]]:
    """Lint every package file under ``root`` plus the repo-level
    checks.  Returns (violations, stats) where stats carries
    ``files``, ``suppressed``, and the literal ``metric_names`` seen
    (so callers can assert the scan isn't vacuous)."""
    root = pathlib.Path(root)
    violations: List[Violation] = []
    metric_names_seen: List[str] = []
    n_files = 0
    n_suppressed = 0
    for path in sorted((root / "ray_tpu").rglob("*.py")):
        if "__pycache__" in path.parts:
            continue
        rel = path.relative_to(root).as_posix()
        kept, dropped = lint_source(path.read_text(), rel,
                                    metric_names_seen)
        violations.extend(kept)
        n_suppressed += dropped
        n_files += 1
    violations.extend(_pallas_interpret_tests(root))
    violations.extend(_kernel_exports())
    violations.extend(_observatory_mapping())
    violations.extend(_autopilot_attribution())
    violations.extend(contract_registry(root))
    violations.extend(perfledger_direction(root))
    stats = {"files": n_files, "suppressed": n_suppressed,
             "metric_names": metric_names_seen}
    return violations, stats


def lint_files(root, rels: List[str]
               ) -> Tuple[List[Violation], Dict[str, Any]]:
    """Per-file lint of an explicit file list (``--changed`` mode):
    the repo-level registry checks are skipped — they can only drift
    via the files that define them, and the full run in CI holds that
    line.  ``rels`` are repo-relative posix paths; non-package or
    vanished paths are ignored (deleted files show up in git ranges)."""
    root = pathlib.Path(root)
    violations: List[Violation] = []
    metric_names_seen: List[str] = []
    n_files = 0
    n_suppressed = 0
    for rel in sorted(set(rels)):
        rel = rel.replace("\\", "/")
        path = root / rel
        if not rel.endswith(".py") or not rel.startswith("ray_tpu/") \
                or "__pycache__" in rel or not path.exists():
            continue
        kept, dropped = lint_source(path.read_text(), rel,
                                    metric_names_seen)
        violations.extend(kept)
        n_suppressed += dropped
        n_files += 1
    stats = {"files": n_files, "suppressed": n_suppressed,
             "metric_names": metric_names_seen}
    return violations, stats
