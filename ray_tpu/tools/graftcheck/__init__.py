"""graftcheck: static enforcement of the TPU hot-path invariants.

Two engines, one report:

* the **jaxpr auditor** (:mod:`.jaxpr_audit` + :mod:`.programs`)
  traces the canonical jitted programs — train step for both model
  families, ragged prefill, pooled decode, fused-CE fwd/bwd — and
  proves no host transfer, no f64, no materialized logits buffer, no
  length-T0 prefill scan, donation actually applied, and a peak-HBM
  estimate within each program's declared budget;
* the **repo linter** (:mod:`.lint`) walks ``ray_tpu/`` with stdlib
  ``ast`` for the host-side habits that erode those invariants
  (blocking calls on the async serve path, wall-clock telemetry,
  mutable module state under ``@remote``, invalid metric names,
  untested pallas kernels, unlocked shared-state races across the
  fleet's execution contexts, RNG-discipline breaches on the serve
  path, and registry drift between the critical-path component list
  and its downstream views).

Run both with ``python -m ray_tpu.tools.graftcheck`` (exit 0 iff
clean; ``--format json`` for the machine-readable report,
``--format github`` for CI annotations, ``--changed <git-range>``
for fast pre-commit lint of touched files only).  Waive a finding
with ``# graftcheck: disable=<rule>(<reason>)`` — see
docs/static-analysis.md for the rule catalog; bare or no-op waivers
are themselves findings (``suppression-reason`` /
``stale-suppression``).
"""

from __future__ import annotations

import pathlib
from typing import Any, Dict, List

from ray_tpu.tools.graftcheck.core import (SuppressionEntry, Violation,
                                           make_report,
                                           parse_suppression_entries,
                                           parse_suppressions,
                                           render_text,
                                           split_suppressed)
from ray_tpu.tools.graftcheck.jaxpr_audit import (ProgramSpec,
                                                  audit_program,
                                                  audit_programs,
                                                  collect_shapes,
                                                  estimate_peak_bytes,
                                                  iter_eqns,
                                                  logits_sized_shapes,
                                                  scan_lengths)
from ray_tpu.tools.graftcheck.lint import (KNOWN_RULES, lint_files,
                                           lint_repo, lint_source,
                                           pallas_modules)
from ray_tpu.tools.graftcheck.races import THREAD_ROOTS

__all__ = [
    "Violation", "ProgramSpec", "SuppressionEntry", "run_repo_check",
    "run_changed_check", "make_report",
    "render_text", "parse_suppressions", "parse_suppression_entries",
    "split_suppressed",
    "audit_program", "audit_programs", "iter_eqns", "collect_shapes",
    "scan_lengths", "logits_sized_shapes", "estimate_peak_bytes",
    "lint_repo", "lint_source", "lint_files", "pallas_modules",
    "KNOWN_RULES", "THREAD_ROOTS",
]


def run_repo_check(root=None, *, skip_jaxpr: bool = False,
                   skip_lint: bool = False) -> Dict[str, Any]:
    """Run both engines over the repo at ``root`` (defaults to the
    checkout containing this package) and return the combined report
    dict (see :func:`core.make_report`).  ``report["ok"]`` is the CLI
    exit status; tier-1 asserts it on every run."""
    if root is None:
        root = pathlib.Path(__file__).resolve().parents[3]
    root = pathlib.Path(root)
    violations = []
    suppressed = 0
    files_scanned = 0
    infos: Dict[str, Dict[str, Any]] = {}
    if not skip_lint:
        lint_violations, stats = lint_repo(root)
        violations.extend(lint_violations)
        suppressed += stats["suppressed"]
        files_scanned = stats["files"]
    if not skip_jaxpr:
        from ray_tpu.tools.graftcheck.programs import default_programs

        jaxpr_violations, infos = audit_programs(default_programs())
        violations.extend(jaxpr_violations)
    return make_report(violations, suppressed=suppressed,
                       files_scanned=files_scanned, programs=infos)


def run_changed_check(root=None, *, rels: List[str]) -> Dict[str, Any]:
    """Per-file lint of an explicit changed-file list (the CLI's
    ``--changed <git-range>`` resolves the range to paths and calls
    this).  Skips the jaxpr auditor and the repo-level registry checks
    — this is the fast pre-commit path; the full run holds the line in
    CI."""
    if root is None:
        root = pathlib.Path(__file__).resolve().parents[3]
    violations, stats = lint_files(pathlib.Path(root), rels)
    return make_report(violations, suppressed=stats["suppressed"],
                       files_scanned=stats["files"])
