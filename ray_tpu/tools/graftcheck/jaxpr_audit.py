"""The jaxpr auditor: static rules over traced hot-path programs.

``jax.make_jaxpr`` gives the exact program XLA will see — so instead of
hoping a review catches a host callback, a stray float64, or a
rematerialized ``(B*T, V)`` logits buffer, we trace each canonical
program (``programs.py``) and walk its equations.  The rules here grew
out of real regressions measured on the live chip (PERF_NOTES rounds
3-7) and out of the one-off jaxpr asserts the test suite carried
before this module existed (``tests/test_fused_ce.py``,
``tests/test_decode_prefill.py`` — both now call the shared helpers
below, so each invariant lives in exactly one place).

Rules (ids as reported / suppressed):

* ``host-transfer`` — no callback / infeed / outfeed primitives inside
  a jitted program: each one is a device->host fence that stalls the
  async dispatch pipeline.
* ``f64`` — no float64/complex128 intermediate anywhere: one doubles
  HBM and runs the VPU at a fraction of rate (TPUs have no f64 units).
* ``f32-matmul`` — large matmuls must feed the MXU bf16 operands
  (f32 accumulation via ``preferred_element_type`` is the sanctioned
  pattern); an f32xf32 ``dot_general`` above the size threshold runs
  ~3x slower via multi-pass unless the program whitelists it.
* ``logits-buffer`` — no buffer of ``(..., padded_vocab)`` covering >=
  n_tokens rows may appear (fwd or bwd): the fused/streaming CE paths
  exist precisely to keep the (B*T, V) f32 tensor out of HBM.
* ``t0-scan`` — prefill must not scan over the prompt length: a
  length-T0 scan is the one-dispatch-per-token regression.
* ``donation`` — buffers we claim to donate must actually alias an
  output in the lowered program (``tf.aliasing_output``); silently
  dropped donation doubles parameter+optimizer HBM.
* ``hbm-budget`` — a liveness-based peak-bytes estimate of the traced
  program checked against the budget the program declares.
* ``collectives`` — a mesh-sharded program's COMPILED HLO must contain
  the collectives its sharding implies (``require_collectives``
  substrings, e.g. the tensor-axis all-gather/all-reduce of TP
  attention) and must NOT contain any ``forbid_hlo_shapes`` substring
  (full-shape buffers that prove an input was silently replicated —
  the KV pool showing up unsharded is the regression this catches).
* ``per-chip-hbm`` — the compiled per-partition footprint
  (``memory_analysis().argument_size_in_bytes + temp_size_in_bytes``,
  which SPMD partitioning reports per chip) checked against
  ``per_chip_hbm_budget_bytes``.  Unlike ``hbm-budget`` this sees the
  post-partitioning sizes, so a pool that stopped sharding trips it
  even if the traced (global) program is unchanged.

Sharded specs declare ``min_devices``; on hosts with fewer devices the
spec is skipped with an info note instead of failing (tier-1 forces 8
virtual CPU devices via tests/conftest.py, so CI always runs them).

The estimator is conservative-but-approximate: it walks the flattened
equation list with last-use liveness and adds each inner jaxpr's own
peak on top of the bytes live at its call site.  It exists to catch
order-of-magnitude blowups (an accidental dense logits buffer is ~100x
a nano budget), not to referee 10% regressions.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Callable, Dict, List, Optional, Tuple

from ray_tpu.tools.graftcheck.core import Violation

#: primitive names that move data or control to the host mid-program
HOST_PRIMITIVES = frozenset({
    "pure_callback", "io_callback", "debug_callback", "python_callback",
    "callback", "host_callback_call", "infeed", "outfeed",
})

#: f32xf32 dot_generals at or above this many elements (largest
#: operand) are flagged; below it the MXU penalty is noise
F32_MATMUL_MIN_ELEMENTS = 1 << 16


# ---------------------------------------------------------------------------
# jaxpr walking helpers (shared with the test suite)
# ---------------------------------------------------------------------------

def _sub_jaxprs(val):
    """Yield every jaxpr hiding in one eqn param value (ClosedJaxpr,
    raw Jaxpr, or lists/tuples of either — pjit/scan carry one, cond a
    tuple)."""
    if hasattr(val, "jaxpr") and hasattr(getattr(val, "jaxpr"), "eqns"):
        yield val.jaxpr                      # ClosedJaxpr
    elif hasattr(val, "eqns"):
        yield val                            # raw Jaxpr
    elif isinstance(val, (list, tuple)):
        for item in val:
            yield from _sub_jaxprs(item)


def iter_eqns(jaxpr):
    """Depth-first generator over every equation in ``jaxpr`` and every
    nested jaxpr (pjit bodies, scan bodies, cond branches, custom-vjp
    calls, pallas kernels...)."""
    for eqn in jaxpr.eqns:
        yield eqn
        for val in eqn.params.values():
            for inner in _sub_jaxprs(val):
                yield from iter_eqns(inner)


def collect_shapes(jaxpr) -> List[Tuple[tuple, str]]:
    """(shape, dtype-str) of every in/out aval of every deep equation."""
    out = []
    for eqn in iter_eqns(jaxpr):
        for var in list(eqn.invars) + list(eqn.outvars):
            aval = getattr(var, "aval", None)
            if aval is not None and getattr(aval, "shape", None) is not None:
                out.append((tuple(aval.shape),
                            str(getattr(aval, "dtype", ""))))
    return out


def scan_lengths(jaxpr) -> List[int]:
    """``length`` param of every scan primitive anywhere in the jaxpr
    (the shared form of tests/test_decode_prefill.py's walker)."""
    return [eqn.params["length"] for eqn in iter_eqns(jaxpr)
            if eqn.primitive.name == "scan"]


def logits_sized_shapes(fn, args, n_tokens: int,
                        padded_vocab: int) -> List[tuple]:
    """Shapes in ``jax.make_jaxpr(fn)(*args)`` whose trailing dim is
    ``padded_vocab`` and whose leading dims cover >= ``n_tokens`` rows —
    i.e. (B, T, V)/(B*T, V) logits-class buffers.  The shared form of
    tests/test_fused_ce.py's detector."""
    import jax

    closed = jax.make_jaxpr(fn)(*args)
    return [s for s, _dt in collect_shapes(closed.jaxpr)
            if len(s) >= 2 and s[-1] == padded_vocab
            and math.prod(s[:-1]) >= n_tokens]


def _aval_bytes(aval) -> int:
    shape = getattr(aval, "shape", None)
    dtype = getattr(aval, "dtype", None)
    if dtype is None:
        return 0
    itemsize = getattr(dtype, "itemsize", 4)
    n = 1
    for d in (shape or ()):
        n *= int(d)
    return n * itemsize


def estimate_peak_bytes(jaxpr) -> int:
    """Liveness-based peak-bytes estimate of one jaxpr.

    Linear walk with last-use refcounts over the top-level equations;
    each inner jaxpr contributes its own recursive peak (minus its
    inputs, which are already live at the call site).  Scan bodies run
    per-iteration, so their internal peak — not length x peak — is the
    right charge."""
    eqns = list(jaxpr.eqns)
    last_use: Dict[Any, int] = {}
    for i, eqn in enumerate(eqns):
        for v in eqn.invars:
            if not hasattr(v, "val"):          # skip Literals
                last_use[v] = i
    for v in jaxpr.outvars:
        if not hasattr(v, "val"):
            last_use[v] = len(eqns)            # outputs live to the end
    live: Dict[Any, int] = {}
    for v in list(jaxpr.invars) + list(jaxpr.constvars):
        live[v] = _aval_bytes(getattr(v, "aval", None))
    cur = sum(live.values())
    peak = cur
    for i, eqn in enumerate(eqns):
        inner_extra = 0
        for val in eqn.params.values():
            for inner in _sub_jaxprs(val):
                inner_inputs = sum(
                    _aval_bytes(getattr(v, "aval", None))
                    for v in list(inner.invars) + list(inner.constvars))
                inner_extra = max(
                    inner_extra,
                    estimate_peak_bytes(inner) - inner_inputs)
        for v in eqn.outvars:
            if v not in live:
                b = _aval_bytes(getattr(v, "aval", None))
                live[v] = b
                cur += b
        peak = max(peak, cur + max(0, inner_extra))
        for v in eqn.invars:
            if hasattr(v, "val"):
                continue
            if last_use.get(v) == i and v in live:
                cur -= live.pop(v)
    return peak


# ---------------------------------------------------------------------------
# program specs + the rules
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class ProgramSpec:
    """One canonical hot-path program and the invariants it declares.

    ``build()`` returns ``(fn, args)`` — kept lazy so importing the
    auditor never constructs models.  ``forbid_logits`` is the
    ``(n_tokens, padded_vocab)`` pair of the logits-buffer rule;
    ``donate_argnums`` asserts those arguments' leaves alias outputs in
    the lowered program; ``hbm_budget_bytes`` is the declared ceiling
    for the peak estimate (see docs/static-analysis.md for how to size
    one)."""

    name: str
    build: Callable[[], Tuple[Callable, tuple]]
    forbid_logits: Optional[Tuple[int, int]] = None
    forbid_scan_lengths: Tuple[int, ...] = ()
    donate_argnums: Tuple[int, ...] = ()
    hbm_budget_bytes: Optional[int] = None
    allow_f32_matmul: bool = False
    skip_rules: Tuple[str, ...] = ()
    #: skip the spec (info note, not a failure) below this device count
    min_devices: int = 1
    #: substrings that must appear in the compiled HLO (collectives a
    #: sharded program cannot be correct without)
    require_collectives: Tuple[str, ...] = ()
    #: substrings that must NOT appear in the compiled HLO (full
    #: unsharded buffer shapes = silent replication)
    forbid_hlo_shapes: Tuple[str, ...] = ()
    #: compiled per-partition arg+temp byte ceiling
    per_chip_hbm_budget_bytes: Optional[int] = None


def _check_host_transfer(jaxpr, spec) -> List[Violation]:
    out = []
    for eqn in iter_eqns(jaxpr):
        name = eqn.primitive.name
        if name in HOST_PRIMITIVES or "callback" in name:
            out.append(Violation(
                "host-transfer",
                f"primitive '{name}' performs a host round-trip inside "
                f"the jitted program", program=spec.name))
    return out


def _check_f64(jaxpr, spec) -> List[Violation]:
    out = []
    seen = set()
    for shape, dtype in collect_shapes(jaxpr):
        if dtype in ("float64", "complex128") and (shape, dtype) not in seen:
            seen.add((shape, dtype))
            out.append(Violation(
                "f64",
                f"{dtype} buffer of shape {shape} in the traced program "
                f"(TPUs have no f64 units; dtype policy is bf16 compute "
                f"/ f32 accumulate)", program=spec.name))
    return out


def _check_f32_matmul(jaxpr, spec) -> List[Violation]:
    out = []
    for eqn in iter_eqns(jaxpr):
        if eqn.primitive.name != "dot_general":
            continue
        avals = [getattr(v, "aval", None) for v in eqn.invars]
        if any(a is None for a in avals):
            continue
        if not all(str(getattr(a, "dtype", "")) == "float32"
                   for a in avals):
            continue
        biggest = max(math.prod(a.shape) if a.shape else 1
                      for a in avals)
        if biggest >= F32_MATMUL_MIN_ELEMENTS:
            shapes = [tuple(a.shape) for a in avals]
            out.append(Violation(
                "f32-matmul",
                f"f32xf32 dot_general over {shapes} (>= "
                f"{F32_MATMUL_MIN_ELEMENTS} elements) — feed the MXU "
                f"bf16 operands with preferred_element_type=f32, or "
                f"whitelist via allow_f32_matmul", program=spec.name))
    return out


def _check_logits_buffer(jaxpr, spec) -> List[Violation]:
    n_tokens, padded_vocab = spec.forbid_logits
    hits = [s for s, _dt in collect_shapes(jaxpr)
            if len(s) >= 2 and s[-1] == padded_vocab
            and math.prod(s[:-1]) >= n_tokens]
    if hits:
        return [Violation(
            "logits-buffer",
            f"(>= {n_tokens} tokens, {padded_vocab})-sized buffers "
            f"materialized: {sorted(set(hits))} — the fused/streaming "
            f"CE contract forbids a full logits tensor",
            program=spec.name)]
    return []


def _check_t0_scan(jaxpr, spec) -> List[Violation]:
    lengths = scan_lengths(jaxpr)
    out = []
    for forbidden in spec.forbid_scan_lengths:
        if forbidden in lengths:
            out.append(Violation(
                "t0-scan",
                f"scan of forbidden length {forbidden} traced (scan "
                f"lengths: {sorted(set(lengths))}) — prompt processing "
                f"regressed to per-token dispatches",
                program=spec.name))
    return out


def _check_donation(fn, args, spec) -> List[Violation]:
    import jax

    expected = 0
    for argnum in spec.donate_argnums:
        expected += len(jax.tree_util.tree_leaves(args[argnum]))
    lowered = jax.jit(
        fn, donate_argnums=spec.donate_argnums).lower(*args)
    aliased = lowered.as_text().count("tf.aliasing_output")
    if aliased < expected:
        return [Violation(
            "donation",
            f"only {aliased} of {expected} donated buffers alias an "
            f"output in the lowered program — dropped donation doubles "
            f"the HBM those arguments occupy", program=spec.name)]
    return []


def _check_compiled(fn, args, spec) -> Tuple[List[Violation],
                                             Dict[str, Any]]:
    """Lower + compile once and run the HLO-text rules: required
    collectives, forbidden (replicated) shapes, and the per-partition
    footprint.  Compilation is the only way to see these — collectives
    are inserted by the SPMD partitioner, after the jaxpr."""
    import jax

    out: List[Violation] = []
    compiled = jax.jit(fn).lower(*args).compile()
    hlo = compiled.as_text()
    if "collectives" not in spec.skip_rules:
        for pat in spec.require_collectives:
            if pat not in hlo:
                out.append(Violation(
                    "collectives",
                    f"compiled program contains no '{pat}' — the mesh "
                    f"sharding this spec declares implies one; the "
                    f"inputs are likely no longer committed to the "
                    f"mesh", program=spec.name))
        for pat in spec.forbid_hlo_shapes:
            if pat in hlo:
                out.append(Violation(
                    "collectives",
                    f"compiled program materializes forbidden "
                    f"full-shape buffer '{pat}' — an input meant to be "
                    f"sharded is being replicated", program=spec.name))
    info: Dict[str, Any] = {}
    if spec.per_chip_hbm_budget_bytes \
            and "per-chip-hbm" not in spec.skip_rules:
        ma = compiled.memory_analysis()
        # arg+temp is the per-partition resident footprint; outputs
        # alias args under donation so counting them would double-bill
        per_chip = int(ma.argument_size_in_bytes
                       + ma.temp_size_in_bytes)
        info["per_chip_hbm_bytes"] = per_chip
        info["per_chip_hbm_budget_bytes"] = \
            spec.per_chip_hbm_budget_bytes
        if per_chip > spec.per_chip_hbm_budget_bytes:
            out.append(Violation(
                "per-chip-hbm",
                f"compiled per-chip footprint {per_chip / 2**20:.2f} "
                f"MiB exceeds the declared per-chip budget "
                f"{spec.per_chip_hbm_budget_bytes / 2**20:.2f} MiB",
                program=spec.name))
    return out, info


def audit_program(spec: ProgramSpec
                  ) -> Tuple[List[Violation], Dict[str, Any]]:
    """Trace one program and run every rule it doesn't skip.  Returns
    (violations, info) where info carries the audit telemetry that
    rides into the JSON report (eqn count, peak-HBM estimate)."""
    import jax

    if len(jax.devices()) < spec.min_devices:
        return [], {"skipped": f"requires >= {spec.min_devices} "
                               f"devices, have {len(jax.devices())}"}
    fn, args = spec.build()
    closed = jax.make_jaxpr(fn)(*args)
    jaxpr = closed.jaxpr
    checks = {
        "host-transfer": lambda: _check_host_transfer(jaxpr, spec),
        "f64": lambda: _check_f64(jaxpr, spec),
        "f32-matmul": lambda: (
            [] if spec.allow_f32_matmul
            else _check_f32_matmul(jaxpr, spec)),
        "logits-buffer": lambda: (
            _check_logits_buffer(jaxpr, spec)
            if spec.forbid_logits else []),
        "t0-scan": lambda: _check_t0_scan(jaxpr, spec),
        "donation": lambda: (
            _check_donation(fn, args, spec)
            if spec.donate_argnums else []),
    }
    violations: List[Violation] = []
    for rule, run in checks.items():
        if rule not in spec.skip_rules:
            violations.extend(run())
    info: Dict[str, Any] = {
        "eqns": sum(1 for _ in iter_eqns(jaxpr)),
    }
    if "hbm-budget" not in spec.skip_rules:
        peak = estimate_peak_bytes(jaxpr)
        info["peak_hbm_bytes"] = int(peak)
        info["hbm_budget_bytes"] = spec.hbm_budget_bytes
        if spec.hbm_budget_bytes and peak > spec.hbm_budget_bytes:
            violations.append(Violation(
                "hbm-budget",
                f"estimated peak HBM {peak / 2**20:.2f} MiB exceeds the "
                f"declared budget "
                f"{spec.hbm_budget_bytes / 2**20:.2f} MiB",
                program=spec.name))
    if (spec.require_collectives or spec.forbid_hlo_shapes
            or spec.per_chip_hbm_budget_bytes):
        vs, compiled_info = _check_compiled(fn, args, spec)
        violations.extend(vs)
        info.update(compiled_info)
    return violations, info


def audit_programs(specs) -> Tuple[List[Violation],
                                   Dict[str, Dict[str, Any]]]:
    """Audit every spec; a program whose build/trace itself crashes is
    reported as an ``audit-error`` violation instead of killing the
    whole run (the other programs' results still matter)."""
    violations: List[Violation] = []
    infos: Dict[str, Dict[str, Any]] = {}
    for spec in specs:
        try:
            vs, info = audit_program(spec)
        except Exception as e:  # noqa: BLE001 - surfaced as a finding
            violations.append(Violation(
                "audit-error",
                f"tracing failed: {type(e).__name__}: {str(e)[:200]}",
                program=spec.name))
            infos[spec.name] = {"error": type(e).__name__}
            continue
        violations.extend(vs)
        infos[spec.name] = info
    return violations, infos
