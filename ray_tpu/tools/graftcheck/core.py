"""graftcheck shared machinery: violations, suppressions, reports.

A check (jaxpr rule or lint rule) produces :class:`Violation` records;
the driver filters them through per-line ``# graftcheck:
disable=<rule>(<reason>)[,<rule>(<reason>)...]`` suppressions and
assembles one report that both the text renderer and ``--format json``
consume.  Suppression is deliberate and visible: a disable comment on
the offending line (or on a standalone comment line directly above it)
names the rule it waives AND says why in the parenthesized reason, so
every waiver is grep-able and reviewable.  The linter's hygiene rules
(lint.py) flag a bare reason-less waiver (``suppression-reason``) and
a waiver that drops nothing (``stale-suppression``).
"""

from __future__ import annotations

import dataclasses
import io
import re
import tokenize
from typing import Any, Dict, List, Optional, Set, Tuple

#: one disable comment: ``disable=`` then one or more
#: ``<rule>(<reason>)`` entries (the reason is optional at PARSE time —
#: bare entries still suppress, the hygiene rule just flags them)
_DISABLE_RE = re.compile(
    r"#\s*graftcheck:\s*disable="
    r"((?:[a-z][a-z0-9\-]*(?:\([^)\n]*\))?\s*,?\s*)+)",
    re.IGNORECASE)
#: one ``<rule>`` or ``<rule>(<reason>)`` entry inside the group above
_ENTRY_RE = re.compile(r"([a-z][a-z0-9\-]*)(?:\(([^)\n]*)\))?",
                       re.IGNORECASE)


@dataclasses.dataclass
class Violation:
    """One invariant breach, from either engine."""

    rule: str
    message: str
    file: Optional[str] = None     # repo-relative path (lint)
    line: Optional[int] = None
    program: Optional[str] = None  # audited program name (jaxpr)

    def location(self) -> str:
        if self.file is not None:
            where = self.file
            if self.line is not None:
                where += f":{self.line}"
            return where
        return f"<jaxpr:{self.program}>" if self.program else "<repo>"

    def to_dict(self) -> Dict[str, Any]:
        out = {"rule": self.rule, "message": self.message}
        if self.file is not None:
            out["file"] = self.file
        if self.line is not None:
            out["line"] = self.line
        if self.program is not None:
            out["program"] = self.program
        return out

    def __str__(self) -> str:
        return f"{self.location()}: [{self.rule}] {self.message}"


def _parse_entry_group(group: str) -> Dict[str, Optional[str]]:
    """rule id -> reason (None when the entry carries no parens)."""
    return {m.group(1): m.group(2)
            for m in _ENTRY_RE.finditer(group)}


def parse_suppressions(source: str) -> Dict[int, Set[str]]:
    """1-based line -> set of rule ids disabled on that line.

    A disable comment sharing a line with code covers that line; a
    standalone comment line covers itself AND the next line, so wrapped
    statements can carry the waiver above them.  Reasons are accepted
    (``disable=rule(why)``) but not required here — the hygiene rule in
    lint.py enforces them."""
    out: Dict[int, Set[str]] = {}
    for lineno, text in enumerate(source.splitlines(), start=1):
        m = _DISABLE_RE.search(text)
        if not m:
            continue
        rules = set(_parse_entry_group(m.group(1)))
        out.setdefault(lineno, set()).update(rules)
        if text.lstrip().startswith("#"):
            out.setdefault(lineno + 1, set()).update(rules)
    return out


@dataclasses.dataclass
class SuppressionEntry:
    """One disable comment, as the hygiene rules see it."""

    line: int                         # the comment's own line
    covered: Tuple[int, ...]          # lines the waiver applies to
    rules: Dict[str, Optional[str]]   # rule id -> reason (None = bare)


def parse_suppression_entries(source: str) -> List[SuppressionEntry]:
    """Every disable comment in ``source`` with its coverage and
    per-rule reasons.  Token-based (COMMENT tokens only) so disable
    patterns quoted inside docstrings don't register as waivers for
    the hygiene rules; returns [] when the source doesn't tokenize
    (the parse-error violation covers that case)."""
    try:
        toks = list(tokenize.generate_tokens(
            io.StringIO(source).readline))
    except Exception:  # noqa: BLE001 - broken source: linter reports it
        return []
    entries: List[SuppressionEntry] = []
    for tok in toks:
        if tok.type != tokenize.COMMENT:
            continue
        m = _DISABLE_RE.search(tok.string)
        if not m:
            continue
        lineno = tok.start[0]
        standalone = tok.line.lstrip().startswith("#")
        covered = (lineno, lineno + 1) if standalone else (lineno,)
        entries.append(SuppressionEntry(
            line=lineno, covered=covered,
            rules=_parse_entry_group(m.group(1))))
    return entries


def is_suppressed(v: Violation,
                  suppressions: Dict[int, Set[str]]) -> bool:
    if v.line is None:
        return False
    rules = suppressions.get(v.line, ())
    return v.rule in rules or "all" in rules


def split_suppressed(violations: List[Violation],
                     suppressions: Dict[int, Set[str]]):
    """(kept, suppressed) partition of one file's violations."""
    kept, dropped = [], []
    for v in violations:
        (dropped if is_suppressed(v, suppressions) else kept).append(v)
    return kept, dropped


def make_report(violations: List[Violation], *,
                suppressed: int = 0,
                files_scanned: int = 0,
                programs: Optional[Dict[str, Dict[str, Any]]] = None
                ) -> Dict[str, Any]:
    """The machine-readable report (``--format json`` emits exactly
    this; ``sweep_tpu.py`` summarizes it into SWEEPJSON lines)."""
    return {
        "ok": not violations,
        "violations": [v.to_dict() for v in violations],
        "summary": {
            "n_violations": len(violations),
            "n_suppressed": suppressed,
            "files_scanned": files_scanned,
            "rules_failed": sorted({v.rule for v in violations}),
        },
        "programs": programs or {},
    }


def render_text(report: Dict[str, Any]) -> str:
    lines: List[str] = []
    for v in report["violations"]:
        where = v.get("file") or f"<jaxpr:{v.get('program', '?')}>"
        if v.get("line") is not None:
            where += f":{v['line']}"
        lines.append(f"{where}: [{v['rule']}] {v['message']}")
    s = report["summary"]
    for name, info in sorted(report["programs"].items()):
        budget = info.get("hbm_budget_bytes")
        peak = info.get("peak_hbm_bytes")
        extra = ""
        if peak is not None:
            extra = f"  peak_hbm={peak / 2**20:.2f}MiB"
            if budget:
                extra += f" / budget={budget / 2**20:.2f}MiB"
        lines.append(f"audited {name}: {info.get('eqns', '?')} eqns"
                     + extra)
    lines.append(
        f"graftcheck: {s['n_violations']} violation(s), "
        f"{s['n_suppressed']} suppressed, {s['files_scanned']} files, "
        f"{len(report['programs'])} programs audited")
    return "\n".join(lines)
