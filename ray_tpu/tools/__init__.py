"""Developer tooling shipped with the package (static analysis,
auditing).  Nothing here runs on the hot path; tools import lazily so
``import ray_tpu`` stays cheap."""
