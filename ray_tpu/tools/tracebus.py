"""Request tracebus: fleet-wide causal tracing + critical-path CLI.

The serving stack records WHERE time went in three silos — per-request
lifecycle records (serve/telemetry.py), flight-recorder decision
journals (_private/flightrec.py), and device-observatory program
invokes (_private/device_stats.py).  All three stamp the same process
monotonic clock (``time.perf_counter``), which is the load-bearing
fact this module exploits: ``collect()`` merges them into ONE document
where a request's spans stitch router → replica engine → device
program via parent ids on a single timeline.

* ``collect(fleet_or_engine)`` — snapshot a live ``LLMFleet`` (or a
  single engine instance) into a JSON-able tracebus document:
  request snapshots with per-token timestamps, per-lane flightrec
  journals rebased to absolute clock, and timestamped device program
  invokes.
* ``build_request_spans(req)`` — one request's span tree
  (router.route → engine.queue / kv.reserve / engine.requeue →
  engine.prefill → kv.handoff → engine.decode; kv.handoff appears
  only on disaggregated fleets, covering the prefill-replica export
  through the decode-replica install fence), every span a
  monotonic-clock window with a parent id; ``attach_device_spans``
  parents the matching prefill program dispatch under the request's
  prefill span.
* ``critical_path_table(...)`` — the pXX decomposition
  e2e = router_wait + queue_wait + requeue + prefill + handoff +
  inter_token + spec_rollback (components from serve/telemetry.py
  ``critical_path``, which sum to e2e by construction).
* ``chrome_trace(doc)`` — the merged Perfetto timeline: one pid per
  replica (slot lanes + a flightrec decision lane), a router pid, and
  a device-program pid.

CLI: ``python -m ray_tpu.tools.tracebus <cmd> <dump.json>`` with
``report`` / ``trace <request_id>`` / ``critical-path
--percentile 99`` / ``export`` — dumps are written by
``write_dump(collect(fleet), path)`` (bench/traffic harnesses) so the
CLI, like tools/flightrec.py, reads artifacts without importing jax.

Caveat: merging assumes one clock domain, i.e. in-process replicas
(build_llm_fleet's model).  Cross-host fleets would need clock-offset
estimation — out of scope here, flagged in docs/observability.md.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Any, Dict, List, Optional

from ray_tpu._private.telemetry import (complete_event, instant_event,
                                        percentile, process_name_event,
                                        thread_name_event)
from ray_tpu.serve.telemetry import (CRITICAL_PATH_COMPONENTS,
                                     latency_anatomy,
                                     merge_anatomy_samples)

__all__ = ["collect", "write_dump", "load_dump", "COMPONENT_SPANS",
           "build_request_spans", "attach_device_spans",
           "find_request", "critical_path_table", "chrome_trace",
           "report_lines", "trace_lines", "main"]

DUMP_VERSION = 1

#: critical-path component -> the tracebus span that carries it (None
#: for derived legs with no dedicated span: prefill_wait is the gap
#: between prefill chunks, spec_rollback is an attr on engine.decode).
#: graftcheck's contract-registry rule pins this mapping both ways:
#: every CRITICAL_PATH_COMPONENTS member must appear here, and every
#: named span must still be emitted by build_request_spans below.
COMPONENT_SPANS: Dict[str, Optional[str]] = {
    "router_wait_ms": "router.wait",
    "queue_wait_ms": "engine.queue",
    "requeue_ms": "engine.requeue",
    "kv_fetch_ms": "kv.fetch",
    "prefill_ms": "engine.prefill",
    "prefill_wait_ms": None,
    "handoff_ms": "kv.handoff",
    "inter_token_ms": "engine.decode",
    "spec_rollback_ms": None,
}


# ---------------------------------------------------------------------------
# collection
# ---------------------------------------------------------------------------

def _abs_events(recorder) -> Dict[str, Any]:
    """One flight recorder's journal with timestamps restored to the
    absolute monotonic clock (snapshot() rebases to its t0)."""
    t0 = float(getattr(recorder, "t0", 0.0))
    events = []
    for e in recorder.snapshot():
        e = dict(e)
        e["ts"] = t0 + float(e.get("t_s", 0.0))
        events.append(e)
    return {"t0": t0, "events": events}


def _device_programs(prefix: str = "serve.") -> Dict[str, Any]:
    """Timestamped program invoke/compile windows from the process
    device observatory ({} when the registry is unavailable)."""
    try:
        from ray_tpu._private.device_stats import get_registry

        reg = get_registry()
        return {
            "invokes": {name: [[float(ts), float(d)] for ts, d in evs]
                        for name, evs
                        in reg.invoke_events(prefix).items()},
            "compiles": {name: [[float(ts), float(d)] for ts, d in evs]
                         for name, evs
                         in reg.compile_windows(prefix).items()},
        }
    except Exception:  # noqa: BLE001 - collection is best-effort
        return {"invokes": {}, "compiles": {}}


def collect(target, name: Optional[str] = None) -> Dict[str, Any]:
    """Snapshot a live fleet (``LLMFleet``) or single engine instance
    into a tracebus document.  Duck-typed: a fleet exposes
    ``trace_records`` + per-replica handles; an engine exposes
    ``trace_records`` + ``engine_stats``."""
    doc: Dict[str, Any] = {
        "version": DUMP_VERSION,
        "source": name or getattr(target, "name", None)
        or getattr(target, "deployment", "engine"),
        "clock": "perf_counter",
        "requests": [],
        "flightrec": {},
        "programs": _device_programs(),
    }
    replicas = getattr(target, "_replicas", None)
    if replicas is not None:  # fleet
        doc["requests"] = target.trace_records()
        fleet_tel = getattr(target, "telemetry", None)
        if fleet_tel is not None:
            doc["flightrec"]["router"] = _abs_events(fleet_tel.flightrec)
        for rep in list(replicas) + list(getattr(target, "_retired",
                                                 ())):
            tel = getattr(rep.inst, "_telemetry", None)
            if tel is not None:
                doc["flightrec"][rep.name] = _abs_events(tel.flightrec)
        anatomy = target.latency_anatomy() \
            if hasattr(target, "latency_anatomy") else None
    else:  # single engine
        for snap in target.trace_records():
            snap.setdefault("replica", snap.get("deployment"))
            doc["requests"].append(snap)
        tel = getattr(target, "_telemetry", None)
        if tel is not None:
            doc["flightrec"][tel.deployment] = _abs_events(tel.flightrec)
        samples = (target.anatomy_samples()
                   if hasattr(target, "anatomy_samples") else
                   merge_anatomy_samples([]))
        anatomy = latency_anatomy(samples)
    doc["latency_anatomy"] = anatomy
    return doc


def write_dump(doc: Dict[str, Any], path: str) -> str:
    with open(path, "w") as f:
        json.dump(doc, f)
    return path


def load_dump(path: str) -> Dict[str, Any]:
    with open(path) as f:
        doc = json.load(f)
    if "requests" not in doc:
        raise ValueError(f"{path} is not a tracebus dump "
                         "(no 'requests' array)")
    return doc


# ---------------------------------------------------------------------------
# span trees
# ---------------------------------------------------------------------------

def _tid(req: Dict[str, Any]) -> str:
    return req.get("trace_id") or f"req{req.get('id')}"


def build_request_spans(req: Dict[str, Any]) -> List[Dict[str, Any]]:
    """One request's span tree from its hop timestamps: every span a
    {name, span_id, parent_id, start, end, attrs} dict on the
    monotonic clock.  Router-side spans recorded live on the
    TraceContext are included verbatim; engine-side hops are
    synthesized deterministically from the lifecycle record (ids
    ``<trace>:eN`` so they never collide with the context's ``:N``)."""
    tid = _tid(req)
    root_id = f"{tid}:0"
    end_guess = req.get("finish") or req.get("first_token") \
        or req.get("admit") or req.get("engine_enqueue") \
        or req.get("enqueue") or 0.0
    spans: List[Dict[str, Any]] = [{
        "name": f"request {tid[:10]}",
        "span_id": root_id, "parent_id": None,
        "start": req.get("enqueue") or 0.0, "end": end_guess,
        "attrs": {"request": req.get("request"),
                  "replica": req.get("replica"),
                  "tenant": req.get("tenant"),
                  "status": req.get("status"),
                  "prompt_len": req.get("prompt_len"),
                  "tokens": req.get("tokens")},
    }]
    spans.extend(dict(s) for s in req.get("spans", ()))
    n = 0

    def emit(name, start, end, parent=root_id, **attrs):
        nonlocal n
        n += 1
        sid = f"{tid}:e{n}"
        spans.append({"name": name, "span_id": sid,
                      "parent_id": parent, "start": float(start),
                      "end": float(end), "attrs": attrs})
        return sid

    enq = req.get("enqueue")
    t_eng = req.get("engine_enqueue")
    admit = req.get("admit")
    first = req.get("first_token")
    finish = req.get("finish")
    if enq is not None and t_eng is not None and t_eng > enq:
        emit("router.wait", enq, t_eng)
    if t_eng is not None and admit is not None:
        queue_id = emit("engine.queue", t_eng, admit)
        rq = req.get("requeue_ts")
        if rq is not None:
            emit("engine.requeue", rq, admit, parent=queue_id,
                 requeues=req.get("requeues", 0))
        kv = req.get("kv_reserve")
        if kv:
            emit("kv.reserve", kv[0], kv[1], parent=queue_id,
                 blocks=kv[2] if len(kv) > 2 else None,
                 hit_blocks=kv[3] if len(kv) > 3 else None,
                 evicted=kv[4] if len(kv) > 4 else None,
                 reprefill_waste_tokens=kv[5] if len(kv) > 5
                 else None)
        # host-tier restore (serve/kv_tier.py): evicted prefix blocks
        # re-admitted via H2D copy during this admission — its own
        # span inside queue wait, matching the kv_fetch_ms component
        kf = req.get("kv_fetch")
        if kf:
            emit("kv.fetch", kf[0], kf[1], parent=queue_id,
                 blocks=kf[2] if len(kf) > 2 else None,
                 tokens=kf[3] if len(kf) > 3 else None,
                 bytes=kf[4] if len(kf) > 4 else None)
    if admit is not None and first is not None:
        chunks = req.get("prefill_chunks")
        if chunks:
            # chunked streaming prefill: one child span per chunk so
            # the timeline shows decode waves in the gaps between them
            for ci, c in enumerate(chunks):
                emit("engine.prefill", c[0], c[1],
                     chunk=ci, n_chunks=len(chunks),
                     tokens=int(c[2]), bucket=int(c[3]),
                     slot=req.get("slot"))
        else:
            emit("engine.prefill", admit, first,
                 bucket=req.get("bucket"), slot=req.get("slot"))
    # disaggregated handoff (serve/llm.py role-split fleets): the
    # block move from prefill replica to decode replica — export
    # start through install fence, between the prefill and decode
    # legs, matching the handoff_ms critical-path component
    kh = req.get("kv_handoff")
    if kh:
        emit("kv.handoff", kh[0], kh[1],
             blocks=kh[2] if len(kh) > 2 else None,
             bytes=kh[3] if len(kh) > 3 else None,
             path=kh[4] if len(kh) > 4 else None)
    if first is not None and finish is not None:
        emit("engine.decode", first, finish,
             tokens=req.get("tokens"),
             spec_rounds=req.get("spec_rounds", 0),
             spec_accepted=req.get("spec_accepted", 0),
             spec_rollback_s=req.get("spec_rollback_s", 0.0))
    return spans


def attach_device_spans(spans: List[Dict[str, Any]],
                        req: Dict[str, Any],
                        programs: Dict[str, Any]
                        ) -> List[Dict[str, Any]]:
    """Parent the device-observatory prefill dispatch under the
    request's ``engine.prefill`` span: the prefill program runs once
    per admission, so the invoke (or compile, for a fresh bucket)
    whose window ends closest to the request's first token inside the
    prefill window IS this request's device work.  Decode dispatches
    are pooled across slots and stay on the shared device lane."""
    prefills = [s for s in spans if s["name"] == "engine.prefill"]
    if not prefills:
        return spans
    # chunked prefill emits several engine.prefill spans; the search
    # window covers all of them and the matched dispatch parents under
    # the chunk whose window contains it (falling back to the last
    # chunk, whose dispatch produced the first token).
    lo = min(s["start"] for s in prefills)
    hi = max(s["end"] for s in prefills) + 1e-4
    last = prefills[-1]
    best = None
    for kind_key, kind in (("invokes", "invoke"),
                           ("compiles", "compile")):
        for name, evs in (programs.get(kind_key) or {}).items():
            if "prefill" not in name:
                continue
            for ts, dur in evs:
                if lo <= ts <= hi:
                    gap = abs(last["end"] - ts)
                    if best is None or gap < best[0]:
                        best = (gap, name, ts, dur, kind)
    if best is not None:
        _gap, name, ts, dur, kind = best
        parent = next(
            (s for s in prefills
             if s["start"] <= ts <= s["end"] + 1e-4), last)
        spans.append({
            "name": f"device {name}",
            "span_id": f"{_tid(req)}:dev",
            "parent_id": parent["span_id"],
            "start": max(parent["start"], ts - dur), "end": ts,
            "attrs": {"program": name, "kind": kind,
                      "dur_ms": round(dur * 1e3, 3)},
        })
    return spans


def find_request(doc: Dict[str, Any], request_id: Any
                 ) -> Optional[Dict[str, Any]]:
    """Locate one request in a tracebus document by trace id (full or
    prefix), ``replica:id``, or bare engine-local id."""
    rid = str(request_id)
    rep_hint = None
    if ":" in rid:
        rep_hint, rid = rid.split(":", 1)
    for req in doc.get("requests", []):
        if rep_hint is not None and req.get("replica") != rep_hint:
            continue
        trace = req.get("trace_id") or ""
        if trace == rid or (len(rid) >= 6 and trace.startswith(rid)):
            return req
        if str(req.get("id")) == rid:
            return req
    return None


# ---------------------------------------------------------------------------
# critical path
# ---------------------------------------------------------------------------

def critical_path_table(doc: Dict[str, Any], pct: float = 99.0,
                        tenant: Optional[str] = None
                        ) -> Dict[str, Any]:
    """The pXX latency decomposition over completed requests: each
    component's own pXX (a table of independent percentiles) plus the
    pXX-e2e exemplar request, whose components sum to its measured
    e2e exactly (the per-request invariant the decomposition keeps)."""
    reqs = [r for r in doc.get("requests", [])
            if r.get("critical_path")
            and (tenant is None or r.get("tenant") == tenant)]
    cps = [r["critical_path"] for r in reqs]
    table = {k: percentile(sorted(c[k] for c in cps), pct)
             if cps else None
             for k in ("e2e_ms",) + CRITICAL_PATH_COMPONENTS}
    exemplar = None
    if cps:
        cut = percentile(sorted(c["e2e_ms"] for c in cps), pct)
        cands = [r for r in reqs
                 if r["critical_path"]["e2e_ms"] >= cut]
        exemplar = min(
            cands, key=lambda r: r["critical_path"]["e2e_ms"],
            default=None)
    return {
        "percentile": pct,
        "tenant": tenant,
        "requests": len(cps),
        "components": table,
        "component_sum_ms": round(sum(
            table[k] for k in CRITICAL_PATH_COMPONENTS), 4)
        if cps else None,
        "exemplar": {
            "request": exemplar.get("request"),
            "replica": exemplar.get("replica"),
            "critical_path": exemplar["critical_path"],
        } if exemplar is not None else None,
    }


def critical_path_lines(doc: Dict[str, Any], pct: float = 99.0,
                        tenant: Optional[str] = None) -> List[str]:
    t = critical_path_table(doc, pct, tenant)
    hdr = f"critical path p{pct:g}"
    if tenant:
        hdr += f" tenant={tenant}"
    lines = [f"{hdr}  ({t['requests']} completed requests)"]
    if not t["requests"]:
        return lines + ["  (no completed requests)"]
    comps = t["components"]
    e2e = comps["e2e_ms"] or 0.0
    for k in CRITICAL_PATH_COMPONENTS:
        v = comps[k] or 0.0
        share = (v / e2e * 100.0) if e2e else 0.0
        lines.append(f"  {k:<18} {v:>10.3f} ms  {share:>5.1f}%")
    lines.append(f"  {'e2e_ms':<18} {e2e:>10.3f} ms")
    ex = t["exemplar"]
    if ex:
        cp = ex["critical_path"]
        comp_sum = sum(cp[k] for k in CRITICAL_PATH_COMPONENTS)
        lines.append(
            f"exemplar {ex['request']} on {ex['replica']}: "
            f"e2e {cp['e2e_ms']:.3f} ms, components sum "
            f"{comp_sum:.3f} ms")
        for k in CRITICAL_PATH_COMPONENTS:
            lines.append(f"    {k:<18} {cp[k]:>10.3f} ms")
    return lines


# ---------------------------------------------------------------------------
# chrome-trace export
# ---------------------------------------------------------------------------

def chrome_trace(doc: Dict[str, Any],
                 path: Optional[str] = None) -> List[Dict[str, Any]]:
    """The merged timeline: pid 0 = router (flightrec decision lane),
    one pid per replica (request spans in slot lanes + that replica's
    flightrec lane), and a device pid with one lane per program.
    Span args carry span_id/parent_id so the causal chain survives
    into the exported JSON."""
    t0s: List[float] = []
    for req in doc.get("requests", []):
        if req.get("enqueue") is not None:
            t0s.append(req["enqueue"])
    for lane in doc.get("flightrec", {}).values():
        t0s.extend(e["ts"] for e in lane.get("events", ()))
    base = min(t0s) if t0s else 0.0

    events: List[Dict[str, Any]] = []
    lanes = sorted({req.get("replica") or req.get("deployment")
                    or "engine" for req in doc.get("requests", [])})
    pid_of = {name: i + 1 for i, name in enumerate(lanes)}
    events.append(process_name_event(0, f"router {doc.get('source')}"))
    events.append(thread_name_event(0, 0, "decisions"))
    for name, pid in pid_of.items():
        events.append(process_name_event(pid, f"replica {name}"))
        events.append(thread_name_event(pid, 0, "flightrec"))

    for req in doc.get("requests", []):
        lane = req.get("replica") or req.get("deployment") or "engine"
        pid = pid_of[lane]
        tid_lane = (req.get("slot") if req.get("slot") is not None
                    else 0) + 1
        spans = attach_device_spans(
            build_request_spans(req), req, doc.get("programs", {}))
        for s in spans:
            dur = max(0.0, s["end"] - s["start"])
            args = dict(s["attrs"], span_id=s["span_id"],
                        parent_id=s["parent_id"])
            # router-side spans render on the router pid; the rest on
            # the owning replica's slot lane
            span_pid = 0 if s["name"].startswith("router.") else pid
            events.append(complete_event(
                s["name"], "tracebus", s["start"] - base, dur,
                span_pid, 0 if span_pid == 0 else tid_lane, args))
        for i, ts in enumerate(req.get("token_ts") or ()):
            events.append(instant_event(
                "token", "tracebus", ts - base, pid, tid_lane,
                {"i": i, "request": req.get("request")}))

    for lane_name, lane in doc.get("flightrec", {}).items():
        pid = 0 if lane_name == "router" else pid_of.get(lane_name)
        if pid is None:
            continue
        for e in lane.get("events", ()):
            args = {k: v for k, v in e.items()
                    if k not in ("kind", "ts", "t_s")}
            events.append(instant_event(
                str(e.get("kind", "event")), "flightrec",
                e["ts"] - base, pid, 0, args))

    dev_pid = len(lanes) + 1
    programs = doc.get("programs", {}) or {}
    prog_names = sorted(set(programs.get("invokes", {}))
                        | set(programs.get("compiles", {})))
    if prog_names:
        events.append(process_name_event(dev_pid, "device programs"))
        for t, name in enumerate(prog_names):
            events.append(thread_name_event(dev_pid, t, name))
        for kind_key, cat in (("invokes", "device"),
                              ("compiles", "compile")):
            for name, evs in (programs.get(kind_key) or {}).items():
                t = prog_names.index(name)
                for ts, dur in evs:
                    events.append(complete_event(
                        name, cat, ts - dur - base, dur, dev_pid, t,
                        {"kind": kind_key[:-1]}))

    from ray_tpu._private.telemetry import write_chrome_trace

    return write_chrome_trace(events, path)


# ---------------------------------------------------------------------------
# report / trace rendering
# ---------------------------------------------------------------------------

def report_lines(doc: Dict[str, Any]) -> List[str]:
    reqs = doc.get("requests", [])
    done = [r for r in reqs if r.get("status") == "ok"]
    lines = [
        f"tracebus: {doc.get('source', '?')}  clock="
        f"{doc.get('clock', '?')}",
        f"requests: {len(reqs)} retained / {len(done)} completed",
    ]
    by_lane: Dict[str, int] = {}
    for r in reqs:
        lane = r.get("replica") or r.get("deployment") or "engine"
        by_lane[lane] = by_lane.get(lane, 0) + 1
    if by_lane:
        lines.append("by replica: " + ", ".join(
            f"{k}={v}" for k, v in sorted(by_lane.items())))
    anatomy = doc.get("latency_anatomy")
    if anatomy:
        itl = anatomy.get("itl_ms") or {}
        lines.append(
            f"itl_ms: n={itl.get('count')} p50={itl.get('p50')} "
            f"p95={itl.get('p95')} p99={itl.get('p99')}")
        tpot = anatomy.get("tpot_ms") or {}
        lines.append(
            f"tpot_ms: n={tpot.get('count')} p50={tpot.get('p50')} "
            f"p99={tpot.get('p99')}")
    lines.extend(critical_path_lines(doc, 99.0))
    return lines


def trace_lines(doc: Dict[str, Any], request_id: Any) -> List[str]:
    req = find_request(doc, request_id)
    if req is None:
        return [f"request {request_id!r} not found "
                f"({len(doc.get('requests', []))} retained)"]
    spans = attach_device_spans(
        build_request_spans(req), req, doc.get("programs", {}))
    base = min(s["start"] for s in spans)
    by_parent: Dict[Any, List[Dict[str, Any]]] = {}
    for s in spans:
        by_parent.setdefault(s["parent_id"], []).append(s)
    lines = [f"request {req.get('request')}  replica="
             f"{req.get('replica')}  tenant={req.get('tenant')}  "
             f"status={req.get('status')}"]

    def walk(parent, depth):
        for s in sorted(by_parent.get(parent, ()),
                        key=lambda s: s["start"]):
            dur_ms = (s["end"] - s["start"]) * 1e3
            lines.append(
                f"{'  ' * depth}{s['name']:<24} "
                f"+{(s['start'] - base) * 1e3:>9.3f} ms  "
                f"dur {dur_ms:>9.3f} ms  [{s['span_id']}"
                f" <- {s['parent_id']}]")
            walk(s["span_id"], depth + 1)

    walk(None, 0)
    cp = req.get("critical_path")
    if cp:
        lines.append("critical path:")
        for k in ("e2e_ms",) + CRITICAL_PATH_COMPONENTS:
            lines.append(f"  {k:<18} {cp[k]:>10.3f} ms")
    return lines


# ---------------------------------------------------------------------------
# autopilot evidence
# ---------------------------------------------------------------------------

def request_evidence(doc: Dict[str, Any],
                     pct: float = 99.0) -> Dict[str, Any]:
    """Request-level evidence for autopilot attribution: the pXX
    decomposition overall and per tenant — which lifecycle leg (not
    which program) dominates tail latency, the complement of the
    roofline's program-granularity view."""
    overall = critical_path_table(doc, pct)
    tenants = sorted({r.get("tenant") for r in doc.get("requests", [])
                      if r.get("tenant")})
    comps = overall["components"]
    dominant = None
    if overall["requests"]:
        dominant = max(CRITICAL_PATH_COMPONENTS,
                       key=lambda k: comps[k] or 0.0)
    return {
        "percentile": pct,
        "overall": overall,
        "by_tenant": {t: critical_path_table(doc, pct, tenant=t)
                      for t in tenants},
        "dominant_component": dominant,
    }


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------

def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m ray_tpu.tools.tracebus",
        description="inspect tracebus dumps (fleet-wide causal "
                    "request traces)")
    sub = ap.add_subparsers(dest="cmd", required=True)

    p = sub.add_parser("report", help="summary of one dump")
    p.add_argument("dump")

    p = sub.add_parser("trace", help="one request's span tree")
    p.add_argument("dump")
    p.add_argument("request_id",
                   help="trace id (or prefix), replica:id, or "
                        "engine-local id")

    p = sub.add_parser("critical-path",
                       help="pXX latency decomposition table")
    p.add_argument("dump")
    p.add_argument("--percentile", type=float, default=99.0)
    p.add_argument("--tenant", default=None)

    p = sub.add_parser("export",
                       help="merged chrome-trace timeline")
    p.add_argument("dump")
    p.add_argument("-o", "--out", default=None,
                   help="write trace JSON here (default: stdout)")

    args = ap.parse_args(argv)
    try:
        doc = load_dump(args.dump)
    except (OSError, ValueError, json.JSONDecodeError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2

    if args.cmd == "report":
        for line in report_lines(doc):
            print(line)
        return 0
    if args.cmd == "trace":
        lines = trace_lines(doc, args.request_id)
        for line in lines:
            print(line)
        return 0 if not lines[0].endswith("retained)") else 1
    if args.cmd == "critical-path":
        for line in critical_path_lines(doc, args.percentile,
                                        args.tenant):
            print(line)
        return 0
    # export
    events = chrome_trace(doc, args.out)
    if args.out:
        print(f"wrote {len(events)} events to {args.out}")
    else:
        print(json.dumps(events))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
