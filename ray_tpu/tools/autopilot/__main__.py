"""CLI for the autopilot loop — one command per stage.

    python -m ray_tpu.tools.autopilot attribute [--snapshot FILE]
    python -m ray_tpu.tools.autopilot plan [--budget N] [--format ...]
    python -m ray_tpu.tools.autopilot verdict [--out-dir DIR]

``plan`` prints the bare grid JSON on stdout by default, so the whole
loop is shell-composable::

    python sweep_tpu.py "$(python -m ray_tpu.tools.autopilot plan)"
    python -m ray_tpu.tools.autopilot verdict

(rationales go to stderr; ``--format full`` puts the whole graded plan
on stdout instead).  ``verdict`` exits 1 naming the regressed metrics,
so it gates a session the way pytest gates a merge.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Any, Dict, List, Optional

from ray_tpu.tools.autopilot import attribution, planner, verdict


def _load_snapshot(path: str) -> Dict[str, Any]:
    """A canned snapshot file: either a bare ``{name: block}`` programs
    dict, or an ``engine_stats()`` / dashboard dump carrying
    ``programs`` (and optionally ``device`` and ``kv_scope``) keys."""
    with open(path) as f:
        obj = json.load(f)
    if isinstance(obj.get("programs"), dict):
        return {"programs": obj["programs"],
                "device": obj.get("device"),
                "kv_scope": obj.get("kv_scope")}
    return {"programs": obj, "device": None, "kv_scope": None}


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m ray_tpu.tools.autopilot",
        description="closed-loop perf autopilot: attribute the "
                    "bottleneck, plan the next sweep, file the verdict")
    ap.add_argument("--history", default=None,
                    help="ledger path (default: <repo>/"
                         "BENCH_HISTORY.jsonl, env RAYTPU_BENCH_HISTORY"
                         " overrides)")
    ap.add_argument("--baseline", default=None,
                    help="BASELINE.json path")
    sub = ap.add_subparsers(dest="cmd", required=True)

    p_att = sub.add_parser(
        "attribute",
        help="classify programs compute- vs HBM-bound against the "
             "device ridge and name the bottleneck")
    p_att.add_argument("--snapshot", default=None,
                       help="canned programs JSON (engine_stats dump "
                            "or bare snapshot) instead of this "
                            "process's live registry")
    p_att.add_argument("--format", choices=("text", "json"),
                       default="text")

    p_plan = sub.add_parser(
        "plan",
        help="emit the next sweep grid (sweep_tpu.py argv[1]) from "
             "ledger coverage + attribution")
    p_plan.add_argument("--budget", type=int, default=8,
                        help="max variants in the grid (default 8)")
    p_plan.add_argument("--snapshot", default=None,
                        help="attribute this canned snapshot first and "
                             "bias the plan toward its bottleneck")
    p_plan.add_argument("--include-fresh", action="store_true",
                        help="keep candidates already measured at the "
                             "current SHA")
    p_plan.add_argument("--format", choices=("grid", "full", "text"),
                        default="grid",
                        help="grid: bare sweep_tpu JSON on stdout "
                             "(rationales on stderr); full: whole "
                             "graded plan JSON; text: human table")

    p_ver = sub.add_parser(
        "verdict",
        help="file AUTOPILOT.md/.json; exit 1 naming regressed metrics")
    p_ver.add_argument("--tolerance", type=float,
                       default=None,
                       help="relative tolerance band (default 5%%)")
    p_ver.add_argument("--budget", type=int, default=8,
                       help="budget for the embedded next plan")
    p_ver.add_argument("--out-dir", default=None,
                       help="where to write AUTOPILOT.md/.json "
                            "(default: repo root)")
    p_ver.add_argument("--no-write", action="store_true",
                       help="print the verdict without filing reports")
    p_ver.add_argument("--format", choices=("text", "json"),
                       default="text")
    args = ap.parse_args(argv)

    if args.cmd == "attribute":
        if args.snapshot:
            snap = _load_snapshot(args.snapshot)
            report = attribution.attribute(snap["programs"],
                                           device=snap["device"],
                                           kv_scope=snap["kv_scope"])
        else:
            report = attribution.attribute_registry()
        if args.format == "json":
            print(json.dumps(report, indent=1, sort_keys=True))
        else:
            print(attribution.render_text(report))
        return 0

    if args.cmd == "plan":
        att = None
        if args.snapshot:
            snap = _load_snapshot(args.snapshot)
            att = attribution.attribute(snap["programs"],
                                        device=snap["device"],
                                        kv_scope=snap["kv_scope"])
        p = planner.plan(args.history, args.baseline,
                         budget=args.budget, attribution=att,
                         include_fresh=args.include_fresh)
        if args.format == "full":
            print(json.dumps(p, indent=1, sort_keys=True))
        elif args.format == "text":
            print(planner.render_text(p))
        else:
            print(json.dumps(p["grid"]))
            for g in p["variants"]:
                print(f"autopilot: [{g['status']}] {g['id']} "
                      f"#{g['hash']}: {g['rationale']}",
                      file=sys.stderr)
        if not p["grid"]:
            print("autopilot: plan is empty (all candidates fresh — "
                  "pass --include-fresh to re-run them)",
                  file=sys.stderr)
        return 0

    # verdict
    from ray_tpu.tools import perfledger

    tol = (perfledger.DEFAULT_TOLERANCE if args.tolerance is None
           else args.tolerance)
    v = verdict.build_verdict(args.history, args.baseline,
                              tolerance=tol, budget=args.budget)
    if not args.no_write:
        paths = verdict.write_reports(v, args.out_dir)
        print(f"autopilot: wrote {paths['md']} and {paths['json']}",
              file=sys.stderr)
    if args.format == "json":
        print(json.dumps(v, indent=1, sort_keys=True))
    else:
        print(verdict.render_markdown(v))
    if v["regressed"]:
        print("autopilot: REGRESSED: " + ", ".join(v["regressed"]),
              file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
