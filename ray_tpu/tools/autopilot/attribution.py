"""Stage 1 of the autopilot loop: roofline attribution.

The perf observatory (``_private/device_stats.py``) already records,
per named program, the compiler's own FLOP count, bytes accessed, and
steady-state invoke walltimes.  This module turns that wall of gauges
into ONE statement: which program is the bottleneck, which side of the
roofline it sits on, and which knobs move it.

* **classification** — arithmetic intensity (FLOPs/byte from
  ``cost_analysis``) against the device ridge point
  (``peak_flops / hbm_bandwidth``): below the ridge the MXU starves on
  HBM no matter how well it is fed (*hbm-bound*), above it the program
  is *compute-bound* and MFU headroom is the whole story.
* **ranking** — headroom-weighted time share: a program that eats 70%
  of the walltime at 90% of its roofline ceiling is LESS interesting
  than one eating 25% at a third of its ceiling.  ``score =
  time_share * headroom`` ranks them; the top entry is named as *the*
  bottleneck.
* **knobs** — ``PROGRAM_KNOBS`` maps every runtime program the
  observatory registers to the sweep-able knobs that move it, which is
  what the planner (stage 2) grids over.  The graftcheck
  ``autopilot-attribution`` rule pins this catalog to the static
  ProgramSpec catalog, mirroring the PR-8 ``observatory-mapping``
  rule, so a new hot-path program cannot ship without an attribution
  entry.

Inputs are snapshot dicts — ``ProgramRegistry.snapshot()``,
``engine_stats()["programs"]``, or a dashboard ``/api/perf/programs``
dump — so attribution runs equally on the live process and on a canned
JSON file from a tunnel session.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

from ray_tpu._private import device_stats as _ds

#: runtime program name -> the sweep-able knobs that move it (the
#: planner's vocabulary).  Keys must stay a subset of
#: ``device_stats.KNOWN_PROGRAMS`` and must cover every
#: ``STATIC_PROGRAM_MAP`` target — both enforced by graftcheck's
#: ``autopilot-attribution`` rule, so the static auditor's hot-path
#: catalog, the runtime observatory, and this attribution table cannot
#: drift apart.
PROGRAM_KNOBS: Dict[str, Tuple[str, ...]] = {
    "train.step": ("batch", "remat_policy", "ce_impl",
                   "flash_resident"),
    "bench.train_step": ("batch", "remat_policy", "ce_impl",
                         "flash_resident"),
    "serve.prefill": ("prefill_bucket", "batch", "flash_resident"),
    "serve.paged_prefill": ("prefill_bucket", "block_size",
                            "flash_resident"),
    "serve.decode": ("batch", "kv_layout", "block_size",
                     "flash_resident"),
    "serve.spec_verify": ("spec_k", "spec_draft", "kv_layout"),
    "serve.spec_draft": ("spec_k", "spec_draft"),
    "serve.kv_handoff_export": ("block_size", "prefill_replicas"),
    "serve.kv_handoff_install": ("block_size", "decode_replicas"),
    "serve.sharded_prefill": ("tensor", "prefill_bucket", "batch"),
    "serve.sharded_paged_prefill": ("tensor", "prefill_bucket",
                                    "block_size"),
    "serve.sharded_decode": ("tensor", "batch", "kv_layout",
                             "block_size"),
    "serve.sharded_spec_verify": ("tensor", "spec_k", "spec_draft"),
    "serve.sharded_spec_draft": ("tensor", "spec_k", "spec_draft"),
    "serve.sharded_kv_handoff_export": ("tensor", "block_size",
                                        "prefill_replicas"),
    "serve.sharded_kv_handoff_install": ("tensor", "block_size",
                                         "decode_replicas"),
}


def classify(intensity: Optional[float],
             ridge: float) -> str:
    """``compute-bound`` / ``hbm-bound`` by arithmetic intensity vs the
    ridge point; ``unmeasured`` when the cost harvest never landed
    (no AOT compile on this backend, or ``RAYTPU_DEVICE_STATS_COST=0``)."""
    if not isinstance(intensity, (int, float)):
        return "unmeasured"
    return "compute-bound" if intensity >= ridge else "hbm-bound"


def _busy_ms(block: Dict[str, Any]) -> float:
    """Approximate walltime spent in a program's steady state: mean
    invoke over the recent window times total invokes.  Programs that
    only ever compiled contribute zero — they cannot be the
    steady-state bottleneck."""
    invoke = block.get("invoke_ms") or {}
    mean = invoke.get("mean")
    invokes = block.get("invokes") or 0
    if not isinstance(mean, (int, float)) or not invokes:
        return 0.0
    return float(mean) * int(invokes)


def _headroom(block: Dict[str, Any], cls: str,
              device: Dict[str, Any]) -> Optional[float]:
    """Distance from the program's own roofline ceiling, in [0, 1].

    Compute-bound: ``1 - mfu`` (the ceiling is the peak-FLOPs line).
    HBM-bound: ``1 - achieved_bytes_per_sec / peak_bw`` (the ceiling
    is the bandwidth line — a bandwidth-saturated program has no
    headroom even at terrible MFU).  None when the inputs to either
    ratio are missing."""
    invoke = block.get("invoke_ms") or {}
    mean_ms = invoke.get("mean")
    if cls == "compute-bound":
        mfu = block.get("mfu")
        if isinstance(mfu, (int, float)):
            return round(min(1.0, max(0.0, 1.0 - float(mfu))), 4)
        return None
    if cls == "hbm-bound":
        nbytes = block.get("bytes_accessed")
        bw = device.get("peak_hbm_bytes_per_sec")
        if (isinstance(nbytes, (int, float))
                and isinstance(mean_ms, (int, float)) and mean_ms > 0
                and isinstance(bw, (int, float)) and bw > 0):
            util = float(nbytes) / (float(mean_ms) / 1e3) / float(bw)
            return round(min(1.0, max(0.0, 1.0 - util)), 4)
        return None
    return None


#: re-prefill waste fraction above which the serving bottleneck is
#: called cache thrash: the KV pool is evicting prefixes it re-fills,
#: so prefill compute is going to content the pool already held
CACHE_THRASH_WASTE_FRAC = 0.15


def attribute(programs: Dict[str, Dict[str, Any]],
              device: Optional[Dict[str, Any]] = None,
              request_anatomy: Optional[Dict[str, Any]] = None,
              train_anatomy: Optional[Dict[str, Any]] = None,
              kv_scope: Optional[Dict[str, Any]] = None,
              kv_tier: Optional[Dict[str, Any]] = None
              ) -> Dict[str, Any]:
    """Attribute a programs snapshot against the device roofline.

    ``programs`` is any ``{name: block}`` snapshot the observatory
    emits; ``device`` is a :func:`device_stats.device_roofline` block
    (taken from the snapshot's origin when attributing a remote dump;
    defaults to this process's devices).  ``request_anatomy`` is an
    optional tracebus ``request_evidence()`` block (tools/tracebus.py)
    — the p99 per-request critical-path decomposition — which names
    the dominant *lifecycle* leg (queue wait, prefill, inter-token
    gaps, ...) to complement the roofline's program-granularity view:
    a device bottleneck only matters if the request tail is actually
    spent on device.  ``train_anatomy`` is the trainwatch view — a
    ``train_stats()``-shaped dict (or just its ``anatomy``/``goodput``
    blocks, train/goodput.py): when ``data_wait`` dominates the step
    anatomy the summary cites *input-bound* — sweeping device knobs
    cannot move a loop that is starving on its batch iterator.
    ``kv_scope`` is the kvscope block (``engine_stats()["kv_scope"]``
    or the fleet-pooled variant): when the re-prefill waste fraction
    crosses :data:`CACHE_THRASH_WASTE_FRAC` the summary names the
    serving loop *cache-thrash-bound* — a meaningful share of prefill
    compute is re-filling prefixes the pool already held and evicted,
    so the lever is pool size (or a host-RAM KV tier), not program
    knobs.  ``kv_tier`` is the host-tier block
    (``engine_stats()["kv_tier"]`` or the fleet-pooled variant): when
    the RESIDUAL waste is below threshold but the would-be waste —
    counting tokens the tier re-admitted via H2D as churn that would
    have been re-prefill without it — crosses it, the summary stops
    calling the loop cache-thrash-bound and instead credits the tier
    with absorbing the churn (the lever becomes tier budget, not pool
    size).  Returns::

        {"device": {...roofline...},
         "programs": {name: {"class", "arithmetic_intensity", "mfu",
                             "time_share", "headroom", "score",
                             "busy_ms", "recompile_storm", "knobs"}},
         "ranked": [names, best-score first],
         "bottleneck": name | None,
         "request_anatomy": evidence block | None,
         "summary": one-sentence statement}
    """
    if device is None:
        device = _ds.device_roofline()
    ridge = float(device.get("ridge_flops_per_byte") or 1.0)
    busy = {name: _busy_ms(block)
            for name, block in programs.items()}
    total_ms = sum(busy.values())
    out: Dict[str, Dict[str, Any]] = {}
    for name, block in programs.items():
        intensity = block.get("arithmetic_intensity")
        cls = classify(intensity, ridge)
        share = (busy[name] / total_ms) if total_ms > 0 else 0.0
        headroom = _headroom(block, cls, device)
        # unmeasured headroom is treated as full headroom for ranking:
        # "we do not even know" is a reason to look, not to skip
        score = share * (1.0 if headroom is None else headroom)
        out[name] = {
            "class": cls,
            "arithmetic_intensity": intensity,
            "ridge_flops_per_byte": ridge,
            "mfu": block.get("mfu"),
            "busy_ms": round(busy[name], 3),
            "time_share": round(share, 4),
            "headroom": headroom,
            "score": round(score, 4),
            "invokes": block.get("invokes"),
            "recompile_storm": bool(block.get("recompile_storm")),
            "knobs": list(PROGRAM_KNOBS.get(name, ())),
        }
    ranked = sorted(out, key=lambda n: (-out[n]["score"], n))
    bottleneck = next((n for n in ranked if out[n]["score"] > 0), None)
    if bottleneck is not None:
        b = out[bottleneck]
        knobs = "/".join(b["knobs"]) or "(no catalogued knobs)"
        summary = (
            f"bottleneck: {bottleneck} ({b['class']}, "
            f"{b['time_share']:.0%} of program walltime, headroom "
            f"{'unknown' if b['headroom'] is None else b['headroom']})"
            f" — sweep {knobs}")
    elif programs:
        summary = ("no steady-state invokes recorded — programs "
                   "compiled but never ran; nothing to attribute")
    else:
        summary = "no programs registered"
    if request_anatomy and request_anatomy.get("dominant_component"):
        dom = request_anatomy["dominant_component"]
        pct = request_anatomy.get("percentile", 99)
        comps = (request_anatomy.get("overall") or {}).get(
            "components") or {}
        val = comps.get(dom)
        summary += (
            f"; request p{pct:g} tail dominated by {dom}"
            + (f" ({val:.1f} ms)" if isinstance(val, (int, float))
               else ""))
    if train_anatomy:
        from ray_tpu.train.goodput import dominant_component

        anatomy = train_anatomy.get("anatomy") or train_anatomy
        dom = dominant_component(anatomy)
        if dom is not None:
            mean = (anatomy.get(dom) or {}).get("mean")
            ratio = (train_anatomy.get("goodput") or {}).get("ratio")
            gp = (f", goodput {ratio}" if isinstance(
                ratio, (int, float)) else "")
            if dom == "data_wait_ms":
                summary += (
                    f"; training is input-bound: data_wait dominates "
                    f"step anatomy ({mean:.1f} ms mean{gp}) — feed "
                    f"the loop before sweeping device knobs")
            else:
                summary += (f"; train step anatomy dominated by "
                            f"{dom} ({mean:.1f} ms mean{gp})")
    if kv_scope:
        # engine shape nests the waste under "forensics"; the
        # fleet-pooled block (router fleet_stats) is flat
        fx = kv_scope.get("forensics") or kv_scope
        frac = fx.get("reprefill_waste_frac") or 0.0
        # tokens the host tier re-admitted via H2D are churn that
        # WOULD have been re-prefill waste without it — the tier block
        # is authoritative, the kvscope forensics mirror is fallback
        restored = int((kv_tier or {}).get("tokens_restored")
                       or fx.get("tokens_restored") or 0)
        if frac >= CACHE_THRASH_WASTE_FRAC:
            summary += (
                f"; serving is cache-thrash-bound: {frac:.0%} of "
                f"prefill tokens re-filled previously-resident "
                f"prefixes ({fx.get('reprefill_waste_tokens', 0)} "
                f"tokens) — grow the KV pool before sweeping "
                f"program knobs")
            if restored:
                summary += (
                    f" (host KV tier restored {restored} tokens but "
                    f"thrash persists — grow its byte budget too)")
        elif restored:
            prefill = float(fx.get("prefill_tokens") or 0)
            waste = float(fx.get("reprefill_waste_tokens") or 0)
            denom = prefill + restored
            would_be = (waste + restored) / denom if denom > 0 else 0.0
            if would_be >= CACHE_THRASH_WASTE_FRAC:
                hit_rate = (kv_tier or {}).get("hit_rate")
                hr = (f", tier hit rate {hit_rate:.0%}"
                      if isinstance(hit_rate, (int, float)) else "")
                summary += (
                    f"; host KV tier is absorbing cache churn: "
                    f"{restored} tokens re-admitted via H2D instead "
                    f"of re-prefill (would-be waste {would_be:.0%} "
                    f"vs {frac:.0%} residual{hr}) — pool churn is "
                    f"handled, not a bottleneck")
    return {"device": device, "programs": out, "ranked": ranked,
            "bottleneck": bottleneck,
            "request_anatomy": request_anatomy,
            "train_anatomy": train_anatomy, "kv_scope": kv_scope,
            "kv_tier": kv_tier, "summary": summary}


def attribute_registry() -> Dict[str, Any]:
    """Attribute this process's live ``ProgramRegistry`` (the
    ``bench.py --autopilot`` / dashboard path)."""
    devices = _ds.device_memory_stats()
    snapshot = _ds.get_registry().snapshot(
        n_devices=max(1, len(devices)))
    return attribute(snapshot)


def render_text(report: Dict[str, Any]) -> str:
    """Human rendering of one attribution report."""
    dev = report["device"]
    lines = [
        f"device: {dev.get('device_kind') or dev.get('backend') or '?'}"
        f"  peak {dev['peak_flops_per_chip']:.3g} FLOP/s, "
        f"{dev['peak_hbm_bytes_per_sec']:.3g} B/s, "
        f"ridge {dev['ridge_flops_per_byte']} FLOP/B",
        "",
    ]
    for name in report["ranked"]:
        p = report["programs"][name]
        ai = p["arithmetic_intensity"]
        lines.append(
            f"  {name:<28s} {p['class']:<14s} "
            f"AI={'-' if ai is None else format(ai, '.1f'):<8s} "
            f"share={p['time_share']:<7.2%} "
            f"headroom={'-' if p['headroom'] is None else p['headroom']}"
            f" score={p['score']}")
    anatomy = report.get("request_anatomy")
    if anatomy and anatomy.get("overall", {}).get("requests"):
        over = anatomy["overall"]
        comps = over["components"]
        pct = anatomy.get("percentile", 99)
        parts = " ".join(
            f"{k.replace('_ms', '')}={comps[k]:.1f}"
            for k in sorted(comps) if k != "e2e_ms"
            and isinstance(comps.get(k), (int, float)))
        lines += ["", f"  request p{pct:g} critical path "
                      f"({over['requests']} reqs, "
                      f"e2e {comps.get('e2e_ms') or 0.0:.1f} ms): "
                      f"{parts}"]
    lines += ["", report["summary"]]
    return "\n".join(lines)


__all__: List[str] = ["CACHE_THRASH_WASTE_FRAC", "PROGRAM_KNOBS",
                      "attribute", "attribute_registry", "classify",
                      "render_text"]
