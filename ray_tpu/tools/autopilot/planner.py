"""Stage 2 of the autopilot loop: sweep planning.

Turns "what do we not know yet" into a concrete, runnable grid.  The
planner owns a static candidate catalog — every A/B the PERF_NOTES
rounds queued (ce_impl, remat policy, flash residency, decode batch,
tensor degree, spec_k, kv layout, block size, prefill buckets) as
``sweep_tpu.py`` ``[batch, {overrides}]`` entries — and grades each
candidate against the ledger:

* **regressed** — the candidate's variant-hash series exists in
  BENCH_HISTORY.jsonl and its newest point regressed (perfledger
  ``check``): re-measure first, a regression verdict on one stale
  point is noise until confirmed.
* **unmeasured** — no series under the candidate's hash: the A/B has
  never produced a ledger point.
* **stale** — measured, but the newest point's provenance SHA is not
  the current tree (or predates provenance stamping): numbers from a
  different tree don't answer today's question.
* **fresh** — measured at the current SHA; dropped from the plan.

Candidates are mapped to the observatory's program names, so when an
attribution report is supplied the ones targeting *the* bottleneck get
a priority bump — the Ray-paper move of scheduling work from live
metric signals instead of operator intuition.  ``--budget N`` keeps
the emitted grid affordable, highest expected information first.

The grid hash MUST match what ``sweep_tpu.py`` will later record, so
:func:`mirror_variant` reproduces, default-for-default, the exact
variant dict each sweep mode writes into its SWEEPJSON record; a unit
test locks the two implementations together.
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.tools import perfledger

#: grid entries the PERF_NOTES rounds queued, in catalog order (ties in
#: priority resolve to this order).  ``programs`` names the observatory
#: programs the knob moves — the hook that lets an attribution report
#: re-rank the catalog around the measured bottleneck.
CANDIDATES: Tuple[Dict[str, Any], ...] = (
    # -- train: fused-CE impl + remat policy + flash residency (r6/r7)
    {"id": "train-ce-fused-b32", "batch": 32,
     "overrides": {"ce_impl": "streaming_xla"},
     "programs": ("train.step", "bench.train_step"),
     "rationale": "round-6 control arm: streaming fused CE at the "
                  "round-5 best batch"},
    {"id": "train-ce-pallas-b24", "batch": 24,
     "overrides": {"ce_impl": "pallas"},
     "programs": ("train.step", "bench.train_step"),
     "rationale": "round-6 queued A/B: pallas CE kernel, smaller batch "
                  "to fit the fused logits"},
    {"id": "train-ce-pallas-b32", "batch": 32,
     "overrides": {"ce_impl": "pallas"},
     "programs": ("train.step", "bench.train_step"),
     "rationale": "pallas CE at the control batch — isolates the "
                  "kernel from the batch effect"},
    {"id": "train-ce-pallas-b48", "batch": 48,
     "overrides": {"ce_impl": "pallas"},
     "programs": ("train.step", "bench.train_step"),
     "rationale": "pallas CE frees logit HBM — test whether the saved "
                  "memory buys a bigger batch"},
    {"id": "train-remat-dots-b32", "batch": 32,
     "overrides": {"remat_policy": "dots_nb"},
     "programs": ("train.step", "bench.train_step"),
     "rationale": "remat dots-no-batch vs default: trade recompute "
                  "for activation HBM"},
    {"id": "train-flash-resident-b32", "batch": 32,
     "overrides": {"flash_resident": "on"},
     "programs": ("train.step", "bench.train_step"),
     "rationale": "flash-resident attention on the train step "
                  "(round-7 queue)"},
    # -- decode: batch scaling + flash residency (r8/r9)
    {"id": "decode-b8", "batch": 8, "overrides": {"mode": "decode"},
     "programs": ("serve.decode", "serve.prefill"),
     "rationale": "decode control arm at batch 8"},
    {"id": "decode-b16", "batch": 16, "overrides": {"mode": "decode"},
     "programs": ("serve.decode", "serve.prefill"),
     "rationale": "decode batch 16 — is steady-state decode still "
                  "HBM-bound at 2x batch?"},
    {"id": "decode-b16-flash", "batch": 16,
     "overrides": {"mode": "decode", "flash_resident": "on"},
     "programs": ("serve.decode", "serve.prefill"),
     "rationale": "flash-resident attention under decode: the kernel "
                  "reads the cache it keeps resident"},
    # -- tensor parallel decode (r9)
    {"id": "decode-sharded-t4", "batch": 8,
     "overrides": {"mode": "decode_sharded", "tensor": 4},
     "programs": ("serve.sharded_decode",),
     "rationale": "tensor degree 4: per-chip KV shrinks 4x, collective "
                  "cost enters the inter-token path"},
    {"id": "decode-sharded-t8", "batch": 8,
     "overrides": {"mode": "decode_sharded", "tensor": 8},
     "programs": ("serve.sharded_decode",),
     "rationale": "tensor degree 8 vs 4: where does the all-gather "
                  "overtake the HBM win?"},
    # -- speculative decoding spec_k (r10)
    {"id": "spec-k2", "batch": 8,
     "overrides": {"mode": "decode_spec", "spec_k": 2},
     "programs": ("serve.spec_verify", "serve.spec_draft"),
     "rationale": "spec_k=2: cheapest draft, dispatch/token floor 0.5"},
    {"id": "spec-k4", "batch": 8,
     "overrides": {"mode": "decode_spec", "spec_k": 4},
     "programs": ("serve.spec_verify", "serve.spec_draft"),
     "rationale": "spec_k=4: the round-10 default arm"},
    {"id": "spec-k8", "batch": 8,
     "overrides": {"mode": "decode_spec", "spec_k": 8},
     "programs": ("serve.spec_verify", "serve.spec_draft"),
     "rationale": "spec_k=8: acceptance decay vs dispatch savings "
                  "crossover"},
    # -- traffic: kv layout, block size, prefill buckets, tensor (r8-11)
    {"id": "traffic-dense", "batch": 8,
     "overrides": {"mode": "traffic", "kv_layout": "dense"},
     "programs": ("serve.decode", "serve.prefill"),
     "rationale": "dense-KV control arm under seeded shared-prefix "
                  "load"},
    {"id": "traffic-paged", "batch": 8,
     "overrides": {"mode": "traffic", "kv_layout": "paged"},
     "programs": ("serve.decode", "serve.paged_prefill"),
     "rationale": "paged KV vs dense: prefix reuse + hit rate vs gather "
                  "overhead"},
    {"id": "traffic-paged-bs32", "batch": 8,
     "overrides": {"mode": "traffic", "kv_layout": "paged",
                   "block_size": 32},
     "programs": ("serve.decode", "serve.paged_prefill"),
     "rationale": "block 32 vs 16: fewer page-table hops per token at "
                  "coarser sharing granularity"},
    {"id": "traffic-paged-bs64", "batch": 8,
     "overrides": {"mode": "traffic", "kv_layout": "paged",
                   "block_size": 64},
     "programs": ("serve.decode", "serve.paged_prefill"),
     "rationale": "block 64: the coarse end of the block-size curve"},
    {"id": "traffic-bucket256", "batch": 8,
     "overrides": {"mode": "traffic", "kv_layout": "paged",
                   "prefill_bucket": 256},
     "programs": ("serve.paged_prefill", "serve.prefill"),
     "rationale": "prefill bucket 256 vs 128: recompile count vs "
                  "padding waste"},
    {"id": "traffic-paged-t4", "batch": 8,
     "overrides": {"mode": "traffic", "kv_layout": "paged",
                   "tensor": 4},
     "programs": ("serve.sharded_decode",
                  "serve.sharded_paged_prefill"),
     "rationale": "sharded engine under live traffic: does the tensor "
                  "win survive scheduling noise?"},
)

#: status -> base priority; fresh candidates fall out of the plan
_STATUS_SCORE = {"regressed": 3.0, "unmeasured": 2.0, "stale": 1.0,
                 "fresh": 0.0}
#: added when the candidate targets the attribution's named bottleneck
_BOTTLENECK_BONUS = 0.5


def mirror_variant(batch: int,
                   overrides: Dict[str, Any]) -> Dict[str, Any]:
    """The exact variant dict ``sweep_tpu.run_sweep`` would record for
    ``[batch, overrides]`` — same keys, same defaults, leftovers under
    ``overrides`` — so ``perfledger._variant_key`` of the mirror equals
    the hash of the future measurement.  Kept in lockstep with
    sweep_tpu.py by ``tests/test_autopilot.py``."""
    kw = dict(overrides)
    mode = kw.pop("mode", "train")
    if mode in ("decode", "decode_sharded"):
        prompt_len = kw.pop("prompt_len",
                            kw.pop("max_seq", kw.pop("seq", 128)))
        return {"mode": mode, "batch": batch, "prompt_len": prompt_len,
                "new_tokens": kw.pop("new_tokens", 64),
                "preset": kw.pop("preset", "gpt2"),
                # planner candidates always carry an explicit tensor
                # for the sharded mode (sweep_tpu's default is "all
                # local devices", which the planner cannot know)
                "tensor": kw.pop("tensor", 1), "overrides": kw}
    if mode == "decode_spec":
        return {"mode": mode, "batch": batch,
                "prompt_len": kw.pop("prompt_len", 128),
                "new_tokens": kw.pop("new_tokens", 64),
                "preset": kw.pop("preset", "gpt2"),
                "spec_k": kw.pop("spec_k", kw.pop("k", 4)),
                "spec_draft": kw.pop("spec_draft", "aligned"),
                "kv_layout": kw.pop("kv_layout", "dense"),
                "tensor": kw.pop("tensor", 1), "overrides": kw}
    if mode == "traffic":
        variant = {"mode": mode, "max_slots": batch,
                   "kv_layout": kw.pop("kv_layout", "paged"),
                   "tensor": kw.pop("tensor", 1),
                   "spec_k": kw.pop("spec_k", 0),
                   "requests": kw.pop("requests", 64),
                   "prefix_len": kw.pop("prefix_len", 256),
                   "p_shared": kw.pop("p_shared", 0.75),
                   "rate_rps": kw.pop("rate_rps", 32.0),
                   "preset": kw.pop("preset", "gpt2"),
                   "block_size": kw.pop("block_size", 16),
                   "prefill_bucket": kw.pop("prefill_bucket", 128),
                   # identity keys sweep_tpu records so A/B arms never
                   # hash into one ledger series — mirrored with the
                   # same `or None` normalization (0 = off = default)
                   "prefill_chunk_tokens":
                       kw.pop("prefill_chunk", None) or None,
                   "long_prompt_len": kw.pop("long_prompt_len", None),
                   "kv_host_tier_bytes":
                       kw.pop("kv_host_tier_bytes", None) or None,
                   "kv_num_blocks":
                       kw.pop("kv_num_blocks", None) or None}
        for consumed in ("spec_draft", "ttft_slo_ms", "e2e_slo_ms",
                         "seed", "prefix_groups", "tail_len_mean",
                         "tail_len_max", "vocab", "new_tokens",
                         "time_scale", "latency_slo_ms",
                         "max_queue_depth"):
            kw.pop(consumed, None)
        variant["overrides"] = kw
        return variant
    return {"batch_per_chip": batch,
            "seq": kw.pop("max_seq", kw.pop("seq", 1024)),
            "preset": kw.pop("preset", "gpt2"), "overrides": kw}


def candidate_status(cand: Dict[str, Any],
                     entries: List[Dict[str, Any]],
                     verdicts: Dict[str, Any],
                     current_sha: Optional[str]) -> Dict[str, Any]:
    """Grade one candidate against the ledger: its mirrored variant
    hash, which series exist under it, and whether the newest point is
    regressed / stale / fresh."""
    variant = mirror_variant(cand["batch"], cand["overrides"])
    vhash = perfledger._variant_key(variant)
    suffix = "#" + vhash
    names = [n for n in verdicts if n.endswith(suffix)]
    if not names:
        return {"variant": variant, "hash": vhash,
                "status": "unmeasured", "series": []}
    if any(verdicts[n].get("verdict") == "regress"
           or verdicts[n].get("baseline_verdict") == "regress"
           for n in names):
        return {"variant": variant, "hash": vhash,
                "status": "regressed", "series": names}
    newest = max(verdicts[n]["entry"] for n in names)
    prov = entries[newest].get("provenance") or {}
    sha = prov.get("git_sha")
    if sha is None or current_sha is None or sha != current_sha:
        return {"variant": variant, "hash": vhash, "status": "stale",
                "series": names, "measured_sha": sha}
    return {"variant": variant, "hash": vhash, "status": "fresh",
            "series": names, "measured_sha": sha}


def plan(history: Optional[str] = None,
         baseline: Optional[str] = None,
         budget: int = 8,
         attribution: Optional[Dict[str, Any]] = None,
         include_fresh: bool = False) -> Dict[str, Any]:
    """The next sweep: every catalog candidate graded against the
    ledger, the top ``budget`` by expected information kept.  Returns::

        {"git_sha": ..., "budget": ..., "bottleneck": ...,
         "variants": [{"id", "batch", "overrides", "variant", "hash",
                       "status", "score", "rationale"}],
         "skipped_fresh": [ids],
         "grid": [[batch, overrides], ...]}     # sweep_tpu.py argv[1]
    """
    entries = perfledger.load_history(history)
    verdicts = perfledger.check(history, baseline)["verdicts"]
    current_sha = perfledger.provenance().get("git_sha")
    bottleneck = (attribution or {}).get("bottleneck")
    bottleneck_knobs = set()
    if bottleneck and attribution:
        prog = (attribution.get("programs") or {}).get(bottleneck) or {}
        bottleneck_knobs = set(prog.get("knobs") or ())
    graded: List[Dict[str, Any]] = []
    skipped: List[str] = []
    for order, cand in enumerate(CANDIDATES):
        st = candidate_status(cand, entries, verdicts, current_sha)
        score = _STATUS_SCORE[st["status"]]
        targets_bottleneck = bottleneck in (cand.get("programs") or ())
        if targets_bottleneck:
            score += _BOTTLENECK_BONUS
        if st["status"] == "fresh" and not include_fresh:
            skipped.append(cand["id"])
            continue
        reason = cand["rationale"]
        if st["status"] == "regressed":
            reason = (f"REGRESSED in ledger ({', '.join(st['series'])})"
                      f" — re-measure to confirm; " + reason)
        elif st["status"] == "stale":
            reason = (f"stale (measured at "
                      f"{st.get('measured_sha') or 'unknown SHA'}, "
                      f"tree is {current_sha or 'unknown'}); " + reason)
        if targets_bottleneck:
            reason += (f" [targets bottleneck {bottleneck}: "
                       f"{'/'.join(sorted(bottleneck_knobs)) or '-'}]")
        graded.append({"id": cand["id"], "batch": cand["batch"],
                       "overrides": dict(cand["overrides"]),
                       "programs": list(cand.get("programs") or ()),
                       "variant": st["variant"], "hash": st["hash"],
                       "status": st["status"], "score": round(score, 2),
                       "order": order, "rationale": reason})
    graded.sort(key=lambda g: (-g["score"], g["order"]))
    chosen = graded[:max(0, budget)] if budget else graded
    for g in chosen:
        g.pop("order", None)
    return {"git_sha": current_sha, "budget": budget,
            "bottleneck": bottleneck,
            "variants": chosen, "skipped_fresh": skipped,
            "grid": [[g["batch"], g["overrides"]] for g in chosen]}


def render_text(p: Dict[str, Any]) -> str:
    """Human rendering of one plan."""
    lines = [f"plan @ {p['git_sha'] or 'unknown SHA'} — "
             f"{len(p['variants'])} of budget {p['budget']}"
             + (f", bottleneck {p['bottleneck']}" if p["bottleneck"]
                else "")]
    for g in p["variants"]:
        lines.append(f"  [{g['status']:<10s}] {g['id']:<24s} "
                     f"#{g['hash']}  {g['rationale']}")
    if p["skipped_fresh"]:
        lines.append(f"  (fresh, skipped: "
                     f"{', '.join(p['skipped_fresh'])})")
    lines.append("")
    lines.append("run: python sweep_tpu.py "
                 + json.dumps(json.dumps(p["grid"])))
    return "\n".join(lines)


__all__ = ["CANDIDATES", "mirror_variant", "candidate_status", "plan",
           "render_text"]
