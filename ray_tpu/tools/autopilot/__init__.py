"""Closed-loop perf autopilot over the observatory + ledger.

observatory → **attribute** → **plan** → sweep → ledger → **verdict**
(and the verdict's report embeds the next plan, closing the loop).
Three stages behind ``python -m ray_tpu.tools.autopilot``; see
docs/observability.md#autopilot for the loop diagram and
docs/static-analysis.md for the lint rules that pin this package to
the program catalogs.
"""

from ray_tpu.tools.autopilot.attribution import (PROGRAM_KNOBS,
                                                 attribute,
                                                 attribute_registry,
                                                 classify)
from ray_tpu.tools.autopilot.planner import (CANDIDATES,
                                             mirror_variant, plan)
from ray_tpu.tools.autopilot.verdict import (build_verdict,
                                             render_markdown,
                                             write_reports)

__all__ = [
    "PROGRAM_KNOBS", "attribute", "attribute_registry", "classify",
    "CANDIDATES", "mirror_variant", "plan",
    "build_verdict", "render_markdown", "write_reports",
]
