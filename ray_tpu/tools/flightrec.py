"""Postmortem CLI over flight-recorder dumps.

``python -m ray_tpu.tools.flightrec <cmd> <dump.json>`` inspects the
postmortem files the SLO watchdog / engine crash handler write
(``_private/flightrec.py`` ``dump()``):

* ``report``    — human summary: trigger, event counts by kind, drop
  counter, step-duration percentiles, recent sheds/errors, the
  breaching objective's burn rates when the dump carries an SLO
  context, — on fleet dumps (serve/router.py) — the per-replica
  routing table plus the last scale-up/scale-down/drain decisions,
  and — on trainwatch dumps (train/goodput.py) — the train lanes:
  step wall percentiles, the anomaly table (step index + trigger
  metric), recent checkpoint events, and the watchdog's metric trail.
  Exits 0 on a readable dump — scripts gate on it.
* ``events``    — the journal itself, filtered (``--kind``,
  ``--last``, ``--since/--until`` seconds) and printed one JSON
  object per line for ``jq`` piping; the correlate workflow is
  ``--kind slo_breach`` to find the breach time, then
  ``--since/--until`` around it.
* ``trace``     — convert the journal into a chrome-trace
  instant-event lane (and ``--merge`` it into an existing
  ``export_timeline()`` / ``ray_tpu timeline`` JSON), so decisions
  land on the same Perfetto canvas as the engine spans.
* ``sweepjson`` — summarize the dump into the SWEEPJSON metric-record
  shape ``tools/perfledger.py ingest`` consumes, so postmortems can
  join the ledger's trend series.

Pure stdlib + the chrome-trace builders; never imports jax, so it
works on a laptop holding only the dump file.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Any, Dict, List, Optional

from ray_tpu._private.telemetry import (instant_event,
                                        process_name_event, summarize,
                                        thread_name_event)

__all__ = ["load_dump", "filter_events", "report_lines",
           "trace_events", "sweepjson_records", "main"]


def load_dump(path: str) -> Dict[str, Any]:
    with open(path) as f:
        doc = json.load(f)
    if "events" not in doc:
        raise ValueError(f"{path} is not a flight-recorder dump "
                         "(no 'events' array)")
    return doc


def filter_events(events: List[Dict[str, Any]], *,
                  kinds: Optional[List[str]] = None,
                  since: Optional[float] = None,
                  until: Optional[float] = None,
                  last: Optional[int] = None,
                  request: Optional[str] = None
                  ) -> List[Dict[str, Any]]:
    """`request` follows ONE request through the journal: events whose
    ``req`` equals it (engine-local id) or whose ``trace`` matches it
    (tracebus id, full or prefix — serve/telemetry.py tags lifecycle
    and kv_* events with the trace in scope)."""
    out = events
    if kinds:
        want = set(kinds)
        out = [e for e in out if e.get("kind") in want]
    if request is not None:
        rid = str(request)
        out = [e for e in out
               if str(e.get("req")) == rid
               or (isinstance(e.get("trace"), str)
                   and e["trace"].startswith(rid))]
    if since is not None:
        out = [e for e in out if e.get("t_s", 0.0) >= since]
    if until is not None:
        out = [e for e in out if e.get("t_s", 0.0) <= until]
    if last is not None:
        out = out[-last:]
    return out


def report_lines(doc: Dict[str, Any]) -> List[str]:
    events = doc.get("events", [])
    lines = [
        f"flight record: {doc.get('source', '?')}"
        f"  reason={doc.get('reason') or '(manual)'}",
        f"created {doc.get('created', '?')}  uptime "
        f"{doc.get('uptime_s', '?')}s  events "
        f"{doc.get('events_retained', len(events))} retained / "
        f"{doc.get('events_recorded', '?')} recorded / "
        f"{doc.get('events_dropped', 0)} dropped",
    ]
    counts = doc.get("counts_by_kind") or {}
    if counts:
        lines.append("events by kind: " + ", ".join(
            f"{k}={v}" for k, v in sorted(counts.items())))
    steps = [e["dur_ms"] for e in events
             if e.get("kind") == "step" and "dur_ms" in e]
    if steps:
        s = summarize(steps)
        lines.append(f"step dur_ms: n={s['count']} mean={s['mean']} "
                     f"p50={s['p50']} p95={s['p95']} max={s['max']}")
    # train lanes (trainwatch dumps, train/goodput.py): step metric
    # trail percentiles plus the anomaly table the watchdog journaled
    tsteps = [e for e in events if e.get("kind") == "train_step"]
    if tsteps:
        walls = [e["wall_ms"] for e in tsteps if "wall_ms" in e]
        losses = [e["loss"] for e in tsteps
                  if isinstance(e.get("loss"), (int, float))]
        line = f"train steps: n={len(tsteps)}"
        if walls:
            s = summarize(walls)
            line += (f"  wall_ms p50={s['p50']} p95={s['p95']} "
                     f"max={s['max']}")
        if losses:
            line += f"  last_loss={losses[-1]}"
        lines.append(line)
    anomalies = [e for e in events if e.get("kind") == "train_anomaly"]
    if anomalies:
        lines.append("train anomalies (step  metric  value  reason):")
        for e in anomalies[-10:]:
            lines.append(f"  {e.get('step')}  {e.get('metric')}  "
                         f"{e.get('value')}  {e.get('reason')}")
    for label, kind in (("checkpoint saves", "ckpt_save"),
                        ("checkpoint restores", "ckpt_restore")):
        tail = filter_events(events, kinds=[kind], last=3)
        if tail:
            lines.append(f"last {label}:")
            for e in tail:
                lines.append("  " + json.dumps(e, sort_keys=True))
    ctx = doc.get("context") or {}
    if ctx.get("trainer"):
        lines.append(
            f"train anomaly: trainer={ctx['trainer']}  "
            f"step={ctx.get('step')}  reason={ctx.get('reason')}  "
            f"{ctx.get('metric')}={ctx.get('value')}")
        trail = ctx.get("trail") or []
        if trail:
            lines.append("metric trail (last "
                         f"{len(trail)} steps):")
            for t in trail[-8:]:
                lines.append("  " + json.dumps(t, sort_keys=True))
    slo = ctx.get("slo")
    if isinstance(slo, dict):
        objective = ctx.get("objective")
        lines.append(
            f"SLO breach: objective={objective or '?'}  "
            f"breaches={slo.get('breaches')}")
        for name, obj in (slo.get("objectives") or {}).items():
            mark = " <-- BREACHED" if obj.get("breached") else ""
            lines.append(
                f"  {name}: target {obj.get('target_ms')}ms  "
                f"attainment {obj.get('attainment')}  "
                f"burn_rate {obj.get('burn_rate')}"
                f" ({obj.get('violations')}/{obj.get('samples')} "
                f"over target){mark}")
    if ctx.get("program"):
        lines.append(f"recompile storm: program={ctx['program']}")
    if ctx.get("error"):
        lines.append(f"engine error: {ctx['error']}")
    # fleet routing table: aggregate the router's `route` events per
    # replica so a postmortem shows where traffic actually landed and
    # why (prefix affinity vs. load fallback vs. round-robin)
    routes = [e for e in events if e.get("kind") == "route"]
    if routes:
        table: Dict[str, Dict[str, Any]] = {}
        for e in routes:
            row = table.setdefault(str(e.get("replica", "?")), {
                "routed": 0, "prefix_affinity": 0, "p2c": 0,
                "round_robin": 0, "matched_blocks": 0,
                "tenants": set()})
            row["routed"] += 1
            policy = str(e.get("policy", "?"))
            if policy in row:
                row[policy] += 1
            row["matched_blocks"] += int(e.get("matched_blocks", 0))
            if e.get("tenant"):
                row["tenants"].add(str(e["tenant"]))
        lines.append("routing table (route events by replica):")
        lines.append("  replica  routed  prefix  p2c  rr  "
                     "matched_blocks  tenants")
        for name in sorted(table):
            row = table[name]
            tenants = ",".join(sorted(row["tenants"])) or "-"
            lines.append(
                f"  {name}  {row['routed']}  "
                f"{row['prefix_affinity']}  {row['p2c']}  "
                f"{row['round_robin']}  {row['matched_blocks']}  "
                f"{tenants}")
    # healthwatch lane (serve/health.py): the liveness state machine's
    # journaled transitions + stall events, aggregated per replica so
    # a postmortem reads "which replica got sick, when, and why"
    # alongside the routing table above
    trans = [e for e in events if e.get("kind") == "health_transition"]
    stalls = [e for e in events if e.get("kind") == "request_stall"]
    if trans or stalls:
        health: Dict[str, Dict[str, Any]] = {}
        for e in trans:
            row = health.setdefault(str(e.get("replica", "?")), {
                "transitions": 0, "suspect": 0, "dead": 0,
                "recovered": 0, "stalls": 0, "last": None,
                "detect_ms": None})
            row["transitions"] += 1
            to = str(e.get("to", "?"))
            if to == "suspect":
                row["suspect"] += 1
            elif to == "dead":
                row["dead"] += 1
            elif to == "healthy":
                row["recovered"] += 1
            row["last"] = (f"{e.get('from')}->{to} "
                           f"({e.get('reason')})")
            if e.get("time_to_detect_ms") is not None:
                row["detect_ms"] = e["time_to_detect_ms"]
        for e in stalls:
            row = health.setdefault(str(e.get("replica", "?")), {
                "transitions": 0, "suspect": 0, "dead": 0,
                "recovered": 0, "stalls": 0, "last": None,
                "detect_ms": None})
            row["stalls"] += 1
        lines.append("health transitions (by replica):")
        lines.append("  replica  transitions  suspect  dead  "
                     "recovered  stalls  detect_ms  last")
        for name in sorted(health):
            row = health[name]
            lines.append(
                f"  {name}  {row['transitions']}  {row['suspect']}  "
                f"{row['dead']}  {row['recovered']}  {row['stalls']}  "
                f"{row['detect_ms'] if row['detect_ms'] is not None else '-'}  "
                f"{row['last'] or '-'}")
        tail = filter_events(events, kinds=["request_stall"], last=3)
        if tail:
            lines.append("last request stalls:")
            for e in tail:
                lines.append("  " + json.dumps(e, sort_keys=True))
    for label, kind in (("scale-ups", "scale_up"),
                        ("scale-downs", "scale_down"),
                        ("drains", "drain")):
        tail = filter_events(events, kinds=[kind], last=3)
        if tail:
            lines.append(f"last {label}:")
            for e in tail:
                lines.append("  " + json.dumps(e, sort_keys=True))
    for label, kind in (("sheds", "shed"), ("errors", "error"),
                        ("requeues", "requeue"),
                        ("pool exhaustions", "kv_exhausted")):
        tail = filter_events(events, kinds=[kind], last=3)
        if tail:
            lines.append(f"last {label}:")
            for e in tail:
                lines.append("  " + json.dumps(e, sort_keys=True))
    return lines


def trace_events(doc: Dict[str, Any],
                 merge: Optional[List[Dict[str, Any]]] = None,
                 pid: int = 90, tid: int = 0) -> List[Dict[str, Any]]:
    """The journal as a chrome-trace instant-event lane.  `merge`
    prepends an existing timeline's events (export_timeline() /
    ``ray_tpu timeline`` write bare event arrays) — both use relative
    perf_counter origins, so the lanes line up when the dump and the
    timeline came from the same engine."""
    events: List[Dict[str, Any]] = list(merge or [])
    events.append(process_name_event(
        pid, f"flightrec {doc.get('source', '?')}"))
    events.append(thread_name_event(pid, tid, "engine decisions"))
    for e in doc.get("events", []):
        args = {k: v for k, v in e.items()
                if k not in ("kind", "t_s", "seq")}
        args["seq"] = e.get("seq")
        events.append(instant_event(
            str(e.get("kind", "event")), "flightrec",
            float(e.get("t_s", 0.0)), pid, tid, args))
    return events


def sweepjson_records(doc: Dict[str, Any]) -> List[Dict[str, Any]]:
    """Metric-shaped records ({"metric", "value", "unit", "detail"})
    in the SWEEPJSON dialect ``perfledger ingest`` reads."""
    events = doc.get("events", [])
    counts = doc.get("counts_by_kind") or {}
    detail = {"source": doc.get("source"), "reason": doc.get("reason"),
              "created": doc.get("created")}
    recs: List[Dict[str, Any]] = [
        {"metric": "flightrec_events_retained",
         "value": doc.get("events_retained", len(events)),
         "unit": "events", "detail": detail},
        {"metric": "flightrec_events_dropped",
         "value": doc.get("events_dropped", 0),
         "unit": "events", "detail": detail},
    ]
    for kind in ("shed", "error", "requeue", "kv_exhausted",
                 "recompile_storm", "train_anomaly"):
        if counts.get(kind):
            recs.append({"metric": f"flightrec_{kind}_events",
                         "value": counts[kind], "unit": "events",
                         "detail": detail})
    steps = [e["dur_ms"] for e in events
             if e.get("kind") == "step" and "dur_ms" in e]
    if steps:
        s = summarize(steps)
        recs.append({"metric": "flightrec_step_p95_ms",
                     "value": s["p95"], "unit": "ms",
                     "detail": dict(detail, count=s["count"],
                                    p50=s["p50"])})
    slo = (doc.get("context") or {}).get("slo")
    if isinstance(slo, dict):
        for name, obj in (slo.get("objectives") or {}).items():
            if isinstance(obj.get("burn_rate"), (int, float)):
                recs.append({
                    "metric": f"flightrec_{name}_burn_rate",
                    "value": obj["burn_rate"], "unit": "ratio",
                    "detail": dict(detail,
                                   target_ms=obj.get("target_ms"))})
            if isinstance(obj.get("attainment"), (int, float)):
                recs.append({
                    "metric": f"flightrec_{name}_slo_attainment",
                    "value": obj["attainment"], "unit": "fraction",
                    "detail": dict(detail,
                                   target_ms=obj.get("target_ms"))})
    return recs


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m ray_tpu.tools.flightrec",
        description="inspect flight-recorder postmortem dumps")
    sub = ap.add_subparsers(dest="cmd", required=True)

    p = sub.add_parser("report", help="human summary of one dump")
    p.add_argument("dump")

    p = sub.add_parser("events", help="filtered journal, JSONL")
    p.add_argument("dump")
    p.add_argument("--kind", default=None,
                   help="comma-separated event kinds to keep")
    p.add_argument("--last", type=int, default=None,
                   help="keep only the last N (after other filters)")
    p.add_argument("--since", type=float, default=None,
                   help="relative seconds (t_s) lower bound")
    p.add_argument("--until", type=float, default=None,
                   help="relative seconds (t_s) upper bound")
    p.add_argument("--request", default=None,
                   help="follow one request: engine-local id (req "
                        "field) or tracebus trace id / prefix")

    p = sub.add_parser("trace",
                       help="chrome-trace instant-event lane")
    p.add_argument("dump")
    p.add_argument("-o", "--out", default=None,
                   help="write trace JSON here (default: stdout)")
    p.add_argument("--merge", default=None,
                   help="existing timeline JSON to merge the lane "
                        "into (export_timeline / ray_tpu timeline)")

    p = sub.add_parser("sweepjson",
                       help="SWEEPJSON records for perfledger ingest")
    p.add_argument("dump")

    args = ap.parse_args(argv)
    try:
        doc = load_dump(args.dump)
    except (OSError, ValueError, json.JSONDecodeError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2

    if args.cmd == "report":
        for line in report_lines(doc):
            print(line)
        return 0
    if args.cmd == "events":
        kinds = args.kind.split(",") if args.kind else None
        for e in filter_events(doc["events"], kinds=kinds,
                               since=args.since, until=args.until,
                               last=args.last, request=args.request):
            print(json.dumps(e, sort_keys=True))
        return 0
    if args.cmd == "trace":
        merge = None
        if args.merge:
            with open(args.merge) as f:
                merge = json.load(f)
        events = trace_events(doc, merge=merge)
        payload = json.dumps(events)
        if args.out:
            with open(args.out, "w") as f:
                f.write(payload)
            print(f"wrote {len(events)} events to {args.out}")
        else:
            print(payload)
        return 0
    # sweepjson
    for rec in sweepjson_records(doc):
        print(json.dumps(rec, sort_keys=True))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
