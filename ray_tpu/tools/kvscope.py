"""kvscope CLI — inspect KV-cache & HBM observatory snapshots.

    python -m ray_tpu.tools.kvscope report   SNAPSHOT
    python -m ray_tpu.tools.kvscope timeline SNAPSHOT [--engine NAME]
    python -m ray_tpu.tools.kvscope export   SNAPSHOT [-o trace.json]

``SNAPSHOT`` is a JSON file carrying one or more ``kv_scope`` blocks
(serve/kvscope.py shape), accepted in any of the forms the stack
emits: a bare block, an ``engine_stats()`` dump, or the dashboard's
``/api/serve/kvscope`` map of ``{deployment: {"kv_scope": ...}}``.

``report`` prints the occupancy / forensics / HBM-ledger summary;
``timeline`` renders the occupancy ring as a text strip chart (one
row per engine wave); ``export`` writes a chrome-trace with counter
lanes (``ph: "C"``) — load it next to a tracebus export and the pool
pressure curve lines up under the request spans that caused it.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Any, Dict, List, Optional

from ray_tpu._private.telemetry import (process_name_event,
                                        write_chrome_trace)


def load_snapshot(path: str) -> Dict[str, Dict[str, Any]]:
    """Normalize any supported snapshot form to ``{name: kv_scope}``.

    Raises ValueError when no kv_scope block can be found, naming the
    keys that were present (the usual failure is passing a tracebus
    dump here by mistake).
    """
    with open(path) as f:
        obj = json.load(f)
    if not isinstance(obj, dict):
        raise ValueError(f"snapshot root must be a JSON object, got "
                         f"{type(obj).__name__}")
    if "occupancy" in obj and "forensics" in obj:   # bare block
        return {"engine": obj}
    if isinstance(obj.get("kv_scope"), dict):       # engine_stats dump
        return {str(obj.get("deployment", "engine")): obj["kv_scope"]}
    out: Dict[str, Dict[str, Any]] = {}             # dashboard map
    for name, blk in obj.items():
        if not isinstance(blk, dict):
            continue
        if isinstance(blk.get("kv_scope"), dict):
            out[str(name)] = blk["kv_scope"]
        elif "occupancy" in blk and "forensics" in blk:
            out[str(name)] = blk
    if not out:
        raise ValueError(
            f"no kv_scope block in snapshot (top-level keys: "
            f"{sorted(obj)[:8]})")
    return out


def _fmt_bytes(n: Optional[int]) -> str:
    if n is None:
        return "-"
    sign, n = ("-", -n) if n < 0 else ("", n)
    for unit in ("B", "KiB", "MiB", "GiB"):
        if n < 1024 or unit == "GiB":
            return (f"{sign}{n:.1f} {unit}" if unit != "B"
                    else f"{sign}{n} B")
        n /= 1024.0
    return f"{sign}{n:.1f} GiB"


# ---------------------------------------------------------------------------
# report
# ---------------------------------------------------------------------------

def report_lines(scopes: Dict[str, Dict[str, Any]]) -> List[str]:
    lines: List[str] = []
    for name, blk in sorted(scopes.items()):
        occ = blk.get("occupancy") or {}
        fx = blk.get("forensics") or {}
        lines.append(
            f"{name}: kvscope "
            f"{'enabled' if blk.get('enabled') else 'DISABLED'}")
        lines.append(
            f"  occupancy: {occ.get('occupancy_ratio', 0.0):.1%} now, "
            f"p95 {occ.get('occupancy_p95', 0.0):.1%} over "
            f"{occ.get('samples', 0)} waves, fragmentation "
            f"{occ.get('fragmentation', 0.0):.1%}")
        waste = fx.get("reprefill_waste_tokens", 0)
        lines.append(
            f"  re-prefill waste: {waste} tokens "
            f"({fx.get('reprefill_waste_frac', 0.0):.1%} of "
            f"{fx.get('prefill_tokens', 0)} prefilled) across "
            f"{fx.get('reprefill_events', 0)} events; "
            f"{fx.get('keys_evicted', 0)} keys evicted "
            f"({fx.get('keys_tracked', 0)} tracked, "
            f"{fx.get('keys_forgotten', 0)} forgotten)")
        by_tenant = fx.get("waste_by_tenant") or {}
        for tenant, tok in sorted(by_tenant.items(),
                                  key=lambda kv: -kv[1]):
            share = tok / waste if waste else 0.0
            lines.append(f"    tenant {tenant:<12} {tok:>8} tokens "
                         f"{share:>6.1%}")
        for row in fx.get("top_keys") or []:
            lines.append(
                f"    key {row.get('key_prefix')}… "
                f"(len {row.get('key_len')}): "
                f"{row.get('tokens')} tokens re-filled")
        blocks = blk.get("blocks_by_tenant") or {}
        if blocks:
            lines.append("  live blocks by tenant: " + ", ".join(
                f"{t}={n}" for t, n in sorted(blocks.items())))
        ledger = blk.get("hbm_ledger") or {}
        rows = ledger.get("per_chip") or []
        if rows:
            lines.append(
                f"  hbm ledger (min headroom "
                f"{_fmt_bytes(ledger.get('min_headroom_bytes'))}):")
            for r in rows:
                lines.append(
                    f"    chip {r.get('id')} [{r.get('platform')}]: "
                    f"limit {_fmt_bytes(r.get('bytes_limit'))}, "
                    f"in use {_fmt_bytes(r.get('bytes_in_use'))}, "
                    f"kv pool {_fmt_bytes(r.get('kv_pool_bytes'))}, "
                    f"program budget "
                    f"{_fmt_bytes(r.get('program_budget_bytes'))}, "
                    f"headroom {_fmt_bytes(r.get('headroom_bytes'))}")
        else:
            lines.append("  hbm ledger: no device rows")
    return lines


# ---------------------------------------------------------------------------
# timeline
# ---------------------------------------------------------------------------

def timeline_lines(scopes: Dict[str, Dict[str, Any]],
                   engine: Optional[str] = None,
                   width: int = 40) -> List[str]:
    """One row per ring sample: wave offset, block counts, and a bar
    of pool occupancy (``#`` in-use, ``+`` parked-LRU, ``.`` free)."""
    lines: List[str] = []
    for name, blk in sorted(scopes.items()):
        if engine is not None and name != engine:
            continue
        ring = (blk.get("occupancy") or {}).get("ring") or []
        lines.append(f"{name}: {len(ring)} occupancy samples")
        if not ring:
            continue
        t0 = ring[0].get("t_s", 0.0)
        total = max(1, sum(int(ring[0].get(k, 0))
                           for k in ("free", "cached", "in_use")))
        for s in ring:
            used = int(s.get("in_use", 0))
            cached = int(s.get("cached", 0))
            n_used = round(width * used / total)
            n_cache = round(width * cached / total)
            bar = ("#" * n_used + "+" * n_cache).ljust(width, ".")
            lines.append(
                f"  +{s.get('t_s', 0.0) - t0:>8.3f}s "
                f"use={used:<4} lru={cached:<4} "
                f"free={s.get('free', 0):<4} "
                f"frag={s.get('frag', 0.0):.2f} |{bar}|")
    return lines


# ---------------------------------------------------------------------------
# chrome-trace export (counter lanes)
# ---------------------------------------------------------------------------

def _counter_event(name: str, ts_s: float, pid: int,
                   args: Dict[str, Any]) -> Dict[str, Any]:
    """A chrome-trace "C" (counter) event — renders as a stacked area
    lane, the right shape for pool occupancy over time."""
    return {"name": name, "cat": "kvscope", "ph": "C",
            "ts": ts_s * 1e6, "pid": pid, "tid": 0, "args": args}


def chrome_trace(scopes: Dict[str, Dict[str, Any]],
                 path: Optional[str] = None) -> List[Dict[str, Any]]:
    """Counter lanes per engine: ``kv blocks`` (in_use / cached / free
    stacked), ``kv occupancy`` and ``kv fragmentation`` ratios.  Times
    are rebased per engine (rings are perf_counter-clocked, which is
    not comparable across processes)."""
    events: List[Dict[str, Any]] = []
    for pid, (name, blk) in enumerate(sorted(scopes.items()), 1):
        occ = blk.get("occupancy") or {}
        ring = occ.get("ring") or []
        events.append(process_name_event(pid, f"kvscope {name}"))
        if not ring:
            continue
        t0 = ring[0].get("t_s", 0.0)
        num_blocks = sum(int(ring[0].get(k, 0))
                         for k in ("free", "cached", "in_use"))
        for s in ring:
            ts = s.get("t_s", 0.0) - t0
            free = int(s.get("free", 0))
            cached = int(s.get("cached", 0))
            events.append(_counter_event(
                "kv blocks", ts, pid,
                {"in_use": int(s.get("in_use", 0)), "cached": cached,
                 "free": free}))
            usable = max(1, num_blocks - 1)
            events.append(_counter_event(
                "kv occupancy", ts, pid,
                {"ratio": round(1.0 - free / usable, 4)}))
            events.append(_counter_event(
                "kv fragmentation", ts, pid,
                {"frag": float(s.get("frag", 0.0))}))
    return write_chrome_trace(events, path)


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------

def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m ray_tpu.tools.kvscope",
        description="inspect kvscope snapshots (KV pool occupancy, "
                    "eviction forensics, HBM ledger)")
    sub = ap.add_subparsers(dest="cmd", required=True)

    p = sub.add_parser("report", help="occupancy / waste / ledger "
                                      "summary")
    p.add_argument("snapshot")

    p = sub.add_parser("timeline", help="occupancy ring as a text "
                                        "strip chart")
    p.add_argument("snapshot")
    p.add_argument("--engine", default=None,
                   help="only this deployment's ring")

    p = sub.add_parser("export", help="chrome-trace counter lanes")
    p.add_argument("snapshot")
    p.add_argument("-o", "--out", default=None,
                   help="write trace JSON here (default: stdout)")

    args = ap.parse_args(argv)
    try:
        scopes = load_snapshot(args.snapshot)
    except (OSError, ValueError, json.JSONDecodeError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2

    if args.cmd == "report":
        for line in report_lines(scopes):
            print(line)
        return 0
    if args.cmd == "timeline":
        for line in timeline_lines(scopes, args.engine):
            print(line)
        return 0
    # export
    events = chrome_trace(scopes, args.out)
    if args.out:
        print(f"wrote {len(events)} events to {args.out}")
    else:
        print(json.dumps(events))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
