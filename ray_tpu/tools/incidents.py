"""Cross-replica incident timelines over tracebus/flightrec dumps.

Healthwatch (serve/health.py) journals its liveness transitions into
per-replica flight recorders; SLO burn, autoscale, drain, and chaos
events land in the same journals.  During an incident the operator's
question is singular — "which replica got sick, when was it caught,
and who was hurt" — but the evidence is scattered over N replica
journals plus the fleet router's.  This CLI merges them onto ONE
rebased clock (the tracebus merge pattern: every lane stamps the same
process ``perf_counter``) and answers in three shapes:

* ``report``   — the incident digest: each sick replica with its
  fault-injection instant (when chaos stamped one), SUSPECT/DEAD
  transition times, detection latency, stall/requeue counts and the
  affected request ids, plus the fleet's SLO burn window (first
  ``slo_breach`` → pairing ``slo_recover``) and any autoscale/drain
  decisions inside it.
* ``timeline`` — every incident-relevant event from every lane,
  chronological, one line each — the raw merged story.
* ``export``   — a chrome-trace instant-event lane (pid 95, above
  flightrec's pid-90 convention) composable with ``tracebus export``
  timelines via ``--merge``, so incidents render on the same Perfetto
  canvas as the request spans.

Input is either a tracebus dump (``tracebus.write_dump(collect(...))``
— per-lane journals under ``flightrec`` with absolute timestamps) or a
single flight-recorder dump (``events`` with dump-relative ``t_s``).
Pure stdlib + the chrome-trace builders; never imports jax, so it
works on a laptop holding only the dump file.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Any, Dict, List, Optional

from ray_tpu._private.telemetry import (instant_event,
                                        process_name_event,
                                        thread_name_event)

__all__ = ["load", "merge_events", "extract_incidents",
           "burn_windows", "report_lines", "timeline_lines",
           "trace_events", "main"]

#: journal kinds that tell the incident story (everything else —
#: route, token, kv_* — is request-path detail the tracebus CLI owns)
INCIDENT_KINDS = frozenset({
    "fault_injected", "health_transition", "request_stall",
    "requeue", "slo_breach", "slo_recover", "scale_up", "scale_down",
    "drain", "handoff_dropped", "shed", "error",
})


def load(path: str) -> Dict[str, Any]:
    """Accept a tracebus dump or a bare flight-recorder dump."""
    with open(path) as f:
        doc = json.load(f)
    if "flightrec" not in doc and "events" not in doc:
        raise ValueError(
            f"{path} is neither a tracebus dump (no 'flightrec' "
            "lanes) nor a flight-recorder dump (no 'events')")
    return doc


def merge_events(doc: Dict[str, Any],
                 kinds: Optional[frozenset] = INCIDENT_KINDS
                 ) -> List[Dict[str, Any]]:
    """All lanes' journal events on one rebased clock: each returned
    event carries ``lane`` (recorder name) and ``t`` (seconds from the
    earliest merged event).  ``kinds=None`` keeps everything."""
    raw: List[Dict[str, Any]] = []
    lanes = doc.get("flightrec")
    if isinstance(lanes, dict):  # tracebus dump: absolute timestamps
        for lane_name, lane in lanes.items():
            for e in lane.get("events", ()):
                if kinds is not None and e.get("kind") not in kinds:
                    continue
                ev = dict(e)
                ev["lane"] = lane_name
                ev["_ts"] = float(e.get("ts", e.get("t_s", 0.0)))
                raw.append(ev)
    else:  # single flight-recorder dump: dump-relative t_s
        lane_name = str(doc.get("source", "engine"))
        for e in doc.get("events", ()):
            if kinds is not None and e.get("kind") not in kinds:
                continue
            ev = dict(e)
            ev["lane"] = lane_name
            ev["_ts"] = float(e.get("t_s", 0.0))
            raw.append(ev)
    base = min((e["_ts"] for e in raw), default=0.0)
    for e in raw:
        e["t"] = round(e.pop("_ts") - base, 6)
    raw.sort(key=lambda e: (e["t"], str(e.get("kind"))))
    return raw


def _dedup(events: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
    """Health transitions journal to BOTH the fleet recorder and the
    replica's own (two lanes, same instant) — collapse those twins so
    counters don't double."""
    seen = set()
    out = []
    for e in events:
        key = (e.get("kind"), e.get("replica"), e.get("to"),
               e.get("reason"), e.get("req"), round(e["t"], 6))
        if key in seen:
            continue
        seen.add(key)
        out.append(e)
    return out


def extract_incidents(events: List[Dict[str, Any]]
                      ) -> List[Dict[str, Any]]:
    """Per-replica incident digests from the merged stream: one entry
    per replica that got sick (any transition away from healthy, a
    stamped fault, a stall, or death-requeues), in first-symptom
    order."""
    events = _dedup(events)
    incidents: Dict[str, Dict[str, Any]] = {}

    def inc_for(rep: str) -> Dict[str, Any]:
        return incidents.setdefault(rep, {
            "replica": rep, "fault_t": None, "fault_kind": None,
            "suspect_t": None, "dead_t": None, "recover_t": None,
            "time_to_detect_ms": None, "transitions": 0,
            "stalls": 0, "requeued": 0, "affected": []})

    def touch(inc: Dict[str, Any], req: Any) -> None:
        if req is not None and req not in inc["affected"]:
            inc["affected"].append(req)

    for e in events:
        kind = e.get("kind")
        rep = e.get("replica")
        if kind == "fault_injected" and rep:
            inc = inc_for(rep)
            if inc["fault_t"] is None:
                inc["fault_t"] = e["t"]
                inc["fault_kind"] = e.get("fault")
        elif kind == "health_transition" and rep:
            inc = inc_for(rep)
            inc["transitions"] += 1
            to = e.get("to")
            if to == "suspect" and inc["suspect_t"] is None:
                inc["suspect_t"] = e["t"]
            elif to == "dead" and inc["dead_t"] is None:
                inc["dead_t"] = e["t"]
                inc["time_to_detect_ms"] = e.get("time_to_detect_ms")
            elif to == "healthy":
                inc["recover_t"] = e["t"]
        elif kind == "request_stall" and rep:
            inc = inc_for(rep)
            inc["stalls"] += 1
            touch(inc, e.get("req"))
        elif kind == "requeue" \
                and e.get("reason") == "replica_dead":
            # journaled on the dead replica's own recorder — the lane
            # IS the sick replica
            inc = inc_for(str(e.get("lane")))
            inc["requeued"] += 1
            touch(inc, e.get("req"))
    order = []
    for inc in incidents.values():
        marks = [t for t in (inc["fault_t"], inc["suspect_t"],
                             inc["dead_t"]) if t is not None]
        order.append((min(marks) if marks else float("inf"), inc))
    return [inc for _t, inc in sorted(order, key=lambda p: p[0])]


def burn_windows(events: List[Dict[str, Any]]
                 ) -> List[Dict[str, Any]]:
    """SLO burn windows per (lane, objective): opened by a
    ``slo_breach``, closed by the next ``slo_recover`` on the same
    lane+objective (``end=None`` = still burning at dump time)."""
    open_by_key: Dict[tuple, Dict[str, Any]] = {}
    out: List[Dict[str, Any]] = []
    for e in _dedup(events):
        kind = e.get("kind")
        if kind not in ("slo_breach", "slo_recover"):
            continue
        key = (e.get("lane"), e.get("objective"))
        if kind == "slo_breach":
            if key not in open_by_key:
                win = {"lane": key[0], "objective": key[1],
                       "start": e["t"], "end": None,
                       "burn_rate": e.get("burn_rate"),
                       "target_ms": e.get("target_ms")}
                open_by_key[key] = win
                out.append(win)
        else:
            win = open_by_key.pop(key, None)
            if win is not None:
                win["end"] = e["t"]
    return out


def report_lines(doc: Dict[str, Any]) -> List[str]:
    events = merge_events(doc)
    lines = [
        f"incident report: {doc.get('source', '?')}  "
        f"({len(events)} incident events, clock rebased to the "
        "earliest)",
    ]
    incidents = extract_incidents(events)
    if not incidents:
        lines.append("no incidents: every replica stayed healthy")
    for inc in incidents:
        lines.append(f"replica {inc['replica']}:")
        if inc["fault_t"] is not None:
            lines.append(f"  fault injected: {inc['fault_kind']} "
                         f"@ {inc['fault_t']:.3f}s")
        if inc["suspect_t"] is not None:
            lines.append(f"  SUSPECT @ {inc['suspect_t']:.3f}s")
        if inc["dead_t"] is not None:
            detect = ("  time_to_detect_ms="
                      f"{inc['time_to_detect_ms']}"
                      if inc["time_to_detect_ms"] is not None else "")
            lines.append(f"  DEAD    @ {inc['dead_t']:.3f}s{detect}")
        if inc["recover_t"] is not None:
            lines.append(f"  recovered @ {inc['recover_t']:.3f}s")
        lines.append(
            f"  transitions={inc['transitions']}  "
            f"stalls={inc['stalls']}  "
            f"requeued_on_death={inc['requeued']}")
        if inc["affected"]:
            ids = ", ".join(str(r) for r in inc["affected"][:12])
            more = len(inc["affected"]) - 12
            lines.append(f"  affected requests: {ids}"
                         + (f" (+{more} more)" if more > 0 else ""))
    wins = burn_windows(events)
    if wins:
        for w in wins:
            end = (f"{w['end']:.3f}s" if w["end"] is not None
                   else "(unrecovered)")
            span = (f"  ({round((w['end'] - w['start']) * 1e3, 1)}ms)"
                    if w["end"] is not None else "")
            lines.append(
                f"slo burn window [{w['lane']}/{w['objective']}]: "
                f"{w['start']:.3f}s -> {end}{span}  "
                f"burn_rate={w['burn_rate']}")
    else:
        lines.append("(no slo breach observed)")
    scale = [e for e in _dedup(events)
             if e.get("kind") in ("scale_up", "scale_down", "drain",
                                  "handoff_dropped")]
    if scale:
        lines.append("control-plane decisions in window:")
        for e in scale[-6:]:
            detail = {k: v for k, v in e.items()
                      if k not in ("t", "lane", "t_s", "ts", "seq")}
            lines.append(f"  {e['t']:.3f}s  "
                         + json.dumps(detail, sort_keys=True))
    return lines


def timeline_lines(doc: Dict[str, Any]) -> List[str]:
    lines = []
    for e in merge_events(doc):
        detail = {k: v for k, v in e.items()
                  if k not in ("t", "lane", "kind", "t_s", "ts",
                               "seq")}
        lines.append(f"{e['t']:9.3f}s  {e['lane']:<20}  "
                     f"{str(e.get('kind')):<18}  "
                     + json.dumps(detail, sort_keys=True))
    return lines


def trace_events(doc: Dict[str, Any],
                 merge: Optional[List[Dict[str, Any]]] = None,
                 pid: int = 95, tid: int = 0
                 ) -> List[Dict[str, Any]]:
    """The incident stream as a chrome-trace instant-event lane —
    pid 95 by convention (flightrec's decision lane sits at 90), so
    ``--merge`` with a ``tracebus export`` timeline stacks cleanly."""
    events: List[Dict[str, Any]] = list(merge or [])
    events.append(process_name_event(
        pid, f"incidents {doc.get('source', '?')}"))
    events.append(thread_name_event(pid, tid, "health + slo + chaos"))
    for e in merge_events(doc):
        args = {k: v for k, v in e.items()
                if k not in ("kind", "t", "t_s", "ts", "seq")}
        events.append(instant_event(
            str(e.get("kind", "event")), "incidents",
            float(e["t"]), pid, tid, args))
    return events


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m ray_tpu.tools.incidents",
        description="merged cross-replica incident timelines from "
                    "tracebus / flight-recorder dumps")
    sub = ap.add_subparsers(dest="cmd", required=True)

    p = sub.add_parser("report",
                       help="incident digest: sick replicas, "
                            "detection latency, burn windows")
    p.add_argument("dump")

    p = sub.add_parser("timeline",
                       help="every incident event, merged and "
                            "chronological")
    p.add_argument("dump")

    p = sub.add_parser("export",
                       help="chrome-trace incident lane (pid 95)")
    p.add_argument("dump")
    p.add_argument("-o", "--out", default=None,
                   help="write trace JSON here (default: stdout)")
    p.add_argument("--merge", default=None,
                   help="existing timeline JSON to merge the lane "
                        "into (tracebus export / flightrec trace)")

    args = ap.parse_args(argv)
    try:
        doc = load(args.dump)
    except (OSError, ValueError, json.JSONDecodeError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2

    if args.cmd == "report":
        for line in report_lines(doc):
            print(line)
        return 0
    if args.cmd == "timeline":
        for line in timeline_lines(doc):
            print(line)
        return 0
    # export
    merge = None
    if args.merge:
        with open(args.merge) as f:
            merge = json.load(f)
    events = trace_events(doc, merge=merge)
    payload = json.dumps(events)
    if args.out:
        with open(args.out, "w") as f:
            f.write(payload)
        print(f"wrote {len(events)} events to {args.out}")
    else:
        print(payload)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
