"""Actor API: @remote classes, ActorHandle, ActorMethod.

(Reference analog: python/ray/actor.py — :377 ActorClass, :657
ActorClass._remote, :1020 ActorHandle, :92 ActorMethod.)
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Optional

import cloudpickle

from ray_tpu._private import worker_context
from ray_tpu._private.worker_context import ObjectRef
from ray_tpu.remote_function import _build_resources, _pg_option


class ActorMethod:
    def __init__(self, handle: "ActorHandle", name: str):
        self._handle = handle
        self._name = name

    def remote(self, *args, **kwargs):
        return self._handle._invoke(self._name, args, kwargs,
                                    self._handle._method_opts.get(self._name, {}))

    def options(self, **opts):
        return _BoundMethod(self._handle, self._name, opts)

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"actor method {self._name}() cannot be called directly; "
            f"use .{self._name}.remote().")


class _BoundMethod:
    def __init__(self, handle, name, opts):
        self._handle = handle
        self._name = name
        self._opts = opts

    def remote(self, *args, **kwargs):
        return self._handle._invoke(self._name, args, kwargs, self._opts)


class ActorHandle:
    """Handle to a (possibly remote) actor; picklable — passing a handle to
    a task or actor lets it call methods too (reference: actor handles are
    first-class serializable)."""

    def __init__(self, actor_id: bytes, method_opts: Optional[Dict] = None):
        self._actor_id = actor_id
        self._method_opts = method_opts or {}

    @property
    def actor_id(self) -> bytes:
        return self._actor_id

    def _invoke(self, method: str, args, kwargs, opts):
        cw = worker_context.core_worker()
        num_returns = opts.get("num_returns", 1)
        if num_returns == "dynamic":
            raise ValueError(
                "num_returns='dynamic' is not supported for actor "
                "methods (only stateless tasks); return a list of "
                "ray_tpu.put refs instead")
        refs = cw.submit_actor_task(self._actor_id, method, args, kwargs,
                                    num_returns=num_returns)
        wrapped = [ObjectRef(r) for r in refs]
        if num_returns == 0:
            return None
        return wrapped[0] if num_returns == 1 else wrapped

    def __getattr__(self, name):
        if name.startswith("_"):
            raise AttributeError(name)
        return ActorMethod(self, name)

    def __reduce__(self):
        return (ActorHandle, (self._actor_id, self._method_opts))

    def __repr__(self):
        from ray_tpu._private.ids import ActorID

        return f"ActorHandle({ActorID(self._actor_id).hex()[:16]})"

    def __eq__(self, other):
        return isinstance(other, ActorHandle) and other._actor_id == self._actor_id

    def __hash__(self):
        return hash(self._actor_id)


class ActorClass:
    """Created by ``@ray_tpu.remote`` on a class."""

    def __init__(self, cls, options: Optional[Dict[str, Any]] = None):
        self._cls = cls
        self._options = dict(options or {})
        self._pickled: Optional[bytes] = None
        self._export_lock = threading.Lock()
        self.__name__ = getattr(cls, "__name__", "ActorClass")

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"actor class {self.__name__} cannot be instantiated directly; "
            f"use {self.__name__}.remote().")

    def options(self, **opts) -> "ActorClass":
        merged = dict(self._options)
        merged.update(opts)
        ac = ActorClass(self._cls, merged)
        ac._pickled = self._pickled
        return ac

    def bind(self, *args, **kwargs):
        """Record a lazy actor-construction DAG node (reference:
        ray.dag ClassNode)."""
        from ray_tpu.dag import ClassNode

        return ClassNode(self, args, kwargs)

    def __reduce__(self):
        return (ActorClass, (self._cls, self._options))

    def remote(self, *args, **kwargs) -> ActorHandle:
        from ray_tpu import _auto_init

        _auto_init()
        cw = worker_context.core_worker()
        with self._export_lock:
            if self._pickled is None:
                self._pickled = cloudpickle.dumps(self._cls)
        fid = cw.export_function(self._pickled)
        opts = self._options
        resources = _build_resources(opts)
        actor_id = cw.create_actor(
            fid, args, kwargs,
            resources=resources,
            name=opts.get("name") or "",
            max_restarts=opts.get("max_restarts", 0),
            lifetime=opts.get("lifetime") or "",
            # 0 = unset: the worker raises it for async actors (classes
            # with coroutine methods default to high concurrency so their
            # coroutines interleave — reference async-actor semantics)
            max_concurrency=opts.get("max_concurrency", 0),
            pg=_pg_option(opts),
        )
        # Creation is ASYNC (reference semantics): the handle returns
        # immediately; worker spawn + ctor run in the background and the
        # first method call parks until the actor is ALIVE (or raises
        # ActorDiedError if the ctor failed).  Infeasible shapes still
        # fail fast — the GCS checks feasibility inside actor_register.
        return ActorHandle(actor_id)
