"""Vectorized envs + connector pipelines (reference:
rllib/env/vector_env.py:24, rllib/connectors/connector.py:84)."""

import numpy as np
import pytest

from ray_tpu.rllib.connectors import (CastFlatten, ConnectorPipeline,
                                      ObsFilter, default_obs_pipeline)
from ray_tpu.rllib.policy import PolicySpec
from ray_tpu.rllib.rollout_worker import RolloutWorker
from ray_tpu.rllib.vector_env import (CartPoleVecEnv, SyncVectorEnv,
                                      make_vector_env)

pytestmark = pytest.mark.fast


def test_cartpole_vec_matches_gymnasium_physics():
    """The batched implementation must track gymnasium's CartPole-v1
    transition function exactly (same action sequence → same states)."""
    import gymnasium as gym

    ref = gym.make("CartPole-v1")
    ref_obs, _ = ref.reset(seed=0)
    vec = CartPoleVecEnv(3, seed=0)
    vec.vector_reset()
    # align: overwrite vec state row 0 with the gym initial state
    vec._state[0] = np.asarray(ref_obs, np.float64)
    rng = np.random.RandomState(1)
    for _ in range(60):
        a = int(rng.randint(2))
        ref_obs, ref_r, ref_term, ref_trunc, _ = ref.step(a)
        obs, rews, terms, truncs, infos = vec.vector_step(
            np.array([a, 0, 1]))
        np.testing.assert_allclose(infos["final_obs"][0], ref_obs,
                                   rtol=1e-5, atol=1e-6)
        assert rews[0] == ref_r
        assert bool(terms[0]) == bool(ref_term)
        if ref_term or ref_trunc:
            break
        # rows stay aligned only until reset; keep syncing
        vec._state[0] = np.asarray(ref_obs, np.float64)


def test_cartpole_vec_auto_reset_and_truncation():
    vec = CartPoleVecEnv(2, seed=0)
    vec.vector_reset()
    # drive env 0 off the rail with constant action; env 1 too (same
    # policy) — both must auto-reset and keep stepping
    terms_seen = 0
    for _ in range(300):
        obs, rews, terms, truncs, infos = vec.vector_step(
            np.array([1, 1]))
        terms_seen += int(terms.sum())
        assert obs.shape == (2, 4)
        # post-reset rows are within the fresh-state range
        for i in range(2):
            if terms[i] or truncs[i]:
                assert np.all(np.abs(obs[i]) <= 0.05 + 1e-9)
                assert np.any(np.abs(infos["final_obs"][i]) > 0.05)
    assert terms_seen >= 2
    # truncation at 500 steps: balance is impossible with constant
    # action, so exercise the step-counter reset instead
    assert vec._steps.max() < 500


def test_sync_vector_env_semantics():
    import gymnasium as gym

    vec = SyncVectorEnv(lambda: gym.make("CartPole-v1"), 3)
    obs = vec.vector_reset(seed=0)
    assert obs.shape == (3, 4)
    for _ in range(250):
        obs, rews, terms, truncs, infos = vec.vector_step([1, 1, 0])
        assert obs.shape == (3, 4) and infos["final_obs"].shape == (3, 4)
        if terms.any():
            break
    assert terms.any(), "constant-action cartpole must terminate"


def test_make_vector_env_dispatch():
    vec = make_vector_env("CartPole-v1", None, 4, seed=0)
    assert isinstance(vec, CartPoleVecEnv) and vec.num_envs == 4

    import gymnasium as gym

    vec2 = make_vector_env(
        lambda cfg: gym.make("CartPole-v1"), None, 2)
    assert isinstance(vec2, SyncVectorEnv) and vec2.num_envs == 2

    class MyVec(CartPoleVecEnv):
        pass

    vec3 = make_vector_env(lambda cfg: MyVec(6), None, 99)
    assert isinstance(vec3, MyVec) and vec3.num_envs == 6


def test_connector_pipeline_state_roundtrip():
    pipe = default_obs_pipeline((4,), "MeanStdFilter")
    rng = np.random.RandomState(0)
    for _ in range(10):
        pipe(rng.randn(8, 4) * 3 + 1)
    state = pipe.get_state()
    pipe2 = default_obs_pipeline((4,), "MeanStdFilter")
    pipe2.set_state(state)
    x = rng.randn(5, 4).astype(np.float32)
    np.testing.assert_allclose(pipe(x, update=False),
                               pipe2(x, update=False), rtol=1e-6)
    # normalized output is ~zero-mean/unit-var on the training stream
    y = pipe(rng.randn(2000, 4) * 3 + 1, update=False)
    assert abs(float(y.mean())) < 0.2 and 0.7 < float(y.std()) < 1.3


def test_cast_flatten_connector():
    c = CastFlatten()
    out = c(np.zeros((5, 2, 3), np.float64))
    assert out.shape == (5, 6) and out.dtype == np.float32


def test_worker_fragment_shapes_and_episodes():
    spec = PolicySpec(obs_dim=4, n_actions=2, hidden=(8,))
    w = RolloutWorker(env="CartPole-v1", policy_spec=spec, num_envs=4,
                      rollout_fragment_length=100, seed=0)
    batch = w.sample()
    assert batch.count == 400
    assert batch["obs"].shape == (400, 4)
    assert np.isfinite(batch["advantages"]).all()
    assert len(w.pop_episode_returns()) >= 1  # random policy episodes end


def test_worker_filter_sync_through_connectors():
    spec = PolicySpec(obs_dim=4, n_actions=2, hidden=(8,))
    w = RolloutWorker(env="CartPole-v1", policy_spec=spec, num_envs=2,
                      rollout_fragment_length=50, seed=0,
                      observation_filter="MeanStdFilter")
    w.sample()
    delta = w.pop_filter_delta()
    assert delta is not None
    state = w.get_filter_state()
    w.set_filter_state(state)  # roundtrip doesn't throw
    # a second pop returns an EMPTY delta (cleared on pop)
    d2 = w.pop_filter_delta()
    assert d2 is not None


def test_multidim_obs_filter_through_pipeline():
    """Regression: MeanStdFilter must operate on the FLATTENED rows the
    pipeline feeds it (a (H, W)-shaped filter after CastFlatten raised
    a broadcast error)."""
    pipe = default_obs_pipeline((3, 5), "MeanStdFilter")
    rng = np.random.RandomState(0)
    out = pipe(rng.randn(8, 3, 5))
    assert out.shape == (8, 15)
    out2 = pipe(rng.randn(8, 3, 5), update=False)
    assert np.isfinite(out2).all()


def test_sync_vector_env_reuses_probe():
    built = []

    class CountingEnv:
        def __init__(self):
            built.append(1)
            import gymnasium as gym

            self._e = gym.make("CartPole-v1")
            self.observation_space = self._e.observation_space
            self.action_space = self._e.action_space

        def reset(self, seed=None):
            return self._e.reset(seed=seed)

        def step(self, a):
            return self._e.step(a)

    vec = make_vector_env(lambda cfg: CountingEnv(), None, 3)
    assert vec.num_envs == 3
    assert len(built) == 3  # probe reused, not 4 constructions


def test_evaluation_worker_greedy_episodes(ray_start_shared):
    """Algorithm.evaluate: dedicated worker, deterministic actions,
    training rollout state untouched (reference: evaluation WorkerSet
    with explore=False)."""
    from ray_tpu.rllib import PPO, PPOConfig

    cfg = PPOConfig(env="CartPole-v1", num_workers=1,
                    num_envs_per_worker=4, rollout_fragment_length=64,
                    train_batch_size=256, seed=0,
                    evaluation_interval=2, evaluation_num_episodes=4)
    algo = PPO(cfg)
    try:
        r1 = algo.train()
        assert "evaluation" not in r1  # interval=2
        r2 = algo.train()
        ev = r2["evaluation"]
        assert ev["episodes_this_eval"] == 4
        assert np.isfinite(ev["episode_reward_mean"])
        assert ev["episode_reward_min"] <= ev["episode_reward_max"]
        # deterministic policy: direct evaluate() twice is repeatable
        e1 = algo.evaluate()
        e2 = algo.evaluate()
        assert e1["episode_reward_mean"] == e2["episode_reward_mean"]
    finally:
        algo.stop()
