"""Synthetic traffic generator (serve/traffic.py): determinism of the
seeded workload, and the continuous paged engine driven under it —
the same entry points bench.py --traffic and sweep_tpu.py's
{"mode": "traffic"} variants use, so the tier-1 run here is the
canary for the whole traffic tooling path."""

import numpy as np
import pytest

from ray_tpu.serve.traffic import TrafficGenerator, TrafficSpec

def _overrides():
    import jax.numpy as jnp

    return {"dtype": jnp.float32, "use_flash": False, "remat": False}


def test_spec_validation():
    with pytest.raises(ValueError, match="num_requests"):
        TrafficSpec(num_requests=0)
    with pytest.raises(ValueError, match="rate_rps"):
        TrafficSpec(rate_rps=0.0)
    with pytest.raises(ValueError, match="p_shared"):
        TrafficSpec(p_shared=1.5)


def test_generator_is_seed_deterministic():
    spec = TrafficSpec(num_requests=20, seed=42, num_prefix_groups=3,
                       prefix_len=16, vocab=300)
    r1 = TrafficGenerator(spec).requests()
    r2 = TrafficGenerator(spec).requests()
    assert len(r1) == len(r2) == 20
    for a, b in zip(r1, r2):
        assert a.arrival_s == b.arrival_s and a.group == b.group
        np.testing.assert_array_equal(a.prompt, b.prompt)
    # a different seed really changes the workload
    r3 = TrafficGenerator(
        TrafficSpec(num_requests=20, seed=43, num_prefix_groups=3,
                    prefix_len=16, vocab=300)).requests()
    assert any(not np.array_equal(a.prompt, b.prompt)
               for a, b in zip(r1, r3))


def test_generator_workload_shape():
    spec = TrafficSpec(num_requests=40, seed=5, num_prefix_groups=2,
                       prefix_len=32, p_shared=0.8, tail_len_mean=6.0,
                       tail_len_max=12, vocab=100)
    gen = TrafficGenerator(spec)
    reqs = gen.requests()
    # arrivals are sorted Poisson offsets
    arr = [r.arrival_s for r in reqs]
    assert arr == sorted(arr) and arr[0] > 0
    shared = [r for r in reqs if r.group >= 0]
    unique = [r for r in reqs if r.group < 0]
    assert shared and unique            # the mixture has both kinds
    for r in shared:
        np.testing.assert_array_equal(r.prompt[:32],
                                      gen.prefixes[r.group])
        assert 33 <= len(r.prompt) <= 32 + 12
    for r in unique:
        assert 1 <= len(r.prompt) <= 12
    # tokens avoid the reserved 0/1 ids
    for r in reqs:
        assert r.prompt.min() >= 2 and r.prompt.max() < 100
        assert r.prompt.dtype == np.int32


def test_traffic_32_requests_through_paged_engine():
    """Tier-1 canary: a seeded 32-request shared-prefix burst through
    the paged continuous engine — everything completes, prefix reuse
    is visible in engine stats, and the report is self-consistent."""
    jax = pytest.importorskip("jax")  # noqa: F841
    from ray_tpu.serve.traffic import run_traffic

    spec = TrafficSpec(num_requests=32, seed=0, rate_rps=100.0,
                       num_prefix_groups=2, prefix_len=32,
                       p_shared=0.75, tail_len_mean=5.0,
                       tail_len_max=12, vocab=500)
    rep = run_traffic(spec, family="gpt2", preset="nano",
                      kv_layout="paged", max_slots=4,
                      max_new_tokens=4, prefill_bucket=16,
                      time_scale=0.0, latency_slo_ms=600000.0,
                      config_overrides=_overrides())
    assert rep["offered"] == 32
    assert rep["completed"] == 32 and rep["shed"] == 0
    assert rep["latency_ms"]["count"] == 32
    assert rep["slo_attainment"] == 1.0   # SLO is generous on purpose
    assert rep["prefix_hit_rate"] > 0
    eng = rep["engine"]
    assert eng["requests"]["finished"] == 32
    assert eng["kv_cache"]["blocks_in_use"] == 0
    assert eng["kv_cache"]["prefix_block_hits"] > 0


@pytest.mark.slow
def test_traffic_poisson_soak_with_shedding():
    """Soak: sustained Poisson load with a tight queue bound — the
    engine must stay healthy across many admit/retire/evict cycles,
    shed cleanly instead of erroring, and account for every request."""
    jax = pytest.importorskip("jax")  # noqa: F841
    from ray_tpu.serve.batching import AdmissionPolicy
    from ray_tpu.serve.traffic import run_traffic

    spec = TrafficSpec(num_requests=160, seed=1, rate_rps=400.0,
                       num_prefix_groups=3, prefix_len=32,
                       p_shared=0.7, tail_len_mean=6.0,
                       tail_len_max=16, vocab=500)
    rep = run_traffic(spec, family="gpt2", preset="nano",
                      kv_layout="paged", max_slots=4,
                      max_new_tokens=4, prefill_bucket=16,
                      time_scale=0.02, latency_slo_ms=600000.0,
                      admission_policy=AdmissionPolicy(
                          max_queue_depth=8),
                      config_overrides=_overrides())
    eng = rep["engine"]
    assert rep["completed"] + rep["shed"] == 160
    assert eng["requests"]["errors"] == 0
    assert eng["requests"]["finished"] == rep["completed"]
    assert eng["rejections_by_reason"].get("shed_queue_full", 0) \
        == rep["shed"]
    # the pool fully drains after the storm
    assert eng["kv_cache"]["blocks_in_use"] == 0
    assert rep["prefix_hit_rate"] > 0
