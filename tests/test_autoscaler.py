"""Autoscaler tests: fake provider, demand-driven scale-up, idle
scale-down (reference patterns: test_autoscaler_fake_multinode.py,
test_autoscaler_fake_scaledown.py; pure-unit test_autoscaler.py)."""

import time
from typing import Dict, List

import pytest

import ray_tpu
from ray_tpu.autoscaler import (AutoscalingCluster, FakeNodeProvider,
                                NodeTypeConfig, StandardAutoscaler)
from ray_tpu.autoscaler.node_provider import NodeProvider

pytestmark = pytest.mark.fast


# ---- pure-unit: mocked provider + mocked GCS ------------------------------

class MockProvider(NodeProvider):
    def __init__(self):
        self.created: List[tuple] = []
        self.terminated: List[str] = []
        self._n = 0
        self._types: Dict[str, str] = {}

    def non_terminated_nodes(self):
        return [p for p in self._types if p not in self.terminated]

    def create_node(self, node_type, resources, count):
        ids = []
        for _ in range(count):
            pid = f"m{self._n}"
            self._n += 1
            self._types[pid] = node_type
            ids.append(pid)
        self.created.append((node_type, count))
        return ids

    def terminate_node(self, pid):
        self.terminated.append(pid)

    def node_type(self, pid):
        return self._types.get(pid)

    def node_resources(self, pid):
        return {}

    def internal_id(self, pid):
        return None


def _gcs_stub(demand, nodes):
    def call(method, payload):
        if method == "autoscaler_demand":
            return demand
        if method == "node_list":
            return nodes
        raise AssertionError(method)
    return call


def test_unit_scale_up_bin_packs():
    provider = MockProvider()
    a = StandardAutoscaler(
        _gcs_stub({"pending": [{"CPU": 1.0}] * 5, "infeasible": []}, []),
        provider, [NodeTypeConfig("cpu-4", {"CPU": 4.0}, max_workers=8)])
    out = a.update()
    # 5 one-CPU tasks pack into two 4-CPU nodes, not five.
    assert out["launched"] == 2
    assert provider.created == [("cpu-4", 2)]


def test_unit_no_feasible_type_no_launch():
    provider = MockProvider()
    a = StandardAutoscaler(
        _gcs_stub({"pending": [{"TPU": 8.0}], "infeasible": []}, []),
        provider, [NodeTypeConfig("cpu-4", {"CPU": 4.0})])
    assert a.update()["launched"] == 0


def test_unit_max_workers_cap():
    provider = MockProvider()
    a = StandardAutoscaler(
        _gcs_stub({"pending": [{"CPU": 4.0}] * 10, "infeasible": []}, []),
        provider, [NodeTypeConfig("cpu-4", {"CPU": 4.0}, max_workers=3)])
    assert a.update()["launched"] == 3


def test_unit_existing_capacity_absorbs_demand():
    provider = MockProvider()
    nodes = [{"node_id": b"n1", "alive": True,
              "resources_total": {"CPU": 8.0},
              "resources_available": {"CPU": 8.0}}]
    a = StandardAutoscaler(
        _gcs_stub({"pending": [{"CPU": 2.0}] * 4, "infeasible": []}, nodes),
        provider, [NodeTypeConfig("cpu-4", {"CPU": 4.0})])
    assert a.update()["launched"] == 0


# ---- end-to-end: fake provider, real cluster ------------------------------

@pytest.fixture
def autoscaling_cluster():
    c = AutoscalingCluster(
        [NodeTypeConfig("cpu-2", {"CPU": 2.0}, max_workers=4)],
        idle_timeout_s=3.0, update_interval_s=0.3)
    c.connect()
    yield c
    ray_tpu.shutdown()
    c.shutdown()


def test_e2e_tasks_trigger_scale_up_then_down(autoscaling_cluster):
    """Queued CPU tasks on a 0-CPU cluster make the fake provider add
    nodes; the tasks then run; idle nodes are later reclaimed (VERDICT r2
    item 3 done-criterion)."""

    @ray_tpu.remote(num_cpus=1)
    def work(i):
        time.sleep(0.2)
        return i * 2

    refs = [work.remote(i) for i in range(6)]
    assert ray_tpu.get(refs, timeout=120) == [0, 2, 4, 6, 8, 10]
    provider = autoscaling_cluster.provider
    assert provider.non_terminated_nodes(), "no nodes were launched"
    # Idle scale-down: demand is gone; nodes must drain away.
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:
        if not provider.non_terminated_nodes():
            break
        time.sleep(0.5)
    assert not provider.non_terminated_nodes(), "idle nodes not reclaimed"
