"""Model zoo tests: shapes, numerics, grads, and sharded execution on the
fake 8-device mesh (reference test pattern: _fake_gpus,
rllib/algorithms/algorithm_config.py:344)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.models import (GPT2Config, MLPConfig, gpt2_config, gpt2_forward,
                            gpt2_init, gpt2_logical_axes, gpt2_loss,
                            gpt2_param_count, mlp_forward, mlp_init, mlp_loss,
                            resnet_config, resnet_forward, resnet_init,
                            resnet_loss)
from ray_tpu.parallel import MeshSpec, fake_mesh
from ray_tpu.parallel.sharding import param_shardings, shard_params


def test_gpt2_forward_shapes():
    cfg = gpt2_config("nano", use_flash=False)
    params = gpt2_init(jax.random.PRNGKey(0), cfg)
    tokens = jnp.zeros((2, 16), jnp.int32)
    logits = gpt2_forward(params, tokens, cfg)
    assert logits.shape == (2, 16, cfg.padded_vocab)
    assert logits.dtype == jnp.float32


def test_gpt2_param_count_gpt2_small():
    cfg = gpt2_config("gpt2")
    n = gpt2_param_count(cfg)
    assert 120e6 < n < 130e6  # 124M


def test_gpt2_loss_decreases_under_sgd():
    cfg = gpt2_config("nano", use_flash=False, remat=False)
    params = gpt2_init(jax.random.PRNGKey(0), cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 33), 0,
                                cfg.vocab_size)
    batch = {"tokens": tokens}

    loss_g = jax.jit(jax.value_and_grad(
        lambda p: gpt2_loss(p, batch, cfg)))
    l0, g = loss_g(params)
    for _ in range(5):
        params = jax.tree.map(lambda p, gg: p - 0.1 * gg, params, g)
        l1, g = loss_g(params)
    assert float(l1) < float(l0)
    # initial loss should be ~ log(vocab) for random params
    assert abs(float(l0) - np.log(cfg.vocab_size)) < 1.0


def test_gpt2_causality():
    """Changing a future token must not change past logits."""
    cfg = gpt2_config("nano", use_flash=False)
    params = gpt2_init(jax.random.PRNGKey(0), cfg)
    t1 = jax.random.randint(jax.random.PRNGKey(1), (1, 16), 0,
                            cfg.vocab_size)
    t2 = t1.at[0, 10].set((t1[0, 10] + 1) % cfg.vocab_size)
    l1 = gpt2_forward(params, t1, cfg)
    l2 = gpt2_forward(params, t2, cfg)
    np.testing.assert_allclose(np.asarray(l1[0, :10]),
                               np.asarray(l2[0, :10]), atol=2e-2)
    assert not np.allclose(np.asarray(l1[0, 10]), np.asarray(l2[0, 10]),
                           atol=1e-3)


def test_gpt2_sharded_fsdp_tp_matches_single_device():
    """The same loss under a 2x2x2 data×fsdp×tensor mesh and on one
    device — the GSPMD partition must be numerically faithful."""
    cfg = gpt2_config("nano", use_flash=False, remat=False,
                      dtype=jnp.float32)
    params = gpt2_init(jax.random.PRNGKey(0), cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (8, 17), 0,
                                cfg.vocab_size)
    batch = {"tokens": tokens}
    expected = float(gpt2_loss(params, batch, cfg))

    mesh = fake_mesh(8, MeshSpec(data=2, fsdp=2, tensor=2))
    axes = gpt2_logical_axes(cfg)
    with jax.set_mesh(mesh):
        sharded = shard_params(params, axes, mesh)
        shardings = param_shardings(axes, mesh)
        f = jax.jit(lambda p, b: gpt2_loss(p, b, cfg),
                    in_shardings=(shardings, None))
        got = float(f(sharded, batch))
    assert abs(got - expected) < 1e-3


def test_mlp_train_step():
    cfg = MLPConfig(in_dim=16, hidden=(32,), n_classes=4)
    params = mlp_init(jax.random.PRNGKey(0), cfg)
    x = jax.random.normal(jax.random.PRNGKey(1), (8, 16))
    y = jax.random.randint(jax.random.PRNGKey(2), (8,), 0, 4)
    loss, g = jax.value_and_grad(mlp_loss)(params, {"x": x, "y": y}, cfg)
    assert np.isfinite(float(loss))
    params2 = jax.tree.map(lambda p, gg: p - 0.5 * gg, params, g)
    loss2 = mlp_loss(params2, {"x": x, "y": y}, cfg)
    assert float(loss2) < float(loss)


def test_resnet_tiny_forward_and_loss():
    cfg = resnet_config("tiny", dtype=jnp.float32)
    params, state = resnet_init(jax.random.PRNGKey(0), cfg)
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 32, 32, 3))
    y = jnp.array([0, 1])
    (loss, new_state) = resnet_loss(params, state, {"x": x, "y": y}, cfg)
    assert np.isfinite(float(loss))
    # BN running stats must update in training mode
    assert not np.allclose(np.asarray(new_state["stem"]["mean"]),
                           np.asarray(state["stem"]["mean"]))
    logits, _ = resnet_forward(params, state, x, cfg, training=False)
    assert logits.shape == (2, cfg.n_classes)


def test_vit_forward_loss_and_grad():
    """ViT tiny: shapes, loss finiteness, grads flow, param count."""
    import jax
    import jax.numpy as jnp

    from ray_tpu.models import (vit_config, vit_forward, vit_init,
                                vit_loss, vit_param_count)

    cfg = vit_config("tiny")
    params = vit_init(jax.random.PRNGKey(0), cfg)
    n = sum(x.size for x in jax.tree.leaves(params))
    assert n == vit_param_count(cfg), (n, vit_param_count(cfg))
    imgs = jax.random.normal(jax.random.PRNGKey(1), (2, 32, 32, 3))
    labels = jnp.array([1, 3])
    logits = vit_forward(params, imgs, cfg)
    assert logits.shape == (2, cfg.n_classes)
    assert logits.dtype == jnp.float32
    loss, grads = jax.value_and_grad(
        lambda p: vit_loss(p, {"images": imgs, "labels": labels}, cfg)
    )(params)
    assert jnp.isfinite(loss)
    # zero-init head => uniform logits => loss == log(n_classes)
    import math as _m

    assert abs(float(loss) - _m.log(cfg.n_classes)) < 1e-3
    g = jax.tree.leaves(grads)
    assert all(jnp.all(jnp.isfinite(x)) for x in g)
    # the zero-init head blocks backbone grads at step 0 (standard ViT
    # init); the head itself must receive gradient
    assert float(jnp.abs(grads["head_w"]).sum()) > 0
    assert float(jnp.abs(grads["patch_w"]).sum()) == 0.0


def test_vit_shards_on_mesh():
    """ViT trains one jitted step under an fsdp x tensor mesh."""
    import jax
    import jax.numpy as jnp

    from ray_tpu.models import vit_config, vit_init, vit_logical_axes, vit_loss
    from ray_tpu.parallel import MeshSpec, fake_mesh
    from ray_tpu.parallel.sharding import shard_params

    mesh = fake_mesh(8, MeshSpec(data=2, fsdp=2, tensor=2))
    cfg = vit_config("tiny")
    axes = vit_logical_axes(cfg)
    params = vit_init(jax.random.PRNGKey(0), cfg)
    with jax.set_mesh(mesh):
        params = shard_params(params, axes, mesh)
        imgs = jax.random.normal(jax.random.PRNGKey(1), (4, 32, 32, 3))
        labels = jnp.arange(4)

        @jax.jit
        def step(p):
            return jax.value_and_grad(
                lambda q: vit_loss(q, {"images": imgs, "labels": labels},
                                   cfg))(p)

        loss, grads = step(params)
        assert jnp.isfinite(loss)
