"""Core API tests: tasks, objects, errors.

Reference analog: python/ray/tests/test_basic.py (uses the same
start-a-real-mini-cluster-in-process fixture pattern, conftest.py:245).
"""

import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu import exceptions

pytestmark = pytest.mark.fast


def test_put_get(ray_start_regular):
    ref = ray_tpu.put(42)
    assert ray_tpu.get(ref) == 42
    ref2 = ray_tpu.put({"a": [1, 2, 3], "b": "x"})
    assert ray_tpu.get(ref2) == {"a": [1, 2, 3], "b": "x"}


def test_put_get_large_numpy(ray_start_regular):
    arr = np.arange(1_000_000, dtype=np.float32)
    ref = ray_tpu.put(arr)
    out = ray_tpu.get(ref)
    np.testing.assert_array_equal(arr, out)


def test_simple_task(ray_start_regular):
    @ray_tpu.remote
    def f(x):
        return x + 1

    assert ray_tpu.get(f.remote(1)) == 2


def test_many_tasks(ray_start_regular):
    @ray_tpu.remote
    def f(x):
        return x * 2

    refs = [f.remote(i) for i in range(50)]
    assert ray_tpu.get(refs) == [i * 2 for i in range(50)]


def test_task_with_ref_arg(ray_start_regular):
    @ray_tpu.remote
    def f(x):
        return x + 1

    @ray_tpu.remote
    def g(x):
        return x * 10

    ref = f.remote(1)
    assert ray_tpu.get(g.remote(ref)) == 20


def test_task_large_args_and_returns(ray_start_regular):
    @ray_tpu.remote
    def make():
        return np.ones((1000, 1000), dtype=np.float32)

    @ray_tpu.remote
    def total(a):
        return float(a.sum())

    ref = make.remote()
    assert ray_tpu.get(total.remote(ref)) == 1000 * 1000


def test_task_multiple_returns(ray_start_regular):
    @ray_tpu.remote(num_returns=3)
    def f():
        return 1, 2, 3

    a, b, c = f.remote()
    assert ray_tpu.get([a, b, c]) == [1, 2, 3]


def test_task_error(ray_start_regular):
    @ray_tpu.remote
    def boom():
        raise ValueError("bad")

    with pytest.raises(exceptions.RayTaskError, match="bad"):
        ray_tpu.get(boom.remote())


def test_task_error_propagates_through_dependents(ray_start_regular):
    @ray_tpu.remote
    def boom():
        raise ValueError("root cause")

    @ray_tpu.remote
    def child(x):
        return x

    with pytest.raises(exceptions.RayTaskError):
        ray_tpu.get(child.remote(boom.remote()))


def test_nested_tasks(ray_start_regular):
    @ray_tpu.remote
    def inner(x):
        return x * 2

    @ray_tpu.remote
    def outer(x):
        return ray_tpu.get(inner.remote(x)) + 1

    assert ray_tpu.get(outer.remote(10)) == 21


def test_nested_object_ref_in_container(ray_start_regular):
    @ray_tpu.remote
    def f(d):
        return ray_tpu.get(d["ref"]) + 1

    ref = ray_tpu.put(41)
    assert ray_tpu.get(f.remote({"ref": ref})) == 42


def test_wait(ray_start_regular):
    @ray_tpu.remote
    def fast():
        return "fast"

    @ray_tpu.remote
    def slow():
        time.sleep(5)
        return "slow"

    ray_tpu.get(fast.remote())  # pre-warm a worker (slow spawn on 1-core CI)
    a, b = fast.remote(), slow.remote()
    ready, not_ready = ray_tpu.wait([a, b], num_returns=1, timeout=4)
    assert ready == [a]
    assert not_ready == [b]


def test_get_timeout(ray_start_regular):
    @ray_tpu.remote
    def slow():
        time.sleep(10)

    with pytest.raises(exceptions.GetTimeoutError):
        ray_tpu.get(slow.remote(), timeout=0.5)


def test_options_override(ray_start_regular):
    @ray_tpu.remote(num_cpus=1)
    def f():
        return "ok"

    assert ray_tpu.get(f.options(num_cpus=2).remote()) == "ok"


def test_kwargs(ray_start_regular):
    @ray_tpu.remote
    def f(a, b=0, c=0):
        return a + b + c

    assert ray_tpu.get(f.remote(1, c=3)) == 4


def test_cluster_resources(ray_start_regular):
    res = ray_tpu.cluster_resources()
    assert res["CPU"] == 4.0
    nodes = ray_tpu.nodes()
    assert len(nodes) == 1
    assert nodes[0]["Alive"]


def test_parallelism(ray_start_regular):
    """4 CPUs -> 4 sleep(1) tasks run concurrently, well under 4s.
    First round pre-warms the worker pool so process spawn time (slow on
    tiny CI hosts) is not in the timed window."""

    @ray_tpu.remote
    def nap(t):
        time.sleep(t)
        return 1

    ray_tpu.get([nap.remote(0.01) for _ in range(4)])
    start = time.monotonic()
    assert sum(ray_tpu.get([nap.remote(1.0) for _ in range(4)])) == 4
    assert time.monotonic() - start < 3.5


def test_remote_function_direct_call_raises(ray_start_regular):
    @ray_tpu.remote
    def f():
        return 1

    with pytest.raises(TypeError):
        f()


def test_dynamic_num_returns_generator(ray_start_shared):
    """num_returns="dynamic": the task yields a runtime-decided number
    of values; get(ref) resolves to per-item ObjectRefs (reference:
    generator tasks / ObjectRefGenerator)."""
    import numpy as np

    @ray_tpu.remote(num_returns="dynamic")
    def chunks(n):
        for i in range(n):
            yield np.full(1000, i, np.int64)  # big enough to hit shm

    ref = chunks.remote(5)
    item_refs = ray_tpu.get(ref, timeout=60)
    assert len(item_refs) == 5
    vals = ray_tpu.get(item_refs, timeout=60)
    for i, v in enumerate(vals):
        assert v[0] == i and v.shape == (1000,)

    # runtime-decided count: same task, different n
    assert len(ray_tpu.get(chunks.remote(2), timeout=60)) == 2

    # non-generator result is a loud error
    @ray_tpu.remote(num_returns="dynamic")
    def not_gen():
        return [1, 2, 3]

    with pytest.raises(Exception, match="generator"):
        ray_tpu.get(ray_tpu.get(not_gen.remote(), timeout=60),
                    timeout=60)


def test_dynamic_returns_survive_source_drop(ray_start_shared):
    """Item refs stay valid after the primary generator ref is dropped
    (the contained-ref pinning keeps items alive)."""
    import gc

    import numpy as np

    @ray_tpu.remote(num_returns="dynamic")
    def gen():
        yield np.arange(2000)
        yield np.arange(2000) * 2

    ref = gen.remote()
    items = ray_tpu.get(ref, timeout=60)
    del ref
    gc.collect()
    a, b = ray_tpu.get(items, timeout=60)
    assert a[1] == 1 and b[1] == 2
