"""Object store tests (reference analog: plasma tests under
src/ray/object_manager/plasma/test/)."""

import multiprocessing
import os

import pytest

from ray_tpu._private.ids import JobID, ObjectID, TaskID
from ray_tpu._private.object_store import (
    ObjectStoreClient,
    ObjectStoreFull,
)

pytestmark = pytest.mark.fast

CAP = 32 * 1024 * 1024


_TASK = TaskID.for_driver(JobID.from_int(1))


def _oid(i: int) -> ObjectID:
    return ObjectID.for_return(_TASK, i + 1)


@pytest.fixture
def store():
    name = f"/raytpu_test_{os.getpid()}"
    s = ObjectStoreClient(name, create=True, capacity=CAP)
    yield s
    s.close(destroy=True)


def test_put_get_roundtrip(store):
    oid = _oid(0)
    store.put_bytes(oid, b"hello world", metadata=b"meta")
    buf = store.get(oid)
    assert bytes(buf.data) == b"hello world"
    assert bytes(buf.metadata) == b"meta"
    buf.close()


def test_create_seal_get(store):
    oid = _oid(1)
    view = store.create(oid, 8)
    view[:] = b"abcdefgh"
    view.release()
    assert not store.contains(oid)  # not sealed yet
    store.seal(oid)
    assert store.contains(oid)
    with store.get(oid) as buf:
        assert bytes(buf.data) == b"abcdefgh"


def test_get_nonblocking_missing(store):
    assert store.get(_oid(2), timeout_ms=0) is None


def test_get_timeout(store):
    assert store.get(_oid(3), timeout_ms=50) is None


def test_delete_and_refcount(store):
    oid = _oid(4)
    store.put_bytes(oid, b"x" * 100)
    buf = store.get(oid)
    assert not store.delete(oid)  # pinned
    buf.close()
    assert store.delete(oid)
    assert not store.contains(oid)


def test_eviction_under_pressure(store):
    # Fill the store with unpinned objects, then create one that
    # requires eviction.
    big = CAP // 8
    for i in range(10, 20):
        try:
            store.put_bytes(_oid(i), b"\0" * big)
        except ObjectStoreFull:
            break
    # this must succeed by evicting LRU unpinned objects
    store.put_bytes(_oid(99), b"\1" * big)
    with store.get(_oid(99)) as buf:
        assert bytes(buf.data[:4]) == b"\1\1\1\1"
    assert store.stats()["evictions"] > 0


def test_store_full_when_pinned(store):
    big = CAP // 4
    bufs = []
    oids = []
    i = 30
    while True:
        oid = _oid(i)
        try:
            store.put_bytes(oid, b"\0" * big)
        except ObjectStoreFull:
            break
        bufs.append(store.get(oid))  # pin it
        oids.append(oid)
        i += 1
    with pytest.raises(ObjectStoreFull):
        store.put_bytes(_oid(98), b"\2" * big)
    for b in bufs:
        b.close()
    # now eviction can reclaim
    store.put_bytes(_oid(98), b"\2" * big)


def test_zero_size_object(store):
    oid = _oid(5)
    store.put_bytes(oid, b"", metadata=b"only-meta")
    with store.get(oid) as buf:
        assert bytes(buf.data) == b""
        assert bytes(buf.metadata) == b"only-meta"


def test_abort(store):
    oid = _oid(6)
    v = store.create(oid, 16)
    v.release()
    store.abort(oid)
    assert store.get(oid, timeout_ms=0) is None
    # id is reusable after abort
    store.put_bytes(oid, b"second try")
    with store.get(oid) as buf:
        assert bytes(buf.data) == b"second try"


def _child_reader(shm_name, oid_bytes, q):
    client = ObjectStoreClient(shm_name)
    buf = client.get(ObjectID(oid_bytes), timeout_ms=5000)
    q.put(bytes(buf.data))
    buf.close()
    client.close()


def test_cross_process_zero_copy(store):
    """A child process attaches and blocks in get() until the parent seals."""
    ctx = multiprocessing.get_context("fork")
    q = ctx.Queue()
    oid = _oid(7)
    p = ctx.Process(target=_child_reader, args=(store.shm_name, oid.binary(), q))
    p.start()
    # seal after the child is (likely) waiting
    import time

    time.sleep(0.2)
    store.put_bytes(oid, b"cross-process payload")
    assert q.get(timeout=10) == b"cross-process payload"
    p.join(timeout=10)
    assert p.exitcode == 0


def test_many_objects(store):
    for i in range(1000):
        store.put_bytes(_oid(1000 + i), bytes([i % 256]) * 100)
    for i in range(0, 1000, 37):
        with store.get(_oid(1000 + i)) as buf:
            assert bytes(buf.data) == bytes([i % 256]) * 100
    assert store.stats()["num_objects"] >= 1000
