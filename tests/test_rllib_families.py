"""Round-5 RLlib algorithm families: PG / A2C / A3C, SimpleQ / ApexDQN,
LinUCB / LinTS bandits, ARS.

Reference analogs: rllib/algorithms/{pg,a2c,a3c,simple_q,apex_dqn,
bandit,ars} — learning checks follow the check_learning_achieved
pattern scaled to CI (rllib/utils/test_utils.py:480).
"""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.rllib import (A2C, A2CConfig, A3C, A3CConfig, ApexDQN,
                           ApexDQNConfig, ARS, ARSConfig, LinTS,
                           LinTSConfig, LinUCB, LinUCBConfig, PG,
                           PGConfig, SimpleQ, SimpleQConfig)


def _train_until(algo, key, target, iters):
    best = -np.inf
    try:
        for _ in range(iters):
            result = algo.train()
            best = max(best, result.get(key, -np.inf))
            if best >= target:
                break
    finally:
        algo.stop()
    return best


# ---------------------------------------------------------------------------
# policy-gradient family
# ---------------------------------------------------------------------------

def test_pg_learns_cartpole(ray_start_shared):
    algo = PG(PGConfig(env="CartPole-v1", num_workers=1,
                       num_envs_per_worker=8, train_batch_size=2048,
                       rollout_fragment_length=256, lr=4e-3,
                       hidden=(32,), seed=0))
    best = _train_until(algo, "episode_reward_mean", 80.0, 25)
    assert best >= 60.0, best


def test_a2c_learns_cartpole(ray_start_shared):
    algo = A2C(A2CConfig(env="CartPole-v1", num_workers=1,
                         num_envs_per_worker=8, train_batch_size=2048,
                         rollout_fragment_length=256, lr=4e-3,
                         hidden=(32,), seed=0))
    best = _train_until(algo, "episode_reward_mean", 120.0, 25)
    assert best >= 80.0, best


def test_a3c_learns_cartpole(ray_start_shared):
    algo = A3C(A3CConfig(env="CartPole-v1", num_workers=2,
                         num_envs_per_worker=4, updates_per_iter=4,
                         rollout_fragment_length=256, lr=4e-3,
                         hidden=(32,), seed=0))
    best = _train_until(algo, "episode_reward_mean", 120.0, 20)
    assert best >= 80.0, best


def test_pg_uses_raw_returns():
    # PG's batch prep must substitute return-to-go for the GAE
    # advantage and skip standardization
    from ray_tpu.rllib import sample_batch as sb
    from ray_tpu.rllib.sample_batch import SampleBatch

    cfg = PGConfig(obs_dim=4, n_actions=2)
    batch = SampleBatch({sb.ADVANTAGES: np.zeros(4, np.float32),
                         sb.VALUE_TARGETS: np.array([1, 2, 3, 4],
                                                    np.float32)})
    PG._prepare_batch(object.__new__(PG), batch)
    np.testing.assert_array_equal(batch[sb.ADVANTAGES],
                                  [1.0, 2.0, 3.0, 4.0])


# ---------------------------------------------------------------------------
# DQN variants
# ---------------------------------------------------------------------------

class _ContextBanditEnv:
    """10-step episodes; reward 2 for matching the context parity, 0
    otherwise — solvable by any Q learner, fast to run."""

    class _Space:
        def __init__(self, shape=None, n=None):
            self.shape = shape
            self.n = n

    def __init__(self, seed=0):
        self.observation_space = self._Space(shape=(2,))
        self.action_space = self._Space(n=2)
        self._rng = np.random.RandomState(seed)
        self._t = 0

    def _obs(self):
        side = self._rng.randint(2)
        self._side = side
        return np.asarray([side, 1 - side], np.float32)

    def reset(self, seed=None, options=None):
        if seed is not None:
            self._rng = np.random.RandomState(seed)
        self._t = 0
        return self._obs(), {}

    def step(self, action):
        r = 2.0 if int(action) == self._side else 0.0
        self._t += 1
        done = self._t >= 10
        return self._obs(), r, done, False, {}


def test_simpleq_config_disables_double_q():
    cfg = SimpleQConfig(obs_dim=2, n_actions=2)
    assert cfg.double_q is False and cfg.prioritized_replay is False
    assert cfg.q_spec().double_q is False


def test_simpleq_learns_context_bandit(ray_start_shared):
    cfg = SimpleQConfig(env=lambda _: _ContextBanditEnv(),
                        num_workers=1, hidden=(32,), buffer_size=5000,
                        learning_starts=200, train_batch_size=64,
                        train_intensity=16, target_update_freq=200,
                        epsilon_decay_steps=1500,
                        rollout_fragment_length=100, lr=5e-3,
                        gamma=0.0, seed=0)
    best = _train_until(SimpleQ(cfg), "episode_reward_mean", 18.0, 25)
    assert best >= 15.0, best


def test_apex_dqn_learns_context_bandit(ray_start_shared):
    cfg = ApexDQNConfig(env=lambda _: _ContextBanditEnv(),
                        num_workers=2, hidden=(32,), buffer_size=5000,
                        learning_starts=200, train_batch_size=64,
                        train_intensity=8, target_update_freq=200,
                        updates_per_iter=4,
                        rollout_fragment_length=100, lr=5e-3,
                        gamma=0.0, seed=0)
    algo = ApexDQN(cfg)
    # the epsilon ladder must spread across workers, highest first
    eps = algo._worker_eps
    assert len(eps) == 2 and eps[0] > eps[1] > 0.0
    best = _train_until(algo, "episode_reward_mean", 18.0, 25)
    assert best >= 15.0, best


def test_apex_requires_prioritized():
    with pytest.raises(ValueError):
        ApexDQN(ApexDQNConfig(env=lambda _: _ContextBanditEnv(),
                              prioritized_replay=False, obs_dim=2,
                              n_actions=2))


# ---------------------------------------------------------------------------
# linear bandits
# ---------------------------------------------------------------------------

class _LinearBanditEnv:
    """One-step contextual bandit: reward = <w_arm, x> + noise with
    fixed hidden arm weights — the exact model class LinUCB/LinTS
    assume, so regret should vanish quickly."""

    class _Space:
        def __init__(self, shape=None, n=None):
            self.shape = shape
            self.n = n

    def __init__(self, seed=0, d=4, arms=3, noise=0.05):
        rng = np.random.RandomState(seed + 999)
        self.w = rng.standard_normal((arms, d))
        self.observation_space = self._Space(shape=(d,))
        self.action_space = self._Space(n=arms)
        self._rng = np.random.RandomState(seed)
        self._noise = noise

    def reset(self, seed=None, options=None):
        if seed is not None:
            self._rng = np.random.RandomState(seed)
        self._x = self._rng.standard_normal(
            self.w.shape[1]).astype(np.float64)
        return self._x.copy(), {}

    def step(self, arm):
        r = float(self.w[int(arm)] @ self._x
                  + self._noise * self._rng.standard_normal())
        self._best = float(np.max(self.w @ self._x))
        return self._x.copy(), r, True, False, {}


@pytest.mark.parametrize("cls,cfg_cls", [(LinUCB, LinUCBConfig),
                                         (LinTS, LinTSConfig)])
def test_linear_bandit_converges(cls, cfg_cls):
    env_holder = {}

    def creator(_):
        env_holder["env"] = _LinearBanditEnv(seed=1)
        return env_holder["env"]

    algo = cls(cfg_cls(env=creator, steps_per_iter=64, seed=1))
    first = algo.train()["mean_reward"]
    last = first
    for _ in range(6):
        last = algo.train()["mean_reward"]
    algo.cleanup()
    # after ~450 pulls the posterior should be near-greedy-optimal;
    # early exploration rounds score measurably worse
    assert last > first, (first, last)
    env = env_holder["env"]
    # posterior mean should select the true best arm on fresh contexts
    hits = 0
    for t in range(50):
        x, _ = env.reset(seed=10_000 + t)
        arm = algo.compute_actions(x)
        hits += int(np.argmax(env.w @ x) == arm)
    assert hits >= 40, hits


# ---------------------------------------------------------------------------
# ARS
# ---------------------------------------------------------------------------

def test_ars_improves_cartpole(ray_start_shared):
    algo = ARS(ARSConfig(env="CartPole-v1", num_workers=2,
                         population=12, top_k=6, sigma=0.05, lr=0.02,
                         seed=3))
    first = algo.train()["ars_mean_fitness"]
    best = first
    for _ in range(12):
        best = max(best, algo.train()["ars_mean_fitness"])
    algo.cleanup()
    assert best > first + 20, (first, best)


def test_ars_is_linear_policy():
    assert ARSConfig().hidden == ()


# ---------------------------------------------------------------------------
# DDPPO + the compute/apply gradients Policy API
# ---------------------------------------------------------------------------

def test_policy_compute_apply_gradients_roundtrip():
    # compute_gradients + apply_gradients must equal one learn step
    from ray_tpu.rllib.policy import JaxPolicy, PolicySpec
    from ray_tpu.rllib import sample_batch as sb
    from ray_tpu.rllib.sample_batch import SampleBatch

    rng = np.random.RandomState(0)
    spec = PolicySpec(obs_dim=4, n_actions=2, hidden=(8,),
                      num_sgd_iter=1, minibatch_size=64)
    pol = JaxPolicy(spec, seed=0)
    batch = SampleBatch({
        sb.OBS: rng.randn(32, 4).astype(np.float32),
        sb.ACTIONS: rng.randint(0, 2, 32).astype(np.int64),
        sb.ACTION_LOGP: np.full(32, -0.69, np.float32),
        sb.ADVANTAGES: rng.randn(32).astype(np.float32),
        sb.VALUE_TARGETS: rng.randn(32).astype(np.float32),
    })
    grads, stats = pol.compute_gradients(batch)
    assert np.isfinite(stats["total_loss"])
    before = pol.get_weights()
    pol.apply_gradients(grads)
    after = pol.get_weights()
    # weights moved, and in the direction the optimizer dictates
    moved = any(
        not np.allclose(a, b) for a, b in
        zip(jax_leaves(before), jax_leaves(after)))
    assert moved


def jax_leaves(tree):
    import jax

    return jax.tree_util.tree_leaves(tree)


def test_ddppo_learns_cartpole(ray_start_shared):
    from ray_tpu.rllib import DDPPO, DDPPOConfig

    algo = DDPPO(DDPPOConfig(env="CartPole-v1", num_workers=2,
                             num_envs_per_worker=4,
                             rollout_fragment_length=128,
                             num_sgd_iter=6, lr=4e-3, hidden=(32,),
                             seed=0))
    best = _train_until(algo, "episode_reward_mean", 120.0, 25)
    assert best >= 80.0, best


def test_dueling_architecture_and_simpleq_flat():
    import jax.numpy as jnp

    from ray_tpu.rllib.dqn import QPolicy, QPolicySpec, _q_apply
    from ray_tpu.rllib.policy import _net_apply

    spec = QPolicySpec(obs_dim=3, n_actions=4, hidden=(8,),
                       dueling=True)
    pol = QPolicy(spec, seed=0)
    assert set(pol.params) == {"trunk", "v", "a"}
    obs = jnp.asarray(np.random.RandomState(0)
                      .randn(5, 3).astype(np.float32))
    q = _q_apply(spec, pol.params, obs)
    assert q.shape == (5, 4)
    # the dueling identity: mean_a Q == V (advantages centered)
    h = _net_apply(pol.params["trunk"], obs, final_linear=False)
    v = np.asarray(_net_apply(pol.params["v"], h))
    np.testing.assert_allclose(np.asarray(q).mean(-1), v[:, 0],
                               atol=1e-5)
    # SimpleQ keeps the flat estimator
    assert SimpleQConfig(obs_dim=3, n_actions=4).q_spec().dueling \
        is False
    # a mismatched checkpoint tree fails with the knob named, not a
    # TypeError inside the jitted update
    flat = QPolicy(QPolicySpec(obs_dim=3, n_actions=4, hidden=(8,),
                               dueling=False), seed=0)
    with pytest.raises(ValueError, match="dueling=False"):
        pol.set_weights(flat.get_weights())


def test_nstep_transition_folding():
    from ray_tpu.rllib.dqn import _nstep_transitions

    gamma = 0.9
    nxt = np.arange(1, 7, dtype=np.float32).reshape(6, 1)
    rew = np.asarray([1, 1, 1, 1, 1, 1], np.float32)
    done = np.asarray([0, 0, 1, 0, 0, 0], bool)       # terminal at t=2
    bound = np.asarray([0, 0, 1, 0, 1, 0], bool)      # + trunc at t=4
    R, n2, dn, disc = _nstep_transitions(rew, done, bound, nxt,
                                         gamma, 3)
    # t=0: spans 0,1,2 (stops at terminal): 1 + .9 + .81
    np.testing.assert_allclose(R[0], 1 + 0.9 + 0.81)
    assert dn[0] and disc[0] == 0.0 and n2[0, 0] == 3.0
    # t=1: spans 1,2 → terminal, discount 0
    np.testing.assert_allclose(R[1], 1 + 0.9)
    assert disc[1] == 0.0
    # t=3: spans 3,4 → TRUNCATION cuts the window but still bootstraps
    np.testing.assert_allclose(R[3], 1 + 0.9)
    assert not dn[3] and np.isclose(disc[3], 0.81)
    assert n2[3, 0] == 5.0
    # t=5: fragment tail, single step, bootstraps with gamma^1
    np.testing.assert_allclose(R[5], 1.0)
    assert np.isclose(disc[5], 0.9)


def test_nstep_dqn_learns(ray_start_shared):
    from ray_tpu.rllib import DQN, DQNConfig

    cfg = DQNConfig(env=lambda _: _ContextBanditEnv(), num_workers=1,
                    hidden=(32,), buffer_size=5000, learning_starts=200,
                    train_batch_size=64, train_intensity=16,
                    target_update_freq=200, epsilon_decay_steps=1500,
                    rollout_fragment_length=100, lr=5e-3, gamma=0.5,
                    n_step=3, seed=0)
    best = _train_until(DQN(cfg), "episode_reward_mean", 18.0, 25)
    assert best >= 15.0, best


def test_c51_projection_and_heads():
    import jax
    import jax.numpy as jnp

    from ray_tpu.rllib.dqn import (QPolicy, QPolicySpec,
                                   _project_distribution, _q_apply,
                                   _q_logits)

    spec = QPolicySpec(obs_dim=2, n_actions=3, hidden=(8,),
                       num_atoms=11, v_min=-5.0, v_max=5.0)
    pol = QPolicy(spec, seed=0)
    obs = jnp.asarray(np.random.RandomState(0)
                      .randn(4, 2).astype(np.float32))
    logits = _q_logits(spec, pol.params, obs)
    assert logits.shape == (4, 3, 11)
    q = _q_apply(spec, pol.params, obs)
    assert q.shape == (4, 3)
    # expectations live inside the support
    assert (np.asarray(q) >= -5).all() and (np.asarray(q) <= 5).all()

    # projection: a delta at z=0 with reward 1, discount 1 lands as a
    # delta at z=1 (on-grid for this support, dz=1)
    probs = jnp.zeros((1, 11)).at[0, 5].set(1.0)
    proj = _project_distribution(spec, probs, jnp.asarray([1.0]),
                                 jnp.asarray([1.0]))
    np.testing.assert_allclose(np.asarray(proj)[0, 6], 1.0, atol=1e-6)
    np.testing.assert_allclose(np.asarray(proj).sum(), 1.0, rtol=1e-6)
    # off-grid reward splits mass between neighbors
    proj2 = _project_distribution(spec, probs, jnp.asarray([0.5]),
                                  jnp.asarray([1.0]))
    np.testing.assert_allclose(np.asarray(proj2)[0, 5], 0.5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(proj2)[0, 6], 0.5, atol=1e-6)
    # terminal (discount 0): everything collapses onto z=reward
    proj3 = _project_distribution(spec, probs, jnp.asarray([2.0]),
                                  jnp.asarray([0.0]))
    np.testing.assert_allclose(np.asarray(proj3)[0, 7], 1.0, atol=1e-6)


def test_c51_dqn_learns(ray_start_shared):
    from ray_tpu.rllib import DQN, DQNConfig

    cfg = DQNConfig(env=lambda _: _ContextBanditEnv(), num_workers=1,
                    hidden=(32,), buffer_size=5000, learning_starts=200,
                    train_batch_size=64, train_intensity=16,
                    target_update_freq=200, epsilon_decay_steps=1500,
                    rollout_fragment_length=100, lr=5e-3, gamma=0.0,
                    num_atoms=21, v_min=0.0, v_max=4.0, seed=0)
    best = _train_until(DQN(cfg), "episode_reward_mean", 18.0, 25)
    assert best >= 15.0, best


def test_noisy_net_exploration_and_greedy_eval():
    import jax

    from ray_tpu.rllib.dqn import QPolicy, QPolicySpec

    spec = QPolicySpec(obs_dim=2, n_actions=4, hidden=(8,),
                       dueling=True, noisy=True)
    pol = QPolicy(spec, seed=0)
    assert "w_sigma" in pol.params["v"]
    obs = np.zeros((64, 2), np.float32)
    # exploring path (epsilon>0 marker): resampled noise varies actions
    acts = [tuple(pol.compute_actions(obs, epsilon=1.0))
            for _ in range(5)]
    assert len(set(acts)) > 1, acts
    # greedy path is deterministic (mean weights, no noise)
    g1 = pol.compute_actions(obs, epsilon=0.0)
    g2 = pol.compute_actions(obs, epsilon=0.0)
    np.testing.assert_array_equal(g1, g2)


def test_rainbow_learns(ray_start_shared):
    from ray_tpu.rllib import Rainbow, RainbowConfig

    cfg = RainbowConfig(env=lambda _: _ContextBanditEnv(),
                        num_workers=1, hidden=(32,), buffer_size=5000,
                        learning_starts=200, train_batch_size=64,
                        train_intensity=16, target_update_freq=200,
                        epsilon_decay_steps=1500,
                        rollout_fragment_length=100, lr=5e-3,
                        gamma=0.5, num_atoms=21, v_min=0.0,
                        v_max=8.0, seed=0)
    assert cfg.dueling and cfg.n_step == 3 and cfg.prioritized_replay
    best = _train_until(Rainbow(cfg), "episode_reward_mean", 18.0, 25)
    assert best >= 15.0, best
