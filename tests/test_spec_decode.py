"""Speculative decoding on the continuous serve engine (round 11).

The contract under test: with ``spec_decode=SpecConfig(...)`` the
engine proposes k tokens per slot per round (zero-weight n-gram draft
or a small draft MODEL) and verifies all k+1 positions in ONE jitted
target dispatch — and at temperature 0 every caller still gets the
BIT-IDENTICAL continuation the non-spec dense single-request oracle
produces, for both families and both KV layouts.  Telemetry must
account for every proposed token (proposed == accepted + rejected),
and an aligned draft (same family/preset/seed as the target) must
push target dispatches per emitted token under 1/2 at k=4.

Engines are driven directly (``dep.func_or_class()`` on a private
event loop) — the idiom test_serve_paged.py established — so each
test owns its engine, its slots, and its block pool.
"""

import asyncio

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from ray_tpu.models.decode_common import (SamplingParams,
                                          sample_token)  # noqa: E402
from ray_tpu.serve.llm import (SpecConfig,
                               build_llm_deployment)  # noqa: E402

MAX_NEW = 6
_OVR = {"dtype": jnp.float32, "use_flash": False, "remat": False}

# Every spec engine in this file runs k=4: the jitted-program cache in
# serve/llm.py is keyed by SpecConfig, so one verify compile per
# (family, layout) serves the parity, stop, eos, and bench tests.
K = 4


def _build(family="gpt2", **kw):
    kw.setdefault("max_new_tokens", MAX_NEW)
    kw.setdefault("temperature", 0.0)
    kw.setdefault("scheduler", "continuous")
    kw.setdefault("prefill_bucket", 16)
    kw.setdefault("config_overrides", _OVR)
    return build_llm_deployment(family, "nano", **kw)


def _drive(dep, prompts, *, sampling=None, timeout=300):
    """Run all prompts concurrently on a fresh engine instance;
    sampling (optional) is a parallel list of per-request
    SamplingParams/None.  Returns (results, engine_stats)."""
    sps = sampling or [None] * len(prompts)

    async def main():
        inst = dep.func_or_class()
        try:
            outs = await asyncio.wait_for(
                asyncio.gather(*[
                    inst(p) if sp is None else inst(p, sampling=sp)
                    for p, sp in zip(prompts, sps)]),
                timeout)
            stats = inst.engine_stats()
        finally:
            inst.shutdown_engine()
        return outs, stats

    return asyncio.run(main())


def _family_oracle(family):
    """(cfg, params, generate) for the dense single-request greedy
    reference — what every spec/non-spec engine must reproduce."""
    if family == "gpt2":
        from ray_tpu.models import gpt2_config, gpt2_init
        from ray_tpu.models.gpt2_decode import generate
        cfg = gpt2_config("nano", **_OVR)
        return cfg, gpt2_init(jax.random.PRNGKey(0), cfg), generate
    from ray_tpu.models import llama_config, llama_init
    from ray_tpu.models.llama_decode import llama_generate
    cfg = llama_config("nano", **_OVR)
    return cfg, llama_init(jax.random.PRNGKey(0), cfg), llama_generate


_REF_CACHE = {}


def _references(family, prompts, max_new=MAX_NEW):
    cfg, params, generate = _family_oracle(family)
    out = []
    for p in prompts:
        key = (family, max_new, tuple(int(t) for t in p))
        if key not in _REF_CACHE:
            _REF_CACHE[key] = np.asarray(generate(
                params, jnp.asarray(p, jnp.int32)[None], cfg,
                max_new_tokens=max_new, temperature=0.0))[0]
        out.append(_REF_CACHE[key])
    return out


def _prompts(seed=7, lens=(3, 7, 5)):
    rng = np.random.RandomState(seed)
    return [rng.randint(1, 500, (n,)).astype(np.int32) for n in lens]


# ---------------------------------------------------------------------------
# tentpole acceptance: greedy spec == dense single-request oracle
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("kv_layout", ["dense", "paged"])
@pytest.mark.parametrize("family", ["gpt2", "llama"])
def test_spec_ngram_greedy_parity(family, kv_layout):
    """n-gram draft, both families x both KV layouts: outputs are
    bit-identical to the oracle and the spec telemetry balances."""
    prompts = _prompts()
    dep = _build(family, kv_layout=kv_layout, kv_block_size=16,
                 max_slots=4,
                 spec_decode=SpecConfig(draft="ngram", k=K))
    outs, stats = _drive(dep, prompts)
    refs = _references(family, prompts)
    for p, o, r in zip(prompts, outs, refs):
        assert o.shape == (len(p) + MAX_NEW,)
        np.testing.assert_array_equal(o[:len(p)], p)
        np.testing.assert_array_equal(o, r)

    assert stats["requests"]["finished"] == len(prompts)
    spec = stats["spec"]
    assert spec["rounds"] > 0
    assert spec["proposed"] > 0
    assert spec["proposed"] == spec["accepted"] + spec["rejected"]
    assert 0.0 <= spec["accept_rate"] <= 1.0
    # every round proposes exactly k per active slot
    assert spec["proposed"] % K == 0


# llama compiles a second full draft-scan program family; the gpt2
# case + the ngram parity matrix above cover the tier-1 contract
@pytest.mark.parametrize("family", [
    "gpt2", pytest.param("llama", marks=pytest.mark.slow)])
def test_spec_aligned_model_draft_accepts_everything(family):
    """A draft MODEL with the target's own family/preset/seed proposes
    the target's argmax every time: acceptance is exactly 1.0 and the
    output is still the oracle's, token for token."""
    prompts = _prompts(seed=11, lens=(4, 6))
    dep = _build(family, max_slots=2,
                 spec_decode=SpecConfig(draft=f"{family}:nano", k=K))
    outs, stats = _drive(dep, prompts)
    refs = _references(family, prompts)
    for o, r in zip(outs, refs):
        np.testing.assert_array_equal(o, r)
    spec = stats["spec"]
    assert spec["rejected"] == 0
    assert spec["accept_rate"] == 1.0


def test_spec_sharded_engine_smoke():
    """Spec decode on the tensor-parallel engine over 8 virtual
    devices: greedy streams stay bit-identical to the single-chip
    oracle (logits all-reduce in a different order; argmax must not
    care), and spec telemetry still balances."""
    from ray_tpu.parallel import MeshSpec, fake_mesh

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 devices (conftest forces them in CI)")
    mesh = fake_mesh(8, MeshSpec(data=4, tensor=2))
    prompts = _prompts(seed=3, lens=(5, 7))
    dep = _build("gpt2", max_slots=2, mesh=mesh,
                 spec_decode=SpecConfig(draft="ngram", k=K))
    outs, stats = _drive(dep, prompts)
    refs = _references("gpt2", prompts)
    for o, r in zip(outs, refs):
        np.testing.assert_array_equal(o, r)
    assert stats["mesh"]["axes"] == {"data": 4, "tensor": 2}
    spec = stats["spec"]
    assert spec["rounds"] > 0
    assert spec["proposed"] == spec["accepted"] + spec["rejected"]


# ---------------------------------------------------------------------------
# stop sequences / eos: host-side matching frees slots mid-flight
# ---------------------------------------------------------------------------

def test_stop_sequence_truncates_midflight():
    """A stop sequence drawn from the oracle's own continuation must
    cut the output right after the match — with and without spec (the
    spec emission loop checks stops token by token)."""
    prompts = _prompts(seed=5, lens=(6,))
    ref = _references("gpt2", prompts)[0]
    cont = [int(t) for t in ref[len(prompts[0]):]]
    stop = (cont[1], cont[2])

    # earliest generated prefix whose suffix is `stop` (degenerate
    # continuations can repeat tokens, matching before position 3)
    cut = next(i + 1 for i in range(len(cont))
               if tuple(cont[max(0, i + 1 - len(stop)):i + 1]) == stop)
    assert cut < MAX_NEW                        # stop really truncates
    want = ref[:len(prompts[0]) + cut]

    for spec in (None, SpecConfig(draft="ngram", k=K)):
        dep = _build("gpt2", stop_sequences=[stop], max_slots=2,
                     spec_decode=spec)
        outs, stats = _drive(dep, prompts)
        np.testing.assert_array_equal(outs[0], want)
        assert stats["requests"]["finished"] == 1


def test_eos_frees_slots_for_same_wave_refill():
    """3 concurrent requests through 2 paged slots with an eos_id that
    ends some continuations early: freed slots must be refilled from
    the queue in the SAME wave, every caller gets the oracle
    continuation truncated at its own first eos, and the pager ends
    the run with zero blocks in use."""
    prompts = _prompts(seed=9, lens=(3, 7, 4))
    refs = _references("gpt2", prompts)
    # eos = the first generated token of prompt 0 -> that request
    # finishes after one token, freeing its slot almost immediately
    eos = int(refs[0][len(prompts[0])])

    def truncate(p, r):
        cont = list(r[len(p):])
        cut = cont.index(eos) + 1 if eos in cont else len(cont)
        return np.concatenate([p, np.asarray(cont[:cut], p.dtype)])

    dep = _build("gpt2", kv_layout="paged", kv_block_size=16,
                 max_slots=2, eos_id=eos,
                 spec_decode=SpecConfig(draft="ngram", k=K))
    outs, stats = _drive(dep, prompts)
    for p, o, r in zip(prompts, outs, refs):
        np.testing.assert_array_equal(o, truncate(p, r))
    assert stats["requests"]["finished"] == 3
    # 4 requests through 2 slots: mid-flight refill must have happened
    assert stats["max_active_slots"] == 2
    assert stats["kv_cache"]["blocks_in_use"] == 0


# ---------------------------------------------------------------------------
# per-request sampling on the continuous engine
# ---------------------------------------------------------------------------

def test_mixed_sampling_wave_keeps_greedy_rows_exact():
    """One wave mixing default-greedy requests with a per-request
    SamplingParams override: the greedy rows must still match the
    oracle bit for bit, and the sampled row must be a valid in-vocab
    continuation of its own prompt."""
    prompts = _prompts(seed=13, lens=(4, 6))
    sp = SamplingParams(temperature=0.8, top_k=8)
    dep = _build("gpt2", max_slots=2, seed=0)
    outs, stats = _drive(dep, prompts,
                         sampling=[None, sp])
    refs = _references("gpt2", prompts)
    np.testing.assert_array_equal(outs[0], refs[0])
    cfg, *_ = _family_oracle("gpt2")
    sampled = outs[1]
    assert sampled.shape == (len(prompts[1]) + MAX_NEW,)
    np.testing.assert_array_equal(sampled[:len(prompts[1])],
                                  prompts[1])
    assert (sampled[len(prompts[1]):] < cfg.vocab_size).all()
    assert stats["requests"]["finished"] == 2


def test_sampling_and_spec_validation_errors():
    p = np.array([1, 2, 3], np.int32)

    # malformed SpecConfig values fail fast at construction
    with pytest.raises(ValueError):
        SpecConfig(k=0)
    with pytest.raises(ValueError):
        SpecConfig(draft="bogus")
    with pytest.raises(ValueError):
        SpecConfig(draft="bert:nano")
    with pytest.raises(ValueError):
        SpecConfig(ngram_order=0)

    # spec requires the continuous scheduler, and a real SpecConfig
    with pytest.raises(ValueError):
        build_llm_deployment("gpt2", "nano", scheduler="batch",
                             spec_decode=SpecConfig())
    with pytest.raises(ValueError):
        build_llm_deployment("gpt2", "nano", scheduler="continuous",
                             spec_decode="ngram")
    # empty stop sequences are a config bug, not a no-op
    with pytest.raises(ValueError):
        build_llm_deployment("gpt2", "nano",
                             stop_sequences=[[]])

    # the batch scheduler runs one fused generate per micro-batch:
    # per-request overrides are rejected at call time
    batch_dep = build_llm_deployment(
        "gpt2", "nano", max_new_tokens=2, config_overrides=_OVR)
    inst = batch_dep.func_or_class()
    with pytest.raises(ValueError, match="continuous"):
        asyncio.run(inst(p, sampling=SamplingParams(temperature=0.5)))

    # spec bakes ONE sampling config into the verify program
    spec_dep = _build("gpt2", spec_decode=SpecConfig())
    sinst = spec_dep.func_or_class()
    with pytest.raises(ValueError, match="spec_decode"):
        asyncio.run(
            sinst(p, sampling=SamplingParams(temperature=0.5)))

    # non-SamplingParams sampling objects are rejected, not coerced
    plain = _build("gpt2")
    pinst = plain.func_or_class()
    with pytest.raises(ValueError, match="SamplingParams"):
        asyncio.run(pinst(p, sampling={"temperature": 0.5}))


# ---------------------------------------------------------------------------
# jitted-program cache key covers the full sampling/spec config
# ---------------------------------------------------------------------------

def test_jitted_fns_cache_keyed_by_sampling_and_spec():
    """Regression (round-11 satellite): engines differing in top_k /
    top_p / SpecConfig must never alias one compiled program — and a
    bare float temperature (the pre-round-11 call shape) still hits
    the same cache entry as its SamplingParams equivalent."""
    from ray_tpu.models import gpt2_config
    from ray_tpu.models.gpt2_decode import (decode_step, paged_prefill,
                                            prefill, verify_step)
    from ray_tpu.serve.llm import _jitted_engine_fns

    cfg = gpt2_config("nano", **_OVR)

    def fns(sampling, **kw):
        return _jitted_engine_fns(prefill, decode_step, paged_prefill,
                                  cfg, sampling, **kw)

    base = fns(0.0)
    assert fns(0.0) is base                     # cache hit
    assert fns(SamplingParams(temperature=0.0)) is base   # coerced
    assert fns(SamplingParams(temperature=0.7, top_k=2)) \
        is not fns(SamplingParams(temperature=0.7, top_k=4))
    assert fns(SamplingParams(temperature=0.7, top_p=0.9)) \
        is not fns(SamplingParams(temperature=0.7))

    k2 = fns(0.0, spec=SpecConfig(k=2), verify_fn=verify_step)
    k4 = fns(0.0, spec=SpecConfig(k=4), verify_fn=verify_step)
    assert k2 is not base and k4 is not base and k2 is not k4
    assert k2.spec_verify is not None
    assert base.spec_verify is None
    # same spec -> same entry (SpecConfig is hashable by value)
    assert fns(0.0, spec=SpecConfig(k=2), verify_fn=verify_step) is k2


# ---------------------------------------------------------------------------
# bench acceptance: aligned draft amortizes target dispatches
# ---------------------------------------------------------------------------

def test_bench_spec_dispatches_per_token_under_half():
    """The CPU bench criterion from the round-11 issue: with an
    aligned draft at k=4, target dispatches per emitted token must
    drop below 1/2 (the non-spec engine is exactly 1.0) with
    acceptance ~1.0."""
    import bench

    tok_s, stats, dispatches_per_token, n_chips = \
        bench.time_decode_spec(4, prompt_len=16, new_tokens=12,
                               preset="nano", spec_k=4,
                               spec_draft="aligned",
                               config_overrides=_OVR)
    assert tok_s > 0 and n_chips >= 1
    assert stats["spec"]["accept_rate"] == 1.0
    assert dispatches_per_token < 0.5


# ---------------------------------------------------------------------------
# sample_token distribution properties (jit-static top_k / top_p)
# ---------------------------------------------------------------------------

def _batched_logits(row, n=512):
    return jnp.tile(jnp.asarray(row, jnp.float32)[None, :], (n, 1))


def test_sample_token_top_k_restricts_support():
    row = np.array([3.0, 2.5, 1.0, 0.5, -1.0, -2.0, -3.0, -4.0])
    toks = np.asarray(sample_token(_batched_logits(row),
                                   jax.random.PRNGKey(0), 1.0, None,
                                   top_k=2))
    assert set(toks.tolist()) == {0, 1}         # both survive, only both


def test_sample_token_top_p_keeps_smallest_nucleus():
    # probs ~ [0.6, 0.3, 0.1, ...]: mass before token2 is 0.9 >= 0.7,
    # so top_p=0.7 keeps exactly {0, 1} (the top-1 always survives)
    row = np.log(np.array([0.6, 0.3, 0.06, 0.02, 0.02]))
    toks = np.asarray(sample_token(_batched_logits(row),
                                   jax.random.PRNGKey(1), 1.0, None,
                                   top_p=0.7))
    assert set(toks.tolist()) == {0, 1}


def test_sample_token_padded_tail_never_sampled():
    # the padded tail holds the LARGEST logits; the mask must win for
    # greedy and for every filtered sampling combination
    row = np.array([1.0, 0.5, 0.2, 9.0, 9.0, 9.0])
    tail = jnp.asarray([True, True, True, False, False, False])
    greedy = np.asarray(sample_token(jnp.asarray(row, jnp.float32),
                                     None, 0.0, tail))
    assert int(greedy) == 0
    for kw in ({}, {"top_k": 2}, {"top_p": 0.9},
               {"top_k": 4, "top_p": 0.95}):
        toks = np.asarray(sample_token(_batched_logits(row, 256),
                                       jax.random.PRNGKey(2), 1.0,
                                       tail, **kw))
        assert (toks < 3).all()


def test_sample_token_greedy_invariant_to_filters():
    row = np.array([0.1, 2.0, 1.5, -0.5])
    lg = jnp.asarray(row, jnp.float32)
    want = int(np.argmax(row))
    for kw in ({}, {"top_k": 1}, {"top_k": 3}, {"top_p": 0.5}):
        assert int(sample_token(lg, None, 0.0, None, **kw)) == want
