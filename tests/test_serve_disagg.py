"""Disaggregated prefill/decode serving: role-split replica fleets
with block-granular KV handoff (serve/llm.py + serve/router.py).

The correctness bar is the same as every other serve-layer feature:
whatever the fleet splits, stages, or requeues, every caller must get
the bit-identical greedy continuation the dense single-engine oracle
produces — the handoff install reproduces ``paged_prefill``'s exact
post-state (pos = prompt length, start = 0, same filled block rows),
so the first decode step on the receiving replica is the same program
on the same bytes.  Covered here:

- cold traffic through a 1-prefill + 1-decode fleet, both model
  families, fast (same-process device copy) and staged (D2H→H2D host
  hop) handoff paths;
- resident-prefix bypass: a prefix already hot on a decode replica
  routes straight to it (prefix_affinity), skipping the prefill fleet;
- speculative decoding on the decode side of the split;
- chunked streaming prefill on the prefill side (long prompts hand
  off at last-chunk completion);
- handoff pool exhaustion: a decode pool too small for the arriving
  package requeues (push-front) and completes once blocks free, still
  bit-identical;
- construction-time validation and the traffic-harness report keys.
"""

import asyncio

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from ray_tpu.serve.llm import (SpecConfig,
                               build_llm_deployment)  # noqa: E402
from ray_tpu.serve.router import build_llm_fleet  # noqa: E402

MAX_NEW = 6
_OVR = {"dtype": jnp.float32, "use_flash": False, "remat": False}
_ENGINE_KW = dict(max_new_tokens=MAX_NEW, temperature=0.0,
                  kv_block_size=16, prefill_bucket=16, max_slots=2,
                  config_overrides=_OVR)


def _fleet(name, family="gpt2", **kw):
    kw = {**_ENGINE_KW, **kw}
    kw.setdefault("num_prefill_replicas", 1)
    kw.setdefault("num_decode_replicas", 1)
    return build_llm_fleet(family, "nano", fleet_name=name, **kw)


def _oracle(family, prompt, max_new=MAX_NEW):
    """Dense solo greedy continuation — the parity reference."""
    if family == "gpt2":
        from ray_tpu.models import gpt2_config, gpt2_init
        from ray_tpu.models.gpt2_decode import generate
        cfg = gpt2_config("nano", **_OVR)
        params = gpt2_init(jax.random.PRNGKey(0), cfg)
    else:
        from ray_tpu.models import llama_config, llama_init
        from ray_tpu.models.llama_decode import llama_generate \
            as generate
        cfg = llama_config("nano", **_OVR)
        params = llama_init(jax.random.PRNGKey(0), cfg)
    out = generate(params, jnp.asarray(np.asarray(prompt)[None]), cfg,
                   max_new_tokens=max_new, temperature=0.0)
    return np.asarray(out)[0]


def _prompts(lens, seed=0):
    rng = np.random.RandomState(seed)
    return [rng.randint(2, 500, n).astype(np.int32) for n in lens]


def _drive(fleet, prompts, tenant=None, timeout=300):
    """All prompts concurrently through the fleet; fleet_stats and
    per-role engine stats taken before shutdown so handoff counters
    and roles are live."""
    async def main():
        try:
            outs = await asyncio.wait_for(
                asyncio.gather(*[fleet(p, tenant=tenant)
                                 for p in prompts]), timeout)
            by_role = {r.role: r.engine_stats()
                       for r in fleet.router.live_replicas}
            return outs, fleet.fleet_stats(), by_role
        finally:
            fleet.shutdown()

    return asyncio.run(main())


def _assert_oracle(family, prompts, outs):
    for p, o in zip(prompts, outs):
        np.testing.assert_array_equal(np.asarray(o),
                                      _oracle(family, p))


# ---------------------------------------------------------------------------
# cold traffic, both families, both handoff paths
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("family", ["gpt2", "llama"])
def test_disagg_cold_matches_oracle(family):
    """Block-boundary-crossing prompt mix through a 1p+1d fleet: every
    request routes prefill-first, hands its blocks to the decode
    replica over the fast path, and lands the oracle continuation."""
    prompts = _prompts([7, 19, 33, 12])
    fleet = _fleet(f"t_disagg_{family}", family=family)
    outs, st, by_role = _drive(fleet, prompts)
    _assert_oracle(family, prompts, outs)

    assert st["router"]["disaggregated"] is True
    assert st["router"]["routed_by_policy"]["disagg_prefill"] == 4
    assert st["router"]["handoffs"] == 4
    hoff = st["handoff"]
    assert hoff["handoffs_out"] == 4 and hoff["handoffs_in"] == 4
    assert hoff["fast_path"] == 4 and hoff["staged"] == 0
    # blocks actually moved: ceil(len/16) summed over the mix
    assert hoff["blocks_moved"] == sum(-(-n // 16)
                                       for n in (7, 19, 33, 12))
    roles = {name: rep["role"]
             for name, rep in st["replicas"].items()}
    assert sorted(roles.values()) == ["decode", "prefill"]
    # per-role occupancy pooled for the kvscope observatory
    assert set(st["kv_scope"]["occupancy_by_role"]) == {"prefill",
                                                        "decode"}


def test_disagg_staged_path_matches_oracle():
    """handoff_staged=True forces the D2H→H2D host-staging hop (the
    cross-process wire path) — byte-for-byte the same splice."""
    prompts = _prompts([7, 19, 33, 12], seed=3)
    fleet = _fleet("t_disagg_staged", handoff_staged=True)
    outs, st, by_role = _drive(fleet, prompts)
    _assert_oracle("gpt2", prompts, outs)
    assert st["handoff"]["staged"] == 4
    assert st["handoff"]["fast_path"] == 0


# ---------------------------------------------------------------------------
# resident prefix skips the prefill fleet entirely
# ---------------------------------------------------------------------------

def test_disagg_resident_prefix_routes_straight_to_decode():
    """Once a shared prefix is resident on a decode replica, the
    router's stage-one check sends the request straight there —
    no prefill admission, no handoff — and the continuation is still
    the oracle's."""
    rng = np.random.RandomState(11)
    prefix = rng.randint(2, 500, 32)
    wave1 = [np.concatenate([prefix, rng.randint(2, 500, 3)])
             .astype(np.int32) for _ in range(2)]
    wave2 = [np.concatenate([prefix, rng.randint(2, 500, 4)])
             .astype(np.int32) for _ in range(2)]
    fleet = _fleet("t_disagg_prefix", routing="prefix")

    async def main():
        try:
            o1 = [await fleet(p) for p in wave1]
            o2 = [await fleet(p) for p in wave2]
            return o1, o2, fleet.fleet_stats()
        finally:
            fleet.shutdown()

    o1, o2, st = asyncio.run(main())
    _assert_oracle("gpt2", wave1, o1)
    _assert_oracle("gpt2", wave2, o2)
    by_policy = st["router"]["routed_by_policy"]
    # only the cold first request pays the prefill→handoff hop; once
    # its two full prefix blocks are resident on the decode replica,
    # every later sharer routes straight there
    assert by_policy["disagg_prefill"] >= 1
    assert by_policy["prefix_affinity"] >= 3
    assert st["handoff"]["handoffs_in"] < len(wave1) + len(wave2)


# ---------------------------------------------------------------------------
# decode-side speculative decoding + prefill-side chunked streaming
# ---------------------------------------------------------------------------

def test_disagg_spec_decode_matches_oracle():
    """spec_decode applies to the decode fleet only (drafting is
    decode-side work): the verify loop starts from the handed-off
    state and greedy outputs stay oracle-identical."""
    prompts = _prompts([9, 21, 33], seed=5)
    fleet = _fleet("t_disagg_spec",
                   spec_decode=SpecConfig(draft="ngram", k=2))
    outs, st, by_role = _drive(fleet, prompts)
    _assert_oracle("gpt2", prompts, outs)
    assert by_role["decode"]["spec"]["rounds"] > 0
    assert by_role["prefill"]["spec"]["rounds"] == 0


def test_disagg_chunked_long_prompts_match_oracle():
    """Long prompts admitted chunk-by-chunk on the prefill replica
    hand off at last-chunk completion — the package carries the chunk
    windows, and the splice is still bit-exact."""
    prompts = _prompts([70, 96, 50], seed=7)
    fleet = _fleet("t_disagg_chunk", prefill_bucket=32,
                   prefill_engine_kw={"prefill_chunk_tokens": 32})
    outs, st, by_role = _drive(fleet, prompts)
    _assert_oracle("gpt2", prompts, outs)
    assert st["handoff"]["handoffs_in"] == 3
    assert by_role["prefill"]["prefill_chunks"]["requests"] >= 2
    assert by_role["decode"]["prefill_chunks"]["requests"] == 0


# ---------------------------------------------------------------------------
# handoff pool exhaustion requeues, then completes
# ---------------------------------------------------------------------------

def test_disagg_handoff_pool_exhaustion_requeues():
    """A decode pool with room for one resident request at a time:
    concurrent handoffs collide on allocation, requeue (push-front,
    never dropped), and every request still finishes bit-identical."""
    prompts = _prompts([65, 67, 66, 68], seed=9)
    # 5 blocks per request (65-68 prompt + 6 new <= 80 tokens); the
    # smallest legal pool (8 usable + null sink) fits one resident
    # request at a time but never two
    fleet = _fleet("t_disagg_requeue",
                   decode_engine_kw={"kv_num_blocks": 9})
    outs, st, by_role = _drive(fleet, prompts)
    _assert_oracle("gpt2", prompts, outs)
    hoff = st["handoff"]
    assert hoff["handoffs_in"] == 4
    assert hoff["requeues"] >= 1


# ---------------------------------------------------------------------------
# construction-time validation
# ---------------------------------------------------------------------------

def test_disagg_validation_errors():
    with pytest.raises(ValueError, match="BOTH"):
        build_llm_fleet("gpt2", "nano", num_prefill_replicas=1,
                        **_ENGINE_KW)
    with pytest.raises(ValueError, match="kv_block_size must match"):
        build_llm_fleet("gpt2", "nano", num_prefill_replicas=1,
                        num_decode_replicas=1,
                        decode_engine_kw={"kv_block_size": 32},
                        **_ENGINE_KW)
    with pytest.raises(ValueError, match="role"):
        build_llm_deployment("gpt2", "nano", scheduler="continuous",
                             kv_layout="paged", role="oracle")
    with pytest.raises(ValueError, match="paged"):
        build_llm_deployment("gpt2", "nano", scheduler="continuous",
                             kv_layout="dense", role="prefill")
    with pytest.raises(ValueError, match="split roles"):
        build_llm_deployment("gpt2", "nano", scheduler="continuous",
                             kv_layout="paged", handoff_staged=True)


def test_admit_prefilled_rejected_on_prefill_replica():
    dep = build_llm_deployment(
        "gpt2", "nano", scheduler="continuous", kv_layout="paged",
        role="prefill", **_ENGINE_KW)
    inst = dep.func_or_class()
    try:
        with pytest.raises(ValueError, match="decode-capable"):
            asyncio.run(inst.admit_prefilled(object()))
    finally:
        inst.shutdown_engine()


# ---------------------------------------------------------------------------
# traffic harness surfaces the disagg report keys
# ---------------------------------------------------------------------------

def test_traffic_disagg_report_keys():
    from ray_tpu.serve.traffic import (TenantSpec, TrafficSpec,
                                       run_traffic_fleet)

    tenants = (
        TenantSpec("interactive", rate_share=1.0,
                   slo_class="interactive", prefix_groups=(0,)),
        TenantSpec("batch", rate_share=1.0, slo_class="batch",
                   prefix_groups=(1,)))
    spec = TrafficSpec(num_requests=8, seed=0, rate_rps=100.0,
                       num_prefix_groups=2, prefix_len=32,
                       p_shared=0.5, tail_len_mean=6.0,
                       tail_len_max=16, vocab=500, tenants=tenants)
    rep = run_traffic_fleet(
        spec, num_replicas=1, num_prefill_replicas=1,
        num_decode_replicas=1, family="gpt2", preset="nano",
        kv_block_size=16, max_slots=2, max_new_tokens=4,
        prefill_bucket=16, time_scale=0.0,
        config_overrides={"dtype": jnp.float32, "use_flash": False})
    assert rep["num_prefill_replicas"] == 1
    assert rep["num_decode_replicas"] == 1
    assert rep["handoff_staged"] is False
    assert rep["completed"] + rep["shed"] == rep["offered"]
    assert rep["handoff"]["handoffs_in"] > 0
    assert isinstance(rep["handoff_ms_p99"], (int, float))
    # flattened per-role pool-pressure lines for the sweep record
    for key in ("prefill_kv_occupancy_mean", "prefill_kv_occupancy_p95",
                "decode_kv_occupancy_mean", "decode_kv_occupancy_p95"):
        assert key in rep, key
    # decode pools carry the steady-state residency; prefill pools
    # drain at handoff
    assert rep["decode_kv_occupancy_mean"] >= 0.0
