"""Regression tests for the round-3 advisor findings (ADVICE.md r3)."""

import time

import numpy as np
import pytest

import ray_tpu

pytestmark = pytest.mark.fast


def test_async_actor_sync_methods_serialize(ray_start_shared):
    """An actor auto-detected as async (has a coroutine method) must
    still run its SYNC methods one at a time — auto-raised concurrency
    applies only to coroutine methods (reference: sync methods of an
    async actor execute on the event loop and serialize)."""

    @ray_tpu.remote
    class Counter:
        def __init__(self):
            self.v = 0

        async def poke(self):  # makes the class auto-async
            return "async"

        def incr(self):
            # read-modify-write with a sleep in the window: races lose
            # increments unless calls serialize
            v = self.v
            time.sleep(0.005)
            self.v = v + 1
            return self.v

        def get(self):
            return self.v

    c = Counter.remote()
    refs = [c.incr.remote() for _ in range(20)]
    ray_tpu.get(refs)
    assert ray_tpu.get(c.get.remote()) == 20
    # the coroutine method still works concurrently with sync ones
    assert ray_tpu.get(c.poke.remote()) == "async"


def test_generate_rejects_overlong_output():
    import jax
    import jax.numpy as jnp
    from ray_tpu.models import gpt2_config, gpt2_init
    from ray_tpu.models.gpt2_decode import generate

    cfg = gpt2_config("nano", dtype=jnp.float32, use_flash=False,
                      remat=False, max_seq=32)
    params = gpt2_init(jax.random.PRNGKey(0), cfg)
    prompt = np.zeros((1, 20), np.int32)
    with pytest.raises(ValueError, match="max_seq"):
        generate(params, jnp.asarray(prompt), cfg, max_new_tokens=20)


def test_zero_copy_span_matching_rejects_hidden_view(ray_start_shared):
    """ADVICE r3: a custom reducer that rebuilds TWO distinct views over
    one out-of-band buffer satisfies ``len(arrays) >= n_oob`` while a
    second buffer's only view hides inside an opaque object — a
    count-based check would release the shm pin with that hidden view
    live.  Span matching (one array per buffer) must detect the
    mismatch and take the copy path, keeping the hidden view valid."""
    from tests import _zero_copy_helpers as zh

    # >100KB each so the object lands in the shm store (smaller values
    # inline into the memory store and never reach the zero-copy path)
    a = np.arange(32768, dtype=np.float64)
    b = np.arange(32768, dtype=np.float64) * 2
    # value: TwoViews visibly splits a's single oob buffer into two
    # arrays; Hider keeps b's only view opaque to the shallow walk
    ref = ray_tpu.put({"tv": zh.TwoViews(a), "h": zh.Hider(b)})
    out = ray_tpu.get(ref)
    v1, v2 = out["tv"]
    np.testing.assert_array_equal(np.concatenate([v1, v2]), a)
    hidden = out["h"].arr
    np.testing.assert_array_equal(hidden, b)
    # drop every visible array, churn the arena, then re-check the
    # hidden view: if the pin was released early this reads garbage
    del out, v1, v2, ref
    import gc
    gc.collect()
    for i in range(8):
        ray_tpu.get(ray_tpu.put(
            np.arange(65536, dtype=np.float64) + i))
    np.testing.assert_array_equal(hidden, b)


def test_multiagent_absent_agent_bootstraps_with_value():
    """Inactive-but-alive agents (turn-based envs) must bootstrap with a
    value estimate, not 0.0, at the fragment boundary."""
    from ray_tpu.rllib.multi_agent import MultiAgentRolloutWorker
    from ray_tpu.rllib.policy import PolicySpec

    class TurnEnv:
        """Two agents alternate; obs dict only contains the mover."""

        def __init__(self, cfg=None):
            self.t = 0

        def reset(self, seed=None):
            self.t = 0
            return {"a0": np.zeros(4, np.float32)}, {}

        def step(self, actions):
            self.t += 1
            agent = f"a{self.t % 2}"
            obs = {agent: np.full(4, self.t, np.float32)}
            rews = {k: 1.0 for k in actions}
            return obs, rews, {"__all__": False}, {"__all__": False}, {}

    specs = {"shared": PolicySpec(obs_dim=4, n_actions=2, hidden=(8,))}
    w = MultiAgentRolloutWorker(
        env_creator=TurnEnv, env_config={}, policy_specs=specs,
        policy_mapping_fn=lambda aid: "shared", gamma=0.99, lam=0.95,
        rollout_fragment_length=5, seed=0)
    batches = w.sample()  # a1 is absent from the final obs dict
    assert "shared" in batches
    # the flush path must not crash and must produce aligned columns
    bat = batches["shared"]
    assert len(bat["obs"]) == len(bat["advantages"])
