"""R2D2 (recurrent replay DQN) and CRR (offline advantage-weighted
regression).

Reference analogs: rllib/algorithms/r2d2 and rllib/algorithms/crr —
learning checks follow the check_learning_achieved pattern scaled to CI
(rllib/utils/test_utils.py:480).
"""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.rllib import (CRR, CRRConfig, JsonWriter, R2D2, R2D2Config,
                           SampleBatch)
from ray_tpu.rllib import sample_batch as sb


# ---------------------------------------------------------------------------
# R2D2
# ---------------------------------------------------------------------------

class _MemoryEnv:
    """A cue appears only at t=0 (obs = [±1, phase...]); acting on the
    final step is rewarded iff the action matches the initial cue's
    sign.  Feedforward Q is chance (reward 0.5/episode expected);
    only a recurrent policy can carry the cue to the decision step."""

    LEN = 5

    class _Space:
        def __init__(self, shape=None, n=None):
            self.shape = shape
            self.n = n

    def __init__(self, seed=0):
        self.observation_space = self._Space(shape=(2,))
        self.action_space = self._Space(n=2)
        self._rng = np.random.RandomState(seed)

    def reset(self, seed=None, options=None):
        if seed is not None:
            self._rng = np.random.RandomState(seed)
        self._cue = int(self._rng.randint(2))
        self._t = 0
        return np.asarray([1.0 if self._cue else -1.0, 0.0],
                          np.float32), {}

    def step(self, action):
        self._t += 1
        done = self._t >= self.LEN
        r = 0.0
        if done and int(action) == self._cue:
            r = 1.0
        # post-cue observations carry only the phase, never the cue
        obs = np.asarray([0.0, self._t / self.LEN], np.float32)
        return obs, r, done, False, {}


def test_r2d2_validates_burn_in():
    with pytest.raises(ValueError, match="burn_in"):
        R2D2(R2D2Config(obs_dim=2, n_actions=2, seq_len=4, burn_in=4))


def test_r2d2_learns_memory_env(ray_start_shared):
    cfg = R2D2Config(env=lambda _: _MemoryEnv(), num_workers=1,
                     hidden=(32,), lstm_cell_size=32, seq_len=6,
                     burn_in=0, buffer_size=2000, learning_starts=32,
                     train_batch_size=32, train_intensity=8,
                     target_update_freq=400, epsilon_decay_steps=3000,
                     rows_per_sample=16, lr=2e-3, gamma=0.9, seed=0)
    algo = R2D2(cfg)
    best = -np.inf
    try:
        for _ in range(30):
            result = algo.train()
            best = max(best, result.get("episode_reward_mean", -np.inf))
            if best >= 0.9:
                break
    finally:
        algo.stop()
    # memoryless play scores ~0.5; recurrent Q should approach 1.0
    assert best >= 0.8, best


def test_r2d2_burn_in_changes_only_warmup():
    # with burn_in=2 the first two steps contribute no TD loss terms:
    # constructing identical sequences with garbage in the burn-in
    # prefix must produce the same loss as clean ones
    from ray_tpu.rllib.r2d2 import (R2D2Policy, R2D2Spec, SEQ_C0,
                                    SEQ_H0, SEQ_MASK)
    import jax.numpy as jnp

    spec = R2D2Spec(obs_dim=2, n_actions=2, hidden=(8,), cell=8,
                    seq_len=4, burn_in=2, gamma=0.9)
    pol = R2D2Policy(spec, seed=0)
    rng = np.random.RandomState(0)
    base = {
        sb.OBS: rng.randn(1, 3, 5, 2).astype(np.float32),
        sb.ACTIONS: rng.randint(0, 2, (1, 3, 4)).astype(np.int32),
        sb.REWARDS: rng.randn(1, 3, 4).astype(np.float32),
        sb.DONES: np.zeros((1, 3, 4), bool),
        SEQ_MASK: np.ones((1, 3, 4), np.float32),
        SEQ_H0: np.zeros((1, 3, 8), np.float32),
        SEQ_C0: np.zeros((1, 3, 8), np.float32),
    }
    # rewards/actions inside the burn-in window are ignored by the loss
    messy = {k: np.copy(v) for k, v in base.items()}
    messy[sb.REWARDS][:, :, :2] = 99.0
    p0, o0 = pol.params, pol.opt_state
    pol.params, pol.opt_state = p0, o0
    _, _, l_base = pol._update(p0, o0, pol.target,
                               {k: jnp.asarray(v) for k, v in
                                base.items()})
    _, _, l_messy = pol._update(p0, o0, pol.target,
                                {k: jnp.asarray(v) for k, v in
                                 messy.items()})
    np.testing.assert_allclose(float(l_base), float(l_messy), rtol=1e-6)


# ---------------------------------------------------------------------------
# CRR
# ---------------------------------------------------------------------------

class _PointEnv:
    """1-D point control: state x, action pushes it; reward -(x^2)."""

    class _Space:
        def __init__(self, shape=None, n=None):
            self.shape = shape
            self.n = n

    def __init__(self, seed=0):
        self.observation_space = self._Space(shape=(1,))
        self.action_space = self._Space(shape=(1,))
        self._rng = np.random.RandomState(seed)

    def reset(self, seed=None, options=None):
        if seed is not None:
            self._rng = np.random.RandomState(seed)
        self._x = self._rng.uniform(-2, 2, size=1).astype(np.float32)
        self._t = 0
        return self._x.copy(), {}

    def step(self, a):
        self._x = np.clip(self._x + 0.5 * np.asarray(a).ravel(), -3, 3)
        self._t += 1
        r = float(-(self._x[0] ** 2))
        return self._x.copy().astype(np.float32), r, self._t >= 30, \
            False, {}


def _log_point(path, n=1500, seed=2):
    rng = np.random.RandomState(seed)
    env = _PointEnv(seed=seed)
    obs_l, act_l, rew_l, done_l, next_l = [], [], [], [], []
    o, _ = env.reset(seed=seed)
    for _ in range(n):
        a = np.clip(-0.7 * o + 0.3 * rng.randn(1), -1, 1)
        o2, r, term, trunc, _ = env.step(a)
        obs_l.append(o)
        act_l.append(a.astype(np.float32))
        rew_l.append(r)
        done_l.append(term)
        next_l.append(o2)
        o = o2
        if term or trunc:
            o, _ = env.reset()
    with JsonWriter(str(path)) as w:
        w.write(SampleBatch({
            sb.OBS: np.asarray(obs_l, np.float32),
            sb.ACTIONS: np.asarray(act_l, np.float32),
            sb.REWARDS: np.asarray(rew_l, np.float32),
            sb.DONES: np.asarray(done_l, bool),
            sb.NEXT_OBS: np.asarray(next_l, np.float32)}))


@pytest.mark.parametrize("mode", ["bin", "exp"])
def test_crr_trains_offline(ray_start_shared, tmp_path, mode):
    log = tmp_path / "cont.json"
    _log_point(log)
    algo = CRR(CRRConfig(input_path=str(log), hidden=(32, 32),
                         sgd_steps_per_iter=100, lr=1e-3,
                         weight_mode=mode, seed=0))
    stats = None
    for _ in range(10):
        stats = algo.train()
    assert np.isfinite(stats["critic_loss"])
    assert 0.0 <= stats["mean_weight"], stats
    # the learned policy pushes the point toward 0
    obs = np.asarray([[1.5], [-1.5]], np.float32)
    acts = algo.compute_actions(obs)
    assert acts[0, 0] < 0 and acts[1, 0] > 0, acts


def test_crr_rejects_bad_mode(tmp_path):
    log = tmp_path / "x.json"
    _log_point(log, n=50)
    with pytest.raises(ValueError, match="weight_mode"):
        CRR(CRRConfig(input_path=str(log), weight_mode="nope"))
