"""KV-cache decode: per-step logits must match the full forward pass
(teacher forcing), and generate() must be deterministic/greedy-correct.
"""

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.models import gpt2_config, gpt2_forward, gpt2_init
from ray_tpu.models.gpt2_decode import decode_step, generate, init_cache


def _cfg():
    # float32 end-to-end so decode-vs-forward comparison is exact-ish
    return gpt2_config("nano", dtype=jnp.float32, use_flash=False,
                       remat=False)


def test_decode_matches_full_forward():
    cfg = _cfg()
    params = gpt2_init(jax.random.PRNGKey(0), cfg)
    B, T = 2, 12
    toks = jax.random.randint(jax.random.PRNGKey(1), (B, T), 0,
                              cfg.vocab_size)
    full = gpt2_forward(params, toks, cfg)          # (B, T, V)

    cache = init_cache(cfg, B)
    step = jax.jit(lambda c, t: decode_step(params, c, t, cfg))
    for t in range(T):
        logits, cache = step(cache, toks[:, t])
        np.testing.assert_allclose(np.asarray(logits),
                                   np.asarray(full[:, t]), rtol=2e-4,
                                   atol=2e-4)
    assert int(cache["pos"]) == T


def test_generate_greedy_is_argmax_chain():
    cfg = _cfg()
    params = gpt2_init(jax.random.PRNGKey(0), cfg)
    prompt = jnp.array([[1, 2, 3]], dtype=jnp.int32)
    out = generate(params, prompt, cfg, max_new_tokens=4,
                   temperature=0.0)
    assert out.shape == (1, 7)
    # greedy chain must match step-by-step argmax over the full forward
    seq = prompt
    for _ in range(4):
        logits = gpt2_forward(params, seq, cfg)[:, -1, :cfg.vocab_size]
        nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)[:, None]
        seq = jnp.concatenate([seq, nxt], axis=1)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(seq))
    # sampled tokens stay inside the true vocab (padded tail masked)
    out2 = generate(params, prompt, cfg, max_new_tokens=8,
                    temperature=1.0, key=jax.random.PRNGKey(7))
    assert int(out2.max()) < cfg.vocab_size
