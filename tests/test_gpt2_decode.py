"""KV-cache decode: per-step logits must match the full forward pass
(teacher forcing), generate() must be deterministic/greedy-correct, and
the batched single-dispatch prefill must reproduce the per-token scan
reference token for token (incl. ragged left-padded batches).
"""

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.models import gpt2_config, gpt2_forward, gpt2_init
from ray_tpu.models.gpt2_decode import (decode_step, generate,
                                        init_cache, prefill)


def _cfg():
    # float32 end-to-end so decode-vs-forward comparison is exact-ish
    return gpt2_config("nano", dtype=jnp.float32, use_flash=False,
                       remat=False)


def test_decode_matches_full_forward():
    cfg = _cfg()
    params = gpt2_init(jax.random.PRNGKey(0), cfg)
    B, T = 2, 12
    toks = jax.random.randint(jax.random.PRNGKey(1), (B, T), 0,
                              cfg.vocab_size)
    full = gpt2_forward(params, toks, cfg)          # (B, T, V)

    cache = init_cache(cfg, B)
    step = jax.jit(lambda c, t: decode_step(params, c, t, cfg))
    for t in range(T):
        logits, cache = step(cache, toks[:, t])
        np.testing.assert_allclose(np.asarray(logits),
                                   np.asarray(full[:, t]), rtol=2e-4,
                                   atol=2e-4)
    np.testing.assert_array_equal(np.asarray(cache["pos"]),
                                  np.full((B,), T, np.int32))


def test_prefill_matches_stepwise_cache():
    # one batched prefill dispatch must leave the same K/V + logits as
    # T0 sequential decode steps
    cfg = _cfg()
    params = gpt2_init(jax.random.PRNGKey(0), cfg)
    B, T = 2, 9
    toks = jax.random.randint(jax.random.PRNGKey(2), (B, T), 0,
                              cfg.vocab_size)
    logits_b, cache_b = prefill(params, toks, cfg)

    cache_s = init_cache(cfg, B)
    for t in range(T):
        logits_s, cache_s = decode_step(params, cache_s, toks[:, t],
                                        cfg)
    np.testing.assert_allclose(np.asarray(logits_b),
                               np.asarray(logits_s), rtol=2e-4,
                               atol=2e-4)
    np.testing.assert_array_equal(np.asarray(cache_b["pos"]),
                                  np.asarray(cache_s["pos"]))
    np.testing.assert_allclose(np.asarray(cache_b["k"][:, :, :T]),
                               np.asarray(cache_s["k"][:, :, :T]),
                               rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(np.asarray(cache_b["v"][:, :, :T]),
                               np.asarray(cache_s["v"][:, :, :T]),
                               rtol=2e-4, atol=2e-4)


def test_generate_greedy_is_argmax_chain():
    cfg = _cfg()
    params = gpt2_init(jax.random.PRNGKey(0), cfg)
    prompt = jnp.array([[1, 2, 3]], dtype=jnp.int32)
    out = generate(params, prompt, cfg, max_new_tokens=4,
                   temperature=0.0)
    assert out.shape == (1, 7)
    # greedy chain must match step-by-step argmax over the full forward
    seq = prompt
    for _ in range(4):
        logits = gpt2_forward(params, seq, cfg)[:, -1, :cfg.vocab_size]
        nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)[:, None]
        seq = jnp.concatenate([seq, nxt], axis=1)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(seq))
    # sampled tokens stay inside the true vocab (padded tail masked)
    out2 = generate(params, prompt, cfg, max_new_tokens=8,
                    temperature=1.0, key=jax.random.PRNGKey(7))
    assert int(out2.max()) < cfg.vocab_size


def test_batched_prefill_parity_with_scan_reference():
    # greedy outputs must be token-for-token identical between the
    # batched prefill and the old per-token scan prefill
    cfg = _cfg()
    params = gpt2_init(jax.random.PRNGKey(0), cfg)
    prompt = jax.random.randint(jax.random.PRNGKey(3), (3, 10), 0,
                                cfg.vocab_size)
    out_b = generate(params, prompt, cfg, max_new_tokens=6,
                     temperature=0.0, prefill_impl="batched")
    out_s = generate(params, prompt, cfg, max_new_tokens=6,
                     temperature=0.0, prefill_impl="scan")
    np.testing.assert_array_equal(np.asarray(out_b), np.asarray(out_s))


def test_ragged_batch_matches_per_row_generation():
    # a LEFT-padded ragged batch must decode each row exactly as if it
    # were generated alone (per-slot masks keep pad K/V unread)
    cfg = _cfg()
    params = gpt2_init(jax.random.PRNGKey(0), cfg)
    rng = np.random.RandomState(0)
    lens = [3, 7, 5]
    t0 = max(lens)
    rows = [rng.randint(1, cfg.vocab_size, (n,)).astype(np.int32)
            for n in lens]
    padded = np.zeros((len(lens), t0), np.int32)
    for i, r in enumerate(rows):
        padded[i, t0 - lens[i]:] = r
    out = generate(params, jnp.asarray(padded), cfg, max_new_tokens=5,
                   temperature=0.0, lengths=jnp.asarray(lens, jnp.int32))
    for i, r in enumerate(rows):
        ref = generate(params, jnp.asarray(r[None], jnp.int32), cfg,
                       max_new_tokens=5, temperature=0.0)
        np.testing.assert_array_equal(
            np.asarray(out)[i, t0 - lens[i]:], np.asarray(ref)[0])
