"""Workflow: durable DAGs, events, and the management surface
(reference analogs: workflow/api.py run/resume/resume_all/get_status/
cancel:468, workflow/event_listener.py, http_event_provider.py)."""

import os
import threading
import time

import pytest

import ray_tpu
from ray_tpu import workflow


def _count_file(tmp_path, name="attempts"):
    return str(tmp_path / name)


def test_run_and_durable_resume(ray_start_shared, tmp_path):
    storage = str(tmp_path / "wf")
    marker = _count_file(tmp_path)

    @workflow.step
    def base():
        with open(marker, "a") as f:
            f.write("x")
        return 10

    @workflow.step
    def double(x):
        return x * 2

    dag = double.step(base.step())
    assert workflow.run(dag, workflow_id="w1", storage=storage) == 20
    assert workflow.get_status("w1", storage=storage) == "SUCCEEDED"
    assert workflow.get_output("w1", storage=storage) == 20
    # resume without rebuilding the dag: loads the persisted DAG and
    # short-circuits every completed step (base must NOT re-execute)
    assert workflow.resume(workflow_id="w1", storage=storage) == 20
    assert open(marker).read() == "x"


def test_step_retries(ray_start_shared, tmp_path):
    storage = str(tmp_path / "wf")
    marker = _count_file(tmp_path)

    @workflow.step(max_retries=3, retry_delay_s=0.01)
    def flaky():
        with open(marker, "a") as f:
            f.write("x")
        if len(open(marker).read()) < 3:
            raise RuntimeError("transient")
        return "ok"

    assert workflow.run(flaky.step(), workflow_id="wr",
                        storage=storage) == "ok"
    assert len(open(marker).read()) == 3


def test_retries_exhausted_fails_workflow(ray_start_shared, tmp_path):
    storage = str(tmp_path / "wf")

    @workflow.step(max_retries=1, retry_delay_s=0.01)
    def always_fails():
        raise ValueError("permanent")

    with pytest.raises(Exception, match="permanent"):
        workflow.run(always_fails.step(), workflow_id="wf_fail",
                     storage=storage)
    assert workflow.get_status("wf_fail", storage=storage) == "FAILED"


def test_event_gated_workflow_and_crash_resume(ray_start_shared, tmp_path):
    storage = str(tmp_path / "wf")

    @workflow.step
    def combine(ev, tag):
        return (tag, ev)

    dag = combine.step(
        workflow.wait_for_event("go", timeout_s=60.0), "done")

    def poster():
        time.sleep(1.0)
        workflow.post_event("go", {"k": 41})

    t = threading.Thread(target=poster)
    t.start()
    result = workflow.run(dag, workflow_id="we", storage=storage)
    t.join()
    assert result == ("done", {"k": 41})

    # simulate a crash AFTER the event landed but before the sink step:
    # drop the sink step's stored result, clear the event, and resume —
    # the wait step's value must come from storage, not a fresh wait
    # (which would time out: the event is gone).
    workflow.clear_event("go")
    steps_dir = os.path.join(storage, "we", "steps")
    for f in os.listdir(steps_dir):
        if f.startswith("combine"):
            os.unlink(os.path.join(steps_dir, f))
    meta_story = workflow.get_status("we", storage=storage)
    assert meta_story == "SUCCEEDED"
    ev_listener = workflow.KVEventListener(timeout_s=3.0)
    assert workflow.resume(workflow_id="we",
                           storage=storage) == ("done", {"k": 41})


def test_wait_for_event_default_listener_signature():
    s = workflow.wait_for_event("chan", timeout_s=5.0)
    s2 = workflow.wait_for_event("chan", timeout_s=5.0)
    assert s.step_id() == s2.step_id()  # deterministic identity
    s3 = workflow.wait_for_event("other", timeout_s=5.0)
    assert s3.step_id() != s.step_id()


def test_cancel_preempts_event_wait(ray_start_shared, tmp_path):
    storage = str(tmp_path / "wf")
    dag = workflow.wait_for_event("never", timeout_s=300.0)
    errs = []

    def run_wf():
        try:
            workflow.run(dag, workflow_id="wc", storage=storage)
        except BaseException as e:  # noqa: BLE001
            errs.append(e)

    t = threading.Thread(target=run_wf)
    t.start()
    time.sleep(1.5)  # let the wait step start
    workflow.cancel("wc", storage=storage)
    t.join(timeout=30)
    assert not t.is_alive(), "cancel did not preempt the event wait"
    assert errs and isinstance(errs[0], workflow.WorkflowCancelledError)
    assert workflow.get_status("wc", storage=storage) == "CANCELED"


def test_resume_all(ray_start_shared, tmp_path):
    storage = str(tmp_path / "wf")

    @workflow.step
    def val(x):
        return x + 1

    workflow.run(val.step(1), workflow_id="done1", storage=storage)
    # two crashed runs: status left RUNNING on disk
    for wid, x in (("crashed1", 10), ("crashed2", 20)):
        try:
            workflow.run(val.step(x), workflow_id=wid, storage=storage)
        finally:
            pass
        # rewind status to RUNNING to simulate a mid-run crash
        from ray_tpu.workflow.api import _Storage

        st = _Storage(storage, wid)
        meta = st.read_meta()
        meta["status"] = "RUNNING"
        st.write_meta(meta)
    out = workflow.resume_all(storage=storage)
    assert set(out) == {"crashed1", "crashed2"}
    assert out["crashed1"] == 11 and out["crashed2"] == 21
    assert workflow.get_status("crashed1", storage=storage) == "SUCCEEDED"


def test_delete_and_list(ray_start_shared, tmp_path):
    storage = str(tmp_path / "wf")

    @workflow.step
    def one():
        return 1

    workflow.run(one.step(), workflow_id="d1", storage=storage)
    assert [m["workflow_id"] for m in workflow.list_all(storage)] == ["d1"]
    workflow.delete("d1", storage=storage)
    assert workflow.list_all(storage) == []


def test_timer_listener(ray_start_shared, tmp_path):
    storage = str(tmp_path / "wf")
    t0 = time.time()
    dag = workflow.wait_for_event(workflow.TimerListener, 0.5)
    fired_at = workflow.run(dag, workflow_id="wt", storage=storage)
    assert fired_at >= t0 + 0.5
