"""LLaMA-family decoder: RoPE / RMSNorm / SwiGLU / GQA numerics.

Reference analog: none in the reference framework (it ships no models);
architecture per the public llama lineage.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from ray_tpu.models import (llama_config, llama_forward, llama_init,
                            llama_logical_axes, llama_loss,
                            llama_param_count)
from ray_tpu.models.llama import apply_rope, rope_frequencies


def test_forward_shapes_and_axes_match_params():
    cfg = llama_config("nano")
    params = llama_init(jax.random.PRNGKey(0), cfg)
    axes = llama_logical_axes(cfg)
    # every param leaf has an axis annotation of matching rank
    p_leaves = jax.tree_util.tree_leaves_with_path(params)
    a_map = {jax.tree_util.keystr(k): v for k, v in
             jax.tree_util.tree_leaves_with_path(
                 axes, is_leaf=lambda x: isinstance(x, tuple))}
    for path, leaf in p_leaves:
        ax = a_map[jax.tree_util.keystr(path)]
        assert len(ax) == leaf.ndim, (path, ax, leaf.shape)
    tokens = jnp.zeros((2, 16), jnp.int32)
    logits = llama_forward(params, tokens, cfg)
    assert logits.shape == (2, 16, cfg.padded_vocab)
    assert logits.dtype == jnp.float32


def test_rope_preserves_norm_and_relative_positions():
    # rotations preserve vector norms, and q·k depends only on the
    # RELATIVE offset between positions
    D = 8
    cos, sin = rope_frequencies(32, D, 10_000.0)
    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(1, 32, 1, D).astype(np.float32))
    k = jnp.asarray(rng.randn(1, 32, 1, D).astype(np.float32))
    qr = apply_rope(q, cos, sin)
    np.testing.assert_allclose(
        np.linalg.norm(np.asarray(qr), axis=-1),
        np.linalg.norm(np.asarray(q), axis=-1), rtol=1e-5)
    kr = apply_rope(k, cos, sin)
    # same content placed at positions (i, j) vs (i+s, j+s) gives the
    # same dot product
    qq = np.asarray(q[0, 0, 0])
    kk = np.asarray(k[0, 0, 0])
    def dot_at(i, j):
        qi = apply_rope(jnp.asarray(qq)[None, None, None, :],
                        cos[i:i + 1], sin[i:i + 1])
        kj = apply_rope(jnp.asarray(kk)[None, None, None, :],
                        cos[j:j + 1], sin[j:j + 1])
        return float(jnp.sum(qi * kj))
    np.testing.assert_allclose(dot_at(3, 1), dot_at(13, 11), rtol=1e-4)
    np.testing.assert_allclose(dot_at(5, 5), dot_at(20, 20), rtol=1e-4)


def test_gqa_numerically_equals_mha_with_repeated_kv_weights():
    # GQA with n_kv_head < n_head must equal standard MHA whose kv
    # projection weights are the kv-head weights repeated head-wise —
    # the exact statement of query-group sharing (catches repeat/tile
    # or head-ordering mistakes)
    cfg_gqa = llama_config("nano", n_kv_head=1)      # 2 q heads share
    cfg_mha = llama_config("nano", n_kv_head=2)
    tokens = jnp.asarray(
        np.random.RandomState(0).randint(0, 512, (2, 16)), jnp.int32)
    params = llama_init(jax.random.PRNGKey(0), cfg_gqa)
    params_mha = jax.tree.map(lambda x: x, params)
    blocks = dict(params_mha["blocks"])
    attn = dict(blocks["attn"])
    # (L, d, 1, hd) → (L, d, 2, hd): both mha kv heads ARE the one
    # gqa kv head
    attn["wk"] = jnp.repeat(params["blocks"]["attn"]["wk"], 2, axis=2)
    attn["wv"] = jnp.repeat(params["blocks"]["attn"]["wv"], 2, axis=2)
    blocks["attn"] = attn
    params_mha["blocks"] = blocks
    out_gqa = llama_forward(params, tokens, cfg_gqa)
    out_mha = llama_forward(params_mha, tokens, cfg_mha)
    np.testing.assert_allclose(np.asarray(out_gqa),
                               np.asarray(out_mha), atol=2e-2,
                               rtol=2e-2)
    with pytest.raises(ValueError, match="divide"):
        llama_config("nano", n_head=2, n_kv_head=3)


def test_llama_overfits_tiny_sequence():
    cfg = llama_config("nano", remat=False)
    params = llama_init(jax.random.PRNGKey(0), cfg)
    rng = np.random.RandomState(0)
    tokens = jnp.asarray(rng.randint(0, 512, (4, 17)), jnp.int32)
    tx = optax.adam(1e-2)
    opt = tx.init(params)

    @jax.jit
    def step(params, opt):
        loss, grads = jax.value_and_grad(llama_loss)(
            params, {"tokens": tokens}, cfg)
        updates, opt = tx.update(grads, opt)
        return optax.apply_updates(params, updates), opt, loss

    params, opt, first = step(params, opt)
    for _ in range(30):
        params, opt, loss = step(params, opt)
    assert float(loss) < float(first) * 0.5, (float(first),
                                              float(loss))


def test_param_count_matches_tree():
    cfg = llama_config("tiny")
    params = llama_init(jax.random.PRNGKey(0), cfg)
    actual = sum(l.size for l in jax.tree_util.tree_leaves(params))
    # exact up to vocab padding (count uses the unpadded vocab)
    pad_extra = 2 * (cfg.padded_vocab - cfg.vocab_size) * cfg.d_model
    assert actual - pad_extra == llama_param_count(cfg)


def test_llama_trains_on_dp_fsdp_tp_mesh():
    # one jitted train step under a 2x2x2 data/fsdp/tensor mesh — the
    # logical-axis table must map every llama param (incl. the
    # unsharded kv_heads axis of GQA) onto the mesh
    import optax

    from ray_tpu.parallel import MeshSpec, make_mesh
    from ray_tpu.parallel.sharding import shard_params

    cfg = llama_config("nano", use_flash=False)
    axes = llama_logical_axes(cfg)
    params = llama_init(jax.random.PRNGKey(0), cfg)
    mesh = make_mesh(MeshSpec(data=2, fsdp=2, tensor=2))
    with jax.set_mesh(mesh):
        params = shard_params(params, axes, mesh)
        tx = optax.adam(1e-3)
        opt = tx.init(params)
        tokens = jnp.zeros((4, 17), jnp.int32)

        @jax.jit
        def step(params, opt):
            loss, grads = jax.value_and_grad(llama_loss)(
                params, {"tokens": tokens}, cfg)
            u, opt = tx.update(grads, opt)
            return optax.apply_updates(params, u), opt, loss

        _, _, loss = step(params, opt)
    assert np.isfinite(float(loss))
