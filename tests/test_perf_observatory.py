"""Perf observatory (round 10): compiled-cost registry, recompile
watchdog, per-chip HBM surfaces, program-keyed compile telemetry, and
the perfledger regression gates.

The registry tests opt INTO the AOT cost harvest
(RAYTPU_DEVICE_STATS_COST=1 — conftest defaults it off to protect the
tier-1 time budget) and use unique engine identities (temperature) so
their programs get fresh jit-cache wrappers regardless of what other
serve tests compiled earlier in the process.

conftest.py forces 8 virtual CPU devices, so the mesh tests run in
tier-1; CPU devices report ``memory_stats() -> None``, which is exactly
what the stable-key contract of ``device_memory_stats()`` pins.
"""

import asyncio
import io
import json

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from ray_tpu._private import device_stats as ds  # noqa: E402
from ray_tpu.parallel import MeshSpec, fake_mesh  # noqa: E402

_OVR = {"dtype": jnp.float32, "use_flash": False, "remat": False}


@pytest.fixture(scope="module")
def mesh():
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 devices (conftest forces them in CI)")
    return fake_mesh(8, MeshSpec(data=4, tensor=2))


def _run_engine(dep, prompts, timeout=300):
    async def main():
        inst = dep.func_or_class()
        try:
            outs = await asyncio.wait_for(
                asyncio.gather(*[inst(p) for p in prompts]), timeout)
            stats = inst.engine_stats()
        finally:
            inst.shutdown_engine()
        return outs, stats

    return asyncio.run(main())


# ---------------------------------------------------------------------------
# registry core
# ---------------------------------------------------------------------------

def test_instrument_counts_compiles_and_harvests_cost(monkeypatch):
    monkeypatch.setenv("RAYTPU_DEVICE_STATS_COST", "1")
    reg = ds.ProgramRegistry()
    f = reg.instrument("serve.decode", jax.jit(lambda x: x * 2 + 1))
    for n in (4, 4, 8, 8, 8):
        f(jnp.ones((n,), jnp.float32))
    snap = reg.snapshot()["serve.decode"]
    assert snap["compile_events"] == 2        # two distinct shapes
    assert snap["invokes"] == 3               # re-used signatures only
    assert snap["xla_flops"] is not None
    assert snap["peak_hbm_bytes"] is not None
    assert snap["compile_seconds"] > 0


def test_instrument_cost_harvest_gated_by_env(monkeypatch):
    monkeypatch.setenv("RAYTPU_DEVICE_STATS_COST", "0")
    reg = ds.ProgramRegistry()
    f = reg.instrument("serve.decode", jax.jit(lambda x: x + 1))
    f(jnp.ones((3,), jnp.float32))
    snap = reg.snapshot()["serve.decode"]
    assert snap["compile_events"] == 1        # counting stays on
    assert snap["xla_flops"] is None          # harvest skipped


def test_cost_summary_shape():
    compiled = jax.jit(
        lambda x: x @ x).lower(jnp.ones((8, 8), jnp.float32)).compile()
    cost = ds._cost_summary(compiled)
    assert cost["xla_flops"] > 0
    assert cost["peak_hbm_bytes"] > 0
    assert "arithmetic_intensity" in cost


def test_static_program_map_covers_all_specs():
    """Runtime counterpart of the graftcheck ``observatory-mapping``
    rule: every audited spec maps to a known runtime program."""
    from ray_tpu.tools.graftcheck.programs import default_programs

    names = {s.name for s in default_programs()}
    assert names == set(ds.STATIC_PROGRAM_MAP)
    assert set(ds.STATIC_PROGRAM_MAP.values()) <= ds.KNOWN_PROGRAMS


# ---------------------------------------------------------------------------
# recompile watchdog
# ---------------------------------------------------------------------------

def test_watchdog_deterministic_clock(monkeypatch):
    events = []
    monkeypatch.setattr("ray_tpu._private.events.report_event",
                        lambda *a, **k: events.append((a, k)))
    reg = ds.ProgramRegistry(storm_window_s=60.0, storm_threshold=3)
    reg.record_compile("p", 0.1, now=0.0)
    reg.record_compile("p", 0.1, now=1.0)
    assert not reg.snapshot()["p"]["recompile_storm"]
    reg.record_compile("p", 0.1, now=2.0)     # 3rd inside the window
    snap = reg.snapshot()["p"]
    assert snap["recompile_storm"]
    assert snap["recompile_storms_total"] == 1
    assert len(events) == 1 and events[0][1]["severity"] == "WARNING"
    # compiles spaced wider than the window never storm
    reg2 = ds.ProgramRegistry(storm_window_s=60.0, storm_threshold=3)
    for t in (0.0, 100.0, 200.0, 300.0):
        reg2.record_compile("q", 0.1, now=t)
    assert not reg2.snapshot()["q"]["recompile_storm"]
    assert reg2.snapshot()["q"]["recompile_storms_total"] == 0


def test_watchdog_fires_on_planted_shape_churn():
    """The classic bug the watchdog exists for: a decode loop whose
    batch dimension is never bucketed, compiling per request."""
    reg = ds.ProgramRegistry(storm_window_s=600.0, storm_threshold=4)
    step = reg.instrument("serve.decode",
                          jax.jit(lambda x: jnp.tanh(x).sum()))
    for n in range(1, 6):                     # 5 distinct shapes
        step(jnp.ones((n, 4), jnp.float32))
    snap = reg.snapshot()["serve.decode"]
    assert snap["compile_events"] == 5
    assert snap["recompile_storm"]
    assert snap["recompile_storms_total"] >= 1


# ---------------------------------------------------------------------------
# program-keyed compile telemetry (satellite: beyond prefill buckets)
# ---------------------------------------------------------------------------

def test_telemetry_program_compile_counter():
    from ray_tpu.serve.telemetry import EngineTelemetry

    t = EngineTelemetry("obs_test", max_slots=2)
    t.record_program_compile("serve.decode")
    t.record_program_compile("serve.decode")
    t.record_program_compile("serve.sharded_decode")
    stats = t.engine_stats()
    assert stats["program_compiles"] == {"serve.decode": 2,
                                         "serve.sharded_decode": 1}
    # prefill-bucket counter contract untouched
    assert stats["prefill_compiles"] == len(stats["prefill_buckets"])


def test_registry_subscription_feeds_telemetry():
    from ray_tpu.serve.telemetry import EngineTelemetry

    t = EngineTelemetry("obs_sub", max_slots=2)
    reg = ds.ProgramRegistry()
    reg.subscribe(t.record_program_compile)
    reg.record_compile("serve.decode", 0.01)
    reg.record_compile("serve.decode", 0.01)
    assert t.engine_stats()["program_compiles"] == {"serve.decode": 2}


# ---------------------------------------------------------------------------
# engine integration: registry populated, per-chip HBM under a mesh
# ---------------------------------------------------------------------------

def test_registry_populated_after_engine_build(monkeypatch):
    monkeypatch.setenv("RAYTPU_DEVICE_STATS_COST", "1")
    from ray_tpu.serve.llm import build_llm_deployment

    rng = np.random.RandomState(3)
    prompts = [rng.randint(2, 400, n).astype(np.int32) for n in (7, 11)]
    # unique temperature -> fresh _JIT_CACHE entry -> fresh
    # instrumented wrappers that harvest under the env opt-in above
    dep = build_llm_deployment(
        "gpt2", "nano", max_new_tokens=4, temperature=0.0127,
        scheduler="continuous", max_slots=2, prefill_bucket=16,
        config_overrides=_OVR)
    outs, stats = _run_engine(dep, prompts)
    assert len(outs) == 2
    snap = ds.get_registry().snapshot()
    for program in ("serve.prefill", "serve.decode"):
        assert snap[program]["compile_events"] >= 1
        assert snap[program]["xla_flops"] is not None
        assert snap[program]["peak_hbm_bytes"] is not None
    # the same block rides engine_stats(), serve namespace only
    assert "serve.decode" in stats["programs"]
    assert stats["programs"]["serve.decode"]["compile_events"] >= 1
    # the registry subscription mirrored compiles into the
    # program-keyed telemetry counter
    assert stats["program_compiles"].get("serve.decode", 0) >= 1


def test_sharded_engine_reports_programs_and_per_chip_hbm(
        monkeypatch, mesh):
    monkeypatch.setenv("RAYTPU_DEVICE_STATS_COST", "1")
    from ray_tpu.serve.llm import build_llm_deployment

    rng = np.random.RandomState(5)
    prompts = [rng.randint(2, 400, n).astype(np.int32) for n in (9, 9)]
    dep = build_llm_deployment(
        "gpt2", "nano", max_new_tokens=4, temperature=0.0127,
        scheduler="continuous", kv_layout="paged", kv_block_size=16,
        prefill_bucket=16, max_slots=2, mesh=mesh,
        config_overrides=_OVR)
    outs, stats = _run_engine(dep, prompts)
    assert len(outs) == 2
    # acceptance: per-program xla_flops / peak_hbm_bytes /
    # compile_events on the 8-virtual-device sharded engine
    progs = stats["programs"]
    assert "serve.sharded_decode" in progs
    for name in ("serve.sharded_decode", "serve.sharded_paged_prefill"):
        assert progs[name]["compile_events"] >= 1
        assert progs[name]["xla_flops"] is not None
        assert progs[name]["peak_hbm_bytes"] is not None
    # acceptance: per-chip HBM entries with stable keys (None values
    # on CPU, real byte counts on TPU)
    devices = stats["mesh"]["devices"]
    assert len(devices) == 8
    for entry in devices:
        for key in ("id", "platform", "device_kind", "bytes_in_use",
                    "peak_bytes_in_use", "bytes_limit"):
            assert key in entry
    assert sorted(e["id"] for e in devices) == list(range(8))


def test_device_memory_stats_stable_keys():
    entries = ds.device_memory_stats()
    assert len(entries) == len(jax.devices())
    for entry in entries:
        assert entry["platform"] == "cpu"
        assert "bytes_in_use" in entry      # key present, value None
        assert entry["bytes_in_use"] is None


# ---------------------------------------------------------------------------
# perfledger: golden verdict fixtures + CLI gates
# ---------------------------------------------------------------------------

def _bench_rec(value, metric="obs_tokens_per_sec"):
    return {"metric": metric, "value": value, "unit": "tok/s",
            "vs_baseline": None, "detail": {}}


def test_perfledger_verdicts_improve_flat_regress(tmp_path):
    from ray_tpu.tools import perfledger as pl

    hist = str(tmp_path / "hist.jsonl")
    pl.append_records([_bench_rec(100.0)], "bench", path=hist)
    assert pl.check(hist)["verdicts"][
        "obs_tokens_per_sec"]["verdict"] == "new"
    pl.append_records([_bench_rec(101.0)], "bench", path=hist)
    assert pl.check(hist)["verdicts"][
        "obs_tokens_per_sec"]["verdict"] == "flat"
    pl.append_records([_bench_rec(120.0)], "bench", path=hist)
    assert pl.check(hist)["verdicts"][
        "obs_tokens_per_sec"]["verdict"] == "improve"
    pl.append_records([_bench_rec(80.0)], "bench", path=hist)
    result = pl.check(hist)
    assert result["verdicts"]["obs_tokens_per_sec"]["verdict"] \
        == "regress"
    assert result["ok"] is False


def test_perfledger_latency_direction(tmp_path):
    from ray_tpu.tools import perfledger as pl

    hist = str(tmp_path / "hist.jsonl")
    rec = lambda v: _bench_rec(v, metric="obs_prefill_ttft_ms")  # noqa: E731
    pl.append_records([rec(10.0)], "bench", path=hist)
    pl.append_records([rec(20.0)], "bench", path=hist)
    assert pl.check(hist)["verdicts"][
        "obs_prefill_ttft_ms"]["verdict"] == "regress"


def test_perfledger_check_cli_exit_codes(tmp_path, capsys):
    """Acceptance: ``python -m ray_tpu.tools.perfledger check`` exits
    nonzero on a fixture regression (and zero when clean)."""
    from ray_tpu.tools import perfledger as pl

    hist = str(tmp_path / "hist.jsonl")
    pl.append_records([_bench_rec(100.0)], "bench", path=hist)
    pl.append_records([_bench_rec(100.0)], "bench", path=hist)
    assert pl.main(["--history", hist, "check"]) == 0
    pl.append_records([_bench_rec(50.0)], "bench", path=hist)
    assert pl.main(["--history", hist, "check"]) == 1
    capsys.readouterr()


def test_perfledger_ingest_sweepjson_and_wrappers(tmp_path):
    from ray_tpu.tools import perfledger as pl

    sweep = {"sweep": {"mode": "train", "batch_per_chip": 8,
                       "overrides": {}},
             "tok_s_chip": 500.0, "mfu": 0.2, "chips": 8}
    wrapper = {"n": 5, "cmd": "python bench.py",
               "parsed": _bench_rec(77.0)}
    text = ("human noise line\n"
            "SWEEPJSON " + json.dumps(sweep) + "\n"
            + json.dumps(_bench_rec(42.0)) + "\n")
    recs = pl.parse_text(text)
    assert len(recs) == 2
    recs += pl.parse_text(json.dumps(wrapper, indent=1))
    assert len(recs) == 3
    hist = str(tmp_path / "hist.jsonl")
    assert pl.append_records(recs, "ingest", path=hist) == 3
    series = pl.metric_series(pl.load_history(hist))
    assert "obs_tokens_per_sec" in series
    assert any(k.startswith("sweep.train.tok_s_chip") for k in series)


def test_perfledger_variant_series_do_not_mix(tmp_path):
    """Different sweep variants must form different series — a b24
    point never gates a b32 point."""
    from ray_tpu.tools import perfledger as pl

    hist = str(tmp_path / "hist.jsonl")
    a = {"sweep": {"mode": "train", "batch_per_chip": 24,
                   "overrides": {}}, "tok_s_chip": 900.0}
    b = {"sweep": {"mode": "train", "batch_per_chip": 32,
                   "overrides": {}}, "tok_s_chip": 100.0}
    pl.append_records([a, b], "sweep", path=hist)
    result = pl.check(hist)
    assert all(v["verdict"] == "new"
               for v in result["verdicts"].values())
    assert result["ok"]


def test_perfledger_report_renders(tmp_path):
    from ray_tpu.tools import perfledger as pl

    hist = str(tmp_path / "hist.jsonl")
    pl.append_records([_bench_rec(100.0)], "bench", path=hist)
    pl.append_records([_bench_rec(80.0)], "bench", path=hist)
    text = pl.report(hist)
    assert "obs_tokens_per_sec" in text
    assert "regress" in text
    assert "REGRESSIONS DETECTED" in text


# ---------------------------------------------------------------------------
# sweep -> ledger end-to-end
# ---------------------------------------------------------------------------

def test_sweep_appends_to_bench_history(tmp_path, monkeypatch):
    """Acceptance: sweep_tpu.py appends its records to
    BENCH_HISTORY.jsonl end-to-end (time_config stubbed — the sweep
    plumbing, record shape, and ledger append are what's under test)."""
    import sweep_tpu

    calls = []

    def fake_time_config(batch, seq=1024, n_steps=20, preset="gpt2",
                         **kw):
        calls.append(batch)
        return 1000.0, 0.33, 2.5, 1, {"mfu_xla": 0.31,
                                      "xla_flops": 1.0e9,
                                      "peak_hbm_bytes": 1 << 20,
                                      "model_flops": 1.1e9,
                                      "compile_seconds": 0.5}

    monkeypatch.setattr(sweep_tpu, "time_config", fake_time_config)
    hist = str(tmp_path / "BENCH_HISTORY.jsonl")
    out = io.StringIO()
    records = sweep_tpu.run_sweep([[2, {"preset": "tiny"}]], 1,
                                  out=out, audit=False,
                                  ledger_path=hist)
    assert calls == [2]
    assert records[0]["mfu_xla"] == 0.31
    assert "SWEEPJSON" in out.getvalue()
    from ray_tpu.tools import perfledger as pl

    entries = pl.load_history(hist)
    assert len(entries) == 1
    series = pl.metric_series(entries)
    assert any(k.startswith("sweep.train.mfu_xla") for k in series)
    assert any(k.startswith("sweep.train.tok_s_chip") for k in series)
