"""DQN + replay buffers + offline RL + multi-agent (round-3 RLlib depth).

Reference analogs: rllib/utils/replay_buffers tests, rllib/algorithms/
dqn, offline/json_{reader,writer}, env/multi_agent_env — learning tests
follow the check_learning_achieved pattern scaled to CI
(rllib/utils/test_utils.py:480).
"""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.rllib import (BC, BCConfig, DQN, DQNConfig, JsonReader,
                           JsonWriter, MultiAgentEnv, MultiAgentPPO,
                           MultiAgentPPOConfig, PrioritizedReplayBuffer,
                           ReplayBuffer, SampleBatch)
from ray_tpu.rllib import sample_batch as sb


# ---------------------------------------------------------------------------
# replay buffers
# ---------------------------------------------------------------------------

def _batch(lo, hi):
    n = hi - lo
    return SampleBatch({sb.OBS: np.arange(lo, hi, dtype=np.float32)
                        .reshape(n, 1),
                        sb.ACTIONS: np.arange(lo, hi, dtype=np.int64)})


def test_replay_buffer_ring_and_sampling():
    buf = ReplayBuffer(8, seed=0)
    buf.add(_batch(0, 6))
    assert len(buf) == 6
    buf.add(_batch(6, 12))   # wraps: 12 rows into capacity 8
    assert len(buf) == 8
    got = buf.sample(64)
    assert got.count == 64
    # ring kept the newest 8 rows (4..11)
    assert set(got[sb.ACTIONS].tolist()) <= set(range(4, 12))


def test_prioritized_replay_prefers_high_td():
    buf = PrioritizedReplayBuffer(16, alpha=1.0, beta=1.0, seed=0)
    idx = buf.add(_batch(0, 16))
    # give row 3 overwhelming priority
    errs = np.full(16, 1e-4)
    errs[3] = 100.0
    buf.update_priorities(idx, errs)
    got, sample_idx, weights = buf.sample(256)
    frac_3 = float(np.mean(sample_idx == 3))
    assert frac_3 > 0.9
    # importance weights: the over-sampled row gets the SMALLEST weight
    others = weights[sample_idx != 3]
    if len(others):
        assert weights[sample_idx == 3].max() <= others.min() + 1e-6
    assert weights.max() <= 1.0 + 1e-6


# ---------------------------------------------------------------------------
# tiny deterministic env: action == observation bucket pays 1
# ---------------------------------------------------------------------------

class BanditEnv:
    """Contextual bandit: obs in {0,1,2}, correct action = obs."""

    class _Space:
        def __init__(self, n):
            self.n = n
            self.shape = (3,)

    def __init__(self, episode_len=20, seed=0):
        self.observation_space = self._Space(3)
        self.action_space = self._Space(3)
        self._rng = np.random.RandomState(seed)
        self._len = episode_len
        self._t = 0

    def _obs(self):
        self._state = self._rng.randint(3)
        one_hot = np.zeros(3, np.float32)
        one_hot[self._state] = 1.0
        return one_hot

    def reset(self, seed=None):
        self._t = 0
        return self._obs(), {}

    def step(self, action):
        r = 1.0 if int(action) == self._state else 0.0
        self._t += 1
        done = self._t >= self._len
        return self._obs(), r, done, False, {}


@pytest.mark.parametrize("prioritized", [False, True])
def test_dqn_learns_bandit(ray_start_shared, prioritized):
    cfg = DQNConfig(env=lambda _: BanditEnv(), num_workers=1,
                    hidden=(32,), buffer_size=5000, learning_starts=200,
                    train_batch_size=64, train_intensity=16,
                    target_update_freq=200, epsilon_decay_steps=1500,
                    rollout_fragment_length=100, lr=5e-3, gamma=0.0,
                    prioritized_replay=prioritized, seed=0)
    algo = DQN(cfg)
    try:
        result = {}
        for _ in range(25):
            result = algo.train()
            if result.get("episode_reward_mean", 0) >= 18.0:
                break
        assert result.get("episode_reward_mean", 0) >= 15.0, result
    finally:
        algo.stop()


# ---------------------------------------------------------------------------
# offline: writer -> reader roundtrip; BC clones an expert
# ---------------------------------------------------------------------------

def test_json_writer_reader_roundtrip(tmp_path):
    path = str(tmp_path / "data.jsonl")
    b = SampleBatch({sb.OBS: np.random.randn(5, 3).astype(np.float32),
                     sb.ACTIONS: np.arange(5)})
    with JsonWriter(path) as w:
        w.write(b)
        w.write(b)
    reader = JsonReader(path)
    allb = reader.read_all()
    assert allb.count == 10
    np.testing.assert_array_equal(allb[sb.ACTIONS][:5], b[sb.ACTIONS])
    assert allb[sb.OBS].dtype == np.float32
    assert reader.next().count == 5


def test_bc_clones_expert(tmp_path):
    # expert on the bandit: action = argmax(obs)
    path = str(tmp_path / "expert.jsonl")
    rng = np.random.RandomState(0)
    obs = np.eye(3, dtype=np.float32)[rng.randint(3, size=512)]
    acts = obs.argmax(axis=-1)
    with JsonWriter(path) as w:
        w.write(SampleBatch({sb.OBS: obs, sb.ACTIONS: acts}))
    algo = BC(BCConfig(input_path=path, hidden=(32,), lr=1e-2, seed=0))
    for _ in range(10):
        result = algo.train()
    assert result["loss"] < 0.1
    test_obs = np.eye(3, dtype=np.float32)
    np.testing.assert_array_equal(algo.compute_actions(test_obs),
                                  [0, 1, 2])


# ---------------------------------------------------------------------------
# multi-agent: two policies coordinate on a matching game
# ---------------------------------------------------------------------------

class MatchEnv(MultiAgentEnv):
    """Both agents see the same one-hot state; each is paid for matching
    it.  Independent learning with one policy per agent must solve it."""

    def __init__(self, config=None, episode_len=10):
        self._len = episode_len
        self._rng = np.random.RandomState((config or {}).get("seed", 0))
        self._t = 0

    def _obs(self):
        self._state = self._rng.randint(2)
        one_hot = np.zeros(2, np.float32)
        one_hot[self._state] = 1.0
        return {"a0": one_hot, "a1": one_hot}

    def reset(self, seed=None):
        self._t = 0
        return self._obs(), {}

    def step(self, action_dict):
        rews = {aid: (1.0 if int(a) == self._state else 0.0)
                for aid, a in action_dict.items()}
        self._t += 1
        done = self._t >= self._len
        obs = self._obs()
        dones = {"__all__": done}
        return obs, rews, dones, {"__all__": False}, {}


def test_multi_agent_ppo_learns(ray_start_shared):
    cfg = MultiAgentPPOConfig(
        env=lambda c: MatchEnv(c), num_workers=1,
        policies={"p0": (2, 2), "p1": (2, 2)},
        policy_mapping_fn=lambda aid: {"a0": "p0", "a1": "p1"}[aid],
        rollout_fragment_length=100, train_batch_size=400,
        num_sgd_iter=8, minibatch_size=64, hidden=(32,), lr=5e-3,
        gamma=0.0, seed=0)
    algo = MultiAgentPPO(cfg)
    try:
        result = {}
        for _ in range(20):
            result = algo.train()
            # both agents paid every step: max return = 2 * 10
            if result.get("episode_reward_mean", 0) >= 18.0:
                break
        assert result.get("episode_reward_mean", 0) >= 14.0, result
    finally:
        algo.stop()


# ---------------------------------------------------------------------------
# continuous-action PPO (diagonal Gaussian)
# ---------------------------------------------------------------------------

class TargetEnv:
    """1-D continuous bandit: obs one-hot in R^2 selects a target; reward
    = -(a - target)^2.  Optimal mean = target per state."""

    class _Box:
        shape = (1,)

    class _ObsSpace:
        shape = (2,)

    def __init__(self, episode_len=10, seed=0):
        self.observation_space = self._ObsSpace()
        self.action_space = self._Box()
        self._rng = np.random.RandomState(seed)
        self._len = episode_len
        self._t = 0
        self._targets = np.array([-1.0, 1.0])

    def _obs(self):
        self._state = self._rng.randint(2)
        one_hot = np.zeros(2, np.float32)
        one_hot[self._state] = 1.0
        return one_hot

    def reset(self, seed=None):
        self._t = 0
        return self._obs(), {}

    def step(self, action):
        a = float(np.asarray(action).ravel()[0])
        r = -(a - self._targets[self._state]) ** 2
        self._t += 1
        done = self._t >= self._len
        return self._obs(), r, done, False, {}


def test_continuous_ppo_learns(ray_start_shared):
    from ray_tpu.rllib import PPO, PPOConfig

    cfg = PPOConfig(env=lambda _=None: TargetEnv(), num_workers=1,
                    rollout_fragment_length=100, train_batch_size=400,
                    num_sgd_iter=8, minibatch_size=64, hidden=(32,),
                    lr=5e-3, gamma=0.0, entropy_coeff=0.0, seed=0)
    algo = PPO(cfg)
    try:
        assert cfg.continuous and cfg.n_actions == 1
        result = {}
        for _ in range(25):
            result = algo.train()
            # optimum 0; random-init policy starts around -1.5 to -3
            if result.get("episode_reward_mean", -99) >= -2.0:
                break
        assert result.get("episode_reward_mean", -99) >= -4.0, result
    finally:
        algo.stop()


def test_sac_learns_continuous_target(ray_start_shared):
    """SAC drives the tanh-Gaussian actor onto the per-state targets of
    the continuous bandit (off-policy counterpart of the PPO test)."""
    from ray_tpu.rllib import SAC, SACConfig

    cfg = SACConfig(env=lambda _=None: TargetEnv(), num_workers=1,
                    hidden=(32, 32), buffer_size=20_000,
                    learning_starts=200, train_batch_size=128,
                    train_intensity=32, lr=3e-3, gamma=0.0,
                    rollout_fragment_length=100, seed=0)
    algo = SAC(cfg)
    try:
        result = {}
        for _ in range(25):
            result = algo.train()
            if result.get("episode_reward_mean", -99) >= -2.0:
                break
        assert result.get("episode_reward_mean", -99) >= -4.0, result
    finally:
        algo.stop()


def test_ppo_learner_data_parallel_mesh_matches_single_device():
    """JaxPolicy with a data mesh: update runs sharded over 8 virtual
    devices and reaches (numerically) the same params as single-device
    — GSPMD turns the minibatch gradients into psums, no tower code."""
    import jax

    from ray_tpu.parallel import MeshSpec, fake_mesh
    from ray_tpu.rllib.policy import JaxPolicy, PolicySpec

    spec = PolicySpec(obs_dim=8, n_actions=4, hidden=(16,),
                      num_sgd_iter=2, minibatch_size=64)
    rng = np.random.RandomState(0)
    n = 256
    batch = SampleBatch({
        sb.OBS: rng.randn(n, 8).astype(np.float32),
        sb.ACTIONS: rng.randint(0, 4, n),
        sb.ACTION_LOGP: rng.randn(n).astype(np.float32) * 0.1 - 1.5,
        sb.ADVANTAGES: rng.randn(n).astype(np.float32),
        sb.VALUE_TARGETS: rng.randn(n).astype(np.float32),
    })
    single = JaxPolicy(spec, seed=0)
    s_stats = single.learn_on_batch(batch)

    mesh = fake_mesh(8, MeshSpec(data=8))
    multi = JaxPolicy(spec, seed=0, mesh=mesh)
    m_stats = multi.learn_on_batch(batch)

    assert np.isfinite(m_stats["total_loss"])
    # same data, same seed, same update math -> same resulting params
    for a, b in zip(jax.tree.leaves(single.params),
                    jax.tree.leaves(multi.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-5)


def test_dqn_learner_mesh_matches_single_device():
    """DQN TD update on an 8-virtual-device data mesh matches the
    single-device update numerically."""
    import jax

    from ray_tpu.parallel import MeshSpec, fake_mesh
    from ray_tpu.rllib.dqn import QPolicy, QPolicySpec

    spec = QPolicySpec(obs_dim=5, n_actions=3, hidden=(16,))
    rng = np.random.RandomState(0)

    def minis():
        out = []
        for _ in range(4):
            out.append(SampleBatch({
                sb.OBS: rng.randn(64, 5).astype(np.float32),
                sb.ACTIONS: rng.randint(0, 3, 64),
                sb.REWARDS: rng.randn(64).astype(np.float32),
                sb.DONES: np.zeros(64, np.bool_),
                sb.NEXT_OBS: rng.randn(64, 5).astype(np.float32),
            }))
        return out

    data = minis()
    single = QPolicy(spec, seed=0)
    single.learn_on_minibatches(data)

    mesh = fake_mesh(8, MeshSpec(data=8))
    multi = QPolicy(spec, seed=0, mesh=mesh)
    loss, _ = multi.learn_on_minibatches(data)
    assert np.isfinite(loss)
    for a, b in zip(jax.tree.leaves(single.params),
                    jax.tree.leaves(multi.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-5)


def test_sac_learner_mesh_runs():
    """SAC update over an 8-virtual-device data mesh runs and produces
    finite stats (stochastic update: exact single-device parity is not
    defined because per-shard RNG fold differs)."""
    from ray_tpu.parallel import MeshSpec, fake_mesh
    from ray_tpu.rllib.sac import SACPolicy, SACSpec

    spec = SACSpec(obs_dim=4, action_dim=2, hidden=(16,))
    rng = np.random.RandomState(0)
    minis = [SampleBatch({
        sb.OBS: rng.randn(64, 4).astype(np.float32),
        sb.ACTIONS: np.tanh(rng.randn(64, 2)).astype(np.float32),
        sb.REWARDS: rng.randn(64).astype(np.float32),
        sb.DONES: np.zeros(64, np.bool_),
        sb.NEXT_OBS: rng.randn(64, 4).astype(np.float32),
    }) for _ in range(3)]
    mesh = fake_mesh(8, MeshSpec(data=8))
    pol = SACPolicy(spec, seed=0, mesh=mesh)
    stats = pol.learn_on_minibatches(minis)
    assert np.isfinite(stats["critic_loss"])
    assert np.isfinite(stats["actor_loss"])


# ---------------------------------------------------------------------------
# observation filters
# ---------------------------------------------------------------------------

def test_mean_std_filter_and_parallel_merge():
    from ray_tpu.rllib.filters import MeanStdFilter, merge_filter_states

    rng = np.random.RandomState(0)
    data = rng.randn(500, 3) * 5.0 + 100.0
    f = MeanStdFilter((3,))
    out = f(data)
    assert abs(float(out.mean())) < 0.2 and 0.8 < float(out.std()) < 1.2
    np.testing.assert_allclose(f.mean, data.mean(0), rtol=1e-6)

    # parallel merge (Chan et al.) == single-stream stats
    f1, f2 = MeanStdFilter((3,)), MeanStdFilter((3,))
    f1(data[:200])
    f2(data[200:])
    merged = merge_filter_states([f1.get_state(), f2.get_state()])
    np.testing.assert_allclose(merged["mean"], data.mean(0), rtol=1e-6)
    f3 = MeanStdFilter((3,))
    f3.set_state(merged)
    np.testing.assert_allclose(f3.std, data.std(0, ddof=1) + f3.eps,
                               rtol=1e-5)


def test_ppo_learns_with_obs_filter(ray_start_shared):
    """PPO with MeanStdFilter solves a bandit whose observations are
    badly scaled/offset (raw obs would stall tanh nets); filter stats
    merge across 2 workers every step."""
    from ray_tpu.rllib import PPO, PPOConfig

    class ScaledBandit(BanditEnv):
        def _obs(self):
            return super()._obs() * 500.0 + 3000.0

    cfg = PPOConfig(env=lambda _=None: ScaledBandit(), num_workers=2,
                    rollout_fragment_length=100, train_batch_size=400,
                    num_sgd_iter=8, minibatch_size=64, hidden=(32,),
                    lr=5e-3, gamma=0.0, seed=0,
                    observation_filter="MeanStdFilter")
    algo = PPO(cfg)
    try:
        result = {}
        for _ in range(25):
            result = algo.train()
            if result.get("episode_reward_mean", 0) >= 18.0:
                break
        assert result.get("episode_reward_mean", 0) >= 14.0, result
        # filters actually synchronized: workers share merged counts
        states = ray_tpu.get(
            [w.get_filter_state.remote()
             for w in algo.workers.workers], timeout=30)
        counts = [s["count"] for s in states]
        assert all(c > 400 for c in counts), counts
    finally:
        algo.stop()


def test_filter_delta_sync_counts_history_once():
    """Two rounds of delta-merge: global count equals the number of
    observations seen, NOT geometric in the number of syncs (the
    full-state-merge bug would give 2x per round)."""
    from ray_tpu.rllib.filters import MeanStdFilter, merge_filter_states

    rng = np.random.RandomState(0)
    global_state = None
    total = 0
    for _round in range(3):
        deltas = []
        for _w in range(2):
            d = MeanStdFilter((4,))
            d(rng.randn(50, 4))
            total += 50
            deltas.append(d.get_state())
        global_state = merge_filter_states(
            ([global_state] if global_state else []) + deltas)
    assert global_state["count"] == total  # 300, not 2^3-inflated
