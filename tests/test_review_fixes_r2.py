"""Regression tests for the multi-layer review findings (batch 2)."""

import os

import numpy as np
import pytest

import ray_tpu
from ray_tpu import data as rdata
from ray_tpu.rllib import sample_batch as sb
from ray_tpu.rllib.policy import JaxPolicy, PolicySpec
from ray_tpu.rllib.sample_batch import SampleBatch


def test_ppo_update_with_batch_smaller_than_minibatch():
    spec = PolicySpec(obs_dim=4, n_actions=2, hidden=(8,),
                      num_sgd_iter=2, minibatch_size=128)
    pol = JaxPolicy(spec, seed=0)
    rng = np.random.RandomState(0)
    n = 40  # < minibatch_size
    obs = rng.randn(n, 4).astype(np.float32)
    actions, logp, _ = pol.compute_actions(obs)
    stats = pol.learn_on_batch(SampleBatch({
        sb.OBS: obs, sb.ACTIONS: actions, sb.ACTION_LOGP: logp,
        sb.ADVANTAGES: rng.randn(n).astype(np.float32),
        sb.VALUE_TARGETS: rng.randn(n).astype(np.float32)}))
    assert np.isfinite(stats["total_loss"])


def test_dataset_materialization_cached(ray_start_shared, tmp_path):
    marker = str(tmp_path / "runs")

    def stage(batch, _marker=marker):
        with open(_marker, "a") as f:
            f.write("x")
        return batch

    ds = rdata.range(20, parallelism=2).map_batches(stage)
    assert ds.count() == 20
    assert len(ds.take_all()) == 20  # second consumption: no re-run
    with open(marker) as f:
        assert len(f.read()) == 2  # once per block, once total


def test_init_address_defaults_to_zero_capacity(ray_start_shared):
    """Attach-mode zero-capacity default is asserted end-to-end in
    tests/test_multinode.py (head has no CPUs; tasks never land on the
    driver's node).  Local mode keeps full requested capacity:"""
    assert ray_tpu.cluster_resources().get("CPU", 0) == 4.0
