"""Flight recorder + SLO burn-rate engine: ring-journal semantics,
deterministic burn-rate math, the end-to-end breach → postmortem-dump
pipeline through a real paged continuous engine, the postmortem CLI,
and the hot-path overhead guard.

The e2e test is the acceptance path: a deliberately impossible
SLOConfig (sub-microsecond targets) forces a breach on the first
requests, the watchdog writes a dump mid-run, and the CLI reads it
back in a subprocess — the whole loop a production postmortem walks.
"""

import asyncio
import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from ray_tpu._private.flightrec import (FlightRecorder,
                                        default_dump_dir)  # noqa: E402
from ray_tpu.serve.llm import build_llm_deployment  # noqa: E402
from ray_tpu.serve.slo import SLOConfig, SLOTracker  # noqa: E402
from ray_tpu.tools.flightrec import (filter_events, load_dump,
                                     report_lines, sweepjson_records,
                                     trace_events)  # noqa: E402
from ray_tpu.tools.flightrec import main as flightrec_main  # noqa: E402

_OVR = {"dtype": jnp.float32, "use_flash": False, "remat": False}
_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _build(**kw):
    kw.setdefault("max_new_tokens", 4)
    kw.setdefault("temperature", 0.0)
    kw.setdefault("scheduler", "continuous")
    kw.setdefault("kv_layout", "paged")
    kw.setdefault("kv_block_size", 16)
    kw.setdefault("prefill_bucket", 16)
    kw.setdefault("max_slots", 2)
    kw.setdefault("config_overrides", _OVR)
    return build_llm_deployment("gpt2", "nano", **kw)


def _drive(dep, prompts, timeout=300):
    async def main():
        inst = dep.func_or_class()
        try:
            outs = await asyncio.wait_for(
                asyncio.gather(*[inst(p) for p in prompts]), timeout)
            stats = inst.engine_stats()
        finally:
            inst.shutdown_engine()
        return outs, stats

    return asyncio.run(main())


def _prompts(n, lo=8, hi=14, seed=0):
    rng = np.random.RandomState(seed)
    return [rng.randint(2, 50, size=rng.randint(lo, hi))
            .astype(np.int32) for _ in range(n)]


# ---------------------------------------------------------------------------
# FlightRecorder ring semantics
# ---------------------------------------------------------------------------

def test_ring_saturation_counts_drops():
    rec = FlightRecorder("t", capacity=8, enabled=True)
    for i in range(20):
        rec.record("step", i=i)
    assert rec.recorded == 20
    assert rec.retained == 8
    assert rec.dropped == 12
    snap = rec.snapshot()
    # oldest events forgotten, survivors in order with global seq
    assert [e["seq"] for e in snap] == list(range(13, 21))
    assert [e["i"] for e in snap] == list(range(12, 20))
    assert rec.counts_by_kind() == {"step": 8}
    st = rec.stats()
    assert st["enabled"] and st["capacity"] == 8
    assert st["recorded"] == 20 and st["dropped"] == 12


def test_injectable_ts_rebases_to_start():
    rec = FlightRecorder("t", enabled=True)
    rec.record("admit", ts=rec.t0 + 1.5, req="r0")
    (e,) = rec.snapshot()
    assert e["t_s"] == pytest.approx(1.5)
    assert e["kind"] == "admit" and e["req"] == "r0"


def test_env_disable(monkeypatch, tmp_path):
    monkeypatch.setenv("RAYTPU_FLIGHTREC", "0")
    rec = FlightRecorder("t")
    rec.record("step")
    assert not rec.enabled
    assert rec.recorded == 0 and rec.snapshot() == []
    assert rec.dump(reason="x") is None
    assert rec.stats()["dumps"] == []
    # explicit override beats the env
    assert FlightRecorder("t", enabled=True).enabled


def test_dump_roundtrip(tmp_path):
    rec = FlightRecorder("eng:0", capacity=4, enabled=True)
    rec.dump_dir = str(tmp_path)
    for i in range(6):
        rec.record("step", dur_ms=float(i))
    path = rec.dump(reason="unit/test",
                    context={"note": "hi"})
    assert path is not None and os.path.dirname(path) == str(tmp_path)
    assert rec.dumps == [path] and rec.stats()["dumps"] == [path]
    doc = load_dump(path)
    assert doc["version"] == 1
    assert doc["source"] == "eng:0"
    assert doc["reason"] == "unit/test"
    assert doc["events_recorded"] == 6
    assert doc["events_retained"] == 4
    assert doc["events_dropped"] == 2
    assert doc["counts_by_kind"] == {"step": 4}
    assert doc["context"] == {"note": "hi"}
    assert len(doc["events"]) == 4
    # second dump gets a distinct filename from the per-recorder counter
    path2 = rec.dump(reason="unit/test")
    assert path2 != path


def test_default_dump_dir_env(monkeypatch, tmp_path):
    monkeypatch.setenv("RAYTPU_FLIGHTREC_DIR", str(tmp_path / "d"))
    assert default_dump_dir() == str(tmp_path / "d")


# ---------------------------------------------------------------------------
# SLOConfig / burn-rate math (deterministic, fake telemetry)
# ---------------------------------------------------------------------------

class _FakeTelemetry:
    deployment = "fake"

    def __init__(self, samples):
        self._samples = samples

    def slo_samples(self):
        return self._samples


def test_slo_config_validation():
    with pytest.raises(ValueError):
        SLOConfig(objective=1.0)
    with pytest.raises(ValueError):
        SLOConfig(windows_s=())
    with pytest.raises(ValueError):
        SLOConfig(windows_s=(0.0,))
    with pytest.raises(ValueError):
        SLOConfig(ttft_ms=-1.0)
    with pytest.raises(ValueError):
        SLOConfig(min_samples=0)
    cfg = SLOConfig(ttft_ms=100.0, queue_wait_ms=5.0)
    assert cfg.objectives() == {"ttft": 100.0, "queue_wait": 5.0}


def test_burn_rate_math_and_windows():
    now = 1000.0
    # objective 0.9 -> 10% budget; 2 of 4 recent samples over target
    # -> violation rate 0.5 -> burn 5.0; the old sample falls out of
    # the 10 s window but still counts in the overall attainment
    cfg = SLOConfig(ttft_ms=100.0, objective=0.9, windows_s=(10.0,),
                    dump_on_breach=False)
    tel = _FakeTelemetry({"ttft": [
        (now - 60.0, 500.0),   # outside the window
        (now - 5.0, 50.0), (now - 4.0, 150.0),
        (now - 3.0, 50.0), (now - 2.0, 150.0)]})
    tr = SLOTracker(cfg, tel)
    snap = tr.snapshot(now=now)
    obj = snap["objectives"]["ttft"]
    assert obj["samples"] == 5 and obj["violations"] == 3
    assert obj["attainment"] == pytest.approx(0.4)
    win = obj["windows"]["10s"]
    assert win["samples"] == 4 and win["violations"] == 2
    assert win["burn_rate"] == pytest.approx(5.0)
    assert obj["burn_rate"] == pytest.approx(5.0)
    assert obj["breached"] and snap["breached"]
    # snapshot() is a pure read: no breach accounting happened
    assert snap["breaches"] == 0 and snap["dumps"] == []


def test_check_throttles_dumps_and_counts_breaches(tmp_path):
    now = 1000.0
    cfg = SLOConfig(e2e_ms=10.0, objective=0.5, windows_s=(30.0,),
                    check_interval_s=0.25, dump_dir=str(tmp_path))
    tel = _FakeTelemetry({"e2e": [(now - 1.0, 100.0)]})
    rec = FlightRecorder("fake", enabled=True)
    rec.record("step", dur_ms=1.0)
    tr = SLOTracker(cfg, tel, recorder=rec)
    assert rec.dump_dir == str(tmp_path)   # config redirects the dumps

    snap = tr.check(now=now)
    assert snap is not None and snap["breached"]
    assert tr.breaches == 1 and len(tr.dumps) == 1
    doc = load_dump(tr.dumps[0])
    assert doc["reason"] == "slo_breach_e2e"
    assert doc["context"]["objective"] == "e2e"
    assert doc["context"]["slo"]["objectives"]["e2e"]["breached"]

    # inside the throttle window -> no pass
    assert tr.check(now=now + 0.1) is None
    # still breached on the next pass: not a fresh transition,
    # no second dump
    snap = tr.check(now=now + 1.0)
    assert snap is not None and tr.breaches == 1
    assert len(tr.dumps) == 1


def test_recompile_storm_dump(tmp_path):
    cfg = SLOConfig(ttft_ms=1e9, check_interval_s=0.0,
                    dump_dir=str(tmp_path))
    tel = _FakeTelemetry({"ttft": []})
    rec = FlightRecorder("fake", enabled=True)
    tr = SLOTracker(cfg, tel, recorder=rec)
    tr.note_storm("serve.decode_step")
    tr.check(now=5.0)
    assert tr.breaches == 0          # a storm is not an SLO breach
    assert len(tr.dumps) == 1
    doc = load_dump(tr.dumps[0])
    assert doc["reason"] == "recompile_storm"
    assert doc["context"]["program"] == "serve.decode_step"


def test_max_dumps_caps_postmortems(tmp_path):
    cfg = SLOConfig(ttft_ms=1e9, check_interval_s=0.0,
                    dump_dir=str(tmp_path), max_dumps=2)
    tel = _FakeTelemetry({"ttft": []})
    tr = SLOTracker(cfg, tel,
                    recorder=FlightRecorder("fake", enabled=True))
    for i in range(5):
        tr.note_storm(f"p{i}")
        tr.check(now=float(i))
    assert len(tr.dumps) == 2


# ---------------------------------------------------------------------------
# end-to-end: engine breach -> dump -> CLI report (acceptance path)
# ---------------------------------------------------------------------------

def test_e2e_breach_dump_and_cli(tmp_path):
    # impossible targets: every request violates, burn explodes
    slo = SLOConfig(ttft_ms=1e-4, e2e_ms=1e-4, objective=0.5,
                    windows_s=(30.0,), check_interval_s=0.0,
                    dump_dir=str(tmp_path))
    dep = _build(slo=slo)
    outs, stats = _drive(dep, _prompts(4))
    assert all(isinstance(o, np.ndarray) for o in outs)

    blk = stats["slo"]
    assert blk is not None and blk["breached"]
    assert blk["breaches"] >= 1
    for name in ("ttft", "e2e"):
        obj = blk["objectives"][name]
        assert obj["burn_rate"] > 1.0
        assert obj["violations"] == obj["samples"] > 0
        assert obj["attainment"] == 0.0
    assert blk["config"]["targets_ms"] == {"ttft": 1e-4, "e2e": 1e-4}

    fr = stats["flightrec"]
    assert fr["enabled"] and fr["recorded"] > 0
    assert blk["dumps"] and blk["dumps"] == fr["dumps"]

    dump = blk["dumps"][0]
    doc = load_dump(dump)
    counts = doc["counts_by_kind"]
    # the journal holds the engine's decisions, not just the breach
    for kind in ("admit", "kv_reserve", "slo_breach"):
        assert counts.get(kind, 0) > 0, (kind, counts)
    assert counts.get("step", 0) + counts.get("first_token", 0) > 0
    assert doc["context"]["objective"] in ("ttft", "e2e")

    # the postmortem CLI must read the dump in a fresh process
    proc = subprocess.run(
        [sys.executable, "-m", "ray_tpu.tools.flightrec", "report",
         dump], capture_output=True, text=True, cwd=_REPO, timeout=120)
    assert proc.returncode == 0, proc.stderr
    assert "SLO breach" in proc.stdout
    assert "<-- BREACHED" in proc.stdout


def test_engine_crash_writes_postmortem(tmp_path, monkeypatch):
    monkeypatch.setenv("RAYTPU_FLIGHTREC_DIR", str(tmp_path))
    dep = _build()

    async def main():
        inst = dep.func_or_class()
        try:
            # poison the pooled decode step so the engine loop dies
            # mid-step, with the request holding a slot
            await inst(_prompts(1)[0])   # healthy warmup request
            inst._pool_step = None
            with pytest.raises(Exception):
                await inst(_prompts(1, seed=1)[0])
        finally:
            inst.shutdown_engine()
        return inst._telemetry.flightrec

    rec = asyncio.run(main())
    crash_dumps = [p for p in rec.dumps if "engine_crash" in p]
    assert crash_dumps, rec.dumps
    doc = load_dump(crash_dumps[0])
    assert doc["reason"] == "engine_crash"
    assert doc["context"]["error"]
    assert doc["counts_by_kind"].get("engine_crash", 0) >= 1


# ---------------------------------------------------------------------------
# kv journal event shapes (kvscope forensics contract)
# ---------------------------------------------------------------------------

def test_kv_journal_events_carry_key_and_tenant():
    """Eviction/COW/re-prefill journal events must name WHAT was lost
    — the content key (first tokens + length) and the owning tenant —
    or eviction forensics cannot attribute cache thrash.  Regression
    guard on the event shapes postmortem tooling filters by."""
    from ray_tpu.serve.kv_pager import BlockPager

    rec = FlightRecorder("pager", enabled=True)
    bs = 4
    pager = BlockPager(num_blocks=5, block_size=bs, max_seq=16,
                       recorder=rec)

    # tenant A registers one prefix block, parks it in the LRU pool
    key_a = tuple(range(10, 10 + bs))
    pager.set_request(1, "trace-a", tenant="alpha")
    blocks = pager.allocate(1)
    assert pager.register_prefix(list(key_a), blocks) == 0
    pager.release(blocks)
    pager.set_request(None)

    # tenant B floods the pool: A's parked block is evicted
    pager.set_request(2, "trace-b", tenant="beta")
    flood = pager.allocate(4)
    assert pager.evictions == 1
    pager.release(flood)
    pager.set_request(None)

    ev = {e["kind"]: e for e in rec.snapshot()}
    evict = ev["kv_evict"]
    # the victim's owner, not the evictor, is named as tenant; the
    # evicting admission stays identifiable via req/trace
    assert evict["tenant"] == "alpha"
    assert evict["req"] == 2 and evict["trace"] == "trace-b"
    assert evict["key_prefix"] == list(key_a)[:8]
    assert evict["key_len"] == bs

    # A re-registers the same content: a kv_reprefill event books the
    # waste against the re-filling tenant with the same key tag
    pager.set_request(3, "trace-a2", tenant="alpha")
    blocks = pager.allocate(1)
    assert pager.register_prefix(list(key_a), blocks) == bs
    ev = {e["kind"]: e for e in rec.snapshot()}
    rp = ev["kv_reprefill"]
    assert rp["tokens"] == bs and rp["tenant"] == "alpha"
    assert rp["key_prefix"] == list(key_a)[:8]
    assert rp["key_len"] == bs

    # COW fork of the registered block carries the diverging key
    pager.release(blocks)
    _plen, matched = pager.match_prefix(list(key_a) + [99])
    assert matched
    fresh, src = pager.ensure_private(matched[0])
    assert src == matched[0]
    ev = {e["kind"]: e for e in rec.snapshot()}
    cow = ev["kv_cow"]
    assert cow["key_prefix"] == list(key_a)[:8]
    assert cow["key_len"] == bs
    assert cow["tenant"] == "alpha"
    pager.set_request(None)


# ---------------------------------------------------------------------------
# hot-path overhead guard
# ---------------------------------------------------------------------------

def test_recorder_overhead_under_5pct(monkeypatch):
    """The recorder must be cheap enough to leave on: min-of-repeats
    decode-loop wall time with recording on stays within 5% of the
    same loop with RAYTPU_FLIGHTREC=0 (record() early-returns)."""
    dep = _build(max_new_tokens=8)
    prompts = _prompts(4)

    def run_once():
        t0 = time.perf_counter()
        _drive(dep, prompts)
        return time.perf_counter() - t0

    def best(n=5):
        return min(run_once() for _ in range(n))

    _drive(dep, prompts)               # compile warmup (shared cache)
    monkeypatch.setenv("RAYTPU_FLIGHTREC", "0")
    off = best()
    monkeypatch.setenv("RAYTPU_FLIGHTREC", "1")
    on = best()
    assert on <= off * 1.05, (on, off)


# ---------------------------------------------------------------------------
# CLI functions
# ---------------------------------------------------------------------------

def _synthetic_doc():
    return {
        "version": 1, "source": "eng", "reason": "slo_breach_ttft",
        "created": "2026-01-01T00:00:00", "uptime_s": 9.0,
        "events_recorded": 5, "events_retained": 5,
        "events_dropped": 0,
        "counts_by_kind": {"admit": 1, "shed": 1, "step": 3},
        "context": {"objective": "ttft", "slo": {
            "breaches": 1,
            "objectives": {"ttft": {
                "target_ms": 10.0, "attainment": 0.5,
                "burn_rate": 2.5, "violations": 1, "samples": 2,
                "breached": True}}}},
        "events": [
            {"seq": 1, "t_s": 0.1, "kind": "admit", "req": "r0"},
            {"seq": 2, "t_s": 0.2, "kind": "step", "dur_ms": 5.0},
            {"seq": 3, "t_s": 0.3, "kind": "step", "dur_ms": 7.0},
            {"seq": 4, "t_s": 0.4, "kind": "shed", "req": "r1",
             "reason": "queue full"},
            {"seq": 5, "t_s": 0.5, "kind": "step", "dur_ms": 6.0},
        ],
    }


def test_filter_events_kind_window_last():
    ev = _synthetic_doc()["events"]
    assert [e["seq"] for e in filter_events(ev, kinds=["step"])] \
        == [2, 3, 5]
    assert [e["seq"] for e in filter_events(ev, since=0.25,
                                            until=0.45)] == [3, 4]
    assert [e["seq"] for e in filter_events(ev, kinds=["step"],
                                            last=1)] == [5]


def test_report_lines_summarize_breach():
    text = "\n".join(report_lines(_synthetic_doc()))
    assert "slo_breach_ttft" in text
    assert "events by kind: admit=1, shed=1, step=3" in text
    assert "step dur_ms: n=3" in text
    assert "<-- BREACHED" in text
    assert "last sheds:" in text and "queue full" in text


def test_report_lines_render_fleet_routing_table():
    doc = _synthetic_doc()
    doc["events"] = doc["events"] + [
        {"seq": 6, "t_s": 0.6, "kind": "route", "req": 0,
         "replica": "fleet/r0", "policy": "prefix_affinity",
         "tenant": "interactive", "matched_blocks": 3,
         "outstanding": 0},
        {"seq": 7, "t_s": 0.7, "kind": "route", "req": 1,
         "replica": "fleet/r1", "policy": "p2c", "tenant": "batch",
         "matched_blocks": 0, "outstanding": 1},
        {"seq": 8, "t_s": 0.8, "kind": "route", "req": 2,
         "replica": "fleet/r0", "policy": "prefix_affinity",
         "tenant": "batch", "matched_blocks": 2, "outstanding": 1},
        {"seq": 9, "t_s": 0.9, "kind": "scale_up", "n_before": 2,
         "n_after": 3, "reason": "burn_rate", "signal": 4.2},
        {"seq": 10, "t_s": 1.0, "kind": "scale_down", "n_before": 3,
         "n_after": 2, "reason": "idle", "signal": 31.0,
         "replica": "fleet/r2"},
        {"seq": 11, "t_s": 1.1, "kind": "drain", "replica": "fleet/r2",
         "ok": True, "blocks_in_use": 0, "drained_requests": 0},
    ]
    text = "\n".join(report_lines(doc))
    assert "routing table (route events by replica):" in text
    # per-replica aggregation: r0 got 2 prefix-affinity routes with
    # 3+2 matched blocks across both tenants; r1 one p2c fallback
    assert "fleet/r0  2  2  0  0  5  batch,interactive" in text
    assert "fleet/r1  1  0  1  0  0  batch" in text
    assert "last scale-ups:" in text and '"reason": "burn_rate"' in text
    assert "last scale-downs:" in text and '"reason": "idle"' in text
    assert "last drains:" in text and '"blocks_in_use": 0' in text


def test_trace_events_merge_and_lane():
    doc = _synthetic_doc()
    base = [{"ph": "X", "name": "engine step", "pid": 1}]
    ev = trace_events(doc, merge=base)
    assert ev[0] == base[0]              # merged lane keeps originals
    instants = [e for e in ev if e.get("ph") == "i"]
    assert len(instants) == 5
    assert {e["name"] for e in instants} == {"admit", "step", "shed"}
    assert all(e["cat"] == "flightrec" for e in instants)


def test_sweepjson_records_shape():
    recs = sweepjson_records(_synthetic_doc())
    by_name = {r["metric"]: r for r in recs}
    assert by_name["flightrec_events_retained"]["value"] == 5
    assert by_name["flightrec_shed_events"]["value"] == 1
    assert by_name["flightrec_step_p95_ms"]["unit"] == "ms"
    assert by_name["flightrec_ttft_burn_rate"]["value"] == 2.5
    assert by_name["flightrec_ttft_slo_attainment"]["value"] == 0.5
    # every record is perfledger-ingestable: metric + numeric value
    from ray_tpu.tools.perfledger import extract_metrics
    for r in recs:
        m = extract_metrics(r)
        assert list(m) == [r["metric"]]
    # direction: attainment counts as higher-is-better despite "ttft"
    m = extract_metrics(by_name["flightrec_ttft_slo_attainment"])
    assert m["flightrec_ttft_slo_attainment"]["higher_is_better"]


def test_cli_main_subcommands(tmp_path):
    rec = FlightRecorder("cli", enabled=True)
    rec.dump_dir = str(tmp_path)
    rec.record("admit", req="r0")
    rec.record("step", dur_ms=3.0)
    dump = rec.dump(reason="manual")

    assert flightrec_main(["report", dump]) == 0
    assert flightrec_main(["events", dump, "--kind", "step"]) == 0
    assert flightrec_main(["sweepjson", dump]) == 0
    out = str(tmp_path / "trace.json")
    assert flightrec_main(["trace", dump, "-o", out]) == 0
    with open(out) as f:
        assert any(e.get("ph") == "i" for e in json.load(f))
    # unreadable dump -> exit 2, not a traceback
    bad = tmp_path / "bad.json"
    bad.write_text("{}")
    assert flightrec_main(["report", str(bad)]) == 2
