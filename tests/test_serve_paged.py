"""Paged KV serve engine end-to-end: prefix reuse, copy-on-write,
pool exhaustion, and SLO shedding through the continuous scheduler.

These drive the deployment class directly (``dep.func_or_class()``)
on a private event loop — no serve cluster — so each test owns its
engine and its block pool.  The correctness oracle is always the
dense single-request ``generate`` path: whatever the pager shares,
forks, or recycles, every caller must get the bit-identical greedy
continuation it would have gotten alone on a dense cache."""

import asyncio

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from ray_tpu.serve.batching import (AdmissionPolicy,
                                    OverloadedError)  # noqa: E402
from ray_tpu.serve.llm import build_llm_deployment  # noqa: E402

MAX_NEW = 6
_OVR = {"dtype": jnp.float32, "use_flash": False, "remat": False}


def _build(family="gpt2", **kw):
    kw.setdefault("max_new_tokens", MAX_NEW)
    kw.setdefault("temperature", 0.0)
    kw.setdefault("scheduler", "continuous")
    kw.setdefault("kv_layout", "paged")
    kw.setdefault("kv_block_size", 16)
    kw.setdefault("prefill_bucket", 16)
    kw.setdefault("config_overrides", _OVR)
    return build_llm_deployment(family, "nano", **kw)


def _drive(dep, prompts, *, collect_stats=True, timeout=300):
    """Run all prompts concurrently on a fresh engine instance;
    returns (results, engine_stats).  OverloadedError results are
    returned as the exception instance, not raised."""
    async def main():
        inst = dep.func_or_class()
        try:
            outs = await asyncio.wait_for(
                asyncio.gather(*[inst(p) for p in prompts],
                               return_exceptions=True),
                timeout)
            stats = inst.engine_stats() if collect_stats else None
        finally:
            inst.shutdown_engine()
        return outs, stats

    outs, stats = asyncio.run(main())
    for o in outs:
        if isinstance(o, Exception) \
                and not isinstance(o, OverloadedError):
            raise o
    return outs, stats


def _oracle(family, prompt, max_new=MAX_NEW):
    """Dense solo greedy continuation — the parity reference."""
    if family == "gpt2":
        from ray_tpu.models import gpt2_config, gpt2_init
        from ray_tpu.models.gpt2_decode import generate
        cfg = gpt2_config("nano", **_OVR)
        params = gpt2_init(jax.random.PRNGKey(0), cfg)
    else:
        from ray_tpu.models import llama_config, llama_init
        from ray_tpu.models.llama_decode import llama_generate \
            as generate
        cfg = llama_config("nano", **_OVR)
        params = llama_init(jax.random.PRNGKey(0), cfg)
    out = generate(params, jnp.asarray(np.asarray(prompt)[None]), cfg,
                   max_new_tokens=max_new, temperature=0.0)
    return np.asarray(out)[0]


@pytest.mark.parametrize("family", ["gpt2", "llama"])
def test_shared_prefix_requests_match_dense_solo(family):
    """Two requests sharing a 32-token prefix: the second reuses the
    first's blocks (nonzero prefix-hit rate) yet both continuations
    are bit-identical to dense solo generation."""
    rng = np.random.RandomState(11)
    shared = rng.randint(2, 500, 32)
    a = np.concatenate([shared, rng.randint(2, 500, 3)]).astype(np.int32)
    b = np.concatenate([shared, rng.randint(2, 500, 2)]).astype(np.int32)

    dep = _build(family)

    # sequential so B deterministically sees A's registered blocks
    async def main():
        inst = dep.func_or_class()
        try:
            out_a = await inst(a)
            out_b = await inst(b)
            stats = inst.engine_stats()
        finally:
            inst.shutdown_engine()
        return out_a, out_b, stats

    out_a, out_b, stats = asyncio.run(main())
    np.testing.assert_array_equal(out_a, _oracle(family, a))
    np.testing.assert_array_equal(out_b, _oracle(family, b))
    kv = stats["kv_cache"]
    assert kv["prefix_block_hits"] >= 2      # B reused 2 full blocks
    assert kv["prefix_hit_rate"] > 0
    assert kv["blocks_in_use"] == 0          # everything retired
    assert stats["requests"]["finished"] == 2


def test_identical_prompt_cow_divergence():
    """A prompt that fully matches a resident prompt's blocks forks
    the boundary block (copy-on-write) instead of writing into it —
    and still reproduces the dense solo continuation bit-for-bit."""
    rng = np.random.RandomState(12)
    p = rng.randint(2, 500, 48).astype(np.int32)  # exactly 3 blocks

    dep = _build()

    async def main():
        inst = dep.func_or_class()
        try:
            out1 = await inst(p)
            out2 = await inst(p)          # full match -> COW fork
            stats = inst.engine_stats()
        finally:
            inst.shutdown_engine()
        return out1, out2, stats

    out1, out2, stats = asyncio.run(main())
    want = _oracle("gpt2", p)
    np.testing.assert_array_equal(out1, want)
    np.testing.assert_array_equal(out2, want)
    kv = stats["kv_cache"]
    assert kv["cow_copies"] >= 1
    assert kv["prefix_block_hits"] >= 1


def test_pool_exhaustion_requeues_and_recycles():
    """A pool sized for ~one request at a time: concurrent requests
    must wait for block recycling (requeue path), and every one still
    completes with the exact dense-solo continuation."""
    rng = np.random.RandomState(13)
    prompts = [rng.randint(2, 500, rng.randint(66, 74))
               .astype(np.int32) for _ in range(3)]
    # each request needs ceil((74+6)/16)=5 blocks; the minimum legal
    # pool (1 null + 8) fits only one active request, so concurrent
    # admissions hit the requeue path and later requests must evict
    # earlier prompts' cached blocks (LRU path)
    dep = _build(kv_num_blocks=9, max_slots=2)
    outs, stats = _drive(dep, prompts)
    for p, o in zip(prompts, outs):
        np.testing.assert_array_equal(o, _oracle("gpt2", p))
    assert stats["requests"]["finished"] == 3
    assert stats["kv_cache"]["blocks_in_use"] == 0
    # distinct 5-block prompts through an 8-block pool cannot avoid
    # evicting earlier prompts' cached prefix blocks
    assert stats["kv_cache"]["evictions"] >= 1


def test_admission_policy_sheds_under_overload():
    """queue-depth gate: with a 1-deep queue bound and a burst of
    concurrent requests, some callers get OverloadedError, the shed
    shows up in rejections_by_reason, and the engine still finishes
    the admitted work correctly."""
    rng = np.random.RandomState(14)
    prompts = [rng.randint(2, 500, 8).astype(np.int32)
               for _ in range(8)]
    dep = _build(max_slots=1,
                 admission_policy=AdmissionPolicy(max_queue_depth=1))
    outs, stats = _drive(dep, prompts)
    shed = [o for o in outs if isinstance(o, OverloadedError)]
    done = [o for o in outs if not isinstance(o, Exception)]
    assert shed, "expected at least one load-shed request"
    assert done, "engine must still serve admitted requests"
    assert stats["rejections_by_reason"].get("shed_queue_full", 0) \
        == len(shed)
    assert stats["requests"]["rejected"] == len(shed)
    assert stats["requests"]["finished"] == len(done)
    # policy knobs are surfaced for observability
    assert stats["admission_policy"]["max_queue_depth"] == 1


def test_admission_policy_slo_gate_requires_backlog():
    """The percentile gates only fire with a live backlog — an idle
    engine with terrible historical p95s must still admit."""
    pol = AdmissionPolicy(ttft_slo_ms=1.0, queue_wait_slo_ms=1.0)
    bad_history = {"ttft_ms": {"p95": 900.0},
                   "queue_wait_ms": {"p95": 900.0}}
    assert pol.decide(bad_history, queue_depth=0) is None
    assert pol.decide(bad_history, queue_depth=2) == "queue_wait_slo"
    pol2 = AdmissionPolicy(ttft_slo_ms=1.0)
    assert pol2.decide(bad_history, queue_depth=2) == "ttft_slo"
    # empty history (None percentiles) never sheds
    assert pol2.decide({"ttft_ms": {"p95": None}}, 2) is None


def test_paged_requires_continuous_scheduler():
    with pytest.raises(ValueError, match="paged"):
        build_llm_deployment("gpt2", "nano", scheduler="batch",
                             kv_layout="paged")
    with pytest.raises(ValueError, match="kv_layout"):
        build_llm_deployment("gpt2", "nano", scheduler="continuous",
                             kv_layout="sparse")
