"""K8s-style operator: RayCluster CR -> pod reconciliation.

Reference analog: python/ray/ray_operator/operator.py (legacy operator
reconciling RayCluster CRs); the TPU slice gang semantics are new.
"""

from ray_tpu.operator import (FakePodProvider, RayClusterOperator,
                              RayClusterSpec)

CR = {
    "metadata": {"name": "demo"},
    "spec": {
        "headGroupSpec": {"resources": {"CPU": 2}},
        "workerGroupSpecs": [
            {"groupName": "cpu", "replicas": 2, "maxReplicas": 4,
             "resources": {"CPU": 4}},
            {"groupName": "tpu", "replicas": 1, "maxReplicas": 2,
             "accelerator": "v5e", "topology": "4x4"},
        ],
    },
}


def make():
    prov = FakePodProvider()
    op = RayClusterOperator(prov)
    op.apply(CR)
    return prov, op


def test_initial_reconcile_creates_head_workers_and_slices():
    prov, op = make()
    op.reconcile()
    pods = prov.list_pods("demo")
    heads = [p for p in pods if p.group == "head"]
    cpus = [p for p in pods if p.group == "cpu"]
    tpus = [p for p in pods if p.group == "tpu"]
    assert len(heads) == 1
    assert len(cpus) == 2
    # v5e 4x4 = 16 chips / 4 per host = 4 hosts, gang-created
    assert len(tpus) == 4
    assert {p.host_index for p in tpus} == {0, 1, 2, 3}
    assert all(p.env["TPU_HOSTS_PER_SLICE"] == "4" for p in tpus)
    # idempotent: a second pass takes no actions
    assert op.reconcile() == 0


def test_failed_tpu_pod_tears_down_and_rebuilds_whole_slice():
    prov, op = make()
    op.reconcile()
    victim = [p for p in prov.list_pods("demo") if p.group == "tpu"][2]
    prov.fail_pod(victim.name)
    op.reconcile()   # tear down the 4-pod slice
    op.reconcile()   # rebuild it
    tpus = [p for p in prov.list_pods("demo") if p.group == "tpu"]
    assert len(tpus) == 4
    assert all(p.status == "running" for p in tpus)
    # all four original slice pods were deleted, not just the failed one
    assert len([n for n in prov.deleted if "-tpu-" in n]) == 4


def test_scale_up_down_and_cr_delete():
    prov, op = make()
    op.reconcile()
    cr2 = {"metadata": {"name": "demo"}, "spec": {
        "headGroupSpec": {"resources": {"CPU": 2}},
        "workerGroupSpecs": [
            {"groupName": "cpu", "replicas": 4, "maxReplicas": 4,
             "resources": {"CPU": 4}},
            {"groupName": "tpu", "replicas": 2, "maxReplicas": 2,
             "accelerator": "v5e", "topology": "4x4"},
        ]}}
    op.apply(cr2)
    op.reconcile()
    pods = prov.list_pods("demo")
    assert len([p for p in pods if p.group == "cpu"]) == 4
    assert len([p for p in pods if p.group == "tpu"]) == 8
    # scale back down: newest slice removed whole
    op.apply(CR)
    op.reconcile()
    pods = prov.list_pods("demo")
    assert len([p for p in pods if p.group == "cpu"]) == 2
    assert len([p for p in pods if p.group == "tpu"]) == 4
    # head failure repaired
    head = [p for p in pods if p.group == "head"][0]
    prov.fail_pod(head.name)
    op.reconcile()
    op.reconcile()
    assert [p for p in prov.list_pods("demo")
            if p.group == "head" and p.status == "running"]
    # CR deletion garbage-collects everything
    op.delete("demo")
    op.reconcile()
    assert prov.list_pods("demo") == []


def test_replicas_clamped_and_group_removal():
    prov = FakePodProvider()
    op = RayClusterOperator(prov)
    op.apply({"metadata": {"name": "c"}, "spec": {
        "workerGroupSpecs": [
            {"groupName": "w", "replicas": 99, "maxReplicas": 3,
             "resources": {"CPU": 1}}]}})
    op.reconcile()
    assert len([p for p in prov.list_pods("c") if p.group == "w"]) == 3
    # group dropped from the CR: its pods are deleted
    op.apply({"metadata": {"name": "c"}, "spec": {"workerGroupSpecs": []}})
    op.reconcile()
    assert [p for p in prov.list_pods("c") if p.group == "w"] == []


def test_spec_parse_tpu_hosts():
    spec = RayClusterSpec.from_dict(CR)
    assert spec.group("tpu").num_hosts == 4
    assert spec.group("cpu").num_hosts == 1
