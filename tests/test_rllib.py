"""RLlib slice tests: SampleBatch/GAE units, policy update mechanics,
and the PPO learning tier — CartPole reward must improve within a small
budget (the reference's check_learning_achieved pattern,
rllib/utils/test_utils.py:480, scaled down for CI)."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.rllib import PPO, PPOConfig, SampleBatch
from ray_tpu.rllib import sample_batch as sb
from ray_tpu.rllib.policy import JaxPolicy, PolicySpec
from ray_tpu.rllib.sample_batch import compute_gae


def test_sample_batch_concat_slice_shuffle():
    b1 = SampleBatch({"x": np.arange(4), "y": np.arange(4) * 2})
    b2 = SampleBatch({"x": np.arange(4, 6), "y": np.arange(4, 6) * 2})
    cat = SampleBatch.concat_samples([b1, b2])
    assert cat.count == 6
    assert list(cat.slice(2, 4)["x"]) == [2, 3]
    sh = cat.shuffle(np.random.RandomState(0))
    assert sorted(sh["x"]) == list(range(6))
    np.testing.assert_array_equal(sh["y"], sh["x"] * 2)


def test_gae_simple_case():
    # constant reward 1, value 0, no dones, gamma=lam=1: adv[t] = T-t + last
    r = np.ones(4, np.float32)
    v = np.zeros(4, np.float32)
    d = np.zeros(4, bool)
    adv, vt = compute_gae(r, v, d, last_value=0.0, gamma=1.0, lam=1.0)
    np.testing.assert_allclose(adv, [4, 3, 2, 1])
    np.testing.assert_allclose(vt, adv)
    # terminal cuts the bootstrap
    d2 = np.array([0, 1, 0, 0], bool)
    adv2, _ = compute_gae(r, v, d2, last_value=100.0, gamma=1.0, lam=1.0)
    np.testing.assert_allclose(adv2[:2], [2, 1])


def test_policy_update_reduces_loss():
    spec = PolicySpec(obs_dim=4, n_actions=2, hidden=(16,),
                      num_sgd_iter=4, minibatch_size=32, lr=5e-3)
    pol = JaxPolicy(spec, seed=0)
    rng = np.random.RandomState(0)
    n = 128
    obs = rng.randn(n, 4).astype(np.float32)
    actions, logp, vf = pol.compute_actions(obs)
    batch = SampleBatch({
        sb.OBS: obs, sb.ACTIONS: actions, sb.ACTION_LOGP: logp,
        sb.ADVANTAGES: rng.randn(n).astype(np.float32),
        sb.VALUE_TARGETS: rng.randn(n).astype(np.float32),
    })
    stats1 = pol.learn_on_batch(batch)
    stats2 = pol.learn_on_batch(batch)
    assert np.isfinite(stats1["total_loss"])
    assert stats2["vf_loss"] < stats1["vf_loss"]  # vf regression fits


def test_policy_weights_roundtrip():
    spec = PolicySpec(obs_dim=4, n_actions=2, hidden=(8,))
    p1 = JaxPolicy(spec, seed=0)
    p2 = JaxPolicy(spec, seed=99)
    obs = np.zeros((3, 4), np.float32)
    p2.set_weights(p1.get_weights())
    a1 = p1.compute_actions(obs)[2]
    a2 = p2.compute_actions(obs)[2]
    np.testing.assert_allclose(a1, a2, atol=1e-6)


def test_ppo_cartpole_learns(ray_start_shared):
    cfg = PPOConfig(
        env="CartPole-v1", num_workers=2, num_envs_per_worker=2,
        rollout_fragment_length=100, train_batch_size=800,
        minibatch_size=128, num_sgd_iter=6, lr=5e-3,
        entropy_coeff=0.0, hidden=(32, 32), seed=0)
    algo = PPO(cfg)
    try:
        first = None
        best = -np.inf
        for i in range(12):
            res = algo.train()
            rmean = res["episode_reward_mean"]
            if first is None and np.isfinite(rmean):
                first = rmean
            best = max(best, rmean if np.isfinite(rmean) else best)
        # CartPole starts ~20; PPO should clearly improve within 12 iters
        assert first is not None
        assert best > first + 30, (first, best)
        assert res["timesteps_total"] >= 12 * 800
    finally:
        algo.stop()
