"""Config validation for the ce_impl / flash_resident knobs: invalid
combinations raise ONE coherent ValueError listing every problem, with
pinned messages (issue round-6 satellite — replaces the scattered
ValueErrors the old use_streaming_ce path raised at loss time)."""

import pytest

from ray_tpu.models.gpt2 import (CE_IMPLS, FLASH_RESIDENT_MODES,
                                 GPT2Config, ce_config_problems,
                                 gpt2_config)
from ray_tpu.models.llama import llama_config

pytestmark = pytest.mark.fast


def test_valid_configs_construct():
    for impl in CE_IMPLS:
        for res in FLASH_RESIDENT_MODES:
            cfg = gpt2_config("nano", ce_impl=impl, flash_resident=res)
            assert cfg.ce_impl == impl
            assert cfg.flash_resident == res


def test_invalid_ce_impl_message():
    with pytest.raises(ValueError) as e:
        gpt2_config("nano", ce_impl="fused")
    msg = str(e.value)
    assert msg.startswith("invalid GPT2Config: ")
    assert ("ce_impl must be one of ('dense', 'streaming_xla', 'pallas') "
            "(got 'fused')") in msg


def test_loss_chunks_with_non_dense_impl():
    with pytest.raises(ValueError) as e:
        gpt2_config("nano", ce_impl="pallas", loss_chunks=4)
    assert ("loss_chunks=4 requires ce_impl='dense' (both bound the "
            "logits footprint; pick one)") in str(e.value)


def test_seq_parallel_with_streaming_impl():
    with pytest.raises(ValueError) as e:
        gpt2_config("nano", ce_impl="streaming_xla", seq_parallel=True)
    assert ("ce_impl='streaming_xla' needs an unsharded seq axis"
            in str(e.value))


def test_invalid_flash_resident_message():
    with pytest.raises(ValueError) as e:
        gpt2_config("nano", flash_resident="yes")
    assert ("flash_resident must be one of ('auto', 'on', 'off') "
            "(got 'yes')") in str(e.value)


def test_all_problems_reported_in_one_error():
    """An invalid combo reports EVERY conflict at once, not just the
    first check to trip."""
    with pytest.raises(ValueError) as e:
        gpt2_config("nano", ce_impl="pallas", loss_chunks=2,
                    seq_parallel=True, flash_resident="maybe")
    msg = str(e.value)
    assert "loss_chunks=2 requires ce_impl='dense'" in msg
    assert "needs an unsharded seq axis" in msg
    assert "flash_resident must be one of" in msg
    assert msg.count(";") >= 2  # three problems joined into one error


def test_use_streaming_ce_alias_normalized():
    cfg = gpt2_config("nano", use_streaming_ce=True)
    assert cfg.ce_impl == "streaming_xla"
    # explicit streaming_xla + the alias is redundant but consistent
    cfg2 = gpt2_config("nano", use_streaming_ce=True,
                       ce_impl="streaming_xla")
    assert cfg2.ce_impl == "streaming_xla"


def test_use_streaming_ce_conflicts_with_pallas():
    with pytest.raises(ValueError) as e:
        gpt2_config("nano", use_streaming_ce=True, ce_impl="pallas")
    assert ("use_streaming_ce is a deprecated alias for "
            "ce_impl='streaming_xla' and conflicts with "
            "ce_impl='pallas'") in str(e.value)


def test_llama_config_validated_too():
    with pytest.raises(ValueError) as e:
        llama_config("nano", ce_impl="fused")
    assert str(e.value).startswith("invalid LlamaConfig: ")
    with pytest.raises(ValueError):
        llama_config("nano", flash_resident="always")
    cfg = llama_config("nano", ce_impl="pallas", flash_resident="on")
    assert cfg.ce_impl == "pallas"


def test_ce_config_problems_is_pure():
    assert ce_config_problems("dense", "auto") == []
    assert ce_config_problems("dense", "auto", loss_chunks=8) == []
    assert len(ce_config_problems("bogus", "bogus")) == 2


def test_frozen_config_still_frozen():
    cfg = GPT2Config()
    with pytest.raises(Exception):
        cfg.ce_impl = "pallas"
