"""Test configuration.

Multi-device TPU-style tests run on a virtual 8-device CPU mesh (the
reference's `_fake_gpus` trick generalized: reference
rllib/algorithms/algorithm_config.py:66 places fake GPU towers on CPU; here
XLA emulates N host devices).  Must be set before jax import anywhere in the
test process.
"""

import os

# Force CPU even when the environment points JAX at a real TPU
# (JAX_PLATFORMS=axon); bench.py is what runs on the chip, not pytest.
os.environ["JAX_PLATFORMS"] = "cpu"
prev = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in prev:
    os.environ["XLA_FLAGS"] = (
        prev + " --xla_force_host_platform_device_count=8").strip()

# The perf observatory's AOT cost harvest (device_stats.instrument)
# adds one extra XLA compile per engine program; across the dozens of
# engine configs this suite builds that would eat real minutes of the
# tier-1 870s budget.  Default it off for tests — the observatory test
# opts back in explicitly for the programs it asserts on.
os.environ.setdefault("RAYTPU_DEVICE_STATS_COST", "0")

# A site hook may have force-registered a TPU backend and overridden
# jax_platforms at interpreter start; jax.config wins over the env var,
# so set it through jax.config too.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture
def ray_start_regular():
    """Start a fresh single-node cluster for the test (reference analog:
    python/ray/tests/conftest.py:245 ray_start_regular)."""
    import ray_tpu

    ray_tpu.init(num_cpus=4, object_store_memory=256 * 1024 * 1024)
    yield
    ray_tpu.shutdown()


@pytest.fixture(scope="module")
def ray_start_shared():
    """Module-shared cluster (reference analog: ray_start_regular_shared)."""
    import ray_tpu

    ray_tpu.init(num_cpus=4, object_store_memory=256 * 1024 * 1024)
    yield
    ray_tpu.shutdown()


@pytest.fixture(autouse=True)
def _tracing_isolation():
    """Reset util.tracing after every test: the fallback span list and
    the enabled flag are process globals, so without this a test that
    calls enable_tracing() leaks spans (and the enabled bit) into every
    later test in the same process."""
    yield
    from ray_tpu.util import tracing

    tracing.reset_tracing()
