"""Sequence-parallel attention vs the single-device oracle, on the fake
8-device mesh (4-way seq x 2-way data)."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from ray_tpu.ops.attention import reference_attention
from ray_tpu.ops.ring_attention import ring_attention, ulysses_attention
from ray_tpu.parallel import MeshSpec, fake_mesh


def _qkv(key, B, T, H, D, dtype=jnp.float32):
    ks = jax.random.split(key, 3)
    return tuple(jax.random.normal(k, (B, T, H, D), dtype) for k in ks)


@pytest.mark.parametrize("causal", [True, False])
def test_ring_attention_matches_reference(causal):
    mesh = fake_mesh(8, MeshSpec(data=2, seq=4))
    B, T, H, D = 2, 64, 4, 16
    q, k, v = _qkv(jax.random.PRNGKey(0), B, T, H, D)

    spec = P("data", "seq", None, None)
    f = jax.jit(jax.shard_map(
        functools.partial(ring_attention, causal=causal),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec))
    got = f(q, k, v)
    want = reference_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=2e-5, rtol=2e-5)


def test_ring_attention_gradients_match():
    mesh = fake_mesh(8, MeshSpec(data=2, seq=4))
    B, T, H, D = 2, 32, 2, 8
    q, k, v = _qkv(jax.random.PRNGKey(1), B, T, H, D)
    spec = P("data", "seq", None, None)

    ring = jax.shard_map(functools.partial(ring_attention, causal=True),
                         mesh=mesh, in_specs=(spec, spec, spec),
                         out_specs=spec)

    def loss_ring(q, k, v):
        return jnp.sum(jnp.sin(ring(q, k, v)))

    def loss_ref(q, k, v):
        return jnp.sum(jnp.sin(reference_attention(q, k, v, causal=True)))

    gr = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
    ge = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(gr, ge, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-4, rtol=1e-4,
                                   err_msg=f"d{name}")


@pytest.mark.parametrize("causal", [True, False])
def test_ulysses_matches_reference(causal):
    mesh = fake_mesh(8, MeshSpec(data=2, seq=4))
    B, T, H, D = 2, 64, 4, 16  # H=4 divisible by seq=4
    q, k, v = _qkv(jax.random.PRNGKey(2), B, T, H, D)
    spec = P("data", "seq", None, None)

    f = jax.jit(jax.shard_map(
        functools.partial(ulysses_attention, causal=causal,
                          attend_fn=None if causal else None),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec))
    got = f(q, k, v)
    want = reference_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=2e-5, rtol=2e-5)


def test_ring_attention_long_sequence_smoke():
    """Ring shards a sequence that would be heavy monolithically."""
    mesh = fake_mesh(8, MeshSpec(seq=8))
    B, T, H, D = 1, 1024, 2, 16
    q, k, v = _qkv(jax.random.PRNGKey(3), B, T, H, D, jnp.bfloat16)
    spec = P(None, "seq", None, None)
    f = jax.jit(jax.shard_map(
        functools.partial(ring_attention, causal=True),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec))
    out = f(q, k, v)
    assert out.shape == (B, T, H, D)
    assert np.isfinite(np.asarray(out, dtype=np.float32)).all()
