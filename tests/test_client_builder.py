"""ray_tpu.client() remote-driver builder (reference: ray.client / client_builder.py)."""

import ray_tpu

import pytest

pytestmark = pytest.mark.fast


def test_client_builder():
    ctx = ray_tpu.client().connect()
    try:
        assert ray_tpu.is_initialized()
        assert ctx.address

        @ray_tpu.remote
        def ping():
            return "pong"

        assert ray_tpu.get(ping.remote()) == "pong"
    finally:
        ctx.disconnect()
    assert not ray_tpu.is_initialized()
