"""End-to-end LM serving: model zoo GPT-2 + KV-cache generate behind a
serve deployment with @serve.batch — the framework's pieces composed
the way a user would (model, decode, replica batching, handles).
"""

import numpy as np
import pytest

import ray_tpu
from ray_tpu import serve


def test_serve_generates_text_batched(ray_start_shared):
    @serve.deployment
    class LM:
        def __init__(self):
            import jax
            import jax.numpy as jnp

            from ray_tpu.models import gpt2_config, gpt2_init
            from ray_tpu.models.gpt2_decode import generate

            self.cfg = gpt2_config("nano", dtype=jnp.float32,
                                   use_flash=False, remat=False)
            self.params = gpt2_init(jax.random.PRNGKey(0), self.cfg)
            self._generate = jax.jit(
                lambda p, toks: generate(p, toks, self.cfg,
                                         max_new_tokens=4,
                                         temperature=0.0))

        @serve.batch(max_batch_size=4, batch_wait_timeout_s=0.1)
        async def __call__(self, prompts):
            import jax.numpy as jnp

            # batch of equal-length prompts -> one jitted generate call
            toks = jnp.asarray(np.stack(prompts), jnp.int32)
            out = self._generate(self.params, toks)
            return [np.asarray(row) for row in out]

    handle = serve.run(LM.options(max_concurrent_queries=8).bind())
    try:
        prompts = [np.array([i, i + 1, i + 2]) for i in range(6)]
        refs = [handle.remote(p) for p in prompts]
        outs = ray_tpu.get(refs, timeout=120)
        for p, o in zip(prompts, outs):
            assert o.shape == (7,)
            np.testing.assert_array_equal(o[:3], p)
        # deterministic greedy decode: same prompt -> same continuation
        again = ray_tpu.get(handle.remote(prompts[0]), timeout=60)
        np.testing.assert_array_equal(again, outs[0])
    finally:
        serve.shutdown()


@pytest.mark.parametrize("family", ["gpt2", "llama"])
def test_build_llm_deployment_serves_both_families(ray_start_shared,
                                                   family):
    import jax.numpy as jnp

    from ray_tpu.serve import build_llm_deployment

    dep = build_llm_deployment(
        family, "nano", max_new_tokens=3, temperature=0.0,
        config_overrides={"dtype": jnp.float32, "use_flash": False,
                          "remat": False})
    handle = serve.run(dep.options(max_concurrent_queries=8).bind())
    try:
        prompts = [np.array([1, 2, 3]), np.array([4, 5, 6])]
        outs = ray_tpu.get([handle.remote(p) for p in prompts],
                           timeout=180)
        for p, o in zip(prompts, outs):
            assert o.shape == (6,)
            np.testing.assert_array_equal(o[:3], p)
    finally:
        serve.shutdown()


def test_build_llm_deployment_rejects_unknown_family():
    from ray_tpu.serve import build_llm_deployment

    with pytest.raises(ValueError, match="unknown LM family"):
        build_llm_deployment("bert")
