"""End-to-end LM serving: model zoo GPT-2 + KV-cache generate behind a
serve deployment with @serve.batch — the framework's pieces composed
the way a user would (model, decode, replica batching, handles).
"""

import numpy as np
import pytest

import ray_tpu
from ray_tpu import serve


def test_serve_generates_text_batched(ray_start_shared):
    @serve.deployment
    class LM:
        def __init__(self):
            import jax
            import jax.numpy as jnp

            from ray_tpu.models import gpt2_config, gpt2_init
            from ray_tpu.models.gpt2_decode import generate

            self.cfg = gpt2_config("nano", dtype=jnp.float32,
                                   use_flash=False, remat=False)
            self.params = gpt2_init(jax.random.PRNGKey(0), self.cfg)
            self._generate = jax.jit(
                lambda p, toks: generate(p, toks, self.cfg,
                                         max_new_tokens=4,
                                         temperature=0.0))

        @serve.batch(max_batch_size=4, batch_wait_timeout_s=0.1)
        async def __call__(self, prompts):
            import jax.numpy as jnp

            # batch of equal-length prompts -> one jitted generate call
            toks = jnp.asarray(np.stack(prompts), jnp.int32)
            out = self._generate(self.params, toks)
            return [np.asarray(row) for row in out]

    handle = serve.run(LM.options(max_concurrent_queries=8).bind())
    try:
        prompts = [np.array([i, i + 1, i + 2]) for i in range(6)]
        refs = [handle.remote(p) for p in prompts]
        outs = ray_tpu.get(refs, timeout=120)
        for p, o in zip(prompts, outs):
            assert o.shape == (7,)
            np.testing.assert_array_equal(o[:3], p)
        # deterministic greedy decode: same prompt -> same continuation
        again = ray_tpu.get(handle.remote(prompts[0]), timeout=60)
        np.testing.assert_array_equal(again, outs[0])
    finally:
        serve.shutdown()


@pytest.mark.parametrize("family", ["gpt2", "llama"])
def test_build_llm_deployment_serves_both_families(ray_start_shared,
                                                   family):
    import jax.numpy as jnp

    from ray_tpu.serve import build_llm_deployment

    dep = build_llm_deployment(
        family, "nano", max_new_tokens=3, temperature=0.0,
        config_overrides={"dtype": jnp.float32, "use_flash": False,
                          "remat": False})
    handle = serve.run(dep.options(max_concurrent_queries=8).bind())
    try:
        prompts = [np.array([1, 2, 3]), np.array([4, 5, 6])]
        outs = ray_tpu.get([handle.remote(p) for p in prompts],
                           timeout=180)
        for p, o in zip(prompts, outs):
            assert o.shape == (6,)
            np.testing.assert_array_equal(o[:3], p)
    finally:
        serve.shutdown()


def _reference_continuations(prompts, max_new_tokens):
    """Greedy single-request continuations straight off the decoder —
    what every serve scheduler must reproduce exactly."""
    import jax
    import jax.numpy as jnp

    from ray_tpu.models import gpt2_config, gpt2_init
    from ray_tpu.models.gpt2_decode import generate

    cfg = gpt2_config("nano", dtype=jnp.float32, use_flash=False,
                      remat=False)
    params = gpt2_init(jax.random.PRNGKey(0), cfg)
    return [np.asarray(generate(params,
                                jnp.asarray(p, jnp.int32)[None], cfg,
                                max_new_tokens=max_new_tokens,
                                temperature=0.0))[0]
            for p in prompts]


def test_llm_deployment_ragged_batch(ray_start_shared):
    # ragged prompts through the @serve.batch scheduler: left-padded
    # internally, each caller gets its own pad-free row back, and every
    # row matches single-request generation exactly
    import jax.numpy as jnp

    from ray_tpu.serve import build_llm_deployment

    dep = build_llm_deployment(
        "gpt2", "nano", max_new_tokens=4, temperature=0.0,
        batch_wait_timeout_s=0.2,
        config_overrides={"dtype": jnp.float32, "use_flash": False,
                          "remat": False})
    handle = serve.run(dep.options(max_concurrent_queries=16).bind())
    try:
        rng = np.random.RandomState(0)
        prompts = [rng.randint(1, 500, (n,)).astype(np.int32)
                   for n in (3, 7, 5, 7, 2, 6)]
        outs = ray_tpu.get([handle.remote(p) for p in prompts],
                           timeout=180)
        refs = _reference_continuations(prompts, 4)
        for p, o, r in zip(prompts, outs, refs):
            assert o.shape == (len(p) + 4,)
            np.testing.assert_array_equal(o, r)
    finally:
        serve.shutdown()


def test_llm_deployment_continuous_two_waves(ray_start_shared,
                                             tmp_path):
    # acceptance: >= 16 ragged requests in two waves through a slot
    # pool SMALLER than the request count; the second wave is admitted
    # mid-flight as first-wave slots free; every continuation matches
    # the single-request reference
    import json

    import jax.numpy as jnp

    from ray_tpu.serve import build_llm_deployment

    new = 6
    dep = build_llm_deployment(
        "gpt2", "nano", max_new_tokens=new, temperature=0.0,
        scheduler="continuous", max_slots=3, prefill_bucket=8,
        config_overrides={"dtype": jnp.float32, "use_flash": False,
                          "remat": False})
    handle = serve.run(dep.options(max_concurrent_queries=32).bind())
    try:
        rng = np.random.RandomState(1)
        lens = [3, 9, 5, 7, 4, 8, 6, 2] * 2          # 16 ragged
        prompts = [rng.randint(1, 500, (n,)).astype(np.int32)
                   for n in lens]
        wave1 = [handle.remote(p) for p in prompts[:8]]
        # second wave lands while wave 1 is still decoding
        wave2 = [handle.remote(p) for p in prompts[8:]]
        outs = ray_tpu.get(wave1 + wave2, timeout=300)
        refs = _reference_continuations(prompts, new)
        for p, o, r in zip(prompts, outs, refs):
            assert o.shape == (len(p) + new,)
            np.testing.assert_array_equal(o[:len(p)], p)
            np.testing.assert_array_equal(o, r)

        # --- engine telemetry over the same run -------------------
        stats = ray_tpu.get(handle.method("engine_stats").remote(),
                            timeout=60)
        assert stats["requests"]["enqueued"] == 16
        assert stats["requests"]["admitted"] == 16
        assert stats["requests"]["finished"] == 16
        assert stats["requests"]["rejected"] == 0
        assert stats["requests"]["active"] == 0
        # 16 requests through 3 slots: the pool MUST have run >1 slot
        # concurrently for the continuous scheduler to be doing its job
        assert stats["max_active_slots"] >= 2
        assert stats["max_slots"] == 3
        assert stats["ttft_ms"]["count"] == 16
        assert stats["queue_wait_ms"]["count"] == 16
        assert stats["ttft_ms"]["p50"] <= stats["ttft_ms"]["p95"]
        assert stats["request_latency_ms"]["count"] == 16
        assert stats["engine_steps"] > 0
        assert stats["tokens_generated"] > 0
        # every prompt fits one prefill_bucket=8 bucket (max len 9 -> 16)
        assert sum(stats["prefill_buckets"].values()) == 16
        assert stats["prefill_compiles"] == len(stats["prefill_buckets"])

        # Prometheus-side histograms populated on the replica
        snap = ray_tpu.get(handle.method("metrics_snapshot").remote(),
                           timeout=60)
        for hist in ("serve_ttft_ms", "serve_queue_wait_ms"):
            vals = dict((tuple(map(tuple, k)), v)
                        for k, v in snap[hist]["values"])
            counts = [v for k, v in vals.items()
                      if ("_stat", "count") in k]
            assert counts and sum(counts) >= 16

        # chrome-trace timeline: valid JSON, per-slot lanes with spans
        trace_path = tmp_path / "engine_trace.json"
        ray_tpu.get(handle.method("export_timeline").remote(
            str(trace_path)), timeout=60)
        events = json.loads(trace_path.read_text())
        lanes = {e["args"]["name"] for e in events
                 if e.get("ph") == "M" and e["name"] == "thread_name"}
        assert {"queue", "slot 0", "slot 1", "slot 2",
                "engine steps"} <= lanes
        spans = [e for e in events if e.get("ph") == "X"]
        assert all(e["dur"] >= 0 and "ts" in e for e in spans)
        slot_lanes_used = {e["tid"] for e in spans
                          if e["name"].startswith(("prefill", "decode"))}
        assert len(slot_lanes_used) >= 2       # >1 slot lane occupied
        assert any(e["name"] == "engine_step" for e in spans)
        assert sum(e["name"].startswith("decode") for e in spans) == 16
    finally:
        serve.shutdown()


def test_llm_deployment_rejects_oversized_prompt_continuous(
        ray_start_shared):
    import jax.numpy as jnp

    from ray_tpu.serve import build_llm_deployment

    dep = build_llm_deployment(
        "gpt2", "nano", max_new_tokens=8, temperature=0.0,
        scheduler="continuous", max_slots=2,
        config_overrides={"dtype": jnp.float32, "use_flash": False,
                          "remat": False})
    handle = serve.run(dep.options(max_concurrent_queries=4).bind())
    try:
        too_long = np.arange(1, 126, dtype=np.int32)  # 125+8 > 128
        with pytest.raises(Exception, match="prompt length"):
            ray_tpu.get(handle.remote(too_long), timeout=120)
        # pool must stay healthy for well-sized requests afterwards
        ok = np.array([1, 2, 3], np.int32)
        out = ray_tpu.get(handle.remote(ok), timeout=120)
        assert out.shape == (11,)
    finally:
        serve.shutdown()


def test_build_llm_deployment_rejects_unknown_family():
    from ray_tpu.serve import build_llm_deployment

    with pytest.raises(ValueError, match="unknown LM family"):
        build_llm_deployment("bert")
    with pytest.raises(ValueError, match="unknown scheduler"):
        build_llm_deployment("gpt2", scheduler="speculative")
