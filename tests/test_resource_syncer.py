"""Versioned resource syncer: ordered reports, optimistic spillback
debits, push-on-change freshness.

Reference analog: src/ray/common/ray_syncer/ray_syncer.h (versioned
reporter/receiver gossip) + the cluster resource scheduler's local debit
at decision time.  Unit tests drive the GcsServer rpc surface directly
(the reference pattern: gcs_server_test_util.h fake clients); one
integration test drives a live two-node cluster.
"""

import asyncio

import pytest

import ray_tpu
from ray_tpu._private.gcs import GcsServer
from ray_tpu.cluster_utils import Cluster

pytestmark = pytest.mark.fast

NODE_A = b"A" * 16
NODE_B = b"B" * 16


def _run(coro):
    return asyncio.new_event_loop().run_until_complete(coro)


def make_gcs():
    """rpc-surface-only GcsServer (the reference pattern of driving
    manager classes with fake clients, gcs_server_test_util.h)."""
    gcs = GcsServer.__new__(GcsServer)
    gcs.nodes = {}
    gcs._unschedulable = {}
    gcs._publish = lambda *a, **k: None

    class _Conn:
        pass

    async def reg():
        await gcs.rpc_node_register(_Conn(), {
            "node_id": NODE_A, "resources": {"CPU": 4.0},
            "address": "host-a:1"})
        await gcs.rpc_node_register(_Conn(), {
            "node_id": NODE_B, "resources": {"CPU": 4.0},
            "address": "host-b:1"})
        # B starts slightly used so A is the unique "most free" pick
        gcs.nodes[NODE_B].resources_available = {"CPU": 3.5}

    _run(reg())
    return gcs


def test_stale_report_dropped_equal_version_refreshes():
    gcs = make_gcs()
    a = gcs.nodes[NODE_A]

    async def drive():
        await gcs.rpc_node_resource_update(None, {
            "node_id": NODE_A, "resource_version": 5,
            "resources_available": {"CPU": 1.0}})
        assert a.resources_available == {"CPU": 1.0}
        # older version: reordered duplicate, dropped
        await gcs.rpc_node_resource_update(None, {
            "node_id": NODE_A, "resource_version": 3,
            "resources_available": {"CPU": 9.0}})
        assert a.resources_available == {"CPU": 1.0}
        # same version: authoritative refresh (reconciles debits)
        await gcs.rpc_node_resource_update(None, {
            "node_id": NODE_A, "resource_version": 5,
            "resources_available": {"CPU": 2.0}})
        assert a.resources_available == {"CPU": 2.0}

    _run(drive())
    assert a.resource_version == 5


def test_spillback_picks_debit_between_reports():
    """Two concurrent spillback picks off the same snapshot must not both
    land on the 'most free' node."""
    gcs = make_gcs()

    async def drive():
        r1 = await gcs.rpc_pick_node_for_lease(None, {
            "resources": {"CPU": 3.0}, "exclude": b""})
        r2 = await gcs.rpc_pick_node_for_lease(None, {
            "resources": {"CPU": 3.0}, "exclude": b""})
        return r1, r2

    r1, r2 = _run(drive())
    assert r1["node_id"] == NODE_A          # most free at snapshot time
    assert r2["node_id"] == NODE_B          # debit made A less attractive
    # a fresh versioned report reconciles the debit
    a = gcs.nodes[NODE_A]
    assert a.resources_available["CPU"] == pytest.approx(1.0)

    async def refresh():
        await gcs.rpc_node_heartbeat(None, {
            "node_id": NODE_A, "resource_version": 1,
            "resources_available": {"CPU": 4.0}})

    _run(refresh())
    assert a.resources_available == {"CPU": 4.0}


def test_actor_pick_debits_too():
    gcs = make_gcs()
    n1 = gcs._pick_node({"CPU": 3.0})
    n2 = gcs._pick_node({"CPU": 3.0})
    assert n1.node_id == NODE_A
    assert n2.node_id == NODE_B


def test_push_on_change_reaches_gcs_fast():
    """Acquiring resources on a node pushes a versioned update well
    before the next heartbeat (15s here, so only push-on-change can
    explain the GCS seeing the change within seconds)."""
    import time as _t

    ray_tpu.init(num_cpus=2, object_store_memory=64 * 1024 * 1024,
                 _system_config={"heartbeat_interval_s": 15.0,
                                 "resource_report_debounce_s": 0.02})
    try:
        @ray_tpu.remote(num_cpus=2)
        class Hog:
            def ping(self):
                return "ok"

        h = Hog.remote()
        assert ray_tpu.get(h.ping.remote(), timeout=30) == "ok"
        deadline = _t.time() + 5.0
        avail = None
        while _t.time() < deadline:
            avail = ray_tpu.available_resources().get("CPU", None)
            if avail == 0.0:
                break
            _t.sleep(0.05)
        assert avail == 0.0, f"GCS availability stayed stale: {avail}"
    finally:
        ray_tpu.shutdown()
