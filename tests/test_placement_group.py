"""Placement group public API over the GCS 2PC bundle reservation."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.util import (PlacementGroup, placement_group,
                          remove_placement_group)

pytestmark = pytest.mark.fast


def test_pg_create_ready_and_actor_placement(ray_start_regular):
    pg = placement_group([{"CPU": 1}, {"CPU": 1}], strategy="PACK")
    pg.ready(timeout=30)

    @ray_tpu.remote(num_cpus=1, placement_group=pg,
                    placement_group_bundle_index=0)
    class A:
        def ping(self):
            return "pong"

    a = A.remote()
    assert ray_tpu.get(a.ping.remote()) == "pong"
    remove_placement_group(pg)


def test_pg_infeasible_bundle_rejected(ray_start_regular):
    pg = placement_group([{"CPU": 64.0}])
    with pytest.raises(RuntimeError):
        pg.ready(timeout=5)


def test_pg_reserves_resources_exclusively(ray_start_regular):
    """A PG holding most CPUs starves non-PG leases (gang atomicity)."""
    pg = placement_group([{"CPU": 3}]).ready(timeout=30)

    @ray_tpu.remote(num_cpus=2)
    def heavy():
        return 1

    # 4-CPU node, 3 reserved: a 2-CPU task can't run until PG removed
    ref = heavy.remote()
    ready, not_ready = ray_tpu.wait([ref], timeout=1.5)
    assert not ready
    remove_placement_group(pg)
    assert ray_tpu.get(ref, timeout=30) == 1
