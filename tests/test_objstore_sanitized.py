"""Run the shared-memory store under AddressSanitizer.

Reference analog: the TSAN/ASAN bazel test configs (.bazelrc:92-113)
applied to the plasma store tests.  Builds the `make asan` variant of
objstore.cc and drives a multi-process create/seal/get/delete/evict
stress workload against it in sanitized subprocesses; any ASAN report
fails the test (the subprocess aborts non-zero).
"""

import os
import shutil
import subprocess
import sys
import textwrap

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LIB = os.path.join(REPO, "ray_tpu", "_private", "_lib",
                   "libobjstore_asan.so")

STRESS = textwrap.dedent("""
    import os, random, sys
    sys.path.insert(0, os.environ["REPO"])
    from ray_tpu._private.ids import ObjectID
    from ray_tpu._private.object_store import (ObjectStoreClient,
                                               ObjectStoreError,
                                               ObjectStoreFull)

    name = os.environ["STORE_NAME"]
    role = sys.argv[1]
    if role == "owner":
        store = ObjectStoreClient(name, create=True,
                                  capacity=16 * 1024 * 1024)
    else:
        store = ObjectStoreClient(name)
    rng = random.Random(int(sys.argv[2]))
    mine = []
    for i in range(300):
        op = rng.random()
        try:
            if op < 0.5:
                oid = ObjectID.from_random()
                store.put_bytes(oid, bytes(rng.randrange(1, 65536)))
                mine.append(oid)
            elif op < 0.8 and mine:
                oid = rng.choice(mine)
                buf = store.get(oid, timeout_ms=0)
                if buf is not None:
                    with buf:
                        assert len(buf.data) >= 0
            elif op < 0.9 and mine:
                store.delete(mine.pop(rng.randrange(len(mine))))
            else:
                store.evict(65536)
        except (ObjectStoreFull, ObjectStoreError):
            store.evict(1 << 20)
    store.close(destroy=(role == "owner"))
    print("STRESS-OK")
""")


@pytest.mark.slow
def test_objstore_stress_under_asan(tmp_path):
    if shutil.which("g++") is None:
        pytest.skip("no g++")
    r = subprocess.run(["make", "-C", os.path.join(REPO, "src"), "asan"],
                       capture_output=True, text=True)
    if r.returncode != 0:
        pytest.skip(f"asan build unavailable: {r.stderr[-200:]}")
    script = tmp_path / "stress.py"
    script.write_text(STRESS)
    env = dict(os.environ, REPO=REPO, STORE_NAME="asan_test_store",
               RAYTPU_OBJSTORE_LIB=LIB,
               ASAN_OPTIONS="detect_leaks=0,abort_on_error=1",
               LD_PRELOAD=_find_asan_rt())
    owner = subprocess.Popen([sys.executable, str(script), "owner", "1"],
                             env=env, stdout=subprocess.PIPE,
                             stderr=subprocess.PIPE, text=True)
    out, err = owner.communicate(timeout=300)
    assert owner.returncode == 0, f"ASAN failure:\n{err[-2000:]}"
    assert "STRESS-OK" in out


def _find_asan_rt() -> str:
    r = subprocess.run(["g++", "-print-file-name=libasan.so"],
                       capture_output=True, text=True)
    path = r.stdout.strip()
    return path if os.path.sep in path else ""
