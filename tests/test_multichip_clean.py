"""The multichip program must compile WITHOUT SPMD fallback warnings.

"Involuntary full rematerialization" (spmd_partitioner.cc) means a
sharding transition the partitioner could only solve by replicating a
tensor — correct but a perf cliff on real ICI.  Round-3 verdict: the
embedding-lookup gather and the loss take_along_axis under seq/tensor
sharding triggered it; these tests pin the fix.
"""

import os
import tempfile

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.models import gpt2_config, gpt2_init, gpt2_loss
from ray_tpu.models.gpt2 import _nll_from_logits


def test_nll_matches_gather_formulation():
    """Gather-free nll == take_along_axis nll (incl. padded-vocab mask)."""
    cfg = gpt2_config("nano", dtype=jnp.float32)
    B, T, V = 2, 8, cfg.padded_vocab
    rng = np.random.RandomState(0)
    logits = jnp.asarray(rng.randn(B, T, V).astype(np.float32))
    targets = jnp.asarray(rng.randint(0, cfg.vocab_size, (B, T)))

    got = _nll_from_logits(logits, targets, cfg)

    masked = logits.at[..., cfg.vocab_size:].set(-1e9)
    logp = jax.nn.log_softmax(masked, axis=-1)
    want = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.slow
def test_sharded_train_step_compiles_without_spmd_fallback():
    """Compile grad(gpt2_loss) over a dp×fsdp×seq×tensor mesh and assert
    XLA's C++ stderr contains no involuntary-rematerialization warning."""
    import optax

    from ray_tpu.models import gpt2_logical_axes
    from ray_tpu.parallel import MeshSpec, make_mesh
    from ray_tpu.parallel.sharding import param_shardings, shard_params

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    spec = MeshSpec(data=1, fsdp=2, seq=2, tensor=2)
    mesh = make_mesh(spec, devices=jax.devices()[:8])
    cfg = gpt2_config("tiny", use_flash=False, remat=True,
                      seq_parallel=True)
    params = gpt2_init(jax.random.PRNGKey(0), cfg)
    axes = gpt2_logical_axes(cfg)
    tx = optax.adamw(1e-3)

    with jax.set_mesh(mesh):
        params = shard_params(params, axes, mesh)
        opt_state = tx.init(params)
        p_shard = param_shardings(axes, mesh)

        @jax.jit
        def train_step(params, opt_state, batch):
            loss, grads = jax.value_and_grad(
                lambda p: gpt2_loss(p, batch, cfg))(params)
            updates, opt_state = tx.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), opt_state, loss

        tokens = jnp.zeros((8, 65), jnp.int32)

        # XLA emits the warning on C++ stderr — capture fd 2 around the
        # compile (python-level capsys/capfd miss direct fd writes when
        # pytest runs with -s or capture is reconfigured; dup2 is exact)
        stderr_fd = 2
        saved = os.dup(stderr_fd)
        with tempfile.TemporaryFile(mode="w+b") as tf:
            os.dup2(tf.fileno(), stderr_fd)
            try:
                compiled = train_step.lower(
                    params, opt_state, {"tokens": tokens}).compile()
            finally:
                os.dup2(saved, stderr_fd)
                os.close(saved)
            tf.seek(0)
            captured = tf.read().decode(errors="replace")
        assert "Involuntary full rematerialization" not in captured, \
            captured[-2000:]
        # and the compiled step actually runs
        _, _, loss = compiled(params, opt_state, {"tokens": tokens})
        assert np.isfinite(np.asarray(loss))
