"""Device-plane tests on the virtual 8-device CPU mesh."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from ray_tpu.parallel import (MeshSpec, make_mesh, fake_mesh,
                              parse_accelerator_type, logical_to_mesh_axes,
                              shard_params, DEFAULT_RULES, collective)
from ray_tpu.parallel.topology import SliceTopology, GENERATIONS, mfu


class TestTopology:
    def test_parse_v5e(self):
        t = parse_accelerator_type("v5e-8")
        assert t.generation.name == "v5e"
        assert t.num_chips == 8
        assert t.num_hosts == 2

    def test_parse_v3_cores(self):
        t = parse_accelerator_type("v3-32")  # 32 cores = 16 chips
        assert t.num_chips == 16

    def test_mesh_shape2d(self):
        assert SliceTopology(GENERATIONS["v5e"], 8).mesh_shape2d() == (4, 2)
        assert SliceTopology(GENERATIONS["v4"], 64).mesh_shape2d() == (8, 8)

    def test_bad(self):
        with pytest.raises(ValueError):
            parse_accelerator_type("h100-8")

    def test_mfu(self):
        t = parse_accelerator_type("v5e-8")
        # 100% MFU tokens/s for a 1B model on 8 chips
        peak = t.bf16_tflops * 1e12 / (6 * 1e9)
        assert abs(mfu(peak, int(1e9), t) - 1.0) < 1e-6


class TestMeshSpec:
    def test_resolve_wildcard(self):
        s = MeshSpec(data=-1, tensor=2).resolve(8)
        assert s.data == 4 and s.tensor == 2

    def test_resolve_exact(self):
        s = MeshSpec(fsdp=4, tensor=2).resolve(8)
        assert s.n_devices == 8

    def test_resolve_mismatch(self):
        with pytest.raises(ValueError):
            MeshSpec(data=3).resolve(8)
        with pytest.raises(ValueError):
            MeshSpec(data=-1, fsdp=-1).resolve(8)


class TestMesh:
    def test_make_mesh_axes(self):
        mesh = fake_mesh(8, MeshSpec(data=2, fsdp=2, tensor=2))
        assert mesh.shape["data"] == 2
        assert mesh.shape["tensor"] == 2
        assert mesh.shape["seq"] == 1
        assert mesh.devices.size == 8

    def test_default_all_data(self):
        mesh = fake_mesh(8)
        assert mesh.shape["data"] == 8


class TestShardingRules:
    def test_logical_to_mesh(self):
        spec = logical_to_mesh_axes(("batch", "seq", "embed"))
        assert spec[0] == ("data", "fsdp")
        assert spec[1] == "seq"
        # embed wants fsdp but batch already used it → replicated
        assert len(spec) == 2 or spec[2] is None

    def test_weight_axes(self):
        spec = logical_to_mesh_axes(("embed", "mlp"))
        assert spec == jax.sharding.PartitionSpec("fsdp", "tensor")

    def test_shard_params(self):
        mesh = fake_mesh(8, MeshSpec(fsdp=4, tensor=2))
        params = {"w": jnp.ones((8, 4)), "b": jnp.zeros((4,))}
        axes = {"w": ("embed", "mlp"), "b": ("mlp",)}
        sharded = shard_params(params, axes, mesh)
        shard_shape = sharded["w"].sharding.shard_shape((8, 4))
        assert shard_shape == (2, 2)  # 8/fsdp4, 4/tensor2


class TestXlaCollectives:
    def test_psum_shard_map(self):
        from jax import shard_map
        from jax.sharding import PartitionSpec as P

        mesh = fake_mesh(8, MeshSpec(data=8))
        x = jnp.arange(8.0)

        f = shard_map(lambda v: collective.xla_allreduce(v, "data"),
                      mesh=mesh,
                      in_specs=P("data"), out_specs=P("data"))
        out = f(x)
        np.testing.assert_allclose(np.asarray(out), np.full(8, 28.0))

    def test_broadcast(self):
        from jax import shard_map
        from jax.sharding import PartitionSpec as P

        mesh = fake_mesh(8, MeshSpec(data=8))
        x = jnp.arange(8.0)
        f = shard_map(lambda v: collective.xla_broadcast(v, "data", src=3),
                      mesh=mesh, in_specs=P("data"), out_specs=P("data"))
        np.testing.assert_allclose(np.asarray(f(x)), np.full(8, 3.0))


class TestObjstoreCollectives:
    def test_two_actor_allreduce(self, ray_start_shared):
        import ray_tpu

        @ray_tpu.remote
        class Member:
            def __init__(self, rank):
                collective.init_collective_group(2, rank, group_name="g2")
                self.rank = rank

            def run(self):
                out = collective.allreduce(
                    np.full(4, float(self.rank + 1)), group_name="g2")
                bc = collective.broadcast(
                    np.full(2, float(self.rank)), src_rank=1,
                    group_name="g2")
                return out, bc

        a = Member.remote(0)
        b = Member.remote(1)
        (r0, bc0), (r1, bc1) = ray_tpu.get([a.run.remote(), b.run.remote()])
        np.testing.assert_allclose(r0, np.full(4, 3.0))
        np.testing.assert_allclose(r1, np.full(4, 3.0))
        np.testing.assert_allclose(bc0, np.full(2, 1.0))
        np.testing.assert_allclose(bc1, np.full(2, 1.0))


def test_hybrid_mesh_multislice_collectives():
    """DCN+ICI hybrid mesh (2 virtual slices x 4 devices): data axis
    spans slices, tensor stays intra-slice, and a psum over each axis
    gives the right group sums (the multi-slice layout contract:
    bandwidth-hungry collectives ride ICI)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import PartitionSpec as P

    from ray_tpu.parallel import MeshSpec, make_hybrid_mesh

    devices = jax.devices()[:8]
    mesh = make_hybrid_mesh(MeshSpec(data=4, tensor=2), num_slices=2,
                            devices=devices)
    assert dict(zip(mesh.axis_names, mesh.devices.shape))["data"] == 4
    assert dict(zip(mesh.axis_names, mesh.devices.shape))["tensor"] == 2

    x = jnp.arange(8, dtype=jnp.float32).reshape(4, 2)

    def body(x):
        return (jax.lax.psum(x, "data"), jax.lax.psum(x, "tensor"))

    data_sum, tensor_sum = jax.shard_map(
        body, mesh=mesh, in_specs=P("data", "tensor"),
        out_specs=(P(None, "tensor"), P("data", None)))(x)
    np.testing.assert_allclose(np.asarray(data_sum)[0],
                               x.reshape(4, 2).sum(0))
    np.testing.assert_allclose(np.asarray(tensor_sum)[:, 0],
                               x.reshape(4, 2).sum(1))
