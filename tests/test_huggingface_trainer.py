"""HuggingFaceTrainer (transformers on the train gang) and
Dataset.iter_torch_batches.

Reference analogs: python/ray/train/huggingface/huggingface_trainer.py
and python/ray/data iterator.iter_torch_batches.
"""

import numpy as np
import pytest

import ray_tpu


def test_iter_torch_batches_roundtrip(ray_start_shared):
    import torch

    from ray_tpu import data

    ds = data.from_items([{"x": float(i), "y": i % 2}
                          for i in range(10)])
    batches = list(ds.iter_torch_batches(
        batch_size=4, dtypes={"x": torch.float32}))
    assert len(batches) == 3             # drop_last defaults False
    assert isinstance(batches[0]["x"], torch.Tensor)
    assert batches[0]["x"].dtype == torch.float32
    total = torch.cat([b["x"] for b in batches])
    np.testing.assert_allclose(np.sort(total.numpy()),
                               np.arange(10, dtype=np.float32))


def _tiny_hf_trainer(config):
    import torch
    import transformers

    class _DS(torch.utils.data.Dataset):
        def __len__(self):
            return 32

        def __getitem__(self, i):
            x = torch.randn(4, generator=torch.Generator()
                            .manual_seed(i))
            return {"x": x, "labels": (x.sum() > 0).long()}

    class _Model(torch.nn.Module):
        def __init__(self):
            super().__init__()
            self.lin = torch.nn.Linear(4, 2)

        def forward(self, x=None, labels=None):
            logits = self.lin(x)
            loss = torch.nn.functional.cross_entropy(logits, labels)
            return {"loss": loss, "logits": logits}

    args = transformers.TrainingArguments(
        output_dir=config["out_dir"], num_train_epochs=1,
        per_device_train_batch_size=8, logging_steps=2,
        report_to=[], use_cpu=True, save_strategy="no",
        disable_tqdm=True)
    return transformers.Trainer(model=_Model(), args=args,
                                train_dataset=_DS())


@pytest.mark.slow
def test_huggingface_trainer_end_to_end(ray_start_shared, tmp_path):
    from ray_tpu.air import ScalingConfig
    from ray_tpu.train import HuggingFaceTrainer

    trainer = HuggingFaceTrainer(
        _tiny_hf_trainer,
        trainer_init_config={"out_dir": str(tmp_path / "hf")},
        scaling_config=ScalingConfig(num_workers=1))
    result = trainer.fit()
    assert result.error is None, result.error
    assert "train_loss" in result.metrics \
        or "loss" in result.metrics, result.metrics
    # rank 0 captured the trained model as an AIR checkpoint
    assert result.checkpoint is not None
    path = result.checkpoint.to_directory()
    import os

    assert any(f.endswith((".bin", ".safetensors"))
               for f in os.listdir(path)), os.listdir(path)
