"""Fused pallas lm-head + cross-entropy kernel (ops/fused_ce.py):
interpret-mode numerics and gradients must match the dense logits path,
and the jitted computation must never materialize a (B, T, V) buffer
(detector shared with graftcheck's jaxpr auditor)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.ops.fused_ce import fused_lm_ce
from ray_tpu.tools.graftcheck import logits_sized_shapes

pytestmark = pytest.mark.fast


def _dense_ce(h, wte, targets, valid):
    logits = (h.astype(jnp.float32) @ wte.astype(jnp.float32).T)
    iota = jax.lax.broadcasted_iota(jnp.int32, logits.shape, 1)
    logits = jnp.where(iota < valid, logits, -jnp.inf)
    lse = jax.scipy.special.logsumexp(logits, axis=1)
    tgt = jnp.take_along_axis(logits, targets[:, None], axis=1)[:, 0]
    return lse - tgt


@pytest.mark.parametrize("n,d,v,valid,bn,bv", [
    (16, 32, 128, 100, 8, 64),    # padded vocab tail masked
    (8, 16, 96, 96, 8, 32),       # exact tiling, no padding
    (4, 8, 50, 50, 16, 64),       # tile > vocab: internal pad rows/cols
    (33, 24, 130, 123, 8, 64),    # n AND v non-divisible by the blocks
])
def test_forward_matches_dense(n, d, v, valid, bn, bv):
    rng = np.random.RandomState(0)
    h = jnp.asarray(rng.randn(n, d), jnp.float32)
    wte = jnp.asarray(rng.randn(v, d), jnp.float32)
    targets = jnp.asarray(rng.randint(0, valid, n), jnp.int32)
    got = fused_lm_ce(h, wte, targets, valid, block_n=bn, block_v=bv,
                      compute_dtype=jnp.float32, interpret=True)
    want = _dense_ce(h, wte, targets, valid)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("n,d,v,valid,bn,bv", [
    (16, 32, 128, 100, 8, 64),
    (33, 24, 130, 123, 8, 64),    # non-divisible n and v
])
def test_gradients_match_dense(n, d, v, valid, bn, bv):
    rng = np.random.RandomState(1)
    h = jnp.asarray(rng.randn(n, d), jnp.float32)
    wte = jnp.asarray(rng.randn(v, d), jnp.float32)
    targets = jnp.asarray(rng.randint(0, valid, n), jnp.int32)
    # non-uniform per-token weights exercise the cotangent scaling
    weights = jnp.asarray(rng.rand(n), jnp.float32)

    def loss_fused(h, w):
        return jnp.sum(weights * fused_lm_ce(
            h, w, targets, valid, block_n=bn, block_v=bv,
            compute_dtype=jnp.float32, interpret=True))

    def loss_dense(h, w):
        return jnp.sum(weights * _dense_ce(h, w, targets, valid))

    gh1, gw1 = jax.grad(loss_fused, argnums=(0, 1))(h, wte)
    gh2, gw2 = jax.grad(loss_dense, argnums=(0, 1))(h, wte)
    np.testing.assert_allclose(np.asarray(gh1), np.asarray(gh2),
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(gw1), np.asarray(gw2),
                               rtol=1e-4, atol=1e-5)
    if valid < v:
        # rows past valid_vocab are masked: exactly zero gradient
        assert np.abs(np.asarray(gw1[valid:])).max() < 1e-6


def test_bf16_compute_f32_accumulators():
    """bf16 MXU operands with f32 accumulation: bf16 x bf16 products are
    exact in f32, so a dense f32 oracle over bf16-cast inputs agrees to
    summation order (<= 1e-4)."""
    rng = np.random.RandomState(2)
    n, d, v, valid, bn, bv = 32, 64, 200, 180, 16, 128
    h = jnp.asarray(rng.randn(n, d), jnp.float32)
    wte = jnp.asarray(rng.randn(v, d), jnp.float32)
    targets = jnp.asarray(rng.randint(0, valid, n), jnp.int32)
    got = fused_lm_ce(h, wte, targets, valid, block_n=bn, block_v=bv,
                      compute_dtype=jnp.bfloat16, interpret=True)
    assert got.dtype == jnp.float32
    hb = h.astype(jnp.bfloat16).astype(jnp.float32)
    wb = wte.astype(jnp.bfloat16).astype(jnp.float32)
    want = _dense_ce(hb, wb, targets, valid)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-4, atol=1e-4)
    gh, gw = jax.grad(
        lambda a, b: jnp.mean(fused_lm_ce(
            a, b, targets, valid, block_n=bn, block_v=bv,
            compute_dtype=jnp.bfloat16, interpret=True)),
        argnums=(0, 1))(h, wte)
    assert np.all(np.isfinite(np.asarray(gh)))
    assert np.all(np.isfinite(np.asarray(gw)))


def test_invalid_valid_vocab_raises():
    h = jnp.zeros((4, 8), jnp.float32)
    w = jnp.zeros((16, 8), jnp.float32)
    t = jnp.zeros((4,), jnp.int32)
    with pytest.raises(ValueError, match="valid_vocab"):
        fused_lm_ce(h, w, t, 17, interpret=True)
    with pytest.raises(ValueError, match="valid_vocab"):
        fused_lm_ce(h, w, t, 0, interpret=True)


def _nano_cfgs():
    from ray_tpu.models import gpt2_config

    kw = dict(dtype=jnp.float32, use_flash=False, remat=False)
    return {
        "dense": gpt2_config("nano", ce_impl="dense", **kw),
        "streaming_xla": gpt2_config("nano", ce_impl="streaming_xla",
                                     vocab_tile=64, **kw),
        "pallas": gpt2_config("nano", ce_impl="pallas", ce_block_n=16,
                              ce_block_v=128, **kw),
    }


def test_gpt2_loss_equivalent_across_all_ce_impls():
    from ray_tpu.models import gpt2_init, gpt2_loss

    cfgs = _nano_cfgs()
    params = gpt2_init(jax.random.PRNGKey(0), cfgs["dense"])
    toks = jax.random.randint(jax.random.PRNGKey(1), (2, 17), 0,
                              cfgs["dense"].vocab_size)
    batch = {"tokens": toks}
    losses = {k: float(gpt2_loss(params, batch, c))
              for k, c in cfgs.items()}
    grads = {k: jax.grad(lambda p, c=c: gpt2_loss(p, batch, c))(params)
             for k, c in cfgs.items()}
    for k in ("streaming_xla", "pallas"):
        np.testing.assert_allclose(losses[k], losses["dense"], rtol=1e-5)
        np.testing.assert_allclose(
            np.asarray(grads[k]["wte"]), np.asarray(grads["dense"]["wte"]),
            rtol=2e-4, atol=1e-5)
        np.testing.assert_allclose(
            np.asarray(grads[k]["blocks"]["mlp"]["fc_w"]),
            np.asarray(grads["dense"]["blocks"]["mlp"]["fc_w"]),
            rtol=2e-4, atol=1e-5)


def test_gpt2_loss_pallas_masked_targets():
    """Masked positions must not contribute: pallas agrees with dense
    under a partial mask, and fully-masking a position changes nothing
    about the others."""
    from ray_tpu.models import gpt2_init, gpt2_loss

    cfgs = _nano_cfgs()
    params = gpt2_init(jax.random.PRNGKey(0), cfgs["dense"])
    toks = jax.random.randint(jax.random.PRNGKey(1), (2, 9), 0,
                              cfgs["dense"].vocab_size)
    mask = jnp.ones((2, 8), jnp.float32).at[1, 4:].set(0.0)
    batch = {"tokens": toks, "mask": mask}
    l_p = float(gpt2_loss(params, batch, cfgs["pallas"]))
    l_d = float(gpt2_loss(params, batch, cfgs["dense"]))
    np.testing.assert_allclose(l_p, l_d, rtol=1e-5)
    # garbage targets at masked positions must be inert
    toks2 = toks.at[1, 5:].set(0)
    l_p2 = float(gpt2_loss(params, {"tokens": toks2, "mask": mask},
                           cfgs["pallas"]))
    np.testing.assert_allclose(l_p2, l_p, rtol=1e-5)
    g = jax.grad(lambda p: gpt2_loss(p, batch, cfgs["pallas"]))(params)
    assert np.all(np.isfinite(np.asarray(g["wte"])))


def test_no_btv_buffer_in_pallas_jaxpr():
    """Acceptance: for ce_impl="pallas" no (B, T, V)- or (B*T, V)-shaped
    buffer may appear anywhere in the jitted loss or grad computation
    (the whole point of the fusion).  The dense path is checked to
    trigger the detector, guarding against a vacuous pass.  The
    detector is graftcheck's — the same rule the repo-wide audit
    enforces on every canonical program."""
    from ray_tpu.models import gpt2_init, gpt2_loss

    cfgs = _nano_cfgs()
    params = gpt2_init(jax.random.PRNGKey(0), cfgs["dense"])
    B, T = 2, 32
    toks = jax.random.randint(jax.random.PRNGKey(1), (B, T + 1), 0,
                              cfgs["dense"].vocab_size)
    batch = {"tokens": toks}
    vp = cfgs["dense"].padded_vocab

    dense_hits = logits_sized_shapes(
        lambda p: gpt2_loss(p, batch, cfgs["dense"]), (params,), B * T, vp)
    assert dense_hits, "detector is broken: dense path has a logits buffer"

    for fn in (lambda p: gpt2_loss(p, batch, cfgs["pallas"]),
               jax.grad(lambda p: gpt2_loss(p, batch, cfgs["pallas"]))):
        hits = logits_sized_shapes(fn, (params,), B * T, vp)
        assert not hits, f"(B*T, V)-sized buffers leaked: {hits}"
