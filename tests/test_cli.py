"""CLI end-to-end: start a real head process, join a worker node
process, attach a driver, run a task across them, status, stop."""

import os
import signal
import subprocess
import sys
import time

import pytest

import ray_tpu

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _spawn(args):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    return subprocess.Popen(
        [sys.executable, "-m", "ray_tpu", *args], env=env,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)


def _wait_line(proc, needle, timeout=30):
    deadline = time.monotonic() + timeout
    lines = []
    while time.monotonic() < deadline:
        line = proc.stdout.readline()
        if not line:
            time.sleep(0.05)
            continue
        lines.append(line)
        if needle in line:
            return line
    raise AssertionError(f"{needle!r} not seen in: {lines}")


def test_cli_cluster_end_to_end(tmp_path):
    head = _spawn(["start", "--head", "--host", "127.0.0.1", "--port",
                   "0", "--num-cpus", "0", "--num-tpus", "0"])
    worker = None
    try:
        line = _wait_line(head, "GCS at ")
        address = line.strip().split("GCS at ")[-1]
        worker = _spawn(["start", "--address", address, "--num-cpus",
                         "2", "--num-tpus", "0"])
        _wait_line(worker, "joined")

        ray_tpu.init(address=address, num_cpus=0, num_tpus=0)
        try:
            @ray_tpu.remote(num_cpus=1)
            def f(x):
                return x * 2

            assert ray_tpu.get([f.remote(21)], timeout=60)[0] == 42
            nodes = [n for n in ray_tpu.nodes() if n.get("Alive")]
            assert len(nodes) >= 3  # head + worker + driver's node
        finally:
            ray_tpu.shutdown()
    finally:
        for p in (worker, head):
            if p is not None:
                p.send_signal(signal.SIGTERM)
        for p in (worker, head):
            if p is not None:
                try:
                    p.wait(timeout=15)
                except subprocess.TimeoutExpired:
                    p.kill()


def test_cli_memory_and_dashboard_index(tmp_path):
    """`ray_tpu memory` reports per-node store stats; the dashboard
    serves its HTML frontend at /."""
    import json as json_mod
    import urllib.request

    import ray_tpu
    from ray_tpu.scripts import cli

    ray_tpu.init(num_cpus=2, object_store_memory=128 * 1024 * 1024)
    try:
        ray_tpu.get(ray_tpu.put(b"x" * 200_000))  # populate the store
        stats = list(cli._each_node_stats())
        assert stats and stats[0][1]["object_store"]["capacity"] > 0

        from ray_tpu.dashboard.app import start_dashboard

        url = start_dashboard(port=18266)
        with urllib.request.urlopen(url + "/", timeout=30) as r:
            html = r.read().decode()
        assert "ray_tpu dashboard" in html
        with urllib.request.urlopen(url + "/api/nodes", timeout=30) as r:
            nodes = json_mod.loads(r.read())
        assert nodes and nodes[0]["alive"]
    finally:
        ray_tpu.shutdown()
