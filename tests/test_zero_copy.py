"""Zero-copy get(): numpy values come back as read-only views pinned in
the shared arena; the pin releases when the arrays die.

Reference analog: plasma-backed numpy views
(store_provider/plasma_store_provider.h + SerializationContext zero-copy
reads); here the pin-lifetime is tied to the arrays by weakref
finalizers (client._deserialize_store_buffer).
"""

import gc

import numpy as np
import pytest

import ray_tpu


@pytest.fixture
def small_store():
    # arena sized so ~3 x 8MB objects fit: eviction pressure is real
    ray_tpu.init(num_cpus=2, object_store_memory=32 * 1024 * 1024)
    yield
    ray_tpu.shutdown()


MB8 = 8 * 1024 * 1024 // 8  # float64 elements


def test_get_returns_readonly_view_and_value(small_store):
    src = np.arange(MB8, dtype=np.float64)
    ref = ray_tpu.put(src)
    out = ray_tpu.get(ref)
    np.testing.assert_array_equal(out, src)
    # zero-copy indicator: the result does not own its data and is
    # immutable (shared-memory objects are immutable by contract)
    assert not out.flags.owndata
    assert not out.flags.writeable


def test_pin_released_on_gc_under_pressure(small_store):
    """Filling the arena succeeds because dead zero-copy views release
    their pins (a held pin would make the old objects unevictable)."""
    for i in range(8):  # 8 x 8MB through a 32MB arena
        ref = ray_tpu.put(np.full(MB8, i, dtype=np.float64))
        out = ray_tpu.get(ref)
        assert out[0] == i
        del ref, out
        gc.collect()


def test_live_view_survives_new_puts(small_store):
    """A live zero-copy view pins its object: later puts must not
    corrupt it even under arena pressure."""
    src = np.arange(MB8, dtype=np.float64)
    keep = ray_tpu.get(ray_tpu.put(src))
    checksum_before = float(keep.sum())
    refs = []
    for i in range(3):
        refs.append(ray_tpu.put(np.full(MB8 // 2, i, dtype=np.float64)))
    assert float(keep.sum()) == checksum_before
    np.testing.assert_array_equal(keep, src)


def test_tuple_and_dict_of_arrays_zero_copy(small_store):
    a = np.arange(1000, dtype=np.float32)
    b = np.arange(1000, dtype=np.int64)
    t = ray_tpu.get(ray_tpu.put((a, {"b": b})))
    np.testing.assert_array_equal(t[0], a)
    np.testing.assert_array_equal(t[1]["b"], b)
    assert not t[0].flags.writeable


class Opaque:
    """Array hidden from the shallow walk: must fall back to copying."""

    def __init__(self, arr):
        self.arr = arr


def test_opaque_container_falls_back_to_copy(small_store):
    src = np.arange(4096, dtype=np.float64)
    out = ray_tpu.get(ray_tpu.put(Opaque(src)))
    np.testing.assert_array_equal(out.arr, src)
    # fallback path: safe regardless of who holds the value; the arena
    # pin is already released, so pressure cannot corrupt it
    for i in range(6):
        ray_tpu.put(np.full(MB8 // 2, i, dtype=np.float64))
    np.testing.assert_array_equal(out.arr, src)


def test_memoized_duplicate_with_hidden_array_falls_back(small_store):
    """[a, a, Opaque(b)]: pickle memoizes `a` into ONE oob buffer, so a
    naive count would let Opaque's hidden buffer escape the pin — the
    walk must dedupe by identity and take the copy path."""
    import gc

    a = np.arange(MB8 // 4, dtype=np.float64)
    b = np.arange(MB8 // 4, dtype=np.float64) * 2
    out = ray_tpu.get(ray_tpu.put([a, a, Opaque(b)]))
    hidden = out[2].arr
    checksum = float(hidden.sum())
    del out
    gc.collect()
    # churn the arena: if `hidden` aliased an unpinned region this would
    # corrupt it
    for i in range(6):
        ray_tpu.put(np.full(MB8 // 2, i, dtype=np.float64))
    assert float(hidden.sum()) == checksum
    np.testing.assert_array_equal(hidden, b)


def test_zero_copy_disabled_flag(tmp_path):
    ray_tpu.init(num_cpus=1, object_store_memory=32 * 1024 * 1024,
                 _system_config={"zero_copy_get": False})
    try:
        src = np.arange(4096, dtype=np.float64)
        out = ray_tpu.get(ray_tpu.put(src))
        np.testing.assert_array_equal(out, src)
    finally:
        ray_tpu.shutdown()
