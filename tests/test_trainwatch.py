"""Trainwatch: step anatomy exact-sum, goodput, the health watchdog's
postmortem path, checkpoint accounting, and the <5% overhead guard.

The acceptance invariants this file pins (ISSUE 14):

* an injected NaN loss at step k triggers a watchdog dump WITHIN one
  step whose postmortem names the step index, trainer, and batch
  signature;
* ``train_stats()["anatomy"]`` legs sum EXACTLY to the measured step
  wall — per raw step, across jit and 8-virtual-device mesh steps
  (the same clamp-construction contract as serve's critical path);
* recording stays within 5% of the uninstrumented loop
  (``RAYTPU_TRAINWATCH=0`` early-returns), mirroring flightrec's
  guard;
* ``train_stats()`` keeps its golden schema (the dashboard
  ``/api/train/stats`` and bench ``--train`` pattern-match it).
"""

import json
import time

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402
import optax  # noqa: E402

from ray_tpu.train.goodput import (ANATOMY_COMPONENTS,  # noqa: E402
                                   GoodputTracker, dominant_component,
                                   get_goodput_tracker,
                                   get_health_watchdog,
                                   get_train_recorder,
                                   instrument_trainwatch, watch_data,
                                   worker_skew)
from ray_tpu.train.jax_trainer import jax_utils  # noqa: E402
from ray_tpu.train.telemetry import train_stats  # noqa: E402

SUMMARY_KEYS = {"count", "mean", "p50", "p95", "p99", "max"}

#: every key train_stats() promises, regardless of configuration
TOP_KEYS = {"trainer", "steps", "compiles", "examples",
            "examples_per_sec", "step_time_ms", "anatomy", "goodput",
            "health", "checkpoint", "flightrec"}

ANATOMY_KEYS = {"step_wall_ms", *ANATOMY_COMPONENTS}

GOODPUT_KEYS = {"ratio", "productive_s", "wall_s", "steps", "window"}

HEALTH_KEYS = {"observed", "anomalies", "last_anomaly", "loss",
               "grad_norm", "z_threshold", "dumps"}

CHECKPOINT_KEYS = {"saves", "restores", "bytes_written", "bytes_read",
                   "last_step", "save_ms", "restore_ms"}

FLIGHTREC_KEYS = {"enabled", "capacity", "recorded", "retained",
                  "dropped", "dumps"}


def _mse_loss(params, batch):
    pred = batch["x"] @ params["w"]
    return jnp.mean((pred - batch["y"]) ** 2)


def _batches(n, seed=0, poison_at=None):
    rng = np.random.RandomState(seed)
    for i in range(n):
        batch = {"x": rng.randn(8, 4).astype(np.float32),
                 "y": rng.randn(8, 2).astype(np.float32)}
        if i == poison_at:
            batch["x"][0, 0] = np.nan
        yield batch


def _assert_exact_sum(tracker):
    steps = tracker.last_steps()
    assert steps, "no steps recorded"
    for rec in steps:
        comp_sum = sum(rec[c] for c in ANATOMY_COMPONENTS)
        assert comp_sum == pytest.approx(rec["step_wall_ms"],
                                         rel=1e-9, abs=1e-9), rec


# ---------------------------------------------------------------------------
# NaN injection -> watchdog postmortem within one step
# ---------------------------------------------------------------------------

def test_nan_loss_triggers_watchdog_dump(tmp_path, monkeypatch):
    monkeypatch.setenv("RAYTPU_FLIGHTREC_DIR", str(tmp_path))
    name = "tw_nan"
    tx = optax.sgd(0.01)
    params = {"w": jnp.ones((4, 2))}
    step = jax_utils.build_train_step(_mse_loss, tx, health=True,
                                      telemetry_name=name)
    opt_state = tx.init(params)
    poison_at = 4
    for i, batch in enumerate(_batches(6, poison_at=poison_at)):
        params, opt_state, loss, scalars = step(params, opt_state,
                                                batch)
        wd = step.watchdog
        if i == poison_at:
            # the dump landed before the poisoned call returned —
            # detection latency is ONE step, not an epoch
            assert wd.anomalies >= 1
            assert len(wd.dumps) == 1
    doc = json.loads(open(wd.dumps[0]).read())
    ctx = doc["context"]
    assert doc["source"] == f"train:{name}"
    assert doc["reason"].startswith("train_anomaly_nonfinite")
    assert ctx["trainer"] == name
    assert ctx["step"] == poison_at
    assert ctx["signature"]          # batch signature named
    assert ctx["trail"][-1]["step"] == poison_at
    # the journal carries both the per-step trail and the anomaly
    assert doc["counts_by_kind"].get("train_step", 0) >= poison_at
    assert doc["counts_by_kind"].get("train_anomaly", 0) >= 1
    # cooldown: the second NaN step did not produce a second dump
    assert len(wd.dumps) == 1
    st = train_stats(name)
    assert st["health"]["anomalies"] >= 1
    assert st["health"]["last_anomaly"]["reason"].startswith(
        "nonfinite")
    assert st["health"]["dumps"] == wd.dumps


def test_loss_spike_detection():
    wd = get_health_watchdog("tw_spike", z_threshold=4.0)
    for i in range(20):
        assert wd.observe(i, 1.0 + 0.01 * (i % 3)) is None
    anomaly = wd.observe(20, 50.0)
    assert anomaly is not None
    assert anomaly["reason"] == "loss_spike"
    assert anomaly["metric"] == "loss"


# ---------------------------------------------------------------------------
# anatomy exact-sum: jit and 8-virtual-device mesh steps
# ---------------------------------------------------------------------------

def test_anatomy_sums_exactly_jit_step():
    name = "tw_sum_jit"
    tx = optax.sgd(0.01)
    params = {"w": jnp.ones((4, 2))}
    step = jax_utils.build_train_step(_mse_loss, tx,
                                      telemetry_name=name)
    opt_state = tx.init(params)
    it = watch_data(_batches(5), trainer=name)
    for batch in it:
        params, opt_state, loss = step(params, opt_state, batch)
    tracker = step.goodput
    _assert_exact_sum(tracker)
    st = train_stats(name)
    assert st["anatomy"]["step_wall_ms"]["count"] == 5
    # first call is the compile leg; later calls are device time
    assert st["anatomy"]["compile_ms"]["max"] > 0
    assert st["goodput"]["ratio"] is not None
    # pooled means also reconstruct the wall (same sample count)
    comp_mean = sum(st["anatomy"][c]["mean"]
                    for c in ANATOMY_COMPONENTS)
    assert comp_mean == pytest.approx(
        st["anatomy"]["step_wall_ms"]["mean"], rel=1e-6, abs=1e-3)


def test_anatomy_sums_exactly_mesh_step():
    from ray_tpu.models import (gpt2_config, gpt2_init,
                                gpt2_logical_axes, gpt2_loss)
    from ray_tpu.parallel import MeshSpec, fake_mesh

    mesh = fake_mesh(8, MeshSpec(data=4, tensor=2))
    name = "tw_sum_mesh"
    cfg = gpt2_config("nano", max_seq=32, use_flash=False,
                      dtype=jnp.float32)
    axes = gpt2_logical_axes(cfg)
    tx = optax.sgd(1e-3)
    params = gpt2_init(jax.random.PRNGKey(0), cfg)
    step = jax_utils.build_train_step(
        lambda p, b: gpt2_loss(p, b, cfg), tx, mesh=mesh,
        logical_axes=axes, telemetry_name=name)
    from ray_tpu.parallel.sharding import shard_params

    rng = np.random.RandomState(0)
    # legacy mesh-context spelling (jax.set_mesh where available)
    set_mesh = getattr(jax, "set_mesh", None)
    with (set_mesh(mesh) if set_mesh is not None else mesh):
        params = shard_params(params, axes, mesh)
        opt_state = tx.init(params)
        for _ in range(3):
            batch = {"tokens": rng.randint(
                0, cfg.vocab_size, size=(4, 33)).astype(np.int32)}
            params, opt_state, loss = step(params, opt_state, batch)
    _assert_exact_sum(step.goodput)
    assert train_stats(name)["anatomy"]["step_wall_ms"]["count"] == 3


def test_data_wait_probe_attributes_input_stalls():
    name = "tw_stall"
    tracker = get_goodput_tracker(name)

    def slow_batches():
        for _ in range(4):
            time.sleep(0.02)
            yield {"x": np.zeros((2, 2), np.float32)}

    def fast_step(params, opt_state, batch):
        return params, opt_state, 0.0

    step = instrument_trainwatch(fast_step, tracker=tracker)
    params = opt_state = None
    for batch in watch_data(slow_batches(), tracker=tracker):
        params, opt_state, _ = step(params, opt_state, batch)
    _assert_exact_sum(tracker)
    st = train_stats(name)
    assert st["anatomy"]["data_wait_ms"]["p50"] >= 15.0
    assert dominant_component(st["anatomy"]) == "data_wait_ms"
    # the goodput ratio reads input-bound: almost nothing productive
    assert st["goodput"]["ratio"] < 0.5
    # ...and autopilot attribution cites it
    from ray_tpu.tools.autopilot import attribution

    rep = attribution.attribute({}, train_anatomy=st)
    assert "input-bound" in rep["summary"]
    assert rep["train_anatomy"] is st


def test_checkpoint_pause_lands_in_anatomy_and_counters(tmp_path):
    name = "tw_ckpt"
    from ray_tpu.train.checkpointing import (restore_sharded,
                                             save_sharded)

    tree = {"w": jnp.arange(12.0).reshape(3, 4)}
    target = save_sharded(tree, str(tmp_path / "ck"), step=7,
                          trainer=name)
    restored = restore_sharded(str(tmp_path / "ck"), step=7,
                               trainer=name)
    np.testing.assert_allclose(np.asarray(restored["w"]),
                               np.asarray(tree["w"]))
    tracker = get_goodput_tracker(name)
    rec = tracker.record_step(0.001)   # pause drains into this step
    assert rec["checkpoint_ms"] > 0
    _assert_exact_sum(tracker)
    blk = train_stats(name)["checkpoint"]
    assert blk["saves"] == 1 and blk["restores"] == 1
    assert blk["bytes_written"] == 12 * 4
    assert blk["bytes_read"] == 12 * 4
    assert blk["last_step"] == 7
    assert blk["save_ms"]["count"] == 1
    kinds = get_train_recorder(name).counts_by_kind()
    assert kinds.get("ckpt_save") == 1
    assert kinds.get("ckpt_restore") == 1


# ---------------------------------------------------------------------------
# grad-accum steps are no longer invisible
# ---------------------------------------------------------------------------

def test_accumulated_step_instrumented_and_parity():
    from ray_tpu.train.grad_accum import accumulated_train_step

    name = "tw_accum"
    tx = optax.sgd(0.01)
    params = {"w": jnp.ones((4, 2))}
    opt_state = tx.init(params)
    batch = {"x": jnp.asarray(np.random.RandomState(0)
                              .randn(8, 4), jnp.float32),
             "y": jnp.asarray(np.random.RandomState(1)
                              .randn(8, 2), jnp.float32)}
    plain = accumulated_train_step(_mse_loss, tx, num_microbatches=4)
    wired = accumulated_train_step(_mse_loss, tx, num_microbatches=4,
                                   telemetry=True,
                                   telemetry_name=name)
    p_ref, _, loss_ref = jax.jit(plain)(params, opt_state, batch)
    p_got, _, loss_got = wired(params, opt_state, batch)
    assert float(loss_got) == pytest.approx(float(loss_ref), rel=1e-6)
    np.testing.assert_allclose(np.asarray(p_got["w"]),
                               np.asarray(p_ref["w"]), rtol=1e-6)
    wired(params, opt_state, batch)
    st = train_stats(name)
    assert st["steps"] == 2          # step-time telemetry sees it
    assert st["compiles"] >= 1       # ...and its compile event
    assert st["anatomy"]["step_wall_ms"]["count"] == 2
    _assert_exact_sum(wired.goodput)


# ---------------------------------------------------------------------------
# the jitted health path adds no host transfer
# ---------------------------------------------------------------------------

_FORBIDDEN_PRIMS = {"pure_callback", "io_callback", "debug_callback",
                    "infeed", "outfeed", "device_put", "host_callback"}


def _prims(closed_jaxpr):
    out = set()

    def walk(jx):
        for eqn in jx.eqns:
            out.add(eqn.primitive.name)
            for v in eqn.params.values():
                if hasattr(v, "jaxpr"):
                    walk(v.jaxpr if hasattr(v.jaxpr, "eqns")
                         else v.jaxpr.jaxpr)

    walk(closed_jaxpr.jaxpr)
    return out


def test_health_scalars_add_no_host_transfer():
    tx = optax.sgd(0.01)
    params = {"w": jnp.ones((4, 2))}
    opt_state = tx.init(params)
    batch = {"x": jnp.zeros((8, 4)), "y": jnp.zeros((8, 2))}
    healthy = jax_utils.build_train_step(
        _mse_loss, tx, health=True, telemetry_name="tw_jaxpr")
    jaxpr = jax.make_jaxpr(healthy._raw_step)(params, opt_state, batch)
    bad = _prims(jaxpr) & _FORBIDDEN_PRIMS
    assert not bad, f"health scalars introduced host transfer: {bad}"
    # and the scalars really are step outputs, not side channels
    out = healthy(params, opt_state, batch)
    assert len(out) == 4
    scalars = jax.device_get(out[3])
    assert set(scalars) == {"loss", "grad_norm", "nonfinite"}
    assert int(scalars["nonfinite"]) == 0


# ---------------------------------------------------------------------------
# overhead guard (mirrors flightrec's)
# ---------------------------------------------------------------------------

def test_trainwatch_overhead_under_5pct(monkeypatch):
    """Recording must be cheap enough to leave on: min-of-repeats
    per-step wall with trainwatch on stays within 5% of the same step
    with RAYTPU_TRAINWATCH=0 (the wrapper early-returns).

    The step body is a fixed 5ms host wait, not a jitted matmul: on
    the 8-virtual-device CPU test rig, XLA compute itself jitters by
    more than the 5% budget, which would measure the machine, not the
    wrapper.  A deterministic-duration step isolates exactly what this
    guard is about — the wrapper's added host cost (a signature hash,
    two perf_counter reads, one locked dict append; ~10-50us) against
    a representative ms-scale train-step wall."""
    batch = {"x": np.zeros((4, 4), np.float32)}

    def fenced_step(params, opt_state, b):
        time.sleep(0.005)

    # enabled is latched at tracker construction, so build both
    # wrappers first, then interleave the timed blocks — per-step
    # minimum per arm, so machine drift hits both arms equally
    monkeypatch.setenv("RAYTPU_TRAINWATCH", "0")
    off_step = instrument_trainwatch(
        fenced_step, tracker=GoodputTracker("tw_ovr_off"))
    monkeypatch.setenv("RAYTPU_TRAINWATCH", "1")
    on_step = instrument_trainwatch(
        fenced_step, tracker=GoodputTracker("tw_ovr_on"))

    def min_step(step, n=30):
        best = float("inf")
        for _ in range(n):
            t0 = time.perf_counter()
            step(None, None, batch)
            best = min(best, time.perf_counter() - t0)
        return best

    min_step(off_step, 3), min_step(on_step, 3)   # wrapper warmup
    off = min(min_step(off_step) for _ in range(3))
    on = min(min_step(on_step) for _ in range(3))
    assert on <= off * 1.05, (on, off)


# ---------------------------------------------------------------------------
# golden schema
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("stepped", [False, True],
                         ids=["fresh", "stepped"])
def test_train_stats_schema(stepped, tmp_path, monkeypatch):
    monkeypatch.setenv("RAYTPU_FLIGHTREC_DIR", str(tmp_path))
    name = f"tw_schema_{'stepped' if stepped else 'fresh'}"
    if stepped:
        tx = optax.sgd(0.01)
        params = {"w": jnp.ones((4, 2))}
        step = jax_utils.build_train_step(_mse_loss, tx, health=True,
                                          telemetry_name=name)
        opt_state = tx.init(params)
        for batch in _batches(3):
            params, opt_state, _, _ = step(params, opt_state, batch)
    stats = train_stats(name)
    missing = TOP_KEYS - set(stats)
    assert not missing, f"train_stats() lost keys: {missing}"
    assert set(stats["anatomy"]) == ANATOMY_KEYS
    for comp in stats["anatomy"].values():
        assert set(comp) == SUMMARY_KEYS
    assert set(stats["goodput"]) == GOODPUT_KEYS
    assert set(stats["health"]) == HEALTH_KEYS
    for m in ("loss", "grad_norm"):
        assert set(stats["health"][m]) == {"last", "ewma", "ewma_std"}
    assert set(stats["checkpoint"]) == CHECKPOINT_KEYS
    assert set(stats["checkpoint"]["save_ms"]) == SUMMARY_KEYS
    assert set(stats["flightrec"]) == FLIGHTREC_KEYS
    assert set(stats["step_time_ms"]) == SUMMARY_KEYS
    if stepped:
        assert stats["anatomy"]["step_wall_ms"]["count"] == 3
        assert stats["goodput"]["steps"] == 3
        assert stats["health"]["observed"] == 3
        assert stats["flightrec"]["recorded"] >= 3
    else:
        assert stats["anatomy"]["step_wall_ms"]["count"] == 0
        assert stats["goodput"]["ratio"] is None
        assert stats["health"]["observed"] == 0


# ---------------------------------------------------------------------------
# multi-worker skew
# ---------------------------------------------------------------------------

def test_worker_skew_flags_stragglers():
    rep = worker_skew({"w0": 100.0, "w1": 104.0, "w2": 98.0,
                       "w3": 210.0})
    assert rep["workers"] == 4
    assert rep["stragglers"] == ["w3"]
    assert rep["spread"] > 1.0
    even = worker_skew({"w0": 100.0, "w1": 101.0})
    assert even["stragglers"] == []
    # 2-worker fleet, one 2x slower: the even-count median must not
    # BE the straggler (true median, not upper-middle)
    two = worker_skew({"w0": 100.0, "w1": 200.0})
    assert two["stragglers"] == ["w1"]
    assert worker_skew({})["workers"] == 0


# ---------------------------------------------------------------------------
# postmortem CLI renders the train lanes
# ---------------------------------------------------------------------------

def test_flightrec_report_renders_train_lanes():
    from ray_tpu.tools.flightrec import report_lines, sweepjson_records

    doc = {
        "version": 1, "source": "train:t0",
        "reason": "train_anomaly_nonfinite_loss",
        "created": "2026-08-06T00:00:00", "uptime_s": 2.0,
        "events_recorded": 5, "events_retained": 5,
        "events_dropped": 0,
        "counts_by_kind": {"train_step": 3, "train_anomaly": 1,
                           "ckpt_save": 1},
        "context": {"trainer": "t0", "step": 2,
                    "reason": "nonfinite_loss", "metric": "loss",
                    "value": "nan",
                    "trail": [{"step": 1, "loss": 0.5},
                              {"step": 2, "loss": "nan"}]},
        "events": [
            {"seq": 1, "t_s": 0.1, "kind": "train_step", "step": 0,
             "loss": 0.7, "wall_ms": 12.0},
            {"seq": 2, "t_s": 0.2, "kind": "train_step", "step": 1,
             "loss": 0.5, "wall_ms": 11.0},
            {"seq": 3, "t_s": 0.25, "kind": "ckpt_save", "step": 1,
             "dur_ms": 4.0, "bytes": 48},
            {"seq": 4, "t_s": 0.3, "kind": "train_step", "step": 2,
             "loss": "nan", "wall_ms": 13.0},
            {"seq": 5, "t_s": 0.3, "kind": "train_anomaly", "step": 2,
             "reason": "nonfinite_loss", "metric": "loss",
             "value": "nan"},
        ],
    }
    text = "\n".join(report_lines(doc))
    assert "train steps: n=3" in text
    assert "train anomalies" in text
    assert "2  loss  nan  nonfinite_loss" in text
    assert "trainer=t0" in text
    assert "ckpt_save" in text
    assert "metric trail" in text
    recs = sweepjson_records(doc)
    assert any(r["metric"] == "flightrec_train_anomaly_events"
               and r["value"] == 1 for r in recs)


# ---------------------------------------------------------------------------
# perfledger direction for the new metrics
# ---------------------------------------------------------------------------

def test_perfledger_goodput_direction():
    from ray_tpu.tools.perfledger import (_SWEEP_FIELDS,
                                          higher_is_better)

    assert higher_is_better("train_goodput")
    assert not higher_is_better("train_data_wait_ms_p99")
    for f in ("train_goodput", "train_data_wait_ms_p50",
              "train_data_wait_ms_p99"):
        assert f in _SWEEP_FIELDS
