"""RLlib model catalog: conv stacks, LSTM wrapper, pixel env + learning
gates (reference analogs: rllib/models/catalog.py:195 ModelCatalog,
models/torch/visionnet.py, recurrent_net.py + rnn_sequencing.py, and
the PPO-pixels pass bar of
release/rllib_tests/.../ppo-breakoutnoframeskip-v4.yaml)."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.rllib import sample_batch as sb
from ray_tpu.rllib.envs import MinAtarBreakoutVecEnv, RepeatPrevVecEnv
from ray_tpu.rllib.models import (Encoder, ModelConfig, conv_out_dim,
                                  default_conv_filters)
from ray_tpu.rllib.policy import (JaxPolicy, PolicySpec, STATE_C,
                                  STATE_H)
from ray_tpu.rllib.ppo import PPO, PPOConfig


def test_catalog_picks_conv_for_rank3():
    enc = Encoder((10, 10, 3), ModelConfig(fcnet_hiddens=(32,)))
    assert enc.filters == default_conv_filters((10, 10, 3))
    assert enc.feature_dim == 32
    import jax

    params = enc.init(jax.random.PRNGKey(0))
    assert "conv" in params
    out = enc.apply(params, np.zeros((4, 10, 10, 3), np.float32))
    assert out.shape == (4, 32)


def test_catalog_atari_scale_stack():
    filters = default_conv_filters((84, 84, 4))
    assert len(filters) == 3  # Atari-class three-layer stack
    assert conv_out_dim((84, 84, 4), filters) > 0


def test_mlp_for_rank1_unchanged():
    enc = Encoder((7,), ModelConfig(fcnet_hiddens=(16, 8)))
    assert enc.filters is None and enc.feature_dim == 8


def test_conv_policy_forward_and_update():
    spec = PolicySpec(obs_dim=8 * 8 * 3, n_actions=3, hidden=(32,),
                      obs_shape=(8, 8, 3), minibatch_size=16,
                      num_sgd_iter=2)
    pol = JaxPolicy(spec, seed=0)
    obs = np.random.RandomState(0).rand(16, 8, 8, 3).astype(np.float32)
    actions, logp, vf = pol.compute_actions(obs)
    assert actions.shape == (16,) and vf.shape == (16,)
    assert set(np.asarray(actions)) <= {0, 1, 2}
    batch_data = {
        sb.OBS: obs, sb.ACTIONS: actions, sb.ACTION_LOGP: logp,
        sb.ADVANTAGES: np.random.randn(16).astype(np.float32),
        sb.VALUE_TARGETS: np.zeros(16, np.float32),
        sb.DONES: np.zeros(16, bool),
    }
    from ray_tpu.rllib.sample_batch import SampleBatch

    stats = pol.learn_on_batch(SampleBatch(batch_data))
    assert np.isfinite(stats["total_loss"])


def test_minatar_env_mechanics():
    env = MinAtarBreakoutVecEnv(2, size=8, seed=3)
    obs = env.vector_reset(seed=3)
    assert obs.shape == (2, 8, 8, 3)
    assert obs[:, 1:4, :, 2].all()  # brick rows filled
    assert obs[:, :, :, 1].sum(axis=(1, 2)).tolist() == [1.0, 1.0]
    total_rew = np.zeros(2)
    terms_seen = False
    for _ in range(300):
        obs, rew, terms, truncs, infos = env.vector_step(
            np.zeros(2, np.int64))
        total_rew += rew
        assert obs.shape == (2, 8, 8, 3)
        assert "final_obs" in infos
        terms_seen = terms_seen or terms.any()
    # a noop policy must eventually lose the ball (termination path) —
    # and the ball bouncing straight up/down off the center paddle can
    # also break bricks (reward path exercised in the learning test)
    assert terms_seen


def test_repeat_prev_reward_semantics():
    env = RepeatPrevVecEnv(4, n_symbols=3, seed=0)
    obs = env.vector_reset(seed=0)
    # acting with the CURRENT symbol on the first step scores (prev is
    # seeded equal to the first symbol)
    sym = obs.argmax(axis=1)
    _, rew, *_ = env.vector_step(sym)
    assert rew.tolist() == [1.0] * 4
    # echoing the previous symbol always scores
    prev = env._prev.copy()
    _, rew, *_ = env.vector_step(prev)
    assert rew.tolist() == [1.0] * 4


def test_recurrent_logp_alignment(ray_start_shared):
    """Replaying a recorded fragment through the seq loss with unchanged
    params must reproduce the rollout logp exactly (state columns line
    up) — the invariant rnn_sequencing exists for."""
    import jax
    import jax.numpy as jnp

    from ray_tpu.rllib.models import lstm_step, mlp_apply
    from ray_tpu.rllib.rollout_worker import RolloutWorker

    spec = PolicySpec(obs_dim=3, n_actions=3, hidden=(16,),
                      use_lstm=True, lstm_cell_size=8, max_seq_len=8,
                      minibatch_size=4)
    w = RolloutWorker(env="RepeatPrev", policy_spec=spec, num_envs=4,
                      rollout_fragment_length=32, seed=0)
    batch = w.sample()
    assert batch[sb.OBS].shape == (16, 8, 3)  # 4 envs x 4 chunks
    assert batch[STATE_H].shape == (16, 8)

    params = w.policy.params
    enc = w.policy.encoder
    obs = jnp.asarray(batch[sb.OBS])
    S, L = obs.shape[:2]
    feats = enc.apply(params["enc"],
                      obs.reshape((S * L,) + enc.obs_shape))
    feats_t = jnp.swapaxes(feats.reshape(S, L, -1), 0, 1)
    dones_t = jnp.swapaxes(
        jnp.asarray(batch[sb.DONES], jnp.float32), 0, 1)

    def step(carry, xs):
        f, d = xs
        h, c = lstm_step(params["lstm"], carry, f)
        m = (1.0 - d)[:, None]
        return (h * m, c * m), h

    _, hs = jax.lax.scan(step, (jnp.asarray(batch[STATE_H]),
                                jnp.asarray(batch[STATE_C])),
                         (feats_t, dones_t))
    logits = mlp_apply(params["pi"], jnp.swapaxes(hs, 0, 1))
    logp_all = jax.nn.log_softmax(logits)
    logp = jnp.take_along_axis(
        logp_all, jnp.asarray(batch[sb.ACTIONS])[..., None].astype(
            jnp.int32), axis=-1)[..., 0]
    np.testing.assert_allclose(np.asarray(logp),
                               batch[sb.ACTION_LOGP], atol=1e-5)


@pytest.mark.slow
def test_lstm_solves_memory_task(ray_start_shared):
    """The LSTM policy must clearly beat the feedforward information
    ceiling on RepeatPrev (chance ≈ ep_len/n_symbols ≈ 22 of 64)."""
    cfg = PPOConfig(env="RepeatPrev", num_workers=2,
                    num_envs_per_worker=8, rollout_fragment_length=64,
                    train_batch_size=2048, num_sgd_iter=6,
                    minibatch_size=32, hidden=(64,), use_lstm=True,
                    lstm_cell_size=64, max_seq_len=16, lr=1e-3,
                    entropy_coeff=0.003, gamma=0.9, seed=1)
    algo = PPO(cfg)
    reward = 0.0
    for _ in range(25):
        r = algo.train()
        reward = r.get("episode_reward_mean", 0.0)
    algo.cleanup()
    assert reward > 40.0, f"LSTM stuck at chance: {reward}"


@pytest.mark.slow
def test_cnn_ppo_learns_pixels(ray_start_shared):
    """PPO through the conv policy must learn MinAtar breakout well past
    the noop/random floor (~0.2) — the in-repo analog of the
    reference's PPO-on-Breakout-pixels pass bar."""
    cfg = PPOConfig(env="MinAtarBreakout", env_config={"size": 8},
                    num_workers=2, num_envs_per_worker=8,
                    rollout_fragment_length=128, train_batch_size=2048,
                    num_sgd_iter=4, minibatch_size=256, hidden=(128,),
                    lr=7e-4, entropy_coeff=0.02, seed=1)
    algo = PPO(cfg)
    reward = 0.0
    for _ in range(16):
        r = algo.train()
        reward = max(reward, r.get("episode_reward_mean", 0.0))
    algo.cleanup()
    assert reward > 0.9, f"conv policy failed to learn: {reward}"


def test_attention_logp_alignment(ray_start_shared):
    """Replaying a recorded fragment through the attention seq loss with
    unchanged params must reproduce the rollout logp exactly — the
    chunk-local context + segment-mask design exists for this."""
    import jax.numpy as jnp

    from ray_tpu.rllib.rollout_worker import RolloutWorker

    spec = PolicySpec(obs_dim=3, n_actions=3, hidden=(16,),
                      use_attention=True, attention_dim=16,
                      attention_heads=2, max_seq_len=8,
                      minibatch_size=4)
    w = RolloutWorker(env="RepeatPrev", policy_spec=spec, num_envs=4,
                      rollout_fragment_length=32, seed=0)
    batch = w.sample()
    assert batch[sb.OBS].shape == (16, 8, 3)
    assert STATE_H not in batch  # attention carries no state columns

    (_, stats) = w.policy._loss(
        w.policy.params, {k: jnp.asarray(np.asarray(v))
                          for k, v in batch.items()})
    # ratio == 1 under unchanged params <=> recomputed logp == stored
    # (policy_loss is then exactly -mean(advantages))
    adv = batch[sb.ADVANTAGES]
    np.testing.assert_allclose(float(stats["policy_loss"]),
                               -float(np.mean(adv)), rtol=1e-4,
                               atol=1e-5)


@pytest.mark.slow
def test_attention_solves_memory_task(ray_start_shared):
    """The GTrXL-style attention policy must beat the feedforward
    information ceiling on RepeatPrev, like the LSTM does."""
    cfg = PPOConfig(env="RepeatPrev", num_workers=2,
                    num_envs_per_worker=8, rollout_fragment_length=64,
                    train_batch_size=2048, num_sgd_iter=6,
                    minibatch_size=32, hidden=(64,),
                    use_attention=True, attention_dim=64,
                    attention_heads=4, max_seq_len=16, lr=2e-3,
                    entropy_coeff=0.003, gamma=0.9, seed=1)
    algo = PPO(cfg)
    reward = 0.0
    for _ in range(30):
        r = algo.train()
        reward = r.get("episode_reward_mean", 0.0)
    algo.cleanup()
    assert reward > 40.0, f"attention policy stuck at chance: {reward}"
