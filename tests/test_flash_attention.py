"""Flash-attention kernel numerics vs the XLA reference oracle.

Runs the pallas kernels in interpreter mode on CPU (pallas_call
interpret=True) — real-TPU execution is covered by bench.py on the chip.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.ops.attention import reference_attention
from ray_tpu.ops.flash_attention import flash_attention


def _rand_qkv(key, B, T, H, D, dtype=jnp.float32):
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (B, T, H, D), dtype)
    k = jax.random.normal(kk, (B, T, H, D), dtype)
    v = jax.random.normal(kv, (B, T, H, D), dtype)
    return q, k, v


@pytest.mark.parametrize("causal", [True, False])
def test_flash_forward_matches_reference(causal):
    q, k, v = _rand_qkv(jax.random.PRNGKey(0), 2, 128, 2, 64)
    got = flash_attention(q, k, v, causal=causal, block_q=32, block_k=32,
                          interpret=True)
    want = reference_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5,
                               rtol=2e-5)


def test_flash_forward_uneven_blocks():
    # T not a multiple of the requested block → block shrink path
    q, k, v = _rand_qkv(jax.random.PRNGKey(1), 1, 96, 1, 64)
    got = flash_attention(q, k, v, causal=True, block_q=64, block_k=64,
                          interpret=True)
    want = reference_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5,
                               rtol=2e-5)


@pytest.mark.parametrize("causal", [True, False])
def test_flash_gradients_match_reference(causal):
    q, k, v = _rand_qkv(jax.random.PRNGKey(2), 1, 64, 2, 32)

    def loss_flash(q, k, v):
        o = flash_attention(q, k, v, causal=causal, block_q=32, block_k=32,
                            interpret=True)
        return jnp.sum(o * jnp.cos(o))

    def loss_ref(q, k, v):
        o = reference_attention(q, k, v, causal=causal)
        return jnp.sum(o * jnp.cos(o))

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(gf, gr, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4,
                                   rtol=1e-4, err_msg=f"d{name}")


@pytest.mark.parametrize("causal", [True, False])
def test_resident_kv_forward_matches_reference(causal):
    # whole-kv-resident kernel with the in-kernel causal-early-stop loop
    q, k, v = _rand_qkv(jax.random.PRNGKey(4), 2, 256, 2, 64)
    got = flash_attention(q, k, v, causal=causal, resident_kv=True,
                          interpret=True)
    want = reference_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5,
                               rtol=2e-5)


@pytest.mark.parametrize("causal", [True, False])
def test_resident_kv_gradients_match_reference(causal):
    q, k, v = _rand_qkv(jax.random.PRNGKey(5), 1, 256, 2, 32)

    def loss_flash(q, k, v):
        o = flash_attention(q, k, v, causal=causal, resident_kv=True,
                            interpret=True)
        return jnp.sum(o * jnp.cos(o))

    def loss_ref(q, k, v):
        o = reference_attention(q, k, v, causal=causal)
        return jnp.sum(o * jnp.cos(o))

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(gf, gr, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4,
                                   rtol=1e-4, err_msg=f"d{name}")


def test_resident_kv_multi_chunk_gradients():
    # T large enough that bq=256/chunk=512 runs multiple loop trips with
    # a qi-dependent bound — exercises the dynamic-trip-count path.
    q, k, v = _rand_qkv(jax.random.PRNGKey(6), 1, 1024, 1, 32)

    def loss_flash(q, k, v):
        o = flash_attention(q, k, v, causal=True, resident_kv=True,
                            interpret=True)
        return jnp.sum(o * o)

    def loss_ref(q, k, v):
        o = reference_attention(q, k, v, causal=True)
        return jnp.sum(o * o)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(gf, gr, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4,
                                   rtol=1e-4, err_msg=f"d{name}")


def test_flash_bf16_close_to_f32_reference():
    q, k, v = _rand_qkv(jax.random.PRNGKey(3), 1, 128, 2, 64, jnp.bfloat16)
    got = flash_attention(q, k, v, causal=True, block_q=64, block_k=64,
                          interpret=True).astype(jnp.float32)
    want = reference_attention(q.astype(jnp.float32),
                               k.astype(jnp.float32),
                               v.astype(jnp.float32), causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=5e-2,
                               rtol=5e-2)
