"""QMIX (monotonic value factorization) and MADDPG (centralized-critic
multi-agent DDPG).

Reference analogs: rllib/algorithms/qmix and rllib/algorithms/maddpg —
learning checks follow the check_learning_achieved pattern scaled to CI
(rllib/utils/test_utils.py:480).
"""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.rllib import (MADDPG, MADDPGConfig, QMIX, QMIXConfig)


class _Space:
    def __init__(self, shape=None, n=None):
        self.shape = shape
        self.n = n


class _TeamMatchEnv:
    """Two agents, 8-step episodes.  Each agent privately observes a
    bit; the TEAM reward per step is 1.0 only if BOTH agents act on
    their own bit (else 0) — per-agent rewards are identical (team),
    so credit assignment has to flow through the mixer."""

    LEN = 8

    def __init__(self, seed=0):
        self._rng = np.random.RandomState(seed)
        self.action_spaces = {"a0": _Space(n=2), "a1": _Space(n=2)}

    def _obs(self):
        self._bits = self._rng.randint(2, size=2)
        return {"a0": np.asarray([self._bits[0]], np.float32),
                "a1": np.asarray([self._bits[1]], np.float32)}

    def reset(self, seed=None):
        if seed is not None:
            self._rng = np.random.RandomState(seed)
        self._t = 0
        return self._obs(), {}

    def step(self, action_dict):
        both = (int(action_dict["a0"]) == self._bits[0]
                and int(action_dict["a1"]) == self._bits[1])
        r = 0.5 if both else 0.0        # 0.5 each → team total 1.0
        self._t += 1
        done = self._t >= self.LEN
        obs = self._obs()
        rew = {"a0": r, "a1": r}
        return obs, rew, {"__all__": done}, {"__all__": False}, {}


def test_qmix_learns_team_match(ray_start_shared):
    # gamma=0: steps are iid context draws, so the mixed TD target is
    # the immediate team reward — isolates the factorization learning
    cfg = QMIXConfig(env=lambda _: _TeamMatchEnv(), num_workers=1,
                     hidden=(32,), mixing_embed=16, lr=5e-3,
                     buffer_size=10_000, learning_starts=200,
                     train_batch_size=64, train_intensity=16,
                     target_update_freq=400, epsilon_decay_steps=2000,
                     steps_per_sample=200, gamma=0.0, seed=0)
    algo = QMIX(cfg)
    best = -np.inf
    try:
        for _ in range(40):
            result = algo.train()
            best = max(best, result.get("episode_reward_mean", -np.inf))
            if best >= 7.0:
                break
    finally:
        algo.stop()
    # random play scores 8 * 0.25 = 2.0; solved play scores 8.0
    assert best >= 5.5, best


def test_qmix_mixer_is_monotonic():
    from ray_tpu.rllib.qmix import QMIXPolicy, QMIXSpec
    import jax
    import jax.numpy as jnp
    from ray_tpu.rllib.models import mlp_apply

    spec = QMIXSpec(obs_dim=3, n_actions=2, n_agents=2, state_dim=6,
                    hidden=(8,), mixing_embed=8)
    pol = QMIXPolicy(spec, seed=0)

    # rebuild the mixer closure exactly as the loss uses it
    def mix(q_chosen, state):
        p = pol.params
        w1 = jnp.abs(mlp_apply(p["hyper_w1"], state,
                               final_linear=True)).reshape(
                                   state.shape[0], 2, 8)
        b1 = mlp_apply(p["hyper_b1"], state, final_linear=True)
        hidden = jax.nn.elu(jnp.einsum("bn,bne->be", q_chosen, w1) + b1)
        w2 = jnp.abs(mlp_apply(p["hyper_w2"], state, final_linear=True))
        v = mlp_apply(p["hyper_v"], state, final_linear=True)[..., 0]
        return jnp.sum(hidden * w2, axis=-1) + v

    rng = np.random.RandomState(0)
    state = jnp.asarray(rng.randn(16, 6).astype(np.float32))
    q = jnp.asarray(rng.randn(16, 2).astype(np.float32))
    grads = jax.vmap(jax.grad(lambda qq, ss: mix(qq[None], ss[None])[0]
                              ))(q, state)
    # ∂Q_tot/∂Q_i ≥ 0 everywhere — the QMIX monotonicity guarantee
    assert float(jnp.min(grads)) >= 0.0


class _SharedPointEnv:
    """Two agents jointly push a 2-D point toward the origin; each
    controls one axis.  Identical rewards -|x|^2 — cooperative
    continuous control."""

    LEN = 25

    def __init__(self, seed=0):
        self._rng = np.random.RandomState(seed)
        self.action_spaces = {"a0": _Space(shape=(1,)),
                              "a1": _Space(shape=(1,))}

    def _obs(self):
        return {"a0": self._x.copy(), "a1": self._x.copy()}

    def reset(self, seed=None):
        if seed is not None:
            self._rng = np.random.RandomState(seed)
        self._x = self._rng.uniform(-2, 2, size=2).astype(np.float32)
        self._t = 0
        return self._obs(), {}

    def step(self, action_dict):
        self._x[0] = np.clip(
            self._x[0] + 0.5 * float(np.asarray(
                action_dict["a0"]).ravel()[0]), -3, 3)
        self._x[1] = np.clip(
            self._x[1] + 0.5 * float(np.asarray(
                action_dict["a1"]).ravel()[0]), -3, 3)
        self._t += 1
        r = float(-np.sum(self._x ** 2))
        done = self._t >= self.LEN
        return self._obs(), {"a0": r, "a1": r}, \
            {"__all__": done}, {"__all__": False}, {}


def test_maddpg_learns_shared_point(ray_start_shared):
    cfg = MADDPGConfig(env=lambda _: _SharedPointEnv(), num_workers=1,
                       hidden=(32, 32), actor_lr=3e-3, critic_lr=3e-3,
                       buffer_size=20_000, learning_starts=300,
                       train_batch_size=64, train_intensity=16,
                       exploration_noise=0.3, steps_per_sample=250,
                       gamma=0.8, tau=0.02, seed=0)
    algo = MADDPG(cfg)
    first = None
    best = -np.inf
    try:
        for i in range(40):
            result = algo.train()
            mean = result.get("episode_reward_mean", -np.inf)
            if i == 0:
                first = mean
            best = max(best, mean)
            if best >= -30.0:
                break
    finally:
        algo.stop()
    # random policy hovers around -150/episode-pair on this env;
    # trained actors keep the point near the origin
    assert best > first, (first, best)
    assert best >= -60.0, (first, best)


def test_maddpg_actions_decentralized():
    # actor i must depend only on obs_i: perturbing agent 1's obs
    # cannot change agent 0's action
    from ray_tpu.rllib.maddpg import MADDPGPolicy, MADDPGSpec

    spec = MADDPGSpec(obs_dim=2, act_dim=1, n_agents=2, hidden=(8,))
    pol = MADDPGPolicy(spec, seed=0)
    obs = np.zeros((2, 2), np.float32)
    a1 = pol.compute_actions(obs)
    obs2 = obs.copy()
    obs2[1] = 5.0
    a2 = pol.compute_actions(obs2)  # noise=0 → rng state irrelevant
    np.testing.assert_allclose(a1[0], a2[0], atol=1e-6)
    assert not np.allclose(a1[1], a2[1])
