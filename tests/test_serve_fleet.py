"""Fleet control plane acceptance: prefix-affinity routing, weighted
fair queueing, and SLO-driven autoscaling (serve/router.py).

Three end-to-end scenarios over real in-process engine replicas:

1. **Prefix affinity** — on a shared-prefix mix, the prefix-affinity
   fleet's pooled KV hit rate strictly exceeds round-robin's (which
   re-prefills each system prompt on every replica it scatters to),
   while greedy outputs stay bit-identical to the dense single-engine
   oracle — routing is a pure placement decision, never a semantic
   one.
2. **WFQ isolation** — a saturating batch tenant cannot starve an
   interactive tenant: with WFQ the interactive TTFT attainment stays
   above its objective; the same flood through a round-robin fleet
   without WFQ breaches it.  TTFT here includes router queueing (the
   router threads its submit instant to the engine as the enqueue
   time), so the scheduler's reordering is what the metric sees.
3. **Autoscaling** — a burn-rate breach scales up within the policy's
   sustain window; sustained idle scales back down through a graceful
   drain (zero lost requests, zero resident KV blocks), and every
   decision is visible in the flight-recorder dump.
"""

import asyncio
import time

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from ray_tpu.serve.router import (AutoscalePolicy, FairQueue,
                                  TenantClass,
                                  build_llm_fleet)  # noqa: E402
from ray_tpu.serve.slo import SLOConfig  # noqa: E402
from ray_tpu.tools.flightrec import (load_dump,
                                     report_lines)  # noqa: E402

MAX_NEW = 6
_OVR = {"dtype": jnp.float32, "use_flash": False, "remat": False}
_ENGINE_KW = dict(max_new_tokens=MAX_NEW, temperature=0.0,
                  kv_block_size=16, prefill_bucket=16, max_slots=2,
                  config_overrides=_OVR)


def _fleet(name, **kw):
    kw = {**_ENGINE_KW, **kw}
    return build_llm_fleet("gpt2", "nano", fleet_name=name, **kw)


def _oracle(prompt, max_new=MAX_NEW):
    """Dense solo greedy continuation — the parity reference."""
    from ray_tpu.models import gpt2_config, gpt2_init
    from ray_tpu.models.gpt2_decode import generate

    cfg = gpt2_config("nano", **_OVR)
    params = gpt2_init(jax.random.PRNGKey(0), cfg)
    out = generate(params, jnp.asarray(np.asarray(prompt)[None]), cfg,
                   max_new_tokens=max_new, temperature=0.0)
    return np.asarray(out)[0]


def _shared_prefix_mix(n_groups=3, per_group=4, prefix_len=32,
                       seed=11):
    """Shuffled multi-group shared-prefix workload: every request is
    one group's 2-full-block system prompt plus a tiny unique tail."""
    rng = np.random.RandomState(seed)
    prefixes = [rng.randint(2, 500, prefix_len)
                for _ in range(n_groups)]
    order = rng.permutation(np.repeat(np.arange(n_groups), per_group))
    return [np.concatenate(
        [prefixes[g], rng.randint(2, 500, 2 + int(rng.randint(3)))]
    ).astype(np.int32) for g in order]


def _drive_sequential(fleet, prompts, tenant=None):
    async def main():
        try:
            return [await fleet(p, tenant=tenant) for p in prompts]
        finally:
            fleet.shutdown()

    return asyncio.run(main())


# ---------------------------------------------------------------------------
# FairQueue unit semantics (host-only, no engines)
# ---------------------------------------------------------------------------

def test_fair_queue_weighted_interleave_and_idle_redistribution():
    q = FairQueue({"hot": TenantClass("hot", weight=2.0),
                   "cold": TenantClass("cold", weight=1.0)})
    for i in range(4):
        q.push(("cold", i), "cold")
    for i in range(4):
        q.push(("hot", i), "hot")
    served = [q.pop() for _ in range(len(q))]
    # weight 2 tenant gets ~2 of every 3 pops while both backlogged
    first6 = [t for t, _ in served[:6]]
    assert first6.count("hot") == 4 and first6.count("cold") == 2
    # per-tenant order is always FIFO
    assert [i for t, i in served if t == "hot"] == [0, 1, 2, 3]
    assert [i for t, i in served if t == "cold"] == [0, 1, 2, 3]
    # an idle tenant's share redistributes: nothing blocks the
    # remaining backlog once hot drains
    assert [t for t, _ in served[6:]] == ["cold", "cold"]


def test_fair_queue_unknown_tenant_defaults_to_weight_one():
    q = FairQueue()
    q.push("a", "mystery")
    q.push("b", None)
    assert len(q) == 2
    assert {q.pop(), q.pop()} == {"a", "b"}


# ---------------------------------------------------------------------------
# 1. prefix affinity beats round-robin, outputs stay oracle-identical
# ---------------------------------------------------------------------------

def test_prefix_affinity_beats_round_robin_and_matches_oracle():
    prompts = _shared_prefix_mix()

    fleet = _fleet("t_prefix", num_replicas=2, routing="prefix")
    outs = _drive_sequential(fleet, prompts)
    stats_prefix = fleet.fleet_stats()

    fleet = _fleet("t_rr", num_replicas=2, routing="round_robin")
    _drive_sequential(fleet, prompts)
    stats_rr = fleet.fleet_stats()

    # placement quality: affinity concentrates each group's KV on one
    # replica; round-robin re-prefills the prefix on both
    assert stats_prefix["prefix_hit_rate"] > stats_rr["prefix_hit_rate"]
    assert stats_prefix["prefix_hit_rate"] >= 0.45
    routed = stats_prefix["router"]["routed_by_policy"]
    assert routed["prefix_affinity"] > 0      # followers stuck
    assert routed["round_robin"] == 0
    assert stats_rr["router"]["routed_by_policy"]["round_robin"] \
        == len(prompts)
    # both replicas actually served traffic (no accidental collapse
    # onto one replica, which would fake a high hit rate)
    per_rep = [r["routed"] for r in stats_prefix["replicas"].values()]
    assert len(per_rep) == 2 and all(n > 0 for n in per_rep)

    # semantics: every fleet output is bit-identical to the dense
    # single-engine greedy continuation
    for p, o in zip(prompts, outs):
        np.testing.assert_array_equal(o, _oracle(p))


# ---------------------------------------------------------------------------
# 2. WFQ protects the interactive tenant's TTFT under a batch flood
# ---------------------------------------------------------------------------

def _flood(fleet, warm_prompts, batch_prompts, inter_prompts):
    """Warm this fleet's own engine (compile + first-call allocation
    spikes must not pollute the measured TTFTs), then submit the batch
    flood followed by the interactive requests — all concurrent,
    ordering preserved (each submit enqueues at task start, before any
    dispatch completes)."""
    async def main():
        try:
            for p in warm_prompts:
                await fleet(p)                     # tenant-less: not
            return await asyncio.gather(           # scored below
                *[fleet(p, tenant="batch") for p in batch_prompts],
                *[fleet(p, tenant="interactive")
                  for p in inter_prompts])
        finally:
            fleet.shutdown()

    return asyncio.run(main())


def test_wfq_keeps_interactive_ttft_attainment_under_batch_flood():
    rng = np.random.RandomState(3)
    warm = [rng.randint(2, 500, 24).astype(np.int32)
            for _ in range(2)]
    batch = [rng.randint(2, 500, 24).astype(np.int32)
             for _ in range(24)]
    inter = [rng.randint(2, 500, 24).astype(np.int32)
             for _ in range(3)]

    # calibrate one solo request's wall time on this machine (after
    # compile warmup) so the TTFT target scales with the host instead
    # of hard-coding milliseconds
    cal = _fleet("t_cal", num_replicas=1)

    async def calibrate():
        try:
            await cal(warm[0])                     # compile warmup
            ts = []
            for p in batch[:3]:
                t0 = time.perf_counter()
                await cal(p)
                ts.append(time.perf_counter() - t0)
            return sorted(ts)[1]                   # median of 3
        finally:
            cal.shutdown()

    t_solo = asyncio.run(calibrate())
    target_ms = 8.0 * t_solo * 1000.0
    tenants = [TenantClass("interactive", weight=8.0,
                           ttft_ms=target_ms, objective=0.95),
               TenantClass("batch", weight=1.0)]

    # WFQ on: interactive requests overtake the queued batch backlog
    fleet = _fleet("t_wfq", num_replicas=1, tenants=tenants, wfq=True)
    _flood(fleet, warm, batch, inter)
    rep_wfq = fleet.tenant_report()

    # WFQ off (plain FIFO round-robin fleet): interactive waits behind
    # the whole flood
    fleet = _fleet("t_fifo", num_replicas=1, tenants=tenants,
                   routing="round_robin", wfq=False)
    _flood(fleet, warm, batch, inter)
    rep_fifo = fleet.tenant_report()

    got_wfq = rep_wfq["interactive"]["objectives"]["ttft"]
    got_fifo = rep_fifo["interactive"]["objectives"]["ttft"]
    assert got_wfq["samples"] == len(inter)
    assert got_fifo["samples"] == len(inter)
    # attainment above the tenant objective with WFQ, breached without
    assert got_wfq["attainment"] >= 0.95, (got_wfq, target_ms)
    assert got_fifo["attainment"] < 0.95, (got_fifo, target_ms)
    # and not marginally: the flood delays FIFO interactive TTFT past
    # the target at p95
    assert got_fifo["latency_ms"]["p95"] > target_ms


# ---------------------------------------------------------------------------
# 3. autoscaler: burn breach scales up, sustained idle drains down
# ---------------------------------------------------------------------------

def test_autoscale_up_on_burn_then_idle_scale_down_with_drain(
        tmp_path):
    rng = np.random.RandomState(5)
    prompts = [rng.randint(2, 500, 20).astype(np.int32)
               for _ in range(6)]
    # impossible engine-side targets: every request violates, so the
    # 30 s burn window stays breached for the whole test
    slo = SLOConfig(ttft_ms=1e-4, e2e_ms=1e-4, objective=0.5,
                    windows_s=(30.0,), dump_on_breach=False)
    policy = AutoscalePolicy(min_replicas=1, max_replicas=2,
                             burn_threshold=1.0, queue_high=1e9,
                             sustain_s=2.0, idle_s=2.0,
                             up_cooldown_s=0.0, down_cooldown_s=0.0)
    fleet = _fleet("t_scale", num_replicas=1, slo=slo,
                   autoscale=policy)

    async def main():
        outs = [await fleet(p) for p in prompts[:4]]

        # breach observed but not yet sustained: no action
        assert await fleet.autoscale_step(now=100.0) is None
        assert await fleet.autoscale_step(now=101.0) is None
        # past the sustain window: scale up
        act = await fleet.autoscale_step(now=102.5)
        assert act == {"action": "up", "reason": "burn_rate",
                       "signal": act["signal"], "n_replicas": 2}
        assert act["signal"] > 1.0
        assert fleet.num_replicas == 2

        # the new replica serves traffic (so its drain is non-trivial)
        outs += [await fleet(p) for p in prompts[4:]]

        # the burn window never clears inside this test, so swap in a
        # burn-blind policy to exercise the idle path deterministically
        fleet.autoscale_policy = AutoscalePolicy(
            min_replicas=1, max_replicas=2, burn_threshold=1e9,
            queue_high=1e9, sustain_s=2.0, idle_s=2.0,
            up_cooldown_s=0.0, down_cooldown_s=0.0)
        assert await fleet.autoscale_step(now=110.0) is None
        act = await fleet.autoscale_step(now=112.5)
        assert act is not None and act["action"] == "down"
        assert act["reason"] == "idle" and act["n_replicas"] == 1
        # graceful drain: nothing in flight, every KV block freed
        assert act["drain"]["ok"] is True
        assert act["drain"]["blocks_in_use"] == 0
        assert fleet.num_replicas == 1
        # at the floor: no further scale-down
        assert await fleet.autoscale_step(now=120.0) is None

        # the shrunk fleet still serves (no lost capacity)
        outs.append(await fleet(prompts[0]))
        return outs

    try:
        outs = asyncio.run(main())
        # no lost requests anywhere in the episode
        assert len(outs) == len(prompts) + 1
        assert all(isinstance(o, np.ndarray) for o in outs)

        # every decision lands in the flight-recorder dump
        fleet.telemetry.flightrec.dump_dir = str(tmp_path)
        dump = fleet.telemetry.flightrec.dump(reason="test/autoscale")
        doc = load_dump(dump)
        counts = doc["counts_by_kind"]
        assert counts.get("route", 0) == len(outs)
        assert counts.get("scale_up") == 1
        assert counts.get("scale_down") == 1
        assert counts.get("drain") == 1
        ups = [e for e in doc["events"] if e["kind"] == "scale_up"]
        assert ups[0]["reason"] == "burn_rate" \
            and ups[0]["n_after"] == 2
        downs = [e for e in doc["events"] if e["kind"] == "scale_down"]
        assert downs[0]["reason"] == "idle" and downs[0]["replica"]
        drains = [e for e in doc["events"] if e["kind"] == "drain"]
        assert drains[0]["ok"] and drains[0]["blocks_in_use"] == 0
        # the postmortem CLI renders the routing table from this dump
        text = "\n".join(report_lines(doc))
        assert "routing table (route events by replica):" in text
        assert "last scale-ups:" in text
        assert "last drains:" in text
    finally:
        fleet.shutdown()
