"""Scalability envelope smoke (reference: release/benchmarks/README.md
rows — many tasks / actors / PGs / object args — scaled to a 1-core CI
box; the release suite carries the full-size variants)."""

import numpy as np
import pytest

import ray_tpu


@pytest.fixture(scope="module")
def scale_cluster():
    ray_tpu.init(num_cpus=8, object_store_memory=256 * 1024 * 1024)
    yield
    ray_tpu.shutdown()


def test_many_small_tasks(scale_cluster):
    # num_cpus=1: tasks pipeline through the warm 8-worker lease pool
    # (fractional CPUs would fork hundreds of workers on this 1-core
    # box — the release suite carries the big-fan-out variant)
    @ray_tpu.remote(num_cpus=1)
    def inc(x):
        return x + 1

    refs = [inc.remote(i) for i in range(2000)]
    out = ray_tpu.get(refs, timeout=300)
    assert out == [i + 1 for i in range(2000)]


def test_many_actors(scale_cluster):
    @ray_tpu.remote(num_cpus=0.1)
    class A:
        def __init__(self, i):
            self.i = i

        def who(self):
            return self.i

    actors = [A.remote(i) for i in range(60)]
    got = ray_tpu.get([a.who.remote() for a in actors], timeout=300)
    assert got == list(range(60))
    for a in actors:
        ray_tpu.kill(a)


def test_many_object_args_one_task(scale_cluster):
    """Reference row: 10k object args to one task (scaled to 512)."""

    @ray_tpu.remote
    def total(*parts):
        return sum(parts)

    refs = [ray_tpu.put(i) for i in range(512)]
    assert ray_tpu.get(total.remote(*refs), timeout=300) == \
        sum(range(512))


def test_many_returns_one_task(scale_cluster):
    """Reference row: 3k returns from one task (scaled to 256)."""

    @ray_tpu.remote(num_returns=256)
    def fan():
        return tuple(range(256))

    refs = fan.remote()
    out = ray_tpu.get(list(refs), timeout=300)
    assert out == list(range(256))


def test_many_placement_groups(scale_cluster):
    from ray_tpu.util.placement_group import (placement_group,
                                              remove_placement_group)

    pgs = []
    for _ in range(30):
        pg = placement_group([{"CPU": 0.1}])
        pgs.append(pg)
    for pg in pgs:
        pg.ready(timeout=60)
    for pg in pgs:
        remove_placement_group(pg)


def test_get_many_objects_at_once(scale_cluster):
    """Reference row: 10k plasma objects in one ray.get (scaled 1k)."""
    refs = [ray_tpu.put(np.full(64, i, np.int64)) for i in range(1000)]
    out = ray_tpu.get(refs, timeout=300)
    for i in (0, 500, 999):
        assert out[i][0] == i
