from ray_tpu._private.ids import (
    ActorID,
    JobID,
    NodeID,
    ObjectID,
    TaskID,
    put_object_id,
)

import pytest

pytestmark = pytest.mark.fast


def test_job_id_roundtrip():
    j = JobID.from_int(7)
    assert j.to_int() == 7
    assert JobID.from_hex(j.hex()) == j
    assert not j.is_nil()
    assert JobID.nil().is_nil()


def test_lineage_encoding():
    job = JobID.from_int(3)
    task = TaskID.for_task(job)
    assert task.job_id() == job
    obj = ObjectID.for_return(task, 2)
    assert obj.task_id() == task
    assert obj.job_id() == job
    assert obj.return_index() == 2


def test_actor_task_ids():
    job = JobID.from_int(1)
    actor = ActorID.of(job)
    assert actor.job_id() == job
    t = TaskID.for_actor_task(actor)
    assert t.actor_id() == actor


def test_put_ids_unique_and_marked():
    job = JobID.from_int(1)
    t = TaskID.for_driver(job)
    a, b = put_object_id(t), put_object_id(t)
    assert a != b
    assert a.return_index() & 0x80000000
    assert a.task_id() == t


def test_hash_and_sets():
    n1, n2 = NodeID.from_random(), NodeID.from_random()
    s = {n1, n2, n1}
    assert len(s) == 2


def test_pickle_roundtrip():
    import pickle

    t = TaskID.for_task(JobID.from_int(9))
    assert pickle.loads(pickle.dumps(t)) == t
