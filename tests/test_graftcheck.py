"""graftcheck acceptance suite.

Three layers:

1. **repo guard** — the full check (`run_repo_check`) over this
   checkout must come back clean; this is the tier-1 hook that makes
   every hot-path invariant a test failure.
2. **planted jaxpr violations** — each auditor rule must fire on a
   minimal program that breaks exactly it (host transfer, f64, f32
   matmul, logits buffer, length-T0 scan, dropped donation, HBM
   budget), proving none of the rules is vacuously green.
3. **lint fixtures** — each ast rule gets a positive snippet, a
   suppressed variant, and an out-of-scope/clean variant.

The suite also carries the non-vacuity sentinels inherited from the
retired tests/test_metrics_guard.py and tests/test_ops_kernel_guard.py
(the rules themselves moved into graftcheck).
"""

import json
import pathlib
import sys
import textwrap
import warnings

import jax
import jax.numpy as jnp
import pytest

from ray_tpu.tools import graftcheck as gc
from ray_tpu.tools.graftcheck.jaxpr_audit import ProgramSpec, audit_program
from ray_tpu.tools.graftcheck.lint import (KERNEL_EXPORTS,
                                           _autopilot_attribution,
                                           _observatory_mapping,
                                           lint_repo, lint_source,
                                           pallas_modules)

pytestmark = pytest.mark.fast

ROOT = pathlib.Path(__file__).resolve().parents[1]


# ---------------------------------------------------------------------------
# 1. the repo guard
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def repo_report():
    """One full run (lint + 7 traced programs) shared by the guard
    tests below — tracing the train steps is the expensive part."""
    return gc.run_repo_check(ROOT)


def test_repo_is_clean(repo_report):
    assert repo_report["ok"], gc.render_text(repo_report)


def test_repo_audit_covers_canonical_programs(repo_report):
    audited = set(repo_report["programs"])
    assert {"gpt2_train_step", "llama_train_step",
            "gpt2_prefill_ragged", "llama_prefill_ragged",
            "gpt2_decode_step", "gpt2_sharded_decode_step",
            "gpt2_spec_verify_step", "gpt2_chunked_prefill",
            "fused_ce_fwd", "fused_ce_bwd"} <= audited
    for name, info in repo_report["programs"].items():
        assert "error" not in info, f"{name} failed to trace: {info}"
        assert "skipped" not in info, \
            f"{name} skipped under CI's forced 8 devices: {info}"
        assert info["eqns"] > 0
        assert info["peak_hbm_bytes"] > 0


def test_repo_sharded_spec_ran_compiled_rules(repo_report):
    # conftest forces 8 CPU devices, so the sharded spec must have
    # compiled and reported its per-partition footprint — and a
    # sharded pool means strictly less than the global estimate
    info = repo_report["programs"]["gpt2_sharded_decode_step"]
    assert info["per_chip_hbm_bytes"] > 0
    assert info["per_chip_hbm_bytes"] < info["peak_hbm_bytes"]


def test_repo_suppressions_are_visible(repo_report):
    # serve/llm.py carries deliberate host fences behind disable
    # comments; the report must surface (not hide) that they exist
    # (round 11 moved the finish-path fence into a sync helper, so
    # the count dropped from 7 to 6)
    assert repo_report["summary"]["n_suppressed"] >= 6
    assert repo_report["summary"]["files_scanned"] > 100


def test_repo_metric_scan_not_vacuous():
    # inherited from the retired test_metrics_guard.py: the lint scan
    # must actually SEE the telemetry metrics
    violations, stats = lint_repo(ROOT)
    names = [v for v in violations if v.rule == "metric-name"]
    assert not names, names
    assert "serve_ttft_ms" in stats["metric_names"]
    assert "train_step_time_ms" in stats["metric_names"]
    assert len(stats["metric_names"]) >= 15


def test_pallas_module_detector_not_vacuous():
    # inherited from the retired test_ops_kernel_guard.py
    stems = pallas_modules(ROOT)
    assert "flash_attention" in stems
    assert "fused_ce" in stems


def test_kernel_exports_not_vacuous():
    import ray_tpu.ops as ops

    for name in KERNEL_EXPORTS:
        assert name in ops.__all__
        assert callable(getattr(ops, name))


def test_observatory_mapping_clean():
    # round 10: the repo's own spec->runtime map must be complete
    assert _observatory_mapping() == []


def test_observatory_mapping_planted_violations(monkeypatch):
    from ray_tpu._private import device_stats as ds

    # a spec with no runtime mapping
    missing = dict(ds.STATIC_PROGRAM_MAP)
    spec = next(iter(missing))
    del missing[spec]
    monkeypatch.setattr(ds, "STATIC_PROGRAM_MAP", missing)
    rules = {v.rule for v in _observatory_mapping()}
    assert rules == {"observatory-mapping"}

    # a mapping pointing at a program the runtime never registers
    bad_value = dict(ds.STATIC_PROGRAM_MAP)
    bad_value[spec] = "serve.bogus"
    monkeypatch.setattr(ds, "STATIC_PROGRAM_MAP", bad_value)
    msgs = [v.message for v in _observatory_mapping()]
    assert any("not a KNOWN_PROGRAMS" in m for m in msgs)

    # a stale mapping for a spec that no longer exists
    stale = dict(ds.STATIC_PROGRAM_MAP)
    stale[spec] = ds.STATIC_PROGRAM_MAP[spec]
    stale["ghost_spec"] = "train.step"
    monkeypatch.setattr(ds, "STATIC_PROGRAM_MAP", stale)
    msgs = [v.message for v in _observatory_mapping()]
    assert any("matches no" in m for m in msgs)


def test_autopilot_attribution_clean():
    # round 12: the autopilot's knob catalog must cover every runtime
    # program the static map targets
    assert _autopilot_attribution() == []


def test_autopilot_attribution_planted_violations(monkeypatch):
    from ray_tpu.tools.autopilot import attribution as ap

    # a runtime program the static map targets with no knob entry
    missing = dict(ap.PROGRAM_KNOBS)
    del missing["train.step"]
    monkeypatch.setattr(ap, "PROGRAM_KNOBS", missing)
    viols = _autopilot_attribution()
    assert {v.rule for v in viols} == {"autopilot-attribution"}
    assert any("'train.step'" in v.message for v in viols)

    # a knob entry for a program the runtime never registers
    bogus = dict(ap.PROGRAM_KNOBS)
    bogus["serve.bogus"] = ("spec_k",)
    monkeypatch.setattr(ap, "PROGRAM_KNOBS", bogus)
    msgs = [v.message for v in _autopilot_attribution()]
    assert any("not a KNOWN_PROGRAMS" in m for m in msgs)


# ---------------------------------------------------------------------------
# 2. planted jaxpr violations — every auditor rule must fire
# ---------------------------------------------------------------------------

def _spec(fn, args, **kw):
    return ProgramSpec(name="planted", build=lambda: (fn, args), **kw)


def _rules(violations):
    return {v.rule for v in violations}


def test_planted_host_transfer_detected():
    def fn(x):
        jax.debug.print("leak {}", x[0])
        return x * 2

    vs, _ = audit_program(_spec(fn, (jnp.zeros((8,)),)))
    assert "host-transfer" in _rules(vs)


def test_planted_f64_detected():
    from jax.experimental import enable_x64

    def fn(x):
        return (x.astype(jnp.float64) * 2.0).astype(jnp.float32)

    with enable_x64():
        vs, _ = audit_program(_spec(fn, (jnp.zeros((8, 8)),)))
    assert "f64" in _rules(vs)


def test_planted_f32_matmul_detected():
    x = jnp.zeros((256, 256), jnp.float32)   # 65536 elems = threshold
    vs, _ = audit_program(_spec(lambda a: a @ a.T, (x,)))
    assert "f32-matmul" in _rules(vs)
    # the whitelist silences exactly that rule
    vs, _ = audit_program(
        _spec(lambda a: a @ a.T, (x,), allow_f32_matmul=True))
    assert "f32-matmul" not in _rules(vs)


def test_planted_logits_buffer_detected():
    h = jnp.zeros((128, 64), jnp.float32)
    w = jnp.zeros((512, 64), jnp.float32)
    vs, _ = audit_program(_spec(lambda a, b: a @ b.T, (h, w),
                                forbid_logits=(128, 512)))
    assert "logits-buffer" in _rules(vs)
    # a buffer with fewer rows than n_tokens (e.g. a transposed
    # (d_model, V) weight view) must NOT trip the rule
    small = jnp.zeros((64, 64), jnp.float32)
    vs, _ = audit_program(_spec(lambda a, b: a @ b.T, (small, w),
                                forbid_logits=(128, 512)))
    assert "logits-buffer" not in _rules(vs)


def test_planted_t0_scan_detected():
    def fn(xs):
        def body(c, x):
            return c + x, x

        c, _ys = jax.lax.scan(body, jnp.zeros(()), xs)
        return c

    vs, _ = audit_program(_spec(fn, (jnp.zeros((64,)),),
                                forbid_scan_lengths=(64,)))
    assert "t0-scan" in _rules(vs)
    vs, _ = audit_program(_spec(fn, (jnp.zeros((64,)),),
                                forbid_scan_lengths=(128,)))
    assert "t0-scan" not in _rules(vs)


def test_planted_dropped_donation_detected():
    # a reduction's output can never alias its donated input, so the
    # lowered program records no tf.aliasing_output for it
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        vs, _ = audit_program(_spec(lambda x: jnp.sum(x),
                                    (jnp.zeros((32, 32)),),
                                    donate_argnums=(0,)))
    assert "donation" in _rules(vs)
    # same-shape output CAN alias: the rule stays quiet
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        vs, _ = audit_program(_spec(lambda x: x + 1.0,
                                    (jnp.zeros((32, 32)),),
                                    donate_argnums=(0,)))
    assert "donation" not in _rules(vs)


def test_planted_hbm_budget_blowup_detected():
    x = jnp.zeros((256, 256), jnp.float32)   # 256 KiB input
    vs, info = audit_program(
        _spec(lambda a: a @ a.T, (x,), allow_f32_matmul=True,
              hbm_budget_bytes=100 * 1024))
    assert "hbm-budget" in _rules(vs)
    assert info["peak_hbm_bytes"] > 100 * 1024


def test_planted_spec_verify_full_logits_detected():
    """The spec-verify ProgramSpec's whole point is that verify logits
    are (B, k+1, V), never the full-sequence class — a verify that
    materializes the (B*max_seq, V) buffer must trip the rule under
    the real spec's own constraints (and the real spec must carry the
    KV-pool donation + budget the engine depends on)."""
    from ray_tpu.tools.graftcheck.programs import default_programs

    spec = next(s for s in default_programs()
                if s.name == "gpt2_spec_verify_step")
    assert spec.donate_argnums == (1,)
    assert spec.hbm_budget_bytes > 0
    fn, args = spec.build()

    def bad(p, c, b, k):
        out, n_acc, cache = fn(p, c, b, k)
        full = jnp.zeros(spec.forbid_logits, jnp.float32)  # planted
        return out, n_acc + jnp.sum(full).astype(jnp.int32), cache

    with warnings.catch_warnings():
        warnings.simplefilter("ignore")   # cpu donation warning
        vs, _ = audit_program(
            ProgramSpec(name="planted", build=lambda: (bad, args),
                        forbid_logits=spec.forbid_logits,
                        donate_argnums=spec.donate_argnums,
                        allow_f32_matmul=True))
    assert "logits-buffer" in _rules(vs)


def test_planted_chunked_prefill_full_sequence_detected():
    """The chunked-prefill ProgramSpec pins the whole point of
    chunking: each chunk program touches only its own tail, never a
    full-sequence buffer.  A variant that materializes the
    (max_seq, V) logits class or scans the full 128-step sequence
    must trip the rule under the real spec's own constraints."""
    from ray_tpu.tools.graftcheck.programs import default_programs

    spec = next(s for s in default_programs()
                if s.name == "gpt2_chunked_prefill")
    assert spec.forbid_logits == (128, 512)
    assert spec.forbid_scan_lengths == (128,)
    assert spec.hbm_budget_bytes > 0
    fn, args = spec.build()

    def bad_logits(p, c, t, bt, pl, nt, s):
        logits, cache = fn(p, c, t, bt, pl, nt, s)
        full = jnp.zeros(spec.forbid_logits, jnp.float32)  # planted
        return logits + jnp.sum(full), cache

    vs, _ = audit_program(
        ProgramSpec(name="planted", build=lambda: (bad_logits, args),
                    forbid_logits=spec.forbid_logits,
                    allow_f32_matmul=True))
    assert "logits-buffer" in _rules(vs)

    def bad_scan(p, c, t, bt, pl, nt, s):
        logits, cache = fn(p, c, t, bt, pl, nt, s)
        acc, _ys = jax.lax.scan(lambda carry, x: (carry + x, x),
                                jnp.zeros(()),
                                jnp.zeros((128,)))  # planted full seq
        return logits + acc, cache

    vs, _ = audit_program(
        ProgramSpec(name="planted", build=lambda: (bad_scan, args),
                    forbid_scan_lengths=spec.forbid_scan_lengths,
                    allow_f32_matmul=True))
    assert "t0-scan" in _rules(vs)


def test_planted_handoff_logits_and_donation_detected():
    """The disaggregated-handoff ProgramSpecs pin the hop's two
    invariants: a handoff moves K/V bytes and never computes (no
    logits-class buffer in either side), and the decode-side install
    donates the pool (two live pools per handoff is exactly the HBM
    spike disaggregation cannot afford).  A variant that materializes
    the full logits class, or an install that drops the donation, must
    trip under the real specs' own constraints."""
    from ray_tpu.tools.graftcheck.programs import default_programs

    progs = {s.name: s for s in default_programs()}
    exp = progs["gpt2_kv_handoff_export"]
    ins = progs["gpt2_kv_handoff_install"]
    assert exp.hbm_budget_bytes > 0 and ins.hbm_budget_bytes > 0
    assert ins.donate_argnums == (0,)

    # export that routes a forward through the hop: logits buffer
    fn, args = exp.build()

    def bad_export(c, blk_ids):
        ks, vs = fn(c, blk_ids)
        full = jnp.zeros(exp.forbid_logits, jnp.float32)  # planted
        return ks + jnp.sum(full), vs

    vs_, _ = audit_program(
        ProgramSpec(name="planted", build=lambda: (bad_export, args),
                    forbid_logits=exp.forbid_logits,
                    allow_f32_matmul=True))
    assert "logits-buffer" in _rules(vs_)

    # install that reduces the spliced pool instead of returning it:
    # no output can alias the donated pool, so the donation is dropped
    ifn, iargs = ins.build()

    def bad_install(c, blk_ids, ks, vs, slot, bt, pos):
        out = ifn(c, blk_ids, ks, vs, slot, bt, pos)
        return jnp.sum(out["k"]) + jnp.sum(out["v"])

    with warnings.catch_warnings():
        warnings.simplefilter("ignore")   # cpu donation warning
        vs_, _ = audit_program(
            ProgramSpec(name="planted",
                        build=lambda: (bad_install, iargs),
                        donate_argnums=(0,), allow_f32_matmul=True))
    assert "donation" in _rules(vs_)
    # the REAL install keeps the donation live end to end
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        vs_, _ = audit_program(ins)
    assert "donation" not in _rules(vs_)


def test_peak_estimate_counts_live_buffers():
    one_mib = jnp.zeros((512, 512), jnp.float32)  # exactly 1 MiB
    _, info = audit_program(_spec(lambda x: x + 1.0, (one_mib,)))
    # input + output both live at the add: >= 2 MiB
    assert info["peak_hbm_bytes"] >= 2 * 2**20


def test_skip_rules_waives_a_jaxpr_rule():
    def fn(x):
        jax.debug.print("leak {}", x[0])
        return x * 2

    vs, _ = audit_program(_spec(fn, (jnp.zeros((8,)),),
                                skip_rules=("host-transfer",)))
    assert "host-transfer" not in _rules(vs)


def test_planted_missing_collective_detected():
    # an unsharded program can never contain an all-reduce, so a spec
    # requiring one must fire
    vs, _ = audit_program(_spec(lambda x: x + 1.0,
                                (jnp.zeros((8, 8)),),
                                require_collectives=("all-reduce",)))
    assert "collectives" in _rules(vs)


def test_planted_replicated_shape_detected():
    # the input's own full shape appears in the compiled HLO — the
    # forbidden-shape form of the collectives rule must fire on it
    vs, _ = audit_program(_spec(lambda x: x + 1.0,
                                (jnp.zeros((8, 8)),),
                                forbid_hlo_shapes=("f32[8,8]",)))
    assert "collectives" in _rules(vs)


def test_planted_per_chip_hbm_blowup_detected():
    x = jnp.zeros((256, 256), jnp.float32)   # 256 KiB unsharded
    vs, info = audit_program(
        _spec(lambda a: a @ a.T, (x,), allow_f32_matmul=True,
              per_chip_hbm_budget_bytes=1024))
    assert "per-chip-hbm" in _rules(vs)
    assert info["per_chip_hbm_bytes"] > 1024


def test_min_devices_skips_not_fails():
    vs, info = audit_program(
        _spec(lambda x: x + 1.0, (jnp.zeros((8,)),),
              min_devices=10_000))
    assert vs == []
    assert "skipped" in info


# ---------------------------------------------------------------------------
# 3. lint fixtures — positive, suppressed, out-of-scope per rule
# ---------------------------------------------------------------------------

_SERVE = "ray_tpu/serve/fixture.py"


def test_lint_blocking_call_positive():
    src = textwrap.dedent("""\
        import numpy as np

        async def handler(prompt):
            return np.asarray(prompt)
    """)
    kept, n_sup = lint_source(src, _SERVE)
    assert [v.rule for v in kept] == ["blocking-call-in-async"]
    assert n_sup == 0


def test_lint_blocking_call_variants():
    src = textwrap.dedent("""\
        import time
        import ray

        async def handler(ref, arr):
            x = ray.get(ref)
            arr.block_until_ready()
            time.sleep(1)
            return x
    """)
    kept, _ = lint_source(src, _SERVE)
    assert len(kept) == 3
    assert {v.rule for v in kept} == {"blocking-call-in-async"}


def test_lint_blocking_call_suppressed():
    src = textwrap.dedent("""\
        import numpy as np

        async def handler(prompt):
            # deliberate host fence
            # graftcheck: disable=blocking-call-in-async(result fetch)
            return np.asarray(prompt)
    """)
    kept, n_sup = lint_source(src, _SERVE)
    assert not kept
    assert n_sup == 1


def test_lint_blocking_call_scoped_to_serve():
    src = textwrap.dedent("""\
        import numpy as np

        async def handler(prompt):
            return np.asarray(prompt)
    """)
    kept, _ = lint_source(src, "ray_tpu/train/fixture.py")
    assert not kept


def test_lint_blocking_call_ignores_sync_and_nested():
    src = textwrap.dedent("""\
        import numpy as np

        def sync_helper(p):
            return np.asarray(p)

        async def handler(prompt):
            def jitted_body(t):
                return np.asarray(t)   # runs under jit, not the loop
            return jitted_body(prompt)
    """)
    kept, _ = lint_source(src, _SERVE)
    assert not kept


def test_lint_wallclock_positive_and_suppressed():
    src = textwrap.dedent("""\
        import time

        def record():
            return time.time()
    """)
    kept, _ = lint_source(src, "ray_tpu/serve/telemetry.py")
    assert [v.rule for v in kept] == ["wallclock-in-telemetry"]
    # perf_counter is the sanctioned clock
    kept, _ = lint_source(src.replace("time.time()",
                                      "time.perf_counter()"),
                          "ray_tpu/serve/telemetry.py")
    assert not kept
    # out of scope: same call elsewhere is fine
    kept, _ = lint_source(src, "ray_tpu/serve/other.py")
    assert not kept
    suppressed = src.replace(
        "return time.time()",
        "return time.time()  "
        "# graftcheck: disable=wallclock-in-telemetry(epoch label)")
    kept, n_sup = lint_source(suppressed, "ray_tpu/train/telemetry.py")
    assert not kept
    assert n_sup == 1


def test_lint_wallclock_covers_flightrec_and_slo():
    # the flight recorder and SLO burn-rate engine promised monotonic
    # clocks — a planted time.time() in either path must flag
    src = textwrap.dedent("""\
        import time

        def record(kind):
            return time.time()
    """)
    for rel in ("ray_tpu/_private/flightrec.py",
                "ray_tpu/serve/slo.py"):
        kept, _ = lint_source(src, rel)
        assert [v.rule for v in kept] == ["wallclock-in-telemetry"], rel
        kept, _ = lint_source(src.replace("time.time()",
                                          "time.perf_counter()"), rel)
        assert not kept, rel
    # neighbours of the scoped files stay out of scope
    kept, _ = lint_source(src, "ray_tpu/serve/kv_pager.py")
    assert not kept


def test_lint_fleet_router_in_both_rule_scopes():
    # round 11: the fleet router schedules WFQ virtual time and
    # journals routing decisions — both the monotonic-clock and the
    # no-blocking-in-async invariants extend to it
    wall = textwrap.dedent("""\
        import time

        def record_route(req):
            return time.time()
    """)
    kept, _ = lint_source(wall, "ray_tpu/serve/router.py")
    assert [v.rule for v in kept] == ["wallclock-in-telemetry"]
    kept, _ = lint_source(wall.replace("time.time()",
                                       "time.perf_counter()"),
                          "ray_tpu/serve/router.py")
    assert not kept
    block = textwrap.dedent("""\
        import numpy as np

        async def submit(prompt):
            return np.asarray(prompt)
    """)
    kept, _ = lint_source(block, "ray_tpu/serve/router.py")
    assert [v.rule for v in kept] == ["blocking-call-in-async"]


def test_lint_autopilot_in_both_rule_scopes():
    # round 12: the dashboard calls the autopilot from its event loop
    # and verdicts promised ledger-reproducibility — both the
    # monotonic-clock and no-blocking-in-async invariants extend over
    # ray_tpu/tools/autopilot/
    wall = textwrap.dedent("""\
        import time

        def stamp_plan():
            return time.time()
    """)
    kept, _ = lint_source(wall, "ray_tpu/tools/autopilot/planner.py")
    assert [v.rule for v in kept] == ["wallclock-in-telemetry"]
    kept, _ = lint_source(wall.replace("time.time()",
                                       "time.perf_counter()"),
                          "ray_tpu/tools/autopilot/planner.py")
    assert not kept
    block = textwrap.dedent("""\
        import numpy as np

        async def collect(snapshot):
            return np.asarray(snapshot)
    """)
    kept, _ = lint_source(block,
                          "ray_tpu/tools/autopilot/attribution.py")
    assert [v.rule for v in kept] == ["blocking-call-in-async"]
    # sibling tools stay out of both scopes
    kept, _ = lint_source(wall, "ray_tpu/tools/graftcheck/fixture.py")
    assert not kept
    kept, _ = lint_source(block, "ray_tpu/tools/graftcheck/fixture.py")
    assert not kept


def test_lint_wallclock_covers_trainwatch():
    # round 14: the trainwatch anatomy promises legs that sum exactly
    # to the step wall on ONE clock — a planted time.time() in
    # train/goodput.py breaks that invariant and must flag
    src = textwrap.dedent("""\
        import time

        def record_step(call_s):
            return time.time()
    """)
    kept, _ = lint_source(src, "ray_tpu/train/goodput.py")
    assert [v.rule for v in kept] == ["wallclock-in-telemetry"]
    kept, _ = lint_source(src.replace("time.time()",
                                      "time.perf_counter()"),
                          "ray_tpu/train/goodput.py")
    assert not kept
    # train-package neighbours stay out of scope (telemetry.py is
    # covered by the */telemetry.py glob, grad_accum.py is not timed)
    kept, _ = lint_source(src, "ray_tpu/train/grad_accum.py")
    assert not kept


def test_lint_wallclock_covers_kvscope():
    # round 16: the kvscope occupancy ring promised perf_counter
    # timestamps (wall-clock steps would corrupt the timeline around
    # NTP slews) — a planted time.time() in either the host-side core
    # or the CLI must flag
    src = textwrap.dedent("""\
        import time

        def sample(free):
            return time.time()
    """)
    for rel in ("ray_tpu/serve/kvscope.py",
                "ray_tpu/tools/kvscope.py"):
        kept, _ = lint_source(src, rel)
        assert [v.rule for v in kept] == ["wallclock-in-telemetry"], rel
        kept, _ = lint_source(src.replace("time.time()",
                                          "time.perf_counter()"), rel)
        assert not kept, rel
    # the pager itself stays OUT of scope (allocation is not timed)
    kept, _ = lint_source(src, "ray_tpu/serve/kv_pager.py")
    assert not kept


def test_lint_kvscope_sources_clean():
    # kvscope lints itself clean under the full rule set
    import os

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    for rel in ("ray_tpu/serve/kvscope.py",
                "ray_tpu/tools/kvscope.py"):
        with open(os.path.join(repo, rel)) as f:
            kept, _ = lint_source(f.read(), rel)
        assert not kept, [str(v) for v in kept]


def test_lint_wallclock_covers_kv_tier():
    # round 17: the host KV tier never reads a clock — the engine
    # feeds it measured H2D/D2H seconds (note_h2d/note_d2h) — so a
    # planted time.time() inside serve/kv_tier.py must flag
    src = textwrap.dedent("""\
        import time

        def put(key, rows):
            return time.time()
    """)
    kept, _ = lint_source(src, "ray_tpu/serve/kv_tier.py")
    assert [v.rule for v in kept] == ["wallclock-in-telemetry"]
    kept, _ = lint_source(src.replace("time.time()",
                                      "time.perf_counter()"),
                          "ray_tpu/serve/kv_tier.py")
    assert not kept


def test_lint_blocking_call_covers_kv_tier():
    # kv_tier.py lives under ray_tpu/serve/, so the async-path
    # blocking-call scope already covers it: a planted D2H gather
    # inside an async def must flag
    src = textwrap.dedent("""\
        import numpy as np

        async def spill(cache, blk):
            return np.asarray(cache[blk])
    """)
    kept, _ = lint_source(src, "ray_tpu/serve/kv_tier.py")
    assert [v.rule for v in kept] == ["blocking-call-in-async"]


def test_lint_kv_tier_source_clean():
    # the shipped tier lints clean under the full rule set (both the
    # wallclock and blocking-call scopes now include it)
    import os

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    rel = "ray_tpu/serve/kv_tier.py"
    with open(os.path.join(repo, rel)) as f:
        kept, _ = lint_source(f.read(), rel)
    assert not kept, [str(v) for v in kept]


def test_lint_wallclock_covers_healthwatch():
    # round 19: healthwatch state transitions and incident timelines
    # are rebased onto the perf_counter clock shared with flightrec
    # and tracebus — a planted time.time() in the monitor, the chaos
    # injector, or the incidents CLI would skew detection latency and
    # mis-order merged lanes around NTP slews, so each must flag
    src = textwrap.dedent("""\
        import time

        def heartbeat(name):
            return time.time()
    """)
    for rel in ("ray_tpu/serve/health.py",
                "ray_tpu/serve/chaos.py",
                "ray_tpu/tools/incidents.py"):
        kept, _ = lint_source(src, rel)
        assert [v.rule for v in kept] == ["wallclock-in-telemetry"], rel
        kept, _ = lint_source(src.replace("time.time()",
                                          "time.perf_counter()"), rel)
        assert not kept, rel
    # untimed tools neighbours stay out of scope
    kept, _ = lint_source(src, "ray_tpu/tools/fixture.py")
    assert not kept


def test_lint_blocking_call_covers_incidents():
    # health.py/chaos.py live under ray_tpu/serve/ (already in the
    # async blocking-call scope); the incidents CLI is pulled in
    # explicitly so a future async export path can't sneak a
    # device-blocking call past review
    src = textwrap.dedent("""\
        import numpy as np

        async def export(doc):
            return np.asarray(doc)
    """)
    for rel in ("ray_tpu/serve/health.py",
                "ray_tpu/serve/chaos.py",
                "ray_tpu/tools/incidents.py"):
        kept, _ = lint_source(src, rel)
        assert [v.rule for v in kept] == ["blocking-call-in-async"], rel


def test_lint_healthwatch_sources_clean():
    # the shipped healthwatch trio lints clean under the full rule set
    import os

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    for rel in ("ray_tpu/serve/health.py",
                "ray_tpu/serve/chaos.py",
                "ray_tpu/tools/incidents.py"):
        with open(os.path.join(repo, rel)) as f:
            kept, _ = lint_source(f.read(), rel)
        assert not kept, (rel, [str(v) for v in kept])


def test_lint_mutable_global_positive():
    src = textwrap.dedent("""\
        from ray_tpu import remote

        CACHE = {}

        @remote
        def worker(x):
            CACHE[x] = 1
            return x
    """)
    kept, _ = lint_source(src, "ray_tpu/train/fixture.py")
    assert [v.rule for v in kept] == ["mutable-global-in-remote"]


def test_lint_mutable_global_actor_method_and_reads_ok():
    src = textwrap.dedent("""\
        import ray_tpu

        SEEN = []

        @ray_tpu.remote
        class Actor:
            def push(self, x):
                SEEN.append(x)

            def peek(self):
                return len(SEEN)
    """)
    kept, _ = lint_source(src, "ray_tpu/train/fixture.py")
    assert len(kept) == 1           # push mutates; peek only reads
    assert kept[0].rule == "mutable-global-in-remote"
    # non-remote functions may mutate module state freely
    src2 = textwrap.dedent("""\
        CACHE = {}

        def local(x):
            CACHE[x] = 1
    """)
    kept, _ = lint_source(src2, "ray_tpu/train/fixture.py")
    assert not kept


def test_lint_metric_name_positive_and_suppressed():
    src = textwrap.dedent("""\
        from ray_tpu.util.metrics import Counter

        c = Counter("Bad-Name", "desc")
    """)
    kept, _ = lint_source(src, "ray_tpu/util/fixture.py")
    assert [v.rule for v in kept] == ["metric-name"]
    kept, _ = lint_source(src.replace("Bad-Name", "good_name_total"),
                          "ray_tpu/util/fixture.py")
    assert not kept
    # a computed name can't be verified: also a finding
    kept, _ = lint_source(src.replace('"Bad-Name"', "some_var"),
                          "ray_tpu/util/fixture.py")
    assert [v.rule for v in kept] == ["metric-name"]
    suppressed = src.replace(
        'c = Counter("Bad-Name", "desc")',
        'c = Counter("Bad-Name", "desc")  '
        '# graftcheck: disable=metric-name(legacy dashboard name)')
    kept, n_sup = lint_source(suppressed, "ray_tpu/util/fixture.py")
    assert not kept
    assert n_sup == 1


def test_suppression_comment_semantics():
    sup = gc.parse_suppressions(textwrap.dedent("""\
        x = 1  # graftcheck: disable=rule-a
        # graftcheck: disable=rule-b,rule-c
        y = 2
        z = 3
    """))
    assert sup[1] == {"rule-a"}
    assert sup[2] == {"rule-b", "rule-c"}   # standalone covers itself
    assert sup[3] == {"rule-b", "rule-c"}   # ...and the next line
    assert 4 not in sup


# ---------------------------------------------------------------------------
# suppression hygiene: reasons required, waivers must earn their keep
# ---------------------------------------------------------------------------

_HYGIENE_BAD = textwrap.dedent("""\
    import numpy as np

    async def handler(prompt):
        # graftcheck: disable=blocking-call-in-async{reason}
        return np.asarray(prompt)
""")


def test_hygiene_bare_suppression_needs_reason():
    kept, n_sup = lint_source(_HYGIENE_BAD.format(reason=""),
                              "ray_tpu/serve/fixture.py")
    assert [v.rule for v in kept] == ["suppression-reason"]
    assert n_sup == 1          # the waiver still works, it just owes a why


def test_hygiene_reasoned_effective_waiver_is_clean():
    kept, n_sup = lint_source(
        _HYGIENE_BAD.format(reason="(host-side fixture)"),
        "ray_tpu/serve/fixture.py")
    assert kept == []
    assert n_sup == 1


def test_hygiene_unknown_rule_is_stale():
    kept, _ = lint_source(textwrap.dedent("""\
        # graftcheck: disable=no-such-rule(typo'd long ago)
        x = 1
    """), "ray_tpu/serve/fixture.py")
    assert [v.rule for v in kept] == ["stale-suppression"]
    assert "no-such-rule" in kept[0].message


def test_hygiene_noop_waiver_is_stale():
    # the waived rule exists but nothing on the covered lines fires it
    kept, n_sup = lint_source(textwrap.dedent("""\
        # graftcheck: disable=blocking-call-in-async(left behind)
        x = 1
    """), "ray_tpu/serve/fixture.py")
    assert [v.rule for v in kept] == ["stale-suppression"]
    assert n_sup == 0


def test_hygiene_noop_all_waiver_is_stale_too():
    # even a blanket 'all' must actually drop something to stay
    kept, _ = lint_source(textwrap.dedent("""\
        # graftcheck: disable=all(generated file)
        x = 1
    """), "ray_tpu/serve/fixture.py")
    assert [v.rule for v in kept] == ["stale-suppression"]


# ---------------------------------------------------------------------------
# contract-registry / perfledger-direction: planted drift
# ---------------------------------------------------------------------------

def test_contract_registry_clean():
    from ray_tpu.tools.graftcheck.contracts import contract_registry

    assert contract_registry(ROOT) == []


def test_contract_registry_planted_new_component(monkeypatch):
    import ray_tpu.serve.telemetry as telemetry
    from ray_tpu.tools.graftcheck.contracts import contract_registry

    monkeypatch.setattr(
        telemetry, "CRITICAL_PATH_COMPONENTS",
        tuple(telemetry.CRITICAL_PATH_COMPONENTS) + ("phantom_ms",))
    msgs = [v.message for v in contract_registry(ROOT)]
    # the new component must be pinned in every downstream view
    assert any("no COMPONENT_SPANS entry" in m for m in msgs)
    assert any("missing from the golden" in m for m in msgs)
    assert any("not documented" in m for m in msgs)


def test_contract_registry_planted_stale_span(monkeypatch):
    import ray_tpu.tools.tracebus as tracebus
    from ray_tpu.tools.graftcheck.contracts import contract_registry

    spans = dict(tracebus.COMPONENT_SPANS)
    spans["ghost_ms"] = "ghost.span"
    monkeypatch.setattr(tracebus, "COMPONENT_SPANS", spans)
    msgs = [v.message for v in contract_registry(ROOT)]
    assert any("stale mapping" in m for m in msgs)
    assert any("never emits a 'ghost.span'" in m for m in msgs)


def test_perfledger_direction_clean_and_planted(monkeypatch):
    import ray_tpu.tools.perfledger as perfledger
    from ray_tpu.tools.graftcheck.contracts import perfledger_direction

    assert perfledger_direction(ROOT) == []
    monkeypatch.setattr(
        perfledger, "_SWEEP_FIELDS",
        tuple(perfledger._SWEEP_FIELDS) + ("mystery_blips",))
    vs = perfledger_direction(ROOT)
    assert [v.rule for v in vs] == ["perfledger-direction"]
    assert "mystery_blips" in vs[0].message


def test_sweep_record_carries_v2_rule_counters(monkeypatch):
    import ray_tpu.tools.graftcheck as graftcheck_pkg
    import sweep_tpu

    # stub the (expensive, jaxpr-tracing) repo check: the counters'
    # arithmetic is what's under test, the real report shape is
    # pinned by the CLI tests above
    monkeypatch.setattr(graftcheck_pkg, "run_repo_check", lambda: {
        "ok": False,
        "violations": [
            {"rule": "shared-state-race", "message": "m"},
            {"rule": "shared-state-race", "message": "m"},
            {"rule": "rng-discipline", "message": "m"},
        ],
        "summary": {"n_violations": 3, "n_suppressed": 0,
                    "files_scanned": 1, "rules_failed":
                    ["shared-state-race", "rng-discipline"]},
    })
    rec = sweep_tpu._graftcheck_record()
    summary = rec["graftcheck"]
    assert summary["shared_state_race"] == 2
    assert summary["rng_discipline"] == 1
    assert summary["contract_registry"] == 0
    assert rec["ok"] is False


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------

def test_cli_json_clean_on_repo(capsys):
    from ray_tpu.tools.graftcheck.__main__ import main

    rc = main(["--root", str(ROOT), "--skip-jaxpr", "--format", "json"])
    report = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert report["ok"] is True
    assert report["summary"]["n_suppressed"] >= 6


def test_cli_nonzero_on_planted_violation(tmp_path, capsys):
    from ray_tpu.tools.graftcheck.__main__ import main

    pkg = tmp_path / "ray_tpu" / "serve"
    pkg.mkdir(parents=True)
    (pkg / "bad.py").write_text(textwrap.dedent("""\
        import numpy as np

        async def handler(prompt):
            return np.asarray(prompt)
    """))
    (tmp_path / "ray_tpu" / "ops").mkdir()
    (tmp_path / "tests").mkdir()
    rc = main(["--root", str(tmp_path), "--skip-jaxpr",
               "--format", "json"])
    report = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert report["ok"] is False
    assert "blocking-call-in-async" in report["summary"]["rules_failed"]


def test_cli_github_format_annotations(tmp_path, capsys):
    from ray_tpu.tools.graftcheck.__main__ import main

    pkg = tmp_path / "ray_tpu" / "serve"
    pkg.mkdir(parents=True)
    (pkg / "bad.py").write_text(textwrap.dedent("""\
        import numpy as np

        async def handler(prompt):
            return np.asarray(prompt)
    """))
    (tmp_path / "ray_tpu" / "ops").mkdir()
    (tmp_path / "tests").mkdir()
    rc = main(["--root", str(tmp_path), "--skip-jaxpr",
               "--format", "github"])
    out = capsys.readouterr().out
    assert rc == 1
    assert "::error file=ray_tpu/serve/bad.py,line=4::" in out
    assert "[blocking-call-in-async]" in out
    assert "::notice::graftcheck:" in out


def test_cli_changed_lints_only_the_range(tmp_path, capsys):
    import subprocess

    from ray_tpu.tools.graftcheck.__main__ import main

    def git(*argv):
        subprocess.run(
            ["git", "-c", "user.email=t@t", "-c", "user.name=t",
             *argv], cwd=tmp_path, check=True, capture_output=True)

    pkg = tmp_path / "ray_tpu" / "serve"
    pkg.mkdir(parents=True)
    git("init", "-q")
    git("commit", "-qm", "root", "--allow-empty")
    # commit 2: a clean file plus a bad file that predates the range
    (pkg / "old_bad.py").write_text(
        "import numpy as np\n\n"
        "async def old(prompt):\n    return np.asarray(prompt)\n")
    (pkg / "clean.py").write_text("x = 1\n")
    git("add", "-A")
    git("commit", "-qm", "seed")
    # commit 3: touch clean.py only — the old violation is out of range
    (pkg / "clean.py").write_text("x = 2\n")
    git("add", "-A")
    git("commit", "-qm", "touch clean")
    rc = main(["--root", str(tmp_path), "--changed", "HEAD~1..HEAD",
               "--format", "json"])
    report = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert report["ok"] is True
    assert report["summary"]["files_scanned"] == 1
    # now a range that includes the bad file
    rc = main(["--root", str(tmp_path), "--changed",
               "HEAD~2..HEAD", "--format", "json"])
    report = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert "blocking-call-in-async" in report["summary"]["rules_failed"]


def test_cli_changed_bad_range_exits_2(tmp_path, capsys):
    import subprocess

    from ray_tpu.tools.graftcheck.__main__ import main

    subprocess.run(["git", "init", "-q"], cwd=tmp_path, check=True,
                   capture_output=True)
    rc = main(["--root", str(tmp_path), "--changed",
               "not-a-rev..HEAD"])
    assert rc == 2


def test_cli_subprocess_entry_point():
    import subprocess

    proc = subprocess.run(
        [sys.executable, "-m", "ray_tpu.tools.graftcheck",
         "--skip-jaxpr", "--root", str(ROOT)],
        capture_output=True, text=True, cwd=str(ROOT), timeout=300)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "graftcheck:" in proc.stdout
