"""Filesystem seam under Data IO and spill (reference analogs:
file_based_datasource.py:181 filesystem plumbing,
external_storage.py:445 remote spill)."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu import data as rdata
from ray_tpu.data import filesystem as fs_mod


def test_resolve_schemes():
    fs, p = fs_mod.resolve("/tmp/x")
    assert isinstance(fs, fs_mod.LocalFileSystem) and p == "/tmp/x"
    fs, p = fs_mod.resolve("file:///tmp/x")
    assert isinstance(fs, fs_mod.LocalFileSystem) and p == "/tmp/x"
    fs, p = fs_mod.resolve("mem://bucket/a.csv")
    assert isinstance(fs, fs_mod.MemoryFileSystem)
    assert p == "bucket/a.csv"


def test_register_filesystem_plugin():
    class MyFS(fs_mod.MemoryFileSystem):
        pass

    fs_mod.register_filesystem("myscheme", MyFS)
    fs, p = fs_mod.resolve("myscheme://data/x")
    assert isinstance(fs, MyFS) and p == "data/x"


def test_memory_fs_roundtrip():
    fs = fs_mod.MemoryFileSystem()
    with fs.open_output("b/one.txt") as f:
        f.write(b"hello")
    assert fs.exists("b/one.txt")
    with fs.open_input("b/one.txt") as f:
        assert f.read() == b"hello"
    assert fs.list("b", ".txt") == ["b/one.txt"]
    fs.delete("b/one.txt")
    assert not fs.exists("b/one.txt")
    with pytest.raises(FileNotFoundError):
        fs.open_input("b/one.txt")


def test_read_write_mem_scheme(ray_start_shared):
    ds = rdata.from_items([{"a": i, "b": i * 2} for i in range(10)])
    ds.write_parquet("mem://out/pq")
    back = rdata.read_parquet("mem://out/pq")
    rows = sorted(back.take_all(), key=lambda r: r["a"])
    assert rows[3] == {"a": 3, "b": 6}
    assert len(rows) == 10


def test_kv_scheme_read_shuffle_iter_roundtrip(ray_start_shared):
    """The full loop through a remote scheme: write parquet to the
    cluster KV, read it back via remote tasks (workers resolve kv://
    through the GCS), shuffle, iterate jax batches."""
    ds = rdata.from_items([{"x": float(i)} for i in range(32)])
    ds.write_parquet("kv://ds1")
    back = rdata.read_parquet("kv://ds1")
    shuffled = back.random_shuffle(seed=3)
    got = []
    for batch in shuffled.iter_jax_batches(batch_size=8):
        arr = np.asarray(batch["x"])
        assert arr.shape == (8,)
        got.extend(arr.tolist())
    assert sorted(got) == [float(i) for i in range(32)]


def test_file_datasource_read_and_write(ray_start_shared, tmp_path):
    src = rdata.FileDatasource(str(tmp_path / "csvs"), fmt="csv")
    ds = rdata.from_items([{"v": i} for i in range(6)])
    ds.write_datasource(src)
    files = fs_mod.LocalFileSystem().list(str(tmp_path / "csvs"), ".csv")
    assert files, "write_datasource produced no files"
    back = rdata.read_datasource(
        rdata.FileDatasource(str(tmp_path / "csvs"), fmt="csv"))
    assert sorted(r["v"] for r in back.take_all()) == list(range(6))


def test_text_and_numpy_via_seam(ray_start_shared, tmp_path):
    d = tmp_path / "texts"
    d.mkdir()
    (d / "a.txt").write_text("one\ntwo\n")
    ds = rdata.read_text(str(d))
    assert sorted(r["text"] for r in ds.take_all()) == ["one", "two"]

    nd = tmp_path / "np"
    nd.mkdir()
    np.save(nd / "x.npy", np.arange(4))
    ds2 = rdata.read_numpy(str(nd))
    assert sorted(r["value"] for r in ds2.take_all()) == [0, 1, 2, 3]


def test_remote_spill_kv(ray_start_shared):
    """Spill targeting a remote scheme: write through, read back, list,
    delete (external_storage.py:445 analog).  Uses the live cluster's
    KV through a SpillManager pointed at kv://."""
    from ray_tpu._private import worker_context
    from ray_tpu._private.spill import SpillManager

    cw = worker_context.core_worker()
    sm = SpillManager(cw.store, "kv://spilltest")
    oid = b"\x01" * 28
    sm.write_direct(oid, b"payload-bytes")
    assert sm.contains(oid)
    assert sm.read(oid) == b"payload-bytes"
    assert sm.read_range(oid, 8, 5) == b"bytes"
    assert sm.size(oid) == 13
    assert (oid, 13) in sm.list()
    sm.delete(oid)
    assert not sm.contains(oid)
    assert sm.read(oid) is None


def test_remote_spill_under_pressure(ray_start_shared):
    """End-to-end: a SpillManager with a kv:// dir spills real LRU
    objects out of the shm store and serves reads back."""
    from ray_tpu._private import worker_context
    from ray_tpu._private.spill import SpillManager

    cw = worker_context.core_worker()
    sm = SpillManager(cw.store, "kv://spill2")
    # place an object in the store, then force-spill it
    ref = ray_tpu.put(np.arange(1000))
    oid = ref._info.oid
    freed = 0
    for cand, size in cw.store.lru_candidates(1):
        if cand.binary() == oid:
            assert sm._spill_one(cand)
            freed = size
            break
    if freed:  # candidate selection is LRU — our object may be pinned
        assert sm.contains(oid)
        assert sm.read(oid) is not None
