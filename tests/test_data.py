"""Data layer tests: creation, fused lazy transforms over remote tasks,
geometry ops, consumption, Train ingest integration."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu import data as rdata

pytestmark = pytest.mark.fast


def test_range_count_take(ray_start_shared):
    ds = rdata.range(100, parallelism=4)
    assert ds.count() == 100
    assert ds.num_blocks == 4
    assert [r["id"] for r in ds.take(3)] == [0, 1, 2]


def test_map_batches_fused_single_stage_execution(ray_start_shared):
    calls = []

    ds = rdata.range(64, parallelism=4) \
        .map_batches(lambda b: {"id": b["id"] * 2}) \
        .map_batches(lambda b: {"id": b["id"] + 1})
    rows = ds.take_all()
    assert sorted(r["id"] for r in rows) == [2 * i + 1 for i in range(64)]


def test_map_filter_flat_map(ray_start_shared):
    ds = rdata.from_items([{"x": i} for i in range(10)])
    out = ds.map(lambda r: {"x": r["x"] * 10}) \
        .filter(lambda r: r["x"] >= 50) \
        .flat_map(lambda r: [{"x": r["x"]}, {"x": r["x"] + 1}])
    xs = sorted(r["x"] for r in out.take_all())
    assert xs == sorted([v for i in range(5, 10)
                         for v in (i * 10, i * 10 + 1)])


def test_split_equalizes(ray_start_shared):
    ds = rdata.range(100, parallelism=3)
    shards = ds.split(4)
    counts = [s.count() for s in shards]
    assert sum(counts) == 100
    assert max(counts) - min(counts) <= 1


def test_iter_batches_batching(ray_start_shared):
    ds = rdata.range(100, parallelism=4)
    batches = list(ds.iter_batches(batch_size=32))
    sizes = [len(b["id"]) for b in batches]
    assert sum(sizes) == 100
    assert sizes[:-1] == [32, 32, 32]
    ids = np.concatenate([b["id"] for b in batches])
    assert sorted(ids.tolist()) == list(range(100))


def test_random_shuffle_and_sort(ray_start_shared):
    ds = rdata.range(50, parallelism=2)
    sh = ds.random_shuffle(seed=0)
    ids = [r["id"] for r in sh.take_all()]
    assert ids != list(range(50)) and sorted(ids) == list(range(50))
    back = sh.sort("id")
    assert [r["id"] for r in back.take_all()] == list(range(50))


def test_parquet_roundtrip(ray_start_shared, tmp_path):
    ds = rdata.from_numpy({"a": np.arange(40), "b": np.arange(40) * 1.5})
    ds.write_parquet(str(tmp_path / "pq"))
    back = rdata.read_parquet(str(tmp_path / "pq"))
    assert back.count() == 40
    df = back.to_pandas().sort_values("a").reset_index(drop=True)
    np.testing.assert_allclose(df["b"], np.arange(40) * 1.5)


def test_dataset_feeds_trainer(ray_start_shared):
    """Dataset.split → per-worker shards → session.get_dataset_shard,
    the Train ingest path (reference dataset_spec.py:66)."""
    from ray_tpu.train import DataParallelTrainer, ScalingConfig

    def loop(config):
        from ray_tpu.air import session

        shard = session.get_dataset_shard("train")
        n = 0
        for batch in shard.iter_batches(batch_size=8):
            n += len(batch["id"])
        session.report({"rows": n})

    ds = rdata.range(64, parallelism=4)
    trainer = DataParallelTrainer(
        loop, scaling_config=ScalingConfig(num_workers=2),
        datasets={"train": ds})
    result = trainer.fit()
    assert result.error is None, result.error
    assert result.metrics["rows"] == 32


def test_distributed_random_shuffle(ray_start_shared):
    from ray_tpu import data

    ds = data.range(1000, parallelism=4)
    out = ds.random_shuffle(seed=0)
    ids = [r["id"] for r in out.take_all()]
    assert sorted(ids) == list(range(1000))
    assert ids[:50] != list(range(50))  # actually shuffled
    assert out.num_blocks == 4  # stays distributed


def test_distributed_sort(ray_start_shared):
    import numpy as np

    from ray_tpu import data

    rng = np.random.default_rng(1)
    vals = rng.permutation(500).astype("int64")
    ds = data.from_numpy({"v": vals}, parallelism=4).sort("v")
    got = [r["v"] for r in ds.take_all()]
    assert got == sorted(vals.tolist())
    ds_desc = data.from_numpy({"v": vals}, parallelism=4).sort(
        "v", descending=True)
    got = [r["v"] for r in ds_desc.take_all()]
    assert got == sorted(vals.tolist(), reverse=True)


def test_groupby_aggregates(ray_start_shared):
    from ray_tpu import data

    rows = [{"k": i % 3, "v": float(i)} for i in range(30)]
    ds = data.from_items(rows, parallelism=4)
    out = {r["k"]: r["sum(v)"] for r in ds.groupby("k").sum("v").take_all()}
    want = {}
    for r in rows:
        want[r["k"]] = want.get(r["k"], 0.0) + r["v"]
    assert out == want
    counts = {r["k"]: r["count"] for r in
              ds.groupby("k").count().take_all()}
    assert counts == {0: 10, 1: 10, 2: 10}


def test_map_batches_actor_pool(ray_start_shared):
    from ray_tpu import data

    class AddModel:
        def __init__(self):
            self.offset = 100  # "expensive" setup happens once per actor

        def __call__(self, batch):
            batch["id"] = batch["id"] + self.offset
            return batch

    ds = data.range(64, parallelism=4).map_batches(
        AddModel, compute=data.ActorPoolStrategy(size=2, num_cpus=0.5))
    got = sorted(r["id"] for r in ds.take_all())
    assert got == [i + 100 for i in range(64)]


def test_dataset_pipeline_windows_and_repeat(ray_start_shared):
    from ray_tpu import data

    pipe = data.range(40, parallelism=4).window(blocks_per_window=2)
    seen = [b["id"] for b in pipe.iter_batches(batch_size=10)]
    assert sorted(x for b in seen for x in b.tolist()) == list(range(40))
    pipe2 = data.range(10, parallelism=2).repeat(3)
    total = sum(len(b["id"]) for b in pipe2.iter_batches(batch_size=5))
    assert total == 30


def test_read_json_text_numpy(ray_start_shared, tmp_path):
    import json as json_mod

    import numpy as np

    from ray_tpu import data

    jpath = tmp_path / "rows.json"
    jpath.write_text("\n".join(
        json_mod.dumps({"a": i}) for i in range(5)))
    assert sorted(r["a"] for r in
                  data.read_json(str(jpath)).take_all()) == list(range(5))

    tpath = tmp_path / "doc.txt"
    tpath.write_text("alpha\nbeta\n")
    assert [r["text"] for r in data.read_text(str(tpath)).take_all()] == \
        ["alpha", "beta"]

    npath = tmp_path / "arr.npy"
    np.save(npath, np.arange(4))
    assert [r["value"] for r in
            data.read_numpy(str(npath)).take_all()] == [0, 1, 2, 3]


def test_groupby_string_keys_cross_worker(ray_start_shared):
    """String keys must aggregate to ONE row per key even when map tasks
    run in different worker processes (per-process hash() salting must
    not leak into partitioning)."""
    from ray_tpu import data

    rows = [{"name": n, "v": 1.0} for n in
            ("alpha", "beta", "gamma") * 20]
    out = data.from_items(rows, parallelism=6).groupby("name").sum("v")
    table = {r["name"]: r["sum(v)"] for r in out.take_all()}
    assert table == {"alpha": 20.0, "beta": 20.0, "gamma": 20.0}
    assert len(out.take_all()) == 3  # no duplicate partial rows


def test_iter_jax_batches_sharded(ray_start_shared):
    """TPU ingest bridge: batches arrive as jax arrays, sharded over the
    mesh data axis when a sharding is given."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ray_tpu import data
    from ray_tpu.parallel import MeshSpec, fake_mesh

    ds = data.from_numpy({"x": np.arange(64, dtype=np.float32),
                          "y": np.arange(64, dtype=np.int64)})
    # plain device transfer
    batches = list(ds.iter_jax_batches(batch_size=16))
    assert len(batches) == 4
    assert isinstance(batches[0]["x"], jax.Array)
    assert batches[0]["x"].dtype == jnp.float32
    np.testing.assert_array_equal(
        np.concatenate([np.asarray(b["x"]) for b in batches]),
        np.arange(64, dtype=np.float32))

    # mesh-sharded transfer with a host-side cast
    mesh = fake_mesh(8, MeshSpec(data=8))
    sh = NamedSharding(mesh, P("data"))
    b = next(ds.iter_jax_batches(batch_size=32, sharding=sh,
                                 dtypes={"y": np.float32}))
    assert b["x"].sharding == sh
    assert len(b["x"].devices()) == 8
    assert b["y"].dtype == jnp.float32  # host-side cast applied

    # smaller-than-batch dataset with default drop_last=True yields
    # nothing (documented static-shape contract)
    tiny = data.from_numpy({"x": np.arange(5, dtype=np.float32)})
    assert list(tiny.iter_jax_batches(batch_size=16)) == []
    assert len(list(tiny.iter_jax_batches(batch_size=16,
                                          drop_last=False))) == 1


def test_tensor_columns_roundtrip(ray_start_shared):
    """N-D numpy columns survive the block format (FixedSizeList
    encoding): shapes and dtypes reassemble exactly, through transforms
    and the object store."""
    import numpy as np

    from ray_tpu import data

    imgs = np.arange(2 * 4 * 4 * 3, dtype=np.float32).reshape(2, 4, 4, 3)
    toks = np.arange(2 * 7, dtype=np.int64).reshape(2, 7)
    ds = data.from_numpy({"img": imgs, "tok": toks,
                          "label": np.array([1, 2])})
    out = next(ds.iter_batches(batch_size=2))
    assert out["img"].shape == (2, 4, 4, 3)
    assert out["img"].dtype == np.float32
    np.testing.assert_array_equal(out["img"], imgs)
    np.testing.assert_array_equal(out["tok"], toks)
    # through a map_batches transform (remote task) as well
    doubled = ds.map_batches(lambda b: {"img2": b["img"] * 2})
    out2 = next(doubled.iter_batches(batch_size=2))
    np.testing.assert_array_equal(out2["img2"], imgs * 2)


# -- round 4: streaming execution, push-based shuffle, datasources --------

def test_streaming_executor_bounds_submission(ray_start_shared,
                                              tmp_path):
    """The executor must not SUBMIT more than its window (+1 refill)
    ahead of the consumer — asserted structurally on the marker count
    at first yield with an explicit small window, not on wall-clock."""
    import time

    from ray_tpu.data.streaming import StreamingExecutor

    marker = str(tmp_path / "started")

    def slow_stage(table, _m=marker):
        with open(_m, "a") as f:
            f.write("x")
        time.sleep(0.1)
        return table

    ds = rdata.range(64, parallelism=8)
    ex = StreamingExecutor(max_in_flight=2)
    it = ex.execute(ds._block_refs, [slow_stage])
    next(it)  # first block done
    with open(marker) as f:
        started_at_first = len(f.read())
    # window 2 + at most one refill round before the first yield
    assert started_at_first <= 3, started_at_first
    assert len(list(it)) == 7  # remainder all arrives, in order


def test_streaming_iter_batches_caches_on_full_consumption(
        ray_start_shared, tmp_path):
    marker = str(tmp_path / "executed")

    def stage(table, _m=marker):
        with open(_m, "a") as f:
            f.write("x")
        return table

    ds = rdata.range(64, parallelism=8).map_batches(stage)
    assert len(list(ds.iter_batches(batch_size=8))) == 8
    # full consumption caches: re-iterating runs no new stage tasks
    list(ds.iter_batches(batch_size=8))
    with open(marker) as f:
        assert len(f.read()) == 8


def test_streaming_stats_recorded(ray_start_shared):
    ds = rdata.range(20, parallelism=2).map(lambda r: r)
    list(ds.iter_batches(batch_size=10))
    assert "stream" in ds.stats()
    assert "2 blocks" in ds.stats()


def test_push_based_shuffle_matches_two_phase(ray_start_shared):
    """Above the threshold the push-based plan runs — same row multiset
    as the naive exchange, merge stages included."""
    from ray_tpu.data import shuffle as sm

    assert sm.PUSH_BASED_THRESHOLD <= 20
    ds = rdata.range(400, parallelism=20)  # 20 blocks >= threshold
    out = ds.random_shuffle(seed=7)
    vals = sorted(r["id"] for r in out.take_all())
    assert vals == list(range(400))
    assert out.num_blocks == 20


def test_push_based_sort(ray_start_shared):
    import numpy as np

    rng = np.random.RandomState(0)
    items = [{"k": float(x)} for x in rng.randn(300)]
    ds = rdata.from_items(items).repartition(20)
    out = ds.sort("k")
    got = [r["k"] for r in out.take_all()]
    assert got == sorted(r["k"] for r in items)


def test_push_based_groupby(ray_start_shared):
    items = [{"g": i % 17, "v": i} for i in range(340)]
    ds = rdata.from_items(items).repartition(20)
    out = {r["g"]: r["count"] for r in ds.groupby("g").count().take_all()}
    assert out == {g: 20 for g in range(17)}


def test_read_datasource_and_write_datasource(ray_start_shared):
    from ray_tpu.data import RangeDatasource, read_datasource
    from ray_tpu.data.datasource import Datasource

    ds = read_datasource(RangeDatasource(100), parallelism=5)
    assert ds.num_blocks == 5
    assert sorted(r["id"] for r in ds.take_all()) == list(range(100))

    class CollectSink(Datasource):
        def __init__(self, path):
            self.path = path
            self.total = None

        def write_block(self, block, i, **kw):
            import os

            with open(os.path.join(self.path, f"{i}.txt"), "w") as f:
                f.write(str(block.num_rows))
            return block.num_rows

        def on_write_complete(self, results):
            self.total = sum(results)

    import tempfile

    with tempfile.TemporaryDirectory() as d:
        sink = CollectSink(d)
        ds.write_datasource(sink)
        assert sink.total == 100


def test_custom_read_task_num_rows_metadata():
    from ray_tpu.data import RangeDatasource

    tasks = RangeDatasource(10).get_read_tasks(3)
    assert sum(t.num_rows for t in tasks) == 10


def test_column_ops_limit_unique_zip_show(ray_start_shared, capsys):
    ds = rdata.from_items([{"a": i, "b": i * 2, "c": str(i % 3)}
                          for i in range(10)])
    sel = ds.select_columns(["a", "c"]).take(2)
    assert set(sel[0]) == {"a", "c"}
    drop = ds.drop_columns(["b"]).take(1)
    assert set(drop[0]) == {"a", "c"}
    ren = ds.rename_columns({"a": "x"}).take(1)
    assert set(ren[0]) == {"x", "b", "c"}
    assert [r["a"] for r in ds.limit(3).take_all()] == [0, 1, 2]
    assert sorted(ds.unique("c")) == ["0", "1", "2"]

    other = rdata.from_items([{"d": -i} for i in range(10)])
    z = ds.zip(other)
    rows = z.take_all()
    assert rows[4] == {"a": 4, "b": 8, "c": "1", "d": -4}
    # duplicate column names get suffixed
    z2 = ds.zip(rdata.from_items([{"a": 100 + i} for i in range(10)]))
    assert z2.take(1)[0]["a_1"] == 100

    ds.show(2)
    out = capsys.readouterr().out
    assert "'a': 0" in out and out.count("\n") == 2

    with pytest.raises(ValueError, match="equal row counts"):
        ds.zip(rdata.from_items([{"d": 1}]))
    # suffixing finds a FREE name instead of clobbering
    both = rdata.from_items([{"a_1": 10 + i, "a": 100 + i}
                             for i in range(10)])
    z3 = ds.zip(both)
    row = z3.take(1)[0]
    assert row["a"] == 0 and row["a_1"] == 10 and row["a_2"] == 100
