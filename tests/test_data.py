"""Data layer tests: creation, fused lazy transforms over remote tasks,
geometry ops, consumption, Train ingest integration."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu import data as rdata


def test_range_count_take(ray_start_shared):
    ds = rdata.range(100, parallelism=4)
    assert ds.count() == 100
    assert ds.num_blocks == 4
    assert [r["id"] for r in ds.take(3)] == [0, 1, 2]


def test_map_batches_fused_single_stage_execution(ray_start_shared):
    calls = []

    ds = rdata.range(64, parallelism=4) \
        .map_batches(lambda b: {"id": b["id"] * 2}) \
        .map_batches(lambda b: {"id": b["id"] + 1})
    rows = ds.take_all()
    assert sorted(r["id"] for r in rows) == [2 * i + 1 for i in range(64)]


def test_map_filter_flat_map(ray_start_shared):
    ds = rdata.from_items([{"x": i} for i in range(10)])
    out = ds.map(lambda r: {"x": r["x"] * 10}) \
        .filter(lambda r: r["x"] >= 50) \
        .flat_map(lambda r: [{"x": r["x"]}, {"x": r["x"] + 1}])
    xs = sorted(r["x"] for r in out.take_all())
    assert xs == sorted([v for i in range(5, 10)
                         for v in (i * 10, i * 10 + 1)])


def test_split_equalizes(ray_start_shared):
    ds = rdata.range(100, parallelism=3)
    shards = ds.split(4)
    counts = [s.count() for s in shards]
    assert sum(counts) == 100
    assert max(counts) - min(counts) <= 1


def test_iter_batches_batching(ray_start_shared):
    ds = rdata.range(100, parallelism=4)
    batches = list(ds.iter_batches(batch_size=32))
    sizes = [len(b["id"]) for b in batches]
    assert sum(sizes) == 100
    assert sizes[:-1] == [32, 32, 32]
    ids = np.concatenate([b["id"] for b in batches])
    assert sorted(ids.tolist()) == list(range(100))


def test_random_shuffle_and_sort(ray_start_shared):
    ds = rdata.range(50, parallelism=2)
    sh = ds.random_shuffle(seed=0)
    ids = [r["id"] for r in sh.take_all()]
    assert ids != list(range(50)) and sorted(ids) == list(range(50))
    back = sh.sort("id")
    assert [r["id"] for r in back.take_all()] == list(range(50))


def test_parquet_roundtrip(ray_start_shared, tmp_path):
    ds = rdata.from_numpy({"a": np.arange(40), "b": np.arange(40) * 1.5})
    ds.write_parquet(str(tmp_path / "pq"))
    back = rdata.read_parquet(str(tmp_path / "pq"))
    assert back.count() == 40
    df = back.to_pandas().sort_values("a").reset_index(drop=True)
    np.testing.assert_allclose(df["b"], np.arange(40) * 1.5)


def test_dataset_feeds_trainer(ray_start_shared):
    """Dataset.split → per-worker shards → session.get_dataset_shard,
    the Train ingest path (reference dataset_spec.py:66)."""
    from ray_tpu.train import DataParallelTrainer, ScalingConfig

    def loop(config):
        from ray_tpu.air import session

        shard = session.get_dataset_shard("train")
        n = 0
        for batch in shard.iter_batches(batch_size=8):
            n += len(batch["id"])
        session.report({"rows": n})

    ds = rdata.range(64, parallelism=4)
    trainer = DataParallelTrainer(
        loop, scaling_config=ScalingConfig(num_workers=2),
        datasets={"train": ds})
    result = trainer.fit()
    assert result.error is None, result.error
    assert result.metrics["rows"] == 32
