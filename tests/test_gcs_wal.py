"""GCS continuous persistence: WAL between snapshots (reference analog:
the Redis-backed store's continuous durability,
src/ray/gcs/store_client/redis_store_client.h:28).  Every acknowledged
mutation must survive a hard kill, snapshot or not."""

import asyncio
import os
import signal
import subprocess
import sys
import time

import pytest

from ray_tpu._private.gcs import GcsServer, _WAL


def _run(coro):
    return asyncio.get_event_loop_policy().new_event_loop() \
        .run_until_complete(coro)


def _mk(persist):
    return GcsServer(persist_path=str(persist))


def test_wal_survives_crash_before_any_snapshot(tmp_path):
    persist = tmp_path / "gcs.pkl"
    g = _mk(persist)

    async def burst():
        for i in range(50):
            await g.rpc_kv_put(None, {"key": f"k{i}",
                                      "value": f"v{i}".encode()})
        await g.rpc_kv_del(None, {"key": "k0"})
        await g.rpc_job_register(None, {})

    _run(burst())
    # crash: no snapshot was ever written (monitor loop never ran)
    assert not os.path.exists(persist)

    g2 = _mk(persist)
    g2._restore()
    assert g2.kv.get("k49") == b"v49"
    assert "k0" not in g2.kv
    assert g2._job_counter == 1


def test_wal_truncated_after_snapshot_and_replay_idempotent(tmp_path):
    persist = tmp_path / "gcs.pkl"
    g = _mk(persist)
    _run(g.rpc_kv_put(None, {"key": "a", "value": b"1"}))
    # snapshot flow as the monitor loop runs it
    state = g._capture_state()
    g._wal.rotate()
    g._write_snapshot(state)
    g._wal.commit_rotation()
    _run(g.rpc_kv_put(None, {"key": "b", "value": b"2"}))

    g2 = _mk(persist)
    g2._restore()
    assert g2.kv == {"a": b"1", "b": b"2"}


def test_crash_between_rotate_and_snapshot_write(tmp_path):
    """The nastiest window: WAL rotated (records in .old), snapshot not
    yet written.  Replay must fold .old + current."""
    persist = tmp_path / "gcs.pkl"
    g = _mk(persist)
    _run(g.rpc_kv_put(None, {"key": "early", "value": b"x"}))
    g._capture_state()
    g._wal.rotate()          # crash here: snapshot never written
    _run(g.rpc_kv_put(None, {"key": "late", "value": b"y"}))

    g2 = _mk(persist)
    g2._restore()
    assert g2.kv.get("early") == b"x"
    assert g2.kv.get("late") == b"y"


def test_snapshot_write_failure_splices_wal_back(tmp_path):
    persist = tmp_path / "gcs.pkl"
    g = _mk(persist)
    _run(g.rpc_kv_put(None, {"key": "a", "value": b"1"}))
    g._capture_state()
    g._wal.rotate()
    _run(g.rpc_kv_put(None, {"key": "b", "value": b"2"}))
    g._wal.abort_rotation()  # snapshot write "failed"

    g2 = _mk(persist)
    g2._restore()
    assert g2.kv == {"a": b"1", "b": b"2"}


def test_torn_tail_record_dropped(tmp_path):
    persist = tmp_path / "gcs.pkl"
    g = _mk(persist)
    _run(g.rpc_kv_put(None, {"key": "whole", "value": b"1"}))
    # simulate a crash mid-append: chop the last record in half
    wal = str(persist) + ".wal"
    data = open(wal, "rb").read()
    open(wal, "wb").write(data[:len(data) - 3])

    g2 = _mk(persist)
    g2._restore()  # must not raise; the torn record is simply dropped
    assert "whole" not in g2.kv or g2.kv.get("whole") == b"1"


def test_detached_actor_and_pg_records(tmp_path):
    persist = tmp_path / "gcs.pkl"
    g = _mk(persist)

    # zero registered nodes: registration queues (cluster forming) and
    # the REGISTRATION record must survive a crash
    async def ops2():
        await g.rpc_actor_register(None, {
            "actor_id": b"\x02" * 12,
            "spec": {"resources": {"CPU": 1.0}, "fid": b"f"},
            "name": "det2", "max_restarts": 0,
            "lifetime": "detached"})
        await g.rpc_pg_create(None, {
            "pg_id": b"\x03" * 12,
            "bundles": [{"CPU": 1.0}], "strategy": "PACK",
            "name": "mypg"})

    _run(ops2())
    g2 = _mk(persist)
    g2._restore()
    assert g2.named_actors.get("det2") == b"\x02" * 12
    assert g2.named_pgs.get("mypg") == b"\x03" * 12


_KILL_SCRIPT = r"""
import os, sys, time
import ray_tpu
from ray_tpu._private import worker_context

persist = sys.argv[1]
ray_tpu.init(num_cpus=2, object_store_memory=128 * 1024 * 1024,
             _system_config={"gcs_persist_path": persist})
cw = worker_context.core_worker()
for i in range(200):
    cw.kv_put(f"burst:{i}", str(i).encode())
print("BURST_DONE", flush=True)
time.sleep(60)  # parent SIGKILLs us mid-life, snapshot tick or not
"""


def test_hard_kill_mid_burst_loses_nothing(tmp_path):
    """End-to-end: a head process acknowledges 200 kv writes and is
    SIGKILLed; the restarted head must see every one of them."""
    persist = str(tmp_path / "gcs.pkl")
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    repo_root = os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH",
                                                         "")
    script = tmp_path / "burst.py"
    script.write_text(_KILL_SCRIPT)
    proc = subprocess.Popen([sys.executable, str(script), persist],
                            stdout=subprocess.PIPE, env=env, text=True)
    deadline = time.monotonic() + 120
    line = ""
    while time.monotonic() < deadline:
        line = proc.stdout.readline()
        if "BURST_DONE" in line:
            break
    assert "BURST_DONE" in line, "burst process never finished writes"
    os.kill(proc.pid, signal.SIGKILL)
    proc.wait(timeout=30)

    g = GcsServer(persist_path=persist)
    g._restore()
    missing = [i for i in range(200)
               if g.kv.get(f"burst:{i}") != str(i).encode()]
    assert not missing, f"lost {len(missing)} acknowledged writes"
