"""Object spilling + create backpressure + chunked transfer tests.

Reference analogs: python/ray/tests/test_object_spilling.py (spill/restore)
and the chunked ObjectManager pull path (pull_manager.h:48).
"""

import gc
import os

import numpy as np
import pytest

import ray_tpu
from ray_tpu._private import worker_context
from ray_tpu._private.ids import ObjectID


@pytest.fixture
def small_store_cluster():
    ray_tpu.init(num_cpus=2, object_store_memory=64 * 1024 * 1024)
    yield
    ray_tpu.shutdown()


def _cw():
    return worker_context.core_worker()


def test_put_working_set_2x_arena_spills(small_store_cluster):
    """A working set 2x the arena size succeeds: LRU objects spill to disk
    and reads restore them (VERDICT r2 item 2 done-criterion)."""
    cw = _cw()
    n = 16
    each = 8 * 1024 * 1024  # 16 * 8MiB = 128MiB in a 64MiB arena
    refs = [ray_tpu.put(np.full(each // 4, i, dtype=np.int32))
            for i in range(n)]
    spilled = cw.spill.list()
    assert spilled, "nothing spilled despite 2x-arena working set"
    # every object is still readable (store or spill)
    for i, r in enumerate(refs):
        val = ray_tpu.get(r)
        assert val[0] == i and val.shape == (each // 4,)


def test_spill_files_deleted_on_free(small_store_cluster):
    cw = _cw()
    refs = [ray_tpu.put(np.zeros(2 * 1024 * 1024, dtype=np.int32))
            for _ in range(12)]  # 96 MiB: forces spill
    assert cw.spill.list()
    spill_dir = cw.spill.dir
    del refs
    import time

    gc.collect()
    time.sleep(0.3)
    gc.collect()
    time.sleep(0.3)
    leftover = [f for f in os.listdir(spill_dir)
                if not f.endswith(".tmp")] if os.path.isdir(spill_dir) else []
    assert not leftover, f"spill files leaked: {leftover[:3]}"


def test_task_returns_spill_and_restore(small_store_cluster):
    """Task returns larger than the arena in aggregate still resolve."""

    @ray_tpu.remote
    def make(i):
        return np.full(2 * 1024 * 1024, i, dtype=np.int32)  # 8 MiB

    refs = [make.remote(i) for i in range(12)]
    vals = ray_tpu.get(refs, timeout=120)
    for i, v in enumerate(vals):
        assert v[0] == i


def test_oversized_put_fallback_allocates_to_disk(small_store_cluster):
    """An object bigger than the whole arena still puts and gets:
    create falls back to disk-backed allocation (reference: plasma
    CreateAndSpillIfNeeded → fallback allocator, client.h:128)."""
    cw = _cw()
    cw.config.create_retry_timeout_s = 1.0
    big = np.zeros(80 * 1024 * 1024, dtype=np.uint8)  # > arena
    big[7] = 42
    ref = ray_tpu.put(big)
    out = ray_tpu.get(ref, timeout=60)
    assert out[7] == 42 and out.shape == big.shape


def test_oversized_put_without_spill_fails_cleanly(tmp_path):
    """With spilling disabled there is no fallback: create fails with a
    clear error instead of hanging."""
    import ray_tpu as rt

    rt.init(num_cpus=1, object_store_memory=32 * 1024 * 1024,
            _system_config={"spill_dir": "/dev/null/nonexistent-disable",
                            "create_retry_timeout_s": 1.0})
    try:
        from ray_tpu._private.object_store import ObjectStoreError

        cw = _cw()
        cw.spill.dir = ""  # hard-disable the spill path
        with pytest.raises((ObjectStoreError, MemoryError)):
            rt.put(np.zeros(80 * 1024 * 1024, dtype=np.uint8))
    finally:
        rt.shutdown()
