"""Repo-wide metric hygiene guard: every Counter/Gauge/Histogram
declared under ray_tpu/ must carry a literal, Prometheus-exportable
name (^[a-z][a-z0-9_]*$) — and the registry must warn (once) when two
live instances collide on one name, instead of silently dropping data.
"""

import ast
import pathlib
import re
import warnings

import pytest

pytestmark = pytest.mark.fast

_PKG = pathlib.Path(__file__).resolve().parents[1] / "ray_tpu"
_NAME_RE = re.compile(r"^[a-z][a-z0-9_]*$")
_CLASSES = {"Counter", "Gauge", "Histogram"}


def _metric_calls(tree):
    """(lineno, func_label, name_node) for every call in `tree` that
    constructs a util.metrics class — either a bare alias imported via
    ``from ray_tpu.util.metrics import X`` or an attribute call on a
    module imported as ``metrics``."""
    aliases = {}  # local name -> metric class
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and \
                node.module == "ray_tpu.util.metrics":
            for a in node.names:
                if a.name in _CLASSES:
                    aliases[a.asname or a.name] = a.name
    out = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        label = None
        if isinstance(f, ast.Name) and f.id in aliases:
            label = aliases[f.id]
        elif (isinstance(f, ast.Attribute) and f.attr in _CLASSES
                and isinstance(f.value, ast.Name)
                and f.value.id == "metrics"):
            label = f.attr
        if label is None:
            continue
        name_node = node.args[0] if node.args else None
        for kw in node.keywords:
            if kw.arg == "name":
                name_node = kw.value
        out.append((node.lineno, label, name_node))
    return out


def test_every_metric_name_is_literal_and_prometheus_safe():
    found = []
    bad = []
    for path in sorted(_PKG.rglob("*.py")):
        tree = ast.parse(path.read_text(), filename=str(path))
        for lineno, label, name_node in _metric_calls(tree):
            where = f"{path.relative_to(_PKG.parent)}:{lineno}"
            if not (isinstance(name_node, ast.Constant)
                    and isinstance(name_node.value, str)):
                bad.append(f"{where}: {label} name is not a string "
                           f"literal (guard can't verify it)")
                continue
            name = name_node.value
            found.append(name)
            if not _NAME_RE.match(name):
                bad.append(f"{where}: {label} name {name!r} violates "
                           f"^[a-z][a-z0-9_]*$")
    assert not bad, "\n".join(bad)
    # the scan must actually SEE the telemetry metrics, else the guard
    # is vacuously green
    assert "serve_ttft_ms" in found
    assert "train_step_time_ms" in found
    assert len(found) >= 15


def test_metric_invalid_names_raise():
    from ray_tpu.util import metrics

    for name in ("Bad", "1starts_with_digit", "has-dash", "has space",
                 "", "raytpu_app_UPPER"):
        with pytest.raises(ValueError, match="invalid metric name"):
            metrics.Gauge(name, "nope")


def test_duplicate_registration_warns_once_newest_wins():
    from ray_tpu.util import metrics

    g1 = metrics.Gauge("guard_dup_gauge", "first")
    with pytest.warns(RuntimeWarning, match="registered more than once"):
        g2 = metrics.Gauge("guard_dup_gauge", "second")
    # newest instance owns the registry slot
    assert metrics._registry.metrics["guard_dup_gauge"] is g2
    g1.set(1.0)
    g2.set(2.0)
    snap = metrics._registry.snapshot()
    assert snap["guard_dup_gauge"]["values"][0][1] == 2.0
    # the SAME name warns only once per process (no warning storm)
    with warnings.catch_warnings():
        warnings.simplefilter("error", RuntimeWarning)
        metrics.Gauge("guard_dup_gauge", "third")
    # re-registering the SAME instance never warns
    with warnings.catch_warnings():
        warnings.simplefilter("error", RuntimeWarning)
        metrics._registry.register(g2)
