"""Declarative serve config + long-poll push (reference:
serve/schema.py:1, serve/_private/long_poll.py:184)."""

import time

import pytest

import ray_tpu
from ray_tpu import serve
from ray_tpu.serve.schema import ServeApplicationSchema

pytestmark = pytest.mark.fast


# module-level so the import-path machinery can resolve it
@serve.deployment(name="echo_app")
class EchoApp:
    def __call__(self, x):
        return {"echo": x}


def build_app(scale: int = 1):
    return EchoApp.options(num_replicas=scale).bind()


def test_schema_validation_errors():
    with pytest.raises(ValueError, match="import_path"):
        ServeApplicationSchema.parse({})
    with pytest.raises(ValueError, match="format"):
        ServeApplicationSchema.parse({"import_path": "no_colon"})
    with pytest.raises(ValueError, match="unknown deployment config"):
        ServeApplicationSchema.parse({
            "import_path": "m:a",
            "deployments": [{"name": "x", "replicas": 3}]})
    with pytest.raises(ValueError, match="num_replicas"):
        ServeApplicationSchema.parse({
            "import_path": "m:a",
            "deployments": [{"name": "x", "num_replicas": -1}]})
    with pytest.raises(ValueError, match="duplicate"):
        ServeApplicationSchema.parse({
            "import_path": "m:a",
            "deployments": [{"name": "x"}, {"name": "x"}]})
    with pytest.raises(ValueError, match="min_replicas"):
        ServeApplicationSchema.parse({
            "import_path": "m:a",
            "deployments": [{"name": "x", "autoscaling_config":
                             {"min_replicas": 5, "max_replicas": 2}}]})
    ok = ServeApplicationSchema.parse({
        "import_path": "tests.test_serve_config:EchoApp",
        "deployments": [{"name": "echo_app", "num_replicas": 2}]})
    assert ok.deployments[0].num_replicas == 2


def test_apply_config_deploys_and_overrides(ray_start_shared):
    from ray_tpu.serve import schema

    try:
        handle = schema.apply({
            "import_path": "tests.test_serve_config:EchoApp",
            "deployments": [{"name": "echo_app", "num_replicas": 2}]})
        assert handle.call("hi")["echo"] == "hi"
        st = serve.status()
        assert st["echo_app"]["replicas"] == 2
    finally:
        serve.shutdown()


def test_apply_config_builder_function(ray_start_shared):
    from ray_tpu.serve import schema

    try:
        handle = schema.apply({
            "import_path": "tests.test_serve_config:build_app",
            "args": {"scale": 1}})
        assert handle.call("yo")["echo"] == "yo"
    finally:
        serve.shutdown()


def test_long_poll_pushes_membership(ray_start_shared):
    """A redeploy must reach an existing handle via the push channel —
    no 5s polling interval, no stale replica errors."""
    try:
        @serve.deployment(name="lp")
        class V1:
            def __call__(self, x):
                return "v1"

        handle = serve.run(V1.bind())
        assert handle.call("x") == "v1"

        @serve.deployment(name="lp")
        class V2:
            def __call__(self, x):
                return "v2"

        serve.run(V2.bind())  # same name: replica set fully replaced
        # the OLD handle must pick up the new replicas push-style;
        # allow a short beat for the long-poll round trip (well under
        # the old 5s polling interval)
        deadline = time.monotonic() + 4.0
        got = None
        while time.monotonic() < deadline:
            try:
                got = handle.call("x")
                if got == "v2":
                    break
            except Exception:  # noqa: BLE001 - transient during swap
                pass
            time.sleep(0.2)
        assert got == "v2"
    finally:
        serve.shutdown()


def test_listen_for_change_semantics():
    """Controller-level contract: immediate answer on version mismatch,
    block-until-change otherwise, -1 for deleted deployments."""
    import threading

    from ray_tpu.serve.controller import ServeController

    c = ServeController.__new__(ServeController)  # no reconcile thread
    c.deployments = {}
    c.routes = {}
    c._lock = threading.Lock()
    c._change = threading.Condition(c._lock)
    c._stop = True

    assert c.listen_for_change("ghost", 0)["version"] == -1
    c.deployments["d"] = {"config": {}, "replicas": ["r1"], "version": 3,
                          "scale_pending_since": None}
    out = c.listen_for_change("d", 0)   # stale version: immediate
    assert out == {"version": 3, "replicas": ["r1"]}
    out = c.listen_for_change("d", 3, timeout=0.2)  # current: blocks
    assert out["replicas"] is None

    def mutate():
        time.sleep(0.2)
        with c._lock:
            c.deployments["d"]["replicas"] = ["r1", "r2"]
            c._bump_locked("d")

    t = threading.Thread(target=mutate)
    t.start()
    out = c.listen_for_change("d", 3, timeout=5.0)
    t.join()
    assert out["version"] == 4 and out["replicas"] == ["r1", "r2"]
