"""Tune logger/callback surface: per-trial CSV/JSONL/TB artifacts and
Callback lifecycle hooks (reference analogs: tune/logger/csv.py:69
CSVLoggerCallback, logger/tensorboardx.py, tune/callback.py)."""

import csv
import glob
import json
import os

import pytest

import ray_tpu
from ray_tpu import tune
from ray_tpu.tune.logger import read_tfevents


def _objective(config):
    from ray_tpu.air import session

    for i in range(3):
        session.report({"loss": config["x"] / (i + 1), "nested": {"a": i}})


def test_default_loggers_leave_artifacts(ray_start_shared, tmp_path):
    grid = tune.Tuner(
        _objective,
        param_space={"x": tune.grid_search([1.0, 2.0])},
        run_config=ray_tpu.air.RunConfig(storage_path=str(tmp_path),
                                         name="exp"),
    ).fit()
    assert not grid.errors
    trial_dirs = sorted(glob.glob(str(tmp_path / "exp" / "trial_*")))
    assert len(trial_dirs) == 2
    for d in trial_dirs:
        # params.json records the config
        with open(os.path.join(d, "params.json")) as f:
            params = json.load(f)
        assert params["x"] in (1.0, 2.0)
        # result.json: one JSON object per report
        with open(os.path.join(d, "result.json")) as f:
            rows = [json.loads(line) for line in f if line.strip()]
        assert len(rows) == 3
        assert rows[0]["loss"] == params["x"]
        # progress.csv: header + 3 rows, nested keys flattened
        with open(os.path.join(d, "progress.csv")) as f:
            crows = list(csv.DictReader(f))
        assert len(crows) == 3
        assert "nested/a" in crows[0]
        assert float(crows[-1]["loss"]) == pytest.approx(params["x"] / 3)
        # tfevents: scalars parse back out with increasing steps
        ev_files = glob.glob(os.path.join(d, "events.out.tfevents.*"))
        assert len(ev_files) == 1
        scalars = list(read_tfevents(ev_files[0]))
        loss_events = [(v, s) for tag, v, s in scalars
                       if tag == "ray/tune/loss"]
        assert len(loss_events) == 3
        assert [s for _, s in loss_events] == [1, 2, 3]
        assert loss_events[0][0] == pytest.approx(params["x"])


class _Recorder(tune.Callback):
    def __init__(self):
        self.events = []

    def setup(self, experiment_dir):
        self.events.append(("setup", experiment_dir))

    def on_trial_start(self, trial):
        self.events.append(("start", trial.trial_id))

    def on_trial_result(self, trial, result):
        self.events.append(("result", trial.trial_id, result["loss"]))

    def on_checkpoint(self, trial, checkpoint):
        self.events.append(("checkpoint", trial.trial_id))

    def on_trial_error(self, trial, error):
        self.events.append(("error", trial.trial_id))

    def on_trial_complete(self, trial):
        self.events.append(("complete", trial.trial_id, trial.status))

    def on_experiment_end(self, trials):
        self.events.append(("end", len(trials)))


def _ckpt_objective(config):
    from ray_tpu.air import session
    from ray_tpu.air.checkpoint import Checkpoint

    for i in range(2):
        session.report({"loss": 1.0 / (i + 1)},
                       checkpoint=Checkpoint.from_dict({"i": i}))


def test_callback_observes_full_lifecycle(ray_start_shared, tmp_path):
    rec = _Recorder()
    tune.Tuner(
        _ckpt_objective,
        param_space={"x": tune.grid_search([1.0])},
        run_config=ray_tpu.air.RunConfig(storage_path=str(tmp_path),
                                         name="cb", callbacks=[rec]),
    ).fit()
    kinds = [e[0] for e in rec.events]
    assert kinds[0] == "setup"
    assert "start" in kinds and "result" in kinds
    assert "checkpoint" in kinds
    assert kinds.index("start") < kinds.index("result")
    complete = [e for e in rec.events if e[0] == "complete"]
    assert len(complete) == 1 and complete[0][2] == "TERMINATED"
    assert kinds[-1] == "end"
    assert rec.events.count(("result", rec.events[1][1], 1.0)) == 1


def _crashing_objective(config):
    from ray_tpu.air import session

    session.report({"loss": 1.0})
    raise RuntimeError("boom")


def test_callback_sees_trial_error(ray_start_shared, tmp_path):
    rec = _Recorder()
    grid = tune.Tuner(
        _crashing_objective,
        param_space={"x": tune.grid_search([1.0])},
        run_config=ray_tpu.air.RunConfig(
            storage_path=str(tmp_path), name="err", callbacks=[rec],
            failure_config=ray_tpu.air.FailureConfig(max_failures=0)),
    ).fit()
    assert grid.errors
    kinds = [e[0] for e in rec.events]
    assert "error" in kinds
    complete = [e for e in rec.events if e[0] == "complete"]
    assert complete and complete[0][2] == "ERROR"


def test_callback_failure_does_not_abort_run(ray_start_shared, tmp_path):
    class Bad(tune.Callback):
        def on_trial_result(self, trial, result):
            raise ValueError("callback bug")

    grid = tune.Tuner(
        _objective,
        param_space={"x": tune.grid_search([1.0])},
        run_config=ray_tpu.air.RunConfig(storage_path=str(tmp_path),
                                         name="bad", callbacks=[Bad()]),
    ).fit()
    assert not grid.errors
    assert grid.trials[0].iteration == 3


def test_pb2_learns_toward_optimum(ray_start_shared, tmp_path):
    # quadratic bandit: reward improves as lr approaches 0.5; PB2 should
    # exploit+explore the population toward the peak and beat its start
    def obj(config):
        from ray_tpu.air import session
        from ray_tpu.air.checkpoint import Checkpoint

        lr = config["lr"]
        for i in range(12):
            score = -((lr - 0.5) ** 2) * (i + 1)
            session.report({"score": score, "lr": lr},
                           checkpoint=Checkpoint.from_dict({"i": i}))

    sched = tune.PB2(metric="score", mode="max",
                     perturbation_interval=3,
                     hyperparam_bounds={"lr": [0.0, 1.0]}, seed=7)
    grid = tune.Tuner(
        obj,
        param_space={"lr": tune.grid_search([0.05, 0.9, 0.95, 0.99])},
        tune_config=tune.TuneConfig(scheduler=sched,
                                    max_concurrent_trials=4),
        run_config=ray_tpu.air.RunConfig(storage_path=str(tmp_path),
                                         name="pb2"),
    ).fit()
    assert not grid.errors
    assert sched.num_exploits >= 1
    # at least one explored config moved strictly inside the bounds
    # (evidence the GP/cold-start explore actually ran)
    lrs = {t.config["lr"] for t in grid.trials}
    assert any(lr not in (0.05, 0.9, 0.95, 0.99) for lr in lrs)


def test_hyperband_bohb_rung_barrier(ray_start_shared, tmp_path):
    """Synchronous HyperBand: trials pause at the rung budget, the rung
    closes when all report, top 1/eta resume from checkpoint, the rest
    stop (reference: hb_bohb.py HyperBandForBOHB)."""
    def obj(config):
        from ray_tpu.air import session
        from ray_tpu.air.checkpoint import Checkpoint

        start = 0
        for i in range(start, 9):
            session.report({"loss": config["q"] * (9 - i)},
                           checkpoint=Checkpoint.from_dict({"i": i}))

    sched = tune.HyperBandForBOHB(metric="loss", mode="min", max_t=9,
                                  reduction_factor=3)
    grid = tune.Tuner(
        obj,
        param_space={"q": tune.grid_search([1.0, 2.0, 4.0, 8.0, 16.0,
                                            32.0])},
        tune_config=tune.TuneConfig(scheduler=sched,
                                    max_concurrent_trials=3),
        run_config=ray_tpu.air.RunConfig(storage_path=str(tmp_path),
                                         name="hb"),
    ).fit()
    assert len(grid) == 6
    iters = {t.config["q"]: t.iteration for t in grid.trials}
    # the best configs run longest; the worst are cut at the first rung
    best_iters = max(iters[1.0], iters[2.0])
    worst_iters = min(iters[16.0], iters[32.0])
    assert best_iters > worst_iters, iters
    stopped = [t for t in grid.trials if t.status == "STOPPED"]
    assert stopped, "no trial was cut at a rung barrier"


def test_experiment_syncs_to_remote_and_restores(ray_start_shared,
                                                 tmp_path):
    """RunConfig.sync_to uploads the experiment tree to a remote scheme
    on every experiment checkpoint; Tuner.restore(<remote uri>) rebuilds
    from the synced copy after losing the local dir (reference:
    tune/syncer.py cloud sync)."""
    grid = tune.Tuner(
        _ckpt_objective,
        param_space={"x": tune.grid_search([1.0, 2.0])},
        run_config=ray_tpu.air.RunConfig(
            storage_path=str(tmp_path), name="sync",
            sync_to="kv://tune_sync/exp"),
    ).fit()
    assert not grid.errors
    # remote copy is complete enough to restore WITHOUT the local dir
    import shutil

    shutil.rmtree(str(tmp_path / "sync"))
    restored = tune.Tuner.restore("kv://tune_sync/exp", _ckpt_objective)
    grid2 = restored.fit()
    assert len(grid2) == 2
    # finished trials came back finished (nothing re-ran from scratch)
    assert all(t.status == "TERMINATED" for t in grid2.trials)
