"""Train stack tests: Checkpoint forms, DataParallelTrainer/JaxTrainer
end-to-end on real worker actor processes (2 CPU workers — the
BASELINE.json fashion-MNIST-MLP shape), failure surfacing, checkpoints.
"""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.air import Checkpoint
from ray_tpu.train import (DataParallelTrainer, FailureConfig, JaxConfig,
                           JaxTrainer, RunConfig, ScalingConfig)


def test_checkpoint_dict_dir_roundtrip(tmp_path):
    data = {"step": 3, "params": {"w": np.arange(6).reshape(2, 3)}}
    c = Checkpoint.from_dict(data)
    d = c.to_directory(str(tmp_path / "ck"))
    c2 = Checkpoint.from_directory(d)
    got = c2.to_dict()
    assert got["step"] == 3
    np.testing.assert_array_equal(got["params"]["w"],
                                  data["params"]["w"])


def test_checkpoint_object_ref_roundtrip(ray_start_shared):
    c = Checkpoint.from_dict({"x": np.ones(4)})
    ref = c.to_object_ref()
    c2 = Checkpoint.from_object_ref(ref)
    np.testing.assert_array_equal(c2.to_dict()["x"], np.ones(4))


def _mlp_loop(config):
    """2-worker data-parallel MLP: local grads + store allreduce."""
    import jax
    import jax.numpy as jnp

    from ray_tpu.air import session
    from ray_tpu.models import MLPConfig, mlp_init, mlp_loss
    from ray_tpu.train import jax_utils

    cfg = MLPConfig(in_dim=8, hidden=(16,), n_classes=3)
    params = mlp_init(jax.random.PRNGKey(0), cfg)  # same init on all ranks
    shard = session.get_dataset_shard("train")
    x = jnp.asarray(shard["x"])
    y = jnp.asarray(shard["y"])

    grad_fn = jax.jit(jax.value_and_grad(
        lambda p: mlp_loss(p, {"x": x, "y": y}, cfg)))
    lr = config["lr"]
    for step in range(config["steps"]):
        loss, grads = grad_fn(params)
        if session.get_world_size() > 1:
            grads = jax_utils.allreduce_gradients(grads)
        params = jax.tree.map(lambda p, g: p - lr * g, params, grads)
        ckpt = None
        if step == config["steps"] - 1:
            ckpt = {"params": params, "step": step}
        session.report({"loss": float(loss),
                        "rank": session.get_world_rank()},
                       checkpoint=ckpt)


def test_jax_trainer_two_workers_mlp(ray_start_shared):
    rng = np.random.RandomState(0)
    n = 64
    x = rng.randn(n, 8).astype(np.float32)
    w_true = rng.randn(8, 3)
    y = (x @ w_true).argmax(axis=1)

    trainer = JaxTrainer(
        _mlp_loop,
        train_loop_config={"lr": 0.3, "steps": 5},
        jax_config=JaxConfig(distributed="store"),
        scaling_config=ScalingConfig(num_workers=2),
        datasets={"train": {"x": x, "y": y}},
    )
    result = trainer.fit()
    assert result.error is None, result.error
    assert len(result.metrics_history) == 5
    first, last = (result.metrics_history[0]["loss"],
                   result.metrics_history[-1]["loss"])
    assert last < first
    assert result.checkpoint is not None
    ck = result.checkpoint.to_dict()
    assert ck["step"] == 4


def _shard_check_loop(config):
    from ray_tpu.air import session

    shard = session.get_dataset_shard("train")
    session.report({"n": len(shard["x"]),
                    "rank": session.get_world_rank()})


def test_dataset_dict_of_arrays_sharded(ray_start_shared):
    x = np.arange(10)
    trainer = DataParallelTrainer(
        _shard_check_loop,
        scaling_config=ScalingConfig(num_workers=2),
        datasets={"train": {"x": x, "y": x}},
    )
    result = trainer.fit()
    assert result.error is None, result.error
    assert result.metrics["n"] == 5  # 10 rows / 2 workers


def _failing_loop(config):
    from ray_tpu.air import session

    session.report({"ok": 1})
    if session.get_world_rank() == 0:
        raise ValueError("boom at rank 0")
    session.report({"ok": 2})


def test_worker_failure_surfaces_in_result(ray_start_shared):
    trainer = DataParallelTrainer(
        _failing_loop,
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(failure_config=FailureConfig(max_failures=0)),
    )
    result = trainer.fit()
    assert result.error is not None
    assert "boom at rank 0" in str(result.error)
    assert len(result.metrics_history) == 1  # one good round before crash


def _resume_loop(config):
    from ray_tpu.air import session

    ck = session.get_checkpoint()
    start = ck.to_dict()["step"] + 1 if ck is not None else 0
    session.report({"resumed_from": start})


def test_resume_from_checkpoint(ray_start_shared):
    trainer = DataParallelTrainer(
        _resume_loop,
        scaling_config=ScalingConfig(num_workers=1),
        resume_from_checkpoint=Checkpoint.from_dict({"step": 7}),
    )
    result = trainer.fit()
    assert result.error is None, result.error
    assert result.metrics["resumed_from"] == 8


def test_torch_trainer_ddp_gloo(ray_start_shared):
    """TorchTrainer: gloo process group across worker actors, DDP-wrapped
    model trains and gradients stay in sync (reference:
    train/torch/config.py:70 + test_torch_fsdp.py tier)."""
    from ray_tpu import train as train_mod
    from ray_tpu.air import session

    def loop(config):
        import numpy as np
        import torch
        import torch.distributed as dist
        from ray_tpu.train import prepare_model

        torch.manual_seed(0)
        model = prepare_model(torch.nn.Linear(4, 1))
        assert dist.is_initialized() and dist.get_world_size() == 2
        opt = torch.optim.SGD(model.parameters(), lr=0.1)
        x = torch.randn(32, 4, generator=torch.Generator().manual_seed(
            session.get_world_rank()))
        y = x.sum(dim=1, keepdim=True)
        for _ in range(5):
            opt.zero_grad()
            loss = torch.nn.functional.mse_loss(model(x), y)
            loss.backward()
            opt.step()
        # DDP invariant: replicas stay bit-identical after synced steps.
        flat = torch.cat([p.detach().reshape(-1)
                          for p in model.parameters()])
        gathered = [torch.zeros_like(flat)
                    for _ in range(dist.get_world_size())]
        dist.all_gather(gathered, flat)
        sync_ok = all(torch.equal(g, gathered[0]) for g in gathered)
        session.report({"loss": float(loss), "sync_ok": bool(sync_ok)})

    trainer = train_mod.TorchTrainer(
        loop, scaling_config=train_mod.ScalingConfig(num_workers=2))
    result = trainer.fit()
    assert result.error is None, result.error
    assert result.metrics["sync_ok"] is True
    assert result.metrics["loss"] < 1.0


def test_accumulated_train_step_matches_full_batch():
    """Gradient accumulation over 4 microbatches must match one
    full-batch SGD step exactly (linear model, SGD: gradients average
    identically)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from ray_tpu.train import accumulated_train_step

    def loss_fn(params, batch):
        pred = batch["x"] @ params["w"]
        return jnp.mean(jnp.square(pred - batch["y"]))

    # IMPORTANT: mean-of-microbatch-means == full-batch mean only when
    # microbatches are equal-sized (they are, by construction)
    rng = np.random.RandomState(0)
    batch = {"x": jnp.asarray(rng.randn(32, 4), jnp.float32),
             "y": jnp.asarray(rng.randn(32), jnp.float32)}
    params = {"w": jnp.asarray(rng.randn(4), jnp.float32)}
    tx = optax.sgd(0.1)
    opt = tx.init(params)

    # full-batch reference step
    loss_full, grads = jax.value_and_grad(loss_fn)(params, batch)
    upd, _ = tx.update(grads, opt, params)
    ref = optax.apply_updates(params, upd)

    step = jax.jit(accumulated_train_step(loss_fn, tx,
                                          num_microbatches=4))
    new_params, new_opt, loss = step(params, opt, batch)
    np.testing.assert_allclose(np.asarray(new_params["w"]),
                               np.asarray(ref["w"]), rtol=1e-6)
    assert abs(float(loss) - float(loss_full)) < 1e-6

    # divisibility is enforced
    import pytest as _pytest

    bad = {"x": batch["x"][:30], "y": batch["y"][:30]}
    with _pytest.raises(ValueError, match="not divisible"):
        jax.jit(accumulated_train_step(loss_fn, tx,
                                       num_microbatches=4))(params, opt,
                                                            bad)
