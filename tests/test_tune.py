"""Tune tests: variant generation, trial loop over real actors, ASHA
early stopping, Trainer-in-Tuner routing."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu import tune
from ray_tpu.tune.search import BasicVariantGenerator


def test_variant_generator_grid_and_samples():
    space = {"a": tune.grid_search([1, 2, 3]), "b": tune.uniform(0, 1),
             "c": "fixed"}
    gen = BasicVariantGenerator(space, num_samples=2, seed=0)
    variants = gen.variants()
    assert len(variants) == 6  # 3 grid x 2 samples
    assert {v["a"] for v in variants} == {1, 2, 3}
    assert all(0 <= v["b"] <= 1 and v["c"] == "fixed" for v in variants)


def _objective(config):
    from ray_tpu.air import session

    for i in range(3):
        session.report({"score": config["x"] * (i + 1)})


def test_tuner_grid_runs_all_trials(ray_start_shared):
    grid = tune.Tuner(
        _objective,
        param_space={"x": tune.grid_search([1, 2, 5])},
    ).fit()
    assert len(grid) == 3
    assert not grid.errors
    best = grid.get_best_result("score", mode="max")
    assert best.metrics["score"] == 15  # x=5, iter 3
    assert len(best.metrics_history) == 3


def _decaying_objective(config):
    from ray_tpu.air import session

    # trial quality is decided by config["q"]; loss shrinks with iters
    for i in range(20):
        session.report({"loss": config["q"] / (i + 1)})


def test_asha_stops_bad_trials_early(ray_start_shared):
    sched = tune.ASHAScheduler(metric="loss", mode="min", max_t=20,
                               grace_period=2, reduction_factor=2)
    grid = tune.Tuner(
        _decaying_objective,
        param_space={"q": tune.grid_search([1.0, 2.0, 4.0, 8.0])},
        tune_config=tune.TuneConfig(scheduler=sched,
                                    max_concurrent_trials=2),
    ).fit()
    assert len(grid) == 4
    iters = {t.config["q"]: t.iteration for t in grid.trials}
    # the best trial (q=1) must run longest; the worst must stop early
    assert iters[1.0] >= iters[8.0]
    assert any(t.status == "STOPPED" for t in grid.trials)


def _failing_objective(config):
    from ray_tpu.air import session

    session.report({"v": 1})
    if config["x"] == 2:
        raise RuntimeError("trial exploded")
    session.report({"v": 2})


def test_trial_error_isolated(ray_start_shared):
    grid = tune.Tuner(
        _failing_objective,
        param_space={"x": tune.grid_search([1, 2, 3])},
    ).fit()
    assert len(grid.errors) == 1
    ok = [t for t in grid.trials if t.error is None]
    assert len(ok) == 2
    assert all(t.last_result["v"] == 2 for t in ok)


def test_trainer_fit_routes_through_tune(ray_start_shared):
    """BaseTrainer.fit → single tune trial hosting nested train workers."""
    from ray_tpu.train import DataParallelTrainer, ScalingConfig

    def loop(config):
        from ray_tpu.air import session

        for i in range(2):
            session.report({"step": i, "rank": session.get_world_rank()},
                           checkpoint={"i": i} if i == 1 else None)

    trainer = DataParallelTrainer(
        loop, scaling_config=ScalingConfig(num_workers=2))
    result = trainer.fit()
    assert result.error is None, result.error
    assert len(result.metrics_history) == 2
    assert result.checkpoint is not None
    assert result.checkpoint.to_dict()["i"] == 1


def test_pbt_exploits_and_learns(ray_start_shared):
    """PBT: bad-lr trials must clone good-lr trials' checkpoints and end
    up with mutated configs (reference: tune/schedulers/pbt.py)."""
    from ray_tpu import tune
    from ray_tpu.air import session

    def trainable(config):
        import time as _t

        ckpt = session.get_checkpoint()
        score = ckpt.to_dict()["score"] if ckpt else 0.0
        for _ in range(15):
            score += config["lr"]  # higher lr -> better metric
            _t.sleep(0.25)  # keep the two trials' reports overlapping
            session.report(
                {"score": score},
                checkpoint=_dict_checkpoint({"score": score}))

    def _dict_checkpoint(d):
        from ray_tpu.air.checkpoint import Checkpoint

        return Checkpoint.from_dict(d)

    pbt = tune.PopulationBasedTraining(
        metric="score", mode="max", perturbation_interval=3,
        hyperparam_mutations={"lr": [0.1, 1.0]}, quantile_fraction=0.5,
        seed=0)
    grid = tune.Tuner(
        trainable,
        param_space={"lr": tune.grid_search([0.1, 1.0])},
        tune_config=tune.TuneConfig(scheduler=pbt,
                                    max_concurrent_trials=2),
    ).fit()
    assert not grid.errors
    assert pbt.num_exploits >= 1, "PBT never exploited"
    best = grid.get_best_result("score", mode="max")
    assert best.metrics["score"] >= 6.0  # a straight 1.0-lr run hits 12


def test_experiment_checkpoint_and_resume(ray_start_shared, tmp_path):
    """Kill an experiment midway; Tuner.restore completes only the
    unfinished trials from their checkpoints (reference:
    trial_runner.py save/restore)."""
    from ray_tpu import tune
    from ray_tpu.air import session
    from ray_tpu.air.checkpoint import Checkpoint
    from ray_tpu.air.config import RunConfig

    def trainable(config):
        ckpt = session.get_checkpoint()
        start = ckpt.to_dict()["i"] + 1 if ckpt else 0
        for i in range(start, 6):
            if config.get("crash") and i == 3 and start == 0:
                raise RuntimeError("boom")
            session.report({"i": i},
                           checkpoint=Checkpoint.from_dict({"i": i}))

    rc = RunConfig(name="exp1", storage_path=str(tmp_path))
    grid = tune.Tuner(
        trainable,
        param_space={"crash": tune.grid_search([False, True])},
        run_config=rc).fit()
    assert len(grid.errors) == 1  # the crashing trial failed
    # resume: the crashed trial restarts from its i=2 checkpoint and,
    # since start != 0 now, completes
    tuner2 = tune.Tuner.restore(str(tmp_path / "exp1"), trainable)
    grid2 = tuner2.fit()
    assert not grid2.errors
    for t in grid2.trials:
        assert t.metrics_history[-1]["i"] == 5
    # the finished trial was NOT re-run (its history kept exactly 6 rows)
    clean = [t for t in grid2.trials if not t.config["crash"]][0]
    assert len(clean.metrics_history) == 6
