"""Tune tests: variant generation, trial loop over real actors, ASHA
early stopping, Trainer-in-Tuner routing."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu import tune
from ray_tpu.tune.search import BasicVariantGenerator


def test_variant_generator_grid_and_samples():
    space = {"a": tune.grid_search([1, 2, 3]), "b": tune.uniform(0, 1),
             "c": "fixed"}
    gen = BasicVariantGenerator(space, num_samples=2, seed=0)
    variants = gen.variants()
    assert len(variants) == 6  # 3 grid x 2 samples
    assert {v["a"] for v in variants} == {1, 2, 3}
    assert all(0 <= v["b"] <= 1 and v["c"] == "fixed" for v in variants)


def _objective(config):
    from ray_tpu.air import session

    for i in range(3):
        session.report({"score": config["x"] * (i + 1)})


def test_tuner_grid_runs_all_trials(ray_start_shared):
    grid = tune.Tuner(
        _objective,
        param_space={"x": tune.grid_search([1, 2, 5])},
    ).fit()
    assert len(grid) == 3
    assert not grid.errors
    best = grid.get_best_result("score", mode="max")
    assert best.metrics["score"] == 15  # x=5, iter 3
    assert len(best.metrics_history) == 3


def _decaying_objective(config):
    from ray_tpu.air import session

    # trial quality is decided by config["q"]; loss shrinks with iters
    for i in range(20):
        session.report({"loss": config["q"] / (i + 1)})


def test_asha_stops_bad_trials_early(ray_start_shared):
    sched = tune.ASHAScheduler(metric="loss", mode="min", max_t=20,
                               grace_period=2, reduction_factor=2)
    grid = tune.Tuner(
        _decaying_objective,
        param_space={"q": tune.grid_search([1.0, 2.0, 4.0, 8.0])},
        tune_config=tune.TuneConfig(scheduler=sched,
                                    max_concurrent_trials=2),
    ).fit()
    assert len(grid) == 4
    iters = {t.config["q"]: t.iteration for t in grid.trials}
    # the best trial (q=1) must run longest; the worst must stop early
    assert iters[1.0] >= iters[8.0]
    assert any(t.status == "STOPPED" for t in grid.trials)


def _failing_objective(config):
    from ray_tpu.air import session

    session.report({"v": 1})
    if config["x"] == 2:
        raise RuntimeError("trial exploded")
    session.report({"v": 2})


def test_trial_error_isolated(ray_start_shared):
    grid = tune.Tuner(
        _failing_objective,
        param_space={"x": tune.grid_search([1, 2, 3])},
    ).fit()
    assert len(grid.errors) == 1
    ok = [t for t in grid.trials if t.error is None]
    assert len(ok) == 2
    assert all(t.last_result["v"] == 2 for t in ok)


def test_trainer_fit_routes_through_tune(ray_start_shared):
    """BaseTrainer.fit → single tune trial hosting nested train workers."""
    from ray_tpu.train import DataParallelTrainer, ScalingConfig

    def loop(config):
        from ray_tpu.air import session

        for i in range(2):
            session.report({"step": i, "rank": session.get_world_rank()},
                           checkpoint={"i": i} if i == 1 else None)

    trainer = DataParallelTrainer(
        loop, scaling_config=ScalingConfig(num_workers=2))
    result = trainer.fit()
    assert result.error is None, result.error
    assert len(result.metrics_history) == 2
    assert result.checkpoint is not None
    assert result.checkpoint.to_dict()["i"] == 1


def test_pbt_exploits_and_learns(ray_start_shared):
    """PBT: bad-lr trials must clone good-lr trials' checkpoints and end
    up with mutated configs (reference: tune/schedulers/pbt.py)."""
    from ray_tpu import tune
    from ray_tpu.air import session

    def trainable(config):
        import time as _t

        ckpt = session.get_checkpoint()
        score = ckpt.to_dict()["score"] if ckpt else 0.0
        for _ in range(15):
            score += config["lr"]  # higher lr -> better metric
            _t.sleep(0.25)  # keep the two trials' reports overlapping
            session.report(
                {"score": score},
                checkpoint=_dict_checkpoint({"score": score}))

    def _dict_checkpoint(d):
        from ray_tpu.air.checkpoint import Checkpoint

        return Checkpoint.from_dict(d)

    pbt = tune.PopulationBasedTraining(
        metric="score", mode="max", perturbation_interval=3,
        hyperparam_mutations={"lr": [0.1, 1.0]}, quantile_fraction=0.5,
        seed=0)
    grid = tune.Tuner(
        trainable,
        param_space={"lr": tune.grid_search([0.1, 1.0])},
        tune_config=tune.TuneConfig(scheduler=pbt,
                                    max_concurrent_trials=2),
    ).fit()
    assert not grid.errors
    assert pbt.num_exploits >= 1, "PBT never exploited"
    best = grid.get_best_result("score", mode="max")
    assert best.metrics["score"] >= 6.0  # a straight 1.0-lr run hits 12


def test_experiment_checkpoint_and_resume(ray_start_shared, tmp_path):
    """Kill an experiment midway; Tuner.restore completes only the
    unfinished trials from their checkpoints (reference:
    trial_runner.py save/restore)."""
    from ray_tpu import tune
    from ray_tpu.air import session
    from ray_tpu.air.checkpoint import Checkpoint
    from ray_tpu.air.config import RunConfig

    def trainable(config):
        ckpt = session.get_checkpoint()
        start = ckpt.to_dict()["i"] + 1 if ckpt else 0
        for i in range(start, 6):
            if config.get("crash") and i == 3 and start == 0:
                raise RuntimeError("boom")
            session.report({"i": i},
                           checkpoint=Checkpoint.from_dict({"i": i}))

    rc = RunConfig(name="exp1", storage_path=str(tmp_path))
    grid = tune.Tuner(
        trainable,
        param_space={"crash": tune.grid_search([False, True])},
        run_config=rc).fit()
    assert len(grid.errors) == 1  # the crashing trial failed
    # resume: the crashed trial restarts from its i=2 checkpoint and,
    # since start != 0 now, completes
    tuner2 = tune.Tuner.restore(str(tmp_path / "exp1"), trainable)
    grid2 = tuner2.fit()
    assert not grid2.errors
    for t in grid2.trials:
        assert t.metrics_history[-1]["i"] == 5
    # the finished trial was NOT re-run (its history kept exactly 6 rows)
    clean = [t for t in grid2.trials if not t.config["crash"]][0]
    assert len(clean.metrics_history) == 6


def test_trial_fault_tolerance_retries_from_checkpoint(ray_start_shared):
    """A trial whose TRAINABLE raises mid-run is restarted from its last
    checkpoint when FailureConfig.max_failures allows, and the
    experiment completes with no error (reference:
    trial_runner.py:236 _process_trial_failure)."""
    from ray_tpu.air import session
    from ray_tpu.air.checkpoint import Checkpoint
    from ray_tpu.air.config import FailureConfig, RunConfig

    def trainable(config):
        ckpt = session.get_checkpoint()
        start = ckpt.to_dict()["i"] + 1 if ckpt else 0
        for i in range(start, 6):
            if i == 3 and start == 0:
                raise RuntimeError("mid-run crash")
            session.report({"i": i},
                           checkpoint=Checkpoint.from_dict({"i": i}))

    grid = tune.Tuner(
        trainable,
        param_space={"x": tune.grid_search([1, 2])},
        run_config=RunConfig(failure_config=FailureConfig(max_failures=2)),
    ).fit()
    assert not grid.errors
    for t in grid.trials:
        assert t.metrics_history[-1]["i"] == 5
        assert t.num_failures == 1  # exactly one restart consumed


def test_trial_fault_tolerance_survives_actor_death(ray_start_shared):
    """A trial whose ACTOR PROCESS dies (os._exit — no python exception
    reaches the runner) is also restarted from its checkpoint."""
    import os as _os

    from ray_tpu.air import session
    from ray_tpu.air.checkpoint import Checkpoint
    from ray_tpu.air.config import FailureConfig, RunConfig

    def trainable(config):
        ckpt = session.get_checkpoint()
        start = ckpt.to_dict()["i"] + 1 if ckpt else 0
        for i in range(start, 5):
            session.report({"i": i},
                           checkpoint=Checkpoint.from_dict({"i": i}))
            if i == 2 and start == 0:
                _os._exit(1)  # hard kill: actor dies mid-run

    grid = tune.Tuner(
        trainable,
        param_space={"x": tune.grid_search([7])},
        run_config=RunConfig(
            failure_config=FailureConfig(max_failures=-1)),
    ).fit()
    assert not grid.errors
    (t,) = grid.trials
    assert t.metrics_history[-1]["i"] == 4
    assert t.num_failures >= 1


def test_failure_config_exhausted_marks_error(ray_start_shared):
    """When restarts are exhausted the trial surfaces its error (and
    max_failures=0 keeps the old fail-fast behavior)."""
    from ray_tpu.air.config import FailureConfig, RunConfig

    def always_crash(config):
        raise RuntimeError("permanent")

    grid = tune.Tuner(
        always_crash,
        param_space={"x": tune.grid_search([1])},
        run_config=RunConfig(failure_config=FailureConfig(max_failures=2)),
    ).fit()
    assert len(grid.errors) == 1
    assert grid.trials[0].num_failures == 2  # both restarts consumed


def test_tpe_beats_random_on_fixture():
    """On a deterministic quadratic fixture, TPE's best-found value
    after N trials beats random search's (same N, same seed family)."""
    from ray_tpu.tune.search import (BasicVariantGenerator, TPESearcher,
                                     uniform)

    def objective(cfg):
        return (cfg["x"] - 2.0) ** 2 + (cfg["y"] + 1.0) ** 2

    n = 40
    space = {"x": uniform(-10, 10), "y": uniform(-10, 10)}

    tpe = TPESearcher(n_initial=8)
    tpe.setup(space, "loss", "min", seed=1)
    tpe_best = float("inf")
    for i in range(n):
        cfg = tpe.suggest(f"t{i}")
        loss = objective(cfg)
        tpe_best = min(tpe_best, loss)
        tpe.on_trial_complete(f"t{i}", {"loss": loss})

    rnd_best = min(
        objective(c)
        for c in BasicVariantGenerator(space, num_samples=n,
                                       seed=1).variants())
    assert tpe_best < rnd_best


def test_tuner_with_tpe_search_alg(ray_start_shared):
    """End-to-end: Tuner proposes trials via TPESearcher, one at a time,
    and converges toward the optimum."""
    from ray_tpu.air import session

    def trainable(config):
        session.report({"loss": (config["x"] - 3.0) ** 2})

    grid = tune.Tuner(
        trainable,
        param_space={"x": tune.uniform(-10, 10)},
        tune_config=tune.TuneConfig(
            metric="loss", mode="min", num_samples=20,
            search_alg=tune.TPESearcher(n_initial=6), seed=0),
    ).fit()
    assert len(grid.trials) == 20
    assert all(t.last_result is not None for t in grid.trials)
    best = grid.get_best_result("loss", "min")
    # wiring check only (concurrent suggestion lag makes the exact
    # optimum seed-dependent); model quality is pinned deterministically
    # by test_tpe_beats_random_on_fixture
    assert best.metrics["loss"] < 10.0


def test_searcher_exhaustion_ends_experiment(ray_start_shared):
    """A searcher returning None before num_samples must end the run,
    not spin the event loop forever."""
    from ray_tpu.air import session
    from ray_tpu.tune.search import Searcher

    class TwoShot(Searcher):
        def __init__(self):
            self.n = 0

        def suggest(self, trial_id):
            if self.n >= 2:
                return None
            self.n += 1
            return {"x": self.n}

        def on_trial_complete(self, *a, **kw):
            pass

    def trainable(config):
        session.report({"loss": config["x"]})

    grid = tune.Tuner(
        trainable, param_space={},
        tune_config=tune.TuneConfig(metric="loss", mode="min",
                                    num_samples=50,
                                    search_alg=TwoShot()),
    ).fit()
    assert len(grid.trials) == 2  # returned promptly with what it got


def test_errored_trials_count_as_bad_for_tpe():
    """A config that reports a great metric then crashes must land in
    TPE's bad set, not poison the good density."""
    from ray_tpu.tune.search import TPESearcher, uniform

    tpe = TPESearcher(n_initial=4)
    tpe.setup({"x": uniform(0, 10)}, "loss", "min", seed=0)
    # crashy region x<5 reports loss=0.0 then dies; honest region
    # x>=5 reports its true loss (x-7)^2
    for i in range(30):
        cfg = tpe.suggest(f"t{i}")
        if cfg["x"] < 5:
            tpe.on_trial_complete(f"t{i}", {"loss": 0.0}, error=True)
        else:
            tpe.on_trial_complete(f"t{i}", {"loss": (cfg["x"] - 7) ** 2})
    late = [c["x"] for c, _ in tpe._obs[-10:]]
    assert sum(1 for x in late if x >= 5) >= 7, late


def test_tpe_setup_resets_state():
    from ray_tpu.tune.search import TPESearcher, uniform

    tpe = TPESearcher(n_initial=2)
    tpe.setup({"x": uniform(0, 1)}, "loss", "min", seed=0)
    tpe.suggest("a")
    tpe.on_trial_complete("a", {"loss": 0.5})
    tpe.setup({"x": uniform(0, 1)}, "acc", "max", seed=0)
    assert tpe._obs == [] and tpe._live == {}


def test_launch_failure_backoff_does_not_starve_pump(ray_start_shared):
    """A persistently failing trial must not monopolize the run loop:
    failures wait on a backoff queue while healthy trials keep running
    to completion."""
    from ray_tpu.air import session
    from ray_tpu.air.config import FailureConfig, RunConfig

    def trainable(config):
        if config["x"] == "bad":
            raise RuntimeError("always fails")
        for i in range(3):
            session.report({"i": i})

    grid = tune.Tuner(
        trainable,
        param_space={"x": tune.grid_search(["bad", "ok"])},
        tune_config=tune.TuneConfig(max_concurrent_trials=2),
        run_config=RunConfig(failure_config=FailureConfig(max_failures=3)),
    ).fit()
    ok = [t for t in grid.trials if t.config["x"] == "ok"][0]
    bad = [t for t in grid.trials if t.config["x"] == "bad"][0]
    assert ok.error is None and ok.metrics_history[-1]["i"] == 2
    assert bad.error is not None and bad.num_failures == 3
