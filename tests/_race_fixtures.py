"""Planted shared-state races for the ``shared-state-race`` pass.

Each ``# PLANTED: <kind>`` marker names the violation kind the static
pass must report on exactly that line; everything else (the locked,
GIL-atomic, snapshot, and caller-locked sites) is a negative the pass
must stay silent on.  tests/test_graftcheck_races.py lints this file's
source under a ``ray_tpu/serve/`` rel path for the static half, and
drives :meth:`RacyCounter.bump` with 8 real threads for the dynamic
half — the planted ``+=`` demonstrably loses updates under thread
preemption, proving the rule polices real bugs, not style.
"""

import threading


class RacyCounter:
    """Writer/reader/locked-writer threads over one shared state bag."""

    def __init__(self):
        self.n = 0
        self.total = 0
        self.pending = True
        self.flag = False
        self.safe = 0
        self.items = []
        self.log = []
        self.index = {}
        self.safe_items = {}
        self.reps = {"primary": _Rep()}
        self._lock = threading.Lock()
        self._threads = []

    def start(self, iters):
        self._threads = [
            threading.Thread(target=self._writer, args=(iters,)),
            threading.Thread(target=self._reader, args=(iters,)),
            threading.Thread(target=self._locked_writer, args=(iters,)),
        ]
        for t in self._threads:
            t.start()

    def join(self):
        for t in self._threads:
            t.join()

    def bump(self, iters):
        """The dynamic-stress entry point: 8 threads run this loop
        concurrently and the unlocked RMW loses updates.  The
        read-modify-write is stretched across a method call so the
        interpreter has a switch point between the load and the store
        (CPython checks the eval breaker only on backward jumps and
        calls — a bare ``+=`` inside one loop body never yields)."""
        for _ in range(iters):
            v = self.n
            v = self._inc(v)
            self.n = v  # PLANTED: rmw

    @staticmethod
    def _inc(v):
        return v + 1

    def _writer(self, iters):
        now = 0.0
        for i in range(iters):
            self.n += 1  # PLANTED: aug
            self.total = self.total + 1  # PLANTED: rmw
            if self.pending:
                self.pending = False  # PLANTED: check-then-act
            key = i % 7
            if key not in self.index:
                self.index[key] = i  # PLANTED: check-then-act
            rep = self.reps.get("primary")
            rep.fault_ts = now  # PLANTED: multi-init
            rep.fault_kind = "freeze"
            rep.detect_ms = None
            # negatives: single GIL-atomic ops need no lock
            self.items.append(i)
            self.log.append(i)
            self.flag = True

    def _reader(self, iters):
        seen = 0
        for _ in range(iters):
            seen += self.n + self.total + len(self.reps)
            for item in self.items:  # PLANTED: iterate
                seen += item
            for item in list(self.log):  # negative: snapshot copy
                seen += item
            if self.pending and self.flag:
                seen += len(self.index)
        return seen

    def _locked_writer(self, iters):
        for _ in range(iters):
            with self._lock:
                self.safe += 1  # negative: lock held
                self._drain()

    def _drain(self):
        # negative: every call site holds self._lock (caller-locked)
        self.safe_items["k"] = self.safe_items.get("k", 0) + 1


class _Rep:
    """The aliased record _writer re-initializes field by field."""

    def __init__(self):
        self.fault_ts = None
        self.fault_kind = None
        self.detect_ms = None


class HealthMonitor:
    """Name-collides with serve/health.py's monitor on purpose: the
    THREAD_ROOTS seeding path (not Thread-target auto-detection) must
    give heartbeat/maybe_probe/fleet_block their contexts."""

    def __init__(self):
        self.beats = {}
        self.sweeps = 0

    def heartbeat(self, replica):
        self.beats[replica] = self.beats.get(replica, 0) + 1  # PLANTED: rmw

    def maybe_probe(self):
        self.sweeps += 1  # PLANTED: aug
        return dict(self.beats)

    def fleet_block(self):
        return {"beats": dict(self.beats), "sweeps": self.sweeps}
