"""Actor tests (reference analog: python/ray/tests/test_actor.py,
test_actor_failures.py)."""

import time

import pytest

import ray_tpu
from ray_tpu import exceptions


@ray_tpu.remote
class Counter:
    def __init__(self, start=0):
        self.n = start

    def incr(self, by=1):
        self.n += by
        return self.n

    def read(self):
        return self.n


def test_actor_basic(ray_start_regular):
    c = Counter.remote()
    assert ray_tpu.get(c.incr.remote()) == 1
    assert ray_tpu.get(c.incr.remote(5)) == 6
    assert ray_tpu.get(c.read.remote()) == 6


def test_actor_ctor_args(ray_start_regular):
    c = Counter.remote(100)
    assert ray_tpu.get(c.read.remote()) == 100


def test_actor_method_ordering(ray_start_regular):
    c = Counter.remote()
    refs = [c.incr.remote() for _ in range(20)]
    # In-order execution: results are 1..20.
    assert ray_tpu.get(refs) == list(range(1, 21))


def test_two_actors_independent_state(ray_start_regular):
    a, b = Counter.remote(), Counter.remote()
    ray_tpu.get(a.incr.remote())
    assert ray_tpu.get(b.read.remote()) == 0


def test_actor_error(ray_start_regular):
    @ray_tpu.remote
    class Bad:
        def boom(self):
            raise RuntimeError("actor method failed")

        def ok(self):
            return "fine"

    a = Bad.remote()
    with pytest.raises(exceptions.RayTaskError, match="actor method failed"):
        ray_tpu.get(a.boom.remote())
    # Actor survives a method error.
    assert ray_tpu.get(a.ok.remote()) == "fine"


def test_actor_ctor_failure(ray_start_regular):
    @ray_tpu.remote
    class FailsInit:
        def __init__(self):
            raise ValueError("ctor boom")

        def ping(self):
            return 1

    # creation is async (reference semantics): the handle returns
    # immediately and the ctor error surfaces on the first method call
    h = FailsInit.remote()
    with pytest.raises(exceptions.RayActorError):
        ray_tpu.get(h.ping.remote(), timeout=60)


def test_named_actor(ray_start_regular):
    Counter.options(name="global_counter").remote(7)
    h = ray_tpu.get_actor("global_counter")
    assert ray_tpu.get(h.read.remote()) == 7


def test_named_actor_duplicate(ray_start_regular):
    Counter.options(name="dup").remote()
    with pytest.raises(Exception, match="already taken"):
        Counter.options(name="dup").remote()


def test_kill_actor(ray_start_regular):
    c = Counter.remote()
    ray_tpu.get(c.incr.remote())
    ray_tpu.kill(c)
    time.sleep(0.3)
    with pytest.raises(exceptions.RayActorError):
        ray_tpu.get(c.incr.remote())


def test_actor_handle_passed_to_task(ray_start_regular):
    @ray_tpu.remote
    def bump(counter):
        return ray_tpu.get(counter.incr.remote(10))

    c = Counter.remote()
    assert ray_tpu.get(bump.remote(c)) == 10
    assert ray_tpu.get(c.read.remote()) == 10


def test_actor_restart(ray_start_regular):
    @ray_tpu.remote(max_restarts=1)
    class Fragile:
        def pid(self):
            import os

            return os.getpid()

        def die(self):
            import os

            os._exit(1)

    a = Fragile.remote()
    pid1 = ray_tpu.get(a.pid.remote())
    a.die.remote()
    time.sleep(1.0)
    deadline = time.monotonic() + 30
    pid2 = None
    while time.monotonic() < deadline:
        try:
            pid2 = ray_tpu.get(a.pid.remote(), timeout=10)
            break
        except exceptions.RayTpuError:
            time.sleep(0.5)
    assert pid2 is not None and pid2 != pid1


def test_actor_no_restart_dies(ray_start_regular):
    @ray_tpu.remote
    class OneShot:
        def die(self):
            import os

            os._exit(1)

        def ping(self):
            return "pong"

    a = OneShot.remote()
    a.die.remote()
    time.sleep(1.0)
    with pytest.raises(exceptions.RayActorError):
        ray_tpu.get(a.ping.remote(), timeout=15)


def test_actor_resources_block_until_available(ray_start_regular):
    """Two 3-CPU actors cannot coexist on a 4-CPU node: second creation
    must fail (GCS finds no feasible placement while first holds)."""

    @ray_tpu.remote(num_cpus=3)
    class Big:
        def ping(self):
            return 1

    a = Big.remote()
    assert ray_tpu.get(a.ping.remote()) == 1
    # availability is heartbeat-propagated (node -> GCS): poll for it
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline:
        if ray_tpu.available_resources().get("CPU", 0) <= 1.0:
            break
        time.sleep(0.1)
    assert ray_tpu.available_resources()["CPU"] <= 1.0


def test_max_concurrency_actor(ray_start_regular):
    @ray_tpu.remote(max_concurrency=4)
    class Slow:
        def nap(self):
            time.sleep(0.8)
            return 1

    a = Slow.remote()
    start = time.monotonic()
    assert sum(ray_tpu.get([a.nap.remote() for _ in range(4)])) == 4
    assert time.monotonic() - start < 3.0


def test_detached_named_actor_lookup(ray_start_regular):
    Counter.options(name="det", lifetime="detached").remote()
    h = ray_tpu.get_actor("det")
    assert ray_tpu.get(h.read.remote()) == 0


def test_get_tpu_ids_visibility_grant(ray_start_shared):
    """get_runtime_context().get_tpu_ids() reflects the worker's
    TPU_VISIBLE_CHIPS grant (ray.get_gpu_ids analog).  Driver-side it
    is empty; inside a worker it matches the chip env."""
    assert ray_tpu.get_runtime_context().get_tpu_ids() == []

    @ray_tpu.remote
    def whoami():
        import os

        ctx = ray_tpu.get_runtime_context()
        return ctx.get_tpu_ids(), os.environ.get("TPU_VISIBLE_CHIPS", "")

    ids, env = ray_tpu.get(whoami.remote())
    if env:
        assert ids == [int(c) for c in env.split(",")]
    else:
        assert ids == []
