"""Request tracebus: causal span trees, critical-path attribution,
and the merged fleet timeline.

The acceptance test is the headline: a 2-replica ``run_traffic_fleet``
run exports ONE merged chrome trace in which a named request's spans
stitch router → replica engine → device program via parent ids on a
single monotonic clock, and the ``critical-path --percentile 99``
decomposition sums to within 5% of that request's measured e2e.
Unit tests pin the decomposition invariant (components sum to e2e
exactly, garbage clocks clamp to zero), the span-tree parenting, the
flightrec ``--request`` follow filter, the perfledger metric
direction for the new ITL series, the graftcheck scope extensions
over tools/tracebus.py, and the <5% hot-path overhead guard.
"""

import asyncio
import json
import os
import time

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from ray_tpu.serve import telemetry as T  # noqa: E402
from ray_tpu.serve.llm import build_llm_deployment  # noqa: E402
from ray_tpu.tools import tracebus as TB  # noqa: E402
from ray_tpu.util import tracing  # noqa: E402

_OVR = {"dtype": jnp.float32, "use_flash": False, "remat": False}


def _prompts(n, lo=8, hi=14, seed=0):
    rng = np.random.RandomState(seed)
    return [rng.randint(2, 50, size=rng.randint(lo, hi))
            .astype(np.int32) for _ in range(n)]


def _synthetic_rec():
    """One deterministically-clocked request record driven through
    every telemetry hop (requeue, kv reserve, spec round, tokens)."""
    tel = T.EngineTelemetry("dep0")
    ctx = T.TraceContext(origin="router")
    ctx.span("router.route", 0.5, 1.0, replica="dep0", policy="wfq",
             tenant="a", matched_blocks=0, router_req=7)
    rec = tel.record_enqueue(12, now=1.0, tenant="a", ctx=ctx,
                             engine_now=1.2)
    tel.record_requeue(rec, need=3, reason="pool_exhausted", now=1.3)
    tel.record_kv_reserve(rec, 1.35, 1.4, blocks=4, hit_blocks=1)
    tel.record_admit(rec, bucket=16, slot=0, now=1.5)
    tel.record_first_token(rec, now=2.0)
    tel.record_token(rec, now=2.1)
    tel.record_token(rec, n=2, now=2.3)
    tel.record_spec(rec, proposed=4, accepted=2, dur_s=0.2)
    tel.record_finish(rec, n_tokens=5, now=2.5)
    return tel, rec


# ---------------------------------------------------------------------------
# critical-path decomposition
# ---------------------------------------------------------------------------

def test_critical_path_components_sum_to_e2e_exactly():
    _tel, rec = _synthetic_rec()
    cp = T.critical_path(rec)
    assert cp["e2e_ms"] == pytest.approx(1500.0)
    assert cp["router_wait_ms"] == pytest.approx(200.0)
    assert cp["requeue_ms"] == pytest.approx(200.0)
    assert cp["prefill_ms"] == pytest.approx(500.0)
    assert cp["spec_rollback_ms"] == pytest.approx(80.0)
    comp_sum = sum(cp[k] for k in T.CRITICAL_PATH_COMPONENTS)
    assert comp_sum == pytest.approx(cp["e2e_ms"], abs=1e-9)


def test_critical_path_clamps_garbage_clocks():
    """Deterministic tests inject tiny fake clocks while engine_enqueue
    may come from the real perf_counter; the decomposition must clamp
    to [enqueue, finish] and never go negative."""
    tel = T.EngineTelemetry("d")
    rec = tel.record_enqueue(8, now=5.0)
    rec["engine_enqueue"] = 1e6          # wildly out of window
    tel.record_admit(rec, bucket=16, slot=0, now=5.5)
    tel.record_first_token(rec, now=6.0)
    tel.record_finish(rec, n_tokens=2, now=6.5)
    cp = T.critical_path(rec)
    assert all(v >= 0.0 for v in cp.values())
    comp_sum = sum(cp[k] for k in T.CRITICAL_PATH_COMPONENTS)
    assert comp_sum == pytest.approx(cp["e2e_ms"], abs=1e-9)
    # unfinished / rejected records have no decomposition
    assert T.critical_path(tel.record_enqueue(8, now=1.0)) is None


def _synthetic_chunked_rec():
    """The same deterministic clocks as ``_synthetic_rec`` but the
    prefill leg lands as two chunk dispatch windows (1.5-1.6 and
    1.8-1.9) with a parked decode-wave gap between them."""
    tel = T.EngineTelemetry("dep0")
    rec = tel.record_enqueue(96, now=1.0, tenant="a",
                             ctx=T.TraceContext(origin="router"),
                             engine_now=1.2)
    tel.record_requeue(rec, need=3, reason="pool_exhausted", now=1.3)
    tel.record_admit(rec, bucket=32, slot=0, now=1.5)
    tel.record_prefill_chunk(rec, 1.5, 1.6, tokens=32, bucket=32)
    tel.record_prefill_chunk(rec, 1.8, 1.9, tokens=32, bucket=32,
                             last=True)
    tel.record_first_token(rec, now=2.0)
    tel.record_token(rec, n=2, now=2.3)
    tel.record_finish(rec, n_tokens=3, now=2.5)
    return tel, rec


def test_critical_path_chunked_prefill_exact_sum():
    """Chunked prefill splits the admit -> first-token window into
    prefill (the summed chunk windows) and prefill_wait (the parked
    remainder where decode waves ran) — and the decomposition still
    sums to e2e exactly."""
    _tel, rec = _synthetic_chunked_rec()
    cp = T.critical_path(rec)
    assert cp["e2e_ms"] == pytest.approx(1500.0)
    assert cp["prefill_ms"] == pytest.approx(200.0)
    assert cp["prefill_wait_ms"] == pytest.approx(300.0)
    comp_sum = sum(cp[k] for k in T.CRITICAL_PATH_COMPONENTS)
    assert comp_sum == pytest.approx(cp["e2e_ms"], abs=1e-9)


def test_critical_path_chunk_windows_clamp_to_first_token():
    """A chunk window leaking past the first-token stamp (scheduler
    jitter) is clamped into [admit, first]: prefill never exceeds the
    window and the exact-sum invariant holds."""
    tel = T.EngineTelemetry("d")
    rec = tel.record_enqueue(64, now=1.0, engine_now=1.0)
    tel.record_admit(rec, bucket=32, slot=0, now=1.5)
    tel.record_prefill_chunk(rec, 1.4, 1.7, tokens=32, bucket=32)
    tel.record_prefill_chunk(rec, 1.9, 2.2, tokens=32, bucket=32,
                             last=True)
    tel.record_first_token(rec, now=2.0)
    tel.record_finish(rec, n_tokens=2, now=2.5)
    cp = T.critical_path(rec)
    # (1.5..1.7) + (1.9..2.0) after clamping -> 300 ms of 500
    assert cp["prefill_ms"] == pytest.approx(300.0)
    assert cp["prefill_wait_ms"] == pytest.approx(200.0)
    comp_sum = sum(cp[k] for k in T.CRITICAL_PATH_COMPONENTS)
    assert comp_sum == pytest.approx(cp["e2e_ms"], abs=1e-9)


def _synthetic_handoff_rec():
    """A disaggregated request on deterministic clocks: the decode-side
    record seeded from the prefill replica's handoff package meta
    (enqueue 1.0, engine 1.2, admit 1.5, first token 2.0), with the
    export→install window 2.0-2.2 carved out of the decode leg."""
    ctx = T.TraceContext(origin="router")
    ctx.span("router.route", 0.5, 1.0, replica="fleet/p0",
             policy="disagg_prefill", tenant="a", router_req=7)
    tel = T.EngineTelemetry("fleet/d0", role="decode")
    rec = tel.record_enqueue_handoff(
        {"prompt_len": 12, "enqueue": 1.0, "engine_enqueue": 1.2,
         "admit": 1.5, "first_token": 2.0, "bucket": 16,
         "tenant": "a", "ctx": ctx}, now=2.05)
    tel.record_kv_handoff(rec, 2.0, 2.2, blocks=2, nbytes=4096,
                          path="staged")
    tel.record_admit_handoff(rec, slot=0, now=2.2)
    tel.record_token(rec, now=2.4)
    tel.record_finish(rec, n_tokens=3, now=2.5)
    return tel, rec


def test_critical_path_handoff_exact_sum():
    """handoff_ms is the export→install window carved from the decode
    leg — the other components read exactly like the monolithic
    engine's, and the decomposition still sums to e2e exactly."""
    _tel, rec = _synthetic_handoff_rec()
    cp = T.critical_path(rec)
    assert cp["e2e_ms"] == pytest.approx(1500.0)
    assert cp["router_wait_ms"] == pytest.approx(200.0)
    assert cp["queue_wait_ms"] == pytest.approx(300.0)
    assert cp["prefill_ms"] == pytest.approx(500.0)
    assert cp["handoff_ms"] == pytest.approx(200.0)
    assert cp["inter_token_ms"] == pytest.approx(300.0)
    comp_sum = sum(cp[k] for k in T.CRITICAL_PATH_COMPONENTS)
    assert comp_sum == pytest.approx(cp["e2e_ms"], abs=1e-9)


def test_critical_path_handoff_clamps_to_decode_leg():
    """A handoff window leaking outside [first_token, finish] (clock
    skew across two replicas' journals) clamps into the decode leg and
    the exact-sum invariant holds."""
    tel = T.EngineTelemetry("d", role="decode")
    rec = tel.record_enqueue_handoff(
        {"prompt_len": 12, "enqueue": 1.0, "engine_enqueue": 1.2,
         "admit": 1.5, "first_token": 2.0}, now=2.0)
    tel.record_kv_handoff(rec, 1.8, 3.0, blocks=1, nbytes=64,
                          path="fast")
    tel.record_admit_handoff(rec, slot=0, now=2.1)
    tel.record_finish(rec, n_tokens=2, now=2.5)
    cp = T.critical_path(rec)
    # (1.8..3.0) clamps to the 2.0..2.5 decode window -> all 500 ms
    assert cp["handoff_ms"] == pytest.approx(500.0)
    assert cp["inter_token_ms"] == pytest.approx(0.0)
    assert all(v >= 0.0 for v in cp.values())
    comp_sum = sum(cp[k] for k in T.CRITICAL_PATH_COMPONENTS)
    assert comp_sum == pytest.approx(cp["e2e_ms"], abs=1e-9)


def test_handoff_span_chain_parent_ids():
    """The merged timeline shows the full disaggregated chain —
    router.route → engine.prefill → kv.handoff → engine.decode — every
    leg a child of the request root, in causal start order, with the
    handoff span carrying blocks/bytes/path attrs."""
    _tel, rec = _synthetic_handoff_rec()
    snap = T.request_snapshot(rec, deployment="fleet/d0")
    spans = TB.build_request_spans(snap)
    by_id = {s["span_id"]: s for s in spans}
    names = [s["name"] for s in spans]
    for name in ("router.route", "engine.queue", "engine.prefill",
                 "kv.handoff", "engine.decode"):
        assert name in names, name
    root = next(s for s in spans if s["parent_id"] is None)
    chain = [next(s for s in spans if s["name"] == nm)
             for nm in ("router.route", "engine.prefill",
                        "kv.handoff", "engine.decode")]
    for s in chain:
        assert by_id[s["parent_id"]] is root, s["name"]
    starts = [s["start"] for s in chain]
    assert starts == sorted(starts)
    kh = chain[2]
    assert (kh["start"], kh["end"]) == (2.0, 2.2)
    assert kh["attrs"] == {"blocks": 2, "bytes": 4096,
                           "path": "staged"}


def test_tracebus_opt_out(monkeypatch):
    monkeypatch.setenv("RAYTPU_TRACEBUS", "0")
    tel = T.EngineTelemetry("d")
    rec = tel.record_enqueue(8, now=1.0)
    assert rec["ctx"] is None and rec["token_ts"] is None
    tel.record_token(rec, now=2.0)       # must be a no-op, not a crash
    assert rec["token_ts"] is None


# ---------------------------------------------------------------------------
# span trees
# ---------------------------------------------------------------------------

def test_span_tree_parent_ids_and_device_stitch():
    _tel, rec = _synthetic_rec()
    snap = T.request_snapshot(rec, deployment="dep0")
    snap["replica"] = "dep0"
    programs = {"invokes": {"serve.prefill_b16": [[1.95, 0.3]]},
                "compiles": {}}
    spans = TB.attach_device_spans(
        TB.build_request_spans(snap), snap, programs)
    by_id = {s["span_id"]: s for s in spans}
    names = {s["name"] for s in spans}
    assert {"router.route", "engine.queue", "engine.requeue",
            "kv.reserve", "engine.prefill",
            "engine.decode"} <= names
    root = next(s for s in spans if s["parent_id"] is None)
    # router span recorded live on the TraceContext parents to root
    route = next(s for s in spans if s["name"] == "router.route")
    assert route["parent_id"] == root["span_id"]
    # requeue + kv reserve nest under the queue span
    queue = next(s for s in spans if s["name"] == "engine.queue")
    for child in ("engine.requeue", "kv.reserve"):
        s = next(x for x in spans if x["name"] == child)
        assert s["parent_id"] == queue["span_id"]
    # device program invoke parents under engine.prefill: the full
    # router -> engine -> device chain
    dev = next(s for s in spans if s["name"].startswith("device "))
    prefill = by_id[dev["parent_id"]]
    assert prefill["name"] == "engine.prefill"
    assert by_id[prefill["parent_id"]] is root
    # every span is a window on one clock inside the request
    for s in spans:
        assert s["end"] >= s["start"] >= 0.0


def test_chunked_span_tree_one_prefill_span_per_chunk():
    """Chunked records emit one engine.prefill span per chunk (with
    chunk ordinals) and the matched device dispatch parents under the
    chunk whose window contains it."""
    _tel, rec = _synthetic_chunked_rec()
    snap = T.request_snapshot(rec, deployment="dep0")
    programs = {"invokes": {"serve.paged_prefill": [[1.85, 0.04]]},
                "compiles": {}}
    spans = TB.attach_device_spans(
        TB.build_request_spans(snap), snap, programs)
    pf = [s for s in spans if s["name"] == "engine.prefill"]
    assert len(pf) == 2
    assert [s["attrs"]["chunk"] for s in pf] == [0, 1]
    assert all(s["attrs"]["n_chunks"] == 2 for s in pf)
    assert [s["attrs"]["tokens"] for s in pf] == [32, 32]
    assert (pf[0]["start"], pf[0]["end"]) == (1.5, 1.6)
    assert (pf[1]["start"], pf[1]["end"]) == (1.8, 1.9)
    # the invoke at t=1.85 sits inside chunk 1's window
    dev = next(s for s in spans if s["name"].startswith("device "))
    assert dev["parent_id"] == pf[1]["span_id"]


def test_chunked_device_stitch_falls_back_to_last_chunk():
    """A dispatch timestamped in the parked gap between chunks (clock
    skew) still parents under the last chunk — the one whose sample
    became the first token — rather than dangling."""
    _tel, rec = _synthetic_chunked_rec()
    snap = T.request_snapshot(rec, deployment="dep0")
    programs = {"invokes": {"serve.paged_prefill": [[1.7, 0.05]]},
                "compiles": {}}
    spans = TB.attach_device_spans(
        TB.build_request_spans(snap), snap, programs)
    pf = [s for s in spans if s["name"] == "engine.prefill"]
    dev = next(s for s in spans if s["name"].startswith("device "))
    assert dev["parent_id"] == pf[-1]["span_id"]


def test_fallback_span_record_carries_start_duration():
    tracing.enable_tracing()
    t0 = time.perf_counter()
    tracing.record_span("probe")
    tracing.record_span("window", start=12.5, duration=0.25)
    probe, window = tracing.recorded_spans()[-2:]
    assert probe.start >= t0 and probe.duration == 0.0
    assert window.start == 12.5 and window.duration == 0.25


# ---------------------------------------------------------------------------
# flightrec request follow + perfledger direction
# ---------------------------------------------------------------------------

def test_flightrec_filter_by_request():
    from ray_tpu.tools.flightrec import filter_events

    events = [
        {"kind": "admit", "req": 0, "trace": "abcdef0123456789"},
        {"kind": "admit", "req": 1, "trace": "fedcba9876543210"},
        {"kind": "step", "dur_ms": 1.0},
        {"kind": "requeue", "req": 0, "trace": "abcdef0123456789"},
    ]
    got = filter_events(events, request="abcdef01")
    assert [e["kind"] for e in got] == ["admit", "requeue"]
    assert filter_events(events, request="1")[0]["req"] == 1
    assert filter_events(events, request="nope") == []


def test_perfledger_itl_direction_and_fields():
    """'itl_ms_*' must trend lower-is-better: the _HIGHER_OVERRIDES
    substring match ('slo_attainment'/'accept_rate') must not catch
    it, and the _ms suffix must."""
    from ray_tpu.tools.perfledger import (_SWEEP_FIELDS,
                                          higher_is_better)

    assert "itl_ms_p50" in _SWEEP_FIELDS
    assert "itl_ms_p99" in _SWEEP_FIELDS
    assert higher_is_better("itl_ms_p50") is False
    assert higher_is_better("itl_ms_p99") is False
    assert higher_is_better("gpt2_traffic_itl_ms_p99") is False
    assert higher_is_better("gpt2_traffic_ttft_critical_path") is False
    # the overrides still win where they should
    assert higher_is_better("interactive_ttft_slo_attainment") is True


# ---------------------------------------------------------------------------
# graftcheck scopes over tools/tracebus.py
# ---------------------------------------------------------------------------

def test_graftcheck_wallclock_scope_covers_tracebus():
    from ray_tpu.tools.graftcheck.lint import lint_source

    src = ("import time\n"
           "def collect():\n"
           "    return time.time()\n")
    kept, _ = lint_source(src, "ray_tpu/tools/tracebus.py")
    assert [v.rule for v in kept] == ["wallclock-in-telemetry"]
    # same source outside the scope stays clean
    kept, _ = lint_source(src, "ray_tpu/tools/unrelated.py")
    assert kept == []


def test_graftcheck_blocking_async_scope_covers_tracebus():
    from ray_tpu.tools.graftcheck.lint import lint_source

    src = ("import time\n"
           "async def pump():\n"
           "    time.sleep(1)\n")
    kept, _ = lint_source(src, "ray_tpu/tools/tracebus.py")
    assert [v.rule for v in kept] == ["blocking-call-in-async"]
    kept, _ = lint_source(src, "ray_tpu/tools/unrelated.py")
    assert kept == []


def test_tracebus_module_passes_its_own_lint():
    from ray_tpu.tools.graftcheck.lint import lint_source

    with open(TB.__file__) as f:
        kept, _ = lint_source(f.read(), "ray_tpu/tools/tracebus.py")
    assert kept == [], [str(v) for v in kept]


# ---------------------------------------------------------------------------
# fleet acceptance: merged trace + CLI + <=5% decomposition
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def fleet_dump(tmp_path_factory):
    from ray_tpu.serve.traffic import (TenantSpec, TrafficSpec,
                                       run_traffic_fleet)

    tenants = (
        TenantSpec("interactive", rate_share=1.0,
                   slo_class="interactive", prefix_groups=(0,)),
        TenantSpec("batch", rate_share=1.0, slo_class="batch",
                   prefix_groups=(1,)))
    spec = TrafficSpec(num_requests=8, seed=0, rate_rps=100.0,
                       num_prefix_groups=2, prefix_len=32,
                       p_shared=0.75, tail_len_mean=6.0,
                       tail_len_max=16, vocab=500, tenants=tenants)
    path = str(tmp_path_factory.mktemp("tracebus") / "dump.json")
    rep = run_traffic_fleet(
        spec, num_replicas=2, family="gpt2", preset="nano",
        kv_block_size=16, max_slots=2, max_new_tokens=4,
        prefill_bucket=16, time_scale=0.0,
        config_overrides={"dtype": jnp.float32, "use_flash": False},
        trace_dump=path)
    return rep, path


def test_fleet_report_carries_anatomy(fleet_dump):
    rep, _ = fleet_dump
    assert rep["completed"] > 0
    assert isinstance(rep["itl_ms_p50"], (int, float))
    assert isinstance(rep["itl_ms_p99"], (int, float))
    assert rep["itl_ms_p50"] <= rep["itl_ms_p99"]
    cp = rep["ttft_critical_path"]
    assert isinstance(cp["total_p99_ms"], (int, float))
    assert cp["total_p99_ms"] >= 0.0
    assert rep["fleet"]["latency_anatomy"]["requests"] > 0


def test_fleet_dump_stitches_router_engine_device(fleet_dump):
    _rep, path = fleet_dump
    doc = TB.load_dump(path)
    reqs = [r for r in doc["requests"] if r.get("critical_path")]
    assert reqs, "no completed requests in the dump"
    # requests landed on two replica lanes
    assert len({r["replica"] for r in doc["requests"]}) == 2
    # router journal + one journal per replica merged onto one clock
    assert "router" in doc["flightrec"]
    assert len(doc["flightrec"]) >= 3
    stitched = 0
    for req in reqs:
        spans = TB.attach_device_spans(
            TB.build_request_spans(req), req, doc["programs"])
        by_id = {s["span_id"]: s for s in spans}
        route = next((s for s in spans
                      if s["name"] == "router.route"), None)
        assert route is not None, req["request"]
        root = by_id[route["parent_id"]]
        assert root["parent_id"] is None
        prefill = next(s for s in spans
                       if s["name"] == "engine.prefill")
        assert by_id[prefill["parent_id"]] is root
        dev = next((s for s in spans
                    if s["name"].startswith("device ")), None)
        if dev is not None:
            assert by_id[dev["parent_id"]] is prefill
            stitched += 1
    # at least one named request carries the full
    # router -> engine -> device chain
    assert stitched > 0


def test_fleet_dump_critical_path_within_5pct(fleet_dump):
    _rep, path = fleet_dump
    doc = TB.load_dump(path)
    table = TB.critical_path_table(doc, 99.0)
    assert table["requests"] > 0
    ex = table["exemplar"]["critical_path"]
    comp_sum = sum(ex[k] for k in T.CRITICAL_PATH_COMPONENTS)
    assert abs(comp_sum - ex["e2e_ms"]) <= 0.05 * ex["e2e_ms"]
    # per-tenant slicing stays well-formed
    for tenant in ("interactive", "batch"):
        tt = TB.critical_path_table(doc, 99.0, tenant=tenant)
        assert tt["tenant"] == tenant


def test_fleet_dump_cli_subcommands(fleet_dump, tmp_path, capsys):
    _rep, path = fleet_dump
    doc = TB.load_dump(path)
    rid = next(r["request"] for r in doc["requests"]
               if r.get("critical_path"))

    assert TB.main(["report", path]) == 0
    assert "critical path p99" in capsys.readouterr().out

    assert TB.main(["trace", path, rid[:8]]) == 0
    out = capsys.readouterr().out
    assert "engine.prefill" in out and "router.route" in out

    assert TB.main(["critical-path", path,
                    "--percentile", "99"]) == 0
    assert "prefill_ms" in capsys.readouterr().out

    trace_out = str(tmp_path / "merged_trace.json")
    assert TB.main(["export", path, "-o", trace_out]) == 0
    capsys.readouterr()
    with open(trace_out) as f:
        events = json.load(f)
    # one merged timeline: router pid 0 + a lane per replica, spans
    # carrying their causal ids into the export
    lanes = {e["args"]["name"] for e in events
             if e.get("name") == "process_name"}
    assert sum(1 for name in lanes
               if name.startswith("replica ")) == 2
    assert any(name.startswith("router") for name in lanes)
    spans = [e for e in events if e.get("ph") == "X"
             and e.get("cat") == "tracebus"]
    assert any(e["args"].get("parent_id") for e in spans)

    # unknown request id -> nonzero exit, not a traceback
    assert TB.main(["trace", path, "veryunknown"]) == 1


def test_unreadable_dump_exits_2(tmp_path, capsys):
    bad = tmp_path / "bad.json"
    bad.write_text("{}")
    assert TB.main(["report", str(bad)]) == 2
    assert TB.main(["report", str(tmp_path / "missing.json")]) == 2
    capsys.readouterr()


# ---------------------------------------------------------------------------
# hot-path overhead guard (mirrors the flightrec guard)
# ---------------------------------------------------------------------------

def test_tracebus_overhead_under_5pct(monkeypatch):
    """Per-token stamping + context threading must be cheap enough to
    leave on: min-of-repeats decode-loop wall time with tracebus on
    stays within 5% of RAYTPU_TRACEBUS=0."""
    dep = build_llm_deployment(
        "gpt2", "nano", scheduler="continuous", kv_layout="paged",
        kv_block_size=16, prefill_bucket=16, max_slots=2,
        max_new_tokens=8, temperature=0.0, config_overrides=_OVR)
    prompts = _prompts(4)

    def drive():
        async def main():
            inst = dep.func_or_class()
            try:
                await asyncio.gather(*[inst(p) for p in prompts])
            finally:
                inst.shutdown_engine()

        asyncio.run(main())

    def best(n=5):
        def run_once():
            t0 = time.perf_counter()
            drive()
            return time.perf_counter() - t0

        return min(run_once() for _ in range(n))

    drive()                            # compile warmup (shared cache)
    monkeypatch.setenv("RAYTPU_TRACEBUS", "0")
    off = best()
    monkeypatch.setenv("RAYTPU_TRACEBUS", "1")
    on = best()
    assert on <= off * 1.05, (on, off)
