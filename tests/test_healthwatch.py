"""Healthwatch acceptance: liveness state machine, stall detection,
chaos fault injection, death-requeue, and incident timelines.

Host-only units drive :class:`HealthMonitor` with injected clocks
(HEALTHY→SUSPECT→DEAD thresholds, idle immunity, fault-stamped
detection latency, stall dedup, probe throttling) and pin the chaos
injector's wave arithmetic.  The end-to-end scenario freezes one
replica of a live two-replica fleet mid-traffic and demands the full
story: the monitor catches it within ``dead_ms``, the router requeues
its stranded queue and routes around it, every request still matches
the dense single-engine oracle bit-for-bit, and the incidents CLI
names the sick replica, its detection latency, and the SLO burn
window from one tracebus dump.  A final interleaved min-of-5 guard
bounds healthwatch's chaos-free hot-path overhead under 5%.
"""

import asyncio
import json
import time

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from ray_tpu.serve.chaos import ChaosConfig, ChaosInjector  # noqa: E402
from ray_tpu.serve.health import (DEAD, HEALTHY, SUSPECT,  # noqa: E402
                                  HealthConfig, HealthMonitor,
                                  empty_fleet_health, empty_health,
                                  healthwatch_enabled)
from ray_tpu.serve.router import build_llm_fleet  # noqa: E402
from ray_tpu.serve.slo import SLOConfig  # noqa: E402

MAX_NEW = 6
_OVR = {"dtype": jnp.float32, "use_flash": False, "remat": False}
_ENGINE_KW = dict(max_new_tokens=MAX_NEW, temperature=0.0,
                  kv_block_size=16, prefill_bucket=16, max_slots=2,
                  config_overrides=_OVR)


class _Recorder:
    """Journal stand-in: keeps every record as a plain dict."""

    def __init__(self):
        self.events = []

    def record(self, kind, **fields):
        self.events.append(dict(fields, kind=kind))

    def kinds(self):
        return [e["kind"] for e in self.events]


def _monitor(rec=None, **cfg_kw):
    cfg = HealthConfig(**{**dict(suspect_ms=30.0, dead_ms=90.0,
                                 stall_ms=50.0, probe_ms=0.0),
                          **cfg_kw})
    return HealthMonitor(cfg, deployment="t_hw", recorder=rec,
                         enabled=True, now=0.0)


# ---------------------------------------------------------------------------
# state machine units (host-only, injected clocks)
# ---------------------------------------------------------------------------

def test_state_machine_suspect_dead_recover_cycle():
    rec = _Recorder()
    m = _monitor(rec)
    m.register("r0", now=0.0)
    m.heartbeat("r0", now=0.0)
    # fresh heartbeat: nothing to report
    assert m.probe(now=0.01) == []
    assert m.state("r0") == HEALTHY
    # stale past suspect_ms
    [tr] = m.probe(now=0.05)
    assert tr["to"] == SUSPECT and tr["reason"] == "heartbeat_stale"
    assert m.state("r0") == SUSPECT
    # stale past dead_ms
    [tr] = m.probe(now=0.10)
    assert tr["to"] == DEAD and tr["reason"] == "heartbeat_lost"
    assert m.state("r0") == DEAD
    # dead stays dead: no duplicate transitions on further sweeps
    assert m.probe(now=0.20) == []
    # the loop comes back: recovery on the next heartbeat
    m.heartbeat("r0", now=0.25)
    assert m.state("r0") == HEALTHY

    blk = m.replica_block("r0", now=0.25)
    assert blk["enabled"] is True
    assert blk["suspect_count"] == 1 and blk["dead_count"] == 1
    assert blk["recoveries"] == 1 and blk["transitions"] == 3
    assert [t["to"] for t in blk["transition_log"]] == \
        [SUSPECT, DEAD, HEALTHY]
    kinds = rec.kinds()
    assert kinds.count("health_transition") == 3
    assert rec.events[-1]["to"] == HEALTHY
    assert rec.events[-1]["reason"] == "heartbeat_resumed"


def test_idle_replicas_are_never_suspected():
    m = _monitor()
    m.register("r0", now=0.0)
    # replicas register idle: stale-by-hours is not a failure
    assert m.probe(now=10.0) == []
    m.heartbeat("r0", now=10.0)
    m.note_idle("r0", now=10.5)
    # parked with no work: still immune however old the stamp gets
    assert m.probe(now=20.0) == []
    assert m.state("r0") == HEALTHY
    # the next heartbeat re-arms the staleness clock
    m.heartbeat("r0", now=20.0)
    out = m.probe(now=20.2)
    assert [t["to"] for t in out] == [DEAD]


def test_detection_latency_measured_from_fault_instant():
    rec = _Recorder()
    m = _monitor(rec)
    m.register("r0", now=0.0)
    m.heartbeat("r0", now=0.0)
    m.note_fault("r0", kind="freeze", now=0.02)
    [s] = m.probe(now=0.05)
    assert s["to"] == SUSPECT
    [d] = m.probe(now=0.12)
    assert d["to"] == DEAD
    # fault stamped at 20ms, DEAD at 120ms -> 100ms to detect
    assert d["time_to_detect_ms"] == pytest.approx(100.0)
    assert m.time_to_detect_ms == pytest.approx(100.0)
    blk = m.fleet_block(now=0.12)
    assert blk["time_to_detect_ms"] == pytest.approx(100.0)
    assert blk["faults_injected"] == 1
    assert rec.kinds()[0] == "fault_injected"
    assert rec.events[0]["fault"] == "freeze"


class _StallTele:
    """EngineTelemetry stand-in for the stall sweep."""

    def __init__(self):
        self.stalls = []

    def stalled_requests(self, stall_ms, now=None):
        return list(self.stalls)


def test_stall_sweep_suspects_replica_once_per_request():
    rec = _Recorder()
    m = _monitor(rec)
    rrec = _Recorder()
    tele = _StallTele()
    m.register("r0", recorder=rrec, telemetry=tele, now=0.0)
    m.heartbeat("r0", now=0.0)
    tele.stalls = [{"id": "q-1", "silent_ms": 70.0, "trace": None}]
    out = m.probe(now=0.01)
    assert [t["to"] for t in out] == [SUSPECT]
    assert out[0]["reason"] == "request_stall"
    # the same stalled request again: no duplicate journal entry and
    # no second transition
    assert m.probe(now=0.02) == []
    stalls = [e for e in rrec.events if e["kind"] == "request_stall"]
    assert len(stalls) == 1 and stalls[0]["req"] == "q-1"
    assert "trace" not in stalls[0]  # None trace never journaled
    # the fleet recorder got its copy of the stall too
    assert rec.kinds().count("request_stall") == 1
    assert m.replica_block("r0", now=0.02)["stalls"] == 1


def test_maybe_probe_throttles_by_probe_ms():
    m = _monitor(probe_ms=50.0)
    m.register("r0", now=0.0)
    m.heartbeat("r0", now=0.0)
    assert m.maybe_probe(now=0.0) == []  # arms the window
    # inside the window: no sweep, even though the beat is now stale
    assert m.maybe_probe(now=0.04) == []
    assert m.state("r0") == HEALTHY
    # past the window: the sweep runs and suspects
    out = m.maybe_probe(now=0.06)
    assert [t["to"] for t in out] == [SUSPECT]


def test_disabled_monitor_is_inert():
    m = HealthMonitor(HealthConfig(suspect_ms=1.0, dead_ms=2.0),
                      deployment="t_off", enabled=False)
    m.register("r0")
    m.heartbeat("r0")
    m.note_fault("r0")
    assert m.probe(now=99.0) == []
    assert m.maybe_probe(now=99.0) == []
    assert m.state("r0") == HEALTHY
    assert m.replica_block("r0") == empty_health()
    assert m.fleet_block() == empty_fleet_health()
    assert m.time_to_detect_ms is None


def test_kill_switch_env(monkeypatch):
    monkeypatch.setenv("RAYTPU_HEALTHWATCH", "0")
    assert not healthwatch_enabled()
    assert HealthMonitor(deployment="t_env").enabled is False
    monkeypatch.setenv("RAYTPU_HEALTHWATCH", "1")
    assert healthwatch_enabled()
    assert HealthMonitor(deployment="t_env").enabled is True


# ---------------------------------------------------------------------------
# chaos injector units
# ---------------------------------------------------------------------------

def test_chaos_config_validation():
    for bad in (dict(freeze_poll_ms=0.0), dict(freeze_waves=-1),
                dict(freeze_after_waves=-1), dict(delay_token_ms=-1.0),
                dict(delay_token_waves=-1), dict(drop_handoff_nth=-1)):
        with pytest.raises(ValueError):
            ChaosConfig(**bad)


def test_default_chaos_config_arms_nothing():
    cfg = ChaosConfig()
    assert not cfg.any_faults()
    inj = ChaosInjector(cfg)
    inj.bind("f/r0")
    assert not any(inj.frozen("f/r0") for _ in range(50))
    assert inj.token_delay_s("f/r0") == 0.0
    assert not inj.should_drop_handoff()
    st = inj.stats()
    assert st["armed"] is False
    assert st["frozen_polls"] == {} and st["dropped_handoffs"] == 0


def test_chaos_freeze_window_and_single_fault_stamp():
    rec = _Recorder()
    m = _monitor(rec)
    m.register("f/r1", now=0.0)
    m.heartbeat("f/r1", now=0.0)
    inj = ChaosInjector(ChaosConfig(freeze_replica=1,
                                    freeze_after_waves=2,
                                    freeze_waves=3), monitor=m)
    inj.bind("f/r0")
    inj.bind("f/r1")
    # the untargeted replica never freezes (index targeting is by
    # bind order)
    assert not any(inj.frozen("f/r0") for _ in range(10))
    # victim: 2 real waves, 3 frozen poll windows, then thaw for good
    assert [inj.frozen("f/r1") for _ in range(7)] == \
        [False, False, True, True, True, False, False]
    assert inj.stats()["frozen_polls"] == {"f/r1": 3}
    # the fault instant was stamped on the monitor exactly once
    faults = [e for e in rec.events if e["kind"] == "fault_injected"]
    assert len(faults) == 1
    assert faults[0]["replica"] == "f/r1"
    assert faults[0]["fault"] == "freeze"


def test_chaos_token_delay_budget_and_handoff_drop_counter():
    inj = ChaosInjector(ChaosConfig(delay_token_replica="f/r0",
                                    delay_token_ms=4.0,
                                    delay_token_waves=2,
                                    drop_handoff_nth=2))
    inj.bind("f/r0")
    assert inj.token_delay_s("f/r0") == pytest.approx(0.004)
    assert inj.token_delay_s("f/r0") == pytest.approx(0.004)
    assert inj.token_delay_s("f/r0") == 0.0  # wave budget spent
    assert inj.token_delay_s("f/other") == 0.0
    # exactly the Nth (1-based) package drops
    assert [inj.should_drop_handoff() for _ in range(4)] == \
        [False, True, False, False]
    assert inj.dropped_handoffs == 1


def test_perfledger_tracks_detection_latency_lower_is_better():
    from ray_tpu.tools.perfledger import _SWEEP_FIELDS, higher_is_better

    assert "time_to_detect_ms" in _SWEEP_FIELDS
    assert not higher_is_better("time_to_detect_ms")


# ---------------------------------------------------------------------------
# end-to-end: frozen replica detected, routed around, oracle-identical
# ---------------------------------------------------------------------------

def _oracle(prompt, max_new=MAX_NEW):
    """Dense solo greedy continuation — the parity reference."""
    from ray_tpu.models import gpt2_config, gpt2_init
    from ray_tpu.models.gpt2_decode import generate

    cfg = gpt2_config("nano", **_OVR)
    params = gpt2_init(jax.random.PRNGKey(0), cfg)
    out = generate(params, jnp.asarray(np.asarray(prompt)[None]), cfg,
                   max_new_tokens=max_new, temperature=0.0)
    return np.asarray(out)[0]


def test_chaos_freeze_detected_requeued_and_oracle_identical(
        tmp_path, capsys):
    rng = np.random.RandomState(7)
    # fixed-length prompts: the oracle's generate jit compiles once
    prompts = [rng.randint(2, 500, 24).astype(np.int32)
               for _ in range(12)]

    health = HealthConfig(suspect_ms=30.0, dead_ms=90.0,
                          stall_ms=60_000.0, probe_ms=1.0)
    chaos = ChaosConfig(seed=0, freeze_replica=1, freeze_after_waves=2,
                        freeze_waves=150, freeze_poll_ms=5.0)
    # unreachable-fast TTFT target: the freeze window burns the SLO,
    # giving the incident report a burn window to name
    slo = SLOConfig(ttft_ms=5.0, e2e_ms=600_000.0, objective=0.5,
                    dump_on_breach=False)
    # inflight cap above max_slots so the frozen replica's engine
    # queue holds not-yet-admitted requests for the router to rescue
    fleet = build_llm_fleet(
        "gpt2", "nano", fleet_name="t_chaos", num_replicas=2,
        routing="round_robin", wfq=False, slo=slo, health=health,
        chaos=chaos, max_inflight_per_replica=6, **_ENGINE_KW)
    frozen_name = "t_chaos/r1"

    async def main():
        tasks = [asyncio.create_task(fleet(p)) for p in prompts]
        # keep the healthy replica's pump busy until detection fires:
        # every submit runs the router's health sweep, and the pings
        # themselves route around the sick replica
        pings = []
        deadline = time.perf_counter() + 60.0
        while (fleet.health.time_to_detect_ms is None
               and time.perf_counter() < deadline):
            pings.append(asyncio.create_task(fleet(prompts[0])))
            await asyncio.sleep(0.02)
        outs = await asyncio.gather(*tasks)
        pouts = await asyncio.gather(*pings)
        return outs, pouts

    try:
        outs, pouts = asyncio.run(main())

        # detection: the frozen replica went SUSPECT then DEAD, and
        # the latency is measured from the chaos fault instant
        fs = fleet.fleet_stats()
        hb = fs["health"]
        assert hb["enabled"] is True
        assert hb["faults_injected"] >= 1
        assert hb["chaos"]["armed"] is True
        assert hb["chaos"]["frozen_polls"].get(frozen_name, 0) > 0
        ttd = hb["time_to_detect_ms"]
        assert ttd is not None and 0 < ttd < 60_000.0
        rep_blk = hb["replicas"][frozen_name]
        assert rep_blk["time_to_detect_ms"] == ttd
        tos = [t["to"] for t in rep_blk["transitions"]]
        assert SUSPECT in tos and DEAD in tos
        # the loop thawed and heartbeat: nobody is dead at the end
        assert hb["by_state"][DEAD] == 0

        # rescue: the dead replica's queued (not-yet-admitted)
        # requests were push_front-requeued to the healthy replica
        assert hb["requeued_on_death"] >= 1
        assert fs["router"]["requeued_on_death"] == \
            hb["requeued_on_death"]

        # semantics: chaos + requeue never change results — every
        # request is bit-identical to the dense greedy oracle
        for p, o in zip(prompts, outs):
            np.testing.assert_array_equal(o, _oracle(p))
        ping_oracle = _oracle(prompts[0])
        for o in pouts:
            np.testing.assert_array_equal(o, ping_oracle)

        # one tracebus dump carries every lane the incident spans
        from ray_tpu.tools import incidents, tracebus

        dump_path = str(tmp_path / "chaos_dump.json")
        tracebus.write_dump(tracebus.collect(fleet), dump_path)
    finally:
        fleet.shutdown()

    doc = incidents.load(dump_path)
    events = incidents.merge_events(doc)
    incs = incidents.extract_incidents(events)
    inc = next(i for i in incs if i["replica"] == frozen_name)
    assert inc["fault_kind"] == "freeze"
    assert inc["suspect_t"] is not None and inc["dead_t"] is not None
    assert inc["time_to_detect_ms"] == pytest.approx(ttd)
    assert inc["requeued"] == hb["requeued_on_death"]
    assert incidents.burn_windows(events), "no SLO burn window found"

    # the CLI report names the sick replica, its detection latency,
    # and the burn window
    assert incidents.main(["report", dump_path]) == 0
    text = capsys.readouterr().out
    assert frozen_name in text
    assert "fault injected: freeze" in text
    assert "time_to_detect_ms=" in text
    assert "slo burn window" in text
    assert "requeued_on_death=" in text

    # timeline: merged chronological stream mentions the transitions
    assert incidents.main(["timeline", dump_path]) == 0
    text = capsys.readouterr().out
    assert "health_transition" in text and "fault_injected" in text

    # export: a chrome-trace incident lane at pid 95
    trace_path = str(tmp_path / "incidents_trace.json")
    assert incidents.main(
        ["export", dump_path, "-o", trace_path]) == 0
    capsys.readouterr()
    with open(trace_path) as f:
        trace = json.load(f)
    assert any(e.get("ph") == "i" and e.get("pid") == 95
               for e in trace)


def test_flightrec_report_renders_health_lane(tmp_path):
    """The flightrec CLI's postmortem report grows a health lane:
    per-replica transition/stall counts from the journaled stream."""
    from ray_tpu._private.flightrec import FlightRecorder
    from ray_tpu.tools.flightrec import load_dump, report_lines

    fr = FlightRecorder("t_lane", capacity=64)
    m = HealthMonitor(HealthConfig(suspect_ms=30.0, dead_ms=90.0),
                      deployment="t_lane", recorder=fr, enabled=True)
    m.register("t_lane/r0", now=0.0)
    m.heartbeat("t_lane/r0", now=0.0)
    m.note_fault("t_lane/r0", kind="freeze", now=0.01)
    m.probe(now=0.05)
    m.probe(now=0.12)
    m.heartbeat("t_lane/r0", now=0.2)
    fr.dump_dir = str(tmp_path)
    path = fr.dump(reason="test/health_lane")
    text = "\n".join(report_lines(load_dump(path)))
    assert "health transitions (by replica):" in text
    assert "t_lane/r0" in text


# ---------------------------------------------------------------------------
# traffic harness carries the detection headlines
# ---------------------------------------------------------------------------

def test_traffic_report_carries_detection_headlines():
    from ray_tpu.serve.traffic import TrafficSpec, run_traffic_fleet

    spec = TrafficSpec(num_requests=10, seed=3, rate_rps=500.0,
                       num_prefix_groups=2, prefix_len=32,
                       p_shared=0.5, tail_len_mean=4.0, tail_len_max=8,
                       vocab=500)
    rep = run_traffic_fleet(
        spec, num_replicas=2, max_slots=2, max_new_tokens=4,
        prefill_bucket=16, time_scale=0.0, routing="round_robin",
        wfq=False, config_overrides=_OVR,
        health=HealthConfig(suspect_ms=30.0, dead_ms=90.0,
                            stall_ms=60_000.0, probe_ms=1.0),
        chaos=ChaosConfig(freeze_replica=1, freeze_after_waves=2,
                          freeze_waves=100, freeze_poll_ms=5.0),
        max_inflight_per_replica=5)
    # the flattened healthwatch headlines are always present
    assert "time_to_detect_ms" in rep
    assert isinstance(rep["requests_requeued_on_death"], int)
    hb = rep["fleet"]["health"]
    assert hb["enabled"] is True
    assert hb["faults_injected"] >= 1
    assert hb["chaos"]["frozen_polls"]
    assert rep["completed"] + rep["shed"] == rep["offered"]


# ---------------------------------------------------------------------------
# overhead + inertness guards
# ---------------------------------------------------------------------------

def test_healthwatch_overhead_under_five_percent_and_chaos_inert():
    rng = np.random.RandomState(9)
    prompts = [rng.randint(2, 500, 24).astype(np.int32)
               for _ in range(6)]
    # generous thresholds: no transitions fire, so the measurement is
    # the pure hot-path cost (heartbeat + throttled probe per wave)
    fleet = build_llm_fleet(
        "gpt2", "nano", fleet_name="t_ovh", num_replicas=1,
        routing="round_robin", wfq=False,
        health=HealthConfig(suspect_ms=60_000.0, dead_ms=120_000.0,
                            stall_ms=60_000.0, probe_ms=1.0),
        **_ENGINE_KW)

    # chaos hooks provably inert when unset: nothing attached anywhere
    assert fleet.chaos is None
    for rep in fleet._replicas:
        assert rep.inst._chaos is None
    assert "chaos" not in fleet.fleet_stats()["health"]
    monitor = fleet.health
    assert monitor is not None

    def _arm(on):
        fleet.router._health = monitor if on else None
        for rep in fleet._replicas:
            rep.inst._health = monitor if on else None

    async def main():
        # compile + first-allocation warmup, outside the measurement
        await asyncio.gather(*[fleet(p) for p in prompts])
        on, off = [], []
        for _ in range(5):  # interleaved pairs: drift hits both arms
            for armed, acc in ((True, on), (False, off)):
                _arm(armed)
                t0 = time.perf_counter()
                await asyncio.gather(*[fleet(p) for p in prompts])
                acc.append(time.perf_counter() - t0)
        _arm(True)
        return min(on), min(off)

    try:
        t_on, t_off = asyncio.run(main())
    finally:
        fleet.shutdown()
    # min-of-5 absorbs scheduler noise; the epsilon absorbs timer
    # granularity on very fast hosts
    assert t_on <= t_off * 1.05 + 0.002, (t_on, t_off)
