"""Sharded checkpoint save/restore with resharding (orbax-backed).

SURVEY §7 names orbax-style sharded checkpoints as a design-fresh gap;
these tests cover: sharded save on one mesh, restore onto a DIFFERENT
mesh layout (elastic restart), value fidelity, step management, and AIR
interop.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.models import gpt2_config, gpt2_init, gpt2_logical_axes
from ray_tpu.parallel import MeshSpec, fake_mesh
from ray_tpu.parallel.sharding import param_shardings, shard_params
from ray_tpu.train import (latest_step, restore_sharded, save_sharded,
                           sharded_checkpoint_to_air)


def test_save_sharded_restore_resharded(tmp_path):
    cfg = gpt2_config("nano")
    axes = gpt2_logical_axes(cfg)
    params = gpt2_init(jax.random.PRNGKey(0), cfg)

    mesh_a = fake_mesh(8, MeshSpec(fsdp=4, tensor=2))
    with jax.set_mesh(mesh_a):
        sharded = shard_params(params, axes, mesh_a)
    path = save_sharded(sharded, str(tmp_path / "ckpt"), step=3)

    # restore onto a DIFFERENT layout: pure data-parallel mesh
    mesh_b = fake_mesh(8, MeshSpec(data=8))
    restored = restore_sharded(str(tmp_path / "ckpt"), step=3,
                               mesh=mesh_b, axes=axes)
    for orig, new in zip(jax.tree.leaves(params),
                         jax.tree.leaves(restored)):
        np.testing.assert_array_equal(np.asarray(orig), np.asarray(new))
    # restored arrays carry mesh_b shardings matching the rule table
    want = param_shardings(axes, mesh_b)
    for s_want, leaf in zip(jax.tree.leaves(want),
                            jax.tree.leaves(restored)):
        assert leaf.sharding == s_want

    assert latest_step(str(tmp_path / "ckpt")) == 3


def test_restore_without_mesh_and_air_interop(tmp_path):
    tree = {"w": jnp.arange(8.0), "b": jnp.ones((2, 2))}
    path = save_sharded(tree, str(tmp_path / "flat"))
    back = restore_sharded(str(tmp_path / "flat"))
    np.testing.assert_array_equal(np.asarray(back["w"]), np.arange(8.0))

    ckpt = sharded_checkpoint_to_air(str(tmp_path / "flat"))
    assert ckpt.to_dict()["sharded_checkpoint_path"].endswith("flat")
