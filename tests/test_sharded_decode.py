"""Mesh-sharded serving parity: tensor-parallel paged decode over an
8-device fake mesh must be BIT-IDENTICAL (greedy token streams) to the
single-chip dense oracle for both families.

Sharding is driven entirely by committed input shardings: params and
the KV pool are device_put under parallel.sharding.DECODE_RULES (heads
/ mlp / vocab / pool KV-heads over `tensor`; everything the host
scheduler reads stays replicated) and GSPMD propagates them through
the UNCHANGED jitted programs.  Logits are not asserted bitwise —
row-parallel contractions all-reduce partial sums in a different
order than a single chip — but greedy argmax token streams are, and
that is the property serving correctness rests on.

conftest.py forces 8 virtual CPU devices, so every test here runs in
tier-1.
"""

import asyncio
import functools

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from ray_tpu.models import (gpt2_config, gpt2_init, gpt2_logical_axes,
                            llama_config, llama_init,
                            llama_logical_axes)  # noqa: E402
from ray_tpu.models import gpt2_decode, llama_decode  # noqa: E402
from ray_tpu.models.decode_common import (cache_logical_axes,
                                          make_vocab_tail_mask,
                                          sample_token)  # noqa: E402
from ray_tpu.parallel import (MeshSpec, fake_mesh,
                              mesh_axes_for_shape)  # noqa: E402
from ray_tpu.parallel.sharding import (DECODE_RULES,
                                       shard_by_shape)  # noqa: E402

BS = 16
_OVR = {"dtype": jnp.float32, "use_flash": False, "remat": False}


@pytest.fixture(scope="module")
def mesh():
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 devices (conftest forces them in CI)")
    return fake_mesh(8, MeshSpec(data=4, tensor=2))


def _family(name):
    """(cfg, params, axes, prefill, paged_prefill, decode_step,
    init_paged_cache, generate) — params NOT yet sharded."""
    if name == "gpt2":
        cfg = gpt2_config("nano", **_OVR)
        return (cfg, gpt2_init(jax.random.PRNGKey(0), cfg),
                gpt2_logical_axes(cfg), gpt2_decode.prefill,
                gpt2_decode.paged_prefill, gpt2_decode.decode_step,
                gpt2_decode.init_paged_cache, gpt2_decode.generate)
    cfg = llama_config("nano", **_OVR)
    return (cfg, llama_init(jax.random.PRNGKey(0), cfg),
            llama_logical_axes(cfg), llama_decode.llama_prefill,
            llama_decode.llama_paged_prefill,
            llama_decode.llama_decode_step,
            llama_decode.llama_init_paged_cache,
            llama_decode.llama_generate)


def _right_aligned(tokens, t_pad):
    out = np.zeros((1, t_pad), np.int32)
    out[0, t_pad - len(tokens):] = tokens
    return jnp.asarray(out)


@functools.lru_cache(maxsize=None)
def _jitted(family):
    """Module-lifetime jitted (decode_step, paged_prefill) per family:
    the sharded programs compile once and every test reuses the XLA
    cache — eager dispatch of sharded nano ops over 8 devices is what
    dominates otherwise."""
    _, _, _, _, paged_prefill, decode_step, _, _ = _family(family)
    return (jax.jit(decode_step, static_argnums=3),
            jax.jit(paged_prefill, static_argnums=3))


# ---------------------------------------------------------------------------
# sharding structure
# ---------------------------------------------------------------------------

def test_divisibility_guard_replicates_non_dividing_dims(mesh):
    # 2 heads / tensor=2 shards; 1 KV head / tensor=2 replicates;
    # odd dims replicate regardless of the rule table
    spec = mesh_axes_for_shape((4, 2, 32), (None, "heads", None), mesh,
                               DECODE_RULES)
    assert tuple(spec) == (None, "tensor")
    spec = mesh_axes_for_shape((4, 1, 32), (None, "kv_heads", None),
                               mesh, DECODE_RULES)
    assert tuple(spec) == ()
    spec = mesh_axes_for_shape((3,), ("mlp",), mesh, DECODE_RULES)
    assert tuple(spec) == ()


def test_params_and_pool_committed_to_mesh(mesh):
    cfg, params, axes, *_, init_paged, _ = _family("gpt2")
    sp = shard_by_shape(params, axes, mesh, DECODE_RULES)
    qkv = sp["blocks"]["attn"]["qkv_w"]
    assert "tensor" in tuple(qkv.sharding.spec)
    # per-chip shard halves the heads dim
    full = qkv.shape
    shard = qkv.sharding.shard_shape(full)
    assert shard[-2] * 2 == full[-2]

    cache = init_paged(cfg, 2, num_blocks=17, block_size=BS, mesh=mesh)
    kspec = tuple(cache["k"].sharding.spec)
    assert kspec == (None, None, None, "tensor")
    # host-facing leaves stay replicated
    for name in ("block_tables", "pos", "start"):
        assert tuple(cache[name].sharding.spec) in ((), (None,),
                                                    (None, None))
    # the paged axes annotation covers every leaf
    assert set(cache_logical_axes(cache)) == set(cache)


def test_llama_gqa_pool_replicates_but_q_heads_shard(mesh):
    cfg, params, axes, *_, init_paged, _ = _family("llama")
    sp = shard_by_shape(params, axes, mesh, DECODE_RULES)
    # wq: 2 query heads shard over tensor=2
    wq = sp["blocks"]["attn"]["wq"]
    assert "tensor" in tuple(wq.sharding.spec)
    # wk: 1 KV head — the guard replicates instead of erroring
    wk = sp["blocks"]["attn"]["wk"]
    assert "tensor" not in tuple(s for s in wk.sharding.spec
                                 if isinstance(s, str))
    cache = init_paged(cfg, 2, num_blocks=17, block_size=BS, mesh=mesh)
    assert "tensor" not in tuple(s for s in cache["k"].sharding.spec
                                 if isinstance(s, str))


# ---------------------------------------------------------------------------
# model-layer parity: sharded paged decode == single-chip dense oracle
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("family", ["gpt2", "llama"])
def test_sharded_paged_decode_matches_dense_oracle(family, mesh):
    cfg, params, axes, prefill, _, _, init_paged, generate = \
        _family(family)
    decode_step, paged_prefill = _jitted(family)
    sp = shard_by_shape(params, axes, mesh, DECODE_RULES)

    rng = np.random.RandomState(3)
    prompt = rng.randint(2, cfg.vocab_size, 9).astype(np.int32)
    new = 6
    oracle = np.asarray(generate(params, jnp.asarray(prompt[None]),
                                 cfg, max_new_tokens=new,
                                 temperature=0.0))[0, len(prompt):]

    nb_row = cfg.max_seq // BS
    cache = init_paged(cfg, 2, num_blocks=1 + 2 * nb_row,
                       block_size=BS, mesh=mesh)
    row_bt = np.zeros(nb_row, np.int32)
    row_bt[0] = 1
    logits, cache = paged_prefill(
        sp, cache, _right_aligned(prompt, 16), cfg,
        row_bt=jnp.asarray(row_bt), prefix_len=np.int32(0),
        n_tail=np.int32(len(prompt)), slot=np.int32(0))
    tail = make_vocab_tail_mask(cfg)
    tok = sample_token(logits[None], None, 0.0, tail)
    cur = jnp.asarray([int(tok[0]), 0], jnp.int32)  # row 1 idle
    stream = [int(tok[0])]
    for _ in range(new - 1):
        logits, cache = decode_step(sp, cache, cur, cfg)
        nxt = sample_token(logits, None, 0.0, tail)
        stream.append(int(nxt[0]))
        cur = jnp.asarray([int(nxt[0]), int(nxt[1])], jnp.int32)
    assert stream == oracle.tolist()


@pytest.mark.parametrize("family", ["gpt2", "llama"])
def test_sharded_prefix_reuse_prefill_matches_dense(family, mesh):
    """Prefix-reuse under the mesh: sequence B extends blocks written
    by sequence A's sharded prefill; its logits must match dense
    full-prompt prefill (numerically — the all-reduce changes float
    summation order) and its greedy stream must match exactly."""
    cfg, params, axes, prefill, _, _, init_paged, generate = \
        _family(family)
    decode_step, paged_prefill = _jitted(family)
    sp = shard_by_shape(params, axes, mesh, DECODE_RULES)
    rng = np.random.RandomState(7)
    shared = rng.randint(2, cfg.vocab_size, 32).astype(np.int32)
    # equal lengths: the dense generate oracle compiles ONE shape
    a = np.concatenate([shared, rng.randint(2, cfg.vocab_size, 3)
                        .astype(np.int32)])
    b = np.concatenate([shared, rng.randint(2, cfg.vocab_size, 3)
                        .astype(np.int32)])

    nb_row = cfg.max_seq // BS
    cache = init_paged(cfg, 2, num_blocks=1 + 2 * nb_row,
                       block_size=BS, mesh=mesh)
    bt_a = jnp.arange(1, 1 + nb_row, dtype=jnp.int32)
    _, cache = paged_prefill(sp, cache, _right_aligned(a, 48), cfg,
                             row_bt=bt_a, prefix_len=np.int32(0),
                             n_tail=np.int32(len(a)), slot=np.int32(0))
    bt_b = np.zeros(nb_row, np.int32)
    bt_b[0], bt_b[1], bt_b[2] = 1, 2, 1 + nb_row
    got, cache = paged_prefill(sp, cache, _right_aligned(b[32:], 16),
                               cfg, row_bt=jnp.asarray(bt_b),
                               prefix_len=np.int32(32),
                               n_tail=np.int32(len(b) - 32),
                               slot=np.int32(1))
    want, _ = prefill(params, jnp.asarray(b[None]), cfg,
                      lengths=jnp.asarray([len(b)]))
    np.testing.assert_allclose(np.asarray(got), np.asarray(want)[0],
                               atol=1e-4)

    # greedy streams from the shared sharded pool == dense solo
    # (equal lengths -> one batched oracle generate call)
    new = 4
    out = np.asarray(generate(params, jnp.asarray(np.stack([a, b])),
                              cfg, max_new_tokens=new, temperature=0.0))
    oracle = {0: out[0, len(a):], 1: out[1, len(b):]}
    tail = make_vocab_tail_mask(cfg)
    tok = jnp.asarray([int(oracle[0][0]),
                       int(np.argmax(np.asarray(got)))], jnp.int32)
    streams = [[], []]
    for _ in range(new):
        streams[0].append(int(tok[0]))
        streams[1].append(int(tok[1]))
        logits, cache = decode_step(sp, cache, tok, cfg)
        tok = sample_token(logits, None, 0.0, tail)
    assert streams[0] == oracle[0].tolist()
    assert streams[1] == oracle[1].tolist()


# ---------------------------------------------------------------------------
# continuous-scheduler e2e under the mesh
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("family", ["gpt2", "llama"])
def test_continuous_engine_two_waves_under_mesh(family, mesh):
    """6 requests through 3 slots (two admission waves) on the sharded
    engine: every caller gets the bit-identical dense-solo greedy
    continuation, and engine_stats reports the live mesh."""
    from ray_tpu.serve.llm import build_llm_deployment

    max_new = 5
    rng = np.random.RandomState(21)
    # two distinct lengths (-> 2 prefill buckets, 2 oracle compile
    # shapes) keeps the two-wave coverage without compiling a dense
    # generate program per request
    prompts = [rng.randint(2, 500, n).astype(np.int32)
               for n in (9, 23, 9, 23, 9, 23)]
    dep = build_llm_deployment(
        family, "nano", max_new_tokens=max_new, temperature=0.0,
        scheduler="continuous", kv_layout="paged", kv_block_size=BS,
        prefill_bucket=16, max_slots=3, mesh=mesh,
        config_overrides=_OVR)

    async def main():
        inst = dep.func_or_class()
        try:
            outs = await asyncio.wait_for(
                asyncio.gather(*[inst(p) for p in prompts]), 300)
            stats = inst.engine_stats()
        finally:
            inst.shutdown_engine()
        return outs, stats

    outs, stats = asyncio.run(main())
    cfg, params, *_, generate = _family(family)
    for n in (9, 23):  # one batched oracle generate per length
        idx = [i for i, p in enumerate(prompts) if len(p) == n]
        want = np.asarray(generate(
            params, jnp.asarray(np.stack([prompts[i] for i in idx])),
            cfg, max_new_tokens=max_new, temperature=0.0))
        for row, i in enumerate(idx):
            np.testing.assert_array_equal(outs[i], want[row])
    assert stats["requests"]["finished"] == 6
    assert stats["mesh"]["axes"] == {"data": 4, "tensor": 2}
    assert stats["mesh"]["n_devices"] == 8
    assert stats["mesh"]["kv_shards"] == (2 if family == "gpt2" else 1)
    kv = stats["kv_cache"]
    assert kv["pool_bytes_per_chip"] * kv["tensor_shards"] \
        == kv["pool_bytes"]


def test_jit_cache_keyed_by_layout_and_mesh(mesh):
    """Regression (round-9 satellite): equal-config engines differing
    only in kv_layout or mesh must NOT share jitted programs."""
    from ray_tpu.serve.llm import _jitted_engine_fns

    from ray_tpu.models.gpt2_decode import (decode_step, paged_prefill,
                                            prefill)

    cfg = gpt2_config("nano", **_OVR)
    base = _jitted_engine_fns(prefill, decode_step, paged_prefill,
                              cfg, 0.0, kv_layout="dense", mesh=None)
    paged = _jitted_engine_fns(prefill, decode_step, paged_prefill,
                               cfg, 0.0, kv_layout="paged", mesh=None)
    meshed = _jitted_engine_fns(prefill, decode_step, paged_prefill,
                                cfg, 0.0, kv_layout="paged", mesh=mesh)
    assert base is not paged
    assert paged is not meshed
    # same identity -> same cached tuple (the cache still works)
    again = _jitted_engine_fns(prefill, decode_step, paged_prefill,
                               cfg, 0.0, kv_layout="paged", mesh=mesh)
    assert again is meshed
