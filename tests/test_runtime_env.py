"""Runtime env unit tests (reference: plugin.py:24 RuntimeEnvPlugin)."""

import os

import pytest

from ray_tpu import runtime_env as re_mod


def test_validate_rejects_unknown_field():
    with pytest.raises(ValueError):
        re_mod.validate({"bogus": 1})


def test_validate_env_vars_typed():
    with pytest.raises(ValueError):
        re_mod.validate({"env_vars": {"A": 1}})
    assert re_mod.validate({"env_vars": {"A": "1"}})


def test_pack_and_materialize_roundtrip(tmp_path):
    src = tmp_path / "pkg"
    src.mkdir()
    (src / "data.txt").write_text("payload")
    (src / "mod.py").write_text("X = 5")
    kv = {}
    packed = re_mod.pack({"working_dir": str(src)},
                         lambda k, v: kv.__setitem__(k, v))
    assert packed["working_dir"].startswith("gcs://runtimeenv/")
    cache = tmp_path / "cache"
    ctx = re_mod.materialize(packed, kv.get, str(cache))
    assert ctx.cwd and os.path.isfile(os.path.join(ctx.cwd, "data.txt"))
    env = {}
    cwd = ctx.apply(env)
    assert cwd == ctx.cwd
    assert env["PYTHONPATH"].startswith(ctx.cwd)


def test_env_hash_stable():
    a = re_mod.env_hash({"env_vars": {"A": "1", "B": "2"}})
    b = re_mod.env_hash({"env_vars": {"B": "2", "A": "1"}})
    assert a == b


def test_custom_plugin(tmp_path):
    calls = []

    def my_plugin(value, ctx, kv_get, cache_dir):
        calls.append(value)
        ctx.env_vars["PLUGGED"] = str(value)

    re_mod.register_plugin("myfield", my_plugin)
    try:
        ctx = re_mod.materialize({"myfield": 7}, lambda k: None,
                                 str(tmp_path))
        assert calls == [7]
        assert ctx.env_vars["PLUGGED"] == "7"
    finally:
        re_mod.PLUGINS.pop("myfield", None)
