"""Runtime env unit tests (reference: plugin.py:24 RuntimeEnvPlugin)."""

import os

import pytest

from ray_tpu import runtime_env as re_mod


def test_validate_rejects_unknown_field():
    with pytest.raises(ValueError):
        re_mod.validate({"bogus": 1})


def test_validate_env_vars_typed():
    with pytest.raises(ValueError):
        re_mod.validate({"env_vars": {"A": 1}})
    assert re_mod.validate({"env_vars": {"A": "1"}})


def test_pack_and_materialize_roundtrip(tmp_path):
    src = tmp_path / "pkg"
    src.mkdir()
    (src / "data.txt").write_text("payload")
    (src / "mod.py").write_text("X = 5")
    kv = {}
    packed = re_mod.pack({"working_dir": str(src)},
                         lambda k, v: kv.__setitem__(k, v))
    assert packed["working_dir"].startswith("gcs://runtimeenv/")
    cache = tmp_path / "cache"
    ctx = re_mod.materialize(packed, kv.get, str(cache))
    assert ctx.cwd and os.path.isfile(os.path.join(ctx.cwd, "data.txt"))
    env = {}
    cwd = ctx.apply(env)
    assert cwd == ctx.cwd
    assert env["PYTHONPATH"].startswith(ctx.cwd)


def test_env_hash_stable():
    a = re_mod.env_hash({"env_vars": {"A": "1", "B": "2"}})
    b = re_mod.env_hash({"env_vars": {"B": "2", "A": "1"}})
    assert a == b


def test_custom_plugin(tmp_path):
    calls = []

    def my_plugin(value, ctx, kv_get, cache_dir):
        calls.append(value)
        ctx.env_vars["PLUGGED"] = str(value)

    re_mod.register_plugin("myfield", my_plugin)
    try:
        ctx = re_mod.materialize({"myfield": 7}, lambda k: None,
                                 str(tmp_path))
        assert calls == [7]
        assert ctx.env_vars["PLUGGED"] == "7"
    finally:
        re_mod.PLUGINS.pop("myfield", None)


def test_conda_plugin_activates_named_env(tmp_path, monkeypatch):
    """conda plugin: named env -> activation env vars (PATH/CONDA_*),
    driven through a fake conda binary (none is installed here)."""
    import os
    import stat

    from ray_tpu import runtime_env as re_mod

    base = tmp_path / "conda_base"
    envdir = base / "envs" / "myenv" / "bin"
    envdir.mkdir(parents=True)
    fake = tmp_path / "bin"
    fake.mkdir()
    conda = fake / "conda"
    conda.write_text(f"#!/bin/sh\necho {base}\n")
    conda.chmod(conda.stat().st_mode | stat.S_IEXEC)
    monkeypatch.setenv("PATH", f"{fake}:{os.environ['PATH']}")

    ctx = re_mod.materialize({"conda": "myenv"}, lambda k: None,
                             str(tmp_path / "cache"))
    assert ctx.env_vars["CONDA_DEFAULT_ENV"] == "myenv"
    assert ctx.env_vars["CONDA_PREFIX"] == str(base / "envs" / "myenv")
    assert ctx.env_vars["PATH"].startswith(
        str(base / "envs" / "myenv" / "bin"))


def test_conda_plugin_missing_binary_fails_loudly(tmp_path, monkeypatch):
    from ray_tpu import runtime_env as re_mod

    monkeypatch.setenv("PATH", str(tmp_path))  # nothing on PATH
    with pytest.raises(RuntimeError, match="conda"):
        re_mod.materialize({"conda": "x"}, lambda k: None,
                           str(tmp_path / "cache"))


def test_container_plugin_builds_command_prefix(tmp_path, monkeypatch):
    import stat

    from ray_tpu import runtime_env as re_mod

    fake = tmp_path / "bin"
    fake.mkdir()
    podman = fake / "podman"
    podman.write_text("#!/bin/sh\nexit 0\n")
    podman.chmod(podman.stat().st_mode | stat.S_IEXEC)
    monkeypatch.setenv("PATH", str(fake))

    ctx = re_mod.materialize(
        {"container": {"image": "img:tag",
                       "run_options": ["-v", "/data:/data"]}},
        lambda k: None, str(tmp_path / "cache"))
    assert ctx.command_prefix[0] == str(podman)
    assert ctx.command_prefix[-1] == "img:tag"
    assert "-v" in ctx.command_prefix

    with pytest.raises(RuntimeError, match="image"):
        re_mod.materialize({"container": {}}, lambda k: None,
                           str(tmp_path / "cache"))


def test_validate_accepts_conda_container():
    from ray_tpu import runtime_env as re_mod

    out = re_mod.validate({"conda": "env1",
                           "container": {"image": "x"}})
    assert out["conda"] == "env1"
