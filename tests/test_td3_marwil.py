"""TD3/DDPG + MARWIL (reference analogs: rllib/algorithms/td3, ddpg,
marwil)."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.rllib import (BC, BCConfig, DDPG, DDPGConfig, JsonWriter,
                           MARWIL, MARWILConfig, TD3, TD3Config)
from ray_tpu.rllib import sample_batch as sb
from ray_tpu.rllib.sample_batch import SampleBatch


class _PointEnv:
    """1-D continuous control: move a point to the origin; reward is
    -|x|.  Optimal policy: a = -x (clipped)."""

    def __init__(self, seed: int = 0):
        import gymnasium as gym

        self.observation_space = gym.spaces.Box(-2.0, 2.0, (1,),
                                                np.float32)
        self.action_space = gym.spaces.Box(-1.0, 1.0, (1,), np.float32)
        self._rng = np.random.RandomState(seed)
        self._x = 0.0
        self._t = 0

    def reset(self, seed=None):
        if seed is not None:
            self._rng = np.random.RandomState(seed)
        self._x = float(self._rng.uniform(-2, 2))
        self._t = 0
        return np.asarray([self._x], np.float32), {}

    def step(self, a):
        self._x = float(np.clip(self._x + float(np.asarray(a).ravel()[0]),
                                -2, 2))
        self._t += 1
        rew = -abs(self._x)
        trunc = self._t >= 30
        return (np.asarray([self._x], np.float32), rew, False, trunc,
                {})

    def close(self):
        pass


@pytest.mark.slow
def test_td3_learns_point_control(ray_start_shared):
    cfg = TD3Config(env=lambda _cfg: _PointEnv(), num_workers=2,
                    rollout_fragment_length=60, train_batch_size=128,
                    train_intensity=24, learning_starts=300,
                    hidden=(64, 64), lr=1e-3, seed=3)
    algo = TD3(cfg)
    reward = -1e9
    for _ in range(30):
        r = algo.train()
        reward = max(reward, r.get("episode_reward_mean", -1e9))
    algo.cleanup()
    # random walk scores ~ -30; a = -x scores ~ -2.5
    assert reward > -12.0, reward


def test_ddpg_config_degrades_td3(ray_start_shared):
    cfg = DDPGConfig(env=lambda _cfg: _PointEnv(), num_workers=1,
                     rollout_fragment_length=40, learning_starts=100,
                     train_intensity=4, hidden=(32,), seed=0)
    assert cfg.smoothing_sigma == 0.0 and cfg.policy_delay == 1
    algo = DDPG(cfg)
    r = algo.train()
    r = algo.train()
    assert np.isfinite(r.get("critic_loss", 0.0))
    algo.cleanup()


def _write_offline_logs(path, n_eps=60, good_frac=0.5, seed=0):
    """Logged episodes on a 3-state chain where action==state earns
    reward; a mix of expert and random behavior so MARWIL's advantage
    weighting has something to exploit."""
    rng = np.random.RandomState(seed)
    with JsonWriter(str(path)) as w:
        for ep in range(n_eps):
            expert = rng.rand() < good_frac
            obs, acts, rews, dones = [], [], [], []
            for t in range(10):
                s = rng.randint(0, 3)
                one_hot = np.zeros(3, np.float32)
                one_hot[s] = 1.0
                a = s if expert else rng.randint(0, 3)
                obs.append(one_hot)
                acts.append(a)
                rews.append(1.0 if a == s else 0.0)
                dones.append(t == 9)
            w.write(SampleBatch({
                sb.OBS: np.asarray(obs, np.float32),
                sb.ACTIONS: np.asarray(acts, np.int64),
                sb.REWARDS: np.asarray(rews, np.float32),
                sb.DONES: np.asarray(dones, bool),
            }))


def test_marwil_beats_bc_on_mixed_data(ray_start_shared, tmp_path):
    """Most of the logged behavior is random: BC imitates the mixture
    (its argmax follows the noisy majority), MARWIL's advantage
    weighting recovers the expert.  Compared head-to-head on the SAME
    logs via each policy's logit margin toward the expert action."""
    log = tmp_path / "logs.json"
    _write_offline_logs(log, good_frac=0.3, seed=4)

    def expert_margin(logits_fn):
        eye = np.eye(3, dtype=np.float32)
        logits = logits_fn(eye)
        correct = logits[np.arange(3), np.arange(3)]
        best_other = np.max(
            logits + np.where(np.eye(3, dtype=bool), -np.inf, 0.0),
            axis=1)
        return float(np.mean(correct - best_other))

    from ray_tpu.rllib.policy import _net_apply

    bc = BC(BCConfig(input_path=str(log), hidden=(32,),
                     sgd_steps_per_iter=150, lr=5e-3, seed=0))
    marwil = MARWIL(MARWILConfig(input_path=str(log), beta=2.0,
                                 hidden=(32,), sgd_steps_per_iter=150,
                                 lr=5e-3, seed=0))
    for _ in range(6):
        bc.train()
        stats = marwil.train()
    assert np.isfinite(stats["vf_loss"])
    m_bc = expert_margin(
        lambda x: np.asarray(_net_apply(bc.params, x)))
    m_marwil = expert_margin(
        lambda x: np.asarray(_net_apply(marwil.params["pi"], x)))
    # MARWIL must recover the expert and do so more decisively than BC
    eye = np.eye(3, dtype=np.float32)
    assert (marwil.compute_actions(eye) == np.arange(3)).all()
    assert m_marwil > m_bc, (m_marwil, m_bc)


def test_marwil_requires_rewards(ray_start_shared, tmp_path):
    log = tmp_path / "logs.json"
    with JsonWriter(str(log)) as w:
        w.write(SampleBatch({
            sb.OBS: np.zeros((4, 3), np.float32),
            sb.ACTIONS: np.zeros(4, np.int64)}))
    with pytest.raises(ValueError, match="rewards"):
        MARWIL(MARWILConfig(input_path=str(log)))


def _log_continuous(path, n=1500, seed=2):
    """Logged transitions on the 1-D point env from a decent behavior
    policy (a = -0.7x + noise) for offline CQL."""
    rng = np.random.RandomState(seed)
    env = _PointEnv(seed=seed)
    with JsonWriter(str(path)) as w:
        obs_l, act_l, rew_l, done_l, next_l = [], [], [], [], []
        o, _ = env.reset(seed=seed)
        for t in range(n):
            a = np.clip(-0.7 * o + 0.3 * rng.randn(1), -1, 1)
            o2, r, term, trunc, _ = env.step(a)
            obs_l.append(o); act_l.append(a.astype(np.float32))
            rew_l.append(r); done_l.append(term); next_l.append(o2)
            o = o2
            if term or trunc:
                o, _ = env.reset()
        w.write(SampleBatch({
            sb.OBS: np.asarray(obs_l, np.float32),
            sb.ACTIONS: np.asarray(act_l, np.float32),
            sb.REWARDS: np.asarray(rew_l, np.float32),
            sb.DONES: np.asarray(done_l, bool),
            sb.NEXT_OBS: np.asarray(next_l, np.float32)}))


def test_cql_trains_offline(ray_start_shared, tmp_path):
    from ray_tpu.rllib import CQL, CQLConfig

    log = tmp_path / "cont.json"
    _log_continuous(log)
    algo = CQL(CQLConfig(input_path=str(log), hidden=(32, 32),
                         sgd_steps_per_iter=100, lr=1e-3, seed=0))
    stats = None
    for _ in range(10):
        stats = algo.train()
    assert np.isfinite(stats["critic_loss"])
    assert np.isfinite(stats["cql_penalty"])
    # the learned deterministic policy pushes the point toward 0
    obs = np.asarray([[1.5], [-1.5]], np.float32)
    acts = algo.compute_actions(obs)
    assert acts[0, 0] < 0 and acts[1, 0] > 0, acts


@pytest.mark.slow
def test_es_improves_cartpole(ray_start_shared):
    from ray_tpu.rllib import ES, ESConfig

    algo = ES(ESConfig(env="CartPole-v1", num_workers=2,
                       population=12, sigma=0.1, lr=0.05,
                       hidden=(16,), seed=3))
    first = algo.train()["es_mean_fitness"]
    best = first
    for _ in range(12):
        best = max(best, algo.train()["es_mean_fitness"])
    algo.cleanup()
    assert best > first + 20, (first, best)
