"""SlateQ (slate recommendation) and ApexDDPG (async continuous
off-policy).

Reference analogs: rllib/algorithms/slateq and
rllib/algorithms/apex_ddpg — learning checks follow the
check_learning_achieved pattern scaled to CI
(rllib/utils/test_utils.py:480).
"""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.rllib import (ApexDDPG, ApexDDPGConfig, SlateQ,
                           SlateQConfig)


class _RecEnv:
    """Recsim-style slate env: hidden user taste w; v*(d) = exp(w·d)
    drives a conditional-logit click among the slate + null; reward =
    the clicked doc's quality (last feature).  Learning the choice
    model AND ranking by v·q beats random slates by a wide margin."""

    LEN = 10
    N_DOCS = 12
    DOC_DIM = 4

    def __init__(self, seed=0):
        self._rng = np.random.RandomState(seed)
        self._w = np.asarray([1.5, -1.0, 0.5])

    def _draw(self):
        docs = self._rng.randn(self.N_DOCS,
                               self.DOC_DIM).astype(np.float32)
        return {"user": np.asarray([1.0], np.float32),
                "docs": docs}

    def reset(self, seed=None):
        if seed is not None:
            self._rng = np.random.RandomState(seed)
        self._t = 0
        self._obs = self._draw()
        return self._obs, {}

    def step(self, slate):
        docs = self._obs["docs"]
        slate = np.asarray(slate, int)
        v = np.exp(docs[slate, :3] @ self._w)
        null = 1.0
        p = np.concatenate([v, [null]])
        p = p / p.sum()
        pick = self._rng.choice(len(p), p=p)
        if pick < len(slate):
            click = int(slate[pick])
            r = float(docs[click, 3])           # quality feature
        else:
            click, r = -1, 0.0
        self._t += 1
        done = self._t >= self.LEN
        self._obs = self._draw()
        return self._obs, r, done, False, {"click": click}


def test_slateq_learns_to_rank(ray_start_shared):
    cfg = SlateQConfig(env=lambda _: _RecEnv(), num_workers=1,
                       slate_size=2, hidden=(32,), embed=16, lr=3e-3,
                       buffer_size=10_000, learning_starts=300,
                       train_batch_size=64, train_intensity=8,
                       target_update_freq=400, epsilon_decay_steps=2500,
                       steps_per_sample=250, gamma=0.0, seed=0)
    algo = SlateQ(cfg)
    first = None
    best = -np.inf
    try:
        for i in range(25):
            result = algo.train()
            mean = result.get("episode_reward_mean", -np.inf)
            if i == 0:
                first = mean
            best = max(best, mean)
            if best >= 6.0:
                break
    finally:
        algo.stop()
    # random slates average ~1.5/episode on this env; ranking by
    # v·quality roughly triples it
    assert best > first, (first, best)
    assert best >= 3.5, (first, best)


def test_slateq_greedy_slate_ranks_by_v_times_q():
    from ray_tpu.rllib.slateq import SlateQPolicy, SlateQSpec
    import jax.numpy as jnp
    from ray_tpu.rllib.models import mlp_apply

    spec = SlateQSpec(user_dim=2, doc_dim=3, n_docs=6, slate_size=2,
                      hidden=(8,), embed=4)
    pol = SlateQPolicy(spec, seed=0)
    rng = np.random.RandomState(0)
    user = rng.randn(2).astype(np.float32)
    docs = rng.randn(6, 3).astype(np.float32)
    slate = np.asarray(pol._greedy(pol.params, user, docs))
    # recompute the ranking from the towers directly
    eu = np.asarray(mlp_apply(pol.params["u_tower"], jnp.asarray(user),
                              final_linear=True))
    ed = np.asarray(mlp_apply(pol.params["d_tower"], jnp.asarray(docs),
                              final_linear=True))
    v = np.exp(np.clip(ed @ eu, -10, 10))
    both = np.concatenate(
        [np.tile(user, (6, 1)), docs], axis=-1)
    q = np.asarray(mlp_apply(pol.params["q"], jnp.asarray(both),
                             final_linear=True))[..., 0]
    want = np.argsort(-(v * q))[:2]
    np.testing.assert_array_equal(np.sort(slate), np.sort(want))


class _PointEnv:
    def __init__(self, seed=0):
        import gymnasium as gym

        self.observation_space = gym.spaces.Box(-2.0, 2.0, (1,),
                                                np.float32)
        self.action_space = gym.spaces.Box(-1.0, 1.0, (1,), np.float32)
        self._rng = np.random.RandomState(seed)
        self._x = 0.0
        self._t = 0

    def reset(self, seed=None):
        if seed is not None:
            self._rng = np.random.RandomState(seed)
        self._x = float(self._rng.uniform(-2, 2))
        self._t = 0
        return np.asarray([self._x], np.float32), {}

    def step(self, a):
        self._x = float(np.clip(
            self._x + float(np.asarray(a).ravel()[0]), -2, 2))
        self._t += 1
        return (np.asarray([self._x], np.float32), -abs(self._x),
                False, self._t >= 30, {})

    def close(self):
        pass


def test_apex_ddpg_sigma_ladder():
    cfg = ApexDDPGConfig(obs_dim=1, action_dim=1, num_workers=3,
                         expl_sigma=0.1, ladder_base=4.0)
    # ladder spans expl_sigma .. expl_sigma*base, increasing
    n = cfg.num_workers
    sigmas = [cfg.expl_sigma * cfg.ladder_base ** (i / (n - 1))
              for i in range(n)]
    assert sigmas[0] == pytest.approx(0.1)
    assert sigmas[-1] == pytest.approx(0.4)
    assert sigmas == sorted(sigmas)


@pytest.mark.slow
def test_apex_ddpg_learns_point_control(ray_start_shared):
    cfg = ApexDDPGConfig(env=lambda _cfg: _PointEnv(), num_workers=2,
                         rollout_fragment_length=60,
                         train_batch_size=128, train_intensity=24,
                         learning_starts=300, updates_per_iter=2,
                         hidden=(64, 64), lr=1e-3, seed=3)
    algo = ApexDDPG(cfg)
    reward = -1e9
    for _ in range(30):
        r = algo.train()
        reward = max(reward, r.get("episode_reward_mean", -1e9))
    algo.cleanup()
    assert reward > -12.0, reward
