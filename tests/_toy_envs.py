"""Shared deterministic toy envs for algorithm learning tests."""

import numpy as np


class _Space:
    def __init__(self, shape=None, n=None):
        self.shape = shape
        self.n = n


class ContextFlipEnv:
    """Deterministic: obs is a one-hot side bit; acting on the side
    yields +1 and flips it.  Dynamics and reward are exactly
    representable by small models — used by the model-based learning
    gates (MBMPO, Dreamer)."""

    def __init__(self, seed=0, horizon=10):
        self.observation_space = _Space(shape=(2,))
        self.action_space = _Space(n=2)
        self.horizon = horizon
        self._rng = np.random.RandomState(seed)

    def reset(self, seed=None, options=None):
        if seed is not None:
            self._rng = np.random.RandomState(seed)
        self._side = self._rng.randint(2)
        self._t = 0
        return self._obs(), {}

    def _obs(self):
        o = np.zeros(2, np.float32)
        o[self._side] = 1.0
        return o

    def step(self, a):
        r = 1.0 if int(a) == self._side else 0.0
        self._side = 1 - self._side
        self._t += 1
        return self._obs(), r, self._t >= self.horizon, False, {}

    def close(self):
        pass
