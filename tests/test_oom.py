"""OOM defense: memory monitor + retriable-LIFO worker killing.

Reference analogs: src/ray/common/memory_monitor.h:48 (node memory
polling), src/ray/raylet/worker_killing_policy.h:30,58 (retriable-LIFO
victim selection), exercised here the way the reference's
worker_killing_policy_test.cc does (policy unit tests) plus an
end-to-end kill-and-retry run driven through the fake-usage test hook.
"""

import os
import time

import pytest

import ray_tpu
from ray_tpu._private.node_manager import pick_oom_victim

pytestmark = pytest.mark.fast


class FakeWorker:
    def __init__(self, state, started_at, lease_id=0):
        self.state = state
        self.started_at = started_at
        self.lease_id = lease_id


def test_policy_prefers_retriable_then_lifo():
    # LIFO for tasks keys on lease order, not process start: a reused
    # idle worker (old started_at) holding the newest lease dies first
    task_newest_lease = FakeWorker("leased", 1.0, lease_id=7)
    task_old_lease = FakeWorker("leased", 9.0, lease_id=3)
    actor_new = FakeWorker("actor", 3.0)
    idle = FakeWorker("idle", 4.0)
    assert pick_oom_victim(
        [task_old_lease, task_newest_lease, actor_new, idle]
    ) is task_newest_lease
    # actors only die when no leased task workers remain
    assert pick_oom_victim([actor_new, idle]) is actor_new
    # idle/starting workers are never OOM victims
    assert pick_oom_victim([idle]) is None
    assert pick_oom_victim([]) is None


@pytest.fixture
def oom_cluster(tmp_path):
    usage_path = str(tmp_path / "fake_usage")
    with open(usage_path, "w") as f:
        f.write("0.10")
    ray_tpu.init(num_cpus=4, object_store_memory=128 * 1024 * 1024,
                 _system_config={
                     "memory_usage_threshold": 0.9,
                     "memory_monitor_interval_s": 0.1,
                     "memory_monitor_fake_usage_path": usage_path,
                 })
    yield usage_path
    ray_tpu.shutdown()


def test_oom_kill_retries_task(oom_cluster, tmp_path):
    """A task hogging memory is killed when usage crosses the threshold
    and succeeds on retry once pressure is gone."""
    usage_path = oom_cluster
    marker = str(tmp_path / "attempt_marker")

    @ray_tpu.remote(max_retries=2)
    def hog(marker_path):
        if not os.path.exists(marker_path):
            # first attempt: simulate the allocation that caused the
            # pressure, then block until the monitor kills us
            with open(marker_path, "w") as f:
                f.write("1")
            time.sleep(60)
        return "retried-ok"

    ref = hog.remote(marker)
    # wait for attempt 1 to be running, then raise reported memory usage
    deadline = time.time() + 20
    while not os.path.exists(marker) and time.time() < deadline:
        time.sleep(0.05)
    assert os.path.exists(marker), "first attempt never started"
    with open(usage_path, "w") as f:
        f.write("0.99")
    # drop pressure shortly after so the retry isn't killed too; the
    # monitor's post-kill pause gives us a window
    time.sleep(0.8)
    with open(usage_path, "w") as f:
        f.write("0.10")
    assert ray_tpu.get(ref, timeout=60) == "retried-ok"


def test_oom_kill_restarts_actor(oom_cluster):
    """With no leased task workers, the newest actor is killed and its
    max_restarts policy brings it back."""
    usage_path = oom_cluster

    @ray_tpu.remote(max_restarts=2)
    class Counter:
        def __init__(self):
            self.n = 0

        def bump(self):
            self.n += 1
            return self.n

        def pid(self):
            return os.getpid()

    c = Counter.remote()
    assert ray_tpu.get(c.bump.remote()) == 1
    pid1 = ray_tpu.get(c.pid.remote())

    with open(usage_path, "w") as f:
        f.write("0.99")
    time.sleep(0.8)
    with open(usage_path, "w") as f:
        f.write("0.10")

    # restarted actor loses state (reference semantics: constructor
    # re-runs) and lives in a fresh process
    deadline = time.time() + 60
    while time.time() < deadline:
        try:
            pid2 = ray_tpu.get(c.pid.remote(), timeout=30)
            if pid2 != pid1:
                break
        except Exception:
            time.sleep(0.2)
    else:
        pytest.fail("actor was not OOM-killed/restarted")
    assert ray_tpu.get(c.bump.remote()) == 1


def test_pick_tpu_chips_prefers_contiguous_runs():
    """ICI-aware chip selection: contiguous runs win, best-fit keeps
    large runs intact, fragmented pools fall back to lowest indices."""
    from ray_tpu._private.node_manager import pick_tpu_chips

    # free = two runs: [0..3] and [6..7]; need 2 -> take the SMALL run
    assert pick_tpu_chips([0, 1, 2, 3, 6, 7], 2) == [6, 7]
    # need 4 -> only the big run fits
    assert pick_tpu_chips([0, 1, 2, 3, 6, 7], 4) == [0, 1, 2, 3]
    # fragmented: no run of 3 -> lowest indices
    assert pick_tpu_chips([0, 2, 4, 6], 3) == [0, 2, 4]
    # single chip: endpoint of the smallest run, so contiguous runs
    # stay intact for future multi-chip grants
    assert pick_tpu_chips([5, 1], 1) == [1]
    assert pick_tpu_chips([0, 1, 2, 3, 7], 1) == [7]
    assert pick_tpu_chips([0, 1, 2, 3], 1) == [3]
    # unsorted input handled
    assert pick_tpu_chips([7, 6, 3, 2, 1, 0], 2) == [6, 7]
    assert pick_tpu_chips([], 0) == []
