"""BlockPager unit tests: the host-side allocator/prefix-index under
the paged KV cache (serve/kv_pager.py).  Pure host logic — no jax, no
device arrays — so these pin the subsystem's bookkeeping invariants
(refcounts, LRU eviction, COW forks, content-addressed matching)
independently of the decode kernels that consume the block ids."""

import pytest

from ray_tpu.serve.kv_pager import BlockPager


def _pager(num_blocks=9, block_size=4, max_seq=16):
    return BlockPager(num_blocks, block_size, max_seq)


def test_constructor_validates_geometry():
    with pytest.raises(ValueError, match="multiple"):
        BlockPager(9, block_size=5, max_seq=16)
    with pytest.raises(ValueError, match="full"):
        # needs 4 blocks + null = 5 minimum
        BlockPager(4, block_size=4, max_seq=16)


def test_allocate_release_roundtrip_and_refcounts():
    p = _pager()
    assert p.blocks_free == 8          # block 0 reserved
    blocks = p.allocate(3)
    assert len(blocks) == 3
    assert 0 not in blocks             # null block never allocated
    assert p.blocks_in_use == 3 and p.blocks_free == 5
    p.release(blocks)
    assert p.blocks_in_use == 0 and p.blocks_free == 8
    # double release must blow up, not corrupt the free list
    with pytest.raises(ValueError, match="unallocated"):
        p.release([blocks[0]])


def test_allocate_exhaustion_returns_none_and_allocates_nothing():
    p = _pager()
    assert p.allocate(9) is None       # > 8 available
    assert p.blocks_free == 8          # nothing leaked
    got = p.allocate(8)
    assert len(got) == 8
    assert p.allocate(1) is None
    p.release(got[:1])
    assert p.allocate(1) is not None   # recycled after release


def test_match_prefix_exact_block_aligned_and_capped():
    p = _pager()
    prompt = list(range(10, 22))       # 12 tokens = 3 blocks of 4
    blocks = p.allocate(3)
    p.register_prefix(prompt, blocks)
    p.release(blocks)                  # park in the cached pool
    assert p.blocks_cached == 3

    # identical prompt: full match but capped at n-1 -> 2 full blocks
    # of prefix (8 tokens <= 11) plus the boundary block
    n, matched = p.match_prefix(prompt)
    assert matched == blocks
    assert n == 11                     # len(prompt) - 1 cap
    p.release(matched)

    # longer prompt extending the prefix: all 3 blocks reusable
    n, matched = p.match_prefix(prompt + [99, 98])
    assert matched == blocks and n == 12
    p.release(matched)

    # diverging in the middle of block 2: only block 1 matches
    div = prompt[:5] + [777] + prompt[6:]
    n, matched = p.match_prefix(div)
    assert matched == blocks[:1] and n == 4
    p.release(matched)

    # content addressing: unrelated tokens match nothing
    n, matched = p.match_prefix([1, 2, 3, 4, 5])
    assert matched == [] and n == 0


def test_match_revives_cached_blocks_and_shares_refcounts():
    p = _pager()
    prompt = list(range(8))            # 2 full blocks
    blocks = p.allocate(2)
    p.register_prefix(prompt, blocks)
    # still live (ref 1) — a second matcher shares via refcount
    _, m1 = p.match_prefix(prompt + [50, 51, 52, 53])
    assert m1 == blocks
    p.release(blocks)                  # original owner retires
    assert p.blocks_cached == 0        # still referenced by matcher
    p.release(m1)
    assert p.blocks_cached == 2        # now parked, not freed


def test_lru_eviction_prefers_coldest_prefix():
    p = _pager(num_blocks=6, block_size=4, max_seq=16)  # 5 usable
    a, b = p.allocate(1), p.allocate(1)
    p.register_prefix([1, 2, 3, 4], a)
    p.register_prefix([5, 6, 7, 8], b)
    p.release(a)                       # a is LRU (parked first)
    p.release(b)
    got = p.allocate(4)                # free list has 3 -> evict 1
    assert len(got) == 4 and p.evictions == 1
    assert a[0] in got                 # the colder prefix went
    # evicted key must not match any more (index deregistered)
    n, matched = p.match_prefix([1, 2, 3, 4, 9])
    assert matched == [] and n == 0
    # b's key survived
    n, matched = p.match_prefix([5, 6, 7, 8, 9])
    assert matched == b
    p.release(matched)
    p.release(got)


def test_ensure_private_cow_semantics():
    p = _pager()
    prompt = list(range(4))
    blocks = p.allocate(1)

    # sole referent + unregistered: write in place, no fork
    blk, src = p.ensure_private(blocks[0])
    assert blk == blocks[0] and src is None and p.cow_copies == 0

    # registered block: fork even at refcount 1 (its content is a
    # promise to future matchers)
    p.register_prefix(prompt, blocks)
    blk, src = p.ensure_private(blocks[0])
    assert blk != blocks[0] and src == blocks[0]
    assert p.cow_copies == 1
    # our ref moved to the fork; the original parked in the cache
    assert p.blocks_cached == 1
    p.release([blk])

    # shared block (ref 2): second owner's write forks too
    _, m = p.match_prefix(prompt + [9])
    assert m == blocks
    _, m2 = p.match_prefix(prompt + [7])
    blk2, src2 = p.ensure_private(m2[0])
    assert blk2 != m2[0] and src2 == m2[0] and p.cow_copies == 2
    p.release([blk2])
    p.release(m)


def test_ensure_private_raises_when_pool_exhausted():
    p = _pager(num_blocks=5, block_size=4, max_seq=16)  # 4 usable
    blocks = p.allocate(4)
    p.register_prefix([1, 2, 3, 4], blocks[:1])
    with pytest.raises(MemoryError):
        p.ensure_private(blocks[0])


def test_register_prefix_first_writer_wins():
    p = _pager()
    prompt = [1, 2, 3, 4]
    a = p.allocate(1)
    b = p.allocate(1)
    p.register_prefix(prompt, a)
    p.register_prefix(prompt, b)       # duplicate content: ignored
    _, matched = p.match_prefix(prompt + [9])
    assert matched == a
    p.release(matched)
    p.release(a)
    p.release(b)
    # b was never indexed, so its release frees it outright
    assert p.blocks_cached == 1


def test_prefix_keys_export_content_and_counter():
    p = _pager()
    assert p.prefix_keys() == []       # empty index, no keys
    a = p.allocate(1)
    b = p.allocate(1)
    p.register_prefix([1, 2, 3, 4], a)
    p.register_prefix([5, 6, 7, 8], b)
    keys = p.prefix_keys()
    assert sorted(keys) == [(1, 2, 3, 4), (5, 6, 7, 8)]
    # every exported key is hashable router material
    assert all(isinstance(k, tuple) for k in keys)
    # the export counter accumulates per call (0 + 2 + 2)
    assert p.prefix_keys_exported == 2
    p.prefix_keys()
    assert p.prefix_keys_exported == 4
    s = p.stats()
    assert s["prefix_keys_resident"] == 2
    assert s["prefix_keys_exported"] == 4
    p.release(a)
    p.release(b)


def test_prefix_keys_track_eviction_and_deregistration():
    p = _pager(num_blocks=6, block_size=4, max_seq=16)  # 5 usable
    a, b = p.allocate(1), p.allocate(1)
    p.register_prefix([1, 2, 3, 4], a)
    p.register_prefix([5, 6, 7, 8], b)
    p.release(a)
    p.release(b)
    got = p.allocate(4)                # evicts the colder prefix (a)
    assert p.evictions == 1
    assert p.prefix_keys() == [(5, 6, 7, 8)]
    p.release(got)


def test_stats_shape_and_hit_rate():
    p = _pager()
    prompt = list(range(8))
    blocks = p.allocate(2)
    p.register_prefix(prompt, blocks)
    p.release(blocks)
    p.match_prefix(prompt + [30, 31, 32, 33])   # 2 hits, 1 miss
    s = p.stats()
    assert s["prefix_block_hits"] == 2
    assert s["prefix_block_misses"] == 1
    assert s["prefix_hit_rate"] == pytest.approx(2 / 3, abs=1e-3)
    for key in ("num_blocks", "block_size", "blocks_in_use",
                "blocks_cached", "blocks_free", "cow_copies",
                "evictions"):
        assert key in s
