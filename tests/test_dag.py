"""Lazy DAG API (reference: python/ray/dag — DAGNode/bind/InputNode) +
ray_tpu.client() builder + MedianStoppingRule.
"""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.dag import InputNode


def test_function_dag_diamond(ray_start_shared):
    calls = []

    @ray_tpu.remote
    def source(x):
        return x + 1

    @ray_tpu.remote
    def left(s):
        return s * 2

    @ray_tpu.remote
    def right(s):
        return s * 3

    @ray_tpu.remote
    def join(a, b):
        return a + b

    with InputNode() as inp:
        s = source.bind(inp)
        dag = join.bind(left.bind(s), right.bind(s))

    # (x+1)*2 + (x+1)*3 = 5x + 5
    assert ray_tpu.get(dag.execute(4)) == 25
    # re-executable with new input
    assert ray_tpu.get(dag.execute(0)) == 5


def test_shared_node_executes_once(ray_start_shared):
    @ray_tpu.remote
    def effect(x):
        import os
        import tempfile

        # count executions via the filesystem (workers are separate
        # processes)
        with open(x, "a") as f:
            f.write("1")
        return x

    @ray_tpu.remote
    def reader(p1, p2):
        with open(p1) as f:
            return f.read()

    import tempfile

    with tempfile.NamedTemporaryFile(suffix=".cnt", delete=False) as tf:
        path = tf.name
    shared = effect.bind(path)
    dag = reader.bind(shared, shared)
    assert ray_tpu.get(dag.execute()) == "1"  # one execution, not two


def test_actor_dag(ray_start_shared):
    @ray_tpu.remote
    class Accum:
        def __init__(self, start):
            self.total = start

        def add(self, x):
            self.total += x
            return self.total

    with InputNode() as inp:
        acc = Accum.bind(10)
        dag = acc.add.bind(inp)

    assert ray_tpu.get(dag.execute(5)) == 15
    # each execute() creates a fresh actor per reference semantics
    assert ray_tpu.get(dag.execute(7)) == 17


def test_kwargs_and_nested_containers(ray_start_shared):
    @ray_tpu.remote
    def f(x):
        return x * 10

    @ray_tpu.remote
    def g(items, scale=1):
        return sum(items) * scale

    dag = g.bind([f.bind(1), f.bind(2)], scale=2)
    assert ray_tpu.get(dag.execute()) == 60


def test_median_stopping_rule():
    from ray_tpu.tune import MedianStoppingRule
    from ray_tpu.tune.schedulers import CONTINUE, STOP

    class T:
        def __init__(self, tid):
            self.trial_id = tid
            self.iteration = 0

    rule = MedianStoppingRule(metric="score", mode="max",
                              grace_period=1, min_samples_required=2)
    good, bad, mid = T("good"), T("bad"), T("mid")
    # build history: good reports high, mid middling, bad low
    for step in range(1, 4):
        assert rule.on_trial_result(good, {"score": 10.0 * step}) \
            == CONTINUE
        rule.on_trial_result(mid, {"score": 5.0})
        decision = rule.on_trial_result(bad, {"score": 0.1})
    assert decision == STOP
    # the good trial is never stopped
    assert rule.on_trial_result(good, {"score": 40.0}) == CONTINUE
