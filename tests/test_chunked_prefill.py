"""Chunked streaming prefill: long prompts admitted as block-sized
chunks interleaved with decode waves (``prefill_chunk_tokens``).

The correctness oracle is unchanged from the rest of the paged suite:
dense solo greedy ``generate``.  Chunking only re-schedules *when*
prompt tokens are written into KV blocks — each chunk is the existing
``paged_prefill`` program with ``prefix_len`` = tokens already filled
— so every continuation must stay bit-identical to the one-shot path,
cold and with a resident shared prefix, with and without speculative
decoding, for both decoder families.

The acceptance test is the headline: under a two-tenant mix where
long batch prompts land ahead of short interactive ones, enabling
chunking must make the interactive tenant's p99 TTFT strictly lower
than the one-shot run of the same workload (shapes pre-compiled by a
warmup tenant so the comparison measures scheduling, not XLA).
"""

import asyncio

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from ray_tpu.serve.llm import SpecConfig, build_llm_deployment  # noqa: E402
from ray_tpu.serve.telemetry import CRITICAL_PATH_COMPONENTS  # noqa: E402

MAX_NEW = 6
CHUNK = 32
_OVR = {"dtype": jnp.float32, "use_flash": False, "remat": False}

#: mixed lengths around the chunk boundary: 70 -> 3 chunks (32/32/6),
#: 9 -> not chunked, 100 -> 4 chunks, 33 -> 2 chunks (32/1)
_LENGTHS = (70, 9, 100, 33)


def _build(family="gpt2", chunk=CHUNK, **kw):
    kw.setdefault("max_new_tokens", MAX_NEW)
    kw.setdefault("temperature", 0.0)
    kw.setdefault("scheduler", "continuous")
    kw.setdefault("kv_layout", "paged")
    kw.setdefault("kv_block_size", 16)
    kw.setdefault("prefill_bucket", 16)
    kw.setdefault("max_slots", 4)
    kw.setdefault("config_overrides", _OVR)
    return build_llm_deployment(family, "nano",
                                prefill_chunk_tokens=chunk, **kw)


def _prompts(seed=0, lengths=_LENGTHS):
    rng = np.random.RandomState(seed)
    return [rng.randint(2, 500, size=n).astype(np.int32)
            for n in lengths]


def _drive(dep, prompts, *, sequential=False):
    """Run prompts on a fresh engine; returns (outs, stats, records)."""
    async def main():
        inst = dep.func_or_class()
        try:
            if sequential:
                outs = [await inst(p) for p in prompts]
            else:
                outs = await asyncio.gather(*[inst(p) for p in prompts])
            stats = inst.engine_stats()
            recs = inst.trace_records()
        finally:
            inst.shutdown_engine()
        return [np.asarray(o) for o in outs], stats, recs

    return asyncio.run(main())


def _oracle(family, prompt, max_new=MAX_NEW):
    """Dense solo greedy continuation — the parity reference."""
    if family == "gpt2":
        from ray_tpu.models import gpt2_config, gpt2_init
        from ray_tpu.models.gpt2_decode import generate
        cfg = gpt2_config("nano", **_OVR)
        params = gpt2_init(jax.random.PRNGKey(0), cfg)
    else:
        from ray_tpu.models import llama_config, llama_init
        from ray_tpu.models.llama_decode import llama_generate \
            as generate
        cfg = llama_config("nano", **_OVR)
        params = llama_init(jax.random.PRNGKey(0), cfg)
    out = generate(params, jnp.asarray(np.asarray(prompt)[None]), cfg,
                   max_new_tokens=max_new, temperature=0.0)
    return np.asarray(out)[0]


# ---------------------------------------------------------------------------
# bitwise parity: cold, resident prefix, spec decode, both families
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("family", ["gpt2", "llama"])
def test_chunked_cold_prompts_match_dense_solo(family):
    prompts = _prompts()
    outs, stats, _recs = _drive(_build(family), prompts)
    for p, out in zip(prompts, outs):
        np.testing.assert_array_equal(out, _oracle(family, p))
    pc = stats["prefill_chunks"]
    assert pc["requests"] == 3          # the 9-token prompt one-shots
    assert pc["chunks"] == 9            # 3 + 4 + 2
    assert pc["tokens"] == 70 + 100 + 33
    assert pc["max_chunks_per_request"] == 4


@pytest.mark.parametrize("family", ["gpt2", "llama"])
def test_chunked_resident_prefix_matches_dense_solo(family):
    """The second request reuses the first's registered prefix blocks,
    so its ChunkCursor starts at filled=32 — fewer chunks, same bits."""
    rng = np.random.RandomState(7)
    shared = rng.randint(2, 500, 32)
    a = np.concatenate([shared, rng.randint(2, 500, 40)]).astype(np.int32)
    b = np.concatenate([shared, rng.randint(2, 500, 38)]).astype(np.int32)

    outs, stats, _recs = _drive(_build(family), [a, b],
                                sequential=True)
    np.testing.assert_array_equal(outs[0], _oracle(family, a))
    np.testing.assert_array_equal(outs[1], _oracle(family, b))
    assert stats["kv_cache"]["prefix_block_hits"] >= 2
    pc = stats["prefill_chunks"]
    assert pc["requests"] == 2
    # A (72 cold) chunks 32/32/8; B fills only its 38-token tail
    assert pc["chunks"] == 5
    assert pc["tokens"] == 72 + 38


@pytest.mark.parametrize("family", ["gpt2", "llama"])
def test_chunked_spec_decode_matches_dense_solo(family):
    """Greedy ngram spec decoding over chunked admissions: rollback
    still reproduces the dense argmax stream bit-for-bit."""
    prompts = _prompts(seed=3, lengths=(70, 33))
    dep = _build(family, spec_decode=SpecConfig(draft="ngram", k=2))
    outs, stats, _recs = _drive(dep, prompts)
    for p, out in zip(prompts, outs):
        np.testing.assert_array_equal(out, _oracle(family, p))
    assert stats["prefill_chunks"]["requests"] == 2
    assert stats["spec"]["rounds"] > 0


def test_chunk_equal_to_prompt_stays_one_shot():
    """Prompts at or under the chunk budget take the legacy admission
    path: zero chunk counters, identical outputs."""
    prompts = _prompts(seed=5, lengths=(32, 16, 9))
    outs, stats, _recs = _drive(_build(), prompts)
    for p, out in zip(prompts, outs):
        np.testing.assert_array_equal(out, _oracle("gpt2", p))
    assert stats["prefill_chunks"] == {
        "requests": 0, "chunks": 0, "tokens": 0,
        "max_chunks_per_request": 0}


# ---------------------------------------------------------------------------
# telemetry: critical-path decomposition over chunked records
# ---------------------------------------------------------------------------

def test_chunked_critical_path_sums_and_splits_wait():
    outs, _stats, recs = _drive(_build(), _prompts())
    assert len(outs) == 4
    chunked = [r for r in recs if r.get("prefill_chunks")]
    assert len(chunked) == 3
    for r in recs:
        cp = r["critical_path"]
        comp_sum = sum(cp[k] for k in CRITICAL_PATH_COMPONENTS)
        # live clocks: each component rounds to 4 decimals
        assert comp_sum == pytest.approx(cp["e2e_ms"], abs=1e-2)
    for r in chunked:
        cp = r["critical_path"]
        # chunk windows never exceed the admit -> first-token window
        assert cp["prefill_ms"] >= 0.0
        assert cp["prefill_wait_ms"] >= 0.0
    for r in recs:
        if not r.get("prefill_chunks"):
            assert r["critical_path"]["prefill_wait_ms"] == 0.0


# ---------------------------------------------------------------------------
# construction-time validation
# ---------------------------------------------------------------------------

def test_chunking_requires_paged_layout():
    with pytest.raises(ValueError, match="paged"):
        build_llm_deployment(
            "gpt2", "nano", scheduler="continuous", kv_layout="dense",
            prefill_chunk_tokens=32, config_overrides=_OVR)


@pytest.mark.parametrize("bad", [0, -16, 24])
def test_chunk_tokens_must_be_positive_block_multiple(bad):
    with pytest.raises(ValueError, match="multiple"):
        build_llm_deployment(
            "gpt2", "nano", scheduler="continuous", kv_layout="paged",
            kv_block_size=16, prefill_chunk_tokens=bad,
            config_overrides=_OVR)


# ---------------------------------------------------------------------------
# perfledger: the per-tenant TTFT series trend lower-is-better
# ---------------------------------------------------------------------------

def test_perfledger_tenant_ttft_direction_and_fields():
    from ray_tpu.tools.perfledger import (_SWEEP_FIELDS,
                                          higher_is_better)

    assert "interactive_ttft_ms_p99" in _SWEEP_FIELDS
    assert "batch_ttft_ms_p99" in _SWEEP_FIELDS
    assert higher_is_better("interactive_ttft_ms_p99") is False
    assert higher_is_better("batch_ttft_ms_p99") is False
    # the attainment fractions keep their higher-is-better override
    assert higher_is_better("interactive_ttft_slo_attainment") is True


# ---------------------------------------------------------------------------
# acceptance: chunking strictly improves interactive p99 TTFT
# ---------------------------------------------------------------------------

_LONG = 96           # 3 exact chunks of 32; bucket 96 when one-shot
_N_LONG, _N_SHORT = 6, 4


def _ab_ttft(chunk):
    """Run the two-tenant mix on one engine: warmup compiles every
    prefill shape this configuration uses (under a tenant excluded
    from the measurement), then the measured phase enqueues all longs
    ahead of all shorts."""
    dep = _build(chunk=chunk, max_slots=_N_LONG + _N_SHORT,
                 max_new_tokens=4)
    rng = np.random.RandomState(17)
    longs = [rng.randint(2, 500, _LONG).astype(np.int32)
             for _ in range(_N_LONG)]
    shorts = [rng.randint(2, 500, 10).astype(np.int32)
              for _ in range(_N_SHORT)]
    warm_long = rng.randint(2, 500, _LONG).astype(np.int32)
    warm_short = rng.randint(2, 500, 10).astype(np.int32)

    async def main():
        inst = dep.func_or_class()
        try:
            await inst(warm_long, tenant="warmup")
            await inst(warm_short, tenant="warmup")
            tasks = [asyncio.ensure_future(inst(p, tenant="batch"))
                     for p in longs]
            await asyncio.sleep(0)       # longs enqueue first
            tasks += [asyncio.ensure_future(
                inst(p, tenant="interactive")) for p in shorts]
            await asyncio.gather(*tasks)
            return inst.engine_stats()
        finally:
            inst.shutdown_engine()

    stats = asyncio.run(main())
    tnt = stats["latency_anatomy"]["by_tenant"]
    assert tnt["interactive"]["requests"] == _N_SHORT
    assert tnt["batch"]["requests"] == _N_LONG
    return stats, tnt["interactive"]["ttft_ms"]["p99"]


def test_interactive_ttft_p99_strictly_lower_with_chunking():
    """One-shot admission runs each long prompt's full prefill inline
    before later queue pops, so the short interactive prompts behind
    six 96-token prefills inherit all of them in their TTFT; chunked
    admission defers that work into decode-interleaved chunks and the
    shorts admit almost immediately."""
    stats_off, p99_off = _ab_ttft(None)
    stats_on, p99_on = _ab_ttft(CHUNK)
    assert stats_off["prefill_chunks"]["requests"] == 0
    # warmup long + 6 measured longs all chunk
    assert stats_on["prefill_chunks"]["requests"] == _N_LONG + 1
    assert p99_on < p99_off, (p99_on, p99_off)
