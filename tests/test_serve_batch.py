"""@serve.batch + async actor methods.

Reference analogs: python/ray/serve/batching.py (@serve.batch) and
async actors (core_worker fibers, fiber.h:17) — here a shared user
event loop per worker so concurrent coroutine invocations interleave.
"""

import asyncio
import time

import pytest

import ray_tpu
from ray_tpu import serve


def test_async_actor_methods_interleave(ray_start_shared):
    @ray_tpu.remote(max_concurrency=4)
    class Gate:
        def __init__(self):
            self.ev = asyncio.Event()

        async def wait_open(self):
            await self.ev.wait()
            return "opened"

        async def open(self):
            self.ev.set()
            return True

    g = Gate.remote()
    blocked = g.wait_open.remote()
    # wait_open parks on the event INSIDE the shared loop; open() must
    # still get through (interleaving, not thread-blocking)
    assert ray_tpu.get(g.open.remote(), timeout=10)
    assert ray_tpu.get(blocked, timeout=10) == "opened"


def test_serve_batch_collects(ray_start_shared):
    @serve.deployment
    class Model:
        def __init__(self):
            self.batch_sizes = []

        @serve.batch(max_batch_size=4, batch_wait_timeout_s=0.2)
        async def __call__(self, xs):
            self.batch_sizes.append(len(xs))
            return [x * 10 for x in xs]

        async def seen(self):
            return self.batch_sizes

    handle = serve.run(
        Model.options(max_concurrent_queries=16).bind())
    try:
        refs = [handle.remote(i) for i in range(8)]
        vals = sorted(ray_tpu.get(refs, timeout=60))
        assert vals == [i * 10 for i in range(8)]
        sizes = ray_tpu.get(handle.method("seen").remote(), timeout=30)
        # at least one real batch formed (scheduling jitter tolerated)
        assert max(sizes) >= 2, sizes
        assert sum(sizes) == 8
    finally:
        serve.shutdown()


def test_serve_batch_error_propagates(ray_start_shared):
    @serve.deployment
    class Bad:
        @serve.batch(max_batch_size=4, batch_wait_timeout_s=0.05)
        async def __call__(self, xs):
            raise ValueError("batch exploded")

    handle = serve.run(Bad.options(max_concurrent_queries=8).bind())
    try:
        with pytest.raises(Exception, match="batch exploded"):
            ray_tpu.get(handle.remote(1), timeout=30)
    finally:
        serve.shutdown()


def test_async_actor_default_concurrency(ray_start_shared):
    """Actors with coroutine methods interleave WITHOUT explicit
    max_concurrency (reference: async actors default to high
    concurrency; sync actors stay strictly serial)."""

    @ray_tpu.remote
    class Gate:
        def __init__(self):
            self.ev = asyncio.Event()

        async def wait_open(self):
            await self.ev.wait()
            return "opened"

        async def open(self):
            self.ev.set()
            return True

    g = Gate.remote()
    blocked = g.wait_open.remote()
    assert ray_tpu.get(g.open.remote(), timeout=10)
    assert ray_tpu.get(blocked, timeout=10) == "opened"


def test_cancel_parked_async_method(ray_start_shared):
    """cancel() must cancel a coroutine parked on the user loop (the
    pool thread is blocked in Future.result() where async exceptions
    cannot land)."""

    @ray_tpu.remote
    class Stuck:
        async def forever(self):
            await asyncio.Event().wait()

        async def ping(self):
            return "ok"

    s = Stuck.remote()
    ref = s.forever.remote()
    time.sleep(0.5)  # let it park
    ray_tpu.cancel(ref)
    with pytest.raises(Exception):
        ray_tpu.get(ref, timeout=15)
    # the actor survives and serves new requests
    assert ray_tpu.get(s.ping.remote(), timeout=15) == "ok"


def test_batch_requires_async():
    with pytest.raises(TypeError, match="async"):
        @serve.batch
        def not_async(xs):
            return xs
