"""kvscope — KV-cache & HBM memory observatory.

Covers the three tentpole concerns end to end: occupancy timelines
(the per-wave ring and its exact conservation invariant), eviction
forensics + re-prefill waste (exact accounting against an independent
shadow model of the pager, and per-tenant attribution through a real
churn workload), and the unified HBM ledger (headroom math + the
AdmissionPolicy gate).  Satellites ride along: the prefix_pool churn
traffic class (RNG stream isolation), perfledger direction, the
tracebus kv.reserve tuple extension, autopilot cache-thrash
attribution, the CLI, and the hot-path overhead guard.
"""

import asyncio
import json
import time

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from ray_tpu.serve.batching import AdmissionPolicy  # noqa: E402
from ray_tpu.serve.kv_pager import BlockPager  # noqa: E402
from ray_tpu.serve.kvscope import (KVScope, empty_kv_scope,
                                   hbm_ledger)  # noqa: E402
from ray_tpu.serve.traffic import (TenantSpec, TrafficGenerator,
                                   TrafficSpec, run_traffic)  # noqa: E402

_OVR = {"dtype": jnp.float32, "use_flash": False, "remat": False}


# ---------------------------------------------------------------------------
# KVScope unit: occupancy ring + fragmentation
# ---------------------------------------------------------------------------

def test_occupancy_ring_conservation_invariant():
    scope = KVScope(num_blocks=10, block_size=4, enabled=True)
    # free ids exclude the null block and whatever is in use/parked
    scope.sample(free_ids=[3, 4, 5, 6], cached=2)   # 3 in use (+null)
    scope.sample(free_ids=[], cached=5)             # pool saturated
    scope.sample(free_ids=list(range(1, 10)), cached=0)  # idle
    for s in scope.timeline():
        assert s["free"] + s["cached"] + s["in_use"] == 10, s
        assert s["null"] == 1
    st = scope.stats(free=9, cached=0)
    assert st["occupancy"]["samples"] == 3
    assert st["occupancy"]["occupancy_ratio"] == 0.0
    assert len(st["occupancy"]["ring"]) == 3


def test_fragmentation_is_contiguous_run_deficit():
    scope = KVScope(num_blocks=16, block_size=4, enabled=True)
    assert scope._fragmentation([]) == 0.0
    assert scope._fragmentation([7]) == 0.0
    assert scope._fragmentation([3, 4, 5, 6]) == 0.0       # one run
    # runs of 2+2: longest 2 of 4 free -> deficit 0.5
    assert scope._fragmentation([1, 2, 9, 10]) == 0.5
    # fully shattered: longest run 1 of 4 -> 0.75
    assert scope._fragmentation([1, 4, 8, 12]) == 0.75
    # order must not matter (free list is LIFO, not sorted)
    assert scope._fragmentation([12, 1, 8, 4]) == 0.75


def test_ring_is_bounded():
    scope = KVScope(num_blocks=4, block_size=4, ring_capacity=8,
                    enabled=True)
    for _ in range(20):
        scope.sample([1, 2], cached=0)
    assert len(scope.timeline()) == 8


def test_kill_switch_disables_all_hooks(monkeypatch):
    monkeypatch.setenv("RAYTPU_KVSCOPE", "0")
    scope = KVScope(num_blocks=8, block_size=4)
    assert not scope.enabled
    scope.sample([1, 2], cached=0)
    scope.note_alloc([1], "t")
    assert scope.note_register((1, 2, 3, 4), "t") == 0
    assert scope.note_evict((1, 2, 3, 4)) is None
    st = scope.stats(free=7, cached=0)
    assert st["occupancy"]["samples"] == 0
    assert st["forensics"]["reprefill_waste_tokens"] == 0
    # explicit override beats the env (mirrors FlightRecorder)
    assert KVScope(8, 4, enabled=True).enabled


def test_empty_kv_scope_matches_live_shape():
    scope = KVScope(num_blocks=8, block_size=4, enabled=True)
    live = scope.stats(free=7, cached=0)
    live["hbm_ledger"] = hbm_ledger()
    empty = empty_kv_scope()
    assert set(empty) == set(live)
    assert set(empty["occupancy"]) == set(live["occupancy"])
    assert set(empty["forensics"]) == set(live["forensics"])
    assert set(empty["hbm_ledger"]) == set(live["hbm_ledger"])


# ---------------------------------------------------------------------------
# eviction forensics: exact accounting vs an independent shadow model
# ---------------------------------------------------------------------------

def test_exact_waste_accounting_against_shadow_model():
    """Drive a real BlockPager through three laps of a rotating key
    set that overflows the pool, while the test maintains its OWN
    model of residency (free count, FIFO park order, evicted set) —
    the pager's booked waste must equal the model's, per tenant."""
    bs = 4
    pager = BlockPager(num_blocks=4, block_size=bs, max_seq=8)
    keys = [tuple(range(100 * k, 100 * k + bs)) for k in range(5)]
    tenants = ["alpha", "beta", "alpha", "beta", "alpha"]

    free_count = 3              # num_blocks - null
    parked = []                 # (key) in park order == LRU order
    resident = set()
    evicted = set()
    expected = {}               # tenant -> waste tokens

    for lap in range(3):
        for key, tenant in zip(keys, tenants):
            pager.set_request(1, None, tenant=tenant)
            # shadow: allocation evicts the LRU parked key iff the
            # free list is dry
            if free_count > 0:
                free_count -= 1
            else:
                victim = parked.pop(0)
                resident.discard(victim)
                evicted.add(victim)
            blocks = pager.allocate(1)
            assert blocks is not None
            waste = pager.register_prefix(list(key), blocks)
            # shadow: a register of previously-evicted content books
            # exactly block_size tokens; anything else books nothing
            if key in resident:
                assert waste == 0
                # duplicate content: the fresh block stays
                # unregistered, so release returns it to the free list
                pager.release(blocks)
                free_count += 1
                pager.set_request(None)
                continue
            if key in evicted:
                assert waste == bs
                evicted.discard(key)
                expected[tenant] = expected.get(tenant, 0) + bs
            else:
                assert waste == 0
            resident.add(key)
            parked.append(key)
            pager.release(blocks)      # parks (registered)
            pager.set_request(None)

    st = pager.kv_scope_stats()
    fx = st["forensics"]
    assert fx["waste_by_tenant"] == expected
    assert fx["reprefill_waste_tokens"] == sum(expected.values())
    assert fx["reprefill_waste_tokens"] > 0
    assert fx["reprefill_events"] * bs == fx["reprefill_waste_tokens"]
    assert fx["keys_evicted"] == pager.evictions


def test_evicted_key_ledger_is_bounded():
    scope = KVScope(num_blocks=8, block_size=4, key_cap=3,
                    enabled=True)
    for k in range(5):
        key = (k, k, k, k)
        scope.note_register(key, "t")
        scope.note_evict(key)
    assert scope.keys_evicted == 5
    assert scope.keys_forgotten == 2
    assert len(scope._evicted) == 3
    # a forgotten key re-registering books nothing (it fell off the
    # bounded ledger — undercounting, never overcounting)
    assert scope.note_register((0, 0, 0, 0), "t") == 0
    assert scope.note_register((4, 4, 4, 4), "t") == 4


# ---------------------------------------------------------------------------
# hbm ledger + admission gate
# ---------------------------------------------------------------------------

def test_hbm_ledger_headroom_math():
    led = hbm_ledger(
        pool_bytes_per_chip=100,
        program_budget_bytes=50,
        device_stats=[
            # allocator view dominates
            {"id": 0, "platform": "tpu", "bytes_limit": 1000,
             "bytes_in_use": 400, "peak_bytes_in_use": 500},
            # static commitment dominates (allocator under-reports)
            {"id": 1, "platform": "tpu", "bytes_limit": 1000,
             "bytes_in_use": 10, "peak_bytes_in_use": 10},
            # CPU: no limit -> no measurable headroom
            {"id": 2, "platform": "cpu", "bytes_limit": None,
             "bytes_in_use": None, "peak_bytes_in_use": None},
        ])
    rows = {r["id"]: r for r in led["per_chip"]}
    assert rows[0]["headroom_bytes"] == 1000 - 400
    assert rows[1]["headroom_bytes"] == 1000 - 150
    assert rows[2]["headroom_bytes"] is None
    assert led["min_headroom_bytes"] == 600
    # no devices at all -> inert
    assert hbm_ledger()["min_headroom_bytes"] is None


def test_admission_policy_hbm_headroom_gate():
    pol = AdmissionPolicy(min_headroom_bytes=1 << 20)
    low = {"kv_scope": {"hbm_ledger": {"min_headroom_bytes": 1024}}}
    ok = {"kv_scope": {"hbm_ledger": {"min_headroom_bytes": 2 << 20}}}
    inert = {"kv_scope": {"hbm_ledger": {"min_headroom_bytes": None}}}
    # fires regardless of backlog: exhausted HBM does not heal by
    # admitting more work
    assert pol.decide(low, queue_depth=0) == "hbm_headroom"
    assert pol.decide(low, queue_depth=5) == "hbm_headroom"
    assert pol.decide(ok, queue_depth=0) is None
    # inert when no chip reports a limit (CPU, dense engines)
    assert pol.decide(inert, queue_depth=0) is None
    assert pol.decide({}, queue_depth=0) is None
    assert pol.describe()["min_headroom_bytes"] == 1 << 20
    # default policy: gate off
    assert AdmissionPolicy().decide(low, queue_depth=0) is None


# ---------------------------------------------------------------------------
# prefix_pool churn traffic class
# ---------------------------------------------------------------------------

def test_prefix_pool_validation():
    with pytest.raises(ValueError, match="prefix_pool must be >= 1"):
        TenantSpec("t", 1.0, prefix_pool=0)
    with pytest.raises(ValueError, match="mutually exclusive"):
        TenantSpec("t", 1.0, prefix_groups=(0,), prefix_pool=2)


def test_prefix_pool_rotation_is_deterministic():
    spec = TrafficSpec(num_requests=60, seed=5, num_prefix_groups=3,
                       p_shared=0.9, vocab=300,
                       tenants=(TenantSpec("churn", 0.5, prefix_pool=4),
                                TenantSpec("bg", 0.5)))
    a = TrafficGenerator(spec).requests()
    b = TrafficGenerator(spec).requests()
    assert all(x.group == y.group and np.array_equal(x.prompt, y.prompt)
               and x.arrival_s == y.arrival_s for x, y in zip(a, b))
    # pool requests get distinct negative group ids -(2 + pool_idx),
    # never colliding with spec groups (>= 0) or unique (-1)
    pool_groups = {r.group for r in a
                   if r.tenant == "churn" and r.group < -1}
    assert pool_groups == {-2, -3, -4, -5}
    # round-robin: the churn tenant walks its pool in order
    seq = [-r.group - 2 for r in a
           if r.tenant == "churn" and r.group < -1]
    assert seq[:8] == [(i % 4) for i in range(8)]


def test_prefix_pool_leaves_cotenant_rng_stream_untouched():
    """The churn pool draws from its own seeded stream: flipping one
    tenant's prefix_pool must not perturb any other tenant's prompts
    (and with no pool set at all, the generator is the legacy one)."""
    kw = dict(num_requests=50, seed=7, num_prefix_groups=3,
              p_shared=0.8, vocab=300)
    with_pool = TrafficGenerator(TrafficSpec(
        tenants=(TenantSpec("churn", 0.5, prefix_pool=3),
                 TenantSpec("bg", 0.5)), **kw)).requests()
    without = TrafficGenerator(TrafficSpec(
        tenants=(TenantSpec("churn", 0.5),
                 TenantSpec("bg", 0.5)), **kw)).requests()
    assert len(with_pool) == len(without)
    for x, y in zip(with_pool, without):
        assert x.tenant == y.tenant        # same share draws
        assert x.arrival_s == y.arrival_s  # same arrival process
        if x.tenant == "bg":               # co-tenant bit-identical
            assert x.group == y.group
            assert np.array_equal(x.prompt, y.prompt)


# ---------------------------------------------------------------------------
# end-to-end: seeded churn workload through a real paged engine
# ---------------------------------------------------------------------------

def _churn_spec(n=40):
    return TrafficSpec(
        num_requests=n, seed=3, rate_rps=200.0, num_prefix_groups=2,
        prefix_len=32, p_shared=0.95, tail_len_mean=4.0,
        tail_len_max=8, vocab=300,
        tenants=(TenantSpec("churn", 0.7, prefix_pool=6),
                 TenantSpec("bg", 0.3)))


def test_churn_traffic_books_waste_and_keeps_invariant():
    rep = run_traffic(_churn_spec(), preset="nano", kv_layout="paged",
                      kv_block_size=16, kv_num_blocks=12, max_slots=2,
                      max_new_tokens=4, prefill_bucket=16,
                      time_scale=0.0, config_overrides=_OVR)
    ks = rep["engine"]["kv_scope"]
    assert ks["enabled"]
    # conservation at EVERY ring sample: free + cached + in_use is
    # exactly the pool size (null included in in_use)
    ring = ks["occupancy"]["ring"]
    assert len(ring) > 0
    for s in ring:
        assert s["free"] + s["cached"] + s["in_use"] == 12, s
    # the bounded pool thrashes: evictions happened and the same
    # prefixes came back
    fx = ks["forensics"]
    assert fx["keys_evicted"] > 0
    assert fx["reprefill_events"] > 0
    assert fx["reprefill_waste_tokens"] == \
        fx["reprefill_events"] * 16
    assert sum(fx["waste_by_tenant"].values()) == \
        fx["reprefill_waste_tokens"]
    assert fx["waste_by_tenant"].get("churn", 0) > 0
    assert 0.0 < fx["reprefill_waste_frac"] <= 1.0
    assert fx["reprefill_waste_frac"] == pytest.approx(
        fx["reprefill_waste_tokens"] / fx["prefill_tokens"], abs=1e-4)
    # report headlines flatten for SWEEPJSON/bench
    assert rep["kv_occupancy_p95"] == \
        ks["occupancy"]["occupancy_p95"] > 0
    assert rep["reprefill_waste_frac"] == fx["reprefill_waste_frac"]
    # top offender rows carry the key identity forensics render
    assert fx["top_keys"] and all(
        set(r) == {"key_prefix", "key_len", "tokens"}
        for r in fx["top_keys"])


def test_churn_journal_replay_matches_per_tenant_waste():
    """Independent per-tenant accounting from the flight recorder's
    journal: every kv_reprefill event must name content a prior
    kv_evict event recorded as lost, and the per-tenant sums must
    equal kvscope's waste_by_tenant exactly."""
    from ray_tpu.serve.llm import build_llm_deployment
    from ray_tpu.serve.traffic import drive

    dep = build_llm_deployment(
        "gpt2", "nano", scheduler="continuous", kv_layout="paged",
        kv_block_size=16, kv_num_blocks=12, prefill_bucket=16,
        max_slots=2, max_new_tokens=4, temperature=0.0,
        config_overrides=_OVR)
    requests = TrafficGenerator(_churn_spec()).requests()

    async def main():
        inst = dep.func_or_class()
        try:
            await drive(inst, requests, time_scale=0.0)
            return (inst.engine_stats(),
                    inst._telemetry.flightrec.snapshot())
        finally:
            inst.shutdown_engine()

    stats, events = asyncio.run(main())
    fx = stats["kv_scope"]["forensics"]
    evicted = set()
    replayed = {}
    for e in events:
        ident = (tuple(e.get("key_prefix") or ()), e.get("key_len"))
        if e["kind"] == "kv_evict":
            evicted.add(ident)
        elif e["kind"] == "kv_reprefill":
            assert ident in evicted, e
            replayed[e["tenant"]] = \
                replayed.get(e["tenant"], 0) + e["tokens"]
    assert replayed, "churn workload produced no re-prefill events"
    assert replayed == fx["waste_by_tenant"]
    assert sum(replayed.values()) == fx["reprefill_waste_tokens"]


def test_churn_journal_replay_tier_round_trip_books_zero_waste():
    """Same replay discipline with a host tier attached: every
    ``kv_fetch`` event names content a prior ``kv_evict`` recorded as
    lost, and an evict→fetch→register round-trip books ZERO re-prefill
    waste — while a restored key stays resident, no ``kv_reprefill``
    event may name it.  The forensics mirror must agree with the
    journal exactly (tier_hits == fetch events, tokens_restored ==
    their token sum)."""
    from ray_tpu.serve.llm import build_llm_deployment
    from ray_tpu.serve.traffic import drive

    dep = build_llm_deployment(
        "gpt2", "nano", scheduler="continuous", kv_layout="paged",
        kv_block_size=16, kv_num_blocks=12, prefill_bucket=16,
        max_slots=2, max_new_tokens=4, temperature=0.0,
        kv_host_tier_bytes=1 << 26, config_overrides=_OVR)
    requests = TrafficGenerator(_churn_spec()).requests()

    async def main():
        inst = dep.func_or_class()
        try:
            await drive(inst, requests, time_scale=0.0)
            return (inst.engine_stats(),
                    inst._telemetry.flightrec.snapshot())
        finally:
            inst.shutdown_engine()

    stats, events = asyncio.run(main())
    fx = stats["kv_scope"]["forensics"]
    evicted = set()
    restored_resident = set()
    fetches = 0
    fetched_tokens = 0
    for e in events:
        ident = (tuple(e.get("key_prefix") or ()), e.get("key_len"))
        if e["kind"] == "kv_evict":
            evicted.add(ident)
            restored_resident.discard(ident)
        elif e["kind"] == "kv_fetch":
            # a fetch can only restore content a prior evict lost
            assert ident in evicted, e
            assert e["bytes"] > 0 and e["tokens"] == 16, e
            restored_resident.add(ident)
            fetches += 1
            fetched_tokens += e["tokens"]
        elif e["kind"] == "kv_reprefill":
            # the round-trip invariant: registering a tier-restored
            # key must never book waste
            assert ident not in restored_resident, e
    assert fetches > 0, "tier never restored — workload did not churn"
    assert fx["tier_hits"] == fetches
    assert fx["tokens_restored"] == fetched_tokens
    kt = stats["kv_tier"]
    assert kt["enabled"] and kt["hits"] == fetches
    assert kt["tokens_restored"] == fetched_tokens


# ---------------------------------------------------------------------------
# autopilot attribution: cache-thrash clause
# ---------------------------------------------------------------------------

def test_autopilot_cites_cache_thrash_when_it_dominates():
    from ray_tpu.tools.autopilot.attribution import attribute

    dev = {"ridge_flops_per_byte": 1.0, "peak_flops_per_chip": 1.0,
           "peak_hbm_bytes_per_sec": 1.0}
    thrash = {"forensics": {"reprefill_waste_frac": 0.42,
                            "reprefill_waste_tokens": 8400}}
    rep = attribute({}, device=dev, kv_scope=thrash)
    assert "serving is cache-thrash-bound: 42% of prefill tokens " \
           "re-filled previously-resident prefixes" in rep["summary"]
    assert rep["kv_scope"] is thrash
    # below threshold: no clause
    calm = {"forensics": {"reprefill_waste_frac": 0.02,
                          "reprefill_waste_tokens": 40}}
    rep = attribute({}, device=dev, kv_scope=calm)
    assert "cache-thrash" not in rep["summary"]
    # the fleet-pooled block is flat (no "forensics" nesting)
    rep = attribute({}, device=dev,
                    kv_scope={"reprefill_waste_frac": 0.5,
                              "reprefill_waste_tokens": 100})
    assert "cache-thrash-bound: 50%" in rep["summary"]


# ---------------------------------------------------------------------------
# perfledger direction
# ---------------------------------------------------------------------------

def test_perfledger_ingests_kvscope_fields_lower_is_better():
    from ray_tpu.tools.perfledger import _SWEEP_FIELDS, higher_is_better

    assert "kv_occupancy_p95" in _SWEEP_FIELDS
    assert "reprefill_waste_frac" in _SWEEP_FIELDS
    # pool pressure and cache thrash regress UPWARD
    assert higher_is_better("kv_occupancy_p95") is False
    assert higher_is_better("reprefill_waste_frac") is False
    assert higher_is_better("gpt2_traffic_kv_occupancy_p95") is False
    assert higher_is_better(
        "gpt2_traffic_reprefill_waste_frac") is False
    # existing directions untouched
    assert higher_is_better("ttft_slo_attainment") is True
    assert higher_is_better("prefix_hit_rate") is True


# ---------------------------------------------------------------------------
# tracebus: kv.reserve span tuple extension
# ---------------------------------------------------------------------------

def test_tracebus_kv_reserve_span_carries_eviction_fields():
    from ray_tpu.tools.tracebus import build_request_spans

    req = {"request": "r0", "trace_id": "t" * 8, "enqueue": 0.0,
           "engine_enqueue": 0.01, "admit": 0.05,
           "first_token": 0.08, "finish": 0.1,
           "kv_reserve": (0.02, 0.03, 3, 1, 2, 16)}
    spans = {s["name"]: s for s in build_request_spans(req)}
    kv = spans["kv.reserve"]
    assert kv["attrs"]["blocks"] == 3
    assert kv["attrs"]["hit_blocks"] == 1
    assert kv["attrs"]["evicted"] == 2
    assert kv["attrs"]["reprefill_waste_tokens"] == 16
    # legacy 4-tuple records still render (None-padded)
    req["kv_reserve"] = (0.02, 0.03, 3, 1)
    spans = {s["name"]: s for s in build_request_spans(req)}
    assert spans["kv.reserve"]["attrs"]["evicted"] is None
    assert spans["kv.reserve"]["attrs"]["reprefill_waste_tokens"] \
        is None


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------

def _snapshot_doc():
    scope = KVScope(num_blocks=8, block_size=4, enabled=True)
    scope.sample([1, 2, 3], cached=2)
    scope.sample([1], cached=3)
    scope.note_register((1, 2, 3, 4), "alpha")
    scope.note_evict((1, 2, 3, 4))
    scope.note_register((1, 2, 3, 4), "alpha")
    blk = scope.stats(free=1, cached=3, prefill_tokens=64)
    blk["hbm_ledger"] = hbm_ledger(
        pool_bytes_per_chip=256, program_budget_bytes=64,
        device_stats=[{"id": 0, "platform": "tpu",
                       "bytes_limit": 4096, "bytes_in_use": 1024,
                       "peak_bytes_in_use": 2048}])
    return blk


def test_cli_report_timeline_export(tmp_path):
    from ray_tpu.tools.kvscope import main as kvscope_main

    snap = tmp_path / "snap.json"
    # dashboard-map form: {deployment: {"kv_scope": block}}
    snap.write_text(json.dumps({"llm": {"kv_scope": _snapshot_doc()}}))
    assert kvscope_main(["report", str(snap)]) == 0
    assert kvscope_main(["timeline", str(snap)]) == 0
    out = str(tmp_path / "trace.json")
    assert kvscope_main(["export", str(snap), "-o", out]) == 0
    with open(out) as f:
        events = json.load(f)
    counters = [e for e in events if e.get("ph") == "C"]
    assert counters, events
    names = {e["name"] for e in counters}
    assert names == {"kv blocks", "kv occupancy", "kv fragmentation"}
    blocks = [e for e in counters if e["name"] == "kv blocks"]
    # counter lanes conserve the pool too
    for e in blocks:
        assert e["args"]["in_use"] + e["args"]["cached"] \
            + e["args"]["free"] == 8
    # unreadable snapshot -> exit 2, not a traceback
    bad = tmp_path / "bad.json"
    bad.write_text(json.dumps({"requests": []}))
    assert kvscope_main(["report", str(bad)]) == 2


def test_cli_load_snapshot_accepts_all_forms(tmp_path):
    from ray_tpu.tools.kvscope import load_snapshot

    blk = _snapshot_doc()
    bare = tmp_path / "bare.json"
    bare.write_text(json.dumps(blk))
    assert list(load_snapshot(str(bare))) == ["engine"]
    eng = tmp_path / "eng.json"
    eng.write_text(json.dumps({"deployment": "llm_gpt2_nano",
                               "kv_scope": blk}))
    assert list(load_snapshot(str(eng))) == ["llm_gpt2_nano"]
    dash = tmp_path / "dash.json"
    dash.write_text(json.dumps({"a": {"kv_scope": blk},
                                "b": {"error": "down"}}))
    assert list(load_snapshot(str(dash))) == ["a"]


# ---------------------------------------------------------------------------
# hot-path overhead guard (mirrors flightrec's)
# ---------------------------------------------------------------------------

def test_kvscope_overhead_under_5pct(monkeypatch):
    """kvscope must be cheap enough to leave on: min-of-repeats
    decode-loop wall time with the scope on stays within 5% of the
    same loop with RAYTPU_KVSCOPE=0 (hooks early-return)."""
    from ray_tpu.serve.llm import build_llm_deployment

    dep = build_llm_deployment(
        "gpt2", "nano", scheduler="continuous", kv_layout="paged",
        kv_block_size=16, prefill_bucket=16, max_slots=2,
        max_new_tokens=32, temperature=0.0, config_overrides=_OVR)
    rng = np.random.RandomState(0)
    prompts = [rng.randint(2, 50, size=rng.randint(8, 14))
               .astype(np.int32) for _ in range(6)]

    def run_once(scope_on):
        monkeypatch.setenv("RAYTPU_KVSCOPE", "1" if scope_on else "0")

        async def main():
            inst = dep.func_or_class()
            try:
                await asyncio.gather(*[inst(p) for p in prompts])
            finally:
                inst.shutdown_engine()

        t0 = time.perf_counter()
        asyncio.run(main())
        return time.perf_counter() - t0

    run_once(True)                     # compile warmup (shared cache)
    # CPU-CI wall clocks are noisy at this scale, and noise can only
    # produce FALSE failures here (the hooks are strictly additive
    # work) — so take interleaved min-of-5 pairs and allow a couple
    # of fresh attempts before declaring the hooks expensive
    pairs = []
    for _ in range(3):
        off = min(run_once(False) for _ in range(5))
        on = min(run_once(True) for _ in range(5))
        if on <= off * 1.05:
            return
        pairs.append((on, off))
    raise AssertionError(f"kvscope hooks >5% over baseline: {pairs}")
