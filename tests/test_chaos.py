"""Chaos + GCS fault tolerance tests.

Reference analogs: the NodeKiller chaos harness
(python/ray/_private/test_utils.py:1241-1348) and
python/ray/tests/test_gcs_fault_tolerance.py.
"""

import random
import threading
import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster


class NodeKiller:
    """SIGKILL-style removal of random worker nodes on an interval, with
    replacement — the in-process analog of the reference's
    NodeKillerActor (_kill_raylet, test_utils.py:1327)."""

    def __init__(self, cluster: Cluster, interval_s: float = 2.0):
        self.cluster = cluster
        self.interval = interval_s
        self.kills = 0
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True)

    def start(self):
        self._thread.start()

    def _run(self):
        rng = random.Random(0)
        while not self._stop.wait(self.interval):
            nodes = self.cluster.worker_nodes
            if len(nodes) < 2:
                continue  # keep at least one worker alive
            victim = rng.choice(nodes)
            self.cluster.remove_node(victim)
            self.kills += 1
            # replace it so capacity recovers (rolling failure)
            self.cluster.add_node(num_cpus=2)

    def stop(self):
        self._stop.set()
        self._thread.join(timeout=10)


def test_chaos_lineage_heavy_workload_survives():
    """Tasks with large (shm) returns keep completing while worker nodes
    are repeatedly killed: retries + lineage reconstruction under fire
    (validates the round-2/3 refcount machinery adversarially)."""
    cluster = Cluster(head_num_cpus=0)
    for _ in range(3):
        cluster.add_node(num_cpus=2)
    cluster.connect()
    killer = NodeKiller(cluster, interval_s=1.5)
    try:
        @ray_tpu.remote(num_cpus=1, max_retries=8)
        def produce(i):
            import time as _t

            import numpy as np

            _t.sleep(1.0)  # long enough for the killer to interleave
            return np.full(150_000, i, dtype=np.int64)  # shm-sized

        @ray_tpu.remote(num_cpus=1, max_retries=8)
        def reduce_(arr):
            return int(arr[0]) + int(arr[-1])

        killer.start()
        results = []
        for wave in range(6):
            refs = [produce.remote(wave * 10 + j) for j in range(4)]
            outs = [reduce_.remote(r) for r in refs]
            results.extend(ray_tpu.get(outs, timeout=180))
        assert killer.kills >= 2, "chaos never actually killed a node"
        want = [2 * (w * 10 + j) for w in range(6) for j in range(4)]
        assert results == want
    finally:
        killer.stop()
        ray_tpu.shutdown()
        cluster.shutdown()


def test_gcs_restart_recovers_state(tmp_path):
    """Head restart with a persist file recovers KV, named detached
    actors (re-placed on the new cluster), and the job counter
    (reference: test_gcs_fault_tolerance.py)."""
    persist = str(tmp_path / "gcs_state.pkl")

    # --- first life -------------------------------------------------------
    ray_tpu.init(num_cpus=2, object_store_memory=128 * 1024 * 1024,
                 _system_config={"gcs_persist_path": persist})

    @ray_tpu.remote(lifetime="detached", name="survivor")
    class Counter:
        def __init__(self):
            self.n = 41

        def incr(self):
            self.n += 1
            return self.n

    c = Counter.remote()
    assert ray_tpu.get(c.incr.remote(), timeout=60) == 42
    from ray_tpu._private import worker_context

    cw = worker_context.core_worker()
    cw.kv_put("app_config", b"v2-rollout")
    # let the GCS monitor write its snapshot
    deadline = time.monotonic() + 15
    import os

    while not os.path.exists(persist) and time.monotonic() < deadline:
        time.sleep(0.2)
    assert os.path.exists(persist), "snapshot never written"
    time.sleep(1.5)  # one more tick so the latest mutations land
    ray_tpu.shutdown()

    # --- second life ------------------------------------------------------
    ray_tpu.init(num_cpus=2, object_store_memory=128 * 1024 * 1024,
                 _system_config={"gcs_persist_path": persist})
    try:
        cw = worker_context.core_worker()
        assert cw.kv_get("app_config") == b"v2-rollout"
        # detached actor comes back (fresh instance — reference semantics:
        # restart re-runs the constructor)
        deadline = time.monotonic() + 60
        val = None
        while time.monotonic() < deadline:
            try:
                h = ray_tpu.get_actor("survivor")
                val = ray_tpu.get(h.incr.remote(), timeout=30)
                break
            except Exception:
                time.sleep(0.5)
        assert val == 42, f"restored actor answered {val}"
    finally:
        ray_tpu.shutdown()


def test_actor_queues_until_node_returns():
    """An actor whose shape fits a node TYPE in the cluster but has no
    alive host right now must stay PENDING_CREATION and get created once
    capacity returns — not die with a scheduling error (reference:
    GcsActorScheduler queues pending actors; round-4 fix for the
    false-fail observed under the scale envelope)."""
    cluster = Cluster(head_num_cpus=0)
    worker = cluster.add_node(num_cpus=2)
    cluster.connect()
    try:
        @ray_tpu.remote(num_cpus=2)
        class A:
            def ping(self):
                return "pong"

        # remove the only feasible node; A.remote() blocks in
        # wait_actor_ready, so capacity returns from a timer thread
        cluster.remove_node(worker)
        time.sleep(0.5)
        t = threading.Timer(3.0, cluster.add_node,
                            kwargs={"num_cpus": 2})
        t.start()
        a = A.remote()  # stays PENDING until the node arrives
        assert ray_tpu.get(a.ping.remote(), timeout=120) == "pong"
        t.join()
    finally:
        ray_tpu.shutdown()
        cluster.shutdown()


def test_impossible_actor_shape_still_fails_fast():
    """Shapes exceeding every registered node's TOTAL keep the loud
    immediate error (typo-sized requests must not hang forever)."""
    cluster = Cluster(head_num_cpus=0)
    cluster.add_node(num_cpus=2)
    cluster.connect()
    try:
        @ray_tpu.remote(num_cpus=999)
        class A:
            def ping(self):
                return 1

        # the scheduling error surfaces at creation (wait_actor_ready)
        with pytest.raises(Exception, match="exceeds every registered"):
            A.remote()
    finally:
        ray_tpu.shutdown()
        cluster.shutdown()
