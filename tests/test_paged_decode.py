"""Paged KV-cache model-layer parity: the block-pool layout must be a
pure data-movement change.  Every test pins paged output against the
dense layout (the bit-exactness oracle, same role prefill_impl="scan"
plays for batched prefill): gathered pool views are value-identical to
the dense cache, masked lanes are exactly -1e30 in both layouts, so
softmax zeros land on the same lanes and sums see identical terms."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from ray_tpu.models import (gpt2_config, gpt2_init, llama_config,
                            llama_init)  # noqa: E402
from ray_tpu.models import decode_common  # noqa: E402
from ray_tpu.models import gpt2_decode, llama_decode  # noqa: E402
from ray_tpu.models.decode_common import (dense_to_paged, is_paged,
                                          make_vocab_tail_mask,
                                          sample_token)  # noqa: E402

BS = 16  # block size under test (nano max_seq=128 -> 8 blocks/row)


def _family(name):
    """(cfg, params, prefill, paged_prefill, decode_step,
    init_paged_cache, generate) for one model family."""
    if name == "gpt2":
        cfg = gpt2_config("nano", dtype=jnp.float32, use_flash=False,
                          remat=False)
        params = gpt2_init(jax.random.PRNGKey(0), cfg)
        return (cfg, params, gpt2_decode.prefill,
                gpt2_decode.paged_prefill, gpt2_decode.decode_step,
                gpt2_decode.init_paged_cache, gpt2_decode.generate)
    cfg = llama_config("nano", dtype=jnp.float32, use_flash=False)
    params = llama_init(jax.random.PRNGKey(0), cfg)
    return (cfg, params, llama_decode.llama_prefill,
            llama_decode.llama_paged_prefill,
            llama_decode.llama_decode_step,
            llama_decode.llama_init_paged_cache,
            llama_decode.llama_generate)


@pytest.mark.parametrize("family", ["gpt2", "llama"])
def test_generate_paged_matches_dense_bitwise(family):
    cfg, params, *_, generate = _family(family)
    prompt = jnp.asarray(
        np.random.RandomState(1).randint(2, cfg.vocab_size, (2, 9)),
        jnp.int32)
    dense = generate(params, prompt, cfg, max_new_tokens=6,
                     temperature=0.0)
    paged = generate(params, prompt, cfg, max_new_tokens=6,
                     temperature=0.0, kv_layout="paged",
                     kv_block_size=BS)
    np.testing.assert_array_equal(np.asarray(dense), np.asarray(paged))


def test_dense_to_paged_roundtrip_structure():
    cfg, params, prefill, *_ = _family("gpt2")
    toks = jnp.asarray([[5, 6, 7, 8]], jnp.int32)
    _, cache = prefill(params, toks, cfg, lengths=jnp.asarray([4]))
    paged = dense_to_paged(cache, BS)
    assert is_paged(paged) and not is_paged(cache)
    nb = cfg.max_seq // BS
    assert paged["k"].shape == (cfg.n_layer, 1 + nb, BS, cfg.n_head,
                                cfg.head_dim)
    # block 0 is the null block; the gathered view reassembles the
    # dense layout exactly
    assert not np.asarray(paged["k"][:, 0]).any()
    view = np.asarray(paged["k"])[:, np.asarray(paged["block_tables"])[0]]
    np.testing.assert_array_equal(
        view.reshape(cfg.n_layer, cfg.max_seq, cfg.n_head,
                     cfg.head_dim),
        np.asarray(cache["k"])[:, 0])


@pytest.mark.parametrize("family", ["gpt2", "llama"])
def test_paged_prefill_cold_matches_dense(family):
    cfg, params, prefill, paged_prefill, _, init_paged, _ = \
        _family(family)
    n = 33
    prompt = np.random.RandomState(2).randint(
        2, cfg.vocab_size, n).astype(np.int32)
    want, _ = prefill(params, jnp.asarray(prompt[None]), cfg,
                      lengths=jnp.asarray([n]))

    nb_row = cfg.max_seq // BS
    cache = init_paged(cfg, 1, num_blocks=1 + nb_row, block_size=BS)
    row_bt = jnp.arange(1, 1 + nb_row, dtype=jnp.int32)
    t_pad = 48  # bucket >= n, right-aligned
    toks = np.zeros((1, t_pad), np.int32)
    toks[0, t_pad - n:] = prompt
    got, cache = paged_prefill(params, cache, jnp.asarray(toks), cfg,
                               row_bt=row_bt, prefix_len=np.int32(0),
                               n_tail=np.int32(n), slot=np.int32(0))
    np.testing.assert_allclose(np.asarray(got),
                               np.asarray(want)[0], atol=1e-5)
    assert int(cache["pos"][0]) == n and int(cache["start"][0]) == 0


@pytest.mark.parametrize("family", ["gpt2", "llama"])
def test_paged_prefill_prefix_reuse_matches_dense(family):
    """The tentpole property: a request whose prompt extends blocks
    already resident in the pool (written by ANOTHER sequence's
    prefill) produces the same logits as dense-prefilling its full
    prompt from scratch — and the shared blocks are untouched."""
    cfg, params, prefill, paged_prefill, decode_step, init_paged, \
        generate = _family(family)
    rng = np.random.RandomState(3)
    shared = rng.randint(2, cfg.vocab_size, 32).astype(np.int32)
    a = np.concatenate([shared, rng.randint(2, cfg.vocab_size, 3)
                        .astype(np.int32)])
    b = np.concatenate([shared, rng.randint(2, cfg.vocab_size, 2)
                        .astype(np.int32)])

    nb_row = cfg.max_seq // BS
    cache = init_paged(cfg, 2, num_blocks=1 + 2 * nb_row,
                       block_size=BS)

    def right_aligned(tokens, t_pad):
        out = np.zeros((1, t_pad), np.int32)
        out[0, t_pad - len(tokens):] = tokens
        return jnp.asarray(out)

    # sequence A prefills cold into blocks 1..8
    bt_a = jnp.arange(1, 1 + nb_row, dtype=jnp.int32)
    _, cache = paged_prefill(params, cache, right_aligned(a, 48), cfg,
                             row_bt=bt_a, prefix_len=np.int32(0),
                             n_tail=np.int32(len(a)), slot=np.int32(0))
    pool_before = np.asarray(cache["k"])

    # sequence B reuses A's first two blocks (tokens 0..31) and owns
    # fresh blocks for its tail
    bt_b = np.zeros(nb_row, np.int32)
    bt_b[0], bt_b[1], bt_b[2] = 1, 2, 1 + nb_row
    n_tail = len(b) - 32
    got, cache = paged_prefill(params, cache, right_aligned(b[32:], 16),
                               cfg, row_bt=jnp.asarray(bt_b),
                               prefix_len=np.int32(32),
                               n_tail=np.int32(n_tail),
                               slot=np.int32(1))
    want, _ = prefill(params, jnp.asarray(b[None]), cfg,
                      lengths=jnp.asarray([len(b)]))
    np.testing.assert_allclose(np.asarray(got),
                               np.asarray(want)[0], atol=1e-5)
    # the shared prefix blocks were read, not rewritten
    pool_after = np.asarray(cache["k"])
    np.testing.assert_array_equal(pool_before[:, [1, 2]],
                                  pool_after[:, [1, 2]])

    # greedy decode from the shared pool matches per-sequence dense
    # generate token-for-token (both rows step together)
    tail = make_vocab_tail_mask(cfg)
    streams = [[], []]
    new = 4
    oracle = {}
    for row, tokens in ((0, a), (1, b)):
        out = generate(params, jnp.asarray(tokens[None]), cfg,
                       max_new_tokens=new, temperature=0.0)
        oracle[row] = np.asarray(out)[0, len(tokens):]
    # row 0 starts from its oracle's first token (its prefill parity
    # is already covered by the cold-prefill test); row 1's first
    # token comes from the prefix-reusing paged prefill above
    tok = jnp.asarray([int(oracle[0][0]),
                       int(np.argmax(np.asarray(got)))], jnp.int32)
    for _ in range(new):
        streams[0].append(int(tok[0]))
        streams[1].append(int(tok[1]))
        logits, cache = decode_step(params, cache, tok, cfg)
        tok = sample_token(logits, None, 0.0, tail)
    assert streams[0] == oracle[0].tolist()
    assert streams[1] == oracle[1].tolist()


def test_generate_rejects_unknown_kv_layout():
    cfg, params, *_, generate = _family("gpt2")
    with pytest.raises(ValueError, match="kv_layout"):
        generate(params, jnp.asarray([[1, 2, 3]], jnp.int32), cfg,
                 max_new_tokens=2, temperature=0.0,
                 kv_layout="ragged")


def test_copy_block_copies_all_layers():
    cfg, params, prefill, paged_prefill, _, init_paged, _ = \
        _family("gpt2")
    cache = init_paged(cfg, 1, num_blocks=4, block_size=BS)
    cache["k"] = cache["k"].at[:, 1].set(1.5)
    cache["v"] = cache["v"].at[:, 1].set(-2.5)
    out = decode_common.copy_block(cache, np.int32(1), np.int32(3))
    assert np.asarray(out["k"][:, 3] == 1.5).all()
    assert np.asarray(out["v"][:, 3] == -2.5).all()
    # source and unrelated blocks untouched
    assert np.asarray(out["k"][:, 1] == 1.5).all()
    assert not np.asarray(out["k"][:, 2]).any()
