"""Tiered host-RAM KV cache (serve/kv_tier.py + the pager/engine
spill-restore seam).

Covers the tentpole end to end: the byte-budgeted LRU host store
(unit), the pager's spill-on-evict / second-chance-lookup /
restore-books-no-waste seam (unit, against a fake block saver), and
the acceptance A/B — a seeded churn workload where tier-on yields
strictly lower re-prefill waste AND strictly lower interactive TTFT
p99 than tier-off on the same traffic, with outputs bit-identical to
the dense one-shot oracle and the critical path (now including
``kv_fetch_ms``) still summing exactly to e2e.  Satellites ride
along: the tracebus ``kv.fetch`` span, fleet pooling of the
``kv_tier`` block, the autopilot tier-absorption clause, perfledger
direction, and construction validation.
"""

import asyncio

import numpy as np
import pytest

from ray_tpu.serve.kv_tier import HostKVTier, empty_kv_tier

# ---------------------------------------------------------------------------
# HostKVTier unit: budget, LRU, probe semantics
# ---------------------------------------------------------------------------


def _rows(fill, shape=(1, 4, 1, 2)):
    return np.full(shape, fill, np.float32)


def test_tier_budget_lru_eviction_and_oversize():
    # each entry is 2 * 32 = 64 bytes; budget fits exactly two
    tier = HostKVTier(128)
    assert tier.put((1,), _rows(1), _rows(-1)) == 64
    assert tier.put((2,), _rows(2), _rows(-2)) == 64
    assert tier.bytes_resident == 128 and len(tier) == 2
    # third entry LRU-evicts the first
    assert tier.put((3,), _rows(3), _rows(-3)) == 64
    assert tier.bytes_resident == 128
    assert (1,) not in tier and (2,) in tier and (3,) in tier
    assert tier.evictions == 1 and tier.saves == 3
    # an entry alone exceeding the whole budget is dropped, not stored
    big = np.zeros((1, 4, 1, 64), np.float32)   # 1024 bytes
    assert tier.put((9,), big, big) == 0
    assert (9,) not in tier and tier.bytes_resident == 128
    # re-putting a resident key refreshes bytes, not duplicates
    assert tier.put((2,), _rows(2), _rows(-2)) == 64
    assert tier.bytes_resident == 128 and len(tier) == 2


def test_tier_take_counts_probes_and_keeps_entry():
    tier = HostKVTier(1 << 10)
    tier.put((1, 2), _rows(7), _rows(-7))
    entry = tier.take((1, 2))
    assert entry is not None and entry["k"][0, 0, 0, 0] == 7
    # the tier is a cache: a hit keeps the entry resident
    assert (1, 2) in tier and tier.take((1, 2)) is not None
    assert tier.take((3, 4)) is None
    st = tier.stats()
    assert st["hits"] == 2 and st["misses"] == 1
    assert st["hit_rate"] == pytest.approx(2 / 3, abs=1e-4)
    # a take-hit refreshes LRU position: (1,2) must outlive newcomers
    tier2 = HostKVTier(128)
    tier2.put((1,), _rows(1), _rows(1))
    tier2.put((2,), _rows(2), _rows(2))
    tier2.take((1,))                      # (2,) is now LRU
    tier2.put((3,), _rows(3), _rows(3))
    assert (1,) in tier2 and (2,) not in tier2


def test_tier_engine_fed_copy_accounting():
    tier = HostKVTier(1 << 10)
    tier.note_h2d(0.002)
    tier.note_h2d(0.001)
    tier.note_d2h(0.004)
    tier.note_restored(32)
    st = tier.stats()
    assert st["h2d_ms"] == pytest.approx(3.0)
    assert st["d2h_ms"] == pytest.approx(4.0)
    assert st["tokens_restored"] == 32


def test_tier_validation_and_empty_shape():
    with pytest.raises(ValueError):
        HostKVTier(0)
    with pytest.raises(ValueError):
        HostKVTier(-1)
    live = HostKVTier(64).stats()
    empty = empty_kv_tier()
    assert set(empty) == set(live)
    assert live["enabled"] is True and empty["enabled"] is False
    # every zeroed-twin value is falsy: counters 0, rates 0.0
    assert all(not v for v in empty.values())


# ---------------------------------------------------------------------------
# BlockPager seam: spill on eviction, second-chance chain, restore
# books hits (never waste)
# ---------------------------------------------------------------------------


def _pager_with_tier(num_blocks=4, bs=4, budget=1 << 12):
    from ray_tpu.serve.kv_pager import BlockPager

    pager = BlockPager(num_blocks=num_blocks, block_size=bs,
                       max_seq=8, host_tier=HostKVTier(budget))
    # fake engine block-saver: rows stamped with the block id so a
    # restore's content provenance is checkable
    pager.set_block_saver(
        lambda blk: (_rows(blk), _rows(-blk)))
    return pager


def _park(pager, key_tokens):
    """allocate → register → release one single-block prefix."""
    blocks = pager.allocate(1)
    assert blocks is not None
    waste = pager.register_prefix(list(key_tokens), blocks)
    pager.release(blocks)
    return blocks[0], waste


def test_pager_spills_registered_block_on_eviction():
    pager = _pager_with_tier()          # 3 usable blocks + null
    keys = [tuple(range(10 * k, 10 * k + 4)) for k in range(4)]
    blks = {}
    for key in keys[:3]:
        blks[key], _ = _park(pager, key)
    # pool full of parked prefixes: the 4th allocation evicts the LRU
    # (keys[0]) and must spill it into the tier first
    _park(pager, keys[3])
    tier = pager.tier
    assert keys[0] in tier and tier.saves == 1
    entry = tier._store[keys[0]]
    assert entry["k"][0, 0, 0, 0] == blks[keys[0]]  # right block's rows


def test_tier_lookup_chain_discipline_and_cap():
    pager = _pager_with_tier(num_blocks=8)
    toks = tuple(range(100, 112))       # 3 full blocks of 4
    k0, k1, k2 = toks[:4], toks[:8], toks[:12]
    tier = pager.tier
    tier.put(k0, _rows(0), _rows(0))
    tier.put(k2, _rows(2), _rows(2))    # gap: k1 missing
    # chain stops at the first miss — a gap cannot be skipped
    got = pager.tier_lookup(list(toks) + [999], 0)
    assert [k for k, _ in got] == [k0]
    # starting past the gap finds nothing (probe 1 misses immediately)
    assert pager.tier_lookup(list(toks) + [999], 1) == []
    tier.put(k1, _rows(1), _rows(1))
    got = pager.tier_lookup(list(toks) + [999], 0)
    assert [k for k, _ in got] == [k0, k1, k2]
    # the cap: with no tail token the last full block is NOT probed —
    # the tail prefill must still ingest at least one token
    got = pager.tier_lookup(list(toks), 0)
    assert [k for k, _ in got] == [k0, k1]


def test_note_tier_restore_books_hits_not_waste():
    pager = _pager_with_tier()
    keys = [tuple(range(10 * k, 10 * k + 4)) for k in range(4)]
    for key in keys:                    # 4 parks through 3 blocks:
        _park(pager, key)               # keys[0] evicted + spilled
    assert keys[0] in pager.tier
    pager.set_request(7, tenant="t0")
    pairs = pager.tier_lookup(list(keys[0]) + [5], 0)
    assert [k for k, _ in pairs] == [keys[0]]
    alloc = pager.allocate(1)
    restored = pager.note_tier_restore(pairs, alloc)
    assert restored == 4
    # the key is re-indexed at the fresh block; registering the same
    # prompt books NO waste (first-writer-wins skips restored keys)
    assert pager.register_prefix(list(keys[0]) + [5], alloc) == 0
    fx = pager.kv_scope_stats()["forensics"]
    assert fx["tier_hits"] == 1 and fx["tokens_restored"] == 4
    assert fx["reprefill_waste_tokens"] == 0
    assert pager.tier.tokens_restored == 4
    pager.set_request(None)


# ---------------------------------------------------------------------------
# acceptance: seeded churn A/B through real engines
# ---------------------------------------------------------------------------

jax_mod = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

_OVR = {"dtype": jnp.float32, "use_flash": False, "remat": False}


def _churn_prompts():
    """6 rotating 48-token (3-block) prefixes + unique short tails,
    3 laps: a 12-block pool cannot hold the 18 prefix blocks, so
    every lap re-admits prefixes the previous lap evicted."""
    rng = np.random.RandomState(11)
    prefixes = [rng.randint(2, 300, size=48).astype(np.int32)
                for _ in range(6)]
    prompts = []
    for lap in range(3):
        for i in range(6):
            tail = rng.randint(2, 300, size=4).astype(np.int32)
            prompts.append(np.concatenate(
                [prefixes[i], np.int32([i % 7 + 2]), tail]))
    return prompts


def _run_serial(kv_layout, tier_bytes=None):
    from ray_tpu.serve.llm import build_llm_deployment

    kw = dict(scheduler="continuous", kv_layout=kv_layout,
              prefill_bucket=16, max_slots=2, max_new_tokens=3,
              temperature=0.0, config_overrides=_OVR)
    if kv_layout == "paged":
        kw.update(kv_block_size=16, kv_num_blocks=12,
                  kv_host_tier_bytes=tier_bytes)
    dep = build_llm_deployment("gpt2", "nano", **kw)
    prompts = _churn_prompts()

    async def main():
        inst = dep.func_or_class()
        try:
            outs = []
            for p in prompts:           # serial: deterministic churn
                outs.append(np.asarray(await inst(p)))
            return outs, inst.engine_stats()
        finally:
            inst.shutdown_engine()

    return asyncio.run(main())


def test_churn_ab_bit_identical_and_waste_eliminated():
    dense_out, _ = _run_serial("dense")
    off_out, off_stats = _run_serial("paged")
    on_out, on_stats = _run_serial("paged", tier_bytes=1 << 26)

    # outputs bit-identical to the dense one-shot oracle in BOTH arms
    # (a tier restore is the same K/V content, just a copy not a
    # recompute)
    for d, o, t in zip(dense_out, off_out, on_out):
        assert np.array_equal(d, o)
        assert np.array_equal(d, t)

    # tier-off thrashes: the pool re-prefills evicted prefixes
    off_fx = off_stats["kv_scope"]["forensics"]
    on_fx = on_stats["kv_scope"]["forensics"]
    assert off_fx["reprefill_waste_tokens"] > 0
    # tier-on absorbs ALL of it on this workload (every evicted block
    # fits the budget and every re-admission chain is unbroken)
    assert on_fx["reprefill_waste_tokens"] == 0
    assert on_fx["reprefill_waste_frac"] < \
        off_fx["reprefill_waste_frac"]
    assert on_fx["tier_hits"] > 0
    assert on_fx["tokens_restored"] == on_fx["tier_hits"] * 16

    kt = on_stats["kv_tier"]
    assert kt["enabled"] and kt["hits"] == on_fx["tier_hits"]
    assert kt["saves"] > 0 and kt["bytes_resident"] > 0
    assert kt["tokens_restored"] == on_fx["tokens_restored"]
    assert kt["h2d_ms"] > 0 and kt["d2h_ms"] > 0
    assert 0.0 < kt["hit_rate"] <= 1.0
    # tier-off reports the zero-shaped disabled block, same keys
    off_kt = off_stats["kv_tier"]
    assert set(off_kt) == set(kt) and off_kt["enabled"] is False

    # the critical path gained kv_fetch_ms and still sums exactly
    cp = on_stats["latency_anatomy"]["critical_path"]
    assert "kv_fetch_ms" in cp
    assert cp["kv_fetch_ms"]["count"] > 0
    comp_sum = sum(v["mean"] for k, v in cp.items() if k != "e2e_ms")
    assert comp_sum == pytest.approx(cp["e2e_ms"]["mean"], rel=0.05)


def _tier_traffic_spec(n=36):
    from ray_tpu.serve.traffic import TenantSpec, TrafficSpec

    # prefixes long enough (7 blocks) that one re-prefill costs real
    # forward-pass compute, while a tier restore stays ONE fixed-shape
    # install dispatch — the balance the tier exists to exploit
    return TrafficSpec(
        num_requests=n, seed=5, rate_rps=500.0, num_prefix_groups=2,
        prefix_len=112, p_shared=0.95, tail_len_mean=4.0,
        tail_len_max=8, vocab=300,
        tenants=(TenantSpec("interactive", 0.7,
                            slo_class="interactive", prefix_pool=6),
                 TenantSpec("bg", 0.3)))


def _tier_traffic(tier_bytes):
    from ray_tpu.serve.traffic import run_traffic

    # "tiny" (not nano): the A/B only discriminates when a re-prefill
    # costs real forward-pass compute — at nano scale the whole model
    # is dispatch overhead and both arms measure jax call latency
    return run_traffic(
        _tier_traffic_spec(), preset="tiny", kv_layout="paged",
        kv_block_size=16, kv_num_blocks=20, max_slots=2,
        max_new_tokens=4, prefill_bucket=32, time_scale=0.0,
        kv_host_tier_bytes=tier_bytes, config_overrides=_OVR)


@pytest.mark.slow
def test_churn_traffic_tier_lowers_waste_and_interactive_ttft():
    """The acceptance headline on TenantSpec(prefix_pool=N) traffic
    sized to force eviction: tier-on must yield strictly lower
    re-prefill waste AND strictly lower interactive TTFT p99 than
    tier-off on the same seeded workload — re-admission via H2D copy
    is cheaper than re-prefill."""
    # warm both arms (compiles land here, not in a measured run),
    # then alternate 3 measured runs per arm and compare MEDIANS —
    # a single CPU-scheduler hiccup must not decide a perf assert
    _tier_traffic(None)
    _tier_traffic(1 << 26)
    offs = []
    ons = []
    for _ in range(3):
        offs.append(_tier_traffic(None))
        ons.append(_tier_traffic(1 << 26))
    off, on = offs[0], ons[0]
    assert off["reprefill_waste_frac"] > 0
    assert on["reprefill_waste_frac"] < off["reprefill_waste_frac"]
    assert on["kv_tier_hit_rate"] > 0 and off["kv_tier_hit_rate"] == 0
    assert isinstance(on["interactive_ttft_ms_p99"], float)
    med = lambda rs: sorted(  # noqa: E731
        r["interactive_ttft_ms_p99"] for r in rs)[1]
    assert med(ons) < med(offs)
    # the flattened TTFT critical path carries the new leg
    assert "kv_fetch_ms" in on["ttft_critical_path"]


# ---------------------------------------------------------------------------
# observability satellites: tracebus span, fleet pooling, autopilot,
# perfledger
# ---------------------------------------------------------------------------


def test_tracebus_kv_fetch_span():
    from ray_tpu.tools.tracebus import build_request_spans

    req = {"request": "r0", "trace_id": "t" * 8, "enqueue": 0.0,
           "engine_enqueue": 0.01, "admit": 0.05,
           "first_token": 0.08, "finish": 0.1,
           "kv_fetch": (0.02, 0.03, 3, 48, 4096)}
    spans = {s["name"]: s for s in build_request_spans(req)}
    kv = spans["kv.fetch"]
    assert kv["attrs"]["blocks"] == 3
    assert kv["attrs"]["tokens"] == 48
    assert kv["attrs"]["bytes"] == 4096
    assert kv["start"] == 0.02 and kv["end"] == 0.03
    # no tuple -> no span (every other span still present)
    req2 = dict(req, kv_fetch=None)
    assert "kv.fetch" not in {
        s["name"] for s in build_request_spans(req2)}


@pytest.mark.slow
def test_fleet_stats_pools_kv_tier():
    from ray_tpu.serve.traffic import (TenantSpec, TrafficSpec,
                                       run_traffic_fleet)

    spec = TrafficSpec(
        num_requests=12, seed=0, rate_rps=200.0, num_prefix_groups=2,
        prefix_len=32, p_shared=0.9, tail_len_mean=4.0,
        tail_len_max=8, vocab=300,
        tenants=(TenantSpec("interactive", 0.5,
                            slo_class="interactive",
                            prefix_groups=(0,)),
                 TenantSpec("batch", 0.5, slo_class="batch",
                            prefix_groups=(1,))))
    rep = run_traffic_fleet(spec, num_replicas=2, preset="nano",
                            kv_block_size=16, max_slots=2,
                            max_new_tokens=4, prefill_bucket=16,
                            time_scale=0.0,
                            kv_host_tier_bytes=1 << 24,
                            config_overrides=_OVR)
    kt = rep["fleet"]["kv_tier"]
    assert set(kt) == set(empty_kv_tier())
    assert kt["enabled"] is True
    # pooled hit_rate is recomputed from the SUMMED probes, never
    # averaged across replicas
    probes = kt["hits"] + kt["misses"]
    want = round(kt["hits"] / probes, 4) if probes else 0.0
    assert kt["hit_rate"] == want
    assert rep["kv_tier_hit_rate"] == kt["hit_rate"]


def test_autopilot_credits_tier_absorption():
    from ray_tpu.tools.autopilot.attribution import attribute

    dev = {"ridge_flops_per_byte": 1.0, "peak_flops_per_chip": 1.0,
           "peak_hbm_bytes_per_sec": 1.0}
    # residual waste is calm (2%), but the tier restored enough that
    # the would-be waste crosses the thrash threshold: the verdict
    # must credit the tier, NOT cite cache-thrash
    scope = {"forensics": {"reprefill_waste_frac": 0.02,
                           "reprefill_waste_tokens": 40,
                           "prefill_tokens": 2000}}
    tier = {"enabled": True, "tokens_restored": 960, "hit_rate": 0.9}
    rep = attribute({}, device=dev, kv_scope=scope, kv_tier=tier)
    assert "host KV tier is absorbing cache churn" in rep["summary"]
    assert "cache-thrash-bound" not in rep["summary"]
    assert rep["kv_tier"] is tier
    # thrash persisting THROUGH the tier still cites cache-thrash
    # (and points at the tier budget as a second lever)
    hot = {"forensics": {"reprefill_waste_frac": 0.42,
                         "reprefill_waste_tokens": 8400,
                         "prefill_tokens": 20000}}
    rep = attribute({}, device=dev, kv_scope=hot, kv_tier=tier)
    assert "cache-thrash-bound" in rep["summary"]
    assert "grow its byte budget too" in rep["summary"]
    # tier absorbing a trickle below the would-be threshold: silent
    calm = {"forensics": {"reprefill_waste_frac": 0.0,
                          "reprefill_waste_tokens": 0,
                          "prefill_tokens": 2000}}
    rep = attribute({}, device=dev, kv_scope=calm,
                    kv_tier={"enabled": True, "tokens_restored": 16,
                             "hit_rate": 1.0})
    assert "cache" not in rep["summary"]


def test_perfledger_kv_tier_hit_rate_direction():
    from ray_tpu.tools.perfledger import _SWEEP_FIELDS, higher_is_better

    assert "kv_tier_hit_rate" in _SWEEP_FIELDS
    # the tier hit rate regresses DOWNWARD (higher is better), even
    # with lower-is-better neighbors in the metric name
    assert higher_is_better("kv_tier_hit_rate") is True
    assert higher_is_better("gpt2_traffic_kv_tier_hit_rate") is True
    # existing directions untouched
    assert higher_is_better("reprefill_waste_frac") is False
    assert higher_is_better("kv_occupancy_p95") is False


# ---------------------------------------------------------------------------
# construction validation
# ---------------------------------------------------------------------------


def test_build_validation():
    from ray_tpu.serve.llm import build_llm_deployment

    with pytest.raises(ValueError, match="paged"):
        build_llm_deployment("gpt2", "nano", scheduler="continuous",
                             kv_layout="dense",
                             kv_host_tier_bytes=1 << 20)
    with pytest.raises(ValueError, match="positive"):
        build_llm_deployment("gpt2", "nano", scheduler="continuous",
                             kv_layout="paged",
                             kv_host_tier_bytes=0)
