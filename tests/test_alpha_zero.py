"""AlphaZero: MCTS self-play on tic-tac-toe.

Reference analog: rllib/algorithms/alpha_zero — the learning gate
plays the trained agent against a random opponent.
"""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.rllib import AlphaZero, AlphaZeroConfig, MCTS


class TicTacToe:
    """Canonical-perspective tic-tac-toe: state = (board 9 ints in
    {-1,0,1} from the CURRENT mover's view always as +1, ply)."""

    n_actions = 9

    _LINES = [(0, 1, 2), (3, 4, 5), (6, 7, 8), (0, 3, 6), (1, 4, 7),
              (2, 5, 8), (0, 4, 8), (2, 4, 6)]

    def initial_state(self):
        return (tuple([0] * 9), 0)

    def legal_actions(self, state):
        board, _ = state
        return [i for i in range(9) if board[i] == 0]

    def next_state(self, state, action):
        board, ply = state
        b = list(board)
        b[action] = 1
        # flip perspective: next mover sees their stones as +1
        return (tuple(-x for x in b), ply + 1)

    def terminal_value(self, state):
        board, ply = state
        # lines of -1 belong to the OPPONENT (they just moved)
        for i, j, k in self._LINES:
            if board[i] == board[j] == board[k] == -1:
                return -1.0          # player to move has lost
        if all(x != 0 for x in board):
            return 0.0
        return None

    def to_obs(self, state):
        return np.asarray(state[0], np.float32)


def _play_vs_random(algo, game, episodes=30, seed=0, az_first=True):
    rng = np.random.RandomState(seed)
    wins = draws = 0
    for _ in range(episodes):
        state = game.initial_state()
        az_turn = az_first
        while True:
            term = game.terminal_value(state)
            if term is not None:
                # term is for the player to move; the PREVIOUS mover
                # won when term < 0
                prev_was_az = not az_turn
                if term < 0 and prev_was_az:
                    wins += 1
                elif term == 0:
                    draws += 1
                break
            if az_turn:
                a = algo.compute_action(state, n_sims=40)
            else:
                a = int(rng.choice(game.legal_actions(state)))
            state = game.next_state(state, a)
            az_turn = not az_turn
    return wins, draws, episodes


def test_alpha_zero_beats_random_at_tictactoe(ray_start_shared):
    cfg = AlphaZeroConfig(env=lambda _: TicTacToe(), num_workers=2,
                          hidden=(64,), n_sims=32, games_per_sample=6,
                          train_batch_size=64, train_intensity=8,
                          learning_starts=128, lr=2e-3, seed=0)
    algo = AlphaZero(cfg)
    try:
        for _ in range(10):
            stats = algo.train()
        assert np.isfinite(stats["pi_loss"])
        wins, draws, n = _play_vs_random(algo, algo.game)
        # a competent tic-tac-toe player never loses to random and
        # wins most games moving first
        assert wins + draws >= int(0.85 * n), (wins, draws, n)
        assert wins >= int(0.5 * n), (wins, draws, n)
    finally:
        algo.stop()


def test_mcts_prefers_immediate_win():
    # even an UNTRAINED net must find a one-move win with enough sims
    # (terminal values dominate the search)
    from ray_tpu.rllib.alpha_zero import AZNet, AZSpec

    game = TicTacToe()
    net = AZNet(AZSpec(obs_dim=9, n_actions=9, hidden=(16,)), seed=0)
    # X on 0,1 (current mover); winning move is 2
    board = [1, 1, 0, -1, -1, 0, 0, 0, 0]
    state = (tuple(board), 4)
    mcts = MCTS(game, net, n_sims=200, root_noise=0.0,
                rng=np.random.RandomState(0))
    pi = mcts.policy(state, temperature=1e-7)
    assert int(np.argmax(pi)) in (2, 5)  # 2 wins now; 5 blocks+wins?
    # action 2 completes 0-1-2: must be the choice
    assert int(np.argmax(pi)) == 2, pi
