"""Tests for the smaller parity components: custom metrics, storage API,
usage stats, log streaming, ParallelIterator, joblib backend, dask
scheduler, tracing."""

import sys
import time

import pytest

import ray_tpu


# ---- custom metrics -------------------------------------------------------

def test_metrics_api_and_cluster_export(ray_start_regular):
    from ray_tpu._private import worker_context
    from ray_tpu.util import metrics

    c = metrics.Counter("reqs_total", "requests", tag_keys=("route",))
    c.inc(3, tags={"route": "/a"})
    c.inc(tags={"route": "/b"})
    g = metrics.Gauge("queue_depth", "depth")
    g.set(7)
    h = metrics.Histogram("latency_s", "latency", boundaries=[0.1, 1.0])
    h.observe(0.05)
    h.observe(5.0)

    snap = metrics._registry.snapshot()
    assert snap["reqs_total"]["kind"] == "counter"
    vals = dict((tuple(map(tuple, k)), v)
                for k, v in snap["reqs_total"]["values"])
    assert vals[(("route", "/a"),)] == 3.0
    # publish path: force one flush then merge via the dashboard helper
    cw = worker_context.core_worker()
    import msgpack

    cw.kv_put("metrics:" + cw.worker_id.hex(),
              msgpack.packb({"ts": time.time(),
                             "metrics": metrics._registry.snapshot()}))
    lines = metrics.collect_cluster_metrics(cw.kv_get, cw.kv_keys)
    text = "\n".join(lines)
    assert "raytpu_app_reqs_total" in text
    assert 'route="/a"' in text
    assert "raytpu_app_queue_depth" in text


def test_counter_rejects_negative():
    from ray_tpu.util import metrics

    c = metrics.Counter("neg_test_total")
    with pytest.raises(ValueError):
        c.inc(-1)


# ---- storage --------------------------------------------------------------

def test_storage_api(tmp_path):
    ray_tpu.init(num_cpus=2, object_store_memory=128 * 1024 * 1024,
                 storage=str(tmp_path / "cluster_store"))
    try:
        from ray_tpu._private import storage

        client = storage.get_client("myapp")
        client.put("models/best.txt", b"weights")
        assert client.get("models/best.txt") == b"weights"
        assert client.exists("models/best.txt")
        assert client.list() == ["models/best.txt"]
        # visible from a task (cluster-wide namespace)
        @ray_tpu.remote
        def read():
            from ray_tpu._private import storage

            return storage.get_client("myapp").get("models/best.txt")

        assert ray_tpu.get(read.remote(), timeout=60) == b"weights"
        assert client.delete("models/best.txt")
        assert client.get("models/best.txt") is None
    finally:
        ray_tpu.shutdown()


# ---- usage stats ----------------------------------------------------------

def test_usage_stats_payload(ray_start_regular):
    from ray_tpu._private import usage_lib, worker_context

    payload = usage_lib.collect(worker_context.core_worker())
    assert payload["ray_tpu_version"] == ray_tpu.__version__
    assert payload["num_nodes"] >= 1
    assert "train" not in payload["library_usages"] or \
        "ray_tpu.train" in sys.modules


def test_usage_stats_opt_out(monkeypatch):
    from ray_tpu._private import usage_lib

    monkeypatch.setenv("RAYTPU_USAGE_STATS_ENABLED", "0")
    assert not usage_lib.usage_stats_enabled()


# ---- ParallelIterator -----------------------------------------------------

def test_parallel_iterator_pipeline(ray_start_regular):
    from ray_tpu.util.iter import ParallelIterator

    it = ParallelIterator.from_range(20, num_shards=2)
    it = it.for_each(lambda x: x * 2).filter(lambda x: x % 4 == 0)
    got = sorted(it.gather_sync())
    assert got == sorted(x * 2 for x in range(20) if (x * 2) % 4 == 0)
    it.stop()


def test_parallel_iterator_batch_and_async(ray_start_regular):
    from ray_tpu.util.iter import ParallelIterator

    it = ParallelIterator.from_items(list(range(12)), num_shards=3)
    it = it.batch(2)
    batches = list(it.gather_async())
    assert sorted(x for b in batches for x in b) == list(range(12))
    assert all(len(b) <= 2 for b in batches)
    it.stop()


# ---- joblib ---------------------------------------------------------------

def test_joblib_backend(ray_start_regular):
    joblib = pytest.importorskip("joblib")
    from ray_tpu.util.joblib_backend import register_ray_tpu

    register_ray_tpu()
    with joblib.parallel_backend("ray_tpu", n_jobs=2):
        out = joblib.Parallel()(
            joblib.delayed(lambda x: x ** 2)(i) for i in range(8))
    assert out == [i ** 2 for i in range(8)]


# ---- dask-style graphs ----------------------------------------------------

def test_dask_scheduler_on_plain_graph(ray_start_regular):
    from operator import add, mul

    from ray_tpu.util.dask_scheduler import ray_tpu_dask_get

    dsk = {
        "x": 4,
        "y": (add, "x", 3),          # 7
        "z": (mul, "y", (add, 1, 1)),  # 14 (nested task)
        "w": (sum, ["x", "y", "z"]),   # 25
    }
    assert ray_tpu_dask_get(dsk, "w") == 25
    assert ray_tpu_dask_get(dsk, ["y", "z"]) == [7, 14]


def test_dask_scheduler_detects_cycles(ray_start_regular):
    from operator import add

    from ray_tpu.util.dask_scheduler import ray_tpu_dask_get

    with pytest.raises(ValueError, match="cycle"):
        ray_tpu_dask_get({"a": (add, "b", 1), "b": (add, "a", 1)}, "a")


# ---- tracing --------------------------------------------------------------

def test_tracing_spans_cross_process(ray_start_regular):
    from ray_tpu.util import tracing

    assert tracing.enable_tracing()

    @ray_tpu.remote
    def traced(x):
        return x + 1

    assert ray_tpu.get(traced.remote(1), timeout=60) == 2
    spans = tracing.recorded_spans()
    assert any("traced.remote()" in s.name for s in spans), \
        [s.name for s in spans]
    # the executor-side child span lives in the worker process; verify
    # the carrier made it through by asking the worker for ITS spans
    @ray_tpu.remote
    def span_names():
        from ray_tpu.util import tracing as t

        return [s.name for s in t.recorded_spans()]

    # (the worker enabled tracing lazily when it saw the carrier)
    names = ray_tpu.get(span_names.remote(), timeout=60)
    assert any(n.startswith("execute") for n in names), names


# ---- log streaming --------------------------------------------------------

def test_worker_logs_stream_to_driver(capfd):
    ray_tpu.init(num_cpus=2, object_store_memory=128 * 1024 * 1024,
                 log_to_driver=True)
    try:
        @ray_tpu.remote
        def shout():
            print("HELLO-FROM-WORKER-xyz123")
            return 1

        assert ray_tpu.get(shout.remote(), timeout=60) == 1
        deadline = time.monotonic() + 15
        seen = ""
        while time.monotonic() < deadline:
            seen += capfd.readouterr().err
            if "HELLO-FROM-WORKER-xyz123" in seen:
                break
            time.sleep(0.3)
        assert "HELLO-FROM-WORKER-xyz123" in seen
    finally:
        ray_tpu.shutdown()
